"""Out-of-core streaming data plane (reference
``MemoryDiskFloatMLDataSet.java``): windowing, stateless masks, and streamed
training equivalence with the in-RAM trainer."""

import json
import os

import numpy as np
import pytest


def _write_shards(d, n, dim, shard_rows, seed=0):
    from shifu_tpu.data.shards import Shards
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    logit = x[:, 0] * 1.5 - x[:, 1] + 0.3
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    w = np.ones(n, np.float32)
    os.makedirs(d, exist_ok=True)
    shard = 0
    for s in range(0, n, shard_rows):
        e = min(s + shard_rows, n)
        np.savez(os.path.join(d, f"part-{shard:05d}.npz"),
                 x=x[s:e], y=y[s:e], w=w[s:e])
        shard += 1
    with open(os.path.join(d, "schema.json"), "w") as f:
        json.dump({"outputNames": [f"f{i}" for i in range(dim)],
                   "columnNums": list(range(dim)), "numShards": shard,
                   "numRows": n, "width": dim}, f)
    return Shards.open(d), x, y, w


def test_windows_cover_all_rows_once(tmp_path):
    from shifu_tpu.data.streaming import ShardStream
    shards, x, y, w = _write_shards(str(tmp_path / "s"), 1000, 4,
                                    shard_rows=170)
    stream = ShardStream(shards, ("x", "y", "w"), window_rows=96)
    seen = []
    for win in stream.windows():
        assert win.rows == 96
        seen.append(win.arrays["x"][:win.n_valid])
        # padded tail must carry zero weight
        assert (win.arrays["w"][win.n_valid:] == 0).all()
    got = np.concatenate(seen)
    np.testing.assert_array_equal(got, x)


def test_windows_resumable_and_deterministic(tmp_path):
    from shifu_tpu.data.streaming import ShardStream
    shards, *_ = _write_shards(str(tmp_path / "s"), 500, 3, shard_rows=100)
    stream = ShardStream(shards, ("x",), window_rows=128)
    a = [w.arrays["x"].copy() for w in stream.windows()]
    b = [w.arrays["x"].copy() for w in stream.windows()]  # second epoch
    assert len(a) == len(b)
    for wa, wb in zip(a, b):
        np.testing.assert_array_equal(wa, wb)


def test_stateless_masks_window_invariant():
    """Masking rows [0,1000) in one call or in 10 window calls must agree."""
    from shifu_tpu.data.streaming import window_member_masks
    idx = np.arange(1000)
    y = (idx % 3 == 0).astype(np.float32)
    full_t, full_v = window_member_masks(idx, 3, valid_rate=0.2,
                                         sample_rate=0.8, replacement=True,
                                         targets=y, seed=5)
    for s in range(0, 1000, 100):
        t, v = window_member_masks(idx[s:s + 100], 3, valid_rate=0.2,
                                   sample_rate=0.8, replacement=True,
                                   targets=y[s:s + 100], seed=5)
        np.testing.assert_array_equal(t, full_t[:, s:s + 100])
        np.testing.assert_array_equal(v, full_v[:, s:s + 100])


def test_stateless_mask_rates():
    from shifu_tpu.data.streaming import window_member_masks
    idx = np.arange(200_000)
    t, v = window_member_masks(idx, 1, valid_rate=0.25, sample_rate=0.7,
                               replacement=False, seed=1)
    assert abs(v.mean() - 0.25) < 0.01
    # train mask = Bernoulli(0.7) on the non-valid 75%
    assert abs(t.mean() - 0.7 * 0.75) < 0.01
    tp, _ = window_member_masks(idx, 1, valid_rate=0.0, sample_rate=1.0,
                                replacement=True, seed=2)
    assert abs(tp.mean() - 1.0) < 0.01  # Poisson(1) mean
    # k-fold partitions
    tk, vk = window_member_masks(idx, 4, valid_rate=0.0, kfold=4, seed=3)
    np.testing.assert_array_equal(vk.sum(axis=0), np.ones(len(idx)))
    np.testing.assert_array_equal(tk + vk, np.ones_like(tk))


def test_streamed_fullbatch_matches_in_ram(tmp_path):
    """Full-batch streamed training must reproduce the in-RAM trainer to fp
    tolerance when given the same masks — grad sums are associative."""
    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.train.nn_trainer import (TrainSettings, train_ensemble,
                                            train_ensemble_streamed)
    from shifu_tpu.train.sampling import member_masks

    n, dim, bags = 600, 5, 2
    shards, x, y, w = _write_shards(str(tmp_path / "s"), n, dim,
                                    shard_rows=150)
    train_m, valid_m = member_masks(n, bags, valid_rate=0.25, sample_rate=0.9,
                                    replacement=False, targets=y, seed=0)
    spec = nn_model.NNModelSpec(input_dim=dim, hidden_nodes=[8],
                                activations=["tanh"], loss="log")
    settings = TrainSettings(optimizer="R", learning_rate=0.1, epochs=6,
                             seed=0, l2=1e-4)
    res_ram = train_ensemble(x, y, train_m * w[None, :],
                             valid_m * w[None, :], spec, settings)

    def mask_fn(idx, targets):
        idx = np.minimum(idx, n - 1)  # padded tail is zero-weight anyway
        return train_m[:, idx], valid_m[:, idx]

    stream = ShardStream(shards, ("x", "y", "w"), window_rows=128)
    res_st = train_ensemble_streamed(stream, spec, settings, bags, mask_fn)
    np.testing.assert_allclose(res_st.valid_errors, res_ram.valid_errors,
                               rtol=1e-4, atol=1e-6)
    for pr, ps in zip(res_ram.params, res_st.params):
        for a, b in zip(jax_leaves(pr), jax_leaves(ps)):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)


def jax_leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


def test_streamed_minibatch_converges(tmp_path):
    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream, mask_fn_from_settings
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.train.nn_trainer import (TrainSettings,
                                            train_ensemble_streamed)

    shards, x, y, w = _write_shards(str(tmp_path / "s"), 800, 5,
                                    shard_rows=200)
    spec = nn_model.NNModelSpec(input_dim=5, hidden_nodes=[8],
                                activations=["tanh"], loss="log")
    settings = TrainSettings(optimizer="ADAM", learning_rate=0.05, epochs=15,
                             batch_size=128, seed=0)
    mask_fn = mask_fn_from_settings(1, valid_rate=0.2, seed=0)
    stream = ShardStream(shards, ("x", "y", "w"), window_rows=128)
    res = train_ensemble_streamed(stream, spec, settings, 1, mask_fn)
    # untrained log-loss is ln(2)~0.693; data's Bayes loss ~0.44 — minibatch
    # updates must land well below the untrained baseline
    assert res.valid_errors[0] < 0.5
    assert np.isfinite(res.valid_errors).all()


def test_pipeline_train_streamed_end_to_end(model_set):
    """Force streaming through the CLI pipeline on a tiny window so multiple
    windows exercise the full path; AUC must stay in the healthy range."""
    from shifu_tpu.config import environment
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(model_set, params={}).run() == 0
    environment.set_property("shifu.train.streaming", "on")
    environment.set_property("shifu.train.windowRows", "512")
    try:
        assert TrainProcessor(model_set, params={}).run() == 0
    finally:
        environment.set_property("shifu.train.streaming", "")
        environment.set_property("shifu.train.windowRows", "")
    res = EvalProcessor(model_set, params={"run": True}).run()
    assert res == 0
    with open(os.path.join(model_set, "evals", "Eval1", "EvalPerformance.json")) as f:
        perf = json.load(f)
    assert perf["areaUnderRoc"] > 0.85
