"""WDL (wide-and-deep) tests — reference ``core/dtrain/wdl/`` parity."""

import os

import numpy as np
import pytest

import jax

from shifu_tpu.models import wdl as wdl_model
from shifu_tpu.train.nn_trainer import TrainSettings
from shifu_tpu.train.wdl_trainer import train_wdl_ensemble


def _settings(lr=0.05, l2=0.0, epochs=8, batch=256):
    return TrainSettings(optimizer="ADAM", learning_rate=lr, l2=l2,
                         epochs=epochs, batch_size=batch, seed=0)


def make_data(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    x_num = rng.normal(size=(n, 3)).astype(np.float32)
    x_cat = np.stack([rng.integers(0, 5, n), rng.integers(0, 3, n)],
                     axis=1).astype(np.int32)
    logit = x_num[:, 0] - 0.5 * x_num[:, 1] + (x_cat[:, 0] == 2) * 1.5 \
        + (x_cat[:, 1] == 0) * -1.0
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    return x_num, x_cat, y


SPEC = wdl_model.WDLModelSpec(numeric_dim=3, cat_cardinalities=[5, 3],
                              embed_dim=4, hidden_nodes=[16],
                              activations=["relu"])


def test_wdl_forward_shapes():
    params = wdl_model.init_params(jax.random.PRNGKey(0), SPEC)
    x_num, x_cat, _ = make_data(64)
    out = np.asarray(wdl_model.forward(params, SPEC, x_num, x_cat))
    assert out.shape == (64, 1)
    assert np.all((out > 0) & (out < 1))


def test_wdl_onehot_and_gather_lowerings_agree():
    """The one-hot-matmul embedding path (training batches — grads become
    MXU matmuls, not per-column scatters) must produce EXACTLY the gather
    path's logits (a one-hot matmul sums a single nonzero term)."""
    import jax.numpy as jnp

    import shifu_tpu.models.wdl as W

    x_num, x_cat, _ = make_data(400)
    spec = wdl_model.WDLModelSpec(numeric_dim=3, cat_cardinalities=[6, 4],
                                  embed_dim=5)
    params = wdl_model.init_params(jax.random.PRNGKey(3), spec)
    small = W.forward_logits(params, spec, jnp.asarray(x_num),
                             jnp.asarray(x_cat))
    cap = W._ONEHOT_MAX_ELEMS
    try:
        W._ONEHOT_MAX_ELEMS = 0           # force the gather lowering
        gathered = W.forward_logits(params, spec, jnp.asarray(x_num),
                                    jnp.asarray(x_cat))
    finally:
        W._ONEHOT_MAX_ELEMS = cap
    np.testing.assert_allclose(np.asarray(small), np.asarray(gathered),
                               rtol=1e-6, atol=1e-6)
    # out-of-range / missing-bin indices clip identically per column
    x_bad = x_cat.copy()
    x_bad[:7, 0] = 99
    a = W.forward_logits(params, spec, jnp.asarray(x_num),
                         jnp.asarray(x_bad))
    try:
        W._ONEHOT_MAX_ELEMS = 0
        b = W.forward_logits(params, spec, jnp.asarray(x_num),
                             jnp.asarray(x_bad))
    finally:
        W._ONEHOT_MAX_ELEMS = cap
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-6, atol=1e-6)


def test_wdl_wide_only_and_deep_only():
    x_num, x_cat, y = make_data()
    for wide, deep in ((True, False), (False, True)):
        spec = wdl_model.WDLModelSpec(numeric_dim=3, cat_cardinalities=[5, 3],
                                      embed_dim=4, hidden_nodes=[8],
                                      activations=["relu"],
                                      wide_enable=wide, deep_enable=deep)
        res = train_wdl_ensemble(x_num, x_cat, y, np.ones(len(y)), spec,
                                 _settings(epochs=8))
        assert res.valid_errors[0] < 0.68, (wide, deep, res.valid_errors)


def test_wdl_training_learns():
    x_num, x_cat, y = make_data()
    res = train_wdl_ensemble(x_num, x_cat, y, np.ones(len(y)), SPEC,
                             _settings(l2=1e-5, epochs=25))
    # best validation error (what gets saved) beats the first epoch and
    # approaches the Bayes limit of this noisy data (~0.55; chance = 0.69)
    assert res.valid_errors[0] < res.history[0][1]
    assert res.valid_errors[0] < 0.60


def test_wdl_save_load_roundtrip(tmp_path):
    params = wdl_model.init_params(jax.random.PRNGKey(1), SPEC)
    x_num, x_cat, _ = make_data(128)
    want = np.asarray(wdl_model.forward(params, SPEC, x_num, x_cat))
    path = os.path.join(tmp_path, "model0.wdl")
    wdl_model.save_model(path, SPEC, params)
    m = wdl_model.IndependentWDLModel.load(path)
    np.testing.assert_allclose(m.compute(x_num, x_cat), want, rtol=1e-6)


def test_wdl_pipeline_end_to_end(prepared_set):
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    import json

    model_set = prepared_set          # init/stats/norm ran in the template
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm.WDL
    mc.train.numTrainEpochs = 8
    mc.train.params = {"NumHiddenNodes": [16], "ActivationFunc": ["relu"],
                       "EmbedDim": 4, "LearningRate": 0.01, "MiniBatchs": 512}
    mc.save(mc_path)
    assert TrainProcessor(model_set, params={}).run() == 0
    assert os.path.isfile(os.path.join(model_set, "models", "model0.wdl"))
    assert EvalProcessor(model_set, params={"run_eval": ""}).run() == 0
    perf = json.load(open(os.path.join(model_set, "evals", "Eval1",
                                       "EvalPerformance.json")))
    assert perf["areaUnderRoc"] > 0.7


def test_wdl_mesh_ensemble_equivalence():
    """1-device vs 8-device mesh must train the same 2-member ensemble
    (gradient psum over the data axis is exact)."""
    from shifu_tpu.parallel.mesh import device_mesh
    x_num, x_cat, y = make_data(1024)
    devs = jax.devices("cpu")
    r1 = train_wdl_ensemble(x_num, x_cat, y, np.ones(len(y)), SPEC,
                            _settings(epochs=4, batch=0), bags=2,
                            mesh=device_mesh(2, devices=devs[:1]))
    r8 = train_wdl_ensemble(x_num, x_cat, y, np.ones(len(y)), SPEC,
                            _settings(epochs=4, batch=0), bags=2,
                            mesh=device_mesh(2, devices=devs[:8]))
    np.testing.assert_allclose(r1.valid_errors, r8.valid_errors,
                               rtol=1e-4, atol=1e-5)
    for p1, p8 in zip(r1.params, r8.params):
        a1 = jax.tree_util.tree_leaves(p1)
        a8 = jax.tree_util.tree_leaves(p8)
        for l1, l8 in zip(a1, a8):
            np.testing.assert_allclose(l1, l8, rtol=1e-3, atol=1e-4)


def test_wdl_pipeline_grid_search(prepared_set):
    """List-valued WDL params train sequential trials, a ranked report
    lands, and the best trial saves as model0.wdl."""
    import json

    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.train import TrainProcessor

    model_set = prepared_set
    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "WDL"
    mc.train.numTrainEpochs = 6
    mc.train.params = {"NumHiddenNodes": [[8], [16]],
                       "ActivationFunc": ["relu"],
                       "EmbedDim": 4, "MiniBatchs": 512,
                       "LearningRate": 0.02}
    mc.save(mcp)
    assert TrainProcessor(model_set, params={}).run() == 0
    assert os.path.isfile(os.path.join(model_set, "models", "model0.wdl"))
    report = json.load(open(os.path.join(model_set, "tmp",
                                         "grid_search.json")))
    assert len(report) == 2
    errs = [r["validError"] for r in report]
    assert errs == sorted(errs)
    progress = open(os.path.join(model_set, "tmp", "train.progress")).read()
    assert "Trial [1]" in progress


def test_wdl_pipeline_streamed(prepared_set):
    """WDL trains streamed (forced) through the pipeline and still scores."""
    from shifu_tpu.config import ModelConfig, environment
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor

    model_set = prepared_set          # init/stats/norm ran in the template
    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "WDL"
    mc.train.baggingNum = 2
    mc.train.numTrainEpochs = 15
    mc.train.params = {"LearningRate": 0.05, "MiniBatchs": 256,
                       "EmbedDim": 4, "NumHiddenNodes": [8],
                       "ActivationFunc": ["relu"]}
    mc.save(mcp)
    environment.set_property("shifu.train.streaming", "on")
    environment.set_property("shifu.train.windowRows", 512)
    try:
        assert TrainProcessor(model_set, params={}).run() == 0
    finally:
        environment.set_property("shifu.train.streaming", "")
        environment.set_property("shifu.train.windowRows", "")
    models = [f for f in os.listdir(os.path.join(model_set, "models"))
              if f.endswith(".wdl")]
    assert len(models) == 2                    # both bagging members saved
    assert EvalProcessor(model_set, params={"run_eval": "Eval1"}).run() == 0
