"""End-to-end `train` step over the synthetic fraud model set — the
reference's shell-test pattern (new→init→stats→norm→train) in-process."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.config import ModelConfig


def run_steps(model_set, upto_train_params=None, algorithm=None):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    mc_path = os.path.join(model_set, "ModelConfig.json")
    if algorithm or upto_train_params is not None:
        mc = ModelConfig.load(mc_path)
        if algorithm:
            mc.train.algorithm = algorithm
        if upto_train_params is not None:
            mc.train.params = upto_train_params
        mc.save(mc_path)
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0


def test_train_nn_end_to_end(model_set):
    run_steps(model_set, upto_train_params={
        "Propagation": "R", "LearningRate": 0.1,
        "NumHiddenNodes": [12], "ActivationFunc": ["tanh"]})
    model_path = os.path.join(model_set, "models", "model0.nn")
    assert os.path.isfile(model_path)

    # the saved model separates classes on the training data
    from shifu_tpu.models import load_any
    from shifu_tpu.data.shards import Shards
    m = load_any(model_path)
    data = Shards.open(os.path.join(model_set, "tmp", "NormalizedData")).load_all()
    scores = m.compute(data["x"])[:, 0]
    pos, neg = scores[data["y"] == 1], scores[data["y"] == 0]
    assert pos.mean() > neg.mean() + 0.1

    progress = os.path.join(model_set, "tmp", "train.progress")
    assert os.path.isfile(progress) and "Validation Error" in open(progress).read()


def test_train_lr_end_to_end(model_set):
    from shifu_tpu.config.model_config import Algorithm
    run_steps(model_set, algorithm=Algorithm.LR)
    model_path = os.path.join(model_set, "models", "model0.lr")
    assert os.path.isfile(model_path)
    from shifu_tpu.models import load_any
    from shifu_tpu.data.shards import Shards
    m = load_any(model_path)
    data = Shards.open(os.path.join(model_set, "tmp", "NormalizedData")).load_all()
    scores = m.compute(data["x"])[:, 0]
    pos, neg = scores[data["y"] == 1], scores[data["y"] == 0]
    assert pos.mean() > neg.mean() + 0.1


def test_train_grid_search(model_set):
    run_steps(model_set, upto_train_params={
        "Propagation": "R", "LearningRate": [0.1, 0.25],
        "NumHiddenNodes": [8], "ActivationFunc": ["tanh"]})
    assert os.path.isfile(os.path.join(model_set, "models", "model0.nn"))
    report = json.load(open(os.path.join(model_set, "tmp", "grid_search.json")))
    assert len(report) == 2
    assert report[0]["validError"] <= report[1]["validError"]


def test_train_bagging(model_set):
    mc = ModelConfig.load(os.path.join(model_set, "ModelConfig.json"))
    mc.train.baggingNum = 3
    mc.train.numTrainEpochs = 10
    mc.save(os.path.join(model_set, "ModelConfig.json"))
    run_steps(model_set)
    for i in range(3):
        assert os.path.isfile(os.path.join(model_set, "models", f"model{i}.nn"))
