"""Varselect tests — filter ranking, auto-filter, SE sensitivity, history."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.config import ModelConfig, load_column_configs
from shifu_tpu.pipeline.varselect import pareto_front_ranks


def _prep(model_set, train_first=False):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    if train_first:
        assert NormalizeProcessor(model_set, params={}).run() == 0
        assert TrainProcessor(model_set, params={}).run() == 0


def _ccs(model_set):
    return load_column_configs(os.path.join(model_set, "ColumnConfig.json"))


def test_pareto_front_ranks():
    ks = np.array([1.0, 0.9, 0.5, 0.1])
    iv = np.array([1.0, 0.2, 0.6, 0.1])
    r = pareto_front_ranks(ks, iv)
    assert r[0] == 0                      # dominates everything
    assert r[3] == max(r)                 # dominated by all
    assert r[1] >= 1 and r[2] >= 1


def test_varselect_ks_filter(model_set):
    from shifu_tpu.pipeline.varselect import VarSelectProcessor
    _prep(model_set)
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.varSelect.filterNum = 2
    mc.save(mc_path)
    assert VarSelectProcessor(model_set, params={}).run() == 0
    sel = [c for c in _ccs(model_set) if c.finalSelect]
    assert len(sel) == 2
    # top-KS columns won (amount & country carry the signal)
    names = {c.columnName for c in sel}
    assert "amount" in names


@pytest.mark.parametrize("by", ["IV", "MIX", "PARETO"])
def test_varselect_other_filters(model_set, by):
    from shifu_tpu.pipeline.varselect import VarSelectProcessor
    from shifu_tpu.config.model_config import FilterBy
    _prep(model_set)
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.varSelect.filterNum = 3
    mc.varSelect.filterBy = FilterBy[by]
    mc.save(mc_path)
    assert VarSelectProcessor(model_set, params={}).run() == 0
    assert sum(c.finalSelect for c in _ccs(model_set)) == 3


def test_varselect_se_sensitivity(model_set, monkeypatch):
    from shifu_tpu.data.shards import Shards
    from shifu_tpu.pipeline.varselect import VarSelectProcessor
    from shifu_tpu.config.model_config import FilterBy
    _prep(model_set, train_first=True)
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.varSelect.filterNum = 3
    mc.varSelect.filterBy = FilterBy.SE
    mc.save(mc_path)

    # the streamed sensitivity plane must NEVER materialize the full norm
    # plane on host (the 1TB-north-star constraint)
    def _no_load_all(self):
        raise AssertionError("SE varselect called Shards.load_all — the "
                             "streamed plane must not materialize")
    monkeypatch.setattr(Shards, "load_all", _no_load_all)
    assert VarSelectProcessor(model_set, params={}).run() == 0
    sel = {c.columnName for c in _ccs(model_set) if c.finalSelect}
    assert len(sel) == 3
    se = json.load(open(os.path.join(model_set, "varsels", "se.json")))
    assert len(se) >= 3
    # noise column must rank below the true signal columns
    ranked = list(se)
    assert ranked.index([k for k in se][0]) == 0


def test_varselect_reset_recover(model_set):
    from shifu_tpu.pipeline.varselect import VarSelectProcessor
    _prep(model_set)
    assert VarSelectProcessor(model_set, params={}).run() == 0
    n_sel = sum(c.finalSelect for c in _ccs(model_set))
    assert n_sel > 0
    assert VarSelectProcessor(model_set, params={"reset": True}).run() == 0
    assert sum(c.finalSelect for c in _ccs(model_set)) == 0
    assert VarSelectProcessor(model_set, params={"recover": True}).run() == 0
    assert sum(c.finalSelect for c in _ccs(model_set)) == n_sel


def test_varselect_force_files(model_set, tmp_path):
    from shifu_tpu.pipeline.varselect import VarSelectProcessor
    _prep(model_set)
    fs = tmp_path / "force_select.names"
    fs.write_text("noise\n")
    fr = tmp_path / "force_remove.names"
    fr.write_text("velocity\n")
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.varSelect.forceSelectColumnNameFile = str(fs)
    mc.varSelect.forceRemoveColumnNameFile = str(fr)
    mc.varSelect.filterNum = 2
    mc.save(mc_path)
    assert VarSelectProcessor(model_set, params={}).run() == 0
    by_name = {c.columnName: c for c in _ccs(model_set)}
    assert by_name["noise"].finalSelect          # force-selected despite low ks
    assert not by_name["velocity"].finalSelect   # force-removed
    assert by_name["velocity"].columnFlag is not None


def test_varselect_auto_filter_missing_rate(model_set):
    from shifu_tpu.pipeline.varselect import VarSelectProcessor
    _prep(model_set)
    ccs = _ccs(model_set)
    # artificially mark one column as nearly-all-missing
    for c in ccs:
        if c.columnName == "noise":
            c.columnStats.missingPercentage = 0.99
    from shifu_tpu.config import save_column_configs
    save_column_configs(ccs, os.path.join(model_set, "ColumnConfig.json"))
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.varSelect.autoFilterEnable = True
    mc.varSelect.filterNum = 10
    mc.save(mc_path)
    assert VarSelectProcessor(model_set, params={}).run() == 0
    by_name = {c.columnName: c for c in _ccs(model_set)}
    assert not by_name["noise"].finalSelect


def test_varselect_recursive_se(model_set):
    """-recursive N (reference VarSelectModelProcessor.java:201-227): each
    round re-norms + retrains on the current selection, then re-scores;
    per-round ColumnConfig/se snapshots land in varsels/."""
    from shifu_tpu.pipeline.varselect import VarSelectProcessor
    from shifu_tpu.config.model_config import FilterBy
    _prep(model_set, train_first=True)
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.varSelect.filterNum = 2
    mc.varSelect.filterBy = FilterBy.SE
    mc.save(mc_path)
    assert VarSelectProcessor(model_set,
                              params={"recursive": 2}).run() == 0
    sel = [c for c in _ccs(model_set) if c.finalSelect]
    assert len(sel) == 2
    vdir = os.path.join(model_set, "varsels")
    # snapshots: initial + one per round
    for i in range(3):
        assert os.path.isfile(os.path.join(vdir, f"ColumnConfig.json.{i}"))
    for i in range(2):
        assert os.path.isfile(os.path.join(vdir, f"se.{i}.json"))
    # round-2 model was retrained on round-1's selection: its se scores
    # only cover surviving candidates
    se1 = json.load(open(os.path.join(vdir, "se.1.json")))
    assert len(se1) >= 2


def test_varselect_recursive_rejects_filter_modes(model_set):
    from shifu_tpu.pipeline.varselect import VarSelectProcessor
    _prep(model_set)
    assert VarSelectProcessor(model_set,
                              params={"recursive": 3}).run() == 1


def test_varselect_autofilter_and_recoverauto(model_set):
    """`varselect -autofilter` prunes the current selection by
    missing-rate/KS/IV thresholds and `-recoverauto` undoes it
    (reference ShifuCLI.java:836-837)."""
    from shifu_tpu.pipeline.varselect import VarSelectProcessor
    _prep(model_set)
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.varSelect.filterNum = 100           # select everything first
    mc.save(mc_path)
    assert VarSelectProcessor(model_set, params={}).run() == 0
    before = {c.columnNum for c in _ccs(model_set) if c.finalSelect}
    assert before
    # raise the KS bar so the filter has something to remove
    mc.varSelect.minKsThreshold = \
        sorted((c.columnStats.ks or 0) for c in _ccs(model_set)
               if c.finalSelect)[-1] * 0.99
    mc.save(mc_path)
    assert VarSelectProcessor(model_set,
                              params={"autofilter": True}).run() == 0
    after = {c.columnNum for c in _ccs(model_set) if c.finalSelect}
    assert after < before                  # strictly pruned
    hist = os.path.join(model_set, "varsels", "autofilter.history")
    assert os.path.isfile(hist)
    assert VarSelectProcessor(model_set,
                              params={"recoverauto": True}).run() == 0
    recovered = {c.columnNum for c in _ccs(model_set) if c.finalSelect}
    assert recovered == before


def test_varselect_se_rejects_tree_algorithm(model_set):
    """Reference VarSelectModelProcessor.java:196-200: SE/ST needs NN/LR."""
    from shifu_tpu.config.model_config import Algorithm, FilterBy
    from shifu_tpu.config.validator import ValidationError
    from shifu_tpu.pipeline.varselect import VarSelectProcessor
    _prep(model_set)
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.varSelect.filterBy = FilterBy.SE
    mc.train.algorithm = Algorithm.RF
    mc.save(mc_path)
    with pytest.raises(ValidationError) as e:
        VarSelectProcessor(model_set, params={}).run()
    assert "needs an NN/LR model" in str(e.value)
