"""Stats/binning engine tests: math parity + end-to-end stats step."""

import os

import numpy as np
import pytest

from shifu_tpu.config import load_column_configs
from shifu_tpu.ops.binning import (CategoricalAccumulator, ColumnBinner,
                                   NumericAccumulator)
from shifu_tpu.ops.stats_math import column_metrics, pos_rate, psi
from shifu_tpu.config.model_config import BinningMethod
from shifu_tpu.pipeline.create import InitProcessor
from shifu_tpu.pipeline.stats import StatsProcessor


# ------------------------------------------------------------ pure math
def test_column_metrics_reference_formulas():
    """Hand-checked against ColumnStatsCalculator.java (long[] variant)."""
    neg = np.array([[80.0, 20.0, 0.0]])
    pos = np.array([[10.0, 30.0, 0.0]])
    m = column_metrics(neg, pos)
    p = pos[0] / 40.0
    n = neg[0] / 100.0
    eps = 1e-10
    exp_woe_bins = np.log((n + eps) / (p + eps))
    assert np.allclose(m.bin_woe[0], exp_woe_bins)
    assert np.isclose(m.iv[0], ((n - p) * exp_woe_bins).sum())
    assert np.isclose(m.woe[0], np.log((100 + eps) / (40 + eps)))
    cump, cumn = np.cumsum(p), np.cumsum(n)
    assert np.isclose(m.ks[0], 100 * np.abs(cump - cumn).max())


def test_column_metrics_degenerate_returns_nan():
    m = column_metrics(np.array([[5.0, 5.0]]), np.array([[0.0, 0.0]]))
    assert np.isnan(m.ks[0]) and np.isnan(m.iv[0])


def test_pos_rate_and_psi():
    pr = pos_rate(np.array([1.0, 0.0]), np.array([3.0, 0.0]))
    assert pr[0] == 0.25 and np.isnan(pr[1])
    assert psi(np.array([50, 50.0]), np.array([50, 50.0])) < 1e-12
    assert psi(np.array([90, 10.0]), np.array([10, 90.0])) > 1.0


# ------------------------------------------------------- streaming sketch
def test_numeric_accumulator_quantile_binning(rng):
    x = rng.normal(10, 3, size=(20000, 1))
    valid = np.ones_like(x, dtype=bool)
    y = (rng.random(20000) < 0.3).astype(float)
    w = np.ones(20000)
    acc = NumericAccumulator(n_cols=1)
    for s in range(0, 20000, 5000):  # streamed in 4 chunks
        acc.update_moments(x[s:s + 5000], valid[s:s + 5000])
    acc.finalize_range()
    for s in range(0, 20000, 5000):
        acc.update_histogram(x[s:s + 5000], valid[s:s + 5000], y[s:s + 5000],
                             w[s:s + 5000])
    assert np.isclose(acc.moments["mean"][0], x.mean(), atol=0.01)
    assert np.isclose(np.sqrt(acc.moments["M2"][0] / (20000 - 1)), x.std(ddof=1),
                      atol=0.01)
    bnds = acc.compute_boundaries(BinningMethod.EqualTotal, 10)[0]
    assert bnds[0] == float("-inf") and len(bnds) == 10
    # roughly equal population per bin
    counts = acc.bin_counts(0, bnds)
    tot = counts[:-1, 0] + counts[:-1, 1]
    assert tot.sum() == 20000
    assert tot.min() > 0.6 * 2000 and tot.max() < 1.6 * 2000
    # quantiles close to true
    q = acc.percentile(0, [0.5])
    assert abs(q[0] - np.median(x)) < 0.05


def test_equal_positive_binning_balances_positives(rng):
    n = 30000
    x = rng.exponential(5, size=(n, 1))
    y = (rng.random(n) < np.clip(x[:, 0] / 20, 0, 1)).astype(float)
    acc = NumericAccumulator(n_cols=1)
    acc.update_moments(x, np.ones_like(x, dtype=bool))
    acc.finalize_range()
    acc.update_histogram(x, np.ones_like(x, dtype=bool), y, np.ones(n))
    bnds = acc.compute_boundaries(BinningMethod.EqualPositive, 8)[0]
    counts = acc.bin_counts(0, bnds)
    pos_per_bin = counts[:-1, 0]
    assert pos_per_bin.sum() == y.sum()
    assert pos_per_bin.std() / pos_per_bin.mean() < 0.35


def test_unit_weight_accumulator_matches_weighted_path(rng):
    """unit_weight=True (the production default when no weight column is
    configured, pipeline/stats.py) runs the 2-channel device accumulators
    and mirrors them into the weighted slots at drain — every field must
    match the 4-channel path fed w=1, including missing aggregation and
    multi-chunk drains."""
    n = 12000
    x = rng.normal(size=(n, 3))
    valid = rng.random((n, 3)) > 0.1
    y = (rng.random(n) < 0.3).astype(float)
    w = np.ones(n)
    accs = [NumericAccumulator(n_cols=3, unit_weight=uw) for uw in (True, False)]
    for acc in accs:
        for s in range(0, n, 4000):   # 3 chunks through the pending lists
            acc.update_moments(x[s:s + 4000], valid[s:s + 4000])
        acc.finalize_range()
        for s in range(0, n, 4000):
            acc.update_histogram(x[s:s + 4000], valid[s:s + 4000],
                                 y[s:s + 4000], w[s:s + 4000])
    a, b = accs
    for col in range(3):
        bnds = a.compute_boundaries(BinningMethod.EqualTotal, 8)[col]
        bnds_b = b.compute_boundaries(BinningMethod.EqualTotal, 8)[col]
        np.testing.assert_array_equal(bnds, bnds_b)
        ca, cb = a.bin_counts(col, bnds), b.bin_counts(col, bnds)
        np.testing.assert_allclose(ca, cb, atol=1e-6)
        # weighted slots mirror counts exactly when w == 1
        np.testing.assert_array_equal(ca[:, 2:], ca[:, :2])
    np.testing.assert_allclose(a.missing_agg, b.missing_agg, atol=1e-6)
    assert a.missing_agg[:, :2].sum() == (~valid).sum()


@pytest.mark.parametrize("method", [BinningMethod.EqualTotal,
                                    BinningMethod.EqualPositive,
                                    BinningMethod.EqualInterval,
                                    BinningMethod.WeightEqualTotal])
def test_finalize_sketch_matches_host_path(rng, method):
    """The device-side finalize (one small packed fetch) must reproduce
    the host drain path: same deduped boundaries (f32 rounding only),
    bit-equal bin aggregates, same percentiles/distinct."""
    n = 9000
    x = rng.normal(size=(n, 4))
    x[:, 2] = np.round(x[:, 2])          # few distinct values: dedupe path
    valid = rng.random((n, 4)) > 0.08
    y = (rng.random(n) < 0.35).astype(float)
    w = rng.uniform(0.5, 2.0, n)
    accs = [NumericAccumulator(n_cols=4) for _ in range(2)]
    for acc in accs:
        for s in range(0, n, 3000):
            acc.update_moments(x[s:s + 3000], valid[s:s + 3000])
        acc.finalize_range()
        for s in range(0, n, 3000):
            acc.update_histogram(x[s:s + 3000], valid[s:s + 3000],
                                 y[s:s + 3000], w[s:s + 3000])
    dev, host = accs
    bnds_d, aggs_d, pct_d, dist_d = dev.finalize_sketch(method, 8)
    bnds_h = host.compute_boundaries(method, 8)
    for c in range(4):
        assert len(bnds_d[c]) == len(bnds_h[c]), (c, bnds_d[c], bnds_h[c])
        np.testing.assert_allclose(bnds_d[c][1:], bnds_h[c][1:],
                                   rtol=2e-6, atol=1e-6)
        agg_h = host.bin_counts(c, bnds_h[c])
        # EqualInterval boundaries land exactly ON fine-bucket edges; the
        # host f64 linspace rounds the tie by +-1 ulp either way (device
        # f32 arithmetic ties exactly), so one fine bucket's rows may sit
        # in the adjacent bin — allow exactly that much there
        atol = 6.0 if method == BinningMethod.EqualInterval else 1e-4
        np.testing.assert_allclose(aggs_d[c], agg_h, rtol=1e-6, atol=atol)
        np.testing.assert_allclose(
            pct_d[c], host.percentile(c, [0.25, 0.5, 0.75]),
            rtol=2e-6, atol=1e-6)
        assert dist_d[c] == host.distinct_estimate(c)


def test_finalize_sketch_drained_fallback_and_missing_pct(rng):
    """After a mid-pass drain (TB-scale path) finalize_sketch must take
    the exact f64 host route (no f32 re-upload); an all-missing column
    reports NaN percentiles, not the empty-range fallback edge."""
    n = 4000
    x = rng.normal(size=(n, 2))
    valid = np.ones((n, 2), bool)
    valid[:, 1] = False                    # column 1: all missing
    y = (rng.random(n) < 0.3).astype(float)
    accs = [NumericAccumulator(n_cols=2, unit_weight=True) for _ in range(2)]
    for acc in accs:
        acc.update_moments(x, valid)
        acc.finalize_range()
        acc.update_histogram(x, valid, y, np.ones(n))
    drained, live = accs
    drained._drain_hist()                  # simulate the >8M-row drain
    assert drained.hist is not None and drained._hist_dev is None
    for acc in accs:
        bnds, aggs, pct, dist = acc.finalize_sketch(BinningMethod.EqualTotal, 6)
        assert np.isnan(pct[1]).all()      # no data -> no percentiles
        assert not np.isnan(pct[0]).any()
        assert aggs[1][-1, :2].sum() == n  # all rows in the missing bin
    b_d, a_d, p_d, _ = drained.finalize_sketch(BinningMethod.EqualTotal, 6)
    b_l, a_l, p_l, _ = live.finalize_sketch(BinningMethod.EqualTotal, 6)
    np.testing.assert_allclose(b_d[0][1:], b_l[0][1:], rtol=2e-6, atol=1e-6)
    np.testing.assert_allclose(a_d[0], a_l[0], rtol=1e-5, atol=1e-3)


def test_finalize_sketch_zero_measure_column(rng):
    """A column with zero positives under EqualPositive collapses to the
    reference single-bin shape (host fallback off the packed totals)."""
    n = 2000
    x = rng.normal(size=(n, 1))
    y = np.zeros(n)                       # no positives at all
    acc = NumericAccumulator(n_cols=1, unit_weight=True)
    acc.update_moments(x, np.ones_like(x, bool))
    acc.finalize_range()
    acc.update_histogram(x, np.ones_like(x, bool), y, np.ones(n))
    bnds, aggs, _, _ = acc.finalize_sketch(BinningMethod.EqualPositive, 8)
    assert len(bnds[0]) == 1 and bnds[0][0] == float("-inf")
    assert aggs[0].shape == (2, 4)
    assert aggs[0][0, 1] == n             # all rows in the single bin (neg)


def test_missing_values_go_to_last_bin(rng):
    x = rng.normal(size=(1000, 1))
    valid = rng.random((1000, 1)) > 0.2
    y = np.zeros(1000); y[:100] = 1
    acc = NumericAccumulator(n_cols=1)
    acc.update_moments(x, valid)
    acc.finalize_range()
    acc.update_histogram(x, valid, y, np.ones(1000))
    bnds = acc.compute_boundaries(BinningMethod.EqualTotal, 5)[0]
    counts = acc.bin_counts(0, bnds)
    assert counts[-1].sum() > 0
    assert counts[-1, 0] + counts[-1, 1] == (~valid).sum()


def test_column_binner_semantics():
    b = ColumnBinner(boundaries=np.array([float("-inf"), 1.0, 2.0]))
    idx = b.bin_numeric(np.array([0.5, 1.0, 1.5, 5.0]), np.array([True, True, True, False]))
    assert idx.tolist() == [0, 1, 1, 3]
    cb = ColumnBinner(categories=["US", "GB"])
    assert cb.bin_categorical(np.array(["US", "GB", "XX"])).tolist() == [0, 1, 2]


def test_categorical_accumulator_counts():
    acc = CategoricalAccumulator()
    vals = np.array(["a", "b", "a", "", "c"])
    valid = np.array([True, True, True, False, True])
    y = np.array([1.0, 0.0, 1.0, 1.0, 0.0])
    w = np.ones(5)
    acc.update("col", vals, valid, y, w)
    acc.update("col", vals, valid, y, w)  # streamed twice
    cats, counts, _, _ = acc.finalize("col")
    assert cats[0] == "a"  # most frequent first
    a = counts[cats.index("a")]
    assert a[0] == 4 and a[1] == 0  # 2 pos x 2 updates
    assert counts[-1][0] == 2  # missing row was positive, twice


# ---------------------------------------------------------- end-to-end
def test_stats_step_end_to_end(model_set):
    InitProcessor(model_set).run()
    proc = StatsProcessor(model_set, params={"correlation": True})
    assert proc.run() == 0
    ccs = load_column_configs(os.path.join(model_set, "ColumnConfig.json"))
    by_name = {c.columnName: c for c in ccs}
    amt = by_name["amount"]
    assert amt.columnStats.mean is not None and amt.columnStats.mean > 0
    assert amt.columnStats.missingCount > 0
    assert amt.columnStats.ks is not None and amt.columnStats.ks > 5
    assert amt.columnStats.iv is not None and amt.columnStats.iv > 0.01
    assert amt.columnBinning.binBoundary[0] == float("-inf")
    assert len(amt.columnBinning.binCountPos) == len(amt.columnBinning.binBoundary) + 1
    country = by_name["country"]
    assert set(country.columnBinning.binCategory) == {"US", "GB", "DE", "CN", "BR"}
    assert country.columnStats.ks is not None
    # weighted stats populated
    assert amt.columnStats.weightedIv is not None
    # target/meta/weight columns untouched by binning
    assert by_name["tag"].columnBinning.binBoundary is None
    assert os.path.isfile(os.path.join(model_set, "correlation.csv"))
    # noise column should carry ~no signal
    assert by_name["noise"].columnStats.iv < amt.columnStats.iv


def test_stats_sample_rate_applied(model_set):
    """stats.sampleRate must actually subsample (round-2 gap: validated but
    ignored); sampled stats stay statistically close to the full pass."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.column_config import load_column_configs
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor

    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    ccp = os.path.join(model_set, "ColumnConfig.json")
    full = {c.columnName: c.columnStats.validNumCount
            for c in load_column_configs(ccp) if not c.is_categorical()}
    full_mean = {c.columnName: c.columnStats.mean
                 for c in load_column_configs(ccp) if c.columnStats.mean}

    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.stats.sampleRate = 0.5
    mc.save(mcp)
    assert StatsProcessor(model_set, params={}).run() == 0
    half = {c.columnName: c.columnStats.validNumCount
            for c in load_column_configs(ccp) if not c.is_categorical()}
    for name, n_full in full.items():
        if not n_full:
            continue
        frac = half[name] / n_full
        assert 0.4 < frac < 0.6, (name, frac)     # ~50% of rows seen
    for c in load_column_configs(ccp):
        m = full_mean.get(c.columnName)
        if m and c.columnStats.mean and abs(m) > 0.5:
            assert abs(c.columnStats.mean - m) / abs(m) < 0.2


def test_munropat_exact_boundaries(model_set):
    """MunroPat dispatch: boundaries are EXACT data quantiles (not quantized
    to sketch-bucket edges) and the selection is recorded in ColumnConfig."""
    import pandas as pd
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.column_config import load_column_configs
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor

    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.stats.binningAlgorithm = "MunroPat"
    mc.stats.binningMethod = "EqualTotal"
    mc.stats.maxNumBin = 8
    mc.save(mcp)
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    ccs = load_column_configs(os.path.join(model_set, "ColumnConfig.json"))
    amount = next(c for c in ccs if c.columnName == "amount")
    assert amount.columnBinning.extra["binningAlgorithm"] == "MunroPat"
    bnds = amount.bin_boundary
    # every inner boundary must be an ACTUAL data value (exact quantile)
    df = pd.read_csv(mc.dataSet.dataPath, sep="|")
    vals = set(np.round(pd.to_numeric(df["amount"], errors="coerce")
                        .dropna().to_numpy(), 9))
    for b in bnds[1:]:
        assert np.round(b, 9) in vals, b
    # equal-total: inner bins hold roughly equal counts
    counts = np.asarray(amount.columnBinning.binCountPos[:-1]) + \
        np.asarray(amount.columnBinning.binCountNeg[:-1])
    assert counts.min() > 0.5 * counts.max() - 1


def test_correlation_pairwise_complete_and_categorical(model_set):
    """Correlation covers categoricals (pos-rate encoding) and each pair
    uses only both-valid rows (adjustCount semantics, not mean imputation)."""
    import pandas as pd
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor

    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set,
                          params={"correlation": True}).run() == 0
    path = os.path.join(model_set, "correlation.csv")
    df = pd.read_csv(path, index_col=0)
    assert "country" in df.columns and "channel" in df.columns  # categorical
    assert "amount" in df.columns
    # symmetric with unit diagonal
    m = df.to_numpy()
    np.testing.assert_allclose(np.diag(m), 1.0)
    np.testing.assert_allclose(m, m.T, atol=1e-5)
    # pairwise-complete against pandas on the raw csv (amount has missing)
    mc = ModelConfig.load(os.path.join(model_set, "ModelConfig.json"))
    raw = pd.read_csv(mc.dataSet.dataPath, sep="|")
    expect = pd.to_numeric(raw["amount"], errors="coerce").corr(
        pd.to_numeric(raw["age_days"], errors="coerce"))
    np.testing.assert_allclose(df.loc["amount", "age_days"], expect,
                               atol=1e-4)


# ------------------------------------------------- fused one-pass sweep
def test_fused_sweep_bit_matches_two_pass(rng):
    """Resident fused sweep (chunks retained on device, ONE read + ONE
    H2D) must be BIT-identical to the two-pass flow — same kernels, same
    inputs, same order."""
    n, C = 24000, 4
    x = rng.normal(5, 3, size=(n, C))
    x[:, 2] *= 50
    valid = rng.random((n, C)) > 0.07
    y = (rng.random(n) < 0.3).astype(float)
    w = rng.uniform(0.5, 2.0, n)

    two = NumericAccumulator(n_cols=C, num_buckets=256)
    for s in range(0, n, 7000):
        two.update_moments(x[s:s + 7000], valid[s:s + 7000])
    two.finalize_range()
    for s in range(0, n, 7000):
        two.update_histogram(x[s:s + 7000], valid[s:s + 7000],
                             y[s:s + 7000], w[s:s + 7000])
    one = NumericAccumulator(n_cols=C, num_buckets=256)
    for s in range(0, n, 7000):
        one.update_fused(x[s:s + 7000], valid[s:s + 7000], y[s:s + 7000],
                         w[s:s + 7000])
    one.finalize_fused()
    ra = two.finalize_sketch(BinningMethod.EqualTotal, 12)
    rb = one.finalize_sketch(BinningMethod.EqualTotal, 12)
    for c in range(C):
        np.testing.assert_array_equal(ra[0][c], rb[0][c])   # boundaries
        np.testing.assert_array_equal(ra[1][c], rb[1][c])   # bin stats
    np.testing.assert_array_equal(ra[2], rb[2])             # percentiles
    np.testing.assert_array_equal(ra[3], rb[3])             # distinct


def test_fused_sweep_overflow_refinement_within_bucket(rng):
    """Past the device budget the fused sweep accumulates on the
    PROVISIONAL grid and refines on device: counts conserved exactly,
    boundaries within one provisional bucket of the exact sweep."""
    n, C, K = 24000, 3, 256
    x = rng.normal(0, 2, size=(n, C))
    valid = rng.random((n, C)) > 0.05
    y = (rng.random(n) < 0.3).astype(float)
    w = np.ones(n)
    chunk = 6000
    exact_acc = NumericAccumulator(n_cols=C, num_buckets=K)
    for s in range(0, n, chunk):
        exact_acc.update_moments(x[s:s + chunk], valid[s:s + chunk])
    exact_acc.finalize_range()
    for s in range(0, n, chunk):
        exact_acc.update_histogram(x[s:s + chunk], valid[s:s + chunk],
                                   y[s:s + chunk], w[s:s + chunk])
    # budget fits ~1.5 chunks: chunks 2..4 go through the provisional grid
    budget = int(1.5 * chunk * (5 * C + 8))
    fused = NumericAccumulator(n_cols=C, num_buckets=K,
                               fused_budget=budget)
    for s in range(0, n, chunk):
        fused.update_fused(x[s:s + chunk], valid[s:s + chunk],
                           y[s:s + chunk], w[s:s + chunk])
    assert fused._prov_hist_dev is not None     # overflow really happened
    fused.finalize_fused()
    ra = exact_acc.finalize_sketch(BinningMethod.EqualTotal, 10)
    rb = fused.finalize_sketch(BinningMethod.EqualTotal, 10)
    # total counts conserved exactly (valid cells all land somewhere)
    tot_a = np.sum([g[:, :2].sum() for g in ra[1]])
    tot_b = np.sum([g[:, :2].sum() for g in rb[1]])
    assert tot_a == tot_b
    # boundaries within ~1 provisional bucket (1.5x range / K)
    for c in range(C):
        span = (exact_acc.hi[c] - exact_acc.lo[c]) * 1.5 / K
        m = min(len(ra[0][c]), len(rb[0][c]))
        np.testing.assert_allclose(ra[0][c][1:m], rb[0][c][1:m],
                                   atol=1.01 * span)


def test_fused_sweep_is_stats_default_and_matches_two_pass(model_set):
    """End-to-end: the stats step defaults to the fused sweep and writes
    the SAME ColumnConfig stats the two-pass flow does
    (``-Dshifu.stats.onePass=false`` restores two-pass)."""
    import json
    import shutil

    from shifu_tpu.config import environment

    set2 = model_set + "_twopass"
    shutil.copytree(model_set, set2)
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    environment.set_property("shifu.stats.onePass", "false")
    try:
        assert InitProcessor(set2).run() == 0
        assert StatsProcessor(set2, params={}).run() == 0
    finally:
        environment.set_property("shifu.stats.onePass", "true")
    cc1 = json.load(open(os.path.join(model_set, "ColumnConfig.json")))
    cc2 = json.load(open(os.path.join(set2, "ColumnConfig.json")))
    assert cc1 == cc2


def test_num_buckets_must_be_mxu_tile_aligned():
    """The fine-histogram bucket axis must stay a multiple of 64 in
    [64, 4096] — the two-level one-hot kernel's tile factorization
    (hi*64+lo); a misaligned count would silently fall off the MXU
    path."""
    for bad in (100, 63, 4097, 8192, 0):
        with pytest.raises(ValueError, match="MXU-tile-aligned"):
            NumericAccumulator(n_cols=3, num_buckets=bad)
    NumericAccumulator(n_cols=3, num_buckets=64)
    NumericAccumulator(n_cols=3, num_buckets=4096)
