"""Cross-process telemetry aggregation suite: merged monitor over N
telemetry dirs (tagged table, merged quorum, per-proc step-lag table),
heartbeat-derived clock-offset normalization (a skewed host is neither
mis-flagged stale nor left on its own time axis), merged timeline
export (per-(dir,pid) process rows), merged analysis report, and the
CLI wiring for ``monitor --aggregate`` / ``analysis --telemetry
--aggregate``."""

import json
import os
import time

import pytest

from shifu_tpu import obs
from shifu_tpu.obs import monitor as monitor_mod
from shifu_tpu.obs import timeline as timeline_mod

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _reset():
    obs.reset_for_tests()
    yield
    obs.reset_for_tests()


def _make_proc_dir(base, name, proc, rows, skew_s=0.0, now=None,
                   step="TRAIN", state="running", with_trace=True):
    """One process's telemetry dir: a health record whose embedded ts is
    ``skew_s`` ahead of the file mtime (a skewed host clock), plus a
    tiny trace on the same skewed clock."""
    now = time.time() if now is None else now
    d = os.path.join(base, name)
    hd = os.path.join(d, "telemetry", "health")
    os.makedirs(hd, exist_ok=True)
    path = os.path.join(hd, f"{proc}.json")
    with open(path, "w") as f:
        json.dump({"proc": proc, "step": step, "state": state,
                   "ts": now + skew_s, "started_ts": now + skew_s - 60,
                   "last_progress_ts": now + skew_s - 1,
                   "interval_s": 5.0, "rows": rows, "pid": 4242}, f)
    os.utime(path, (now, now))            # mtime = the common clock
    if with_trace:
        with open(os.path.join(d, "telemetry", "trace.jsonl"), "w") as f:
            f.write(json.dumps(
                {"kind": "meta", "schema_version": obs.SCHEMA_VERSION,
                 "step": step, "ts": now + skew_s, "pid": 4242}) + "\n")
            f.write(json.dumps(
                {"kind": "span", "name": "process", "id": 1,
                 "parent": None, "ts": now + skew_s, "dur_s": 2.0,
                 "attrs": {"rows": rows}}) + "\n")
    return d


def test_clock_offset_estimation(tmp_path):
    now = time.time()
    d0 = _make_proc_dir(str(tmp_path), "w0", "train-0", 100, 0.0, now)
    d1 = _make_proc_dir(str(tmp_path), "w1", "train-1", 100, 300.0, now)
    assert monitor_mod.dir_clock_offset(d0) == 0.0
    assert monitor_mod.dir_clock_offset(d1) == pytest.approx(300.0,
                                                             abs=2.0)
    # sub-threshold jitter collapses to zero
    d2 = _make_proc_dir(str(tmp_path), "w2", "train-2", 1, 0.4, now)
    assert monitor_mod.dir_clock_offset(d2) == 0.0
    assert monitor_mod.dir_clock_offset(str(tmp_path / "absent")) == 0.0


def test_aggregate_normalizes_skewed_clock(tmp_path):
    """A host whose clock runs 5 min ahead must read LIVE after
    normalization (raw classification would call its heartbeat
    impossibly fresh and its past-self stale) — and a genuinely dead
    skewed host still reads stale."""
    now = time.time()
    d0 = _make_proc_dir(str(tmp_path), "w0", "train-0", 5000, 0.0, now)
    d1 = _make_proc_dir(str(tmp_path), "w1", "train-1", 3200, 300.0, now)
    recs, counts = monitor_mod.aggregate_records([d0, d1], now=now)
    assert counts == {"live": 2}
    by = {r["proc"]: r for r in recs}
    assert by["train-1"]["clock_offset_s"] == pytest.approx(300.0,
                                                            abs=2.0)
    assert abs(by["train-1"]["age_s"]) < 5.0     # normalized, not -300
    # dead skewed host: heartbeat 60s old in ITS OWN clock domain
    d2 = _make_proc_dir(str(tmp_path), "w2", "train-2", 10, 300.0,
                        now - 60)
    recs, counts = monitor_mod.aggregate_records([d0, d1, d2], now=now)
    assert counts == {"live": 2, "stale": 1}


def test_aggregate_render_and_step_lag(tmp_path):
    """ACCEPTANCE: monitor --aggregate over >= 2 process telemetry dirs
    renders ONE merged report with a per-proc step-lag table."""
    now = time.time()
    d0 = _make_proc_dir(str(tmp_path), "w0", "train-0", 5000, 0.0, now)
    d1 = _make_proc_dir(str(tmp_path), "w1", "train-1", 3200, 0.0, now)
    text = monitor_mod.render_aggregate([d0, d1], now=now)
    assert "merged monitor over 2 telemetry dir(s)" in text
    assert "train-0" in text and "train-1" in text
    assert "w0" in text and "w1" in text
    assert "quorum 2/2" in text
    assert "per-proc step lag" in text
    lag = monitor_mod.step_lag_table(
        monitor_mod.aggregate_records([d0, d1], now=now)[0], now=now)
    by = {r["proc"]: r for r in lag}
    assert by["train-0"]["rows_lag"] == 0          # the front-runner
    assert by["train-1"]["rows_lag"] == 1800
    assert by["train-1"]["step"] == "TRAIN"
    # empty dirs: a message, not a traceback
    assert "no health records" in monitor_mod.render_aggregate(
        [str(tmp_path / "nothing")])


def test_aggregate_json_doc_and_exit_code(tmp_path):
    now = time.time()
    d0 = _make_proc_dir(str(tmp_path), "w0", "train-0", 100, 0.0, now)
    d1 = _make_proc_dir(str(tmp_path), "w1", "train-1", 90, 0.0,
                        now - 60)                  # stale
    doc, rc = monitor_mod.aggregate_json([d0, d1], now=now)
    assert rc == monitor_mod.EXIT_UNHEALTHY
    assert doc["kind"] == "monitor_aggregate"
    assert doc["schema_version"] == obs.SCHEMA_VERSION
    assert doc["summary"]["total"] == 2
    assert doc["summary"]["counts"]["stale"] == 1
    assert len(doc["step_lag"]) == 2
    assert set(doc["clock_offsets"]) == {"w0", "w1"}
    json.dumps(doc)                                # serializable
    # all healthy -> 0
    doc, rc = monitor_mod.aggregate_json([d0], now=now)
    assert rc == 0


def test_merged_timeline_normalizes_and_separates_procs(tmp_path):
    """ACCEPTANCE (timeline half): merged export gives each (dir, pid)
    its own process row, labels it with the dir, and pulls a skewed
    dir's spans back onto the common clock axis."""
    now = time.time()
    d0 = _make_proc_dir(str(tmp_path), "w0", "train-0", 100, 0.0, now)
    d1 = _make_proc_dir(str(tmp_path), "w1", "train-1", 90, 300.0, now)
    out = timeline_mod.export_merged_timeline(
        [d0, d1], str(tmp_path / "merged.json"))
    doc = json.load(open(out))
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in spans} == {1, 2}     # distinct per dir
    # both procs' spans land within seconds on the normalized axis,
    # not 300s apart
    assert abs(spans[0]["ts"] - spans[1]["ts"]) < 5_000_000
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert any("w0/" in n for n in names)
    assert any("w1/" in n for n in names)
    assert doc["otherData"]["clock_offsets"]["w1"] == pytest.approx(
        300.0, abs=2.0)
    # no readable traces -> None
    assert timeline_mod.export_merged_timeline(
        [str(tmp_path / "none")], str(tmp_path / "no.json")) is None


def test_merged_report_renders_all_dirs(tmp_path):
    from shifu_tpu.obs.report import render_telemetry_merged
    now = time.time()
    d0 = _make_proc_dir(str(tmp_path), "w0", "train-0", 5000, 0.0, now)
    d1 = _make_proc_dir(str(tmp_path), "w1", "train-1", 3200, 120.0, now)
    text = render_telemetry_merged([d0, d1])
    assert "merged telemetry over 2 dir(s)" in text
    assert text.count("== TRAIN") == 2             # both span trees
    assert "clock offset +120" in text
    assert "per-proc step lag" in text
    assert "train-1" in text


def test_cli_monitor_and_analysis_aggregate(tmp_path, capsys):
    from shifu_tpu.cli import main
    now = time.time()
    d0 = _make_proc_dir(str(tmp_path), "w0", "train-0", 5000, 0.0, now)
    d1 = _make_proc_dir(str(tmp_path), "w1", "train-1", 3200, 0.0, now)
    assert main(["monitor", "--once", "--aggregate", d0, d1]) == 0
    out = capsys.readouterr().out
    assert "merged monitor" in out and "per-proc step lag" in out
    # --json carries the health exit code; both live -> 0
    assert main(["monitor", "--once", "--json",
                 "--aggregate", d0, d1]) == 0
    doc = json.loads(capsys.readouterr().out.strip())
    assert doc["kind"] == "monitor_aggregate"
    # analysis --telemetry --aggregate: one merged report
    assert main(["analysis", "--telemetry", "--aggregate", d0, d1]) == 0
    out = capsys.readouterr().out
    assert "merged telemetry" in out and "per-proc step lag" in out
    # analysis --telemetry --timeline --aggregate: one merged trace
    tl = str(tmp_path / "tl.json")
    assert main(["analysis", "--telemetry", "--timeline", tl,
                 "--aggregate", d0, d1]) == 0
    assert "timeline ->" in capsys.readouterr().out
    assert {e["pid"] for e in json.load(open(tl))["traceEvents"]
            if e["ph"] == "X"} == {1, 2}
