"""Elastic multi-controller plane — fast in-process suite (tier-1).

Unit-tests the quorum state machine (close on quorum, close on timeout
with a straggler, bounded-staleness late handling, membership epoch
bumps on leave/rejoin), the exclusive close commit, the coordinator
connect-retry ladder, the monitor's QUORUM LOST flag, and the streamed
trainer's elastic hook — all without subprocesses (injectable clocks,
file boards under tmp_path).  The real kill-a-controller drill lives in
``tests/test_multihost.py``.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from shifu_tpu import faults, obs
from shifu_tpu.config import environment
from shifu_tpu.obs import monitor as monitor_mod
from shifu_tpu.parallel import elastic as el

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.dcn


@pytest.fixture(autouse=True)
def _clean_env():
    environment.reset_for_tests()
    faults.reset_for_tests()
    yield
    environment.reset_for_tests()
    faults.reset_for_tests()
    obs.set_enabled(False)


def _ctx(tmp_path, proc, cfg, clock=None):
    """In-process context: no heartbeat thread; optional fake clock
    (a one-element list advanced by sleep)."""
    kwargs = {}
    if clock is not None:
        kwargs = {"now_fn": lambda: clock[0],
                  "sleep_fn": lambda s: clock.__setitem__(0, clock[0] + s)}
    return el.ElasticContext(str(tmp_path), proc, cfg=cfg,
                             heartbeat=False, **kwargs)


def _pay(v, n=3):
    return {"g": np.full(n, float(v), np.float32)}


# ------------------------------------------------------ pure state machine
def test_quorum_needed_math():
    # the reference's shape: 97% of 1000 workers, 2s timeout
    assert el.quorum_needed(1000, 0.97) == 970
    assert el.quorum_needed(2, 0.97) == 2       # both of a pair
    assert el.quorum_needed(1, 0.97) == 1
    assert el.quorum_needed(0, 0.97) == 1       # a lone survivor proceeds
    assert el.quorum_needed(3, 0.5) == 2


def test_step_closes_on_quorum():
    cfg = el.ElasticConfig(quorum_frac=0.6, step_timeout_ms=2000)
    qs = el.QuorumStep(step=0, cfg=cfg, live={"a", "b", "c"},
                       opened_at=100.0)
    assert qs.needed == 2
    assert qs.decide(100.0) is None
    qs.offer("a")
    assert qs.decide(100.1) is None             # 1 of 2 needed
    qs.offer("b")
    assert qs.decide(100.2) == el.CLOSE_QUORUM  # quorum, before deadline
    assert qs.stragglers() == ["c"]


def test_step_closes_on_timeout_with_straggler():
    cfg = el.ElasticConfig(quorum_frac=1.0, step_timeout_ms=2000)
    qs = el.QuorumStep(step=0, cfg=cfg, live={"a", "b"}, opened_at=100.0)
    qs.offer("a")
    assert qs.decide(101.9) is None             # pre-deadline: wait
    assert qs.decide(102.1) == el.CLOSE_TIMEOUT
    assert qs.stragglers() == ["b"]
    # a timeout close still needs one contribution
    qs2 = el.QuorumStep(step=1, cfg=cfg, live={"a"}, opened_at=100.0)
    assert qs2.decide(200.0) is None


def test_live_set_shrink_unblocks_quorum():
    """The worker-loss masking primitive: when the dead peer drops out
    of the live set (heartbeat staleness), needed shrinks and the
    survivor closes by quorum, not timeout."""
    cfg = el.ElasticConfig(quorum_frac=0.97, step_timeout_ms=60000)
    qs = el.QuorumStep(step=0, cfg=cfg, live={"a", "b"}, opened_at=0.0)
    qs.offer("a")
    assert qs.decide(1.0) is None
    qs.update_live({"a"})                       # b declared dead
    assert qs.decide(1.0) == el.CLOSE_QUORUM


# ----------------------------------------------------------- file board
def test_payload_roundtrip_and_board_contributions(tmp_path):
    board = el.StepBoard(str(tmp_path / "steps"))
    board.ensure()
    pay = {"g": np.arange(5, dtype=np.float32),
           "stats": np.ones((2, 4), np.float32)}
    assert el.decode_payload(el.encode_payload(pay))["g"].tolist() == \
        pay["g"].tolist()
    board.contribute(3, "ctrl-0", pay, epoch=1)
    got = board.contributions(3)
    assert set(got) == {"ctrl-0"}
    dec = el.decode_payload(got["ctrl-0"]["payload"])
    assert np.array_equal(dec["g"], pay["g"])
    assert np.array_equal(dec["stats"], pay["stats"])
    assert board.has_contribution(3, "ctrl-0")
    assert not board.has_contribution(3, "ctrl-1")
    assert board.last_closed_step() == -1


def test_exclusive_close_single_winner(tmp_path):
    """Two racing closers: exactly ONE owns the close record; the loser
    reads the winner's aggregate (never two truths for one step)."""
    b1 = el.StepBoard(str(tmp_path / "steps"))
    b2 = el.StepBoard(str(tmp_path / "steps"))
    b1.ensure()
    d1 = {"step": 0, "by": "ctrl-0", "payload": el.encode_payload(_pay(1))}
    d2 = {"step": 0, "by": "ctrl-1", "payload": el.encode_payload(_pay(2))}
    won1 = b1.try_close(0, d1)
    won2 = b2.try_close(0, d2)
    assert won1 and not won2
    assert b2.close_doc(0)["by"] == "ctrl-0"
    assert b1.last_closed_step() == 0


# -------------------------------------------------------------- protocol
def test_two_controllers_close_and_adopt_same_bits(tmp_path):
    cfg = el.ElasticConfig(quorum_frac=1.0, step_timeout_ms=60000)
    a = _ctx(tmp_path, "ctrl-0", cfg).start()
    b = _ctx(tmp_path, "ctrl-1", cfg).start()
    b.board.contribute(0, "ctrl-1", _pay(2), epoch=1)
    res_a = a.step(0, _pay(1))
    assert res_a.reason == el.CLOSE_QUORUM
    assert res_a.contributors == ["ctrl-0", "ctrl-1"]
    assert np.array_equal(res_a.payload["g"],
                          np.full(3, 3.0, np.float32))
    # the slower controller ADOPTS the committed aggregate, bit-for-bit
    res_b = b.step(0, _pay(2))
    assert np.array_equal(res_b.payload["g"], res_a.payload["g"])
    assert res_b.closed_by == "ctrl-0"
    assert a.steps_closed == 1 and b.steps_closed == 0


def test_timeout_close_with_fake_clock(tmp_path):
    cfg = el.ElasticConfig(quorum_frac=1.0, step_timeout_ms=2000)
    clock = [1000.0]
    a = _ctx(tmp_path, "ctrl-0", cfg, clock).start()
    a.board.announce("ctrl-1")                  # a peer that never shows
    res = a.step(0, _pay(1))
    assert res.reason == el.CLOSE_TIMEOUT
    assert res.contributors == ["ctrl-0"]
    assert res.stragglers == ["ctrl-1"]
    assert a.step_timeouts == 1
    assert clock[0] >= 1002.0                   # the deadline was honored


def test_late_contribution_applied_within_staleness(tmp_path):
    cfg = el.ElasticConfig(quorum_frac=1.0, step_timeout_ms=2000,
                           staleness=2)
    clock = [0.0]
    a = _ctx(tmp_path, "ctrl-0", cfg, clock).start()
    a.board.announce("ctrl-1")
    r0 = a.step(0, _pay(1))                     # times out without b
    assert r0.reason == el.CLOSE_TIMEOUT
    # b's step-0 work lands LATE, inside the staleness window
    a.board.contribute(0, "ctrl-1", _pay(10), late=True)
    r1 = a.step(1, _pay(2))
    assert (0, "ctrl-1") in r1.late_applied
    # step 1 aggregate = own 2s + b's late 10s
    assert np.array_equal(r1.payload["g"], np.full(3, 12.0, np.float32))
    assert a.late_applied == 1 and a.late_dropped == 0


def test_late_contribution_dropped_beyond_staleness(tmp_path):
    cfg = el.ElasticConfig(quorum_frac=1.0, step_timeout_ms=2000,
                           staleness=1)
    clock = [0.0]
    a = _ctx(tmp_path, "ctrl-0", cfg, clock).start()
    a.board.announce("ctrl-1")
    a.step(0, _pay(1))
    a.step(1, _pay(2))                          # window for step 0 passes
    a.board.contribute(0, "ctrl-1", _pay(10), late=True)
    r2 = a.step(2, _pay(3))                     # 2 - 0 > staleness=1
    assert r2.late_applied == []
    assert np.array_equal(r2.payload["g"], np.full(3, 3.0, np.float32))
    assert a.late_dropped == 1


def test_quorum_mode_drops_all_late(tmp_path):
    cfg = el.ElasticConfig(quorum_frac=1.0, step_timeout_ms=2000,
                           staleness=0)
    clock = [0.0]
    a = _ctx(tmp_path, "ctrl-0", cfg, clock).start()
    a.board.announce("ctrl-1")
    a.step(0, _pay(1))
    a.board.contribute(0, "ctrl-1", _pay(10), late=True)
    r1 = a.step(1, _pay(2))
    assert r1.late_applied == []
    assert np.array_equal(r1.payload["g"], np.full(3, 2.0, np.float32))
    assert a.late_dropped == 1


def test_membership_epoch_bumps_on_leave_and_rejoin(tmp_path):
    from shifu_tpu.obs.health import health_dir_for
    cfg = el.ElasticConfig()
    a = _ctx(tmp_path, "ctrl-0", cfg).start()
    b = _ctx(tmp_path, "ctrl-1", cfg).start()
    e0, members = a.board.current_epoch()
    assert set(members) == {"ctrl-0", "ctrl-1"}
    # ---- LEAVE: b's heartbeat goes stale -> it drops out, epoch bumps
    hd = health_dir_for(str(tmp_path))
    os.makedirs(hd, exist_ok=True)
    now = time.time()
    with open(os.path.join(hd, "ctrl-1.json"), "w") as f:
        json.dump({"proc": "ctrl-1", "state": "running",
                   "ts": now - 60, "last_progress_ts": now - 60,
                   "interval_s": 0.5}, f)
    a._refresh_live(reason="test-leave")
    e1, members = a.board.current_epoch()
    assert e1 == e0 + 1 and set(members) == {"ctrl-0"}
    # ---- REJOIN: b comes back (fresh beat, incarnation 2) -> bump again
    with open(os.path.join(hd, "ctrl-1.json"), "w") as f:
        json.dump({"proc": "ctrl-1", "state": "running",
                   "ts": time.time(), "last_progress_ts": time.time(),
                   "interval_s": 0.5}, f)
    b2 = _ctx(tmp_path, "ctrl-1", cfg).start()
    assert b2.rejoined and b2.incarnation == 2
    e2, members = a.board.current_epoch()
    assert e2 >= e1 + 1 and members.get("ctrl-1") == 2


def test_masked_straggler_adopts_committed_history(tmp_path):
    """A controller that starts LATE (or rejoins) walks the committed
    step prefix: every step() finds the close record and adopts the
    winner's aggregate — bit-identical history, no divergence."""
    cfg = el.ElasticConfig(quorum_frac=0.4, step_timeout_ms=60000)
    a = _ctx(tmp_path, "ctrl-0", cfg).start()
    front = [a.step(s, _pay(s + 1)) for s in range(3)]
    b = _ctx(tmp_path, "ctrl-1", cfg).start()
    for s in range(3):
        got = b.step(s, _pay(100))              # its own work arrives late
        assert np.array_equal(got.payload["g"], front[s].payload["g"])
    assert b.steps_closed == 0
    # closed_step() is the journal read a rejoiner replays
    assert b.closed_step(1) is not None
    assert b.closed_step(99) is None


# ----------------------------------------------- streamed trainer hook
def test_streamed_nn_elastic_single_controller_bit_equal(tmp_path):
    """The elastic hook must not perturb the math: a 1-controller
    elastic run (quorum of itself, f32 transport round-trips exactly)
    trains BIT-identical params to the plain streamed path."""
    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream, mask_fn_from_settings
    from shifu_tpu.models.nn import NNModelSpec
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.nn_trainer import (TrainSettings,
                                            train_ensemble_streamed)
    from shifu_tpu import ioutil

    rng = np.random.default_rng(3)
    N, D = 256, 6
    x = rng.normal(size=(N, D)).astype(np.float32)
    y = (rng.random(N) < 0.4).astype(np.float32)
    ddir = tmp_path / "data"
    os.makedirs(ddir)
    ioutil.atomic_savez(str(ddir / "part-00000.npz"), x=x, y=y,
                        w=np.ones(N, np.float32))
    ioutil.atomic_write_json(str(ddir / "schema.json"), {
        "outputNames": [f"c{i}" for i in range(D)],
        "columnNums": list(range(D)), "numShards": 1, "numRows": N})
    spec = NNModelSpec(input_dim=D, hidden_nodes=[4],
                       activations=["tanh"], loss="log")
    settings = TrainSettings(optimizer="ADAM", learning_rate=0.05,
                             epochs=3, batch_size=0, seed=5)
    mask_fn = mask_fn_from_settings(1, valid_rate=0.25, seed=5)
    mesh = device_mesh(n_ensemble=1)

    def run(elastic):
        stream = ShardStream(Shards.open(str(ddir)), ("x", "y", "w"), 128)
        return train_ensemble_streamed(stream, spec, settings, 1,
                                       mask_fn, mesh=mesh,
                                       elastic=elastic)
    plain = run(None)
    ctx = _ctx(tmp_path / "job", "ctrl-0",
               el.ElasticConfig(quorum_frac=1.0,
                                step_timeout_ms=60000)).start()
    elas = run(ctx)
    for pl, ell in zip(plain.params[0], elas.params[0]):
        for k in ("w", "b"):
            assert np.array_equal(np.asarray(pl[k]), np.asarray(ell[k]))
    assert plain.history == elas.history
    # epoch steps 0..2 + the final eval step all closed on the board
    assert ctx.board.last_closed_step() == settings.epochs


def test_streamed_nn_elastic_rejects_minibatch(tmp_path):
    from shifu_tpu.models.nn import NNModelSpec
    from shifu_tpu.train.nn_trainer import (TrainSettings,
                                            train_ensemble_streamed)
    ctx = _ctx(tmp_path, "ctrl-0", el.ElasticConfig())
    with pytest.raises(ValueError, match="full-batch"):
        train_ensemble_streamed(
            None, NNModelSpec(input_dim=2, hidden_nodes=[2],
                              activations=["tanh"]),
            TrainSettings(batch_size=32), 1, None, elastic=ctx)


def test_grad_codec_roundtrip_and_dtype_restore():
    import jax.numpy as jnp
    zero = [{"w": jnp.zeros((3, 2), jnp.bfloat16),
             "b": jnp.zeros((2,), jnp.float32)}]
    ravel, unravel = el.grad_codec(zero)
    tree = [{"w": jnp.full((3, 2), 1.5, jnp.bfloat16),
             "b": jnp.arange(2, dtype=jnp.float32)}]
    flat = ravel(tree)
    assert flat.dtype == np.float32 and flat.shape == (8,)
    back = unravel(flat)
    assert back[0]["w"].dtype == jnp.bfloat16
    assert back[0]["b"].dtype == jnp.float32
    assert np.array_equal(np.asarray(back[0]["b"]),
                          np.asarray(tree[0]["b"]))


# --------------------------------------------------- connect retry ladder
def test_initialize_distributed_retries_then_coded_error(monkeypatch):
    from shifu_tpu.config.errors import ShifuError
    from shifu_tpu.parallel.mesh import initialize_distributed

    calls = []

    def boom(*a, **k):
        calls.append(1)
        raise RuntimeError("connection refused (injected)")

    import jax
    monkeypatch.setattr(jax.distributed, "initialize", boom)
    environment.set_property("shifu.io.retries", "2")
    environment.set_property("shifu.io.retryBaseMs", "1")
    with pytest.raises(ShifuError) as e:
        initialize_distributed("localhost:1", num_processes=2,
                               process_id=0)
    assert e.value.error_code.code == 1063
    assert "after 3 attempt" in str(e.value)
    assert len(calls) == 3                      # 1 try + 2 retries


def test_initialize_distributed_succeeds_after_transient(monkeypatch):
    from shifu_tpu.parallel.mesh import initialize_distributed

    calls = []

    def flaky(*a, **k):
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("coordinator not up yet (injected)")

    import jax
    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    environment.set_property("shifu.io.retryBaseMs", "1")
    initialize_distributed("localhost:1", num_processes=2, process_id=0)
    assert len(calls) == 2


# ----------------------------------------------------- monitor QUORUM LOST
def _write_health(d, proc, age_s, state="running"):
    hd = os.path.join(d, "telemetry", "health")
    os.makedirs(hd, exist_ok=True)
    now = time.time()
    path = os.path.join(hd, f"{proc}.json")
    with open(path, "w") as f:
        json.dump({"proc": proc, "step": "TRAIN", "state": state,
                   "ts": now - age_s, "last_progress_ts": now - age_s,
                   "interval_s": 0.5, "rows": 100}, f)
    # age the mtime WITH the embedded ts: a genuinely dead process left
    # both behind (a mismatched pair reads as clock skew and the
    # aggregate's offset normalization would "revive" the record)
    os.utime(path, (now - age_s, now - age_s))


def test_monitor_quorum_lost_flag_and_exit(tmp_path):
    d0, d1 = str(tmp_path / "p0"), str(tmp_path / "p1")
    _write_health(d0, "ctrl-0", 0.0)
    _write_health(d1, "ctrl-1", 0.0)
    doc, rc = monitor_mod.aggregate_json([d0, d1])
    assert rc == 0 and not doc["summary"]["quorum_lost"]
    assert "QUORUM LOST" not in monitor_mod.render_aggregate([d0, d1])
    # one controller stops heartbeating: 1/2 = 50% < quorumFrac 0.97
    _write_health(d1, "ctrl-1", 60.0)
    doc, rc = monitor_mod.aggregate_json([d0, d1])
    assert rc == monitor_mod.EXIT_UNHEALTHY
    assert doc["summary"]["quorum_lost"] is True
    text = monitor_mod.render_aggregate([d0, d1])
    assert "QUORUM LOST" in text and "quorumFrac" in text
    # the threshold IS the protocol knob
    environment.set_property("shifu.dcn.quorumFrac", "0.4")
    doc, rc = monitor_mod.aggregate_json([d0, d1])
    assert not doc["summary"]["quorum_lost"]


def test_monitor_quorum_lost_cli_subprocess(tmp_path):
    """ACCEPTANCE (satellite): `shifu-tpu monitor --aggregate` flags
    QUORUM LOST and exits 3 when live members fall below quorumFrac."""
    d0, d1 = str(tmp_path / "p0"), str(tmp_path / "p1")
    _write_health(d0, "ctrl-0", 0.0)
    _write_health(d1, "ctrl-1", 60.0)           # dead without a final beat
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SHIFU_TPU_FAULTS", None)
    p = subprocess.run(
        [sys.executable, "-m", "shifu_tpu.cli", "monitor", "--once",
         "--aggregate", d0, d1],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert p.returncode == monitor_mod.EXIT_UNHEALTHY, p.stdout + p.stderr
    assert "QUORUM LOST" in p.stdout
    # healthy pair: flag off, exit 0
    _write_health(d1, "ctrl-1", 0.0)
    p = subprocess.run(
        [sys.executable, "-m", "shifu_tpu.cli", "monitor", "--once",
         "--aggregate", d0, d1],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "QUORUM LOST" not in p.stdout
