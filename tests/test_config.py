"""Config substrate tests: JSON round-trip + reference-contract compatibility."""

import json
import os

import pytest

from shifu_tpu.config import (Algorithm, ColumnConfig, ColumnFlag, ColumnType,
                              ModelConfig, NormType,
                              build_initial_column_configs,
                              load_column_configs, save_column_configs)
from shifu_tpu.config.jsonbean import parse_enum
from shifu_tpu.config.validator import ModelStep, ValidationError, probe

REFERENCE_STYLE_MODEL_CONFIG = {
    "basic": {"name": "cancer-judgement", "author": "", "description": None,
              "runMode": "local", "customPaths": None},
    "dataSet": {"source": "LOCAL", "dataPath": "./data", "dataDelimiter": "|",
                "headerPath": "./data/.pig_header", "headerDelimiter": "|",
                "filterExpressions": "", "weightColumnName": "column_3",
                "targetColumnName": "diagnosis", "posTags": ["M"], "negTags": ["B"],
                "metaColumnNameFile": None, "categoricalColumnNameFile": None},
    "stats": {"maxNumBin": 10, "binningMethod": "EqualPositive", "sampleRate": 1.0,
              "sampleNegOnly": False},
    "varSelect": {"forceEnable": True, "filterEnable": True, "filterNum": 200,
                  "filterBy": "KS",
                  "params": {"worker_sample_rate": 0.5}},
    "normalize": {"stdDevCutOff": 4.0, "sampleRate": 1.0, "sampleNegOnly": False},
    "train": {"baggingNum": 5, "baggingWithReplacement": True,
              "baggingSampleRate": 1.0, "validSetRate": 0.1, "trainOnDisk": False,
              "numTrainEpochs": 100, "algorithm": "NN",
              "params": {"NumHiddenLayers": 2, "ActivationFunc": ["Sigmoid", "Sigmoid"],
                         "NumHiddenNodes": [45, 45], "LearningRate": 0.1,
                         "Propagation": "Q"}},
    "evals": [{"name": "EvalA",
               "dataSet": {"source": "LOCAL", "dataPath": "./evaldata",
                           "dataDelimiter": "|"},
               "performanceBucketNum": 10, "performanceScoreSelector": "mean"}],
}


def test_model_config_loads_reference_style_json():
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    assert mc.basic.name == "cancer-judgement"
    assert mc.dataSet.posTags == ["M"] and mc.dataSet.negTags == ["B"]
    assert mc.train.algorithm == Algorithm.NN
    assert mc.train.params["NumHiddenNodes"] == [45, 45]
    assert mc.stats.binningMethod.name == "EqualPositive"
    assert len(mc.evals) == 1 and mc.evals[0].name == "EvalA"


def test_model_config_round_trip(tmp_path):
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    p = str(tmp_path / "ModelConfig.json")
    mc.save(p)
    mc2 = ModelConfig.load(p)
    assert mc2.to_dict()["dataSet"]["targetColumnName"] == "diagnosis"
    assert mc2.train.params == mc.train.params
    assert mc2.normalize.normType == NormType.ZSCALE  # default preserved


def test_unknown_keys_survive_round_trip(tmp_path):
    d = dict(REFERENCE_STYLE_MODEL_CONFIG)
    d["someFutureSection"] = {"a": 1}
    mc = ModelConfig.from_dict(d)
    p = str(tmp_path / "m.json")
    mc.save(p)
    with open(p) as f:
        out = json.load(f)
    assert out["someFutureSection"] == {"a": 1}


def test_enum_parse_case_insensitive():
    assert parse_enum(NormType, "zscale") == NormType.ZSCALE
    assert parse_enum(Algorithm, "gbt") == Algorithm.GBT
    with pytest.raises(ValueError):
        parse_enum(Algorithm, "nope")


def test_column_config_init_and_round_trip(tmp_path):
    header = ["id", "amount", "country", "tag", "w"]
    ccs = build_initial_column_configs(header, target="tag",
                                      meta_cols=["id"], categorical_cols=["country"],
                                      weight_col="w")
    assert ccs[0].columnFlag == ColumnFlag.Meta
    assert ccs[2].columnType == ColumnType.C
    assert ccs[3].is_target() and ccs[4].is_weight()
    ccs[1].columnStats.mean = 3.5
    ccs[1].columnBinning.binBoundary = [float("-inf"), 1.0, 2.0]
    p = str(tmp_path / "ColumnConfig.json")
    save_column_configs(ccs, p)
    back = load_column_configs(p)
    assert back[1].columnStats.mean == 3.5
    assert back[1].columnBinning.binBoundary[1] == 1.0
    assert back[3].columnFlag == ColumnFlag.Target


def test_validator_catches_problems():
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    probe(mc, ModelStep.TRAIN)  # valid
    mc.train.baggingNum = 0
    mc.train.validSetRate = 1.5
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.TRAIN)
    assert len(e.value.problems) == 2


def test_nn_param_consistency_validated():
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    mc.train.params["NumHiddenLayers"] = 3  # mismatch with 2 nodes/act lists
    with pytest.raises(ValidationError):
        probe(mc, ModelStep.TRAIN)


def test_out_of_order_steps_fail_with_coded_hint(model_set):
    """norm/train before stats/norm fail with ERROR_STEP_PRECONDITION and a
    'run X first' hint, not a deep traceback (verify-skill gotcha)."""
    import pytest
    from shifu_tpu.config.errors import ErrorCode, ShifuError
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    assert InitProcessor(model_set).run() == 0
    with pytest.raises(ShifuError) as ei:
        NormalizeProcessor(model_set, params={}).run()
    assert ei.value.error_code is ErrorCode.ERROR_STEP_PRECONDITION
    assert "stats" in str(ei.value)
    assert StatsProcessor(model_set, params={}).run() == 0
    with pytest.raises(ShifuError) as ei:
        TrainProcessor(model_set, params={}).run()
    assert "norm" in str(ei.value)
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0


def test_profile_json_written(model_set):
    """Per-step wall-clock + per-phase timers land in tmp/profile.json
    (SURVEY §5 tracing/profiling)."""
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0
    prof = json.load(open(os.path.join(model_set, "tmp", "profile.json")))
    assert prof["STATS"]["total_s"] > 0
    # the default stats plane is the fused one-pass sweep (moments +
    # histograms in one streamed read)
    assert "fused_sweep" in prof["STATS"]["phases_s"]
    assert "train" in prof["TRAIN"]["phases_s"]
    assert "load_data" in prof["TRAIN"]["phases_s"]


def test_probe_cross_list_column_conflicts(tmp_path):
    """Reference ModelInspector.checkColumnConf (:213-262): target vs
    meta/force lists, and pairwise list overlaps under forceEnable."""
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    meta_f = tmp_path / "meta.names"
    meta_f.write_text("diagnosis\ntxid\n")       # target in meta!
    frm = tmp_path / "rm.names"
    frm.write_text("txid\namount\n")             # txid also in meta
    mc.dataSet.metaColumnNameFile = str(meta_f)
    mc.varSelect.forceRemoveColumnNameFile = str(frm)
    mc.varSelect.forceEnable = True
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.STATS, str(tmp_path))
    text = "\n".join(e.value.problems)
    assert "target column must not be a meta column" in text
    assert "meta" in text and "forceRemove" in text


def test_probe_force_file_must_exist(tmp_path):
    """Reference ModelInspector.checkVarSelect (:316-357)."""
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    mc.varSelect.forceEnable = True
    mc.varSelect.forceSelectColumnNameFile = "no/such/file.names"
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.VARSELECT, str(tmp_path))
    assert any("does not exist" in p for p in e.value.problems)


def test_probe_stats_multiclass_binning_rules():
    """Reference ModelInspector.checkStatsConf (:263-305)."""
    from shifu_tpu.config.model_config import (BinningAlgorithm,
                                               BinningMethod)
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    mc.dataSet.posTags = ["a", "b", "c"]          # multi-class
    mc.dataSet.negTags = []
    mc.stats.binningMethod = BinningMethod.EqualPositive
    mc.stats.binningAlgorithm = BinningAlgorithm.MunroPat
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.STATS)
    text = "\n".join(e.value.problems)
    assert "EqualPositive" in text
    assert "SPDTI" in text


def test_probe_init_missing_datapath_flagged(tmp_path):
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    mc.dataSet.dataPath = "/no/such/data.csv"
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.INIT, str(tmp_path))
    assert any("does not exist" in p for p in e.value.problems)


def test_probe_init_missing_header_flagged(tmp_path):
    """Reference checkRawData probes headerPath too (:366-369)."""
    data = tmp_path / "d.csv"
    data.write_text("a|b\n1|2\n")
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    mc.dataSet.dataPath = str(data)
    mc.dataSet.headerPath = "/no/such/header"
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.INIT, str(tmp_path))
    assert any("headerPath" in p for p in e.value.problems)


def test_probe_stats_name_files_must_exist(tmp_path):
    """Reference probe() at STATS verifies meta/categorical name files
    (:121-131)."""
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    mc.dataSet.metaColumnNameFile = "no/meta.names"
    mc.dataSet.categoricalColumnNameFile = "no/cat.names"
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.STATS, str(tmp_path))
    text = "\n".join(e.value.problems)
    assert "metaColumnNameFile" in text
    assert "categoricalColumnNameFile" in text


def test_probe_post_correlation_metric_se_pairing():
    """Reference checkVarSelect :335-343."""
    from shifu_tpu.config.model_config import FilterBy
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    mc.varSelect.filterBy = FilterBy.KS
    mc.varSelect.postCorrelationMetric = "SE"
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.VARSELECT)
    assert any("postCorrelationMetric" in p for p in e.value.problems)
    mc.varSelect.filterBy = FilterBy.SE
    probe(mc, ModelStep.VARSELECT)               # both SE: valid


def test_probe_train_multiclass_cross_checks():
    """Reference checkTrainSetting :513-534: OVA algorithm restriction and
    NATIVE-RF impurity restriction."""
    from shifu_tpu.config.model_config import (Algorithm,
                                               MultipleClassification)
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    mc.dataSet.posTags = ["a", "b", "c"]
    mc.dataSet.negTags = []
    mc.train.algorithm = Algorithm.WDL
    mc.train.multiClassifyMethod = MultipleClassification.ONEVSALL
    mc.train.params = {}
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.TRAIN)
    assert any("one vs all" in p for p in e.value.problems)
    mc.train.algorithm = Algorithm.RF
    mc.train.multiClassifyMethod = MultipleClassification.NATIVE
    mc.train.params = {"Impurity": "variance"}
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.TRAIN)
    assert any("entropy/gini" in p for p in e.value.problems)


def test_probe_hinge_requires_svm():
    from shifu_tpu.config.model_config import Algorithm
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    mc.train.algorithm = Algorithm.NN
    mc.train.params = {"Loss": "hinge"}
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.TRAIN)
    assert any("SVM" in p for p in e.value.problems)


def test_probe_eval_semantic_checks(tmp_path):
    """Reference probe() EVAL loop: per-set data existence +
    scoreMetaColumnNameFile + bucket sanity."""
    mc = ModelConfig.from_dict(REFERENCE_STYLE_MODEL_CONFIG)
    ev = mc.evals[0]
    ev.dataSet.dataPath = "/no/such/eval.csv"
    ev.scoreMetaColumnNameFile = "no/score.meta"
    ev.performanceBucketNum = 0
    with pytest.raises(ValidationError) as e:
        probe(mc, ModelStep.EVAL, str(tmp_path))
    text = "\n".join(e.value.problems)
    assert "dataPath does not exist" in text
    assert "scoreMetaColumnNameFile" in text
    assert "performanceBucketNum" in text
