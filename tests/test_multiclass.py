"""Multi-class classification: K-class tags, NATIVE softmax NN, NATIVE
multiclass RF (per-class histogram channels), one-vs-all fan-out, and the
multi-class eval report (reference ``TrainModelProcessor.java:684-714``,
``dt/Impurity.java:368,553``, ``MultiClsTagPredictor``)."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _three_class(n=900, d=5, seed=0):
    """Linearly separable-ish 3-class data."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 3, n)
    centers = np.array([[2.0, 0, 0, 0, 0], [0, 2.0, 0, 0, 0],
                        [0, 0, 2.0, 0, 0]])
    x = rng.normal(size=(n, d)) * 0.5 + centers[y][:, :d]
    return x.astype(np.float32), y.astype(np.float32)


def test_tag_to_class():
    from shifu_tpu.data.reader import tag_to_class
    vals = np.array(["a", "b", "c", "a", "zz", " b "])
    out = tag_to_class(vals, ["a", "b", "c"])
    np.testing.assert_array_equal(out[:4], [0, 1, 2, 0])
    assert np.isnan(out[4])
    assert out[5] == 1.0  # whitespace-stripped


def test_multiclass_tree_kernel_pure_split():
    from shifu_tpu.ops.tree import grow_tree_jit, predict_tree
    rng = np.random.default_rng(0)
    n = 900
    y = np.repeat(np.arange(3), 300)
    bins = rng.integers(0, 4, size=(n, 3)).astype(np.int32)
    bins[:, 0] = y * 2
    stats = np.ones(n, np.float32)[:, None] * \
        np.asarray(jax.nn.one_hot(y, 3), np.float32)
    sf, lm, lv, _, _ = grow_tree_jit(
        jnp.asarray(bins), jnp.asarray(stats), jnp.zeros(3, bool),
        jnp.ones(3, bool), 8, 2, "entropy", 1.0, 0.0, 3)
    assert lv.shape == (7, 3)           # leaf class distributions
    pred = np.asarray(predict_tree(sf, lm, lv, jnp.asarray(bins), 2))
    assert (pred.argmax(1) == y).mean() == 1.0


def test_rf_native_multiclass_trains():
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf
    rng = np.random.default_rng(1)
    n = 1200
    y = rng.integers(0, 3, n).astype(np.float32)
    bins = rng.integers(0, 6, size=(n, 4)).astype(np.int32)
    bins[:, 0] = (y * 2).astype(np.int32)  # informative feature
    w = np.ones(n, np.float32)
    s = DTSettings(n_trees=5, depth=3, impurity="entropy", n_classes=3,
                   bagging_rate=1.0, seed=0)
    res = train_rf(bins, y, w, 8, None, s)
    assert res.trees_built == 5
    assert res.trees[0].leaf_value.ndim == 2       # [nodes, K]
    assert res.spec_kwargs["extra"]["n_classes"] == 3
    # misclassification errors, not losses: must be low on separable data
    assert res.train_error < 0.05
    assert res.valid_error < 0.10


def test_nn_native_multiclass_softmax():
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble
    from shifu_tpu.train.sampling import member_masks

    x, y = _three_class()
    spec = nn_model.NNModelSpec(input_dim=x.shape[1], hidden_nodes=[16],
                                activations=["tanh"], output_dim=3,
                                output_activation="softmax")
    tw, vw = member_masks(len(y), 1, valid_rate=0.2, sample_rate=1.0,
                          replacement=False, targets=y, seed=0)
    res = train_ensemble(x, y, tw, vw, spec,
                         TrainSettings(optimizer="ADAM", learning_rate=0.02,
                                       epochs=60, seed=0))
    probs = np.asarray(nn_model.forward(res.params[0], spec, jnp.asarray(x)))
    assert probs.shape == (len(y), 3)
    np.testing.assert_allclose(probs.sum(1), 1.0, atol=1e-4)
    assert (probs.argmax(1) == y).mean() > 0.9


def test_nn_ova_members_learn_their_class():
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble
    from shifu_tpu.train.sampling import member_masks

    x, y = _three_class()
    spec = nn_model.NNModelSpec(input_dim=x.shape[1], hidden_nodes=[8],
                                activations=["tanh"], loss="log")
    tw, vw = member_masks(len(y), 1, valid_rate=0.2, sample_rate=1.0,
                          replacement=False, targets=y, seed=0)
    tw, vw = np.repeat(tw, 3, axis=0), np.repeat(vw, 3, axis=0)
    y_members = np.stack([(y == k).astype(np.float32) for k in range(3)])
    res = train_ensemble(x, y, tw, vw, spec,
                         TrainSettings(optimizer="ADAM", learning_rate=0.02,
                                       epochs=60, seed=0),
                         y_members=y_members)
    # assembled OVA argmax must recover the class
    scores = np.stack([np.asarray(nn_model.forward(
        res.params[k], spec, jnp.asarray(x)))[:, 0] for k in range(3)], 1)
    assert (scores.argmax(1) == y).mean() > 0.9


@pytest.fixture
def mc_model_set(tmp_path):
    """A 3-class model set (csv + scaffold) ready for init."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import create_new_model

    rng = np.random.default_rng(5)
    n = 1500
    y = rng.integers(0, 3, n)
    f1 = rng.normal(size=n) + (y == 0) * 2.2
    f2 = rng.normal(size=n) + (y == 1) * 2.2
    f3 = rng.normal(size=n) + (y == 2) * 2.2
    kind = np.asarray(["low", "mid", "high"])[y]
    # 10% label noise on the categorical hint
    flip = rng.random(n) < 0.1
    kind[flip] = rng.choice(["low", "mid", "high"], flip.sum())
    tag = np.asarray(["alpha", "beta", "gamma"])[y]
    rows = ["id|f1|f2|f3|kind|tag"]
    for i in range(n):
        rows.append(f"r{i}|{f1[i]:.5f}|{f2[i]:.5f}|{f3[i]:.5f}|"
                    f"{kind[i]}|{tag[i]}")
    csv_path = tmp_path / "mc.csv"
    csv_path.write_text("\n".join(rows) + "\n")
    meta = tmp_path / "meta.names"
    meta.write_text("id\n")

    mdir = create_new_model("mctest", base_dir=str(tmp_path))
    mcp = os.path.join(mdir, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.dataSet.dataPath = str(csv_path)
    mc.dataSet.dataDelimiter = "|"
    mc.dataSet.targetColumnName = "tag"
    mc.dataSet.posTags = ["alpha", "beta", "gamma"]
    mc.dataSet.negTags = []
    mc.dataSet.metaColumnNameFile = str(meta)
    # per-class binning methods are rejected for multi-class targets
    # (reference ModelInspector.checkStatsConf)
    from shifu_tpu.config.model_config import BinningMethod
    mc.stats.binningMethod = BinningMethod.EqualTotal
    mc.train.baggingNum = 1
    mc.train.numTrainEpochs = 40
    mc.evals[0].dataSet.dataPath = str(csv_path)
    mc.evals[0].dataSet.dataDelimiter = "|"
    mc.save(mcp)
    return mdir


def _run_steps(mdir):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor

    assert InitProcessor(mdir).run() == 0
    assert StatsProcessor(mdir, params={}).run() == 0
    assert NormalizeProcessor(mdir, params={}).run() == 0
    assert TrainProcessor(mdir, params={}).run() == 0
    assert EvalProcessor(mdir, params={"run_eval": "Eval1"}).run() == 0
    perf = os.path.join(mdir, "evals", "Eval1", "EvalPerformance.json")
    # path via PathFinder may differ; search for it
    hits = []
    for root, _, files in os.walk(mdir):
        if "EvalPerformance.json" in files:
            hits.append(os.path.join(root, "EvalPerformance.json"))
    assert hits, "no EvalPerformance.json written"
    with open(hits[0]) as f:
        return json.load(f)


def test_e2e_nn_native_multiclass(mc_model_set):
    from shifu_tpu.config import ModelConfig
    mcp = os.path.join(mc_model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "NN"
    mc.train.params = {"NumHiddenNodes": [12], "Propagation": "ADAM",
                       "LearningRate": 0.02}
    mc.save(mcp)
    rep = _run_steps(mc_model_set)
    assert rep["nClasses"] == 3
    assert rep["accuracy"] > 0.85
    assert rep["macroAuc"] > 0.9
    assert len(rep["confusionMatrix"]) == 3


def test_e2e_rf_native_multiclass(mc_model_set):
    from shifu_tpu.config import ModelConfig
    mcp = os.path.join(mc_model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "RF"
    mc.train.params = {"TreeNum": 8, "MaxDepth": 4, "Impurity": "entropy"}
    mc.save(mcp)
    rep = _run_steps(mc_model_set)
    assert rep["accuracy"] > 0.8
    assert rep["macroAuc"] > 0.85


def test_e2e_gbt_ova_multiclass(mc_model_set):
    """GBT has no NATIVE multiclass: must auto-route one-vs-all and save
    one model per class."""
    from shifu_tpu.config import ModelConfig
    mcp = os.path.join(mc_model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "GBT"
    mc.train.params = {"TreeNum": 8, "MaxDepth": 3, "Loss": "log",
                       "LearningRate": 0.2}
    mc.save(mcp)
    rep = _run_steps(mc_model_set)
    models = [f for f in os.listdir(os.path.join(mc_model_set, "models"))
              if f.startswith("model")]
    assert len(models) == 3                       # one forest per class
    assert rep["accuracy"] > 0.8


def test_e2e_gbt_ova_streamed(mc_model_set):
    """OVA over streamed data (VERDICT r3 item 6): each class sweeps its
    own out-of-core ResidentCache; models per class + sane accuracy."""
    from shifu_tpu.config import ModelConfig, environment
    mcp = os.path.join(mc_model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "GBT"
    mc.train.params = {"TreeNum": 6, "MaxDepth": 3, "Loss": "log",
                       "LearningRate": 0.2}
    mc.save(mcp)
    environment.set_property("shifu.train.streaming", "on")
    try:
        rep = _run_steps(mc_model_set)
    finally:
        environment.set_property("shifu.train.streaming", "auto")
    models = [f for f in os.listdir(os.path.join(mc_model_set, "models"))
              if f.startswith("model")]
    assert len(models) == 3
    assert rep["accuracy"] > 0.8


def test_ova_resume_restarts_at_unfinished_class(mc_model_set):
    """Killing an OVA run between classes resumes at the first unfinished
    class — finished class models are NOT retrained (VERDICT r3 item 8)."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    mcp = os.path.join(mc_model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "GBT"
    mc.train.params = {"TreeNum": 5, "MaxDepth": 3, "Loss": "log",
                       "LearningRate": 0.2}
    mc.save(mcp)
    assert InitProcessor(mc_model_set).run() == 0
    assert StatsProcessor(mc_model_set, params={}).run() == 0
    assert NormalizeProcessor(mc_model_set, params={}).run() == 0
    assert TrainProcessor(mc_model_set, params={}).run() == 0
    mdir = os.path.join(mc_model_set, "models")
    # simulate a crash after class 1: class 2's model never landed
    os.remove(os.path.join(mdir, "model2.gbt"))
    m0 = os.path.getmtime(os.path.join(mdir, "model0.gbt"))
    m1 = os.path.getmtime(os.path.join(mdir, "model1.gbt"))
    assert TrainProcessor(mc_model_set, params={"resume": True}).run() == 0
    assert os.path.getmtime(os.path.join(mdir, "model0.gbt")) == m0
    assert os.path.getmtime(os.path.join(mdir, "model1.gbt")) == m1
    assert os.path.isfile(os.path.join(mdir, "model2.gbt"))


def test_e2e_gbt_ova_bagged(mc_model_set):
    """OVA x bagging: one full bagging job per class (reference
    TrainModelProcessor.java:684-714) — B*K models, each stamped with its
    class_index; the scorer averages contributors per class."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.models import tree as tree_model
    mcp = os.path.join(mc_model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "GBT"
    mc.train.baggingNum = 2
    mc.train.params = {"TreeNum": 6, "MaxDepth": 3, "Loss": "log",
                       "LearningRate": 0.2}
    mc.save(mcp)
    rep = _run_steps(mc_model_set)
    mdir = os.path.join(mc_model_set, "models")
    models = sorted(f for f in os.listdir(mdir) if f.startswith("model"))
    assert len(models) == 6                       # 2 bags x 3 classes
    by_class = {}
    for f in models:
        spec, _ = tree_model.load_model(os.path.join(mdir, f))
        by_class.setdefault(spec.extra["class_index"], []).append(f)
    assert {len(v) for v in by_class.values()} == {2}
    assert rep["accuracy"] > 0.8
    # bags are genuinely different forests (per-member validation splits —
    # default sampling would otherwise duplicate GBT bags byte-for-byte)
    f0, f1 = by_class[0]
    _, t0 = tree_model.load_model(os.path.join(mdir, f0))
    _, t1 = tree_model.load_model(os.path.join(mdir, f1))
    assert any((a.split_feat != b.split_feat).any() or
               (a.leaf_value != b.leaf_value).any()
               for a, b in zip(t0, t1))
    # resume skips complete classes: drop class 2's bags, keep the rest
    from shifu_tpu.pipeline.train import TrainProcessor
    for f in by_class[2]:
        os.remove(os.path.join(mdir, f))
    kept = {f: os.path.getmtime(os.path.join(mdir, f))
            for c in (0, 1) for f in by_class[c]}
    assert TrainProcessor(mc_model_set, params={"resume": True}).run() == 0
    for f, mtime in kept.items():
        assert os.path.getmtime(os.path.join(mdir, f)) == mtime
    for f in by_class[2]:
        assert os.path.isfile(os.path.join(mdir, f))


def test_e2e_nn_ova_streamed(mc_model_set):
    """NN ONEVSALL over streamed data: member b*K+k binarizes its class
    on device inside the streamed trainer (closes the last 'no streamed
    mode yet' fallback)."""
    from shifu_tpu.config import ModelConfig, environment
    from shifu_tpu.config.model_config import MultipleClassification
    mcp = os.path.join(mc_model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "NN"
    mc.train.multiClassifyMethod = MultipleClassification.ONEVSALL
    mc.train.baggingNum = 2       # bags x classes: the b*K+k ordering and
    mc.train.params = {"NumHiddenNodes": [12], "Propagation": "ADAM",   # the
                       "LearningRate": 0.02}  # class_index stamp must agree
    mc.save(mcp)
    environment.set_property("shifu.train.streaming", "on")
    try:
        rep = _run_steps(mc_model_set)
    finally:
        environment.set_property("shifu.train.streaming", "auto")
    from shifu_tpu.models import nn as nn_model
    mdir = os.path.join(mc_model_set, "models")
    models = sorted(f for f in os.listdir(mdir) if f.startswith("model"))
    assert len(models) == 6                    # 2 bags x 3 classes
    for i, f in enumerate(models):
        spec, _ = nn_model.load_model(os.path.join(mdir, f))
        assert spec.extra["class_index"] == i % 3   # b-major, class-minor
    assert rep["accuracy"] > 0.8


def test_e2e_nn_ova_multiclass(mc_model_set):
    from shifu_tpu.config import ModelConfig
    mcp = os.path.join(mc_model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "NN"
    mc.train.multiClassifyMethod = "ONEVSALL"
    mc.train.params = {"NumHiddenNodes": [12], "Propagation": "ADAM",
                       "LearningRate": 0.02, "Loss": "log"}
    mc.save(mcp)
    rep = _run_steps(mc_model_set)
    models = [f for f in os.listdir(os.path.join(mc_model_set, "models"))
              if f.startswith("model")]
    assert len(models) == 3
    assert rep["accuracy"] > 0.85


def test_e2e_nn_native_multiclass_streamed(mc_model_set):
    """Streamed NATIVE multiclass must use softmax CE, not the binary
    elementwise loss (regression guard for the streamed per_row_loss path)."""
    from shifu_tpu.config import ModelConfig, environment
    mcp = os.path.join(mc_model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "NN"
    mc.train.params = {"NumHiddenNodes": [12], "Propagation": "ADAM",
                       "LearningRate": 0.02}
    mc.save(mcp)
    environment.set_property("shifu.train.streaming", "on")
    try:
        rep = _run_steps(mc_model_set)
    finally:
        environment.set_property("shifu.train.streaming", "")
    assert rep["accuracy"] > 0.85
    assert rep["macroAuc"] > 0.9


def test_e2e_gbt_ova_bagged_streamed(mc_model_set):
    """OVA x bagging composes with out-of-core streaming: K x B
    sequential streamed jobs (class binarized on device, bag a stateless
    row-index hash) — previously an in-RAM fallback with a warning."""
    from shifu_tpu.config import ModelConfig, environment
    from shifu_tpu.models import tree as tree_model
    mcp = os.path.join(mc_model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "GBT"
    mc.train.baggingNum = 2
    mc.train.params = {"TreeNum": 4, "MaxDepth": 3, "Loss": "log",
                       "LearningRate": 0.2}
    mc.save(mcp)
    environment.set_property("shifu.train.streaming", "on")
    environment.set_property("shifu.train.windowRows", "512")
    try:
        rep = _run_steps(mc_model_set)
    finally:
        environment.set_property("shifu.train.streaming", "auto")
        environment.set_property("shifu.train.windowRows", "")
    mdir = os.path.join(mc_model_set, "models")
    models = sorted(f for f in os.listdir(mdir) if f.startswith("model"))
    assert len(models) == 6                       # 2 bags x 3 classes
    by_class = {}
    for f in models:
        spec, _ = tree_model.load_model(os.path.join(mdir, f))
        by_class.setdefault(spec.extra["class_index"], []).append(f)
    assert {len(v) for v in by_class.values()} == {2}
    assert rep["accuracy"] > 0.8
    # distinct per-bag splits (GBT per-member seeds) really differ
    f0, f1 = by_class[0]
    _, t0 = tree_model.load_model(os.path.join(mdir, f0))
    _, t1 = tree_model.load_model(os.path.join(mdir, f1))
    assert any((a.split_feat != b.split_feat).any() or
               (a.leaf_value != b.leaf_value).any()
               for a, b in zip(t0, t1))
