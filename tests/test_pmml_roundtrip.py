"""PMML round-trip SCORING tests: export pmml, then score the XML with the
independent evaluator in ``tests/helpers/pmml_eval.py`` and assert parity
with the native model — the reference's ``PMMLTranslatorTest.java`` /
``PMMLVerifySuit.java`` regression (a wrong coefficient/predicate in the
emitted PMML fails here, not just a malformed structure)."""

import os
import sys

import numpy as np

from shifu_tpu.config import ModelConfig

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "helpers"))


from pipeline import train_algorithm as _train  # noqa: E402


def _export_pmml(prepared_set):
    from shifu_tpu.pipeline.export import ExportProcessor
    assert ExportProcessor(prepared_set, params={"type": "pmml"}).run() == 0
    import glob
    cands = glob.glob(os.path.join(prepared_set, "export", "*.pmml"))
    assert len(cands) == 1, f"expected exactly one pmml, got {cands}"
    return cands[0]


def _rows_and_native_scores(prepared_set, model_file):
    """Raw row dicts + native model scores through the real transform."""
    from shifu_tpu.config.column_config import load_column_configs
    from shifu_tpu.data import DataSource
    from shifu_tpu.data.transform import DatasetTransformer
    from shifu_tpu.models import load_any

    mc = ModelConfig.load(os.path.join(prepared_set, "ModelConfig.json"))
    ccs = load_column_configs(
        os.path.join(prepared_set, "ColumnConfig.json"))
    src = DataSource(mc.dataSet.dataPath, mc.dataSet.dataDelimiter)
    tf = DatasetTransformer(mc, ccs)
    chunk = next(iter(src.iter_chunks()))
    tc = tf.transform(chunk)
    model = load_any(os.path.join(prepared_set, "models", model_file))
    kind = getattr(model, "input_kind", "norm")
    native = model.compute(tc.bins if kind == "bins" else tc.x)[:, 0]
    df = chunk.data
    cat_names = {cc.columnName for cc in ccs if cc.is_categorical()}
    used = [nc.cc.columnName for nc in tf.norm_cols]
    rows = []
    for i in range(len(df)):
        row = {}
        for name in used:
            v = str(df[name].iloc[i]).strip()
            if name in cat_names:
                row[name] = v
            else:
                row[name] = float(v) if v not in ("", "NA", "nan") else None
        rows.append(row)
    return rows, native


def _assert_parity(pmml_path, rows, native, atol=2e-3, worst_frac=0.002):
    from pmml_eval import PmmlEvaluator
    ev = PmmlEvaluator(pmml_path)
    got = np.array([ev.score(r) for r in rows], np.float64)
    diff = np.abs(got - native)
    # constants are rounded to 6 decimals in the XML; a value landing
    # within that rounding of a bin boundary may flip bins — allow a
    # vanishing fraction of such rows, pin everything else tightly
    frac_off = float((diff > atol).mean())
    assert frac_off <= worst_frac, (
        f"{frac_off:.2%} rows off by >{atol}: max {diff.max():.5f}")
    assert float(np.median(diff)) < 5e-4


def test_pmml_roundtrip_lr(prepared_set):
    _train(prepared_set, "LR", {"LearningRate": 0.1})
    path = _export_pmml(prepared_set)
    rows, native = _rows_and_native_scores(prepared_set, "model0.lr")
    _assert_parity(path, rows, native)


def test_pmml_roundtrip_nn(prepared_set):
    _train(prepared_set, "NN",
           {"Propagation": "B", "LearningRate": 0.1,
            "NumHiddenNodes": [8], "ActivationFunc": ["tanh"]})
    path = _export_pmml(prepared_set)
    rows, native = _rows_and_native_scores(prepared_set, "model0.nn")
    _assert_parity(path, rows, native)


def test_pmml_roundtrip_gbt(prepared_set):
    _train(prepared_set, "GBT",
           {"TreeNum": 6, "MaxDepth": 3, "Loss": "log",
            "LearningRate": 0.1})
    path = _export_pmml(prepared_set)
    rows, native = _rows_and_native_scores(prepared_set, "model0.gbt")
    _assert_parity(path, rows, native)


def test_pmml_roundtrip_rf(prepared_set):
    _train(prepared_set, "RF",
           {"TreeNum": 5, "MaxDepth": 3, "Impurity": "variance"})
    path = _export_pmml(prepared_set)
    rows, native = _rows_and_native_scores(prepared_set, "model0.rf")
    _assert_parity(path, rows, native)
