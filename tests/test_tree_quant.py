"""Quantized (uint8-narrow) tree-traversal scoring — bit-parity and
fallback contracts (``shifu_tpu/ops/tree_quant.py``).

The quant path's one promise is BIT-IDENTITY with the classic traversal:
routing decisions are integer selects on both paths, f32 appears only at
the leaf gather, so any divergence is a bug, never tolerance.  Suites
cover the jnp fallback (the CPU production path), the Pallas kernel in
interpret mode, GBT/RF/mixed ensembles through the serve scorer
(including padded buckets), and the clean-CPU-fallback smoke the CI
tier-1 sweep rides.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from shifu_tpu.models.nn import IndependentNNModel, NNModelSpec, init_params
from shifu_tpu.models.tree import IndependentTreeModel, TreeModelSpec
from shifu_tpu.ops import tree_quant as tq
from shifu_tpu.ops.tree import (grow_tree, predict_forest_stacked,
                                stack_forest)
from shifu_tpu.serve.scorer import AOTScorer, serve_recompile_count

pytestmark = pytest.mark.perf


def _forest(rng, n=600, c=9, n_bins=32, depth=4, n_trees=4,
            weighted=True):
    bins = rng.integers(0, n_bins, size=(n, c)).astype(np.uint8)
    trees = []
    for _ in range(n_trees):
        y = (rng.random(n) < 0.35).astype(np.float32)
        w = (rng.random(n) + 0.5).astype(np.float32) if weighted \
            else np.ones(n, np.float32)
        trees.append(grow_tree(bins.astype(np.int32), y, w, n_bins, depth))
    return bins, trees


def _classic(trees, bins, depth):
    return np.asarray(predict_forest_stacked(
        *stack_forest(trees), jnp.asarray(bins, jnp.int32), depth))


@pytest.mark.parametrize("n_bins,depth", [(32, 4), (64, 6), (256, 3)])
def test_fallback_bit_identical(rng, n_bins, depth):
    bins, trees = _forest(rng, n_bins=n_bins, depth=depth)
    got = np.asarray(tq.predict_forest_quant(
        *tq.stack_forest_quant(trees), jnp.asarray(bins), depth,
        use_kernel=False))
    assert np.array_equal(_classic(trees, bins, depth), got)


@pytest.mark.parametrize("n_bins,depth", [(20, 4), (64, 6)])
def test_pallas_kernel_bit_identical_interpret(rng, n_bins, depth):
    """The TPU kernel, driven in interpret mode on CPU: same one-hot
    select math, bit-identical scores."""
    bins, trees = _forest(rng, n=333, n_bins=n_bins, depth=depth)
    got = np.asarray(tq.predict_forest_quant(
        *tq.stack_forest_quant(trees), jnp.asarray(bins), depth,
        use_kernel=True, interpret=True))
    assert np.array_equal(_classic(trees, bins, depth), got)


def test_kernel_handles_row_padding_blocks(rng):
    """Row counts straddling the kernel's lane blocking (1, 127, 128,
    129) — pad rows must never leak into real rows' scores."""
    bins, trees = _forest(rng, n=300, n_bins=16, depth=3)
    full = np.asarray(tq.predict_forest_quant(
        *tq.stack_forest_quant(trees), jnp.asarray(bins), 3,
        use_kernel=True, interpret=True))
    for n in (1, 127, 128, 129):
        part = np.asarray(tq.predict_forest_quant(
            *tq.stack_forest_quant(trees), jnp.asarray(bins[:n]), 3,
            use_kernel=True, interpret=True))
        assert np.array_equal(part, full[:, :n])


def test_independent_tree_model_quant_scores(rng):
    """``IndependentTreeModel.compute`` (the eval plane's tree column)
    rides the quant path by default and must match the classic link
    math bit-for-bit for GBT and RF."""
    bins, trees = _forest(rng, n_bins=32, depth=4)
    for algorithm in ("GBT", "RF"):
        spec = TreeModelSpec(algorithm=algorithm, n_trees=len(trees),
                             depth=4, n_bins=32, loss="log",
                             learning_rate=0.1, init_score=-0.3)
        m = IndependentTreeModel(spec, trees)
        got = m.compute(bins.astype(np.int32))
        preds = _classic(trees, bins, 4)
        if algorithm == "GBT":
            f = spec.init_score + spec.learning_rate * preds.sum(axis=0)
            want = (1.0 / (1.0 + np.exp(-f)))[:, None].astype(np.float32)
        else:
            want = preds.mean(axis=0)[:, None].astype(np.float32)
        # the same host numpy link expressions on bit-equal traversal
        # outputs: byte-equal results
        assert np.array_equal(want, got)


def test_mixed_ensemble_serve_bucket_parity(rng, monkeypatch):
    """The AOT serving graph over a MIXED ensemble (NN + GBT + RF) on
    padded buckets: the SAME ensemble graph built with the classic
    (widened int32) traversal must emit bit-identical raw scores —
    every column, every bucket, including a partial batch that pads."""
    bins, trees = _forest(rng, n=200, n_bins=32, depth=4, n_trees=3)
    gbt = IndependentTreeModel(
        TreeModelSpec(algorithm="GBT", n_trees=3, depth=4, n_bins=32,
                      loss="log", learning_rate=0.1, init_score=-0.2),
        trees)
    rf = IndependentTreeModel(
        TreeModelSpec(algorithm="RF", n_trees=3, depth=4, n_bins=32),
        trees)
    nn_spec = NNModelSpec(input_dim=4, hidden_nodes=[4],
                          activations=["relu"])
    nn = IndependentNNModel(nn_spec,
                            init_params(jax.random.PRNGKey(0), nn_spec))

    def build(name):
        s = AOTScorer([nn, gbt, rf], buckets=(8, 64), name=name)
        s.warm()
        return s

    quant = build("serve.score.tqtest")
    assert quant.bins_dtype == np.dtype(np.uint8)
    monkeypatch.setattr(tq, "quant_scoring", lambda: False)
    classic = build("serve.score.tqtest.classic")
    assert classic.bins_dtype == np.dtype(np.int32)

    x = rng.normal(size=(13, quant.n_features)).astype(np.float32)
    b = bins[:13, :quant.n_bins_cols]
    raw_q = quant.score_batch(x, b)          # pads 13 -> 64
    raw_c = classic.score_batch(x, b.astype(np.int32))
    assert raw_q.shape == (13, 3)
    assert np.array_equal(raw_c, raw_q)
    full = bins[:64, :quant.n_bins_cols]
    xf = rng.normal(size=(64, quant.n_features)).astype(np.float32)
    assert np.array_equal(classic.score_batch(xf, full.astype(np.int32)),
                          quant.score_batch(xf, full))
    assert serve_recompile_count("serve.score.tqtest") == 0


def test_cpu_backend_clean_fallback_smoke(rng):
    """Tier-1 smoke (CI runs JAX_PLATFORMS=cpu): the default dispatch on
    a CPU backend must pick the fallback — no Pallas crash — and hold
    parity.  Guards the exact regression where a TPU-only kernel leaks
    into the CPU path."""
    assert jax.default_backend() == "cpu"
    assert tq.quant_scoring() is True
    assert tq.quant_kernel() is False        # auto resolves off-TPU
    bins, trees = _forest(rng, n=150, n_bins=16, depth=3)
    got = np.asarray(tq.predict_forest_quant(
        *tq.stack_forest_quant(trees), jnp.asarray(bins), 3))
    assert np.array_equal(_classic(trees, bins, 3), got)


def test_multiclass_leaves_take_fallback(rng):
    """2D (class-distribution) leaf values dispatch to the fallback even
    when the kernel is requested — and stay bit-identical."""
    bins, trees = _forest(rng, n=120, n_bins=16, depth=3, n_trees=2)
    k = 3
    wide = []
    for t in trees:
        lv = np.stack([np.asarray(t.leaf_value)] * k, axis=1)
        wide.append(type(t)(split_feat=t.split_feat,
                            left_mask=t.left_mask, leaf_value=lv,
                            depth=t.depth))
    got = np.asarray(tq.predict_forest_quant(
        *tq.stack_forest_quant(wide), jnp.asarray(bins), 3,
        use_kernel=True, interpret=True))
    want = _classic(wide, bins, 3)
    assert got.shape == want.shape and np.array_equal(want, got)


def test_ensemble_bins_dtype_rules():
    class FakeTree:
        def __init__(self, n_bins):
            self.spec = TreeModelSpec(algorithm="GBT", n_trees=0,
                                      depth=1, n_bins=n_bins)
    FakeTree.__name__ = "IndependentTreeModel"

    class FakeWDL:
        input_kind = "both"

        def __init__(self, cards):
            class S:
                cat_cardinalities = cards
            self.spec = S()
    assert tq.ensemble_bins_dtype([FakeTree(256)]) == np.dtype(np.uint8)
    assert tq.ensemble_bins_dtype([FakeTree(257)]) == np.dtype(np.int32)
    assert tq.ensemble_bins_dtype([FakeWDL([256, 8])]) == np.dtype(np.uint8)
    assert tq.ensemble_bins_dtype([FakeWDL([300])]) == np.dtype(np.int32)


def test_cost_model_registered():
    from shifu_tpu.obs import costs
    fn = costs.cost_models().get("pallas.tree_traverse")
    assert fn is not None
    est = fn(rows=512, n_feat=32, n_bins=64, n_nodes=127, depth=6,
             n_trees=50)
    assert est["flops"] > 0 and est["bytes_accessed"] > 0
    # bins plane billed ONCE (uint8), not per tree — the kernel's point
    est1 = fn(rows=512, n_feat=32, n_bins=64, n_nodes=127, depth=6,
              n_trees=1)
    assert est["bytes_accessed"] - est1["bytes_accessed"] < \
        50 * 512 * 32          # grows with trees' arrays, not the plane
