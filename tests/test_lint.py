"""`shifu-tpu lint` suite: per-rule fixture pairs (a seeded violation
that must flag + a clean twin that must not), suppression-comment and
baseline mechanics, CLI exit codes, and the tier-1 acceptance guards —
the full shifu_tpu/ tree lints clean against the checked-in baseline,
in under 5 seconds, with byte-deterministic output."""

import json
import os
import textwrap
import time

import pytest

from shifu_tpu.lint import run_lint
from shifu_tpu.lint.baseline import (apply_baseline, load_baseline,
                                     write_baseline)
from shifu_tpu.lint.cli import (default_baseline_path, main,
                                repo_root)
from shifu_tpu.lint.engine import Finding, LintEngine, iter_python_files
from shifu_tpu.lint.rules import ALL_RULES, make_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.lint           # `pytest -m lint` collects this


def _lint_snippet(tmp_path, source, rules=None, rel="mod.py"):
    """Write one fixture module and lint it; returns findings."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings, _ = run_lint([str(path)], rules=rules, root=str(tmp_path),
                           full_tree=False)
    return findings


def _rules_hit(findings):
    return {f.rule for f in findings}


# ------------------------------------------------ rule 1: host-sync
def test_host_sync_flags_and_clean_twin(tmp_path):
    bad = """
        import jax

        @jax.jit
        def f(x):
            return float(x) + 1.0

        @jax.jit
        def g(x):
            return x.sum().item()
    """
    found = _lint_snippet(tmp_path, bad, rules=["host-sync-hot-path"])
    assert len(found) == 2
    assert _rules_hit(found) == {"host-sync-hot-path"}

    clean = """
        import jax
        import numpy as np
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            return x * int(np.log2(n))     # static host math: sanctioned

        def host(x):
            return float(x)                # not jitted: fine
    """
    assert _lint_snippet(tmp_path, clean,
                         rules=["host-sync-hot-path"]) == []


def test_host_sync_window_loop(tmp_path):
    bad = """
        def sweep(stream, f):
            tot = 0.0
            for w in stream.prepared(f):
                tot += w.err.item()        # per-window forced fetch
            return tot
    """
    (f,) = _lint_snippet(tmp_path, bad, rules=["host-sync-hot-path"])
    assert "window loop" in f.message

    clean = """
        def sweep(stream, f):
            accs = []
            for w in stream.prepared(f):
                accs.append(w.err)         # accumulate on device
            return [a.item() for a in accs]   # fetch after the sweep
    """
    assert _lint_snippet(tmp_path, clean,
                         rules=["host-sync-hot-path"]) == []


# ------------------------------------------- rule 2: recompile-hazard
def test_recompile_hazard_flags_and_clean_twin(tmp_path):
    bad = """
        import jax

        @jax.jit
        def hot(x):
            return x + 1

        def build():
            return jax.jit(lambda x: x * 2)
    """
    found = _lint_snippet(tmp_path, bad, rules=["recompile-hazard"],
                          rel="train/mod.py")
    assert len(found) == 2
    # the same module OUTSIDE a hot layer is sanctioned (ops/ kernels)
    assert _lint_snippet(tmp_path, bad, rules=["recompile-hazard"],
                         rel="ops/mod.py") == []

    clean = """
        from shifu_tpu import obs

        @obs.costed_jit("plane.hot", lazy=True)
        def hot(x):
            return x + 1
    """
    assert _lint_snippet(tmp_path, clean, rules=["recompile-hazard"],
                         rel="train/mod.py") == []


def test_recompile_hazard_fstring_executable_name(tmp_path):
    bad = """
        from shifu_tpu import obs

        def wrap(fn, shape):
            return obs.costed_jit(f"plane.fn.{shape}", fn)
    """
    (f,) = _lint_snippet(tmp_path, bad, rules=["recompile-hazard"],
                         rel="serve/mod.py")
    assert "f-string executable name" in f.message
    # a CONSTANT f-string (no interpolation) is just a string
    clean = """
        from shifu_tpu import obs

        def wrap(fn):
            return obs.costed_jit(f"plane.fn", fn)
    """
    assert _lint_snippet(tmp_path, clean, rules=["recompile-hazard"],
                         rel="serve/mod.py") == []


# --------------------------------------------- rule 3: knob-registry
def test_knob_registry_flags_and_clean_twin(tmp_path):
    bad = """
        import os
        from shifu_tpu.config import environment

        def f():
            a = environment.get_int("shifu.bogus.knob", 3)
            b = os.environ.get("SHIFU_BOGUS_ENV")
            return a, b

        def g():
            '''Tune with ``-Dshifu.made.up`` if slow.'''
    """
    found = _lint_snippet(tmp_path, bad, rules=["knob-registry"])
    tokens = {m.split("'")[1] for m in (f.message for f in found)}
    assert tokens == {"shifu.bogus.knob", "SHIFU_BOGUS_ENV",
                      "shifu.made.up"}

    clean = """
        import os
        from shifu_tpu.config import environment

        def f():
            '''``-Dshifu.serve.maxDelayMs`` bounds the deadline; a
        line-wrapped mention like ``shifu.tree.`` resolves as a prefix,
        and case-insensitive props (``shifu.train.windowrows``) match.'''
            a = environment.get_float("shifu.serve.maxDelayMs", 2.0)
            b = os.environ.get("SHIFU_TREE_BATCH")
            return a, b
    """
    assert _lint_snippet(tmp_path, clean, rules=["knob-registry"]) == []


def test_knob_registry_readme_and_dead_knob_cross_checks():
    """finish() checks run on full-tree scans: every declared knob is in
    the README table and referenced somewhere in shifu_tpu/ (asserted
    clean on HEAD by the acceptance test; here: the checks exist)."""
    findings, engine = run_lint(rules=["knob-registry"])
    assert engine.full_tree
    assert [f for f in findings
            if "README" in f.message or "never read" in f.message] == []


# ---------------------------------------------- rule 4: atomic-write
def test_atomic_write_flags_and_clean_twins(tmp_path):
    bad = """
        import json
        import numpy as np

        def save(path, doc, arr):
            with open(path, "w") as f:
                json.dump(doc, f)
            np.savez(path + ".npz", arr=arr)
    """
    found = _lint_snippet(tmp_path, bad, rules=["atomic-write"])
    assert len(found) == 2

    clean = """
        import io
        import json
        import os
        import numpy as np
        from shifu_tpu import ioutil

        def save(path, doc, arr):
            ioutil.atomic_write_json(path, doc)        # library path
            buf = io.BytesIO()
            np.savez(buf, arr=arr)                     # buffer, not disk
            ioutil.atomic_write_bytes(path + ".npz", buf.getvalue())

        def manual(path, doc):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:                  # tmp discipline
                json.dump(doc, f)
            os.replace(tmp, path)

        def read(path):
            with open(path) as f:                      # reads are fine
                return f.read()
    """
    assert _lint_snippet(tmp_path, clean, rules=["atomic-write"]) == []


# -------------------------------------------- rule 5: telemetry-guard
def test_telemetry_guard_flags_and_clean_twins(tmp_path):
    bad = """
        from shifu_tpu import obs

        def sweep(windows):
            for w in windows:
                obs.counter("ingest.windows_emitted").inc()
    """
    (f,) = _lint_snippet(tmp_path, bad, rules=["telemetry-guard"])
    assert "hoist" in f.message

    clean = """
        from shifu_tpu import obs

        def hoisted(windows):
            c = obs.counter("ingest.windows_emitted")
            for w in windows:
                c.inc()

        def guarded(windows):
            for w in windows:
                if obs.enabled():
                    obs.counter("ingest.windows_emitted").inc()

        def guarded_hoisted_bool(windows, obs_on):
            for w in windows:
                if obs_on:
                    obs.counter("ingest.windows_emitted").inc()
    """
    assert _lint_snippet(tmp_path, clean,
                         rules=["telemetry-guard"]) == []


# ------------------------------------- rules 6-8: manifest migration
def test_manifest_rules_flag_and_clean_twins(tmp_path):
    bad = """
        from shifu_tpu import obs, faults

        def f():
            obs.counter("ingest.windows_emited").inc()     # typo
            obs.gauge("train.epoch_s").set(1.0)            # wrong type
            with obs.span("serve.requst"):                 # typo
                pass
            faults.fire("norm", "shardz", 1)               # typo
    """
    found = _lint_snippet(tmp_path, bad,
                          rules=["metric-manifest", "span-manifest",
                                 "fault-site"])
    assert sorted(_rules_hit(found)) == ["fault-site", "metric-manifest",
                                         "span-manifest"]
    assert len(found) == 4

    clean = """
        from shifu_tpu import obs, faults

        def f(name):
            obs.counter("ingest.windows_emitted").inc()
            obs.histogram("train.epoch_s").observe(1.0)
            obs.gauge(f"bench.{name}").set(1.0)       # declared prefix
            with obs.span("serve.request"):
                pass
            with obs.span(name):                      # variable: exempt
                pass
            faults.fire("norm", "shard", 1)
    """
    assert _lint_snippet(tmp_path, clean,
                         rules=["metric-manifest", "span-manifest",
                                "fault-site"]) == []


# ------------------------------------------------ suppression comments
def test_inline_and_file_suppressions(tmp_path):
    src = """
        import json

        def a(path, doc):
            with open(path, "w") as f:  # shifu-lint: disable=atomic-write -- why
                json.dump(doc, f)

        def b(path, doc):
            # shifu-lint: disable=atomic-write
            with open(path, "w") as f:
                json.dump(doc, f)

        def c(path, doc):
            with open(path, "w") as f:  # shifu-lint: disable=other-rule
                json.dump(doc, f)
    """
    found = _lint_snippet(tmp_path, src, rules=["atomic-write"])
    assert len(found) == 1              # only c(): wrong rule named
    assert found[0].line == 14

    filewide = """
        # shifu-lint: disable-file=atomic-write
        import json

        def a(path, doc):
            with open(path, "w") as f:
                json.dump(doc, f)

        def b(path, doc):
            with open(path, "w") as f:
                json.dump(doc, f)
    """
    assert _lint_snippet(tmp_path, filewide, rules=["atomic-write"]) == []


# --------------------------------------------------- baseline mechanics
def _f(rule="atomic-write", path="p.py", line=1, msg="m"):
    return Finding(path, line, 0, rule, msg)


def test_baseline_roundtrip_and_apply(tmp_path):
    bl = str(tmp_path / "bl.json")
    write_baseline(bl, [_f(line=1), _f(line=9), _f(msg="other")])
    loaded = load_baseline(bl)
    assert loaded[("atomic-write", "p.py", "m")] == 2   # count-merged
    assert loaded[("atomic-write", "p.py", "other")] == 1

    # 3 current findings with the same fingerprint vs a budget of 2:
    # the extra one is NEW; a baselined fingerprint with no current
    # finding is STALE
    current = [_f(line=1), _f(line=2), _f(line=3)]
    new, old, stale = apply_baseline(current, loaded)
    assert [f.line for f in old] == [1, 2]
    assert [f.line for f in new] == [3]
    assert stale == [("atomic-write", "p.py", "other")]

    # line moves do NOT churn the baseline (fingerprint drops the line)
    new, old, stale = apply_baseline(
        [_f(line=77), _f(line=78), _f(msg="other")], loaded)
    assert new == [] and stale == []

    # the ratchet: fixing SOME of a fingerprint's occurrences leaves
    # unused budget, which reports stale — the baseline must shrink
    new, old, stale = apply_baseline([_f(line=77), _f(msg="other")],
                                     loaded)
    assert new == [] and stale == [("atomic-write", "p.py", "m")]


def test_baseline_missing_and_bad_version(tmp_path):
    assert load_baseline(str(tmp_path / "nope.json")) == {}
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"version": 99, "findings": []}))
    with pytest.raises(ValueError):
        load_baseline(str(bad))


# ----------------------------------------------------------- engine / CLI
def test_parse_error_is_a_finding(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    findings, _ = run_lint([str(tmp_path / "broken.py")],
                           root=str(tmp_path), full_tree=False)
    assert [f.rule for f in findings] == ["parse-error"]


def test_iter_python_files_sorted_deduped(tmp_path):
    (tmp_path / "b.py").write_text("")
    (tmp_path / "a.py").write_text("")
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "c.py").write_text("")
    (tmp_path / "__pycache__").mkdir()
    (tmp_path / "__pycache__" / "x.py").write_text("")
    got = list(iter_python_files([str(tmp_path), str(tmp_path / "a.py")]))
    names = [os.path.relpath(p, tmp_path) for p in got]
    assert names == ["a.py", "b.py", os.path.join("sub", "c.py")]


def test_unknown_rule_name_raises():
    with pytest.raises(ValueError, match="unknown rule"):
        make_rules(["no-such-rule"])


def test_cli_exit_codes_and_json(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import json\n"
                   "def a(p, d):\n"
                   "    with open(p, 'w') as f:\n"
                   "        json.dump(d, f)\n")
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")

    assert main([str(clean), "--no-baseline"]) == 0
    capsys.readouterr()
    assert main([str(bad), "--no-baseline"]) == 2
    out = capsys.readouterr().out
    assert "atomic-write" in out and "bad.py" in out

    assert main([str(bad), "--no-baseline", "--json"]) == 2
    doc = json.loads(capsys.readouterr().out)
    (f,) = doc["new"]
    assert f["rule"] == "atomic-write" and f["line"] == 3
    assert doc["files_scanned"] == 1

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for cls in ALL_RULES:
        assert cls.name in out

    assert main([str(bad), "--rules", "nope"]) == 1


def test_cli_baseline_workflow(tmp_path, capsys):
    """--update-baseline grandfathers today's debt; the next run is
    clean; FIXING the debt turns the entry stale (exit 2) so the
    baseline cannot rot."""
    bad = tmp_path / "bad.py"
    bad.write_text("import json\n"
                   "def a(p, d):\n"
                   "    with open(p, 'w') as f:\n"
                   "        json.dump(d, f)\n")
    bl = str(tmp_path / "bl.json")
    assert main([str(bad), "--baseline", bl]) == 2
    capsys.readouterr()
    assert main([str(bad), "--baseline", bl, "--update-baseline"]) == 0
    capsys.readouterr()
    assert main([str(bad), "--baseline", bl]) == 0
    assert "grandfathered" in capsys.readouterr().out
    bad.write_text("x = 1\n")
    assert main([str(bad), "--baseline", bl]) == 2
    assert "stale baseline entry" in capsys.readouterr().out


def test_cli_subcommand_dispatch(capsys):
    """`shifu-tpu lint` is wired through the main CLI dispatcher."""
    from shifu_tpu.cli import main as cli_main
    assert cli_main(["lint", "--list-rules"]) == 0
    assert "knob-registry" in capsys.readouterr().out


# ----------------------------------------------------- tier-1 acceptance
def test_full_tree_lints_clean_against_checked_in_baseline():
    """ACCEPTANCE: `shifu-tpu lint` exits 0 on HEAD — every knob
    resolves against config/knobs.py, every write/metric/span/fault
    literal honors its contract, and the checked-in baseline is EMPTY
    (no grandfathered debt survived this round)."""
    findings, engine = run_lint()
    assert engine.files_scanned > 60
    baseline = load_baseline(default_baseline_path())
    assert baseline == {}               # nothing was cheap-to-fix left
    new, _, stale = apply_baseline(findings, baseline)
    assert not new, "\n".join(f.render() for f in new)
    assert not stale


def test_full_tree_fast_and_byte_deterministic():
    """ACCEPTANCE: a full-tree run completes in < 5 s and two runs
    render byte-identically (stable file order, stable finding order —
    CI can diff outputs)."""
    t0 = time.perf_counter()
    f1, _ = run_lint()
    elapsed = time.perf_counter() - t0
    assert elapsed < 5.0, f"full-tree lint took {elapsed:.2f}s"
    f2, _ = run_lint()
    r1 = b"\n".join(f.render().encode() for f in f1)
    r2 = b"\n".join(f.render().encode() for f in f2)
    assert r1 == r2


def test_every_rule_has_name_doc_and_fires_somewhere():
    """Catalogue hygiene: unique names, non-empty docs, and every rule
    has at least one seeded-violation test above (checked by name)."""
    names = [cls.name for cls in ALL_RULES]
    assert len(names) == len(set(names))
    for cls in ALL_RULES:
        assert cls.name and cls.doc
    here = open(__file__).read()
    for cls in ALL_RULES:
        assert cls.name in here, f"no fixture exercises {cls.name}"
