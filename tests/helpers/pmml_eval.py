"""Independent PMML-4.2 evaluator for round-trip scoring tests.

A from-scratch interpreter of the PMML subset ``shifu_tpu/export/pmml.py``
emits — NeuralNetwork, RegressionModel, MiningModel/TreeModel segments,
LocalTransformations (Discretize / MapValues / Apply expression trees) —
sharing NO code with the emitter, so a wrong coefficient or predicate in
the generated XML fails the test instead of round-tripping silently.
Mirrors the reference's ``PMMLTranslatorTest`` / ``PMMLVerifySuit``
pattern (score the artifact with an independent engine, compare).
"""

from __future__ import annotations

import math
import re
import xml.etree.ElementTree as ET
from typing import Dict, Optional


def _strip_ns(root: ET.Element) -> None:
    for el in root.iter():
        el.tag = re.sub(r"\{.*\}", "", el.tag)


_MISSING = object()


class PmmlEvaluator:
    """Score one raw row dict {columnName: value} through a PMML file.

    Values: str for categorical, float for numeric, None/NaN/"" = missing.
    Returns the model's final score (after any Output transformedValue).
    """

    def __init__(self, path: str):
        tree = ET.parse(path)
        self.root = tree.getroot()
        _strip_ns(self.root)
        self.model = None
        for tag in ("NeuralNetwork", "RegressionModel", "MiningModel"):
            el = self.root.find(tag)
            if el is not None:
                self.model = el
                self.kind = tag
                break
        if self.model is None:
            raise ValueError(f"{path}: no supported model element")

    # ------------------------------------------------------------ fields
    def _field_value(self, fields: Dict, name: str):
        v = fields.get(name, _MISSING)
        if v is _MISSING:
            return _MISSING
        if v is None:
            return _MISSING
        if isinstance(v, float) and math.isnan(v):
            return _MISSING
        if isinstance(v, str) and v.strip() == "":
            return _MISSING
        return v

    def _eval_expr(self, el: ET.Element, fields: Dict):
        tag = el.tag
        if tag == "Constant":
            return float(el.text)
        if tag == "FieldRef":
            return self._field_value(fields, el.get("field"))
        if tag == "Apply":
            fn = el.get("function")
            args = [self._eval_expr(c, fields) for c in el]
            if any(a is _MISSING for a in args):
                mm = el.get("mapMissingTo")
                return float(mm) if mm is not None else _MISSING
            if fn == "/":
                return args[0] / args[1]
            if fn == "-":
                return args[0] - args[1]
            if fn == "+":
                return sum(args)
            if fn == "*":
                out = 1.0
                for a in args:
                    out *= a
                return out
            if fn == "max":
                return max(args)
            if fn == "min":
                return min(args)
            if fn == "exp":
                return math.exp(args[0])
            raise ValueError(f"unsupported Apply function {fn}")
        if tag == "Discretize":
            return self._eval_discretize(el, fields)
        if tag == "MapValues":
            return self._eval_mapvalues(el, fields)
        raise ValueError(f"unsupported expression {tag}")

    def _eval_discretize(self, el: ET.Element, fields: Dict):
        v = self._field_value(fields, el.get("field"))
        out_type = el.get("dataType", "double")

        def conv(s):
            return int(s) if out_type == "integer" else float(s)

        if v is _MISSING:
            mm = el.get("mapMissingTo")
            return conv(mm) if mm is not None else _MISSING
        v = float(v)
        for b in el.findall("DiscretizeBin"):
            iv = b.find("Interval")
            left = float(iv.get("leftMargin", "-inf"))
            right = float(iv.get("rightMargin", "inf"))
            closure = iv.get("closure", "closedOpen")
            if closure == "closedOpen":
                ok = left <= v < right
            elif closure == "openClosed":
                ok = left < v <= right
            elif closure == "closedClosed":
                ok = left <= v <= right
            else:
                ok = left < v < right
            if ok:
                return conv(b.get("binValue"))
        dv = el.get("defaultValue")
        return conv(dv) if dv is not None else _MISSING

    def _eval_mapvalues(self, el: ET.Element, fields: Dict):
        pair = el.find("FieldColumnPair")
        v = self._field_value(fields, pair.get("field"))
        out_type = el.get("dataType", "double")

        def conv(s):
            return int(s) if out_type == "integer" else float(s)

        if v is _MISSING:
            mm = el.get("mapMissingTo")
            return conv(mm) if mm is not None else _MISSING
        in_col = pair.get("column")
        out_col = el.get("outputColumn")
        for row in el.find("InlineTable").findall("row"):
            if row.find(in_col).text == str(v):
                return conv(row.find(out_col).text)
        dv = el.get("defaultValue")
        return conv(dv) if dv is not None else _MISSING

    def _apply_local_transformations(self, parent: ET.Element,
                                     fields: Dict) -> Dict:
        lt = parent.find("LocalTransformations")
        out = dict(fields)
        if lt is None:
            return out
        for df in lt.findall("DerivedField"):
            expr = next(c for c in df
                        if c.tag in ("Apply", "Discretize", "MapValues",
                                     "FieldRef", "Constant"))
            out[df.get("name")] = self._eval_expr(expr, out)
        return out

    # ------------------------------------------------------------ models
    def score(self, row: Dict) -> Optional[float]:
        if self.kind == "NeuralNetwork":
            return self._score_nn(row)
        if self.kind == "RegressionModel":
            return self._score_regression(row)
        return self._score_mining(row)

    def _score_nn(self, row: Dict) -> float:
        nn = self.model
        fields = self._apply_local_transformations(nn, row)
        acts: Dict[str, float] = {}
        for ni in nn.find("NeuralInputs").findall("NeuralInput"):
            fr = ni.find("DerivedField").find("FieldRef")
            v = self._field_value(fields, fr.get("field"))
            acts[ni.get("id")] = 0.0 if v is _MISSING else float(v)
        for layer in nn.findall("NeuralLayer"):
            fn = layer.get("activationFunction",
                           nn.get("activationFunction"))
            new = {}
            for neuron in layer.findall("Neuron"):
                z = float(neuron.get("bias", "0"))
                for con in neuron.findall("Con"):
                    z += acts[con.get("from")] * float(con.get("weight"))
                new[neuron.get("id")] = _activate(fn, z)
            acts.update(new)
        out_id = nn.find("NeuralOutputs").find("NeuralOutput") \
            .get("outputNeuron")
        return acts[out_id]

    def _score_regression(self, row: Dict) -> float:
        rm = self.model
        fields = self._apply_local_transformations(rm, row)
        table = rm.find("RegressionTable")
        z = float(table.get("intercept", "0"))
        for p in table.findall("NumericPredictor"):
            v = self._field_value(fields, p.get("name"))
            v = 0.0 if v is _MISSING else float(v)
            z += float(p.get("coefficient")) * \
                v ** float(p.get("exponent", "1"))
        if rm.get("normalizationMethod") == "logit":
            return 1.0 / (1.0 + math.exp(-z))
        return z

    def _walk_tree_node(self, node: ET.Element, fields: Dict) -> float:
        while True:
            children = node.findall("Node")
            nxt = None
            for child in children:
                if self._predicate(child, fields):
                    nxt = child
                    break
            if nxt is None:
                return float(node.get("score"))
            node = nxt

    def _predicate(self, node: ET.Element, fields: Dict) -> bool:
        if node.find("True") is not None:
            return True
        ssp = node.find("SimpleSetPredicate")
        if ssp is not None:
            v = self._field_value(fields, ssp.get("field"))
            if v is _MISSING:
                return False
            members = ssp.find("Array").text.split() \
                if ssp.find("Array").text else []
            hit = str(int(v)) in members
            return hit if ssp.get("booleanOperator") == "isIn" else not hit
        return False

    def _score_mining(self, row: Dict) -> float:
        mm = self.model
        fields = self._apply_local_transformations(mm, row)
        seg = mm.find("Segmentation")
        scores = []
        for s in seg.findall("Segment"):
            tm = s.find("TreeModel")
            root = tm.find("Node")
            assert self._predicate(root, fields)
            scores.append(self._walk_tree_node(root, fields))
        method = seg.get("multipleModelMethod")
        total = sum(scores)
        if method == "average":
            total /= max(len(scores), 1)
        out = mm.find("Output")
        if out is not None:
            for of in out.findall("OutputField"):
                if of.get("feature") == "transformedValue":
                    expr = next(c for c in of if c.tag == "Apply")
                    return self._eval_expr(expr, {"rawSum": total})
        return total


def _activate(fn: str, z: float) -> float:
    if fn == "logistic":
        return 1.0 / (1.0 + math.exp(-z))
    if fn == "tanh":
        return math.tanh(z)
    if fn == "rectifier":
        return max(0.0, z)
    if fn == "identity":
        return z
    raise ValueError(f"unsupported activation {fn}")
