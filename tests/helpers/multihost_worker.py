"""Worker for the 2-process multi-host test: each process plays one host
(4 virtual CPU devices), the mesh spans both, and a jitted global reduction
crosses the simulated DCN."""

import os
import sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
# force EXACTLY 4 local devices, replacing any inherited count (pytest's
# conftest exports 8)
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if "xla_force_host_platform_device_count" not in f]
flags.append("--xla_force_host_platform_device_count=4")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

for _name in [n for n in list(getattr(_xb, "_backend_factories", {}))
              if n != "cpu"]:
    _xb._backend_factories.pop(_name, None)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from shifu_tpu.parallel.mesh import (device_mesh,  # noqa: E402
                                     initialize_distributed,
                                     shard_rows_from_local)

os.environ["SHIFU_COORDINATOR"] = f"localhost:{port}"
os.environ["SHIFU_NUM_PROCESSES"] = str(nproc)
os.environ["SHIFU_PROCESS_ID"] = str(pid)
initialize_distributed()

assert jax.process_count() == nproc
assert len(jax.devices()) == 4 * nproc          # global device set

mesh = device_mesh(n_ensemble=1)
assert mesh.shape == {"ensemble": 1, "data": 4 * nproc}, mesh.shape

# each "host" contributes its own row block (its shard files)
local = (np.arange(16, dtype=np.float32).reshape(4, 4) + 100 * pid)
garr = shard_rows_from_local(mesh, local)
assert garr.shape == (4 * nproc, 4), garr.shape

# a global weighted reduction: the cross-host part of a gradient psum
total = float(jax.jit(lambda a: (a * 2.0).sum())(garr))
expected = 2.0 * sum(float((np.arange(16) + 100 * p).sum())
                     for p in range(nproc))
assert total == expected, (total, expected)

# ensemble axis across hosts: members pin to one host each, data stays on
# the host's own ICI domain
mesh2 = device_mesh(n_ensemble=nproc)
assert mesh2.shape == {"ensemble": nproc, "data": 4}
row = [d.process_index for d in mesh2.devices[pid]]
assert row == [pid] * 4, row                     # one host per member row

print(f"proc {pid}: MULTIHOST-OK total={total}", flush=True)
