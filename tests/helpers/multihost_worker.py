"""Worker for the 2-process multi-host test: each process plays one host
(4 virtual CPU devices), the mesh spans both, and a jitted global reduction
crosses the simulated DCN."""

import os
import sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
# own compilation cache: the suite's persistent cache (conftest) may hold
# AOT entries whose recorded machine features mismatch this worker's
# loader and fail with "Target machine feature ... not supported"
os.environ["JAX_COMPILATION_CACHE_DIR"] = \
    os.environ.get("SHIFU_MH_CACHE", "/tmp/shifu_tpu_mh_cache")
# force EXACTLY 4 local devices, replacing any inherited count (pytest's
# conftest exports 8)
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if "xla_force_host_platform_device_count" not in f]
flags.append("--xla_force_host_platform_device_count=4")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

# keep "tpu" registered like the suite conftest does: pallas/mosaic
# registers tpu MLIR lowerings at import time and needs the platform
# known, even under JAX_PLATFORMS=cpu
for _name in [n for n in list(getattr(_xb, "_backend_factories", {}))
              if n not in ("cpu", "tpu")]:
    _xb._backend_factories.pop(_name, None)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from shifu_tpu.parallel.mesh import (device_mesh,  # noqa: E402
                                     initialize_distributed,
                                     shard_rows_from_local)

os.environ["SHIFU_COORDINATOR"] = f"localhost:{port}"
os.environ["SHIFU_NUM_PROCESSES"] = str(nproc)
os.environ["SHIFU_PROCESS_ID"] = str(pid)
initialize_distributed()

assert jax.process_count() == nproc
assert len(jax.devices()) == 4 * nproc          # global device set

mesh = device_mesh(n_ensemble=1)
assert mesh.shape == {"ensemble": 1, "data": 4 * nproc}, mesh.shape

# each "host" contributes its own row block (its shard files)
local = (np.arange(16, dtype=np.float32).reshape(4, 4) + 100 * pid)
garr = shard_rows_from_local(mesh, local)
assert garr.shape == (4 * nproc, 4), garr.shape

# a global weighted reduction: the cross-host part of a gradient psum
total = float(jax.jit(lambda a: (a * 2.0).sum())(garr))
expected = 2.0 * sum(float((np.arange(16) + 100 * p).sum())
                     for p in range(nproc))
assert total == expected, (total, expected)

# ensemble axis across hosts: members pin to one host each, data stays on
# the host's own ICI domain
mesh2 = device_mesh(n_ensemble=nproc)
assert mesh2.shape == {"ensemble": nproc, "data": 4}
row = [d.process_index for d in mesh2.devices[pid]]
assert row == [pid] * 4, row                     # one host per member row

# ---- a REAL trainer across the process boundary (VERDICT r3 item 7):
# each host feeds its own row block; the gradient psum crosses the DCN
# every step; both controllers must converge to the SAME weights.
from shifu_tpu.models.nn import NNModelSpec  # noqa: E402
from shifu_tpu.train.nn_trainer import (TrainSettings,  # noqa: E402
                                        train_ensemble)

N, D = 256, 8
rng = np.random.default_rng(0)                  # same draw on both hosts
x_all = rng.normal(size=(N, D)).astype(np.float32)
wvec = rng.normal(size=D).astype(np.float32) / np.sqrt(D)
y_all = (1 / (1 + np.exp(-(x_all @ wvec) * 3))
         > rng.random(N)).astype(np.float32)
half = N // nproc
x_global = shard_rows_from_local(mesh, x_all[pid * half:(pid + 1) * half])
assert x_global.shape == (N, D)
tw = np.full((1, N), 0.8, np.float32)
vw = np.full((1, N), 0.2, np.float32)
res = train_ensemble(x_global, y_all, tw, vw,
                     NNModelSpec(input_dim=D, hidden_nodes=[8],
                                 activations=["tanh"], loss="log"),
                     TrainSettings(optimizer="ADAM", learning_rate=0.05,
                                   epochs=12),
                     mesh=mesh)
assert res.history[-1][0] < res.history[0][0], res.history
checksum = float(sum(np.abs(layer[k]).sum()
                     for layer in res.params[0] for k in ("w", "b")))
print(f"proc {pid}: MULTIHOST-TRAIN weights={checksum:.8f} "
      f"err={res.train_errors[0]:.6f}", flush=True)

# minibatch path too: its re-pad block must gather (not np.asarray) the
# cross-host-sharded arrays
res_mb = train_ensemble(x_global, y_all, tw, vw,
                        NNModelSpec(input_dim=D, hidden_nodes=[8],
                                    activations=["tanh"], loss="log"),
                        TrainSettings(optimizer="ADAM", learning_rate=0.05,
                                      epochs=3, batch_size=64),
                        mesh=mesh)
assert np.isfinite(res_mb.train_errors[0])
print(f"proc {pid}: MULTIHOST-MINIBATCH ok", flush=True)

# ---- a STREAMED trainer across hosts: windows shard over the GLOBAL
# data axis (ResidentCache + mega coalescing under 2 controllers); both
# processes must absorb identical forests from the replicated fetches
import json  # noqa: E402
import tempfile  # noqa: E402

from shifu_tpu.data.shards import Shards  # noqa: E402
from shifu_tpu.data.streaming import ShardStream  # noqa: E402
from shifu_tpu.train.dt_trainer import (DTSettings,  # noqa: E402
                                        train_gbt_streamed)

_td_ctx = tempfile.TemporaryDirectory(prefix=f"mh_stream_{pid}_")
td = _td_ctx.name                               # auto-removed at exit
rng_t = np.random.default_rng(17)               # same data on both hosts
tbins = rng_t.integers(0, 8, size=(128, 6)).astype(np.int16)
ty = (rng_t.random(128) < 0.4).astype(np.float32)
np.savez(os.path.join(td, "part-00000.npz"), bins=tbins, y=ty,
         w=np.ones(128, np.float32))
with open(os.path.join(td, "schema.json"), "w") as f:
    json.dump({"columnNums": list(range(6)), "numShards": 1,
               "numRows": 128}, f)
stream_t = ShardStream(Shards.open(td), ("bins", "y", "w"),
                       window_rows=64)
sres = train_gbt_streamed(stream_t, 8, None,
                          DTSettings(n_trees=2, depth=2, loss="log",
                                     learning_rate=0.1), mesh=mesh)
tree_sum = float(sum(np.abs(t.leaf_value).sum() + (t.split_feat >= 0).sum()
                     for t in sres.trees))
print(f"proc {pid}: MULTIHOST-STREAMED trees={tree_sum:.8f}", flush=True)

# ---- stats plane across hosts: chunk rows shard over the GLOBAL data
# axis and the moment/histogram reductions psum across the DCN (the
# reference's up-to-999 stats reducers, MapReducerStatsWorker.java)
from shifu_tpu.config.model_config import BinningMethod  # noqa: E402
from shifu_tpu.ops.binning import NumericAccumulator  # noqa: E402

C = 3
xs = rng.normal(size=(200, C)).astype(np.float32)   # same on both hosts
valid = np.ones((200, C), bool)
tgt = (rng.random(200) < 0.4).astype(np.float32)
acc = NumericAccumulator(n_cols=C, num_buckets=64, mesh=mesh)
acc.update_moments(xs, valid)
acc.finalize_range()
acc.update_histogram(xs, valid, tgt, np.ones(200, np.float32))
bnds, aggs, _, _ = acc.finalize_sketch(BinningMethod.EqualTotal, 4)
assert int(aggs[0][:, :2].sum()) == 200
stats_sum = float(sum(np.sum(np.abs(b[np.isfinite(b)])) for b in bnds))
print(f"proc {pid}: MULTIHOST-STATS bnds={stats_sum:.8f}", flush=True)

print(f"proc {pid}: MULTIHOST-OK total={total}", flush=True)
