"""Shared pipeline-driving helpers for export round-trip tests."""

import os

from shifu_tpu.config import ModelConfig


def train_algorithm(model_set: str, algorithm: str, params: dict) -> None:
    """Set train.algorithm/params on a prepared model set and run TRAIN."""
    from shifu_tpu.pipeline.train import TrainProcessor
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = algorithm
    mc.train.params = params
    mc.save(mc_path)
    assert TrainProcessor(model_set, params={}).run() == 0
