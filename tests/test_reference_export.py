"""Round-trip tests for the reference-format model WRITERS
(``export/reference_spec.py``): write → re-read through
``models/reference_import.py`` (the byte-level oracle built against the
reference's own Java readers) → score parity with the native model.

Mirrors the reference's own spec-layer regression pattern: a model trained
here must be consumable by ``IndependentNNModel`` / ``IndependentTreeModel``
/ ``IndependentWDLModel`` byte-for-byte (``BinaryDTSerializer.java:60-160``,
``BinaryWDLSerializer.java:66-125``, Encog EG persistence).
"""

import os
import sys

import numpy as np
import jax

from shifu_tpu.config import ModelConfig

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "helpers"))

from pipeline import train_algorithm as _train  # noqa: E402


def _export_spec(prepared_set):
    from shifu_tpu.pipeline.export import ExportProcessor
    assert ExportProcessor(prepared_set, params={"type": "spec"}).run() == 0
    out = os.path.join(prepared_set, "export", "reference")
    assert os.path.isdir(out)
    return out


def _fraud_eval_arrays(prepared_set, column_nums=None):
    """(bins, x, raw_by_columnNum, missing_row_mask) over the training csv
    through the SAME transform the scorer uses.  ``column_nums``: the
    model spec's feature order (defaults to the transform's own)."""
    from shifu_tpu.config.column_config import load_column_configs
    from shifu_tpu.data import DataSource
    from shifu_tpu.data.transform import DatasetTransformer
    mc = ModelConfig.load(os.path.join(prepared_set, "ModelConfig.json"))
    ccs = load_column_configs(
        os.path.join(prepared_set, "ColumnConfig.json"))
    src = DataSource(mc.dataSet.dataPath, mc.dataSet.dataDelimiter)
    tf = DatasetTransformer(mc, ccs)
    chunks = list(src.iter_chunks())
    assert len(chunks) == 1
    tc = tf.transform(chunks[0])
    df = chunks[0].data
    raw = {}
    missing = np.zeros(tc.n, bool)
    by_num = {cc.columnNum: cc for cc in ccs}
    nums = column_nums if column_nums is not None else \
        [nc.cc.columnNum for nc in tf.norm_cols]
    sel = [by_num[n] for n in nums]
    for j, cc in enumerate(sel):
        if cc.is_categorical():
            # the ref model consumes category INDICES; our bin index IS the
            # category index (missing bin == the ref missing bucket)
            raw[cc.columnNum] = tc.bins[:, j].astype(np.float64)
        else:
            v = np.array([float(x) if str(x).strip() not in ("", "NA")
                          else np.nan for x in df[cc.columnName]])
            raw[cc.columnNum] = v
            missing |= ~np.isfinite(v)
    return tc.bins, tc.x, raw, missing, sel


def test_encog_nn_roundtrip(tmp_path):
    from shifu_tpu.export.reference_spec import write_encog_nn
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.models.reference_import import load_encog_nn

    spec = nn_model.NNModelSpec(input_dim=7, hidden_nodes=[9, 5],
                                activations=["tanh", "relu"])
    params = nn_model.init_params(jax.random.PRNGKey(3), spec)
    params = [{"w": np.asarray(p["w"]), "b": np.asarray(p["b"])}
              for p in params]
    path = str(tmp_path / "model0.nn")
    write_encog_nn(path, spec, params)
    spec2, params2 = load_encog_nn(path)
    assert spec2.input_dim == 7
    assert spec2.hidden_nodes == [9, 5]
    assert [a for a in spec2.activations] == ["tanh", "relu"]
    assert spec2.output_activation == "sigmoid"
    x = np.random.default_rng(0).normal(size=(64, 7)).astype(np.float32)
    y1 = np.asarray(nn_model.forward(params, spec, x))
    y2 = np.asarray(nn_model.forward(params2, spec2, x))
    # text doubles round-trip via repr() exactly
    np.testing.assert_allclose(y1, y2, rtol=1e-6, atol=1e-7)


def test_gbt_reference_export_roundtrip(prepared_set):
    from shifu_tpu.models.reference_import import load_reference_tree
    from shifu_tpu.models.tree import IndependentTreeModel

    _train(prepared_set, "GBT",
           {"TreeNum": 8, "MaxDepth": 3, "Loss": "log", "LearningRate": 0.1})
    out = _export_spec(prepared_set)
    path = os.path.join(out, "model0.gbt")
    assert os.path.isfile(path)
    ref = load_reference_tree(path)
    assert ref.algorithm == "GBT" and ref.version == 4
    native = IndependentTreeModel.load(
        os.path.join(prepared_set, "models", "model0.gbt"))
    bins, _, raw, missing, _ = _fraud_eval_arrays(
        prepared_set, native.spec.column_nums)
    ours = native.compute(bins)[:, 0]
    logits = ref.compute(raw)
    theirs = 1.0 / (1.0 + np.exp(-logits))
    ok = ~missing
    assert ok.sum() > 1000
    # rows with every numeric present score IDENTICALLY; missing rows
    # follow the format's mean-imputation path (see reference_spec doc)
    np.testing.assert_allclose(ours[ok], theirs[ok], rtol=1e-5, atol=1e-6)


def test_rf_reference_export_roundtrip(prepared_set):
    from shifu_tpu.models.reference_import import load_reference_tree
    from shifu_tpu.models.tree import IndependentTreeModel

    _train(prepared_set, "RF",
           {"TreeNum": 6, "MaxDepth": 3, "Impurity": "variance"})
    out = _export_spec(prepared_set)
    path = os.path.join(out, "model0.rf")
    assert os.path.isfile(path)
    ref = load_reference_tree(path)
    assert ref.algorithm == "RF"
    native = IndependentTreeModel.load(
        os.path.join(prepared_set, "models", "model0.rf"))
    bins, _, raw, missing, _ = _fraud_eval_arrays(
        prepared_set, native.spec.column_nums)
    ours = native.compute(bins)[:, 0]
    theirs = ref.compute(raw)                        # mean leaf, no link
    ok = ~missing
    np.testing.assert_allclose(ours[ok], theirs[ok], rtol=1e-5, atol=1e-6)


def test_wdl_reference_roundtrip(tmp_path):
    from shifu_tpu.export.reference_spec import write_reference_wdl
    from shifu_tpu.models import wdl as wdl_model
    from shifu_tpu.models.reference_import import load_reference_wdl

    spec = wdl_model.WDLModelSpec(numeric_dim=4, cat_cardinalities=[5, 3],
                                  embed_dim=4, hidden_nodes=[8],
                                  activations=["relu"],
                                  column_nums=[2, 3, 4, 5],
                                  cat_column_nums=[6, 7])
    params = wdl_model.init_params(jax.random.PRNGKey(1), spec)
    # perturb so wide/bias terms are nonzero in the parity check
    rng = np.random.default_rng(2)
    params["wide_cat"] = [np.asarray(rng.normal(size=v.shape), np.float32)
                          for v in params["wide_cat"]]
    params["wide_num"] = np.asarray(
        rng.normal(size=params["wide_num"].shape), np.float32)
    params["bias"] = np.asarray([0.3], np.float32)
    ccs = [_cc(n, f"num{n}", bounds=[float("-inf"), 0.0], mean=0.5)
           for n in (2, 3, 4, 5)] + \
          [_cc(6, "catA", cats=["x", "y", "z", "w", "v"]),
           _cc(7, "catB", cats=["p", "q", "r"])]
    for cc in ccs:
        cc.columnStats.stdDev = 1.25
        cc.columnBinning.binCountNeg = [10, 5]
        cc.columnBinning.binCountPos = [2, 3]
        cc.columnBinning.binCountWoe = [-0.5, 0.7]
        cc.columnBinning.binPosRate = [0.17, 0.38]
    path = str(tmp_path / "model0.wdl")
    write_reference_wdl(path, spec, params, ccs)
    spec2, params2, col_stats = load_reference_wdl(path)
    # NNColumnStats round-trip: names/types/means and bin tables survive
    assert set(col_stats) == {2, 3, 4, 5, 6, 7}
    assert col_stats[6]["type"] == 2 and col_stats[2]["type"] == 1
    assert col_stats[6]["categories"] == ["x", "y", "z", "w", "v"]
    assert col_stats[2]["mean"] == 0.5 and col_stats[2]["stddev"] == 1.25
    np.testing.assert_allclose(col_stats[3]["count_woes"], [-0.5, 0.7])
    np.testing.assert_allclose(col_stats[7]["pos_rates"], [0.17, 0.38])
    assert col_stats[7]["woe_mean"] != 0.0     # computed, not zero-filled
    assert spec2.numeric_dim == 4
    assert spec2.cat_cardinalities == [5, 3]
    assert spec2.hidden_nodes == [8]
    assert spec2.cat_column_nums == [6, 7]
    x_num = rng.normal(size=(32, 4)).astype(np.float32)
    x_cat = np.stack([rng.integers(0, 5, 32),
                      rng.integers(0, 3, 32)], axis=1).astype(np.int32)
    y1 = np.asarray(wdl_model.forward(params, spec, x_num, x_cat))
    y2 = np.asarray(wdl_model.forward(params2, spec2, x_num, x_cat))
    # f32 binary round trip is exact up to jit reassociation
    np.testing.assert_allclose(y1, y2, rtol=1e-6, atol=1e-7)


def test_nn_export_cli_spec(prepared_set):
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.models.reference_import import load_encog_nn

    _train(prepared_set, "NN",
           {"Propagation": "B", "LearningRate": 0.1,
            "NumHiddenNodes": [8], "ActivationFunc": ["tanh"]})
    out = _export_spec(prepared_set)
    path = os.path.join(out, "model0.nn")
    assert os.path.isfile(path)
    spec2, params2 = load_encog_nn(path)
    from shifu_tpu.models.nn import IndependentNNModel
    native = IndependentNNModel.load(
        os.path.join(prepared_set, "models", "model0.nn"))
    _, x, _, _, _ = _fraud_eval_arrays(prepared_set)
    y1 = native.compute(x)[:, 0]
    y2 = np.asarray(nn_model.forward(params2, spec2,
                                     np.asarray(x, np.float32)))[:, 0]
    np.testing.assert_allclose(y1, y2, rtol=1e-5, atol=1e-6)


def _cc(num, name, cats=None, bounds=None, mean=0.0):
    from shifu_tpu.config.column_config import ColumnConfig, ColumnType
    cc = ColumnConfig(columnNum=num, columnName=name,
                      columnType=ColumnType.C if cats else ColumnType.N)
    cc.columnBinning.binCategory = cats
    cc.columnBinning.binBoundary = bounds
    cc.columnStats.mean = mean
    return cc


def test_tree_writer_categorical_missing_routing(tmp_path):
    """The format routes missing to the NON-bitset side: a tree sending
    the missing bin LEFT must emit isLeft=False with the complement
    bitset; RIGHT-routed missing emits isLeft=True with the left cats.
    Both must score missing and in-set rows exactly like the native
    bin-walk (the reference missing bucket == our missing bin)."""
    from shifu_tpu.export.reference_spec import write_reference_tree
    from shifu_tpu.models.reference_import import load_reference_tree
    from shifu_tpu.models.tree import IndependentTreeModel, TreeModelSpec
    from shifu_tpu.ops.tree import TreeArrays

    cats = ["a", "b", "c"]               # bins 0..2, missing bin = 3
    n_bins = 4
    for missing_left in (True, False):
        # root splits on the categorical: {a, c} (+ missing?) go left
        lm = np.zeros((3, n_bins), bool)
        lm[0, [0, 2]] = True
        lm[0, 3] = missing_left
        tree = TreeArrays(
            split_feat=np.array([0, -1, -1], np.int32),
            left_mask=lm,
            leaf_value=np.array([0.0, 0.25, 0.75], np.float32), depth=1)
        spec = TreeModelSpec(algorithm="RF", n_trees=1, depth=1,
                             n_bins=n_bins, column_nums=[5])
        path = str(tmp_path / f"m_{missing_left}.rf")
        write_reference_tree(path, spec, [tree],
                             [_cc(5, "cat", cats=cats)])
        ref = load_reference_tree(path)
        native = IndependentTreeModel(spec, [tree])
        # rows: each category + a missing value
        bins = np.array([[0], [1], [2], [3]], np.int32)
        ours = native.compute(bins)[:, 0]
        theirs = ref.compute({5: np.array([0.0, 1.0, 2.0, np.nan])})
        np.testing.assert_allclose(ours, theirs, rtol=1e-6)


def test_tree_writer_numeric_threshold_edges(tmp_path):
    """Numeric split edge cases: left-mask covering NO value bins
    (threshold = first boundary) and ALL value bins (threshold = +inf,
    only missing goes right) must round-trip to the same routing."""
    from shifu_tpu.export.reference_spec import write_reference_tree
    from shifu_tpu.models.reference_import import load_reference_tree
    from shifu_tpu.models.tree import IndependentTreeModel, TreeModelSpec
    from shifu_tpu.ops.tree import TreeArrays

    bounds = [float("-inf"), 1.0, 2.0]   # bins 0,1,2; missing bin = 3
    n_bins = 4
    for k_bins in (0, 3):
        lm = np.zeros((3, n_bins), bool)
        lm[0, :k_bins] = True            # 0 => empty left; 3 => all values
        tree = TreeArrays(
            split_feat=np.array([0, -1, -1], np.int32),
            left_mask=lm,
            leaf_value=np.array([0.0, 0.2, 0.8], np.float32), depth=1)
        spec = TreeModelSpec(algorithm="RF", n_trees=1, depth=1,
                             n_bins=n_bins, column_nums=[3])
        path = str(tmp_path / f"m_{k_bins}.rf")
        write_reference_tree(path, spec, [tree],
                             [_cc(3, "num", bounds=bounds, mean=1.5)])
        ref = load_reference_tree(path)
        native = IndependentTreeModel(spec, [tree])
        raw = np.array([0.5, 1.5, 2.5])  # one value per bin
        bins = np.array([[0], [1], [2]], np.int32)
        ours = native.compute(bins)[:, 0]
        theirs = ref.compute({3: raw})
        np.testing.assert_allclose(ours, theirs, rtol=1e-6)
