"""Sharded WDL categorical plane (``train/wdl_shard``).

What the suite pins down:

- **Parity**: the sharded trainer (row-sharded tables + moments, sparse
  per-minibatch row gather, psum-scatter update) produces BIT-identical
  params to the replicated trainer on a 1-device mesh, both full-batch
  and minibatched, in-RAM and streamed; streamed parity stays bitwise at
  2/4 devices (full-batch accumulation has one reduction order), and the
  in-RAM path stays within last-ulp accumulation noise there (data-axis
  psum reassociates the row reduction — the replicated GSPMD program's
  own numerics change identically with device count).
- **Hashed-ID path**: host and device hashing agree bitwise; the plan in
  ``spec.extra`` survives save/load; training consumes bucket ids.
- **Checkpoint resume**: interrupted sharded training resumes bit-exact.
- **Serving**: the sharded serve copy scores bit-identically to the
  classic replicated forward through the AOT bucket scorer with ZERO
  recompiles (the padded-bucket contract).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from shifu_tpu.config import environment
from shifu_tpu.models import wdl as wdl_model
from shifu_tpu.train import wdl_shard
from shifu_tpu.train.nn_trainer import TrainSettings
from shifu_tpu.train.wdl_trainer import (_make_spec, train_wdl_ensemble,
                                         train_wdl_streamed)

pytestmark = pytest.mark.wdl_shard

N = 64
CARDS = [10, 7]          # non-divisible by 2 and 4: padding always active


@pytest.fixture(autouse=True)
def _knob_hygiene(monkeypatch):
    # the replicated reference must take the GATHER lowering (the one-hot
    # einsum branch is a different dense program — parity there is only
    # approximate by design)
    monkeypatch.setattr(wdl_model, "_ONEHOT_MAX_ELEMS", 0)
    yield
    for k in ("shifu.wdl.shardTables", "shifu.wdl.shardMinBytes",
              "shifu.wdl.hashBuckets", "shifu.wdl.serveCopy",
              "shifu.wdl.serveHotRows"):
        environment.set_property(k, "")     # "" = unset, default returns


def _mesh(d):
    devs = np.asarray(jax.devices()[:d]).reshape(1, d)
    return Mesh(devs, ("ensemble", "data"))


def _spec(extra=None):
    return wdl_model.WDLModelSpec(
        numeric_dim=3, cat_cardinalities=list(CARDS), embed_dim=4,
        hidden_nodes=[8], activations=["relu"], extra=extra or {})


def _data(seed=0, n=N):
    rng = np.random.default_rng(seed)
    xn = rng.normal(size=(n, 3)).astype(np.float32)
    xc = np.stack([rng.integers(0, CARDS[0], n),
                   rng.integers(0, CARDS[1], n)], axis=1).astype(np.int32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    return xn, xc, y, np.ones(n, np.float32)


def _settings(**kw):
    base = dict(optimizer="ADAM", learning_rate=0.05, l2=1e-4, epochs=3,
                batch_size=0, early_stop_window=0, seed=7)
    base.update(kw)
    return TrainSettings(**base)


def _leaves(tree):
    return jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, tree))


def _assert_bitwise(a, b):
    for x, z in zip(_leaves(a), _leaves(b)):
        assert x.dtype == z.dtype and x.shape == z.shape
        assert x.tobytes() == z.tobytes()


def _assert_close(a, b, atol):
    for x, z in zip(_leaves(a), _leaves(b)):
        np.testing.assert_allclose(x, z, rtol=0, atol=atol)


# ------------------------------------------------------- in-RAM parity
@pytest.mark.parametrize("bs", [0, 16])
def test_inram_sharded_matches_replicated_bitwise_1dev(bs):
    xn, xc, y, w = _data()
    spec = _spec()
    rep = train_wdl_ensemble(xn, xc, y, w, spec, _settings(batch_size=bs),
                             bags=2, mesh=_mesh(1), shard=False)
    sh = train_wdl_ensemble(xn, xc, y, w, spec, _settings(batch_size=bs),
                            bags=2, mesh=_mesh(1), shard=True)
    _assert_bitwise(rep.params, sh.params)
    assert np.array_equal(rep.valid_errors, sh.valid_errors)
    assert np.array_equal(rep.train_errors, sh.train_errors)


@pytest.mark.parametrize("d", [2, 4])
@pytest.mark.parametrize("bs", [0, 16])
def test_inram_sharded_multi_device_last_ulp(d, bs):
    """At D>1 the data-axis psum reassociates the row reduction (the
    replicated GSPMD all-reduce does the same), so parity is pinned to
    last-ulp accumulation noise rather than bytes."""
    xn, xc, y, w = _data()
    spec = _spec()
    rep = train_wdl_ensemble(xn, xc, y, w, spec, _settings(batch_size=bs),
                             bags=2, mesh=_mesh(1), shard=False)
    sh = train_wdl_ensemble(xn, xc, y, w, spec, _settings(batch_size=bs),
                            bags=2, mesh=_mesh(d), shard=True)
    _assert_close(rep.params, sh.params, atol=1e-5)


def test_sharded_tables_are_actually_sharded():
    """No device may hold a full table row-range: each table leaf's
    per-device shard is 1/D of its padded rows."""
    spec = _spec()
    mesh = _mesh(4)
    plane = wdl_shard.WDLShardPlane(mesh, spec, 2)
    member = plane.pad_params(
        wdl_model.init_params(jax.random.PRNGKey(0), spec))
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[member, member])
    from shifu_tpu.train.optimizers import make_optimizer
    opt = make_optimizer("ADAM", 0.05)
    ostate = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[opt.init(member)] * 2)
    stacked, ostate = plane.put(stacked, ostate)
    for i, t in enumerate(stacked["embed"]):
        vp = plane.vs[i] * plane.d
        assert t.shape[1] == vp
        for sh_piece in t.addressable_shards:
            assert sh_piece.data.shape[1] == plane.vs[i]
    # moments follow the same layout: any optimizer leaf living under an
    # "embed"/"wide_cat" path is row-sharded like its parameter
    flat, _ = jax.tree_util.tree_flatten_with_path(ostate)
    checked = 0
    for path, leaf in flat:
        keys = [getattr(k, "key", getattr(k, "name", None)) for k in path]
        if "embed" not in keys and "wide_cat" not in keys:
            continue
        if leaf.ndim < 2:
            continue
        idx = next(getattr(k, "idx", None) for k in path
                   if hasattr(k, "idx"))
        for sh_piece in leaf.addressable_shards:
            assert sh_piece.data.shape[1] == plane.vs[idx]
        checked += 1
    assert checked >= 2 * len(CARDS)   # m and v per table at least


def test_pad_unpad_roundtrip_non_divisible():
    spec = _spec()
    plane = wdl_shard.WDLShardPlane(_mesh(4), spec, 1)
    assert plane.vs == [-(-c // 4) for c in CARDS]
    member = wdl_model.init_params(jax.random.PRNGKey(1), spec)
    padded = plane.pad_params(member)
    for i, c in enumerate(CARDS):
        assert padded["embed"][i].shape[0] == plane.vs[i] * 4
        assert np.all(np.asarray(padded["embed"][i][c:]) == 0)
    _assert_bitwise(plane.unpad_params(padded), member)


# ------------------------------------------------------ streamed parity
class _Win:
    def __init__(self, i, s, arrays, rows):
        self.index, self.start = i, s
        self.rows = self.n_valid = rows
        self.arrays = arrays


class _FakePlanes:
    def __init__(self, x, bins, y, w, wrows):
        self.x, self.bins, self.y, self.w = x, bins, y, w
        self.window_rows = wrows
        self.num_rows = len(y)

    def windows(self):
        wr = self.window_rows
        for i, s in enumerate(range(0, self.num_rows, wr)):
            yield _Win(i, s, {"x": self.x[s:s + wr], "y": self.y[s:s + wr],
                              "w": self.w[s:s + wr],
                              "bins": self.bins[s:s + wr]}, wr)


def _streamed(d, shard, spec=None, seed=0):
    xn, xc, y, w = _data(seed)
    spec = spec or _spec(extra={"num_feat_idx": [0, 1, 2],
                                "cat_col_idx": [0, 1]})
    planes = _FakePlanes(xn, xc, y, w, 16)
    masks = (np.random.default_rng(5).random((2, N)) > 0.3) \
        .astype(np.float32)

    def mask_fn(idx, yw):
        tm = masks[:, idx * 16:(idx + 1) * 16]
        return tm, 1.0 - tm

    return train_wdl_streamed(planes, spec, _settings(), 2, mask_fn,
                              [0, 1, 2], [0, 1], mesh=_mesh(d),
                              shard=shard)


def test_streamed_sharded_bitwise_1dev():
    rep = _streamed(1, False)
    sh = _streamed(1, True)
    _assert_bitwise(rep.params, sh.params)
    # the error scalars come from a different float64 summation tree
    # (per-shard partial sums + psum) — pinned to last-ulp, params above
    # carry the bitwise claim
    np.testing.assert_allclose(rep.valid_errors, sh.valid_errors,
                               rtol=0, atol=1e-12)


@pytest.mark.parametrize("d", [2, 4])
def test_streamed_sharded_multi_device_last_ulp(d):
    """Multi-device psum reassociates the window reduction, so D>1 is
    pinned to last-ulp accumulation noise (same physics as in-RAM)."""
    rep = _streamed(1, False)
    sh = _streamed(d, True)
    _assert_close(rep.params, sh.params, atol=1e-5)


# ------------------------------------------------------- hashed-ID path
def test_hash_host_device_bitwise():
    from shifu_tpu.ops.hashing import (column_hash_key, hash_bucket_device,
                                       hash_bucket_host)
    ids = np.concatenate([
        np.arange(0, 64, dtype=np.int32),
        np.asarray([2 ** 31 - 1, 12345678, 999983], np.int32)])
    for col in (0, 3, 17):
        key = column_hash_key(col)
        host = hash_bucket_host(ids, key, 1 << 20)
        dev = np.asarray(jax.jit(
            lambda a: hash_bucket_device(a, key, 1 << 20))(
            jnp.asarray(ids)))
        assert np.array_equal(host, dev)
        assert host.min() >= 0 and host.max() < (1 << 20)


class _FakeCC:
    def __init__(self, nbins):
        self._n = nbins

    def num_bins(self):
        return self._n


def test_make_spec_hash_plan():
    by_num = {11: _FakeCC(999), 22: _FakeCC(4)}
    spec = _make_spec(2, by_num, [11, 22], [], [0, 1], [2, 3],
                      {"HashBuckets": 16})
    # 999+1 > 16 buckets -> hashed; 4+1 stays exact
    assert spec.cat_cardinalities == [16, 5]
    assert spec.extra["hash_buckets"] == 16
    assert spec.extra["hashed_cols"] == [0]
    from shifu_tpu.ops.hashing import column_hash_key
    assert spec.extra["hash_keys"] == [column_hash_key(11)]
    # knob form drives the same plan
    environment.set_property("shifu.wdl.hashBuckets", "16")
    spec2 = _make_spec(2, by_num, [11, 22], [], [0, 1], [2, 3], {})
    assert spec2.cat_cardinalities == [16, 5]
    assert spec2.extra["hashed_cols"] == [0]
    # no plan at all without the knob
    environment.set_property("shifu.wdl.hashBuckets", "")
    spec3 = _make_spec(2, by_num, [11, 22], [], [0, 1], [2, 3], {})
    assert "hash_buckets" not in spec3.extra
    assert spec3.cat_cardinalities == [1000, 5]


def test_hashed_training_scores_consistently(tmp_path):
    """Train on hashed ids, save, reload: the standalone scorer hashing
    raw ids host-side matches forward() on pre-hashed ids bitwise, and
    the plan survives the model file."""
    from shifu_tpu.ops.hashing import column_hash_key
    buckets = 6
    spec = _spec(extra={"num_feat_idx": [0, 1, 2], "cat_col_idx": [0, 1],
                        "hash_buckets": buckets, "hashed_cols": [0],
                        "hash_keys": [column_hash_key(0)]})
    spec = wdl_model.WDLModelSpec(
        numeric_dim=3, cat_cardinalities=[buckets, CARDS[1]], embed_dim=4,
        hidden_nodes=[8], activations=["relu"], extra=spec.extra)
    xn, xc, y, w = _data()        # raw ids in [0, 10) for the hashed col
    res = train_wdl_ensemble(xn, xc, y, w, spec, _settings(epochs=2),
                             bags=1, mesh=_mesh(2), shard=True)
    path = str(tmp_path / "model0.wdl")
    wdl_model.save_model(path, spec, res.params[0])
    m = wdl_model.IndependentWDLModel.load(path)
    assert wdl_model.hash_plan(m.spec) is not None
    got = m.compute(xn, xc)
    hashed = wdl_model.apply_hash_host(m.spec, xc)
    assert hashed[:, 0].max() < buckets
    # params must be a jit ARGUMENT (closed-over arrays become XLA
    # constants and const-fold into a slightly different program)
    want = np.asarray(jax.jit(lambda p, a, b: wdl_model.forward(
        p, m.spec, a, b))(m.params, jnp.asarray(xn), jnp.asarray(hashed)))
    assert got.tobytes() == want.tobytes()


# ------------------------------------------------- checkpoint / resume
def test_sharded_checkpoint_resume_bit_exact(tmp_path):
    xn, xc, y, w = _data()
    spec = _spec()

    def run(ckdir, epochs, resume):
        s = _settings(epochs=epochs, batch_size=16)
        s.checkpoint_dir = ckdir
        s.checkpoint_every = 2
        s.resume = resume
        return train_wdl_ensemble(xn, xc, y, w, spec, s, bags=2,
                                  mesh=_mesh(4), shard=True)

    full = run(None, 4, False)
    ckdir = str(tmp_path / "ck")
    run(ckdir, 2, False)                      # interrupted at epoch 2
    resumed = run(ckdir, 4, True)             # restores + 2 more epochs
    _assert_bitwise(full.params, resumed.params)
    assert np.array_equal(full.valid_errors, resumed.valid_errors)


# --------------------------------------------------------------- serve
def test_serve_sharded_bit_identical_zero_recompiles():
    """Same scorer machinery, classic full copy vs sharded serve copy:
    every score byte matches, and the padded-bucket contract holds —
    zero recompiles after warm()."""
    from shifu_tpu.serve.scorer import AOTScorer, serve_recompile_count
    spec = _spec(extra={"num_feat_idx": [0, 2, 4], "cat_col_idx": [1, 3]})
    m = wdl_model.IndependentWDLModel(
        spec, wdl_model.init_params(jax.random.PRNGKey(3), spec))

    def build(copy_mode, name):
        environment.set_property("shifu.wdl.serveCopy", copy_mode)
        s = AOTScorer([m], buckets=(1, 4, 16), name=name)
        s.warm(launch=True)
        return s

    classic = build("full", "serve.score.wdlclassic")
    sharded = build("sharded", "serve.score.wdlsharded")
    rng = np.random.default_rng(7)
    x = rng.normal(size=(16, classic.n_features)).astype(np.float32)
    bins = rng.integers(0, 7, size=(16, classic.n_bins_cols)) \
        .astype(np.int32)
    for n in (1, 3, 4, 11, 16):
        got = sharded.score_batch(x[:n], bins[:n])
        want = classic.score_batch(x[:n], bins[:n])
        assert got.tobytes() == want.tobytes()
    assert serve_recompile_count("serve.score.wdlsharded") == 0


def test_serve_copy_mode_resolution():
    spec = _spec()
    params = wdl_model.init_params(jax.random.PRNGKey(0), spec)
    host = jax.tree_util.tree_map(np.asarray, params)
    # tiny tables, auto -> full (classic forward, fn is None)
    mode, fwd = wdl_shard.build_serve_forward(spec, host)
    assert mode == "full" and fwd is None
    # forced sharded
    environment.set_property("shifu.wdl.serveCopy", "sharded")
    mode, fwd = wdl_shard.build_serve_forward(spec, host)
    assert mode == "sharded" and fwd is not None
    xn, xc, _, _ = _data()
    got = np.asarray(jax.jit(fwd)(jnp.asarray(xn), jnp.asarray(xc)))
    want = np.asarray(jax.jit(lambda a, b: wdl_model.forward(
        params, spec, a, b))(jnp.asarray(xn), jnp.asarray(xc)))
    assert got.tobytes() == want.tobytes()
    # hot copy: head rows score exactly, shape contract holds
    environment.set_property("shifu.wdl.serveCopy", "hot")
    environment.set_property("shifu.wdl.serveHotRows", "4")
    mode, fwd = wdl_shard.build_serve_forward(spec, host)
    assert mode == "hot" and fwd is not None
    hot = np.asarray(jax.jit(fwd)(jnp.asarray(xn), jnp.asarray(xc)))
    assert hot.shape == want.shape
    head = (xc < 4).all(axis=1)
    assert head.any()
    assert np.array_equal(hot[head], want[head])


# ------------------------------------------------------ gating & costs
def test_shard_gating():
    spec = _spec()
    mesh = _mesh(2)
    # explicit override wins both ways
    assert wdl_shard.shard_enabled(spec, mesh, 2, "f32", override=True)
    assert not wdl_shard.shard_enabled(spec, mesh, 2, "f32",
                                       override=False)
    # knob off beats auto sizing
    environment.set_property("shifu.wdl.shardTables", "off")
    assert not wdl_shard.shard_enabled(spec, mesh, 2, "f32")
    environment.set_property("shifu.wdl.shardTables", "on")
    assert wdl_shard.shard_enabled(spec, mesh, 2, "f32")
    # auto: tiny tables stay replicated; a zero threshold shards them
    environment.set_property("shifu.wdl.shardTables", "auto")
    assert not wdl_shard.shard_enabled(spec, mesh, 2, "f32")
    environment.set_property("shifu.wdl.shardMinBytes", "0")
    assert wdl_shard.shard_enabled(spec, mesh, 2, "f32")
    # single-device data axis never shards
    assert not wdl_shard.shard_enabled(spec, _mesh(1), 2, "f32")


def test_cost_models_registered():
    from shifu_tpu.obs.costs import cost_models
    models = cost_models()
    for name in ("wdl.sparse_gather", "wdl.shard_update"):
        assert name in models
    got = models["wdl.sparse_gather"](rows=128, cols=2, embed=4,
                                      members=2, devices=4, bytes_per=4)
    assert got["flops"] > 0 and got["bytes_accessed"] > 0
    got = models["wdl.shard_update"](table_elems=1000, members=2,
                                     steps=3, bytes_per=4)
    assert got["flops"] > 0 and got["bytes_accessed"] > 0


def test_fan_in_scaled_embedding_init():
    """Embedding init scales by embed_dim**-0.5 (fan-in), wide tables
    seed identically (zeros) on every path — replicated, padded-sharded,
    and hashed specs all start from the same math."""
    spec = _spec()
    params = wdl_model.init_params(jax.random.PRNGKey(0), spec)
    emb = np.concatenate([np.asarray(t).ravel() for t in params["embed"]])
    assert abs(emb.std() - spec.embed_dim ** -0.5) < 0.2 * emb.std()
    for t in params["wide_cat"]:
        assert np.all(np.asarray(t) == 0)
    plane = wdl_shard.WDLShardPlane(_mesh(4), spec, 1)
    _assert_bitwise(plane.unpad_params(plane.pad_params(params)), params)
