"""Streamed tree training, mesh-parallel trees, mid-forest resume, Friedman
gain, gain-based FI (reference DTMaster/DTWorker parity features)."""

import json
import os

import numpy as np
import pytest

import jax


def _tree_data(n=1200, c=6, n_bins=8, seed=3):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    logit = (bins[:, 0] - 3) * 0.8 + (bins[:, 1] == 2) * 1.5 - 0.5
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    w = np.ones(n, np.float32)
    return bins, y, w


def _write_tree_shards(d, bins, y, w, shard_rows=300):
    from shifu_tpu.data.shards import Shards
    os.makedirs(d, exist_ok=True)
    shard = 0
    for s in range(0, len(y), shard_rows):
        e = min(s + shard_rows, len(y))
        np.savez(os.path.join(d, f"part-{shard:05d}.npz"),
                 bins=bins[s:e].astype(np.int16), y=y[s:e], w=w[s:e])
        shard += 1
    with open(os.path.join(d, "schema.json"), "w") as f:
        json.dump({"columnNums": list(range(bins.shape[1])),
                   "numShards": shard, "numRows": len(y)}, f)
    return Shards.open(d)


def test_streamed_gbt_matches_in_ram_masks_aside(tmp_path):
    """Streamed GBT with the same hash masks must produce the SAME forest as
    an in-RAM run using those masks (histogram sums are associative)."""
    from shifu_tpu.data.streaming import ShardStream, row_uniform
    from shifu_tpu.train.dt_trainer import (DTSettings, train_gbt,
                                            train_gbt_streamed)

    bins, y, w = _tree_data()
    n_bins = 8
    settings = DTSettings(n_trees=4, depth=3, loss="log", learning_rate=0.1,
                          valid_rate=0.2, seed=0)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    stream = ShardStream(shards, ("bins", "y", "w"), window_rows=256)
    res_st = train_gbt_streamed(stream, n_bins, None, settings)

    # in-RAM run with the hash validation mask instead of np-rng one
    vmask = row_uniform(settings.seed, 11, np.arange(len(y))) < 0.2
    import shifu_tpu.train.dt_trainer as dt
    orig = dt.validation_split
    dt.validation_split = lambda n, rate, seed: vmask
    try:
        res_ram = train_gbt(bins, y, w, n_bins, None, settings)
    finally:
        dt.validation_split = orig
    assert res_st.trees_built == res_ram.trees_built
    for ts, tr in zip(res_st.trees, res_ram.trees):
        np.testing.assert_array_equal(ts.split_feat, tr.split_feat)
        np.testing.assert_array_equal(ts.left_mask, tr.left_mask)
        np.testing.assert_allclose(ts.leaf_value, tr.leaf_value,
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(res_st.valid_error, res_ram.valid_error,
                               rtol=1e-4)


def test_streamed_rf_trains(tmp_path):
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf_streamed

    bins, y, w = _tree_data()
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    stream = ShardStream(shards, ("bins", "y", "w"), window_rows=256)
    settings = DTSettings(n_trees=5, depth=3, impurity="entropy", loss="log",
                          bagging_rate=1.0, seed=1)
    res = train_rf_streamed(stream, 8, None, settings)
    assert res.trees_built == 5
    assert np.isfinite(res.valid_error)
    assert res.feature_importance[0] > 0  # informative feature got gain


def test_gbt_mesh_equivalence():
    """1-device vs 8-device mesh GBT must build identical trees."""
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt

    bins, y, w = _tree_data(n=640)
    settings = DTSettings(n_trees=3, depth=3, loss="log", seed=0)
    devs = jax.devices("cpu")
    r1 = train_gbt(bins, y, w, 8, None, settings,
                   mesh=device_mesh(1, devices=devs[:1]))
    r8 = train_gbt(bins, y, w, 8, None, settings,
                   mesh=device_mesh(1, devices=devs[:8]))
    for t1, t8 in zip(r1.trees, r8.trees):
        np.testing.assert_array_equal(t1.split_feat, t8.split_feat)
        np.testing.assert_allclose(t1.leaf_value, t8.leaf_value,
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(r1.valid_error, r8.valid_error, rtol=1e-4)


def test_gbt_checkpoint_resume_identical():
    """Kill at tree N/2 + resume == uninterrupted run (stateless per-tree
    RNG; reference DTMaster.doCheckPoint fail-over)."""
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt

    bins, y, w = _tree_data(n=800)
    full = train_gbt(bins, y, w, 8, None,
                     DTSettings(n_trees=6, depth=3, loss="log", seed=4))
    half = train_gbt(bins, y, w, 8, None,
                     DTSettings(n_trees=3, depth=3, loss="log", seed=4))
    resumed = train_gbt(bins, y, w, 8, None,
                        DTSettings(n_trees=6, depth=3, loss="log", seed=4),
                        init_trees=half.trees,
                        init_score=half.spec_kwargs["init_score"],
                        start_history=half.history)
    assert resumed.trees_built == full.trees_built
    for tf, tr in zip(full.trees, resumed.trees):
        np.testing.assert_array_equal(tf.split_feat, tr.split_feat)
        np.testing.assert_allclose(tf.leaf_value, tr.leaf_value,
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(full.valid_error, resumed.valid_error,
                               rtol=1e-5)


def test_rf_resume_identical():
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf

    bins, y, w = _tree_data(n=800)
    s = DTSettings(n_trees=6, depth=3, impurity="entropy", loss="log", seed=7)
    full = train_rf(bins, y, w, 8, None, s)
    s_half = DTSettings(n_trees=3, depth=3, impurity="entropy", loss="log",
                        seed=7)
    half = train_rf(bins, y, w, 8, None, s_half)
    resumed = train_rf(bins, y, w, 8, None, s, init_trees=half.trees,
                       start_history=half.history)
    for tf, tr in zip(full.trees, resumed.trees):
        np.testing.assert_array_equal(tf.split_feat, tr.split_feat)
    np.testing.assert_allclose(full.valid_error, resumed.valid_error,
                               rtol=1e-5)


def test_friedman_gain_prefers_balanced_split():
    """FriedmanMSE = (wr*sl - wl*sr)^2 / (wl*wr*(wl+wr)) — check against a
    tiny hand computation via best_splits."""
    import jax.numpy as jnp
    from shifu_tpu.ops.tree import best_splits

    # one node, one feature, 3 bins: w=[2,2,2], y-sums=[2,0,0]
    hist = np.zeros((1, 1, 3, 3), np.float32)
    hist[0, 0, :, 0] = [2, 2, 2]
    hist[0, 0, :, 1] = [2, 0, 0]
    hist[0, 0, :, 2] = [2, 0, 0]
    gain, feat, lmask, leaf, node_w = best_splits(
        jnp.asarray(hist), jnp.zeros(1, bool), jnp.ones(1, bool),
        "friedmanmse", 1.0, 0.0)
    # split after bin0: wl=2, sl=2, wr=4, sr=0 -> (4*2-2*0)^2/(2*4*6) = 64/48
    np.testing.assert_allclose(float(gain[0]), 64 / 48, rtol=1e-5)
    assert int(feat[0]) == 0
    assert np.asarray(lmask)[0, 0] and not np.asarray(lmask)[0, 1]


def test_gain_fi_beats_split_count_semantics():
    """FI must reflect gain magnitude: the informative feature dominates."""
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt

    bins, y, w = _tree_data(n=1000)
    res = train_gbt(bins, y, w, 8, None,
                    DTSettings(n_trees=5, depth=3, loss="log", seed=0))
    fi = res.feature_importance
    assert fi[0] == fi.max()              # bins[:,0] drives the target
    assert fi[0] > 0


def test_pipeline_tree_resume(model_set):
    """`train -resume` restores the mid-forest checkpoint and finishes with
    the full tree count."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.models import tree as tree_model

    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.algorithm = "GBT"
    mc.train.params = {"TreeNum": 6, "MaxDepth": 3, "Loss": "log",
                       "CheckpointInterval": 2}
    mc.save(mcp)
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0
    ckpt = os.path.join(model_set, "tmp", "checkpoints", "forest_ckpt.npz")
    assert os.path.isfile(ckpt)
    # simulate a crash after the checkpoint: resume must finish to 6 trees
    assert TrainProcessor(model_set, params={"resume": True}).run() == 0
    spec, trees = tree_model.load_model(
        os.path.join(model_set, "models", "model0.gbt"))
    assert spec.n_trees == 6


def test_streamed_gbt_mesh_equivalence(tmp_path):
    """Streamed GBT on an 8-device mesh == streamed GBT single-device: the
    window histogram psum over the data axis is associative."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed

    bins, y, w = _tree_data(n=1024)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=3, depth=3, loss="log", seed=0)
    devs = jax.devices("cpu")
    r1 = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings)
    r8 = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, mesh=device_mesh(1, devices=devs[:8]))
    for t1, t8 in zip(r1.trees, r8.trees):
        np.testing.assert_array_equal(t1.split_feat, t8.split_feat)
        np.testing.assert_allclose(t1.leaf_value, t8.leaf_value,
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(r1.valid_error, r8.valid_error, rtol=1e-4)


def test_streamed_gbt_mesh_kernel_equivalence(tmp_path, monkeypatch):
    """Streamed GBT on the 8-device mesh with the shard_map'd MXU kernel
    forced on (interpret mode on CPU) == the scatter path: the out-of-core
    multi-chip config keeps the kernel (VERDICT r3 item 1)."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed

    bins, y, w = _tree_data(n=1024)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=2, depth=3, loss="log", seed=0)
    mesh8 = device_mesh(1, devices=jax.devices("cpu")[:8])
    r_scatter = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, mesh=mesh8)
    monkeypatch.setenv("SHIFU_HIST_PALLAS", "force")
    r_kernel = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, mesh=mesh8)
    for t1, t8 in zip(r_scatter.trees, r_kernel.trees):
        np.testing.assert_array_equal(t1.split_feat, t8.split_feat)
        np.testing.assert_allclose(t1.leaf_value, t8.leaf_value,
                                   rtol=1e-4, atol=1e-5)


def test_streamed_rf_mesh_equivalence(tmp_path):
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf_streamed

    bins, y, w = _tree_data(n=1024)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=3, depth=3, impurity="entropy", loss="log",
                          bagging_rate=1.0, seed=1)
    devs = jax.devices("cpu")
    r1 = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings)
    r8 = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, mesh=device_mesh(1, devices=devs[:8]))
    for t1, t8 in zip(r1.trees, r8.trees):
        np.testing.assert_array_equal(t1.split_feat, t8.split_feat)
        np.testing.assert_allclose(t1.leaf_value, t8.leaf_value,
                                   rtol=1e-4, atol=1e-5)


def test_streamed_rf_native_multiclass(tmp_path):
    """Streamed NATIVE multiclass RF (VERDICT r3 item 6): per-class stat
    channels through the window/fused paths; fused-resident and disk-tail
    runs build identical forests; votes recover the signal."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.ops.tree import predict_forest
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf_streamed

    rng = np.random.default_rng(9)
    n, c, n_bins = 1024, 4, 8
    y = rng.integers(0, 3, n).astype(np.float32)
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    bins[:, 0] = (y * 2).astype(np.int32)          # informative feature
    w = np.ones(n, np.float32)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=4, depth=3, impurity="entropy",
                          n_classes=3, bagging_rate=1.0, seed=1)
    full = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        n_bins, None, settings, cache_budget=1 << 30)
    assert full.trees_built == 4
    assert full.trees[0].leaf_value.shape == (15, 3)   # class distributions
    assert np.isfinite(full.valid_error)
    votes = predict_forest(full.trees, bins)           # [n, 3] mean dist
    assert (votes.argmax(1) == y).mean() > 0.95
    # per prepared RF window: bins ride uint8 (c bytes/row) + y/w f32
    win_bytes = 256 * (c * 1 + 2 * 4)
    tail = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        n_bins, None, settings, cache_budget=2 * win_bytes + 64)
    assert tail.disk_passes > full.disk_passes
    for tf, tt in zip(full.trees, tail.trees):
        np.testing.assert_array_equal(tf.split_feat, tt.split_feat)
        np.testing.assert_allclose(tf.leaf_value, tt.leaf_value,
                                   rtol=1e-4, atol=1e-5)


def test_streamed_rf_native_multiclass_mesh_equivalence(tmp_path):
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf_streamed

    rng = np.random.default_rng(9)
    n, c, n_bins = 1024, 4, 8
    y = rng.integers(0, 3, n).astype(np.float32)
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    bins[:, 0] = (y * 2).astype(np.int32)
    w = np.ones(n, np.float32)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=3, depth=3, impurity="entropy",
                          n_classes=3, bagging_rate=1.0, seed=1)
    r1 = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        n_bins, None, settings)
    r8 = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        n_bins, None, settings,
        mesh=device_mesh(1, devices=jax.devices("cpu")[:8]))
    for t1, t8 in zip(r1.trees, r8.trees):
        np.testing.assert_array_equal(t1.split_feat, t8.split_feat)
        np.testing.assert_allclose(t1.leaf_value, t8.leaf_value,
                                   rtol=1e-4, atol=1e-5)


def test_resident_cache_one_disk_pass_when_fits(tmp_path):
    """Dataset under the device budget: the whole forest costs ONE disk
    pass (the warm pass) — the round-2 (depth+2)-passes-per-tree multiplier
    is gone (MemoryDiskFloatMLDataSet.java:54-99 memory tier)."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed

    bins, y, w = _tree_data(n=1024)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=4, depth=3, loss="log", seed=0)
    res = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=1 << 30)
    assert res.trees_built == 4
    assert res.disk_passes == 1


def test_resident_cache_tail_restream_matches_full_residency(tmp_path):
    """A budget that only fits half the windows must give the SAME forest,
    just with more disk passes (tail re-streams)."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed

    bins, y, w = _tree_data(n=1024)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=2, depth=3, loss="log", seed=0)
    full = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=1 << 30)
    # one prepared 256-row GBT window is 256*(6*1 + 3*4) bytes (uint8 bins
    # + y/tw/vw f32); cap to fit ~2 of the 4 windows
    win_bytes = 256 * (6 * 1 + 3 * 4)
    tail = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=2 * win_bytes + 64)
    assert tail.disk_passes > full.disk_passes
    for tf, tt in zip(full.trees, tail.trees):
        np.testing.assert_array_equal(tf.split_feat, tt.split_feat)
        np.testing.assert_allclose(tf.leaf_value, tt.leaf_value,
                                   rtol=1e-4, atol=1e-5)


def test_rf_fused_matches_tail_restream(tmp_path):
    """RF's fully-resident fused executable and the disk-tail window loop
    must build the same forest (bags/oob state included)."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf_streamed

    bins, y, w = _tree_data(n=1024)
    settings = DTSettings(n_trees=3, depth=3, impurity="entropy",
                          loss="squared", seed=2)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    full = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=1 << 30)
    # per prepared RF window: uint8 bins + y/w f32
    win_bytes = 256 * (6 * 1 + 2 * 4)
    tail = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=2 * win_bytes + 64)
    assert tail.disk_passes > full.disk_passes
    assert full.trees_built == tail.trees_built == 3
    for tf, tt in zip(full.trees, tail.trees):
        np.testing.assert_array_equal(tf.split_feat, tt.split_feat)
        np.testing.assert_allclose(tf.leaf_value, tt.leaf_value,
                                   rtol=1e-4, atol=1e-5)
    for (a, b), (c_, d) in zip(full.history, tail.history):
        assert abs(a - c_) < 1e-5 and abs(b - d) < 1e-5


# ------------------------------------------------ super-batched disk tail
# (round 9: one disk pass feeds everything — exact super-batch schedule
# with subtraction + leaf-sum bottom, coarse-to-fine speculation behind
# SHIFU_TREE_TAIL_C2F, and pass-count guards that fail on any future
# re-stream regression)

GBT_WIN_BYTES = 256 * (6 * 1 + 3 * 4)     # uint8 bins + y/tw/vw f32
RF_WIN_BYTES = 256 * (6 * 1 + 2 * 4)      # uint8 bins + y/w f32


def _forests_bitwise_equal(a, b):
    assert len(a.trees) == len(b.trees)
    for ta, tb in zip(a.trees, b.trees):
        assert np.asarray(ta.split_feat).tobytes() == \
            np.asarray(tb.split_feat).tobytes()
        assert np.asarray(ta.left_mask).tobytes() == \
            np.asarray(tb.left_mask).tobytes()
        assert np.asarray(ta.leaf_value).tobytes() == \
            np.asarray(tb.leaf_value).tobytes()


def test_tail_exact_super_batch_matches_resident(tmp_path, monkeypatch):
    """The exact super-batch tail schedule (c2f off) must reproduce the
    fully-resident forest: STRUCTURE bit-identical, leaf values
    f32-equivalent (the resident run sums each histogram in one fused
    block, the tail run as resident-block + window partials — same
    associativity class, different f32 grouping)."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed

    monkeypatch.setenv("SHIFU_TREE_TAIL_C2F", "0")
    bins, y, w = _tree_data(n=1024)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=4, depth=3, loss="log", seed=0)
    full = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=1 << 30)
    tail = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=2 * GBT_WIN_BYTES + 64)
    for tf, tt in zip(full.trees, tail.trees):
        np.testing.assert_array_equal(tf.split_feat, tt.split_feat)
        np.testing.assert_array_equal(tf.left_mask, tt.left_mask)
        np.testing.assert_allclose(tf.leaf_value, tt.leaf_value,
                                   rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.array(full.history),
                               np.array(tail.history), rtol=1e-5)


def test_tail_c2f_bitwise_matches_exact(tmp_path, monkeypatch):
    """Coarse-to-fine speculation (repairs included) is a SCHEDULE, not a
    model change: the forest must be bit-identical to the exact tail
    schedule, with strictly fewer tail re-streams."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed

    bins, y, w = _tree_data(n=1024)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=5, depth=3, loss="log", seed=0)
    budget = 2 * GBT_WIN_BYTES + 64

    monkeypatch.setenv("SHIFU_TREE_TAIL_C2F", "0")
    exact = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=budget)
    monkeypatch.setenv("SHIFU_TREE_TAIL_C2F", "1")
    c2f = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=budget)
    _forests_bitwise_equal(exact, c2f)
    np.testing.assert_allclose(np.array(exact.history),
                               np.array(c2f.history), rtol=1e-5)
    # the schedule guarantee: exact pays (depth+2) re-streams per tree,
    # speculation must beat it (repairs included)
    assert exact.tail_sweeps == settings.n_trees * (settings.depth + 2)
    assert c2f.tail_sweeps < exact.tail_sweeps


def test_tail_c2f_candidate_k_covering_matches_exact(tmp_path,
                                                     monkeypatch):
    """Bounded-candidate scan at K that covers every split the exact
    trees use (a constant column can never be chosen, so K = C-1 covers
    all) must stay bit-identical to the exact schedule — the documented
    exactness contract of -Dshifu.tree.tailCandidateK."""
    from shifu_tpu.config import environment
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed

    bins, y, w = _tree_data(n=1024)
    bins = bins.copy()
    bins[:, 5] = 0                     # constant -> zero gain everywhere
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=3, depth=3, loss="log", seed=0)
    budget = 2 * GBT_WIN_BYTES + 64

    monkeypatch.setenv("SHIFU_TREE_TAIL_C2F", "0")
    exact = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=budget)
    monkeypatch.setenv("SHIFU_TREE_TAIL_C2F", "1")
    environment.set_property("shifu.tree.tailCandidateK", "5")
    try:
        c2f = train_gbt_streamed(
            ShardStream(shards, ("bins", "y", "w"), window_rows=256),
            8, None, settings, cache_budget=budget)
    finally:
        environment.set_property("shifu.tree.tailCandidateK", "")
    _forests_bitwise_equal(exact, c2f)


def test_tail_disk_passes_relation(tmp_path, monkeypatch):
    """disk_passes must stay = 1 warm pass + tail_sweeps (no hidden full
    re-streams), and bytes_read must be accounted per run."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed

    monkeypatch.setenv("SHIFU_TREE_TAIL_C2F", "0")
    bins, y, w = _tree_data(n=1024)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    settings = DTSettings(n_trees=2, depth=3, loss="log", seed=0)
    res = train_gbt_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, settings, cache_budget=2 * GBT_WIN_BYTES + 64)
    assert res.disk_passes == 1 + res.tail_sweeps
    assert res.bytes_read > 0


def test_rf_tail_super_batch_width_invariance_and_bounds(tmp_path,
                                                         monkeypatch):
    """RF: one super-batch feeds (depth+2) tail sweeps for ALL its trees;
    the batch width must not change the forest (bags are stateless per
    (tree, row), oob chains in tree order), and passes per tree obey the
    ceil(depth/SB)+1 acceptance bound."""
    import math

    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf_streamed

    bins, y, w = _tree_data(n=1024)
    shards = _write_tree_shards(str(tmp_path / "s"), bins, y, w)
    budget = 2 * RF_WIN_BYTES + 64
    n_trees, depth = 6, 3

    wide = DTSettings(n_trees=n_trees, depth=depth, impurity="entropy",
                      loss="squared", seed=2)
    res_w = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, wide, cache_budget=budget)
    # auto super-batch >= n_trees here: the whole forest is ONE batch —
    # depth+2 sweeps total, the first (level 0) riding the warm pass
    assert res_w.tail_sweeps == depth + 1
    sb = n_trees
    assert res_w.tail_sweeps / n_trees <= math.ceil(depth / sb) + 1

    narrow = DTSettings(n_trees=n_trees, depth=depth, impurity="entropy",
                        loss="squared", seed=2, tail_tree_batch=2)
    res_n = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, narrow, cache_budget=budget)
    assert res_n.tail_sweeps == (depth + 1) + 2 * (depth + 2)
    _forests_bitwise_equal(res_w, res_n)

    # env beats auto: SHIFU_TAIL_TREE_BATCH
    monkeypatch.setenv("SHIFU_TAIL_TREE_BATCH", "3")
    res_e = train_rf_streamed(
        ShardStream(shards, ("bins", "y", "w"), window_rows=256),
        8, None, wide, cache_budget=budget)
    assert res_e.tail_sweeps == (depth + 1) + (depth + 2)
    _forests_bitwise_equal(res_w, res_e)
