"""Training-math tests without a cluster — reference pattern
``core/dtrain/DTrainTest.java:44`` (assert error decreases per propagation
algorithm), upgraded: every run exercises the real SPMD path on the virtual
8-device mesh (SURVEY.md §4 rebuild implication)."""

import os

import numpy as np
import pytest

import jax

from shifu_tpu.models import nn as nn_model
from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble
from shifu_tpu.train.sampling import member_masks
from shifu_tpu.train import grid_search


def make_xor(n=512, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-1, 1, size=(n, 2)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.float32)
    return x, y


def two_class(n=2000, d=8, seed=1):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d) / np.sqrt(d)
    y = (1 / (1 + np.exp(-(x @ w) * 3)) > rng.random(n)).astype(np.float32)
    return x, y


SPEC = nn_model.NNModelSpec(input_dim=2, hidden_nodes=[8], activations=["tanh"])


@pytest.mark.parametrize("prop", ["B", "Q", "R", "M"])
def test_propagation_algorithms_reduce_error(prop):
    """DTrainTest parity: each of B/Q/R/M drives training error down."""
    x, y = make_xor()
    tw = np.ones((1, len(y)), np.float32)
    vw = np.ones((1, len(y)), np.float32)
    lr = {"B": 0.5, "Q": 0.1, "R": 0.1, "M": 0.01}[prop]
    res = train_ensemble(x, y, tw, vw, SPEC,
                         TrainSettings(optimizer=prop, learning_rate=lr,
                                       epochs=60, seed=3))
    first = res.history[0][0]
    assert res.train_errors[0] < first * 0.9, (prop, first, res.train_errors)


@pytest.mark.parametrize("rule", ["ADAM", "MOMENTUM", "RMSPROP", "ADAGRAD",
                                  "NESTEROV"])
def test_update_rules_reduce_error(rule):
    x, y = make_xor()
    tw = np.ones((1, len(y)), np.float32)
    vw = np.ones((1, len(y)), np.float32)
    lr = {"ADAM": 0.05, "MOMENTUM": 0.5, "NESTEROV": 0.5, "RMSPROP": 0.05,
          "ADAGRAD": 0.5}[rule]
    res = train_ensemble(x, y, tw, vw, SPEC,
                         TrainSettings(optimizer=rule, learning_rate=lr,
                                       epochs=60, seed=3))
    assert res.train_errors[0] < res.history[0][0] * 0.9


def test_bagged_ensemble_on_mesh():
    """4 bagging members train in one vmapped program across the 8-device
    mesh (the reference's 4 parallel YARN jobs)."""
    x, y = two_class()
    n = len(y)
    tw, vw = member_masks(n, 4, valid_rate=0.2, sample_rate=0.8,
                          replacement=True, targets=y, seed=0)
    spec = nn_model.NNModelSpec(input_dim=x.shape[1], hidden_nodes=[16],
                                activations=["relu"], loss="log")
    res = train_ensemble(x, y, tw, vw, spec,
                         TrainSettings(optimizer="ADAM", learning_rate=0.02,
                                       epochs=30, seed=1))
    assert len(res.params) == 4
    assert np.all(res.valid_errors < 0.69)  # all beat chance log-loss
    # members saw different bags → different weights
    w0 = res.params[0][0]["w"]
    w1 = res.params[1][0]["w"]
    assert not np.allclose(w0, w1)


def test_lr_degenerate_net_learns():
    x, y = two_class()
    spec = nn_model.NNModelSpec(input_dim=x.shape[1], hidden_nodes=[],
                                activations=[], loss="log")
    tw = np.ones((1, len(y)), np.float32)
    res = train_ensemble(x, y, tw, tw, spec,
                         TrainSettings(optimizer="ADAM", learning_rate=0.1,
                                       epochs=40))
    assert res.train_errors[0] < 0.55


def test_svm_hinge_learns_margin():
    """Hinge loss on the linear head learns a separating margin (VERDICT
    r3 item 9: a real SVM, not a silent SVM->LR alias)."""
    from shifu_tpu.pipeline.train import svm_spec

    x, y = two_class()
    spec = svm_spec(x.shape[1], {"Const": 2.0}, list(range(x.shape[1])), [])
    assert spec.loss == "hinge" and spec.output_activation == "linear"
    assert spec.extra["algorithm"] == "SVM"
    tw = np.ones((1, len(y)), np.float32)
    res = train_ensemble(x, y, tw, tw, spec,
                         TrainSettings(optimizer="ADAM", learning_rate=0.1,
                                       epochs=60, l2=0.25))
    import jax.numpy as jnp
    from shifu_tpu.models.nn import forward
    margin = np.asarray(forward(res.params[0], spec,
                                jnp.asarray(x)))[:, 0]
    acc = ((margin > 0) == (y > 0.5)).mean()
    assert acc > 0.75        # labels are sigmoid-noisy; Bayes acc ~0.82
    assert res.train_errors[0] < 0.7          # mean hinge well under 1


def test_svm_nonlinear_kernel_rejected_in_streamed_mode():
    """Nonlinear kernels train via the kernel-matrix dual solver in-RAM
    (tests/test_svm_kernel.py); the STREAMED path cannot materialize the
    kernel matrix and must reject with a coded error."""
    import pytest
    from shifu_tpu.config.errors import ShifuError
    from shifu_tpu.pipeline.train import svm_spec

    with pytest.raises(ShifuError, match="streamed"):
        svm_spec(4, {"Kernel": "RBF"}, [0, 1, 2, 3], [])


def test_svm_pipeline_saves_svm_models(model_set):
    """SVM trains through the pipeline and lands as model0.svm (its own
    extension, not an LR alias)."""
    import os

    from shifu_tpu.config import ModelConfig
    from shifu_tpu.models import load_any
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = "SVM"
    mc.train.params = {"Kernel": "linear", "Const": 1.0,
                       "Propagation": "ADAM", "LearningRate": 0.05}
    mc.save(mc_path)
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0
    path = os.path.join(model_set, "models", "model0.svm")
    assert os.path.isfile(path)
    m = load_any(path)
    assert m.spec.loss == "hinge"


def test_early_stop_window_halts():
    x, y = make_xor(128)
    tw = np.ones((1, len(y)), np.float32)
    res = train_ensemble(x, y, tw, tw, SPEC,
                         TrainSettings(optimizer="M", learning_rate=0.0,
                                       epochs=500, early_stop_window=5))
    assert res.epochs_run <= 10


def test_kfold_masks_partition():
    tw, vw = member_masks(100, 5, valid_rate=0.2, kfold=5)
    assert tw.shape == (5, 100)
    assert np.array_equal(vw.sum(axis=0), np.ones(100))
    assert np.array_equal(tw + vw, np.ones((5, 100)))


def test_model_save_load_roundtrip(tmp_path):
    x, y = make_xor(64)
    params = nn_model.init_params(jax.random.PRNGKey(0), SPEC)
    path = os.path.join(tmp_path, "model0.nn")
    nn_model.save_model(path, SPEC, params)
    m = nn_model.IndependentNNModel.load(path)
    got = m.compute(x)
    want = np.asarray(nn_model.forward(params, SPEC, x))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_grid_search_expand():
    params = {"LearningRate": [0.1, 0.01], "Propagation": ["R", "B"],
              "NumHiddenNodes": [30], "FixedConst": 7}
    trials = grid_search.expand(params)
    assert len(trials) == 4
    assert all(t["NumHiddenNodes"] == [30] and t["FixedConst"] == 7
               for t in trials)
    # shape-changing axis: list of lists
    params2 = {"NumHiddenNodes": [[10], [20, 20]]}
    assert len(grid_search.expand(params2)) == 2


def test_minibatch_mode():
    x, y = two_class(1024)
    spec = nn_model.NNModelSpec(input_dim=x.shape[1], hidden_nodes=[8],
                                activations=["tanh"], loss="log")
    tw = np.ones((1, len(y)), np.float32)
    res = train_ensemble(x, y, tw, tw, spec,
                         TrainSettings(optimizer="ADAM", learning_rate=0.05,
                                       epochs=10, batch_size=256))
    assert res.train_errors[0] < res.history[0][0]


def test_structure_fit_in_grows_net():
    """Continuous-training structure fit-in: old weights embed in the
    top-left block of the grown layer; predictions from the embedded part
    survive (reference NNMaster.java:331-362,605-645)."""
    import jax
    from shifu_tpu.models import nn as nn_model
    small = nn_model.NNModelSpec(input_dim=4, hidden_nodes=[5],
                                 activations=["tanh"])
    big = nn_model.NNModelSpec(input_dim=4, hidden_nodes=[9],
                               activations=["tanh"])
    sp = nn_model.init_params(jax.random.PRNGKey(0), small)
    grown = nn_model.fit_params_into(small, sp, big, jax.random.PRNGKey(1))
    assert grown is not None
    np.testing.assert_array_equal(np.asarray(grown[0]["w"])[:, :5],
                                  np.asarray(sp[0]["w"]))
    np.testing.assert_array_equal(np.asarray(grown[1]["w"])[:5, :],
                                  np.asarray(sp[1]["w"]))
    # shrinking must refuse
    assert nn_model.fit_params_into(big, grown, small,
                                    jax.random.PRNGKey(2)) is None
    # deeper target: old hidden layers copy, output layer fresh-positioned
    deep = nn_model.NNModelSpec(input_dim=4, hidden_nodes=[5, 6],
                                activations=["tanh", "tanh"])
    grown2 = nn_model.fit_params_into(small, sp, deep, jax.random.PRNGKey(3))
    assert grown2 is not None
    np.testing.assert_array_equal(np.asarray(grown2[0]["w"]),
                                  np.asarray(sp[0]["w"]))


def test_fixed_layers_freeze_weights():
    """FixedLayers: the frozen layer's weights must not move during
    training; unfrozen layers must."""
    import jax
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble
    from shifu_tpu.train.sampling import member_masks

    rng = np.random.default_rng(0)
    n, d = 256, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    spec = nn_model.NNModelSpec(input_dim=d, hidden_nodes=[6],
                                activations=["tanh"], loss="log")
    p0 = nn_model.init_params(jax.random.PRNGKey(0), spec)
    tw, vw = member_masks(n, 1, valid_rate=0.2, sample_rate=1.0,
                          replacement=False, targets=y, seed=0)
    res = train_ensemble(x, y, tw, vw, spec,
                         TrainSettings(optimizer="ADAM", learning_rate=0.05,
                                       epochs=5, seed=0,
                                       fixed_layers=(1,)),
                         init_params_list=[p0])
    trained = res.params[0]
    np.testing.assert_array_equal(np.asarray(trained[0]["w"]),
                                  np.asarray(p0[0]["w"]))   # frozen
    assert not np.allclose(np.asarray(trained[0]["b"]),
                           np.asarray(p0[0]["b"]))          # bias free
    assert not np.allclose(np.asarray(trained[1]["w"]),
                           np.asarray(p0[1]["w"]))          # layer 2 moves


def test_pipeline_continuous_growth(model_set):
    """isContinuous + larger NumHiddenNodes: train must warm-start via
    fit-in (no 'fresh init' fallback) and still converge."""
    from shifu_tpu.config import ModelConfig
    from tests.test_pipeline_train import run_steps
    run_steps(model_set, upto_train_params={
        "NumHiddenNodes": [6], "ActivationFunc": ["tanh"],
        "Propagation": "ADAM", "LearningRate": 0.05})
    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.isContinuous = True
    mc.train.numTrainEpochs = 10
    mc.train.params = {"NumHiddenNodes": [12], "ActivationFunc": ["tanh"],
                       "Propagation": "ADAM", "LearningRate": 0.05}
    mc.save(mcp)
    from shifu_tpu.pipeline.train import TrainProcessor
    assert TrainProcessor(model_set, params={}).run() == 0
    from shifu_tpu.models import nn as nn_model
    spec, _ = nn_model.load_model(
        os.path.join(model_set, "models", "model0.nn"))
    assert spec.hidden_nodes == [12]


def test_precision_param_accepted(model_set):
    """Precision=bfloat16 trains through the pipeline (MXU-rate matmuls)."""
    from tests.test_pipeline_train import run_steps
    run_steps(model_set, upto_train_params={
        "NumHiddenNodes": [6], "ActivationFunc": ["tanh"],
        "Propagation": "ADAM", "LearningRate": 0.05,
        "Precision": "bfloat16"})
    assert os.path.isfile(os.path.join(model_set, "models", "model0.nn"))
