"""Observability plane v2 suite: thread-safe registry, heartbeats +
``monitor`` (incl. the SIGSTOP-staleness integration test), timeline
export (Chrome trace_event schema, ingest track), OpenMetrics/JSON
snapshots, the streaming drift monitor (incremental == batch PSI), the
``obs:heartbeat`` fault site, ``bench.py --compare`` regression
tracking, graceful ``analysis --telemetry`` on missing/torn traces, and
the metric-name manifest lint."""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from shifu_tpu import obs
from shifu_tpu.obs import drift as drift_mod
from shifu_tpu.obs import exporter as exporter_mod
from shifu_tpu.obs import health as health_mod
from shifu_tpu.obs import monitor as monitor_mod
from shifu_tpu.obs import timeline as timeline_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.obs        # `pytest -m obs` collects this suite


@pytest.fixture
def telemetry():
    obs.reset_for_tests()
    obs.set_enabled(True)
    yield obs
    obs.reset_for_tests()


def _subprocess_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_ENABLE_X64"] = "true"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/shifu_tpu_jax_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SHIFU_TPU_FAULTS", None)
    env.update(extra)
    return env


# ------------------------------------------------- registry thread-safety
def test_registry_concurrent_increments_exact(telemetry):
    """ingest.* counters increment from the prepared() prep thread while
    trainers update train.* on the main thread and the heartbeat thread
    snapshots — concurrent inc() must lose NO updates (a bare += is a
    non-atomic read-modify-write under the GIL)."""
    c = obs.counter("ingest.windows_emitted")
    h = obs.histogram("train.epoch_s")
    g = obs.gauge("train.valid_err")
    N, T = 20_000, 8
    stop = threading.Event()

    def snapshotter():
        while not stop.is_set():
            obs.snapshot(reset=False)        # heartbeat/exporter reader

    def worker(k):
        for i in range(N):
            c.inc()
            h.observe(float(i))
            g.set_max(float(k * N + i))

    reader = threading.Thread(target=snapshotter, daemon=True)
    reader.start()
    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    reader.join(timeout=5)
    snap = {m["name"]: m for m in obs.snapshot()}
    assert snap["ingest.windows_emitted"]["value"] == N * T
    assert snap["train.epoch_s"]["count"] == N * T
    assert snap["train.valid_err"]["value"] == T * N - 1


# ------------------------------------------------------------- heartbeats
def test_heartbeat_file_contents_and_progress(telemetry, tmp_path):
    hd = str(tmp_path / "health")
    hb = obs.start_heartbeat(hd, step="TRAIN", interval_s=0.1)
    assert hb is not None
    try:
        with obs.span("TRAIN", kind="step"):
            with obs.span("process", kind="phase"):
                obs.counter("ingest.rows_emitted").inc(1234)
                obs.counter("ingest.windows_emitted").inc(3)
                obs.counter("train.trees").inc(7)
                time.sleep(0.35)             # a few beats land
    finally:
        hb.stop(exit_code=0)
    (rec,) = obs.read_health(hd)
    assert rec["kind"] == "health"
    assert rec["schema_version"] == obs.SCHEMA_VERSION
    assert rec["step"] == "TRAIN" and rec["pid"] == os.getpid()
    assert rec["state"] == "exited" and rec["exit_code"] == 0
    assert rec["rows"] == 1234
    assert rec["windows"] == 3 and rec["trees"] == 7
    assert rec["beat"] >= 2                  # the thread really beat
    assert rec["interval_s"] == pytest.approx(0.1)
    # progress timestamps moved when counters moved
    assert rec["last_progress_ts"] >= rec["started_ts"]
    # mid-run beats captured the live phase (deepest main-thread span)
    mid = hb._record("running", None)
    assert mid["phase"] is None              # spans closed by now
    assert obs.classify(rec) == "exited"


def test_heartbeat_phase_tracks_live_spans(telemetry, tmp_path):
    hb = health_mod.HeartbeatWriter(str(tmp_path), step="STATS",
                                    interval_s=5.0)
    hb._started_ts = time.time()
    with obs.span("STATS", kind="step"):
        with obs.span("fused_sweep", kind="phase"):
            rec = hb._record("running", None)
    assert rec["phase"] == "fused_sweep"     # deepest main-thread span
    assert rec["spans"]["MainThread"] == "fused_sweep"


def test_classify_staleness_model():
    now = 1000.0
    base = {"state": "running", "interval_s": 0.5, "ts": now - 0.2,
            "last_progress_ts": now - 1.0}
    assert health_mod.classify(dict(base), now=now) == "live"
    # SIGSTOP'd: no heartbeat for > STALE_FACTOR x interval -> stale
    assert health_mod.classify(dict(base, ts=now - 1.5), now=now) == "stale"
    # alive but no progress-counter movement -> stalled (straggler flag)
    assert health_mod.classify(
        dict(base, last_progress_ts=now - 500), now=now) == "stalled"
    assert health_mod.classify(
        dict(base, state="exited"), now=now) == "exited"
    # the acceptance bound: staleness flips WITHIN 2 heartbeat intervals
    assert health_mod.STALE_FACTOR == 2.0


def test_monitor_renders_and_flags(telemetry, tmp_path):
    mdir = str(tmp_path)
    hd = health_mod.health_dir_for(mdir)
    os.makedirs(hd)
    now = time.time()
    with open(os.path.join(hd, "train-1.json"), "w") as f:
        json.dump({"proc": "train-1", "step": "TRAIN", "state": "running",
                   "ts": now, "last_progress_ts": now, "interval_s": 0.5,
                   "rows": 4096, "windows": 8, "trees": 12,
                   "phase": "process",
                   "spans": {"MainThread": "process",
                             "shifu-ingest": "ingest.window_prep"}}, f)
    with open(os.path.join(hd, "train-2.json"), "w") as f:
        json.dump({"proc": "train-2", "step": "TRAIN", "state": "running",
                   "ts": now - 60, "last_progress_ts": now - 60,
                   "interval_s": 0.5, "rows": 10}, f)
    text = monitor_mod.render_status(mdir, now=now)
    assert "train-1" in text and "live" in text
    assert "4,096" in text and "process" in text
    assert "ingest.window_prep" in text      # the ingest thread's span
    assert "STALE" in text                   # train-2 stopped beating
    assert "quorum 1/2" in text
    # empty dir: a message, not a traceback
    assert "no health records" in monitor_mod.render_status(
        str(tmp_path / "other"))


def test_monitor_cli_once_exit_zero(tmp_path):
    p = subprocess.run(
        [sys.executable, "-m", "shifu_tpu.cli", "--dir", str(tmp_path),
         "monitor", "--once"],
        capture_output=True, text=True, env=_subprocess_env(), cwd=REPO,
        timeout=120)
    assert p.returncode == 0, p.stderr
    assert "no health records" in p.stdout


def test_monitor_flags_sigstopped_train_subprocess(prepared_set):
    """ACCEPTANCE: `shifu_tpu monitor` shows live per-process step/phase/
    rows during a streamed GBT train, and flags a SIGSTOP'd process as
    stale within 2 heartbeat intervals."""
    from shifu_tpu.config import ModelConfig
    mc_path = os.path.join(prepared_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = "GBT"
    # big forest = the train outlives every assertion below; the parent
    # kills the subprocess once staleness is proven
    mc.train.params = {"TreeNum": 5000, "MaxDepth": 4}
    mc.save(mc_path)
    interval = 0.25
    env = _subprocess_env(SHIFU_TPU_TELEMETRY="1",
                          SHIFU_TPU_HEARTBEAT_S=str(interval))
    p = subprocess.Popen(
        [sys.executable, "-m", "shifu_tpu.cli", "--dir", prepared_set,
         "-Dshifu.train.streaming=on", "train"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, cwd=REPO)
    try:
        hd = os.path.join(prepared_set, "telemetry", "health")
        deadline = time.time() + 180        # covers a cold XLA compile

        def wait_for(pred, what):
            while time.time() < deadline:
                recs = obs.read_health(hd)
                if recs and pred(recs[0]):
                    return recs[0]
                assert p.poll() is None, \
                    (f"train exited rc={p.poll()} before {what}\n"
                     + p.stderr.read().decode(errors="replace"))
                time.sleep(0.05)
            raise AssertionError(f"timed out waiting for {what}")

        wait_for(lambda r: r.get("state") == "running", "first heartbeat")
        rec = wait_for(lambda r: (r.get("rows") or 0) > 0
                       and r.get("phase"), "streamed rows + phase")
        assert rec["step"] == "TRAIN"
        text = monitor_mod.render_status(prepared_set)
        assert "TRAIN" in text and "live" in text

        os.kill(p.pid, signal.SIGSTOP)
        time.sleep(health_mod.STALE_FACTOR * interval + 2 * interval)
        (rec,) = obs.read_health(hd)
        assert obs.classify(rec) == "stale"
        text = monitor_mod.render_status(prepared_set)
        assert "stale" in text and "STALE" in text
    finally:
        try:
            os.kill(p.pid, signal.SIGCONT)
        except OSError:
            pass
        p.kill()
        p.communicate(timeout=60)


# -------------------------------------------------- obs:heartbeat faults
def test_heartbeat_kill_leaves_no_torn_health_file(tmp_path):
    """Fault-site interaction: heartbeat writes ride ioutil's atomic
    path, so a hard death mid-heartbeat (obs:heartbeat=<b>:kill) leaves
    the PREVIOUS valid health file — never a torn one — and the next
    writer recovers in place."""
    hd = str(tmp_path / "health")
    script = (
        "import time\n"
        "from shifu_tpu import obs\n"
        "obs.set_enabled(True)\n"
        "obs.counter('train.trees').inc(3)\n"
        f"hb = obs.start_heartbeat({hd!r}, step='TRAIN', proc='train-x',\n"
        "                          interval_s=0.05)\n"
        "time.sleep(5)\n")
    p = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        env=_subprocess_env(SHIFU_TPU_FAULTS="obs:heartbeat=1:kill"),
        cwd=REPO, timeout=120)
    assert p.returncode == 137, p.stderr     # died ON beat 1's commit
    path = os.path.join(hd, "train-x.json")
    with open(path) as f:
        rec = json.load(f)                   # beat 0 intact, NOT torn
    assert rec["beat"] == 0 and rec["state"] == "running"
    assert rec["trees"] == 3
    # recovery: a fresh writer (same proc name) owns the file again
    p2 = subprocess.run(
        [sys.executable, "-c", script.replace("time.sleep(5)",
                                              "time.sleep(0.12)\n"
                                              "hb.stop(exit_code=0)")],
        capture_output=True, text=True, env=_subprocess_env(), cwd=REPO,
        timeout=120)
    assert p2.returncode == 0, p2.stderr
    with open(path) as f:
        rec = json.load(f)
    assert rec["state"] == "exited" and rec["beat"] >= 1
    # the orphan tmp the killed write may have left was swept on start
    assert [f for f in os.listdir(hd) if ".tmp" in f] == []


# --------------------------------------------------------------- timeline
def _make_stream_trace(td, telemetry):
    """A real telemetry trace containing main-thread AND ingest-thread
    spans: one prepared() sweep over tiny materialized shards."""
    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream
    rng = np.random.default_rng(0)
    sd = os.path.join(td, "shards")
    os.makedirs(sd)
    for k in range(3):
        np.savez(os.path.join(sd, f"part-{k:05d}.npz"),
                 bins=rng.integers(0, 16, (512, 4)).astype(np.int16),
                 y=np.zeros(512, np.float32), w=np.ones(512, np.float32))
    with open(os.path.join(sd, "schema.json"), "w") as f:
        json.dump({"columnNums": list(range(4)), "numShards": 3,
                   "numRows": 1536}, f)
    stream = ShardStream(Shards.open(sd), ("bins", "y", "w"), 512,
                         spill=False)
    with obs.span("TRAIN", kind="step"):
        with obs.span("process", kind="phase"):
            for _ in stream.prepared(lambda w: w, depth=2):
                pass
    trace = os.path.join(td, "telemetry", "trace.jsonl")
    obs.flush(trace, step="TRAIN")
    return trace


def test_timeline_chrome_trace_event_schema(telemetry, tmp_path):
    """ACCEPTANCE: --timeline output is valid Chrome trace_event JSON
    with ingest-prep spans on a separate track from device compute."""
    _make_stream_trace(str(tmp_path), telemetry)
    out = timeline_mod.export_timeline(str(tmp_path),
                                       str(tmp_path / "tl.json"))
    with open(out) as f:
        doc = json.load(f)
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "i", "M")
        assert isinstance(ev["name"], str)
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], int) and ev["dur"] >= 1
        elif ev["ph"] == "i":
            assert ev["s"] in ("t", "p", "g")
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    ingest_tids = {e["tid"] for e in spans
                   if e["name"].startswith("ingest.window_prep")}
    compute_tids = {e["tid"] for e in spans if e["name"] == "TRAIN"}
    assert ingest_tids and compute_tids
    assert ingest_tids.isdisjoint(compute_tids)
    # both tracks carry a thread_name metadata label
    labels = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
              if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "ingest" in labels[next(iter(ingest_tids))]
    # span wall-clock survives the unit conversion (us)
    train = next(e for e in spans if e["name"] == "TRAIN")
    assert train["dur"] < 60_000_000        # sane: < 60 s


def test_timeline_pre_v5_trace_routes_by_name(tmp_path):
    """Traces written before schema v5 carry no tid — ingest.* spans
    still route to the ingest track by name."""
    blocks = [{"meta": {"step": "TRAIN", "pid": 7, "ts": 1.0},
               "spans": [
                   {"kind": "span", "name": "TRAIN", "id": 1,
                    "parent": None, "ts": 1.0, "dur_s": 2.0, "attrs": {}},
                   {"kind": "span", "name": "ingest.window_prep", "id": 2,
                    "parent": None, "ts": 1.1, "dur_s": 0.5, "attrs": {}}],
               "events": [], "metrics": []}]
    doc = timeline_mod.to_trace_events(blocks)
    by_name = {e["name"]: e for e in doc["traceEvents"]
               if e["ph"] == "X"}
    assert by_name["TRAIN"]["tid"] == timeline_mod.TID_MAIN
    assert by_name["ingest.window_prep"]["tid"] == timeline_mod.TID_INGEST


def test_timeline_cli(telemetry, tmp_path, capsys):
    from shifu_tpu.cli import main
    _make_stream_trace(str(tmp_path), telemetry)
    out = str(tmp_path / "timeline.json")
    assert main(["--dir", str(tmp_path), "analysis", "--telemetry",
                 "--timeline", out]) == 0
    assert "timeline ->" in capsys.readouterr().out
    with open(out) as f:
        assert json.load(f)["traceEvents"]
    # no trace: hint + exit 0, no file
    assert main(["--dir", str(tmp_path / "none"), "analysis",
                 "--telemetry", "--timeline",
                 str(tmp_path / "no.json")]) == 0
    assert "no telemetry recorded" in capsys.readouterr().out
    assert not os.path.exists(str(tmp_path / "no.json"))


# ----------------------------------------------------- metrics snapshots
def test_openmetrics_rendering(telemetry):
    obs.counter("ingest.bytes_read").inc(4096)
    obs.gauge("drift.psi_max").set(0.125)
    obs.histogram("train.epoch_s").observe(0.5)
    obs.histogram("train.epoch_s").observe(1.5)
    text = exporter_mod.render_openmetrics()
    assert text.endswith("# EOF\n")
    # schema-versioned naming: the handshake gauge + sanitized names
    assert (f"shifu_tpu_telemetry_schema_version {obs.SCHEMA_VERSION}"
            in text)
    assert "# TYPE shifu_tpu_ingest_bytes_read counter" in text
    assert "shifu_tpu_ingest_bytes_read_total 4096" in text
    assert "shifu_tpu_drift_psi_max 0.125" in text
    assert "# TYPE shifu_tpu_train_epoch_s summary" in text
    assert "shifu_tpu_train_epoch_s_count 2" in text
    assert "shifu_tpu_train_epoch_s_sum 2" in text
    assert "shifu_tpu_train_epoch_s_max 1.5" in text
    # the OpenMetrics charset holds for every exposed name (quantile
    # sample lines carry a {quantile="..."} label set, v8)
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        name = line.split(" ")[0].split("{")[0]
        assert re.fullmatch(r"[a-zA-Z_][a-zA-Z0-9_]*", name), name


def test_openmetrics_histogram_quantile_lines(telemetry):
    """Satellite: histogram summaries expose p50/p99 quantile sample
    lines (the registry's log-sketch estimates), not just count/sum."""
    h = obs.histogram("serve.batch_latency_ms")
    for _ in range(99):
        h.observe(2.0)
    h.observe(80.0)
    text = exporter_mod.render_openmetrics()
    q = {}
    for line in text.splitlines():
        if line.startswith("shifu_tpu_serve_batch_latency_ms{quantile="):
            key = line.split('quantile="')[1].split('"')[0]
            q[key] = float(line.split("} ")[1])
    assert set(q) == {"0.5", "0.99"}
    # sketch resolution is ~6.6%/bin: loose relative bounds
    assert q["0.5"] == pytest.approx(2.0, rel=0.15)
    assert q["0.99"] == pytest.approx(2.0, rel=0.15)
    h.observe(80.0)                          # now >1% of mass is at 80
    for _ in range(8):
        h.observe(80.0)
    text = exporter_mod.render_openmetrics()
    line = next(l for l in text.splitlines()
                if l.startswith("shifu_tpu_serve_batch_latency_ms"
                                '{quantile="0.99"}'))
    assert float(line.split("} ")[1]) == pytest.approx(80.0, rel=0.15)
    # pre-v8 snapshot records (no p50/p99 keys) still render summaries
    legacy = [{"kind": "metric", "type": "histogram", "name": "old.h",
               "count": 2, "sum": 3.0, "min": 1.0, "max": 2.0,
               "last": 2.0}]
    text = exporter_mod.render_openmetrics(legacy)
    assert "shifu_tpu_old_h_count 2" in text
    assert 'shifu_tpu_old_h{quantile' not in text


def test_exporter_periodic_and_final_write(telemetry, tmp_path):
    td = str(tmp_path / "telemetry")
    obs.counter("train.trees").inc(5)
    exp = obs.start_exporter(td, step="TRAIN", interval_s=0.05)
    assert exp is not None
    time.sleep(0.2)
    obs.counter("train.trees").inc(5)
    exp.stop()                               # final closing dump
    with open(os.path.join(td, "metrics.json")) as f:
        doc = json.load(f)
    assert doc["schema_version"] == obs.SCHEMA_VERSION
    assert doc["step"] == "TRAIN"
    metrics = {m["name"]: m for m in doc["metrics"]}
    assert metrics["train.trees"]["value"] == 10   # stop() re-dumped
    prom = open(os.path.join(td, "metrics.prom")).read()
    assert "shifu_tpu_train_trees_total 10" in prom
    assert [f for f in os.listdir(td) if ".tmp" in f] == []


# ---------------------------------------------------------- drift monitor
def _drift_columns(rng, n_cols=6, n_bins=8, n_train=4000):
    """ColumnConfigs with boundaries + training-time per-bin counts, and
    the training rows they summarize."""
    from shifu_tpu.config.column_config import ColumnConfig
    cols, train_bins = [], []
    for j in range(n_cols):
        # num_bins() == len(binBoundary): n_bins value bins + missing
        bnd = sorted(rng.normal(size=n_bins).tolist())
        tb = rng.integers(0, n_bins + 1, size=n_train)   # incl. missing
        counts = np.bincount(tb, minlength=n_bins + 1)
        pos = rng.binomial(counts, 0.3)
        cc = ColumnConfig(columnNum=j, columnName=f"c{j}")
        cc.columnBinning.binBoundary = bnd
        cc.columnBinning.binCountNeg = (counts - pos).tolist()
        cc.columnBinning.binCountPos = pos.tolist()
        cols.append(cc)
        train_bins.append(tb)
    return cols, np.stack(train_bins, axis=1)


def test_drift_incremental_matches_batch_psi(telemetry, rng):
    """ACCEPTANCE: the streaming monitor reproduces the batch PSI of the
    stats ``-psi`` formula (ops.stats_math.psi) on the same windows,
    within f32 tolerance."""
    from shifu_tpu.ops.stats_math import psi
    cols, _ = _drift_columns(rng)
    n_bins = 9                               # 8 value bins + missing
    live = rng.integers(0, n_bins, size=(5000, len(cols)))
    live[:, 0] = np.minimum(live[:, 0], 2)   # force drift on column 0

    mon = drift_mod.DriftMonitor(cols, threshold=0.25)
    for s in range(0, len(live), 700):       # ragged windows
        mon.update(live[s:s + 700])
    inc = mon.column_psi()

    for j, cc in enumerate(cols):
        expected = (np.asarray(cc.columnBinning.binCountNeg, float)
                    + np.asarray(cc.columnBinning.binCountPos, float))
        batch = psi(expected,
                    np.bincount(live[:, j], minlength=n_bins))
        assert inc[j] == pytest.approx(float(batch), abs=1e-6)
    summ = mon.summary()
    assert summ["rows"] == 5000
    assert "c0" in summ["flagged"]           # the forced drift
    assert summ["psi_max"] == pytest.approx(np.nanmax(inc))


def test_drift_update_respects_weights_and_shape(telemetry, rng):
    cols, _ = _drift_columns(rng, n_cols=3)
    mon = drift_mod.DriftMonitor(cols)
    win = rng.integers(0, 9, size=(64, 3))
    w = np.ones(64)
    w[32:] = 0.0                             # padded streamed tail
    mon.update(win, weights=w)
    assert mon.rows == 32
    with pytest.raises(ValueError):
        mon.update(rng.integers(0, 9, size=(8, 5)))


def test_drift_emit_gauges_and_json(telemetry, tmp_path, rng):
    cols, _ = _drift_columns(rng, n_cols=4)
    mon = drift_mod.DriftMonitor(cols)
    mon.update(rng.integers(0, 9, size=(512, 4)))
    path = str(tmp_path / "telemetry" / "drift.json")
    summ = mon.emit(path=path)
    snap = {m["name"]: m for m in obs.snapshot()}
    assert snap["drift.rows"]["value"] == 512
    assert snap["drift.psi_max"]["value"] == pytest.approx(
        summ["psi_max"])
    assert snap["drift.columns_tracked"]["value"] == 4
    with open(path) as f:
        doc = json.load(f)
    assert doc["kind"] == "drift" and len(doc["columns"]) == 4
    # the report renders a drift section from the artifact
    from shifu_tpu.obs.report import _render_drift
    out = []
    _render_drift(str(tmp_path), out)
    text = "\n".join(out)
    assert "drift:" in text and "psi c" in text


def test_drift_monitor_none_without_snapshot(telemetry):
    from shifu_tpu.config.column_config import ColumnConfig
    cc = ColumnConfig(columnNum=0, columnName="bare")   # no bin counts
    assert obs.start_drift_monitor([cc]) is None


def test_norm_rerun_emits_drift_artifact(telemetry, prepared_set):
    """End-to-end wiring: a norm re-run over the SAME data as training
    writes telemetry/drift.json with near-zero PSI (live == snapshot) —
    and the health + metrics surfaces appear beside it."""
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    assert NormalizeProcessor(prepared_set, params={}).run() == 0
    tel = os.path.join(prepared_set, "telemetry")
    with open(os.path.join(tel, "drift.json")) as f:
        doc = json.load(f)
    assert doc["rows"] > 0 and doc["columns"]
    # same distribution as the snapshot: tiny PSI everywhere (norm
    # sampling may drop rows, so allow loose-but-small)
    assert doc["psi_max"] < 0.05
    assert doc["flagged"] == []
    # live plane artifacts from the same run
    recs = obs.read_health(os.path.join(tel, "health"))
    assert recs and recs[0]["step"] == "NORMALIZE"
    assert recs[0]["state"] == "exited" and recs[0]["exit_code"] == 0
    assert recs[0]["rows"] > 0
    prom = open(os.path.join(tel, "metrics.prom")).read()
    assert "shifu_tpu_norm_rows_total" in prom
    assert "shifu_tpu_drift_psi_max" in prom
    # and the telemetry report picks up the drift section
    from shifu_tpu.obs.report import render_telemetry
    assert "drift:" in render_telemetry(prepared_set)


# ------------------------------------- analysis --telemetry robustness
def test_analysis_telemetry_missing_empty_torn(tmp_path, capsys):
    from shifu_tpu.cli import main
    from shifu_tpu.obs.report import render_telemetry

    # missing: hint, exit 0
    assert main(["--dir", str(tmp_path), "analysis", "--telemetry"]) == 0
    assert "no telemetry recorded" in capsys.readouterr().out

    # empty file: hint, exit 0
    tel = tmp_path / "telemetry"
    tel.mkdir()
    trace = tel / "trace.jsonl"
    trace.write_text("")
    assert main(["--dir", str(tmp_path), "analysis", "--telemetry"]) == 0
    assert "no telemetry recorded" in capsys.readouterr().out

    # torn final line (crash mid-write): skipped with a warning, the
    # valid prefix still renders, exit 0
    trace.write_text(
        json.dumps({"kind": "meta", "schema_version": obs.SCHEMA_VERSION,
                    "step": "STATS", "ts": 1.0, "pid": 1}) + "\n"
        + json.dumps({"kind": "span", "name": "pass1", "id": 1,
                      "parent": None, "ts": 1.0, "dur_s": 0.5,
                      "attrs": {"rows": 10}}) + "\n"
        + '{"kind": "metric", "type": "coun')        # torn
    text = render_telemetry(str(tmp_path))
    assert "STATS" in text and "pass1" in text
    assert "torn line(s) skipped" in text
    assert main(["--dir", str(tmp_path), "analysis", "--telemetry"]) == 0
    assert "pass1" in capsys.readouterr().out

    # only torn lines: the hint names the skip count
    trace.write_text('{"kind": "meta", "schema_')
    out = render_telemetry(str(tmp_path))
    assert "no telemetry recorded" in out and "torn line" in out


# ------------------------------------------------------ bench --compare
def test_bench_compare_checked_in_trajectory(capsys):
    """The in-repo BENCH_r0N files are the compare's native input: r04 ->
    r05 must parse, print a table, and agree with a hand computation."""
    from shifu_tpu.bench import (bench_metrics, compare_bench,
                                 load_bench_file, run_compare)
    old = load_bench_file(os.path.join(REPO, "BENCH_r04.json"))
    new = load_bench_file(os.path.join(REPO, "BENCH_r05.json"))
    om, nm = bench_metrics(old), bench_metrics(new)
    assert "nn_train_throughput" in om and om["nn_train_throughput"] > 0
    rows, regressed = compare_bench(old, new, threshold=0.9)
    hand = [n for n in om
            if n in nm and ("throughput" in n or n.endswith("_per_sec"))
            and not n.endswith("_vs_baseline")
            and nm[n] < 0.9 * om[n]]
    assert sorted(regressed) == sorted(hand)
    rc = run_compare(os.path.join(REPO, "BENCH_r04.json"),
                     os.path.join(REPO, "BENCH_r05.json"), threshold=0.9)
    out = capsys.readouterr().out
    assert rc == (2 if hand else 0)
    assert "nn_train_throughput" in out and "ratio" in out


def test_bench_compare_flags_regression(tmp_path, capsys):
    from shifu_tpu.bench import run_compare
    old = {"metric": "nn_train_throughput", "value": 100.0,
           "extra": {"gbt_train_throughput_resident": 50.0,
                     "resume_first_tree_s": 1.0}}
    new = {"metric": "nn_train_throughput", "value": 95.0,
           "extra": {"gbt_train_throughput_resident": 20.0,   # 0.4x: bad
                     "resume_first_tree_s": 99.0}}            # untracked
    po, pn = str(tmp_path / "old.json"), str(tmp_path / "new.json")
    with open(po, "w") as f:
        json.dump(old, f)
    with open(pn, "w") as f:
        json.dump({"n": 9, "parsed": new}, f)   # wrapper shape
    assert run_compare(po, pn, threshold=0.9) == 2
    out = capsys.readouterr().out
    assert "REGRESSED" in out
    assert "gbt_train_throughput_resident" in out
    # headline at 0.95x passes the 0.9 threshold; wall-clock extras
    # never regress the compare
    assert out.count("REGRESSED") == 2       # table row + summary line
    assert run_compare(po, po, threshold=0.9) == 0


def test_bench_compare_cli_exit_codes(tmp_path):
    """The shipped entry point: `python bench.py --compare` (no
    benchmark run, no jax traffic) exits 0/2 per the threshold."""
    env = _subprocess_env()
    r04 = os.path.join(REPO, "BENCH_r04.json")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--compare", r04, r04],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert p.returncode == 0, p.stderr
    assert "no tracked throughput regressions" in p.stdout
    bad = str(tmp_path / "bad.json")
    doc = json.load(open(r04))
    doc = doc.get("parsed", doc)
    doc["value"] = doc["value"] * 0.5
    with open(bad, "w") as f:
        json.dump(doc, f)
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--compare", r04, bad, "--threshold", "0.9"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert p.returncode == 2, p.stdout + p.stderr
    assert "REGRESSED" in p.stdout


# ----------------------------------------------------- manifest lint
# The grep-based metric/span scans that lived here through round 12 are
# now first-class AST rules in shifu_tpu/lint (metric-manifest,
# span-manifest, fault-site).  These thin tests keep the tier-1
# coverage — same contract, one framework — and pin the manifests'
# own well-formedness; rule MECHANICS (seeded violations, suppression,
# baseline) live in tests/test_lint.py.


def _manifest_findings(rule: str):
    from shifu_tpu.lint import run_lint
    findings, engine = run_lint(rules=[rule], full_tree=False)
    assert engine.files_scanned > 60         # the scan really sees the tree
    return findings


def test_every_metric_name_is_declared_in_manifest():
    """Lint: a typo'd metric name would silently mint a NEW metric (the
    registry creates on first use) — every counter/gauge/histogram name
    used anywhere in shifu_tpu/ must be declared in obs.manifest, with
    the declared instrument type; f-string families must start with a
    declared prefix.  Runs the metric-manifest rule through the engine."""
    from shifu_tpu.obs import manifest
    problems = _manifest_findings("metric-manifest")
    assert not problems, "\n".join(f.render() for f in problems)
    # the declared set itself is well-formed
    for name, (kind, help_) in manifest.MANIFEST.items():
        assert kind in ("counter", "gauge", "histogram"), name
        assert help_, name


def test_every_span_name_literal_is_declared_in_manifest():
    """Satellite lint: the timeline tracks / report sections / tests
    join on span-name literals, so a typo'd span name silently vanishes
    from every report — every obs.span("...") / obs.record_span("...")
    literal must resolve against obs.manifest.SPANS (or a declared
    SPAN_PREFIXES family).  Step-root spans named by variable
    (obs.span(self.profile_name, ...)) ride outside the lint."""
    from shifu_tpu.obs import manifest
    problems = _manifest_findings("span-manifest")
    assert not problems, "\n".join(f.render() for f in problems)
    # the declared span set itself is well-formed, and the serve plane's
    # request/batch spans are present
    for name, help_ in manifest.SPANS.items():
        assert help_, name
    assert "serve.request" in manifest.SPANS
    assert "serve.batch" in manifest.SPANS
    assert manifest.is_declared_span("bench.serve")
    assert not manifest.is_declared_span("serve.requst")   # the typo case


def test_every_fault_site_literal_is_declared():
    """Every faults.fire(site, point, ...) literal resolves against the
    faults.SITES manifest (an undeclared site could never be armed from
    the documented spec grammar and would silently never fire)."""
    from shifu_tpu import faults
    problems = _manifest_findings("fault-site")
    assert not problems, "\n".join(f.render() for f in problems)
    for (site, point), help_ in faults.SITES.items():
        assert site and point and help_, (site, point)
    assert faults.is_declared_site("serve", "swap")
    assert not faults.is_declared_site("serve", "swapz")


def test_obs_reexport_audit():
    """obs/__init__ re-export audit: everything in __all__ resolves, and
    the v2-plane API is reachable from the package root."""
    for name in obs.__all__:
        assert getattr(obs, name, None) is not None, name
    for required in ("start_heartbeat", "start_exporter",
                     "start_drift_monitor", "read_health", "classify",
                     "render_openmetrics", "live_spans", "MANIFEST",
                     "is_declared"):
        assert required in obs.__all__, required
