"""Multi-host/DCN story: 2 REAL processes (one per simulated host, 4
virtual CPU devices each) bootstrap via jax.distributed, build one global
(ensemble, data) mesh, feed per-host row blocks, and run a jitted global
reduction whose combine crosses the process boundary — the ICI/DCN split
the reference covers with Guagua ZooKeeper + NCCL/MPI."""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "helpers",
                      "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_mesh_and_global_reduction():
    # (own 150s communicate-timeout below; no pytest-timeout plugin here)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker hung")
        outs.append(out)
    if any("Multiprocess computations aren't implemented" in out
           for out in outs):
        # this jaxlib's CPU client has no cross-process collectives —
        # the two-controller path is exercised on real multi-host rigs
        pytest.skip("CPU backend lacks multiprocess computations "
                    "(jaxlib build without gloo collectives)")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert "MULTIHOST-OK" in out
    # the trainer ran across the process boundary and both controllers
    # converged to the SAME weights (the psum crossed the DCN every step)
    import re
    sums = [re.search(r"MULTIHOST-TRAIN weights=([0-9.]+)", out).group(1)
            for out in outs]
    assert sums[0] == sums[1], sums
    # the stats plane also ran across the boundary with identical results
    # on both controllers (data-axis psum over the DCN)
    st = [re.search(r"MULTIHOST-STATS bnds=([0-9.]+)", out).group(1)
          for out in outs]
    assert st[0] == st[1], st
    # and the STREAMED trainer (ResidentCache + coalesced mega path)
    # built the same forest on both controllers
    tr = [re.search(r"MULTIHOST-STREAMED trees=([0-9.]+)", out).group(1)
          for out in outs]
    assert tr[0] == tr[1], tr
