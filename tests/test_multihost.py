"""Multi-host/DCN story: 2 REAL processes (one per simulated host, 4
virtual CPU devices each) bootstrap via jax.distributed, build one global
(ensemble, data) mesh, feed per-host row blocks, and run a jitted global
reduction whose combine crosses the process boundary — the ICI/DCN split
the reference covers with Guagua ZooKeeper + NCCL/MPI.

The ELASTIC half (kill-one-controller-mid-train) needs NO cross-process
collectives: the quorum-gated combine rides the shared ``telemetry/
steps/`` control plane (parallel/elastic), so those tests run even on
jaxlib builds without gloo — only the jax.distributed bootstrap test
keeps its CPU-collectives skip guard."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(__file__), "helpers",
                      "multihost_worker.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEMO_SHAPE = ["--rows", "1024", "--features", "8", "--epochs", "6"]
SYNC_MODE = ["--quorum-frac", "1.0", "--timeout-ms", "120000"]
QUORUM_MODE = ["--quorum-frac", "0.97", "--timeout-ms", "2000"]
KILL_STEP = 3


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_demo(out: str, proc: int, nproc: int, mode_args,
                 heartbeat_s: float, faults_spec: str = None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["SHIFU_TPU_HEARTBEAT_S"] = str(heartbeat_s)
    env.pop("SHIFU_TPU_FAULTS", None)
    if faults_spec:
        env["SHIFU_TPU_FAULTS"] = faults_spec
    cmd = [sys.executable, "-m", "shifu_tpu.parallel.elastic_demo",
           "--out", out, "--proc", str(proc), "--nproc", str(nproc)] \
        + DEMO_SHAPE + list(mode_args)
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _wait(p, what: str, rc_expect: int = 0) -> str:
    try:
        out, _ = p.communicate(timeout=300)
    except subprocess.TimeoutExpired:
        p.kill()
        pytest.fail(f"{what} hung")
    assert p.returncode == rc_expect, \
        f"{what}: rc={p.returncode} (wanted {rc_expect})\n{out[-3000:]}"
    return out


def _params(out: str, proc: int) -> dict:
    with np.load(os.path.join(out, f"params-{proc}.npz")) as z:
        return {k: z[k] for k in z.files}


def _result(out: str, proc: int) -> dict:
    with open(os.path.join(out, f"result-{proc}.json")) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def elastic_control(tmp_path_factory):
    """The uninterrupted 2-controller sync-mode run every kill drill
    compares against (params bit-for-bit, AUC for the quorum bound)."""
    out = str(tmp_path_factory.mktemp("elastic_control"))
    procs = [_launch_demo(out, p, 2, SYNC_MODE, heartbeat_s=300)
             for p in range(2)]
    for i, p in enumerate(procs):
        _wait(p, f"control controller {i}")
    a, b = _params(out, 0), _params(out, 1)
    assert all(np.array_equal(a[k], b[k]) for k in a), \
        "control controllers diverged"
    return out


def test_two_process_mesh_and_global_reduction():
    # (own 150s communicate-timeout below; no pytest-timeout plugin here)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker hung")
        outs.append(out)
    if any("Multiprocess computations aren't implemented" in out
           for out in outs):
        # this jaxlib's CPU client has no cross-process collectives —
        # the two-controller path is exercised on real multi-host rigs
        pytest.skip("CPU backend lacks multiprocess computations "
                    "(jaxlib build without gloo collectives)")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert "MULTIHOST-OK" in out
    # the trainer ran across the process boundary and both controllers
    # converged to the SAME weights (the psum crossed the DCN every step)
    import re
    sums = [re.search(r"MULTIHOST-TRAIN weights=([0-9.]+)", out).group(1)
            for out in outs]
    assert sums[0] == sums[1], sums
    # the stats plane also ran across the boundary with identical results
    # on both controllers (data-axis psum over the DCN)
    st = [re.search(r"MULTIHOST-STATS bnds=([0-9.]+)", out).group(1)
          for out in outs]
    assert st[0] == st[1], st
    # and the STREAMED trainer (ResidentCache + coalesced mega path)
    # built the same forest on both controllers
    tr = [re.search(r"MULTIHOST-STREAMED trees=([0-9.]+)", out).group(1)
          for out in outs]
    assert tr[0] == tr[1], tr


# ------------------------------------------------- elastic kill drills
def test_kill_one_controller_midtrain_sync_bit_identical(
        tmp_path, elastic_control):
    """ACCEPTANCE: SIGKILL one of 2 controllers at an injected
    ``dcn:step`` boundary mid-train.  In sync mode (quorumFrac 1.0) the
    survivor WAITS the step out, the restarted controller rejoins from
    the close journal WITHOUT a job restart (catch-up replay, no
    re-streaming), and the final model is BIT-identical on both
    controllers to the uninterrupted control run."""
    out = str(tmp_path / "job")
    # huge heartbeat interval: staleness must NOT evict the dead
    # controller before its restart, or the survivor would close the
    # step without it and sync bit-identity is (correctly) gone
    survivor = _launch_demo(out, 0, 2, SYNC_MODE, heartbeat_s=300)
    victim = _launch_demo(out, 1, 2, SYNC_MODE, heartbeat_s=300,
                          faults_spec=f"dcn:step={KILL_STEP}:kill")
    vout = _wait(victim, "victim controller", rc_expect=137)
    assert "injected hard exit at dcn:step" in vout
    # the rejoin: same --proc identity, no fault spec, job still live
    rejoiner = _launch_demo(out, 1, 2, SYNC_MODE, heartbeat_s=300)
    rout = _wait(rejoiner, "rejoined controller")
    _wait(survivor, "surviving controller")
    assert "rejoined=1" in rout
    rj = _result(out, 1)
    assert rj["dcn"]["rejoined"] and rj["dcn"]["incarnation"] == 2
    # the committed prefix (steps 0..KILL_STEP-1) replayed, not recomputed
    assert rj["dcn"]["catchup_steps"] >= KILL_STEP
    ctrl = _params(elastic_control, 0)
    for proc in (0, 1):
        got = _params(out, proc)
        assert all(np.array_equal(ctrl[k], got[k]) for k in ctrl), \
            f"controller {proc} diverged from the uninterrupted control"
    # monitor verdict: both controllers exited cleanly, no permanent
    # straggler in the step-lag table
    from shifu_tpu.obs.monitor import aggregate_records, step_lag_table
    recs, counts = aggregate_records([out])
    assert counts.get("exited", 0) == 2 and not counts.get("stale") \
        and not counts.get("stalled"), counts
    assert len(step_lag_table(recs)) == 2


def test_kill_one_controller_midtrain_quorum_bounded_auc(
        tmp_path, elastic_control):
    """Quorum mode (0.97 + 2 s timeout, fast heartbeats): the survivor
    does NOT wait — the dead controller is masked (staleness eviction
    shrinks the quorum) and the job finishes with its contributions
    dropped; |dAUC| vs the uninterrupted run stays <= 0.01.  The late
    restart still rejoins purely from the journal, landing bit-identical
    to the survivor."""
    out = str(tmp_path / "job")
    survivor = _launch_demo(out, 0, 2, QUORUM_MODE, heartbeat_s=0.25)
    victim = _launch_demo(out, 1, 2, QUORUM_MODE, heartbeat_s=0.25,
                          faults_spec=f"dcn:step={KILL_STEP}:kill")
    _wait(victim, "victim controller", rc_expect=137)
    _wait(survivor, "surviving controller")    # finishes under quorum
    sv = _result(out, 0)
    assert sv["epochs_run"] == 6
    auc_ctrl = _result(elastic_control, 0)["auc"]
    assert abs(sv["auc"] - auc_ctrl) <= 0.01, (sv["auc"], auc_ctrl)
    # late rejoin: the whole job is already closed — pure journal replay
    rejoiner = _launch_demo(out, 1, 2, QUORUM_MODE, heartbeat_s=0.25)
    rout = _wait(rejoiner, "late rejoiner")
    assert "rejoined=1" in rout
    rj = _result(out, 1)
    assert rj["dcn"]["catchup_steps"] >= 6     # every epoch + final eval
    a, b = _params(out, 0), _params(out, 1)
    assert all(np.array_equal(a[k], b[k]) for k in a), \
        "rejoiner's replay diverged from the survivor"
