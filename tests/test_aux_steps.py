"""Aux pipeline steps: export (pmml/columnstats/woe/corr), smoke test,
encode, convert, combo — reference processors from SURVEY.md §2.1/2.7."""

import json
import os
import xml.etree.ElementTree as ET

import numpy as np
import pytest

from shifu_tpu.config import ModelConfig


def _set_train_alg(mdir, alg=None, tree_params=None):
    if not alg:
        return
    from shifu_tpu.config.model_config import Algorithm
    mc_path = os.path.join(mdir, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm[alg]
    if tree_params:
        mc.train.params = tree_params
    mc.save(mc_path)


def _train_prepared(prepared_set, alg=None, tree_params=None):
    """Train on a prepared (post-norm) copy — init/stats/norm already ran
    in the session template; norm materializes both planes so any
    algorithm can train from it."""
    from shifu_tpu.pipeline.train import TrainProcessor
    _set_train_alg(prepared_set, alg, tree_params)
    assert TrainProcessor(prepared_set, params={}).run() == 0


def _run_pipeline(model_set, alg=None, tree_params=None):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    _set_train_alg(model_set, alg, tree_params)
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0


NS = {"p": "http://www.dmg.org/PMML-4_2"}


def test_export_pmml_model_stats_and_concise(prepared_set):
    """Default export carries ModelStats with per-bin Extensions
    (reference ModelStatsCreator); `export -c` trims them
    (ShifuCLI.java:366 IS_CONCISE)."""
    model_set = prepared_set
    from shifu_tpu.pipeline.export import ExportProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    assert TrainProcessor(model_set, params={}).run() == 0
    for concise, want_ext in ((False, True), (True, False)):
        assert ExportProcessor(model_set, params={
            "type": "pmml", "concise": concise}).run() == 0
        f = [x for x in os.listdir(os.path.join(model_set, "export"))
             if x.endswith(".pmml")][0]
        doc = ET.parse(os.path.join(model_set, "export", f))
        body = ET.tostring(doc.getroot(), encoding="unicode")
        assert "ModelStats" in body and "UnivariateStats" in body
        assert ("BinCountPos" in body) == want_ext


def test_init_model_fills_algorithm_defaults(model_set):
    """`shifu init -model` fills the reference's per-algorithm default
    train#params (BasicModelProcessor.java:404-500) and is idempotent."""
    import json

    from shifu_tpu.pipeline.create import check_algorithm_param
    mc_path = os.path.join(model_set, "ModelConfig.json")
    with open(mc_path) as f:
        mc = json.load(f)
    mc["train"]["algorithm"] = "RF"
    mc["train"]["params"] = {}
    with open(mc_path, "w") as f:
        json.dump(mc, f)
    assert check_algorithm_param(model_set) == 0
    with open(mc_path) as f:
        mc = json.load(f)
    assert mc["train"]["params"]["MaxDepth"] == 14
    assert mc["train"]["params"]["Impurity"] == "entropy"
    mc["train"]["params"]["MaxDepth"] = 5        # user edit survives re-run
    with open(mc_path, "w") as f:
        json.dump(mc, f)
    assert check_algorithm_param(model_set) == 0
    with open(mc_path) as f:
        assert json.load(f)["train"]["params"]["MaxDepth"] == 5


def test_export_pmml_nn(prepared_set):
    model_set = prepared_set
    from shifu_tpu.pipeline.export import ExportProcessor
    _train_prepared(model_set)
    assert ExportProcessor(model_set, params={"type": "pmml"}).run() == 0
    pmml_files = [f for f in os.listdir(os.path.join(model_set, "export"))
                  if f.endswith(".pmml")]
    assert pmml_files
    doc = ET.parse(os.path.join(model_set, "export", pmml_files[0]))
    root = doc.getroot()
    assert root.find("p:DataDictionary", NS) is not None
    nn = root.find("p:NeuralNetwork", NS)
    assert nn is not None
    layers = nn.findall("p:NeuralLayer", NS)
    assert len(layers) == 2               # 1 hidden + output
    # every neuron in layer0 has one Con per input
    inputs = nn.find("p:NeuralInputs", NS)
    n_in = int(inputs.get("numberOfInputs"))
    neuron0 = layers[0].find("p:Neuron", NS)
    assert len(neuron0.findall("p:Con", NS)) == n_in


def test_export_pmml_nn_onehot(model_set):
    """One-hot-expanding norms export (VERDICT r3 missing item 6): every
    categorical bin becomes an indicator DerivedField, the net inputs bind
    to the flat expanded feature list, and the indicator tables one-hot
    exactly (row out=1 only for the bin's own category)."""
    from shifu_tpu.config.model_config import NormType
    from shifu_tpu.pipeline.export import ExportProcessor

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.normalize.normType = NormType.ZSCALE_ONEHOT
    # txn_id must be meta here: a onehot norm would expand the id column
    # into ~4000 indicator inputs (real configs flag id-like columns; the
    # unflagged fixture is fine for non-expanding norms)
    meta = os.path.join(model_set, "meta.names")
    with open(meta, "w") as f:
        f.write("txn_id\n")
    mc.dataSet.metaColumnNameFile = meta
    mc.save(mc_path)
    _run_pipeline(model_set)
    assert ExportProcessor(model_set, params={"type": "pmml"}).run() == 0
    pmml_files = [f for f in os.listdir(os.path.join(model_set, "export"))
                  if f.endswith(".pmml")]
    doc = ET.parse(os.path.join(model_set, "export", pmml_files[0]))
    nn = doc.getroot().find("p:NeuralNetwork", NS)
    lt = nn.find("p:LocalTransformations", NS)
    defined = {df.get("name") for df in lt.findall("p:DerivedField", NS)}
    # onehot indicator fields carry 0/1 MapValues defaults
    onehot_fields = {
        df.get("name") for df in lt.findall("p:DerivedField", NS)
        if (df.find("p:MapValues", NS) is not None
            and df.find("p:MapValues", NS).get("defaultValue") in ("0", "1"))}
    assert onehot_fields                     # categorical bins expanded
    inputs = nn.find("p:NeuralInputs", NS)
    refs = [ni.find("p:DerivedField/p:FieldRef", NS).get("field")
            for ni in inputs.findall("p:NeuralInput", NS)]
    assert int(inputs.get("numberOfInputs")) == len(refs) == len(defined)
    assert set(refs) == defined              # every input resolves
    # indicator semantics: in each onehot MapValues exactly one row is 1
    # per bin field (except the missing feature whose rows are all 0)
    for df in lt.findall("p:DerivedField", NS):
        if df.get("name") not in onehot_fields:
            continue
        mv = df.find("p:MapValues", NS)
        outs = [r.find("p:out", NS).text
                for r in mv.findall("p:InlineTable/p:row", NS)]
        if mv.get("defaultValue") == "1":    # the missing-bin indicator
            assert all(o == "0" for o in outs)
        else:
            assert outs.count("1") == 1


def test_pmml_numeric_onehot_discretize_indicators():
    """Plain NormType.ONEHOT expands NUMERIC columns too: each bin becomes
    a Discretize indicator over its interval (not an empty MapValues —
    round-4 review finding)."""
    from shifu_tpu.config import ColumnConfig
    from shifu_tpu.config.model_config import NormType
    from shifu_tpu.export.pmml import _local_transformations

    mc = ModelConfig()
    mc.normalize.normType = NormType.ONEHOT
    cc = ColumnConfig(columnNum=0, columnName="amount")
    cc.columnType = cc.columnType.__class__.N
    cc.columnBinning.binBoundary = [float("-inf"), 1.0, 5.0]
    cc.columnBinning.binCountNeg = [1, 1, 1]
    cc.columnBinning.binCountPos = [1, 1, 1]
    parent = ET.Element("x")
    names = _local_transformations(parent, [cc], mc)
    assert len(names) == 4                   # 3 bins + missing indicator
    dfs = parent.find("LocalTransformations").findall("DerivedField")
    assert len(dfs) == 4
    for j, df in enumerate(dfs):
        disc = df.find("Discretize")
        assert disc is not None              # numeric -> Discretize
        if j < 3:
            assert disc.get("mapMissingTo") == "0"
            b = disc.find("DiscretizeBin")
            assert b is not None and b.get("binValue") == "1"
        else:                                # the missing indicator
            assert disc.get("mapMissingTo") == "1"
            assert disc.find("DiscretizeBin") is None


def test_categorical_accumulator_nan_rows_fold_into_missing():
    """factorize codes NaN as -1; such rows must land in the missing slot,
    not crash bincount (round-4 review finding)."""
    import pandas as pd
    from shifu_tpu.ops.binning import CategoricalAccumulator

    vals = pd.Series(["a", None, "b", float("nan")], dtype=str) \
        .str.strip().to_numpy()
    acc = CategoricalAccumulator()
    acc.update("c", vals, np.array([True, True, True, True]),
               np.array([1.0, 0.0, 1.0, 0.0]), np.ones(4), stripped=True)
    cats, counts, n_distinct, n_missing = acc.finalize("c")
    assert set(cats) == {"a", "b"}
    assert counts[-1][0] + counts[-1][1] == 2   # both NaN rows -> missing


def test_export_pmml_tree(prepared_set):
    model_set = prepared_set
    from shifu_tpu.pipeline.export import ExportProcessor
    _train_prepared(model_set, alg="GBT",
                    tree_params={"TreeNum": 3, "MaxDepth": 3, "Loss": "log"})
    assert ExportProcessor(model_set, params={"type": "pmml"}).run() == 0
    pmml_files = [f for f in os.listdir(os.path.join(model_set, "export"))
                  if f.endswith(".pmml")]
    doc = ET.parse(os.path.join(model_set, "export", pmml_files[0]))
    mm = doc.getroot().find("p:MiningModel", NS)
    assert mm is not None
    segs = mm.find("p:Segmentation", NS)
    assert segs.get("multipleModelMethod") == "sum"
    # 3 tree segments + the GBT init-score constant segment
    assert len(segs.findall("p:Segment", NS)) == 4
    assert segs.find("p:Segment[@id='init']", NS) is not None
    # every bin(col) split field is defined in LocalTransformations
    lt = mm.find("p:LocalTransformations", NS)
    defined = {df.get("name") for df in lt.findall("p:DerivedField", NS)}
    used = {p.get("field") for p in mm.iter(f"{{{NS['p']}}}SimpleSetPredicate")}
    assert used <= defined and used
    # log loss -> logistic link output
    out = mm.find("p:Output", NS)
    assert out is not None and len(out.findall("p:OutputField", NS)) == 2


def test_export_columnstats_and_woe(prepared_set):
    model_set = prepared_set          # init/stats ran in the template
    from shifu_tpu.pipeline.export import ExportProcessor
    assert ExportProcessor(model_set, params={"type": "columnstats"}).run() == 0
    stats_csv = os.path.join(model_set, "export", "columnstats.csv")
    lines = open(stats_csv).read().splitlines()
    assert len(lines) > 5 and lines[0].startswith("columnNum,")
    assert ExportProcessor(model_set, params={"type": "woemapping"}).run() == 0
    woe_csv = os.path.join(model_set, "export", "woemapping.csv")
    assert "MISSING" in open(woe_csv).read()


def test_smoke_test_ok_and_one_sided(model_set, tmp_path):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.smoke import SmokeTestProcessor
    assert InitProcessor(model_set).run() == 0
    assert SmokeTestProcessor(model_set, params={}).run() == 0
    # break the tags -> smoke must fail
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.dataSet.posTags = ["never-matches"]
    mc.save(mc_path)
    assert SmokeTestProcessor(model_set, params={}).run() == 1


def test_encode_leaf_indices(prepared_set):
    model_set = prepared_set
    from shifu_tpu.pipeline.encode import EncodeProcessor
    _train_prepared(model_set, alg="RF",
                    tree_params={"TreeNum": 4, "MaxDepth": 3})
    assert EncodeProcessor(model_set, params={}).run() == 0
    enc = os.path.join(model_set, "tmp", "EncodedData")
    lines = open(enc).read().splitlines()
    assert lines[0] == "target|tree0|tree1|tree2|tree3"
    assert len(lines) == 4001
    # leaf ids are valid node indices for depth-3 trees (< 15)
    vals = np.array([r.split("|")[1:] for r in lines[1:]], dtype=int)
    assert vals.max() < 15


def test_convert_roundtrip(prepared_set):
    model_set = prepared_set
    from shifu_tpu.pipeline.convert import run_convert
    from shifu_tpu.models import load_any
    from shifu_tpu.data.shards import Shards
    _train_prepared(model_set)
    models_dir = os.path.join(model_set, "models")
    orig = load_any(os.path.join(models_dir, "model0.nn"))
    data = Shards.open(os.path.join(model_set, "tmp", "NormalizedData")).load_all()
    want = orig.compute(data["x"][:100])
    assert run_convert(model_set, {"tozipb": True}) == 0
    jpath = os.path.join(models_dir, "model0.nn.json")
    assert os.path.isfile(jpath)
    os.remove(os.path.join(models_dir, "model0.nn"))
    os.rename(jpath, os.path.join(models_dir, "model0.nn.json"))
    assert run_convert(model_set, {"tob": True}) == 0
    got = load_any(os.path.join(models_dir, "model0.nn")).compute(data["x"][:100])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_combo_ensemble(model_set):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.combo import run_combo
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.numTrainEpochs = 10
    mc.train.params = {"TreeNum": 5, "MaxDepth": 3, "NumHiddenNodes": [8],
                       "ActivationFunc": ["tanh"], "Loss": "log",
                       "LearningRate": 0.1}
    mc.save(mc_path)
    assert run_combo(model_set, "new", "LR:GBT") == 0
    assert run_combo(model_set, "run", None) == 0
    assert os.path.isfile(os.path.join(model_set, "combo_0_LR", "models",
                                       "model0.lr"))
    assert os.path.isfile(os.path.join(model_set, "combo_1_GBT", "models",
                                       "model0.gbt"))
    assert run_combo(model_set, "eval", None) == 0
    doc = json.load(open(os.path.join(model_set, "ComboEval.Eval1.json")))
    assert doc["areaUnderRoc"] > 0.7
    assert len(doc["memberAuc"]) == 2


def test_analysis_fi_command(prepared_set):
    model_set = prepared_set
    """`analysis -fi model.gbt` writes a ranked .fi file (reference
    ShifuCLI.analysisModelFi)."""
    from shifu_tpu.cli import main as cli_main

    _train_prepared(model_set, alg="GBT",
                    tree_params={"TreeNum": 5, "MaxDepth": 3,
                                 "Loss": "log"})
    mp = os.path.join(model_set, "models", "model0.gbt")
    assert cli_main(["--dir", model_set, "analysis", "-fi", mp]) == 0
    lines = open(mp + ".fi").read().strip().split("\n")
    assert len(lines) >= 4
    name, v = lines[0].split("\t")
    assert float(v) > 0
    # names come from the model spec's feature list (txn_id is a candidate
    # in this fixture — no meta file — and its unique-id pos-rate leak
    # makes it the top splitter, as conftest documents)
    from shifu_tpu.models import tree as tree_model
    spec, _ = tree_model.load_model(mp)
    assert name in spec.feature_names
    assert len(lines) == len(spec.feature_names)


def test_error_codes_surface():
    """Coded errors (reference ShifuErrorCode taxonomy): remote sources,
    missing inputs, missing models."""
    import pytest
    from shifu_tpu.config.errors import ErrorCode, ShifuError
    from shifu_tpu.data.reader import resolve_data_files
    from shifu_tpu.eval.scorer import Scorer

    with pytest.raises(ShifuError) as ei:
        resolve_data_files("hdfs://nn/data/train")
    assert ei.value.error_code is ErrorCode.ERROR_REMOTE_SOURCE
    assert "1007" in str(ei.value)
    with pytest.raises(ShifuError) as ei:
        resolve_data_files("/nonexistent/glob*")
    assert ei.value.error_code is ErrorCode.ERROR_INPUT_NOT_FOUND
    with pytest.raises(ShifuError) as ei:
        Scorer.from_dir("/nonexistent/models")
    assert ei.value.error_code is ErrorCode.ERROR_MODEL_FILE_NOT_FOUND


def test_parquet_source_end_to_end(model_set, tmp_path):
    """A parquet dataPath flows through the same pipeline (reference
    NNParquetWorker/GuaguaParquetMapReduceClient role)."""
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    df = pd.read_csv(mc.dataSet.dataPath, sep="|", dtype=str,
                     keep_default_na=False)
    pdir = tmp_path / "pq"
    pdir.mkdir()
    # typed columns: parquet carries real floats + nulls
    out = pd.DataFrame({
        "amount": pd.to_numeric(df["amount"], errors="coerce"),
        "velocity": pd.to_numeric(df["velocity"], errors="coerce"),
        "age_days": pd.to_numeric(df["age_days"], errors="coerce"),
        "country": df["country"], "channel": df["channel"],
        "tag": df["tag"]})
    pq.write_table(pa.Table.from_pandas(out), str(pdir / "part-0.parquet"))
    mc.dataSet.dataPath = str(pdir)
    mc.dataSet.weightColumnName = None
    mc.train.numTrainEpochs = 15
    mc.train.params = {"NumHiddenNodes": [8], "ActivationFunc": ["tanh"],
                       "Propagation": "ADAM", "LearningRate": 0.05}
    mc.evals[0].dataSet.dataPath = str(pdir)
    mc.save(mcp)
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0
    assert EvalProcessor(model_set, params={"run_eval": "Eval1"}).run() == 0
    perf = json.load(open(os.path.join(model_set, "evals", "Eval1",
                                       "EvalPerformance.json")))
    assert perf["areaUnderRoc"] > 0.7


def test_grid_config_file(model_set):
    """train.gridConfigFile: one explicit trial per line, key:value;...
    (GridSearch.java:119-153); trials validate against the meta schema."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.validator import ValidationError
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    gcf = os.path.join(model_set, "grid.conf")
    open(gcf, "w").write(
        "Propagation:ADAM;LearningRate:0.05\n"
        "Propagation:ADAM;LearningRate:0.2\n"
        "Propagation:ADAM;LearningRate:0.1;RegularizedConstant:0.001\n")
    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.numTrainEpochs = 8
    mc.train.params = {"NumHiddenNodes": [8], "ActivationFunc": ["tanh"]}
    mc.train.gridConfigFile = "grid.conf"
    mc.save(mcp)
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0
    report = json.load(open(os.path.join(model_set, "tmp",
                                         "grid_search.json")))
    assert len(report) == 3
    # a typo in the file must fail probe-style, before training
    open(gcf, "w").write("Propagation:ADAM;LearningRat:0.05\n"
                         "Propagation:ADAM;LearningRate:0.2\n")
    import pytest
    with pytest.raises(ValidationError, match="LearningRate"):
        TrainProcessor(model_set, params={}).run()


def test_combo_resume_skips_trained(model_set):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.combo import run_combo
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.numTrainEpochs = 5
    mc.train.params = {"NumHiddenNodes": [6], "ActivationFunc": ["tanh"],
                       "LearningRate": 0.1}
    mc.save(mc_path)
    assert run_combo(model_set, "new", "LR:NN") == 0
    assert run_combo(model_set, "run", None) == 0
    m0 = os.path.join(model_set, "combo_0_LR", "models", "model0.lr")
    t0 = os.path.getmtime(m0)
    assert run_combo(model_set, "run", None, resume=True) == 0
    assert os.path.getmtime(m0) == t0          # untouched: skipped


def test_encode_ref_model(prepared_set, tmp_path):
    """`encode -ref <dir>`: leaf-encode with ANOTHER model set's tree
    model (reference ModelDataEncodeProcessor ENCODE_REF_MODEL)."""
    import shutil
    model_set = prepared_set
    from shifu_tpu.pipeline.encode import EncodeProcessor
    _train_prepared(model_set, alg="RF",
                    tree_params={"TreeNum": 3, "MaxDepth": 3})
    # champion set = a copy holding the trained model; the working set's
    # own models are deleted so only -ref can supply one
    champ = str(tmp_path / "champion")
    shutil.copytree(model_set, champ)
    shutil.rmtree(os.path.join(model_set, "models"))
    assert EncodeProcessor(model_set, params={}).run() == 1
    assert EncodeProcessor(model_set,
                           params={"ref_model": champ}).run() == 0
    # a per-column binning mismatch must be rejected loudly (silent
    # garbage leaf ids otherwise)
    import json as _json
    ref_cc = os.path.join(champ, "ColumnConfig.json")
    cc = _json.load(open(ref_cc))
    for c in cc:
        if (c.get("columnBinning") or {}).get("binBoundary"):
            c["columnBinning"]["binBoundary"] = \
                c["columnBinning"]["binBoundary"][:-1]
            break
    _json.dump(cc, open(ref_cc, "w"))
    assert EncodeProcessor(model_set,
                           params={"ref_model": champ}).run() == 1
    assert EncodeProcessor(model_set,
                           params={"ref_model": "/nonexistent"}).run() == 1
    enc = os.path.join(model_set, "tmp", "EncodedData")
    lines = open(enc).read().splitlines()
    assert lines[0] == "target|tree0|tree1|tree2"
    assert len(lines) == 4001


def test_eval_score_sorted_and_nosort(prepared_set):
    """`eval -score` writes the score file sorted by mean score
    (reference sorts unless -nosort); -nosort keeps input order."""
    model_set = prepared_set
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    _train_prepared(model_set)

    def means(path):
        rows = open(path).read().splitlines()[1:]
        return [float(r.split("|")[2]) for r in rows]

    assert EvalProcessor(model_set, params={"score": ""}).run() == 0
    hits = []
    for root, _, files in os.walk(model_set):
        for f in files:
            if f.startswith("EvalScore"):
                hits.append(os.path.join(root, f))
    assert hits
    sorted_means = means(hits[0])
    assert sorted_means == sorted(sorted_means, reverse=True)
    assert EvalProcessor(model_set,
                         params={"score": "", "nosort": True}).run() == 0
    unsorted_means = means(hits[0])
    assert unsorted_means != sorted_means     # input order preserved
    assert sorted(unsorted_means, reverse=True) == sorted_means
