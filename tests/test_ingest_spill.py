"""Out-of-core ingest overhaul: mmap spill cache, pipelined prepared
windows, compact uint8 wire format, prefetch knobs, ingest telemetry."""

import json
import os

import numpy as np
import pytest


def _write_shards(d, n, c=6, n_bins=8, shard_rows=300, seed=3):
    from shifu_tpu.data.shards import Shards
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int16)
    y = (rng.random(n) < 0.4).astype(np.float32)
    w = np.ones(n, np.float32)
    os.makedirs(d, exist_ok=True)
    shard = 0
    for s in range(0, n, shard_rows):
        e = min(s + shard_rows, n)
        np.savez(os.path.join(d, f"part-{shard:05d}.npz"),
                 bins=bins[s:e], y=y[s:e], w=w[s:e])
        shard += 1
    with open(os.path.join(d, "schema.json"), "w") as f:
        json.dump({"columnNums": list(range(c)), "numShards": shard,
                   "numRows": n}, f)
    return Shards.open(d), bins, y, w


def _collect(stream, **kw):
    return [(win.start, win.n_valid, win.src,
             {k: np.asarray(a).copy() for k, a in win.arrays.items()})
            for win in stream.windows(**kw)]


def test_spill_second_epoch_identical_and_mmap_backed(tmp_path):
    """Epoch 2 must serve from the committed spill (manifest on disk) and
    reproduce epoch 1's windows exactly — values, srcs, row ids."""
    from shifu_tpu.data.streaming import ShardStream
    shards, bins, y, w = _write_shards(str(tmp_path / "s"), 1000)
    stream = ShardStream(shards, ("bins", "y", "w"), window_rows=96)
    cold = _collect(stream)
    man = os.path.join(str(tmp_path / "s"), ".spill_cache",
                       "spill-bins-y-w", "manifest.json")
    assert os.path.isfile(man)
    with open(man) as f:
        m = json.load(f)
    assert m["rows"] == 1000
    # integer bins narrowed to the compact wire dtype in the spill
    assert np.dtype(m["dtypes"]["bins"]) == np.uint8
    warm = _collect(stream)
    assert len(cold) == len(warm)
    for (s1, v1, src1, a1), (s2, v2, src2, a2) in zip(cold, warm):
        assert (s1, v1, src1) == (s2, v2, src2)
        for k in a1:
            np.testing.assert_array_equal(a1[k], a2[k])
    assert warm[0][3]["bins"].dtype == np.uint8       # zero-cast wire


def test_spill_midshard_resume_equivalence(tmp_path):
    """windows(start_shard, shard_offset, start_row) must be identical
    from the spill fast path and the cold npz path — the ResidentCache
    tail must not care which layout serves it."""
    from shifu_tpu.data.streaming import ShardStream
    d = str(tmp_path / "s")
    shards, *_ = _write_shards(d, 1100, shard_rows=250)
    spilled = ShardStream(shards, ("bins", "y", "w"), window_rows=128)
    list(spilled.windows())                           # build the spill
    cold = ShardStream(shards, ("bins", "y", "w"), window_rows=128,
                       spill=False)
    for kw in ({"start_shard": 2, "shard_offset": 37, "start_row": 537},
               {"start_shard": 1, "shard_offset": 0, "start_row": 250},
               {"start_shard": 4, "shard_offset": 99, "start_row": 1099}):
        a = _collect(spilled, **kw)
        b = _collect(cold, **kw)
        assert len(a) == len(b) and len(a) > 0 or kw["start_row"] == 1099
        for (s1, v1, src1, w1), (s2, v2, src2, w2) in zip(a, b):
            assert (s1, v1, src1) == (s2, v2, src2)
            for k in w1:
                np.testing.assert_array_equal(w1[k], w2[k])


def test_spill_stale_source_invalidates(tmp_path):
    """Rewriting a shard (re-norm) must invalidate the spill: the next
    epoch re-reads npz and rebuilds rather than serving stale bytes."""
    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream
    d = str(tmp_path / "s")
    shards, *_ = _write_shards(d, 500, shard_rows=250)
    list(ShardStream(shards, ("y",), window_rows=100).windows())
    # rewrite shard 1 with different values (and size/mtime)
    part = dict(np.load(os.path.join(d, "part-00001.npz")))
    part["y"] = part["y"] + 7.0
    np.savez(os.path.join(d, "part-00001.npz"), **part)
    stream2 = ShardStream(Shards.open(d), ("y",), window_rows=100)
    got = np.concatenate([w.arrays["y"][:w.n_valid]
                          for w in stream2.windows()])
    assert (got[250:] >= 7.0).all()                   # fresh bytes, not stale


def test_spill_budget_abort_streams_npz_and_marks(tmp_path):
    """A stream larger than the spill budget must abort the write once
    (marker manifest), keep emitting correct windows, and not retry."""
    from shifu_tpu.config import environment
    from shifu_tpu.data.streaming import ShardStream
    d = str(tmp_path / "s")
    shards, bins, y, w = _write_shards(d, 800, shard_rows=200)
    environment.set_property("shifu.stream.spillBudgetBytes", "1024")
    try:
        stream = ShardStream(shards, ("bins", "y", "w"), window_rows=128)
        a = _collect(stream)
        man = os.path.join(d, ".spill_cache", "spill-bins-y-w",
                           "manifest.json")
        with open(man) as f:
            assert "budget" in json.load(f)["aborted"]
        b = _collect(stream)                          # still correct, npz
        for (s1, v1, src1, w1), (s2, v2, src2, w2) in zip(a, b):
            assert (s1, v1, src1) == (s2, v2, src2)
            for k in w1:
                np.testing.assert_array_equal(w1[k], w2[k])
        got = np.concatenate([t[3]["bins"][:t[1]] for t in b])
        np.testing.assert_array_equal(got, bins)
    finally:
        environment.set_property("shifu.stream.spillBudgetBytes", "")


def test_num_rows_without_decoding(tmp_path):
    """Shards.num_rows reads schema shardRows / the sidecar manifest /
    npy headers — never a full npz decode; the sidecar persists."""
    from shifu_tpu.data.shards import ROWS_SIDECAR, Shards
    d = str(tmp_path / "s")
    shards, *_ = _write_shards(d, 1100, shard_rows=250)
    assert shards.num_rows == 1100
    assert shards.shard_rows == [250, 250, 250, 250, 100]
    assert os.path.isfile(os.path.join(d, ROWS_SIDECAR))
    # a fresh handle hits the sidecar (counts survive the process)
    assert Shards.open(d).num_rows == 1100
    # schema shardRows wins when present (norm writes it)
    sch = dict(shards.schema)
    sch["shardRows"] = [250, 250, 250, 250, 100]
    with open(os.path.join(d, "schema.json"), "w") as f:
        json.dump(sch, f)
    s2 = Shards.open(d)
    os.remove(os.path.join(d, ROWS_SIDECAR))
    assert s2.num_rows == 1100
    assert not os.path.isfile(os.path.join(d, ROWS_SIDECAR))  # no scan ran


def test_prefetch_depth_knobs(monkeypatch):
    from shifu_tpu.config import environment
    from shifu_tpu.data.streaming import stream_prefetch_depth
    assert stream_prefetch_depth() == 2                # default
    assert stream_prefetch_depth(5) == 5               # explicit override
    environment.set_property("shifu.stream.prefetch", "7")
    try:
        assert stream_prefetch_depth() == 7
        monkeypatch.setenv("SHIFU_TPU_PREFETCH", "3")  # env beats property
        assert stream_prefetch_depth() == 3
    finally:
        environment.set_property("shifu.stream.prefetch", "")


def test_prepared_pipelined_matches_inline(tmp_path):
    """prepared() with a background thread (depth>0) must yield the same
    sequence as inline prep, and carry src for tail bookkeeping."""
    from shifu_tpu.data.streaming import PreparedWindow, ShardStream
    shards, *_ = _write_shards(str(tmp_path / "s"), 900, shard_rows=200)

    def prep(win):
        return PreparedWindow(win.start, win.n_valid, win.rows, win.index,
                              {k: np.asarray(a, np.float64).sum()
                               for k, a in win.arrays.items()})

    stream = ShardStream(shards, ("bins", "y", "w"), window_rows=128)
    inline = list(stream.prepared(prep, depth=0))
    piped = list(stream.prepared(prep, depth=3))
    assert len(inline) == len(piped) > 0
    for a, b in zip(inline, piped):
        assert (a.start, a.n_valid, a.src) == (b.start, b.n_valid, b.src)
        assert a.src is not None
        assert a.arrays == b.arrays


def test_resident_cache_disk_passes_guard(tmp_path):
    """Regression guard: under budget the whole forest costs ONE disk
    pass; a forced tail costs exactly 1 + sweeps."""
    from shifu_tpu.data.streaming import PreparedWindow, ResidentCache, \
        ShardStream
    shards, *_ = _write_shards(str(tmp_path / "s"), 1024, shard_rows=256)

    def prep(win):
        return PreparedWindow(win.start, win.n_valid, win.rows, win.index,
                              {k: np.asarray(a) for k, a in
                               win.arrays.items()})

    stream = ShardStream(shards, ("bins", "y", "w"), window_rows=256)
    cache = ResidentCache(stream, 1 << 30, prep)
    for _ in range(4):                       # warm + 3 re-sweeps
        n = sum(1 for _ in cache.items())
        assert n == 4
    assert cache.disk_passes == 1
    assert cache.tail is None and cache.resident_rows == 1024

    tail_cache = ResidentCache(stream, 2 * 256 * (6 + 8) + 64, prep)
    for _ in range(4):
        assert sum(1 for _ in tail_cache.items()) == 4
    assert tail_cache.tail is not None
    assert tail_cache.disk_passes == 4       # warm + one per re-sweep


def test_streamed_gbt_trainer_one_disk_pass_and_spill(tmp_path):
    """Trainer-level guard under the new layout: fully-resident streamed
    GBT stays at disk_passes == 1 per forest AND leaves a committed
    spill behind for the next forest."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed
    d = str(tmp_path / "s")
    shards, bins, y, w = _write_shards(d, 1024, shard_rows=256)
    stream = ShardStream(shards, ("bins", "y", "w"), window_rows=256)
    res = train_gbt_streamed(stream, 8, None,
                             DTSettings(n_trees=4, depth=3, loss="log",
                                        seed=0), cache_budget=1 << 30)
    assert res.trees_built == 4
    assert res.disk_passes == 1
    assert os.path.isfile(os.path.join(d, ".spill_cache", "spill-bins-y-w",
                                       "manifest.json"))


def test_put_bins_uint8_wire_roundtrip():
    from shifu_tpu.train.dt_trainer import _put_bins, _wire_bins_dtype
    assert _wire_bins_dtype(256) == np.uint8
    assert _wire_bins_dtype(257) == np.uint16
    bins = np.array([[0, 5], [250, 3]], np.int32)
    d = _put_bins(None, bins, 256)
    assert d.dtype == np.uint8                 # narrow all the way into HBM
    np.testing.assert_array_equal(np.asarray(d), bins)
    d8 = _put_bins(None, bins.astype(np.uint8), 256)   # zero-cast path
    assert d8.dtype == np.uint8
    with pytest.raises(ValueError):
        _put_bins(None, np.array([[300]], np.int32), 256)


def test_uint8_bins_build_identical_trees(tmp_path):
    """Bins shipped/resident as uint8 must grow bit-identical trees to an
    int32 run (the widen happens in-graph)."""
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt
    rng = np.random.default_rng(0)
    bins = rng.integers(0, 7, size=(600, 5)).astype(np.int32)
    y = (rng.random(600) < 0.4).astype(np.float32)
    w = np.ones(600, np.float32)
    s = DTSettings(n_trees=3, depth=3, loss="log", seed=1)
    a = train_gbt(bins, y, w, 8, None, s)
    b = train_gbt(bins.astype(np.uint8), y, w, 8, None, s)
    for ta, tb in zip(a.trees, b.trees):
        np.testing.assert_array_equal(ta.split_feat, tb.split_feat)
        np.testing.assert_array_equal(ta.left_mask, tb.left_mask)
        np.testing.assert_allclose(ta.leaf_value, tb.leaf_value,
                                   rtol=1e-6, atol=1e-7)


def test_ingest_telemetry_counters(tmp_path):
    """With telemetry on, the ingest plane reports bytes/windows/stall and
    ResidentCache disk passes through the obs registry."""
    from shifu_tpu import obs
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed
    shards, *_ = _write_shards(str(tmp_path / "s"), 512, shard_rows=256)
    obs.reset_for_tests()
    obs.set_enabled(True)
    try:
        stream = ShardStream(shards, ("bins", "y", "w"), window_rows=256)
        train_gbt_streamed(stream, 8, None,
                           DTSettings(n_trees=2, depth=2, loss="log"),
                           cache_budget=1 << 30)
        names = {m["name"]: m for m in obs.snapshot()}
        assert names["ingest.bytes_read"]["value"] > 0
        assert names["ingest.windows_emitted"]["value"] >= 2
        assert names["ingest.disk_passes"]["value"] == 1
        assert "ingest.h2d_wait_seconds" in names
    finally:
        obs.reset_for_tests()


def test_report_renders_ingest_stall_fraction(tmp_path):
    from shifu_tpu import obs
    from shifu_tpu.obs.report import render_telemetry
    obs.reset_for_tests()
    obs.set_enabled(True)
    try:
        with obs.span("train", kind="step"):
            obs.counter("ingest.h2d_wait_seconds").inc(0.25)
        obs.flush(os.path.join(str(tmp_path), "telemetry", "trace.jsonl"),
                  step="train")
        text = render_telemetry(str(tmp_path))
        assert "ingest stall fraction" in text
    finally:
        obs.reset_for_tests()


def test_bench_tail_plane_schema():
    """`--plane tail` quick mode exists and the bench/obs schema handshake
    still holds past the v2 (ingest.*) bump — v3 added the varsel.*
    instrumentation; the ingest counters this suite pins remain."""
    from shifu_tpu import obs
    from shifu_tpu.bench import BENCH_TELEMETRY_SCHEMA
    assert BENCH_TELEMETRY_SCHEMA == obs.SCHEMA_VERSION >= 2
    import shifu_tpu.bench as bench_mod
    assert callable(bench_mod.bench_gbt_streamed_tail)
    with pytest.raises(ValueError):
        bench_mod.run_benchmark(plane="nope")


def test_tail_super_batch_disk_pass_telemetry_guard(tmp_path, monkeypatch):
    """Round-9 regression guard, telemetry-backed: under the super-batch
    tail schedule, passes per tree must stay within the acceptance bound
    (RF: ceil(depth/SB)+1; GBT exact: depth+2) — any future change that
    silently reintroduces per-(depth x tree) re-streams fails here."""
    import math

    from shifu_tpu import obs
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import (DTSettings, train_gbt_streamed,
                                            train_rf_streamed)
    shards, *_ = _write_shards(str(tmp_path / "s"), 1024, shard_rows=300)
    budget = 2 * 256 * (6 * 1 + 3 * 4) + 64     # ~2 of 4 windows resident
    n_trees, depth = 6, 3
    obs.reset_for_tests()
    obs.set_enabled(True)
    try:
        res = train_rf_streamed(
            ShardStream(shards, ("bins", "y", "w"), window_rows=256),
            8, None,
            DTSettings(n_trees=n_trees, depth=depth, impurity="entropy",
                       loss="squared", seed=2),
            cache_budget=budget)
        names = {m["name"]: m for m in obs.snapshot(reset=True)}
        sweeps = names["train.tail_sweeps"]["value"]
        assert sweeps == res.tail_sweeps > 0
        assert sweeps / n_trees <= math.ceil(depth / n_trees) + 1
        assert names["ingest.disk_passes"]["value"] == 1 + sweeps

        monkeypatch.setenv("SHIFU_TREE_TAIL_C2F", "0")
        res_g = train_gbt_streamed(
            ShardStream(shards, ("bins", "y", "w"), window_rows=256),
            8, None, DTSettings(n_trees=2, depth=depth, loss="log"),
            cache_budget=budget)
        names = {m["name"]: m for m in obs.snapshot()}
        assert names["train.tail_sweeps"]["value"] == res_g.tail_sweeps \
            == 2 * (depth + 2)
    finally:
        obs.reset_for_tests()


def test_report_renders_tail_sweep_line(tmp_path):
    """The v4 tail-plane line: sweep count, disk passes and speculation
    repairs surface in `analysis --telemetry`."""
    from shifu_tpu import obs
    from shifu_tpu.obs.report import render_telemetry
    obs.reset_for_tests()
    obs.set_enabled(True)
    try:
        with obs.span("train", kind="step"):
            obs.counter("train.tail_sweeps").inc(12)
            obs.counter("ingest.disk_passes").inc(13)
            obs.counter("train.tail_repairs").inc(2)
            obs.counter("train.tail_repair_levels").inc(5)
            obs.counter("ingest.h2d_wait_seconds").inc(0.1)
        obs.flush(os.path.join(str(tmp_path), "telemetry", "trace.jsonl"),
                  step="train")
        text = render_telemetry(str(tmp_path))
        assert "tail sweeps: 12" in text
        assert "13 disk passes" in text
        assert "2 speculation repairs over 5 levels" in text
        assert "ingest stall fraction" in text
    finally:
        obs.reset_for_tests()


def test_bench_cli_tail_help_and_schema_exit(monkeypatch):
    """CI smoke for the tail plane CLI: --help lists it, and a bench/obs
    schema-version mismatch exits NONZERO (code 2) instead of tracing
    out — the guard CI keys off."""
    import importlib.util
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--help"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    assert "tail" in out.stdout

    spec = importlib.util.spec_from_file_location(
        "bench_cli", os.path.join(repo, "bench.py"))
    bench_cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_cli)
    import shifu_tpu.bench as bench_mod
    monkeypatch.setattr(bench_mod, "BENCH_TELEMETRY_SCHEMA",
                        bench_mod.BENCH_TELEMETRY_SCHEMA + 1)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--plane", "tail"])
    with pytest.raises(SystemExit) as ei:
        bench_cli.main()
    assert ei.value.code == 2
