"""Byte-level golden fixture for ``BinaryWDLSerializer``
(``export/reference_spec.write_reference_wdl``).

The round-trip test (``test_reference_export.test_wdl_reference_roundtrip``)
validates the WDL binary format only against our own reader — a
self-consistent-but-wrong drift in BOTH writer and reader would pass it.
This test pins the writer's exact output bytes for a small deterministic
model against a checked-in fixture (``tests/golden/wdl_model_golden.bin``,
the gzip-DECOMPRESSED stream — the gzip header embeds an mtime, so raw
file bytes are not stable), so any byte-layout change is a loud, reviewed
event.

Regenerate (only after verifying the new layout against the reference's
``IndependentWDLModel.loadFromStream``):
``python tests/test_wdl_golden.py --regen``
"""

import gzip
import os
import sys

import numpy as np

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "wdl_model_golden.bin")


def _grid(shape, scale=0.125, offset=-0.5):
    """Deterministic f32 grid exactly representable in binary (eighths):
    immune to RNG/numpy version drift."""
    n = int(np.prod(shape))
    return ((np.arange(n, dtype=np.float32) % 16) * scale
            + offset).reshape(shape)


def _cc(num, name, cats=None, bounds=None, mean=0.25):
    from shifu_tpu.config.column_config import ColumnConfig, ColumnType
    cc = ColumnConfig(columnNum=num, columnName=name,
                      columnType=ColumnType.C if cats else ColumnType.N)
    cc.columnBinning.binCategory = cats
    cc.columnBinning.binBoundary = bounds
    cc.columnBinning.binCountNeg = [10, 5]
    cc.columnBinning.binCountPos = [2, 3]
    cc.columnBinning.binCountWoe = [-0.5, 0.75]
    cc.columnBinning.binWeightedWoe = [-0.25, 0.5]
    cc.columnBinning.binPosRate = [0.125, 0.375]
    cc.columnStats.mean = mean
    cc.columnStats.stdDev = 1.25
    return cc


def _golden_model():
    """The pinned model: 2 numerics, 2 categoricals (cards 3/2), embed 2,
    one hidden layer of 3 — every array an exact-f32 grid."""
    from shifu_tpu.models.wdl import WDLModelSpec
    spec = WDLModelSpec(numeric_dim=2, cat_cardinalities=[3, 2],
                        embed_dim=2, hidden_nodes=[3],
                        activations=["relu"], column_nums=[1, 2],
                        cat_column_nums=[5, 6])
    params = {
        "embed": [_grid((3, 2)), _grid((2, 2), offset=-0.25)],
        "deep": [{"w": _grid((6, 3)), "b": _grid((3,), offset=0.0)},
                 {"w": _grid((3, 1), offset=0.375), "b": _grid((1,))}],
        "wide_cat": [_grid((3,), offset=0.125), _grid((2,), offset=-0.375)],
        "wide_num": _grid((2, 1), offset=0.5),
        "bias": np.asarray([0.25], np.float32),
    }
    ccs = [_cc(1, "num1", bounds=[float("-inf"), 0.5]),
           _cc(2, "num2", bounds=[float("-inf"), 0.0], mean=-0.75),
           _cc(5, "catA", cats=["a", "b"]),
           _cc(6, "catB", cats=["x"])]
    return spec, params, ccs


def _serialize(tmp_path) -> bytes:
    from shifu_tpu.export.reference_spec import write_reference_wdl
    spec, params, ccs = _golden_model()
    path = os.path.join(str(tmp_path), "model0.wdl")
    write_reference_wdl(path, spec, params, ccs)
    with open(path, "rb") as f:
        return gzip.decompress(f.read())


def test_wdl_serializer_bytes_match_golden(tmp_path):
    payload = _serialize(tmp_path)
    assert os.path.isfile(GOLDEN), \
        f"golden fixture missing — run `python {__file__} --regen`"
    with open(GOLDEN, "rb") as f:
        expected = f.read()
    assert payload == expected, (
        f"BinaryWDLSerializer output drifted from the golden fixture "
        f"({len(payload)} vs {len(expected)} bytes) — if the layout change "
        "is intentional, re-validate against the reference's "
        "IndependentWDLModel.loadFromStream and regenerate the fixture")


def test_wdl_golden_model_still_roundtrips(tmp_path):
    """The pinned bytes must stay loadable by our reader with exact
    values — guards reader/writer drifting together AWAY from the pin."""
    from shifu_tpu.models.reference_import import load_reference_wdl
    from shifu_tpu.export.reference_spec import write_reference_wdl
    spec, params, ccs = _golden_model()
    path = os.path.join(str(tmp_path), "model0.wdl")
    write_reference_wdl(path, spec, params, ccs)
    spec2, params2, col_stats = load_reference_wdl(path)
    assert spec2.numeric_dim == 2
    assert spec2.cat_cardinalities == [3, 2]
    assert col_stats[5]["categories"] == ["a", "b"]
    np.testing.assert_array_equal(np.asarray(params2["embed"][0]),
                                  params["embed"][0])
    np.testing.assert_array_equal(np.asarray(params2["deep"][0]["w"]),
                                  params["deep"][0]["w"])
    np.testing.assert_array_equal(np.asarray(params2["wide_num"]),
                                  params["wide_num"])


if __name__ == "__main__":
    if "--regen" in sys.argv:
        import tempfile
        os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
        with tempfile.TemporaryDirectory() as td:
            payload = _serialize(td)
        with open(GOLDEN, "wb") as f:
            f.write(payload)
        print(f"wrote {len(payload)} bytes -> {GOLDEN}")
    else:
        print(__doc__)
