"""Padding-waste squeeze — occupancy-driven serve bucket ladder and the
shape-stable ingest remainder (round 12, ROADMAP #5).

Contracts:
- :func:`refine_ladder` proposes tighter rungs only under rungs that
  systematically pad (share + occupancy gates), never removes rungs,
  and bounds additions;
- :meth:`AOTScorer.extend_buckets` compiles AND warms a new rung before
  publishing it — the zero-recompile sentinel must stay at 0 across a
  refinement;
- the batcher's auto-refinement grows the ladder from observed batch
  sizes and subsequent batches pad to the tighter rung;
- ``serve.bucket_occupancy`` is a HISTOGRAM: p50/p99 quantile lines land
  in metrics.prom (a gauge only ever showed the last batch);
- the training-window remainder ladder pads the tail to a W/2^k rung
  instead of the full window.
"""

import os
import time

import numpy as np
import pytest

import jax

from shifu_tpu import obs
from shifu_tpu.config import environment
from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                 init_params)
from shifu_tpu.serve import AOTScorer, MicroBatcher, serve_recompile_count
from shifu_tpu.serve.scorer import refine_ladder

pytestmark = [pytest.mark.serve, pytest.mark.perf]


@pytest.fixture(autouse=True)
def _clean_env():
    environment.reset_for_tests()
    yield
    environment.reset_for_tests()
    obs.set_enabled(False)


def _nn_models(n=2, n_features=8):
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=[4],
                       activations=["relu"])
    return [IndependentNNModel(spec, init_params(jax.random.PRNGKey(i),
                                                 spec)) for i in range(n)]


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# ------------------------------------------------------------- policy
def test_refine_ladder_proposes_tight_rung():
    lad = refine_ladder((1, 8, 64, 512), {40: 100, 44: 50, 2: 5})
    assert 48 in lad
    assert set((1, 8, 64, 512)) <= set(lad)      # never removes


def test_refine_ladder_share_and_occupancy_gates():
    # traffic share below min_share: no proposal
    assert refine_ladder((1, 8, 64), {40: 1, 7: 99}) == (1, 8, 64)
    # high occupancy already: no proposal
    assert refine_ladder((1, 8, 64), {60: 100}) == (1, 8, 64)
    # smallest rung never subdivides
    assert refine_ladder((8, 64), {2: 100}) == (8, 64)
    # empty evidence: identity
    assert refine_ladder((1, 8), {}) == (1, 8)


def test_refine_ladder_bounds_additions():
    counts = {40: 100, 200: 100, 3: 100}
    lad = refine_ladder((1, 8, 64, 512), counts, max_extra=1)
    assert len(lad) == 5                          # exactly one added


# -------------------------------------------------- extend, ahead of use
def test_extend_buckets_zero_recompiles():
    scorer = AOTScorer(_nn_models(), buckets=(8, 64),
                       name="serve.score.ladder1")
    scorer.warm()
    base = serve_recompile_count("serve.score.ladder1")
    rows = np.random.default_rng(0).normal(size=(40, 8)).astype(np.float32)
    scorer.score_batch(rows)                      # pads 40 -> 64
    assert scorer.extend_buckets([48, 64]) == 1   # 64 already present
    assert scorer.buckets == (8, 48, 64)
    out = scorer.score_batch(rows)                # now pads 40 -> 48
    assert out.shape == (40, 2)
    assert serve_recompile_count("serve.score.ladder1") == base


def test_batcher_auto_refine_grows_ladder():
    """Every ``refine_every`` batches the batcher proposes rungs from
    its observed batch sizes and grows the scorer's ladder on a
    background thread; later batches pad to the tighter rung."""
    environment.set_property("shifu.serve.bucketRefineEvery", 6)
    scorer = AOTScorer(_nn_models(), buckets=(1, 8, 64),
                       name="serve.score.ladder2")
    scorer.warm()
    clk = FakeClock()
    b = MicroBatcher(lambda: scorer, max_delay_s=0.002, clock=clk)
    assert b.refine_every == 6
    rng = np.random.default_rng(0)
    rows = rng.normal(size=(40, 8)).astype(np.float32)
    for _ in range(7):
        b.submit_burst(rows)
        assert b.pump(force=True) == 40
    deadline = time.monotonic() + 10.0
    while 40 not in scorer.buckets and time.monotonic() < deadline:
        time.sleep(0.01)
    assert 40 in scorer.buckets
    b.submit_burst(rows)
    b.pump(force=True)
    assert b.bucket_counts.get(40, 0) >= 1        # padded to the new rung


def test_server_swap_refines_candidate_ladder():
    """A hot-swap builds the candidate on the LIVE ladder refined
    against observed traffic (rungs compiled during BUILD, before the
    flip)."""
    from shifu_tpu.serve import ServeServer
    environment.set_property("shifu.serve.bucketRefineEvery", 0)
    srv = ServeServer(models=_nn_models(), key="m", buckets=(1, 8, 64))
    try:
        srv.batcher.size_counts.update({40: 100, 44: 40})
        srv.swap(_nn_models(n=2))
        assert 48 in srv.registry.get("m").buckets
        assert srv.registry.generation("m") == 1
    finally:
        srv.stop()


# ----------------------------------------------- occupancy distribution
def test_bucket_occupancy_histogram_quantiles(tmp_path):
    obs.reset_for_tests()
    obs.set_enabled(True)
    try:
        scorer = AOTScorer(_nn_models(), buckets=(8, 64),
                           name="serve.score.ladder3")
        scorer.warm()
        environment.set_property("shifu.serve.bucketRefineEvery", 0)
        b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
        rng = np.random.default_rng(0)
        for n in (2, 4, 6, 40, 50):
            b.submit_burst(rng.normal(size=(n, 8)).astype(np.float32))
            b.pump(force=True)
        h = obs.histogram("serve.bucket_occupancy")
        assert h.quantile(0.5) is not None
        td = str(tmp_path / "t")
        obs.write_metrics_files(td, step="SERVE")
        text = open(os.path.join(td, "metrics.prom")).read()
        assert 'shifu_tpu_serve_bucket_occupancy{quantile="0.5"}' in text
        assert 'shifu_tpu_serve_bucket_occupancy{quantile="0.99"}' in text
    finally:
        obs.reset_for_tests()


# ------------------------------------------------- ingest remainder tail
def test_stream_remainder_ladder_tail(tmp_path):
    import json

    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream

    rng = np.random.default_rng(0)
    n, d = 1100, 4                                # tail of 76 past 2x512
    x = rng.normal(size=(n, d)).astype(np.float32)
    td = str(tmp_path / "sh")
    os.makedirs(td)
    k = 0
    for s in range(0, n, 400):
        e = min(s + 400, n)
        np.savez(os.path.join(td, f"part-{k:05d}.npz"), x=x[s:e])
        k += 1
    json.dump({"columnNums": list(range(d)), "numShards": k,
               "numRows": n},
              open(os.path.join(td, "schema.json"), "w"))

    def shapes(rm):
        stream = ShardStream(Shards.open(td), ("x",), 512, spill=False,
                             remainder_multiple=rm)
        wins = list(stream.windows())
        assert np.array_equal(
            np.concatenate([w.arrays["x"][:w.n_valid] for w in wins]), x)
        return [w.rows for w in wins]

    assert shapes(0) == [512, 512, 512]           # old full-W pad
    assert shapes(1) == [512, 512, 128]           # W/4 covers the 76 tail
    # rung must stay a multiple of the mesh data axis
    assert shapes(3) == [512, 512, 512]           # 512/2 % 3 != 0 -> full

    stream = ShardStream(Shards.open(td), ("x",), 512, spill=False,
                         remainder_multiple=1)
    assert stream._tail_rows(76) == 128
    assert stream._tail_rows(100) == 128
    assert stream._tail_rows(129) == 256
    assert stream._tail_rows(512) == 512
    assert stream._tail_rows(1) == 64             # floor at W/8
