"""Live SLO plane suite (obs/slo): log-bin sketch accuracy, sliding
windows with an injected clock, availability/error-budget burn-rate
math, multi-window alert semantics (a forced breach trips within one
window; a transient blip does not page), gauge emission, knob readers,
and the ServeServer integration (/slo payload, heartbeat extras,
monitor flags)."""

import json
import os

import numpy as np
import pytest

import jax

from shifu_tpu import obs
from shifu_tpu.config import environment
from shifu_tpu.obs import slo as slo_mod
from shifu_tpu.obs.slo import (LOG_BINS, LogBins, SLOTracker,
                               quantile_from_counts, slo_objectives)

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_env():
    environment.reset_for_tests()
    obs.reset_for_tests()
    yield
    environment.reset_for_tests()
    obs.reset_for_tests()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# -------------------------------------------------------------- log bins
def test_log_bins_index_monotonic_and_bounded():
    b = LogBins()
    vals = 10.0 ** np.linspace(-7, 4, 400)
    idx = [b.index(float(v)) for v in vals]
    assert idx == sorted(idx)
    assert idx[0] == 0 and idx[-1] == b.n - 1
    assert b.index(0.0) == 0 and b.index(-1.0) == 0
    # vectorized agrees with scalar
    np.testing.assert_array_equal(b.indices(vals), np.asarray(idx))
    # a bin's representative value round-trips into the same bin
    for i in range(1, b.n - 1):
        assert b.index(b.value(i)) == i


def test_quantile_from_counts_accuracy():
    rng = np.random.default_rng(0)
    lat = rng.lognormal(mean=-6.0, sigma=0.8, size=20000)   # ~2.5ms-ish
    counts = np.bincount(LOG_BINS.indices(lat), minlength=LOG_BINS.n)
    for q in (0.5, 0.9, 0.99):
        exact = float(np.quantile(lat, q))
        est = quantile_from_counts(counts, q)
        assert est == pytest.approx(exact, rel=0.15)
    assert quantile_from_counts(np.zeros(LOG_BINS.n, np.int64), 0.5) \
        is None


# -------------------------------------------------------------- tracker
def test_tracker_windows_slide_and_expire():
    clk = FakeClock()
    t = SLOTracker(p99_ms=5.0, window_s=1.0, n_windows=3, clock=clk)
    t.observe_batch(np.full(100, 0.001))
    assert t.quantile_ms(0.5) == pytest.approx(1.0, rel=0.15)
    # 2 windows later the data is still inside the 3-window ring
    clk.t += 2.0
    assert t.quantile_ms(0.5) is not None
    # 4 windows later it has expired
    clk.t += 2.0
    assert t.quantile_ms(0.5) is None
    assert t.availability_observed() == 1.0        # empty = healthy


def test_tracker_availability_and_burn_math():
    clk = FakeClock()
    t = SLOTracker(p99_ms=5.0, availability=0.999, window_s=10.0,
                   n_windows=6, clock=clk)
    t.observe_batch(np.full(990, 0.001))
    t.record_errors(10)
    assert t.availability_observed() == pytest.approx(0.99)
    burn = t.burn_rates()
    # 1% errors against a 0.1% allowance = burn 10
    assert burn["availability"] == pytest.approx(10.0, rel=0.01)
    assert burn["latency"] == 0.0
    # latency budget: 5% of requests over the objective vs 1% allowed
    t2 = SLOTracker(p99_ms=5.0, window_s=10.0, clock=FakeClock())
    lat = np.full(1000, 0.001)
    lat[:50] = 0.050
    t2.observe_batch(lat)
    assert t2.burn_rates()["latency"] == pytest.approx(5.0, rel=0.01)


def test_forced_breach_alerts_within_one_window():
    """ACCEPTANCE: a hard SLO breach (every request over the objective)
    trips the page burn-rate alert within one window."""
    clk = FakeClock()
    t = SLOTracker(p99_ms=0.1, window_s=10.0, n_windows=30, clock=clk)
    assert t.alerts() == []
    t.observe_batch(np.full(200, 0.005))       # 5ms >> 0.1ms objective
    alerts = t.alerts()
    assert alerts and alerts[0]["severity"] == "page"
    assert alerts[0]["budget"] == "latency"
    assert alerts[0]["burn_short"] >= 14.4
    summ = t.summary()
    assert summ["alerting"] is True
    assert summ["horizons"]["short"]["over_objective"] == 200
    compact = t.compact()
    assert compact["alerting"] and "page:latency" in compact["alerts"]


def test_transient_blip_does_not_page():
    """Multi-window suppression: a short burst of slow requests inside a
    long healthy history exceeds the short-window burn but NOT the
    long-window burn — no page."""
    clk = FakeClock()
    t = SLOTracker(p99_ms=2.0, window_s=1.0, n_windows=30, clock=clk)
    for _ in range(29):                        # long healthy history
        t.observe_batch(np.full(1000, 0.0001))
        clk.t += 1.0
    t.observe_batch(np.full(30, 0.050))        # one bad tick
    burn = t.burn_rates(horizon_s=1.0)
    assert burn["latency"] >= 14.4             # short window IS burning
    assert t.alerts() == []                    # long window absorbs it


def test_emit_gauges_and_objectives_knobs():
    obs.set_enabled(True)
    clk = FakeClock()
    t = SLOTracker(p99_ms=2.0, window_s=10.0, clock=clk)
    t.observe_batch(np.full(100, 0.001))
    t.emit_gauges()
    snap = {m["name"]: m for m in obs.snapshot()}
    assert snap["slo.p99_ms"]["value"] == pytest.approx(1.0, rel=0.15)
    assert snap["slo.availability"]["value"] == 1.0
    assert snap["slo.alerts_firing"]["value"] == 0
    assert "slo.burn_rate_short" in snap and "slo.burn_rate_long" in snap
    # knob readers: defaults derive from the deadline; properties win
    p99, avail = slo_objectives(max_delay_ms=2.0)
    assert p99 == 4.0 and avail == slo_mod.DEFAULT_AVAILABILITY
    environment.set_property("shifu.serve.sloP99Ms", "7.5")
    environment.set_property("shifu.serve.sloAvailability", "0.99")
    p99, avail = slo_objectives(max_delay_ms=2.0)
    assert p99 == 7.5 and avail == 0.99


def test_registry_histogram_sketch_quantiles():
    obs.set_enabled(True)
    h = obs.histogram("train.epoch_s")
    for _ in range(99):
        h.observe(0.5)
    h.observe(20.0)
    rec = h.to_record()
    assert rec["p50"] == pytest.approx(0.5, rel=0.15)
    assert rec["p99"] == pytest.approx(0.5, rel=0.15)
    assert h.quantile(0.999) == pytest.approx(20.0, rel=0.15)


# -------------------------------------------------- server integration
def _nn_models(n=2, n_features=8, seed0=0):
    from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                     init_params)
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=[8],
                       activations=["relu"])
    return [IndependentNNModel(spec, init_params(
        jax.random.PRNGKey(seed0 + i), spec)) for i in range(n)]


def test_server_slo_doc_and_status(tmp_path):
    from shifu_tpu.serve import ServeServer
    server = ServeServer(models=_nn_models(), key="s", buckets=(1, 4),
                         max_delay_ms=1.0, slo_p99_ms=500.0)
    rng = np.random.default_rng(0)
    server.score(rng.normal(size=(3, 8)).astype(np.float32))
    st = server.status()
    assert st["queue_depth"] == 0
    assert st["slo"]["objective_p99_ms"] == 500.0
    assert st["slo"]["alerting"] is False
    doc = server.slo_doc()
    assert doc["kind"] == "slo"
    assert doc["horizons"]["long"]["requests"] == 3
    assert doc["objectives"]["p99_ms"] == 500.0
    assert json.loads(json.dumps(doc))          # JSON-serializable


def test_server_breach_trips_slo_and_monitor(tmp_path):
    """ACCEPTANCE: a tiny objective forces a breach; /slo reports the
    page alert and `monitor` renders the SLO BURN flag from the SERVE
    heartbeat within one beat."""
    from shifu_tpu.obs import monitor as monitor_mod
    from shifu_tpu.serve import ServeServer
    obs.set_enabled(True)
    mdir = str(tmp_path)
    server = ServeServer(model_set_dir=mdir, models=_nn_models(),
                         key="b", buckets=(1, 4), max_delay_ms=1.0,
                         slo_p99_ms=1e-6)       # nothing can meet this
    server.start()
    try:
        rng = np.random.default_rng(1)
        server.score(rng.normal(size=(4, 8)).astype(np.float32),
                     timeout=15.0)
        doc = server.slo_doc()
        assert doc["alerting"] is True
        assert any(a["severity"] == "page" and a["budget"] == "latency"
                   for a in doc["alerts"])
        # force one beat NOW (no interval sleep) and read it back
        server._heartbeat.beat()
        (rec,) = obs.read_health(obs.health_dir_for(mdir))
        assert rec["queue_depth"] == 0
        assert rec["slo"]["alerting"] is True
        text = monitor_mod.render_status(mdir)
        assert "SLO BURN" in text
        assert "q=0" in text
    finally:
        server.stop()


def test_serve_heartbeat_queue_depth_sampled(tmp_path):
    """Satellite: SERVE heartbeats carry queue_depth (and the buildup
    flag trips when the queue exceeds the buildup threshold)."""
    from shifu_tpu.obs import monitor as monitor_mod
    from shifu_tpu.serve import ServeServer
    from shifu_tpu.serve.server import QUEUE_BUILDUP_BUCKETS
    obs.set_enabled(True)
    mdir = str(tmp_path)
    server = ServeServer(model_set_dir=mdir, models=_nn_models(),
                         key="q", buckets=(1, 4), max_delay_ms=1.0)
    # NOT started: no worker drains the queue, so depth is observable
    rng = np.random.default_rng(2)
    n = QUEUE_BUILDUP_BUCKETS * 4 + 3
    server.batcher.submit_burst(
        rng.normal(size=(n, 8)).astype(np.float32))
    extras = server._beat_extras()
    assert extras["queue_depth"] == n
    assert extras["queue_buildup"] is True
    snap = {m["name"]: m for m in obs.snapshot()}
    assert snap["serve.queue_depth"]["value"] == n
    # monitor renders the buildup flag from a heartbeat carrying it
    hd = obs.health_dir_for(mdir)
    os.makedirs(hd)
    import time
    with open(os.path.join(hd, "serve-q.json"), "w") as f:
        json.dump({"proc": "serve-q", "step": "SERVE",
                   "state": "running", "ts": time.time(),
                   "last_progress_ts": time.time(), "interval_s": 5.0,
                   **extras}, f)
    text = monitor_mod.render_status(mdir)
    assert "QUEUE BUILDUP" in text and f"q={n}" in text
    server.batcher.drain()
