"""Guards the driver's entry points (`__graft_entry__`) and multi-device
numerics — the round-1 headline failure was exactly this file not existing.

Runs on the conftest-forced 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax


def _entry_module():
    import __graft_entry__
    return __graft_entry__


def test_entry_compiles_and_runs():
    fn, args = _entry_module().entry()
    out = jax.jit(fn)(*args)
    assert np.isfinite(np.asarray(out)).all()


def test_dryrun_multichip_8():
    # the exact call the driver makes
    _entry_module().dryrun_multichip(8)


def test_dryrun_hermetic():
    """Every buffer the dryrun creates must live on the backend it selected —
    the r01/r02 failures were non-hermetic fallback (eager ops landing on a
    broken default TPU backend)."""
    mod = _entry_module()
    devices = mod._pick_devices(8)
    assert all(d.platform == "cpu" for d in devices), \
        "CPU plane is large enough here, so it must be probed & chosen first"
    before_refs = list(jax.live_arrays())   # hold refs: pin ids against reuse
    before = {id(a) for a in before_refs}
    mod.dryrun_multichip(8)
    leaked = [a for a in jax.live_arrays()
              if id(a) not in before and a.devices()
              and any(d.platform != "cpu" for d in a.devices())]
    del before_refs
    assert not leaked


def test_dryrun_survives_broken_default_backend(monkeypatch):
    """The exact recorded r02 failure: default backend init succeeds but every
    op raises (libtpu client/terminal mismatch).  The dryrun must never reach
    it when the CPU plane suffices."""
    real_devices = jax.devices

    def poisoned(*args, **kwargs):
        if args or kwargs:          # explicit backend probe is fine
            return real_devices(*args, **kwargs)
        raise RuntimeError("FAILED_PRECONDITION: libtpu version mismatch")

    monkeypatch.setattr(jax, "devices", poisoned)
    _entry_module().dryrun_multichip(8)


def test_device_mesh_shape():
    from shifu_tpu.parallel.mesh import device_mesh
    devs = jax.devices("cpu")
    assert len(devs) >= 8, "conftest must force an 8-device CPU platform"
    mesh = device_mesh(n_ensemble=2, devices=devs[:8])
    assert mesh.shape["ensemble"] == 2
    assert mesh.shape["data"] == 4


@pytest.mark.parametrize("bags", [1, 2])
def test_one_vs_eight_device_equivalence(bags):
    """Training on a 1-device mesh and an 8-device mesh must agree: the mesh
    only changes WHERE the rows live, never the math (GSPMD inserts the
    psum; full-batch + no dropout makes the run deterministic)."""
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble
    from shifu_tpu.train.sampling import member_masks

    rng = np.random.default_rng(3)
    n, d = 96, 6
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    train_w, valid_w = member_masks(n, bags, valid_rate=0.25, sample_rate=1.0,
                                    replacement=False, targets=y, seed=0)
    spec = nn_model.NNModelSpec(input_dim=d, hidden_nodes=[8],
                                activations=["tanh"], loss="log")
    settings = TrainSettings(optimizer="ADAM", learning_rate=0.05,
                             epochs=5, seed=0)
    devs = jax.devices("cpu")
    res1 = train_ensemble(x, y, train_w, valid_w, spec, settings,
                          mesh=device_mesh(n_ensemble=bags, devices=devs[:1]))
    res8 = train_ensemble(x, y, train_w, valid_w, spec, settings,
                          mesh=device_mesh(n_ensemble=bags, devices=devs[:8]))
    np.testing.assert_allclose(res1.valid_errors, res8.valid_errors,
                               rtol=1e-4, atol=1e-6)
    for p1, p8 in zip(res1.params, res8.params):
        flat1 = jax.tree_util.tree_leaves(p1)
        flat8 = jax.tree_util.tree_leaves(p8)
        for a, b in zip(flat1, flat8):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)


def test_stats_accumulator_mesh_equivalence():
    """NumericAccumulator on a 1-device vs 8-device mesh: the data-axis
    sharding must only change WHERE rows live (reference stats fan-out,
    ``MapReducerStatsWorker.java:111-139``).  Counts are integer-exact
    either way; weighted sums may differ by reduction order only."""
    from shifu_tpu.config.model_config import BinningMethod
    from shifu_tpu.ops.binning import NumericAccumulator
    from shifu_tpu.parallel.mesh import device_mesh

    rng = np.random.default_rng(11)
    n, c = 997, 5                       # deliberately NOT divisible by 8
    x = rng.normal(size=(n, c)).astype(np.float32) * [1, 10, 100, 1, 1]
    valid = rng.random((n, c)) > 0.07
    target = (rng.random(n) < 0.3).astype(np.float32)
    weight = rng.uniform(0.5, 2.0, n).astype(np.float32)
    devs = jax.devices("cpu")

    def run(mesh):
        acc = NumericAccumulator(n_cols=c, num_buckets=256, mesh=mesh)
        for s, e in ((0, 400), (400, n)):    # two uneven chunks
            acc.update_moments(x[s:e], valid[s:e])
        acc.finalize_range()
        for s, e in ((0, 400), (400, n)):
            acc.update_histogram(x[s:e], valid[s:e], target[s:e],
                                 weight[s:e])
        return acc, acc.finalize_sketch(BinningMethod.EqualTotal, 8)

    acc1, (b1, a1, p1, d1) = run(None)
    acc8, (b8, a8, p8, d8) = run(device_mesh(devices=devs[:8]))
    assert acc1.total_rows == acc8.total_rows == n
    np.testing.assert_array_equal(acc1.missing, acc8.missing)
    np.testing.assert_allclose(acc1.moments["mean"], acc8.moments["mean"],
                               rtol=1e-5)
    for i in range(c):
        np.testing.assert_array_equal(b1[i], b8[i])          # boundaries
        np.testing.assert_array_equal(a1[i][:, :2], a8[i][:, :2])  # counts
        np.testing.assert_allclose(a1[i][:, 2:], a8[i][:, 2:], rtol=1e-5)
    np.testing.assert_array_equal(d1, d8)
    np.testing.assert_allclose(p1, p8, rtol=1e-6)


def test_scorer_mesh_equivalence(tmp_path):
    """Scorer with a data-sharded mesh scores identically to the
    single-device layout (reference cluster eval,
    ``EvalModelProcessor.java:424-436``)."""
    from shifu_tpu.eval.scorer import Scorer
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.models.nn import IndependentNNModel
    from shifu_tpu.parallel.mesh import device_mesh

    rng = np.random.default_rng(5)
    d = 6
    spec = nn_model.NNModelSpec(input_dim=d, hidden_nodes=[8],
                                activations=["tanh"])
    models = [IndependentNNModel(
        spec, nn_model.init_params(jax.random.PRNGKey(i), spec))
        for i in range(3)]
    x = rng.normal(size=(997, d)).astype(np.float32)   # not divisible by 8
    devs = jax.devices("cpu")
    r1 = Scorer(models).score(x)
    r8 = Scorer(models, mesh=device_mesh(devices=devs[:8])).score(x)
    np.testing.assert_allclose(r1.scores, r8.scores, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(r1.mean, r8.mean, rtol=1e-5, atol=1e-5)
