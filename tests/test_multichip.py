"""Guards the driver's entry points (`__graft_entry__`) and multi-device
numerics — the round-1 headline failure was exactly this file not existing.

Runs on the conftest-forced 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax


def _entry_module():
    import __graft_entry__
    return __graft_entry__


def test_entry_compiles_and_runs():
    fn, args = _entry_module().entry()
    out = jax.jit(fn)(*args)
    assert np.isfinite(np.asarray(out)).all()


def test_dryrun_multichip_8():
    # the exact call the driver makes
    _entry_module().dryrun_multichip(8)


def test_dryrun_hermetic():
    """Every buffer the dryrun creates must live on the backend it selected —
    the r01/r02 failures were non-hermetic fallback (eager ops landing on a
    broken default TPU backend)."""
    mod = _entry_module()
    devices = mod._pick_devices(8)
    assert all(d.platform == "cpu" for d in devices), \
        "CPU plane is large enough here, so it must be probed & chosen first"
    before_refs = list(jax.live_arrays())   # hold refs: pin ids against reuse
    before = {id(a) for a in before_refs}
    mod.dryrun_multichip(8)
    leaked = [a for a in jax.live_arrays()
              if id(a) not in before and a.devices()
              and any(d.platform != "cpu" for d in a.devices())]
    del before_refs
    assert not leaked


def test_dryrun_survives_broken_default_backend(monkeypatch):
    """The exact recorded r02 failure: default backend init succeeds but every
    op raises (libtpu client/terminal mismatch).  The dryrun must never reach
    it when the CPU plane suffices."""
    real_devices = jax.devices

    def poisoned(*args, **kwargs):
        if args or kwargs:          # explicit backend probe is fine
            return real_devices(*args, **kwargs)
        raise RuntimeError("FAILED_PRECONDITION: libtpu version mismatch")

    monkeypatch.setattr(jax, "devices", poisoned)
    _entry_module().dryrun_multichip(8)


def test_device_mesh_shape():
    from shifu_tpu.parallel.mesh import device_mesh
    devs = jax.devices("cpu")
    assert len(devs) >= 8, "conftest must force an 8-device CPU platform"
    mesh = device_mesh(n_ensemble=2, devices=devs[:8])
    assert mesh.shape["ensemble"] == 2
    assert mesh.shape["data"] == 4


@pytest.mark.parametrize("bags", [1, 2])
def test_one_vs_eight_device_equivalence(bags):
    """Training on a 1-device mesh and an 8-device mesh must agree: the mesh
    only changes WHERE the rows live, never the math (GSPMD inserts the
    psum; full-batch + no dropout makes the run deterministic)."""
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble
    from shifu_tpu.train.sampling import member_masks

    rng = np.random.default_rng(3)
    n, d = 96, 6
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    train_w, valid_w = member_masks(n, bags, valid_rate=0.25, sample_rate=1.0,
                                    replacement=False, targets=y, seed=0)
    spec = nn_model.NNModelSpec(input_dim=d, hidden_nodes=[8],
                                activations=["tanh"], loss="log")
    settings = TrainSettings(optimizer="ADAM", learning_rate=0.05,
                             epochs=5, seed=0)
    devs = jax.devices("cpu")
    res1 = train_ensemble(x, y, train_w, valid_w, spec, settings,
                          mesh=device_mesh(n_ensemble=bags, devices=devs[:1]))
    res8 = train_ensemble(x, y, train_w, valid_w, spec, settings,
                          mesh=device_mesh(n_ensemble=bags, devices=devs[:8]))
    np.testing.assert_allclose(res1.valid_errors, res8.valid_errors,
                               rtol=1e-4, atol=1e-6)
    for p1, p8 in zip(res1.params, res8.params):
        flat1 = jax.tree_util.tree_leaves(p1)
        flat8 = jax.tree_util.tree_leaves(p8)
        for a, b in zip(flat1, flat8):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)
