"""Normalizer semantics + the norm pipeline step."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.config import ColumnConfig, ColumnType
from shifu_tpu.config.model_config import NormType, PrecisionType
from shifu_tpu.data.shards import Shards
from shifu_tpu.ops.normalize import (NormalizedColumn, apply_precision,
                                     woe_mean_std, z_score)
from shifu_tpu.pipeline.create import InitProcessor
from shifu_tpu.pipeline.norm import NormalizeProcessor
from shifu_tpu.pipeline.stats import StatsProcessor


def _numeric_cc() -> ColumnConfig:
    cc = ColumnConfig(columnNum=1, columnName="x")
    cc.columnStats.mean = 10.0
    cc.columnStats.stdDev = 2.0
    cc.columnStats.min = 4.0
    cc.columnBinning.binBoundary = [float("-inf"), 8.0, 12.0]
    cc.columnBinning.binCountPos = [5, 10, 5, 2]
    cc.columnBinning.binCountNeg = [20, 10, 5, 1]
    cc.columnBinning.binCountWoe = [0.5, -0.2, -0.9, -1.5]
    cc.columnBinning.binWeightedWoe = [0.4, -0.1, -0.8, -1.2]
    cc.columnBinning.binPosRate = [0.2, 0.5, 0.5, 2 / 3]
    return cc


def _cate_cc() -> ColumnConfig:
    cc = ColumnConfig(columnNum=2, columnName="c", columnType=ColumnType.C)
    cc.columnStats.mean = 0.3
    cc.columnStats.stdDev = 0.1
    cc.columnBinning.binCategory = ["US", "GB"]
    cc.columnBinning.binPosRate = [0.25, 0.5, 0.1]
    cc.columnBinning.binCountWoe = [0.7, -0.3, 0.05]
    cc.columnBinning.binWeightedWoe = [0.6, -0.2, 0.04]
    cc.columnBinning.binCountPos = [10, 20, 1]
    cc.columnBinning.binCountNeg = [30, 20, 9]
    return cc


def test_zscore_clips_at_cutoff():
    v = np.array([10.0, 20.0, -20.0, 11.0])
    z = z_score(v, 10.0, 2.0, 4.0)
    assert z.tolist() == [0.0, 4.0, -4.0, 0.5]
    assert z_score(v, 10.0, 0.0, 4.0).tolist() == [0, 0, 0, 0]


def test_numeric_zscale_missing_is_zero():
    nc = NormalizedColumn(_numeric_cc(), NormType.ZSCALE, 4.0)
    vals = np.array([12.0, np.nan])
    valid = np.array([True, False])
    bidx = np.array([2, 3])
    out = nc.transform(vals, valid, bidx)
    assert out.shape == (2, 1)
    assert out[0, 0] == 1.0   # (12-10)/2
    assert out[1, 0] == 0.0   # missing -> mean -> z=0


def test_numeric_woe_lookup_and_missing_bin():
    nc = NormalizedColumn(_numeric_cc(), NormType.WOE, 4.0)
    out = nc.transform(np.array([5.0, 9.0, np.nan]),
                       np.array([True, True, False]),
                       np.array([0, 1, 3]))
    assert out[:, 0].tolist() == [0.5, -0.2, -1.5]


def test_weight_woe_uses_weighted_table():
    nc = NormalizedColumn(_numeric_cc(), NormType.WEIGHT_WOE, 4.0)
    out = nc.transform(np.array([5.0]), np.array([True]), np.array([0]))
    assert out[0, 0] == 0.4


def test_woe_zscore_standardizes_woe():
    cc = _numeric_cc()
    nc = NormalizedColumn(cc, NormType.WOE_ZSCALE, 4.0)
    wmean, wstd = woe_mean_std(cc, False)
    out = nc.transform(np.array([5.0]), np.array([True]), np.array([0]))
    assert np.isclose(out[0, 0], (0.5 - wmean) / wstd)


def test_categorical_zscale_posrate():
    nc = NormalizedColumn(_cate_cc(), NormType.ZSCALE, 4.0)
    out = nc.transform(np.zeros(3), np.zeros(3, bool), np.array([0, 1, 2]))
    # posrate z-scored with mean=.3 std=.1
    assert np.allclose(out[:, 0], [(0.25 - .3) / .1, (0.5 - .3) / .1, (0.1 - .3) / .1])


def test_categorical_index_norm():
    nc = NormalizedColumn(_cate_cc(), NormType.ZSCALE_INDEX, 4.0)
    out = nc.transform(np.zeros(3), np.zeros(3, bool), np.array([0, 1, 2]))
    assert out[:, 0].tolist() == [0.0, 1.0, 2.0]  # missing -> last index


def test_onehot_includes_missing_bin():
    nc = NormalizedColumn(_cate_cc(), NormType.ONEHOT, 4.0)
    out = nc.transform(np.zeros(2), np.zeros(2, bool), np.array([1, 2]))
    assert out.shape == (2, 3)
    assert out[0].tolist() == [0, 1, 0]
    assert out[1].tolist() == [0, 0, 1]
    assert nc.output_names() == ["c_0", "c_1", "c_2"]


def test_discrete_zscore_uses_bin_left_boundary():
    cc = _numeric_cc()
    nc = NormalizedColumn(cc, NormType.DISCRETE_ZSCALE, 4.0)
    out = nc.transform(np.array([5.0, 9.0]), np.array([True, True]),
                       np.array([0, 1]))
    # bin0 -> min (4.0) -> z=-3 ; bin1 -> boundary 8.0 -> z=-1
    assert np.allclose(out[:, 0], [-3.0, -1.0])


def test_hybrid_numeric_zscore_categorical_woe():
    n = NormalizedColumn(_numeric_cc(), NormType.HYBRID, 4.0)
    c = NormalizedColumn(_cate_cc(), NormType.HYBRID, 4.0)
    out_n = n.transform(np.array([12.0]), np.array([True]), np.array([2]))
    out_c = c.transform(np.zeros(1), np.zeros(1, bool), np.array([0]))
    assert out_n[0, 0] == 1.0
    assert out_c[0, 0] == 0.7


def test_apply_precision():
    x = np.array([0.123456789])
    assert apply_precision(x, PrecisionType.FLOAT7)[0] == 0.1234568
    assert abs(apply_precision(x, PrecisionType.FLOAT16)[0] - 0.1235) < 1e-3
    assert apply_precision(x, PrecisionType.DOUBLE64)[0] == 0.123456789


def test_norm_step_end_to_end(model_set):
    InitProcessor(model_set).run()
    StatsProcessor(model_set).run()
    assert NormalizeProcessor(model_set, params={}).run() == 0
    norm = Shards.open(os.path.join(model_set, "tmp", "NormalizedData"))
    clean = Shards.open(os.path.join(model_set, "tmp", "CleanedData"))
    data = norm.load_all()
    bins = clean.load_all()
    n = len(data["y"])
    assert n > 3500  # rows with unknown tags dropped only
    assert data["x"].shape[0] == n and data["x"].dtype == np.float32
    assert set(np.unique(data["y"])) == {0.0, 1.0}
    assert (data["w"] > 0).all()
    # compact wire format: bins materialize in the narrowest dtype the
    # ColumnConfig bin space fits (uint16 here — one high-cardinality
    # categorical exceeds 256 bins; pure-numeric sets get uint8)
    assert bins["bins"].dtype == np.dtype(clean.schema["binsDtype"])
    assert bins["bins"].dtype.itemsize <= 2
    assert clean.schema["shardRows"] == clean.shard_rows
    assert sum(clean.schema["shardRows"]) == n
    assert bins["bins"].min() >= 0
    # zscaled features should be roughly centered
    assert abs(np.nanmean(data["x"])) < 1.0
    assert norm.schema["outputNames"] == clean.schema["outputNames"]
