"""Streamed, mask-batched variable-selection plane (ops/sensitivity +
dvarsel streaming): parity with the seed per-column loop, whole-block
onehot freezing, -inf out-of-plane ranking, single-fetch host-sync guard,
streamed genetic wrapper, vectorized pareto/correlation pruning, bench
plane registration."""

import json
import os
import subprocess
import sys
from types import SimpleNamespace

import numpy as np
import pytest

import jax

from shifu_tpu.data.shards import Shards
from shifu_tpu.data.streaming import ShardStream
from shifu_tpu.models.nn import NNModelSpec, init_params
from shifu_tpu.ops import sensitivity as sens
from shifu_tpu.parallel.mesh import device_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_shards(td, arrays, shard_rows=700):
    n = len(next(iter(arrays.values())))
    d = arrays["x"].shape[1]
    k = 0
    for s in range(0, n, shard_rows):
        e = min(s + shard_rows, n)
        np.savez(os.path.join(td, f"part-{k:05d}.npz"),
                 **{key: a[s:e] for key, a in arrays.items()})
        k += 1
    with open(os.path.join(td, "schema.json"), "w") as f:
        json.dump({"outputNames": [f"c{i}" for i in range(d)],
                   "columnNums": list(range(d)),
                   "numShards": k, "numRows": n}, f)
    return Shards.open(td)


@pytest.fixture
def sens_data(rng):
    n, d = 3000, 24
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) < 0.3).astype(np.float32)
    return x, y


@pytest.mark.parametrize("hidden", [[8], [8, 4], []])
def test_streamed_matches_per_column_loop(tmp_path, sens_data, hidden):
    """Resident inputs: streamed mask-batched SE/ST MSEs match the seed's
    per-column loop within f32 accumulation tolerance, and the resulting
    top-k SELECTIONS are identical (incl. 0-hidden LR heads and deeper
    nets — the rank-k first-layer shortcut must stay exact)."""
    x, y = sens_data
    d = x.shape[1]
    spec = NNModelSpec(input_dim=d, hidden_nodes=hidden,
                       activations=["tanh"] * max(1, len(hidden)))
    params = init_params(jax.random.PRNGKey(0), spec)
    masks = sens.mask_matrix(d, [[i] for i in range(16)])
    mse_ref, base_ref = sens.per_column_scores(spec, params, x, y, masks)

    shards = _write_shards(str(tmp_path), {"x": x, "y": y})
    # window 1024 does not divide 3000: the padded tail must not leak
    stream = ShardStream(shards, ("x", "y"), 1024)
    mse, base, n_rows = sens.streamed_sensitivity(
        stream, spec, params, masks, mesh=device_mesh(), mask_batch=5)
    assert n_rows == len(y)
    np.testing.assert_allclose(mse, mse_ref, rtol=3e-5, atol=1e-6)
    np.testing.assert_allclose(base, base_ref, rtol=3e-5)
    # identical selections for both SE (mse - base) and ST (scaled)
    k = 6
    assert set(np.argsort(-(mse - base))[:k]) \
        == set(np.argsort(-(mse_ref - base_ref))[:k])


def test_onehot_blocks_freeze_whole(tmp_path, sens_data):
    """A candidate's onehot feature block freezes as ONE unit: the mask
    matrix sets every index of the block, and the streamed scores equal
    the per-column loop freezing the same whole block."""
    x, y = sens_data
    d = x.shape[1]
    blocks = [[0], [1, 2, 3], [4, 5], [6]]
    masks = sens.mask_matrix(d, blocks)
    assert masks.shape == (4, d)
    assert list(np.flatnonzero(masks[1])) == [1, 2, 3]
    assert masks.sum() == 7

    spec = NNModelSpec(input_dim=d, hidden_nodes=[6], activations=["tanh"])
    params = init_params(jax.random.PRNGKey(1), spec)
    mse_ref, base_ref = sens.per_column_scores(spec, params, x, y, masks)
    shards = _write_shards(str(tmp_path), {"x": x, "y": y})
    mse, base, _ = sens.streamed_sensitivity(
        ShardStream(shards, ("x", "y"), 1536), spec, params, masks,
        mesh=device_mesh(), mask_batch=3)
    np.testing.assert_allclose(mse, mse_ref, rtol=3e-5, atol=1e-6)


def test_out_of_plane_scores_minus_inf():
    """Candidates absent from the trained model's feature plane score
    -inf (never selectable), in-plane candidates get SE/ST transforms."""
    from shifu_tpu.config.model_config import FilterBy
    from shifu_tpu.pipeline.varselect import _scores_from_mse

    cands = [SimpleNamespace(columnNum=i) for i in range(4)]
    mse = np.array([0.30, 0.20])
    se = _scores_from_mse(cands, [0, 2], mse, 0.25, FilterBy.SE)
    assert se[0] == pytest.approx(0.05)
    assert se[2] == pytest.approx(-0.05)
    assert se[1] == float("-inf") and se[3] == float("-inf")
    st = _scores_from_mse(cands, [0, 2], mse, 0.25, FilterBy.ST)
    assert st[0] == pytest.approx(0.05 / 0.25)
    # -inf candidates rank strictly last under both transforms
    assert min(se[0], se[2]) > se[1]


def test_single_fetch_and_program_count(tmp_path, sens_data):
    """Host-sync guard: the whole streamed job fetches ONCE, and issues
    exactly ceil(C/B) mask-batch programs per window."""
    from shifu_tpu import obs

    x, y = sens_data
    d = x.shape[1]
    spec = NNModelSpec(input_dim=d, hidden_nodes=[4], activations=["tanh"])
    params = init_params(jax.random.PRNGKey(0), spec)
    C, B = 11, 4                                  # ceil(11/4) = 3 batches
    masks = sens.mask_matrix(d, [[i] for i in range(C)])
    shards = _write_shards(str(tmp_path), {"x": x, "y": y})
    n_windows = -(-len(y) // 1024)
    obs.reset_for_tests()
    obs.set_enabled(True)
    try:
        sens.streamed_sensitivity(
            ShardStream(shards, ("x", "y"), 1024), spec, params, masks,
            mesh=device_mesh(), mask_batch=B)
        reg = obs.get_registry()
        assert reg.counter("varsel.host_syncs").value == 1
        assert reg.counter("varsel.mask_batches").value \
            == n_windows * -(-C // B)
        # both passes observed every window
        assert reg.counter("varsel.windows").value == 2 * n_windows
    finally:
        obs.reset_for_tests()


def test_genetic_streamed_recovers_xor(tmp_path):
    """The streamed genetic wrapper (fitness = minibatch scans over
    prepared windows, one [P,2] fetch per generation) still finds the
    XOR interaction a filter method cannot see."""
    from shifu_tpu.train.dvarsel import (WrapperSettings,
                                         genetic_varselect_streamed)

    rng = np.random.default_rng(3)
    n, d = 2000, 6
    x = rng.normal(size=(n, d)).astype(np.float32)
    xor = (x[:, 0] > 0) ^ (x[:, 1] > 0)
    y = (rng.random(n) < 1 / (1 + np.exp(-3.0 * np.where(xor, 1, -1)))) \
        .astype(np.float32)
    shards = _write_shards(str(tmp_path),
                           {"x": x, "y": y,
                            "w": np.ones(n, np.float32)}, shard_rows=512)
    stream = ShardStream(shards, ("x", "y", "w"), 1024)
    scores, history = genetic_varselect_streamed(
        stream, {ci: [ci] for ci in range(d)},
        WrapperSettings(n_select=2, population=12, generations=4,
                        epochs=40, seed=2))
    top2 = sorted(scores, key=scores.get, reverse=True)[:2]
    assert set(top2) == {0, 1}, scores
    assert history[-1]["best"] <= history[0]["best"] + 1e-6


def test_pareto_vectorized_matches_reference(rng):
    """The broadcast domination matrix reproduces the seed's per-point
    O(n^2) Python scan exactly."""
    from shifu_tpu.pipeline.varselect import pareto_front_ranks

    def reference(ks, iv):
        n = len(ks)
        remaining = np.arange(n)
        ranks = np.zeros(n, int)
        r = 0
        while len(remaining):
            k, v = ks[remaining], iv[remaining]
            dominated = np.zeros(len(remaining), bool)
            for i in range(len(remaining)):
                dominated[i] = np.any((k >= k[i]) & (v >= v[i]) &
                                      ((k > k[i]) | (v > v[i])))
            front = remaining[~dominated]
            ranks[front] = r
            remaining = remaining[dominated]
            r += 1
        return ranks

    for n in (1, 2, 17, 100):
        ks = rng.random(n)
        iv = rng.random(n)
        # include ties: duplicated points must co-rank
        if n > 4:
            ks[3], iv[3] = ks[1], iv[1]
        np.testing.assert_array_equal(pareto_front_ranks(ks, iv),
                                      reference(ks, iv))


def test_correlation_prune_vectorized(tmp_path):
    """Matrix-row masking keeps the seed semantics: drop the lower-KS
    member of any pair above the threshold; columns missing from the
    matrix always survive."""
    from shifu_tpu.pipeline.varselect import VarSelectProcessor

    names = ["a", "b", "c", "d"]
    mat = np.eye(4)
    mat[0, 1] = mat[1, 0] = 0.95       # a-b highly correlated
    mat[2, 3] = mat[3, 2] = 0.10
    corr = tmp_path / "correlation.csv"
    with open(corr, "w") as f:
        f.write("," + ",".join(names) + "\n")
        for i, nm in enumerate(names):
            f.write(nm + "," + ",".join(f"{v:.4f}" for v in mat[i]) + "\n")

    def col(name, ks):
        return SimpleNamespace(columnName=name,
                               columnStats=SimpleNamespace(ks=ks))

    proc = VarSelectProcessor.__new__(VarSelectProcessor)
    proc.paths = SimpleNamespace(correlation_path=str(corr))
    cols = [col("a", 0.9), col("b", 0.8), col("c", 0.7), col("d", 0.6),
            col("zz_not_in_matrix", 0.5)]
    vs = SimpleNamespace(correlationThreshold=0.8)
    kept, dropped = proc._correlation_prune(cols, vs)
    assert [c.columnName for c in kept] == ["a", "c", "d",
                                           "zz_not_in_matrix"]
    assert dropped == 1


def test_bench_help_lists_varsel_plane():
    """CI smoke: the varsel bench plane is registered in bench.py."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--help"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    assert "varsel" in out.stdout


def test_bench_unknown_plane_names_varsel():
    """run_benchmark's unknown-plane error enumerates the registered
    planes (the handshake for plane registration)."""
    from shifu_tpu.bench import run_benchmark
    with pytest.raises(ValueError, match="varsel"):
        run_benchmark(plane="bogus")
