"""Tests for the ``shifu_tpu/obs`` telemetry subsystem: span
nesting/ordering, JSONL schema round-trip, registry aggregation (host-side
only — recording from inside ``jit`` must fail), zero-output no-op mode,
the disabled-path overhead guard, and the bench/obs schema handshake."""

import json
import logging
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from shifu_tpu import obs

pytestmark = pytest.mark.obs        # `pytest -m obs` collects this suite


@pytest.fixture
def telemetry():
    """Telemetry force-enabled with clean collector/registry; restores
    the (disabled) env default afterwards so other tests stay no-op."""
    obs.reset_for_tests()
    obs.set_enabled(True)
    yield obs
    obs.reset_for_tests()


@pytest.fixture
def telemetry_off():
    obs.reset_for_tests()
    obs.set_enabled(False)
    yield obs
    obs.reset_for_tests()


# ------------------------------------------------------------------ spans
def test_span_nesting_and_ordering(telemetry):
    with obs.span("root", kind="step") as root:
        with obs.span("child_a"):
            obs.event("tick", i=1)
        with obs.span("child_b") as b:
            with obs.span("grandchild"):
                pass
            b.set(rows=10)
    recs = obs.pending_records()
    spans = {r["name"]: r for r in recs if r["kind"] == "span"}
    assert set(spans) == {"root", "child_a", "child_b", "grandchild"}
    assert spans["root"]["parent"] is None
    assert spans["child_a"]["parent"] == spans["root"]["id"]
    assert spans["child_b"]["parent"] == spans["root"]["id"]
    assert spans["grandchild"]["parent"] == spans["child_b"]["id"]
    assert spans["child_b"]["attrs"]["rows"] == 10
    # children close before parents: record order is completion order
    names = [r["name"] for r in recs if r["kind"] == "span"]
    assert names.index("child_a") < names.index("root")
    assert names.index("grandchild") < names.index("child_b")
    # a parent's duration bounds its children's sum
    assert spans["root"]["dur_s"] >= \
        spans["child_a"]["dur_s"] + spans["child_b"]["dur_s"] - 1e-6
    ev = [r for r in recs if r["kind"] == "event"]
    assert ev[0]["name"] == "tick"
    assert ev[0]["parent"] == spans["child_a"]["id"]


def test_span_error_marked(telemetry):
    with pytest.raises(ValueError):
        with obs.span("boom"):
            raise ValueError("x")
    (rec,) = [r for r in obs.pending_records() if r["kind"] == "span"]
    assert rec["attrs"]["error"] == "ValueError"


def test_span_fence_blocks_values(telemetry, monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_TELEMETRY_FENCE", "1")
    obs.set_enabled(True)            # re-derive the fence cache
    assert obs.fencing_enabled()
    with obs.span("fenced") as sp:
        out = sp.fence(jnp.ones((4,)) * 2.0)
    np.testing.assert_array_equal(np.asarray(out), 2.0 * np.ones(4))


# ------------------------------------------------------- JSONL round-trip
def test_jsonl_schema_roundtrip(telemetry, tmp_path):
    with obs.span("STATS", kind="step") as sp:
        with obs.span("pass1", rows=1000):
            obs.counter("stats.rows").inc(1000)
        sp.set(exit_code=0)
    obs.gauge("stats.rows_per_sec").set(12345.6)
    obs.histogram("epoch_s").observe(0.5)
    path = str(tmp_path / "telemetry" / "trace.jsonl")
    assert obs.flush(path, step="STATS")
    lines = [json.loads(line) for line in open(path)]
    assert lines[0]["kind"] == "meta"
    assert lines[0]["schema_version"] == obs.SCHEMA_VERSION
    assert lines[0]["step"] == "STATS"
    kinds = {ln["kind"] for ln in lines}
    assert kinds == {"meta", "span", "metric"}
    metrics = {ln["name"]: ln for ln in lines if ln["kind"] == "metric"}
    assert metrics["stats.rows"]["type"] == "counter"
    assert metrics["stats.rows"]["value"] == 1000
    assert metrics["epoch_s"]["count"] == 1
    # flush drained: a second flush adds an empty block, not duplicates
    assert obs.flush(path, step="EMPTY")
    lines2 = [json.loads(line) for line in open(path)]
    assert sum(1 for ln in lines2 if ln["kind"] == "span") == \
        sum(1 for ln in lines if ln["kind"] == "span")
    # the report renders it
    from shifu_tpu.obs.report import render_telemetry
    text = render_telemetry(str(tmp_path))
    assert "STATS" in text and "pass1" in text
    assert "stats.rows" in text and "rows/s" in text


# ---------------------------------------------------------------- registry
def test_registry_aggregation_host_side(telemetry):
    @jax.jit
    def f(x):
        return (x * 2).sum()

    total = 0.0
    for i in range(3):
        v = float(f(jnp.ones((4,)) * (i + 1)))   # value-forced fetch
        obs.counter("work").inc(v)
        obs.histogram("step_val").observe(v)
        total += v
    snap = {m["name"]: m for m in obs.snapshot()}
    assert snap["work"]["value"] == total
    assert snap["step_val"]["count"] == 3
    assert snap["step_val"]["min"] == 8.0 and snap["step_val"]["max"] == 24.0


def test_registry_rejects_tracers(telemetry):
    """Metrics are host-side only: recording from INSIDE jit passes a
    tracer, which the float() coercion must reject loudly instead of
    silently burying a tracer in the registry."""
    @jax.jit
    def bad(x):
        obs.counter("from_jit").inc(x)     # x is a tracer here
        return x

    with pytest.raises(Exception):         # ConcretizationTypeError
        bad(jnp.ones(()))


def test_registry_gauge_high_water_and_type_guard(telemetry):
    g = obs.gauge("hbm")
    g.set_max(10)
    g.set_max(5)
    assert obs.snapshot()[0]["value"] == 10
    with pytest.raises(TypeError):
        obs.counter("hbm")                  # name already bound to a gauge


# ----------------------------------------------------------- no-op mode
def test_disabled_mode_writes_nothing(telemetry_off, tmp_path):
    assert obs.span("x") is obs.span("y")    # shared null singleton
    with obs.span("root") as sp:
        sp.set(a=1).fence(jnp.ones(3))
        obs.event("tick")
        obs.counter("c").inc()
        obs.gauge("g").set(1)
        obs.histogram("h").observe(1)
    assert obs.pending_records() == []
    assert obs.snapshot() == []
    assert obs.live_spans() == []
    path = str(tmp_path / "telemetry" / "trace.jsonl")
    assert obs.flush(path) is False
    assert not os.path.exists(os.path.dirname(path))
    # v2 observability plane: every factory is a None-returning no-op
    # when disabled — no thread, no file, no directory
    assert obs.start_heartbeat(str(tmp_path / "health"), step="X") is None
    assert obs.start_exporter(str(tmp_path / "telemetry")) is None
    assert obs.start_drift_monitor([]) is None
    assert not os.path.exists(str(tmp_path / "health"))
    assert not os.path.exists(str(tmp_path / "telemetry"))
    # v6 cost plane: analytic-model recording is a no-op too
    obs.record_model_launch("pallas.hist", rows=8, n_feat=2, n_bins=4,
                            n_nodes=1)
    assert obs.cost_snapshot() == []


def test_disabled_processor_writes_no_telemetry_files(telemetry_off,
                                                      model_set):
    from shifu_tpu.pipeline.create import InitProcessor
    assert InitProcessor(model_set).run() == 0
    assert not os.path.exists(os.path.join(model_set, "telemetry"))


def test_enabled_processor_writes_root_span(telemetry, model_set):
    from shifu_tpu.pipeline.create import InitProcessor
    assert InitProcessor(model_set).run() == 0
    trace = os.path.join(model_set, "telemetry", "trace.jsonl")
    assert os.path.isfile(trace)
    lines = [json.loads(line) for line in open(trace)]
    spans = {ln["name"]: ln for ln in lines if ln["kind"] == "span"}
    assert "INIT" in spans and spans["INIT"]["parent"] is None
    assert spans["INIT"]["attrs"]["exit_code"] == 0
    assert spans["setup"]["parent"] == spans["INIT"]["id"]
    assert spans["process"]["parent"] == spans["INIT"]["id"]
    from shifu_tpu.obs.report import render_telemetry
    assert "INIT" in render_telemetry(model_set)


# ------------------------------------------------------- trainer metrics
def test_nn_trainer_emits_per_epoch_events(telemetry):
    from shifu_tpu.models.nn import NNModelSpec
    from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble

    rng = np.random.default_rng(0)
    n, d = 64, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) < 0.4).astype(np.float32)
    w = np.ones((1, n), np.float32)
    spec = NNModelSpec(input_dim=d, hidden_nodes=[4],
                       activations=["tanh"])
    settings = TrainSettings(optimizer="ADAM", learning_rate=0.01,
                             epochs=3)
    train_ensemble(x, y, w, w, spec, settings)
    epochs = [r for r in obs.pending_records()
              if r["kind"] == "event" and r["name"] == "epoch"]
    assert len(epochs) == 3
    assert epochs[0]["attrs"]["trainer"] == "nn"
    assert epochs[-1]["attrs"]["epoch"] == 2
    assert epochs[0]["attrs"]["rows"] == n
    assert epochs[0]["attrs"]["rows_per_sec"] > 0
    snap = {m["name"]: m for m in obs.snapshot()}
    assert snap["train.epochs"]["value"] == 3
    assert snap["train.epoch_s"]["count"] == 3


# -------------------------------------------------- overhead / handshake
def test_disabled_telemetry_overhead_within_noise(telemetry_off):
    """CI guard: with telemetry disabled, an instrumented micro-train
    loop must run within noise of the same loop uninstrumented — the
    no-op span/instrument path may not add per-step work that survives
    timing jitter (generous 1.5x bound, best-of-5 each)."""
    @jax.jit
    def step(p, x):
        return p - 0.01 * (p * x).sum()

    x = jnp.ones((256,))
    p = jnp.ones(())
    step(p, x).block_until_ready()          # compile outside the window

    def plain(p):
        for _ in range(200):
            p = step(p, x)
        return float(p)

    def instrumented(p):
        for i in range(200):
            with obs.span("train_step", i=i) as sp:
                # the v2 plane's per-window hot-path additions: the
                # ingest prep/wait spans (null singletons when off) —
                # they must cost one call + one branch, nothing more
                with obs.span("ingest.window_prep", window=i):
                    p = sp.fence(step(p, x))
                obs.counter("steps").inc()
                obs.histogram("loss").observe(0.0)
        return float(p)

    def best(fn):
        out = []
        for _ in range(5):
            t0 = time.perf_counter()
            fn(p)
            out.append(time.perf_counter() - t0)
        return min(out)

    t_plain, t_inst = best(plain), best(instrumented)
    assert t_inst <= t_plain * 1.5 + 1e-3, \
        (f"disabled-telemetry overhead too high: {t_inst:.4f}s vs "
         f"{t_plain:.4f}s uninstrumented")
    assert obs.pending_records() == []       # and truly recorded nothing


def test_disabled_costed_jit_is_bare_jit(telemetry_off):
    """The cost plane rides the same zero-overhead guarantee: telemetry
    off at wrap time ⇒ costed_jit returns THE bare jax.jit callable (no
    wrapper frames), the lazy (module-scope) form costs one branch per
    call, and neither writes a cost record."""
    from shifu_tpu.obs import costs

    def f(x):
        return (x * 2.0).sum()

    bare = costs.costed_jit("test.bare", f)
    # not a wrapper: the exact type jax.jit returns
    assert type(bare) is type(jax.jit(f))
    assert not isinstance(bare, costs.CostedJit)
    x = jnp.ones((256,))
    float(bare(x))
    assert costs.cost_snapshot() == []       # no registry writes

    lz = costs.costed_jit("test.lazy", f, lazy=True)
    jb = jax.jit(f)
    float(lz(x)), float(jb(x))               # compile both outside timing

    def best(fn):
        out = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(200):
                fn(x)
            out.append(time.perf_counter() - t0)
        return min(out)

    t_plain, t_lazy = best(jb), best(lz)
    assert t_lazy <= t_plain * 1.5 + 1e-3, \
        (f"disabled lazy costed_jit overhead too high: {t_lazy:.4f}s vs "
         f"{t_plain:.4f}s bare jit")
    assert costs.cost_snapshot() == []
    assert obs.pending_records() == []


def test_bench_schema_matches_obs():
    """bench.py must fail loudly when its emitted schema version and the
    obs schema diverge — this pin is the loud failure's test double.
    v3 added the varsel_* extras (streamed mask-batched sensitivity
    plane); v4 the disk-tail super-batch round (tail_* extras +
    train.tail_sweeps / tail_repairs counters); v5 the observability
    plane v2 (tid on span records, drift.* gauges, health heartbeats,
    OpenMetrics snapshots, bench --compare); v6 the device
    cost-attribution plane (cost records per executable, *_mfu /
    *_achieved_bw extras, xla.recompiles sentinel, --compare auto
    mode): the version must be current AND the planes registered, so a
    schema bump cannot land without the emissions being
    re-validated."""
    from shifu_tpu.bench import (BENCH_TELEMETRY_SCHEMA, _mfu_extras,
                                 bench_gbt_streamed_tail, bench_varsel,
                                 is_tracked_throughput,
                                 resolve_compare_paths, run_compare)
    assert BENCH_TELEMETRY_SCHEMA == obs.SCHEMA_VERSION
    assert BENCH_TELEMETRY_SCHEMA >= 6          # cost-attribution era
    assert callable(bench_varsel)
    assert callable(bench_gbt_streamed_tail)
    assert callable(run_compare)                # the BENCH_r0N reader
    # v5 surfaces exist and share the schema constant
    from shifu_tpu.obs import drift, exporter, health, timeline
    assert callable(timeline.to_trace_events)
    assert callable(exporter.render_openmetrics)
    assert callable(health.start_heartbeat)
    assert callable(drift.start_drift_monitor)
    # v6 surfaces: the cost plane + its bench emissions
    from shifu_tpu.obs import costs, utilization
    assert callable(costs.costed_jit)
    assert callable(costs.record_executable)
    assert callable(utilization.render_utilization)
    assert callable(_mfu_extras)
    assert callable(resolve_compare_paths)      # --compare auto mode
    # the compare gates the v6 utilization extras, not just throughputs
    assert is_tracked_throughput("nn_train_mfu")
    assert is_tracked_throughput("wdl_train_achieved_bw")
    assert not is_tracked_throughput("nn_train_mfu_error")


def test_bench_refuses_schema_mismatch(monkeypatch):
    import shifu_tpu.bench as bench_mod
    monkeypatch.setattr(bench_mod, "BENCH_TELEMETRY_SCHEMA",
                        obs.SCHEMA_VERSION + 1)
    with pytest.raises(RuntimeError, match="disagrees"):
        bench_mod.run_benchmark()


# ----------------------------------------------------------------- logging
def test_library_logging_null_handler():
    """Programmatic use must neither print nor warn 'no handlers':
    the package root logger carries a NullHandler."""
    lg = logging.getLogger("shifu_tpu")
    assert any(isinstance(h, logging.NullHandler) for h in lg.handlers)


def test_configure_logging_honors_env(monkeypatch):
    import shifu_tpu
    monkeypatch.setenv("SHIFU_TPU_LOG", "WARNING")
    root_before = logging.getLogger().level
    try:
        shifu_tpu.configure_logging(verbose=True)   # env beats -v
        assert logging.getLogger("shifu_tpu").level == logging.WARNING
    finally:
        monkeypatch.delenv("SHIFU_TPU_LOG")
        logging.getLogger().setLevel(root_before)
        logging.getLogger("shifu_tpu").setLevel(logging.NOTSET)
