"""Overload-protection suite (tier-1-fast: in-process batcher pumps,
injectable clocks, loopback stub backends — zero real sleeps on the
state-machine paths).

Covers the overload tentpole's acceptance surface: bounded admission
(coded 429 + drain-rate Retry-After, oversized bursts still admitted
into an EMPTY queue), deadline propagation (expired tickets shed in
``pump()`` BEFORE pad/launch with a coded 504, ``wait(timeout)``
cancels so abandoned work is never scored), the router's retry budget
(exhaustion propagates a coded 429 instead of amplifying overload),
the per-replica circuit breaker (open -> half-open single probe ->
close), hedged dispatch (first response wins, a first ERROR does not),
brownout degradation (asymmetric hysteresis; policy applied and fully
restored), and the ``serve:admit`` die-during-shed drill (queue depth
and SLO shed accounting stay consistent when the shed path itself
dies).
"""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import jax

from shifu_tpu import faults, obs
from shifu_tpu.config import environment
from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                 init_params)
from shifu_tpu.serve import AOTScorer, MicroBatcher, ServeServer
from shifu_tpu.serve.overload import (CircuitBreaker,
                                      DeadlineExceededError,
                                      OverloadedError, RetryBudget)
from shifu_tpu.serve.router import UP, ServeRouter
from shifu_tpu.serve.server import (BROWNOUT_DELAY_FACTOR,
                                    QUEUE_BUILDUP_BUCKETS, _make_handler)

pytestmark = pytest.mark.serve


@pytest.fixture(autouse=True)
def _clean_env():
    environment.reset_for_tests()
    faults.reset_for_tests()
    yield
    environment.reset_for_tests()
    faults.reset_for_tests()
    obs.set_enabled(False)


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _nn_models(n=3, n_features=8, hidden=(8,), seed0=0):
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=list(hidden),
                       activations=["relu"] * len(hidden))
    return [IndependentNNModel(spec, init_params(
        jax.random.PRNGKey(seed0 + i), spec)) for i in range(n)]


def _batcher(clk, max_delay_s=0.002, slo=None, **props):
    for k, v in props.items():
        environment.set_property(k, str(v))
    scorer = AOTScorer(_nn_models(), buckets=(1, 4))
    scorer.warm(launch=False)
    return MicroBatcher(lambda: scorer, max_delay_s=max_delay_s,
                        clock=clk, slo=slo), scorer


# -------------------------------------------------------- bounded admission
def test_admission_cap_rejects_with_coded_retry_after():
    """At the cap, submit fast-fails with a coded ``OverloadedError``
    carrying a positive Retry-After; the queue is untouched and already
    queued work still completes."""
    clk = FakeClock()
    b, _ = _batcher(clk, **{"shifu.serve.maxQueueRows": 4})
    rng = np.random.default_rng(0)
    t_ok = b.submit_burst(rng.normal(size=(4, 8)).astype(np.float32))
    with pytest.raises(OverloadedError) as ei:
        b.submit_burst(rng.normal(size=(1, 8)).astype(np.float32))
    assert ei.value.code == "overloaded"
    assert ei.value.retry_after_s > 0.0
    assert b.queue_depth == 4
    assert b.stats["shed_overload"] == 1
    assert b.pump() == 4 and t_ok.wait(1.0).shape == (4,)
    # queue drained: admission opens again
    assert b.submit_burst(rng.normal(size=(2, 8))
                          .astype(np.float32)).n == 2


def test_oversized_burst_admitted_into_empty_queue():
    """A burst larger than the cap is still serviceable when the queue
    is EMPTY (it chunks through the top bucket) — the cap bounds queue
    WAIT, it must not make big requests unservable."""
    clk = FakeClock()
    b, _ = _batcher(clk, **{"shifu.serve.maxQueueRows": 4})
    rng = np.random.default_rng(1)
    t = b.submit_burst(rng.normal(size=(9, 8)).astype(np.float32))
    while b.queue_depth:
        clk.t += 0.01               # age the remnant past max_delay
        b.pump()
    assert t.wait(1.0).shape == (9,)
    assert b.stats["shed_overload"] == 0


def test_retry_after_tracks_drain_rate():
    """Once launches establish a drain-rate EWMA, Retry-After ~=
    queued_rows / drain_rate instead of the max-delay fallback."""
    clk = FakeClock()
    b, _ = _batcher(clk, **{"shifu.serve.maxQueueRows": 4})
    rng = np.random.default_rng(2)
    # two spaced launches: 4 rows per 0.01s -> ~400 rows/s drain
    for _ in range(2):
        b.submit_burst(rng.normal(size=(4, 8)).astype(np.float32))
        clk.t += 0.01
        b.pump()
    b.submit_burst(rng.normal(size=(4, 8)).astype(np.float32))
    with pytest.raises(OverloadedError) as ei:
        b.submit_burst(rng.normal(size=(1, 8)).astype(np.float32))
    assert ei.value.retry_after_s == pytest.approx(4 / 400.0, rel=0.6)


# ------------------------------------------------------ deadline propagation
def test_expired_ticket_sheds_before_launch_with_coded_error():
    """A ticket whose deadline passed before its rows launched is shed
    in ``pump()`` with a coded ``DeadlineExceededError`` — never scored,
    never silent — while fresh work in the same pump still launches."""
    slo = obs.SLOTracker(p99_ms=50.0)
    clk = FakeClock()
    b, _ = _batcher(clk, slo=slo,
                    **{"shifu.serve.requestDeadlineMs": 5})
    rng = np.random.default_rng(3)
    t_old = b.submit_burst(rng.normal(size=(2, 8)).astype(np.float32))
    clk.t += 0.006                      # past the 5 ms deadline
    t_new = b.submit_burst(rng.normal(size=(2, 8)).astype(np.float32))
    batches0 = b.stats["batches"]
    clk.t += 0.003                      # t_new aged past max_delay only
    assert b.pump() == 2                # t_new launches, t_old sheds
    with pytest.raises(DeadlineExceededError) as ei:
        t_old.wait(1.0)
    assert ei.value.code == "deadline_exceeded"
    assert t_new.wait(1.0).shape == (2,)
    assert b.stats["shed_expired"] == 1
    assert b.stats["batches"] == batches0 + 1   # expired rows: NO launch
    assert slo.shed_total == 1
    assert b.queue_depth == 0


def test_deadline_ms_argument_overrides_property_default():
    clk = FakeClock()
    b, _ = _batcher(clk, **{"shifu.serve.requestDeadlineMs": 10000})
    rng = np.random.default_rng(4)
    t = b.submit_burst(rng.normal(size=(1, 8)).astype(np.float32),
                       deadline_ms=2.0)
    assert t.deadline == pytest.approx(clk.t + 0.002)
    clk.t += 0.004
    b.pump()
    with pytest.raises(DeadlineExceededError):
        t.wait(1.0)


def test_wait_timeout_cancels_and_pump_sheds():
    """Satellite: ``Ticket.wait(timeout)`` marks the ticket cancelled —
    the client is gone, so ``pump()`` sheds its rows instead of scoring
    into the void (counted ``serve.cancelled``)."""
    slo = obs.SLOTracker(p99_ms=50.0)
    clk = FakeClock()
    b, _ = _batcher(clk, slo=slo)
    rng = np.random.default_rng(5)
    t = b.submit_burst(rng.normal(size=(2, 8)).astype(np.float32))
    with pytest.raises(TimeoutError):
        t.wait(0.005)                   # nobody pumping: times out
    assert t.cancelled
    clk.t += 0.01
    assert b.pump() == 0                # shed, not scored
    assert b.stats["cancelled"] == 1 and b.stats["batches"] == 0
    assert slo.shed_total == 1
    assert b.queue_depth == 0


# ------------------------------------------------------------- retry budget
def test_retry_budget_spends_and_refills_on_success():
    rb = RetryBudget(frac=0.5, initial=1.0, cap=2.0)
    assert rb.try_retry() is True
    assert rb.try_retry() is False      # drained
    for _ in range(2):
        rb.on_success()                 # 2 x 0.5 = one token back
    assert rb.try_retry() is True
    assert rb.try_retry() is False
    for _ in range(100):
        rb.on_success()
    assert rb.tokens == 2.0             # capped


def test_retry_budget_frac_zero_disables_retries():
    environment.set_property("shifu.serve.retryBudgetFrac", "0")
    rb = RetryBudget()
    assert rb.try_retry() is False      # no cold-start allowance either


# ----------------------------------------------------------- circuit breaker
def test_breaker_open_halfopen_close_cycle():
    brk = CircuitBreaker(threshold=2, cooldown_s=1.0)
    assert brk.allow(0.0)
    assert brk.record_failure(0.0) is False
    assert brk.record_failure(0.0) is True      # threshold: OPEN edge
    assert brk.state == "open" and brk.opens == 1
    assert not brk.allow(0.5)                   # cooling down
    assert brk.allow(1.5)                       # the half-open probe
    assert brk.state == "half_open"
    assert not brk.allow(1.6)                   # ONE probe at a time
    brk.record_success()
    assert brk.state == "closed" and brk.allow(1.7)


def test_breaker_failed_probe_reopens():
    brk = CircuitBreaker(threshold=1, cooldown_s=1.0)
    assert brk.record_failure(0.0) is True
    assert brk.allow(1.5)                       # probe
    assert brk.record_failure(1.5) is True      # failed probe: re-OPEN
    assert brk.state == "open" and brk.opens == 2
    assert not brk.allow(2.0)                   # fresh cooldown from 1.5
    assert brk.allow(2.6)


def test_breaker_threshold_zero_never_opens():
    brk = CircuitBreaker(threshold=0)
    for _ in range(10):
        assert brk.record_failure(0.0) is False
    assert brk.state == "closed" and brk.allow(0.0)


# ------------------------------------------------- router overload behavior
def _stub_backend(name, delay_s=0.0, status=200):
    """A loopback worker stub: /healthz + /score (optionally slow or
    erroring) — real HTTP transport without a real model."""

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _reply(self, code, doc):
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):                       # noqa: N802
            self._reply(200, {"state": "serving", "accepts_raw": False,
                              "needs_bins": False, "generation": 0,
                              "alerting": False})

        def do_POST(self):                      # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            if delay_s:
                time.sleep(delay_s)
            self._reply(status, {"scores": [0.5], "replica": name})

        def log_message(self, fmt, *args):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def test_retry_budget_exhaustion_propagates_coded_429():
    """With every replica's transport dead, the router spends its retry
    budget then sheds with a coded ``OverloadedError`` (HTTP 429 at the
    front door) instead of retrying forever — retry amplification is
    the collapse mechanism the budget caps."""
    environment.set_property("shifu.serve.breakerFailures", "0")
    obs.set_enabled(True)
    router = ServeRouter(poll_ms=100, stale_s=60)
    dead = _stub_backend("dead")
    port = dead.server_address[1]
    dead.shutdown()
    dead.server_close()                 # connection refused from now on
    r = router.add_backend("dead", port)
    r.state = UP
    before = obs.counter("serve.fleet_retry_denied").value
    try:
        with pytest.raises(OverloadedError) as ei:
            router.score({"records": [{}]}, timeout=30.0)
        assert ei.value.code == "overloaded"
        assert obs.counter("serve.fleet_retry_denied").value == before + 1
    finally:
        router.stop(kill_workers=False)


def test_breaker_opens_after_transport_failures_and_probes_later():
    """Consecutive transport failures open the dead replica's breaker
    (counted ``serve.fleet_breaker_opens``); ``_pick`` then refuses it
    until the cooldown, after which exactly one half-open probe goes
    through."""
    from shifu_tpu.serve.overload import DEFAULT_BREAKER_COOLDOWN_S
    obs.set_enabled(True)
    clk = FakeClock()
    router = ServeRouter(poll_ms=100, stale_s=60, clock=clk)
    dead = _stub_backend("dead")
    port = dead.server_address[1]
    dead.shutdown()
    dead.server_close()
    r = router.add_backend("dead", port)
    r.state = UP
    before = obs.counter("serve.fleet_breaker_opens").value
    try:
        with pytest.raises((RuntimeError, OverloadedError)):
            router.score({"records": [{}]}, timeout=5.0)
        assert r.breaker.state == "open"
        assert r.doc()["breaker"] == "open"
        assert obs.counter("serve.fleet_breaker_opens").value \
            == before + 1
        assert router._pick() is None           # refused while open
        clk.t += DEFAULT_BREAKER_COOLDOWN_S + 0.1
        assert router._pick() is r              # the half-open probe
        assert r.breaker.state == "half_open"
        assert router._pick() is None           # one probe at a time
        r.breaker.record_success()
        assert router._pick() is r
    finally:
        router.stop(kill_workers=False)


def test_hedged_dispatch_fires_and_first_response_wins():
    """With the hedge armed and the primary slow past the hedge delay,
    a second dispatch fires on a peer and the FAST answer wins (counted
    ``serve.fleet_hedges``); the slow primary's answer is dropped."""
    environment.set_property("shifu.serve.hedgeMs", "40")
    obs.set_enabled(True)
    router = ServeRouter(poll_ms=100, stale_s=60)
    slow = _stub_backend("slow", delay_s=0.5)
    fast = _stub_backend("fast", delay_s=0.0)
    rs = router.add_backend("slow", slow.server_address[1])
    rf = router.add_backend("fast", fast.server_address[1])
    rs.state = rf.state = UP
    rs.requests = 0
    rf.requests = 1                     # tie-break: slow picked first
    before = obs.counter("serve.fleet_hedges").value
    try:
        t0 = time.monotonic()
        out = router.score({"records": [{}]}, timeout=10.0)
        assert out["replica"] == "fast"
        assert time.monotonic() - t0 < 0.45     # did not wait for slow
        assert obs.counter("serve.fleet_hedges").value == before + 1
    finally:
        router.stop(kill_workers=False)
        for httpd in (slow, fast):
            httpd.shutdown()
            httpd.server_close()


def test_hedge_error_does_not_win_while_peer_in_flight():
    """A first ERROR must not beat a good in-flight hedge: the 500 from
    the sick primary is held and the healthy peer's answer returns."""
    environment.set_property("shifu.serve.hedgeMs", "40")
    environment.set_property("shifu.serve.breakerFailures", "0")
    router = ServeRouter(poll_ms=100, stale_s=60)
    sick = _stub_backend("sick", delay_s=0.1, status=500)
    ok = _stub_backend("ok", delay_s=0.15)
    r0 = router.add_backend("sick", sick.server_address[1])
    r1 = router.add_backend("ok", ok.server_address[1])
    r0.state = r1.state = UP
    r0.requests, r1.requests = 0, 1
    try:
        out = router.score({"records": [{}]}, timeout=10.0)
        assert out["replica"] == "ok"
    finally:
        router.stop(kill_workers=False)
        for httpd in (sick, ok):
            httpd.shutdown()
            httpd.server_close()


# ------------------------------------------------------------ HTTP surface
def test_http_429_retry_after_and_504_deadline_coded():
    """The worker front door maps the coded errors: admission reject ->
    429 + Retry-After header, expired-before-launch -> 504 — both carry
    machine-readable ``error`` codes."""
    import http.client
    environment.set_property("shifu.serve.maxQueueRows", "4")
    srv = ServeServer(models=_nn_models(), key="o", buckets=(1, 4),
                      max_delay_ms=1.0)
    rng = np.random.default_rng(6)
    # NOT started: the queue holds, so the cap binds deterministically
    srv.batcher.submit_burst(rng.normal(size=(4, 8)).astype(np.float32))
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(srv))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    conn = http.client.HTTPConnection("127.0.0.1",
                                      httpd.server_address[1], timeout=10)
    try:
        body = json.dumps({"rows": [[0.0] * 8]})
        conn.request("POST", "/score", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        assert resp.status == 429
        assert doc["error"] == "overloaded"
        assert doc["retry_after_ms"] > 0
        assert int(resp.getheader("Retry-After")) >= 1
        # deadline shed: start the worker, send an already-hopeless
        # budget — the pump sheds it before launch, coded 504
        srv.batcher.drain()
        srv.start()
        conn.request("POST", "/score", body=body,
                     headers={"Content-Type": "application/json",
                              "X-Shifu-Deadline-Ms": "0.001"})
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        assert resp.status == 504
        assert doc["error"] == "deadline_exceeded"
    finally:
        conn.close()
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


# ------------------------------------------------------------ brownout mode
def _brownout_server(tmp_path):
    return ServeServer(model_set_dir=str(tmp_path), models=_nn_models(),
                       key="b", buckets=(1, 4), max_delay_ms=2.0)


def test_brownout_enter_exit_hysteresis_applies_and_restores(tmp_path):
    """Queue buildup sustained for 2 checks flips brownout (shrunk
    flush deadline, sampling/refinement suspended); 3 healthy checks
    restore every saved setting — asymmetric hysteresis, no flapping."""
    obs.set_enabled(True)
    srv = _brownout_server(tmp_path)
    b = srv.batcher
    b.trace_sample_rate = 0.25
    b.refine_every = 500
    delay0 = b.max_delay_s
    rng = np.random.default_rng(7)
    n = QUEUE_BUILDUP_BUCKETS * 4 + 1
    b.submit_burst(rng.normal(size=(n, 8)).astype(np.float32))
    assert srv.check_brownout() == "normal"     # 1 stressed check: hold
    assert srv.check_brownout() == "brownout"   # 2nd: flip
    assert b.max_delay_s == pytest.approx(delay0 * BROWNOUT_DELAY_FACTOR)
    assert b.trace_sample_rate == 0.0 and b.refine_every == 0
    snap = {m["name"]: m for m in obs.snapshot()}
    assert snap["serve.mode"]["value"] == 1.0
    assert snap["serve.brownouts"]["value"] == 1.0
    b.drain()
    assert srv.check_brownout() == "brownout"   # healthy x1
    assert srv.check_brownout() == "brownout"   # healthy x2
    assert srv.check_brownout() == "normal"     # healthy x3: restore
    assert b.max_delay_s == pytest.approx(delay0)
    assert b.trace_sample_rate == 0.25 and b.refine_every == 500
    snap = {m["name"]: m for m in obs.snapshot()}
    assert snap["serve.mode"]["value"] == 0.0
    # one stressed blip never flaps the mode back
    b.submit_burst(rng.normal(size=(n, 8)).astype(np.float32))
    assert srv.check_brownout() == "normal"
    b.drain()
    assert srv.check_brownout() == "normal"


def test_brownout_property_disables_governor(tmp_path):
    environment.set_property("shifu.serve.brownout", "false")
    srv = _brownout_server(tmp_path)
    rng = np.random.default_rng(8)
    n = QUEUE_BUILDUP_BUCKETS * 4 + 1
    srv.batcher.submit_burst(rng.normal(size=(n, 8)).astype(np.float32))
    for _ in range(5):
        assert srv.check_brownout() == "normal"
    assert srv.brownout is None
    srv.batcher.drain()


def test_brownout_rides_heartbeat_and_monitor_flag(tmp_path):
    """The mode is operator-visible end to end: heartbeat extras carry
    ``mode`` and the fleet monitor renders ``<< BROWNOUT``."""
    from shifu_tpu.obs import monitor as monitor_mod
    obs.set_enabled(True)
    srv = _brownout_server(tmp_path)
    rng = np.random.default_rng(9)
    n = QUEUE_BUILDUP_BUCKETS * 4 + 1
    srv.batcher.submit_burst(rng.normal(size=(n, 8)).astype(np.float32))
    srv.check_brownout()
    extras = srv._beat_extras()                 # 2nd stressed check
    assert extras["mode"] == "brownout"
    hd = obs.health_dir_for(str(tmp_path))
    os.makedirs(hd)
    with open(os.path.join(hd, "serve-b.json"), "w") as f:
        json.dump({"proc": "serve-b", "step": "SERVE",
                   "state": "running", "ts": time.time(),
                   "last_progress_ts": time.time(), "interval_s": 5.0,
                   **extras}, f)
    text = monitor_mod.render_status(str(tmp_path))
    assert "<< BROWNOUT" in text
    srv.batcher.drain()


# --------------------------------------------------- die-during-shed drill
def test_serve_admit_fault_drill_keeps_accounting_consistent():
    """``serve:admit=1:ioerror`` dies WHILE shed #1 is being rejected:
    the injected fault surfaces instead of the coded 429, but the queue
    depth and the SLO shed accounting must read exactly as if the shed
    had completed — and the NEXT shed (fault disarmed) is again the
    coded rejection."""
    assert faults.is_declared_site("serve", "admit")
    environment.set_property("shifu.faults", "serve:admit=1:ioerror")
    faults.reset_for_tests()
    slo = obs.SLOTracker(p99_ms=50.0)
    clk = FakeClock()
    b, _ = _batcher(clk, slo=slo, **{"shifu.serve.maxQueueRows": 4})
    rng = np.random.default_rng(10)
    t_ok = b.submit_burst(rng.normal(size=(4, 8)).astype(np.float32))
    with pytest.raises(OSError):
        b.submit_burst(rng.normal(size=(1, 8)).astype(np.float32))
    # the drill's contract: death mid-shed corrupted nothing
    assert b.queue_depth == 4
    assert b.stats["shed_overload"] == 1
    assert slo.shed_total == 1
    with pytest.raises(OverloadedError):        # disarmed: coded again
        b.submit_burst(rng.normal(size=(1, 8)).astype(np.float32))
    assert b.stats["shed_overload"] == 2 and slo.shed_total == 2
    b.pump()
    assert t_ok.wait(1.0).shape == (4,)         # queued work unharmed


def test_slo_sheds_counted_outside_availability_burn():
    """Sheds ride ``shed`` in the SLO summary, NOT the availability
    error count — folding load-shedding into burn would drain replicas
    exactly when the fleet is overloaded (congestion collapse by
    alerting)."""
    clk = FakeClock()
    t = obs.SLOTracker(p99_ms=50.0, clock=clk)
    t.observe_batch(np.full(100, 0.001))
    t.record_shed(40)
    doc = t.summary()
    assert doc["shed"] == 40
    assert t.shed_total == 40
    assert not t.alerts(now=clk.t)              # no availability burn
