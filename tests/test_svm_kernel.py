"""Kernel SVM (reference ``core/alg/SVMTrainer.java`` C-SVC with
rbf/poly/sigmoid kernels) — the dual solve must produce a genuinely
nonlinear decision surface, round-trip through the model file, and run
end-to-end through the pipeline."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "helpers"))

from pipeline import train_algorithm  # noqa: E402


def _circles(n=600, seed=0):
    """Concentric rings: linearly inseparable, RBF-trivial."""
    rng = np.random.default_rng(seed)
    r = np.where(rng.random(n) < 0.5, 0.6, 1.6)
    th = rng.random(n) * 2 * np.pi
    x = np.stack([r * np.cos(th), r * np.sin(th)], axis=1)
    x += rng.normal(0, 0.08, x.shape)
    y = (r > 1.0).astype(np.float32)
    return x.astype(np.float32), y


def test_rbf_separates_circles_linear_cannot(tmp_path):
    from shifu_tpu.models.svm import IndependentSVMModel, SVMModelSpec, \
        load_model, save_model
    from shifu_tpu.train.svm_trainer import train_kernel_svm

    x, y = _circles()
    mask = np.ones(len(y), bool)
    mask[::5] = False                         # 20% validation
    spec = SVMModelSpec(input_dim=2, kernel="rbf", gamma=1.0)
    sv_x, alpha_y, tr, va, n_sv = train_kernel_svm(x, y, mask, spec,
                                                   c_penalty=2.0)
    assert n_sv > 0
    model = IndependentSVMModel(spec, sv_x, alpha_y)
    scores = model.compute(x)[:, 0]
    acc = float(((scores > 0.5) == (y > 0.5)).mean())
    assert acc > 0.95, acc                    # rings solved
    # a LINEAR kernel on the same data stays near chance
    lin = SVMModelSpec(input_dim=2, kernel="linear")
    sv_l, ay_l, _, _, _ = train_kernel_svm(x, y, mask, lin, c_penalty=2.0)
    lin_scores = IndependentSVMModel(lin, sv_l, ay_l).compute(x)[:, 0]
    lin_acc = float(((lin_scores > 0.5) == (y > 0.5)).mean())
    assert lin_acc < 0.7, lin_acc
    # save -> load -> identical scores
    path = str(tmp_path / "model0.svm")
    save_model(path, spec, sv_x, alpha_y)
    re = IndependentSVMModel(*load_model(path))
    np.testing.assert_allclose(re.compute(x)[:, 0], scores, rtol=1e-6)


def test_poly_sigmoid_kernels_run():
    from shifu_tpu.models.svm import IndependentSVMModel, SVMModelSpec
    from shifu_tpu.train.svm_trainer import train_kernel_svm

    x, y = _circles(n=300, seed=1)
    mask = np.ones(len(y), bool)
    for kind, kw in (("poly", dict(gamma=1.0, coef0=1.0, degree=3)),
                     ("sigmoid", dict(gamma=0.5, coef0=0.0))):
        spec = SVMModelSpec(input_dim=2, kernel=kind, **kw)
        sv_x, alpha_y, tr, va, n_sv = train_kernel_svm(x, y, mask, spec)
        s = IndependentSVMModel(spec, sv_x, alpha_y).compute(x)
        assert np.isfinite(s).all() and n_sv > 0


def test_pipeline_svm_rbf_end_to_end(prepared_set):
    from shifu_tpu.eval.scorer import Scorer

    train_algorithm(prepared_set, "SVM",
                    {"Kernel": "RBF", "Gamma": 0.2, "Const": 1.0})
    path = os.path.join(prepared_set, "models", "model0.svm")
    assert os.path.isfile(path)
    sc = Scorer.from_dir(os.path.join(prepared_set, "models"))
    assert type(sc.models[0]).__name__ == "IndependentSVMModel"
    # progress surface mirrors the NN trainers' line shape
    prog = open(os.path.join(prepared_set, "tmp",
                             "train.progress")).read()
    assert "Train Error" in prog and "SVs" in prog


def test_kernel_svm_row_cap_and_streaming_rejected(prepared_set):
    import pytest

    from shifu_tpu.config.errors import ShifuError
    from shifu_tpu.models.svm import SVMModelSpec
    from shifu_tpu.train.svm_trainer import MAX_KERNEL_ROWS, \
        train_kernel_svm

    x = np.zeros((MAX_KERNEL_ROWS + 1, 2), np.float32)
    with pytest.raises(ShifuError):
        train_kernel_svm(x, np.zeros(len(x)), np.ones(len(x), bool),
                         SVMModelSpec(input_dim=2))
