"""Fault-injection suite (fast, in-process subset — tier-1 safe).

Exercises the crash-consistency layer end to end WITHOUT subprocesses:
injected ``ioerror`` faults kill a step mid-flight inside this process,
then the re-run proves the journal/resume machinery reproduces the
artifacts an uninterrupted run writes — bit-identically for the
trainers.  Hard-kill (SIGKILL-equivalent) coverage lives in
``test_resume_e2e.py`` (marked slow).
"""

import json
import os
import shutil

import numpy as np
import pytest

from shifu_tpu import faults, obs
from shifu_tpu.config import environment

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_faults():
    environment.reset_for_tests()
    faults.reset_for_tests()
    yield
    environment.reset_for_tests()
    faults.reset_for_tests()
    obs.set_enabled(False)


def set_faults(spec: str) -> None:
    environment.set_property("shifu.faults", spec)
    faults.reset_for_tests()


def _init_stats(mdir: str) -> None:
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    assert InitProcessor(mdir).run() == 0
    assert StatsProcessor(mdir, params={}).run() == 0


def _small_chunks_and_shards(monkeypatch, chunk_rows=500, shard_rows=1024):
    """Shrink the reader chunk + shard size so the 4k-row fixture yields
    several shards (shards flush on chunk boundaries once the buffer
    crosses SHARD_ROWS)."""
    from shifu_tpu.data.reader import DataSource
    orig = DataSource.iter_chunks
    monkeypatch.setattr(
        DataSource, "iter_chunks",
        lambda self, cr=chunk_rows: orig(self, chunk_rows))
    # keep the one-parse plane's chunk geometry in lockstep: the raw
    # cache pins chunkRows, and a cache written at the default geometry
    # would otherwise serve ONE big chunk and defeat the multi-shard
    # setup these tests rely on
    monkeypatch.setattr("shifu_tpu.data.parsepool.CHUNK_ROWS", chunk_rows)
    monkeypatch.setattr("shifu_tpu.pipeline.norm.SHARD_ROWS", shard_rows)


def _shard_arrays(d: str):
    out = {}
    for f in sorted(os.listdir(d)):
        if f.endswith(".npz"):
            out[f] = {k: v.copy()
                      for k, v in np.load(os.path.join(d, f)).items()}
    return out


def _assert_same_shards(a, b):
    assert a.keys() == b.keys()
    for f in a:
        assert a[f].keys() == b[f].keys(), f
        for k in a[f]:
            x, y = a[f][k], b[f][k]
            assert x.dtype == y.dtype and x.shape == y.shape, (f, k)
            assert x.tobytes() == y.tobytes(), (f, k)


# ------------------------------------------------------------ harness unit
def test_parse_spec():
    c = faults.parse_spec("norm:shard=3:ioerror,train:tree=17:kill,"
                          "reader:file=0:ioerror@2")
    assert c[("norm", "shard", "3")] == ["ioerror", 1]
    assert c[("train", "tree", "17")] == ["kill", 1]
    assert c[("reader", "file", "0")] == ["ioerror", 2]
    assert faults.parse_spec("") == {}


def test_parse_spec_rejects_garbage():
    with pytest.raises(ValueError, match="bad fault clause"):
        faults.parse_spec("norm:shard=3:explode")
    with pytest.raises(ValueError, match="bad fault clause"):
        faults.parse_spec("norm=3")


def test_fire_disarms_after_count():
    set_faults("x:p=1:ioerror@2")
    with pytest.raises(faults.InjectedFault):
        faults.fire("x", "p", 1)
    with pytest.raises(faults.InjectedFault):
        faults.fire("x", "p", 1)
    faults.fire("x", "p", 1)            # spent — no-op
    faults.fire("x", "p", 2)            # different value — no-op


# -------------------------------------------------------- journal / ioutil
def test_journal_arm_and_verify(tmp_path):
    from shifu_tpu.pipeline.journal import StepJournal
    root = str(tmp_path)
    art = os.path.join(root, "a.bin")
    with open(art, "wb") as f:
        f.write(b"x" * 100)
    j = StepJournal(os.path.join(root, "J.json"), "T", root)
    j.open_run()
    j.arm({"v": 1})
    j.commit_item("a", files=[art], rows=5)
    assert j.verify_all()
    # a second run over the TORN journal with the same signature resumes
    j2 = StepJournal(os.path.join(root, "J.json"), "T", root)
    assert j2.was_torn
    j2.open_run()
    assert set(j2.arm({"v": 1})) == {"a"}
    # signature change drops the items
    j3 = StepJournal(os.path.join(root, "J.json"), "T", root)
    j3.open_run()
    assert j3.arm({"v": 2}) == {}
    # a completed run does NOT resume (idempotent full re-run)
    j4 = StepJournal(os.path.join(root, "J.json"), "T", root)
    j4.open_run()
    j4.commit_item("a", files=[art])
    j4.complete()
    j5 = StepJournal(os.path.join(root, "J.json"), "T", root)
    assert not j5.was_torn
    j5.open_run()
    assert j5.arm({"v": 2}) == {}


def test_journal_detects_truncated_artifact(tmp_path):
    from shifu_tpu.pipeline.journal import StepJournal
    root = str(tmp_path)
    art = os.path.join(root, "a.bin")
    with open(art, "wb") as f:
        f.write(b"x" * 100)
    j = StepJournal(os.path.join(root, "J.json"), "T", root)
    j.open_run()
    j.arm({})
    j.commit_item("a", files=[art])
    with open(art, "r+b") as f:
        f.truncate(37)                 # committed-looking but torn
    j2 = StepJournal(os.path.join(root, "J.json"), "T", root)
    j2.open_run()
    assert j2.arm({}) == {}            # the torn item dropped out
    assert not j.verify_item({"files": [["a.bin", 100]]})


def test_io_retry_counts_and_provenance(tmp_path):
    from shifu_tpu.ioutil import io_retry
    environment.set_property("shifu.io.retryBaseMs", "1")
    obs.set_enabled(True)
    obs.get_registry().reset()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient weather")
        return "ok"
    assert io_retry(flaky, "unit read", "/data/part-7") == "ok"
    assert obs.get_registry().counter("ingest.retries").value == 2

    environment.set_property("shifu.io.retries", "1")
    with pytest.raises(OSError, match=r"part-9.*permanent"):
        io_retry(lambda: (_ for _ in ()).throw(OSError("permanent")),
                 "unit read", "/data/part-9")


# ------------------------------------------------------- checkpoint fixes
def test_checkpoint_rejects_dtype_mismatch(tmp_path):
    from shifu_tpu.train import checkpoint as ckpt
    d = str(tmp_path)
    ckpt.save_state(d, 3, {"a": np.arange(4, dtype=np.float32)})
    ok = ckpt.restore_state(d, {"a": np.zeros(4, np.float32)})
    assert ok is not None and ok[0] == 3
    # same shape, different dtype: must be rejected, not silently cast
    assert ckpt.restore_state(d, {"a": np.zeros(4, np.float64)}) is None
    assert ckpt.restore_state(d, {"a": np.zeros(4, np.int32)}) is None


def test_checkpoint_sweeps_orphan_tmp(tmp_path):
    from shifu_tpu.train import checkpoint as ckpt
    d = str(tmp_path)
    orphan = os.path.join(d, "ckpt-9.npz.tmp")
    os.makedirs(d, exist_ok=True)
    with open(orphan, "wb") as f:
        f.write(b"torn")
    ckpt.save_state(d, 1, {"a": np.zeros(2, np.float32)})
    assert not os.path.exists(orphan)
    assert ckpt.latest_epoch(d) == 1


# ------------------------------------------------------ retry in the data plane
def test_reader_retries_transient_open(fraud_csv):
    from shifu_tpu.data.reader import DataSource
    environment.set_property("shifu.io.retryBaseMs", "1")
    obs.set_enabled(True)
    obs.get_registry().reset()
    set_faults("reader:file=0:ioerror")
    ds = DataSource(fraud_csv, "|")
    rows = sum(len(c) for c in ds.iter_chunks())
    assert rows > 0
    assert obs.get_registry().counter("ingest.retries").value >= 1


def test_reader_retry_exhaustion_names_the_shard(fraud_csv):
    from shifu_tpu.data.reader import DataSource
    environment.set_property("shifu.io.retryBaseMs", "1")
    environment.set_property("shifu.io.retries", "1")
    set_faults("reader:file=0:ioerror@10")
    ds = DataSource(fraud_csv, "|")
    with pytest.raises(OSError, match=os.path.basename(fraud_csv)):
        list(ds.iter_chunks())


def test_spill_manifest_commit_retries(tmp_path):
    from shifu_tpu.data.spill import SpillWriter, open_spill
    environment.set_property("shifu.io.retryBaseMs", "1")
    set_faults("spill:manifest=0:ioerror")
    d = str(tmp_path / "spill")
    w = SpillWriter(d, ("y",), [["s", 1, 2]], 1 << 20)
    assert w.append({"y": np.arange(8, dtype=np.float32)})
    assert w.finish()                   # first manifest attempt faulted
    rd, writable = open_spill(d, ("y",), [["s", 1, 2]])
    assert rd is not None and rd.rows == 8


# ------------------------------------------------- bounded bad-input tolerance
def _mixed_dir(tmp_path) -> str:
    d = tmp_path / "data"
    d.mkdir()
    with open(d / "part-aaa.csv", "w") as f:
        for i in range(20):
            f.write(f"{i}|{i * 2}|good\n")
    # a .gz that is NOT gzip: decodes fine as a name, dies on first read
    with open(d / "part-bbb.csv.gz", "wb") as f:
        f.write(b"this is not gzip data\n" * 5)
    return str(d)


def test_bad_threshold_default_strict(tmp_path):
    from shifu_tpu.data.reader import DataSource
    ds = DataSource(_mixed_dir(tmp_path), "|",
                    header=["a", "b", "tag"])
    with pytest.raises(OSError):       # gzip.BadGzipFile is an OSError
        list(ds.iter_chunks())


def test_bad_threshold_quarantines_unreadable_file(tmp_path):
    from shifu_tpu.data.reader import DataSource
    environment.set_property("shifu.data.badThreshold", "0.6")
    obs.set_enabled(True)
    obs.get_registry().reset()
    ds = DataSource(_mixed_dir(tmp_path), "|",
                    header=["a", "b", "tag"])
    rows = sum(len(c) for c in ds.iter_chunks())
    assert rows == 20                  # the good file's rows survive
    assert obs.get_registry().counter(
        "data.quarantined_shards").value == 1


def test_bad_threshold_exceeded_is_coded(tmp_path):
    from shifu_tpu.config.errors import ErrorCode, ShifuError
    from shifu_tpu.data.reader import DataSource
    environment.set_property("shifu.data.badThreshold", "0.05")
    ds = DataSource(_mixed_dir(tmp_path), "|",
                    header=["a", "b", "tag"])
    with pytest.raises(ShifuError) as ei:
        list(ds.iter_chunks())
    assert ei.value.error_code == ErrorCode.ERROR_BAD_DATA_THRESHOLD
    assert "part-bbb" in str(ei.value)


def _shard_set(tmp_path, n_shards=4, rows=32) -> str:
    d = tmp_path / "shards"
    d.mkdir()
    for s in range(n_shards):
        np.savez(d / f"part-{s:05d}.npz",
                 y=np.full(rows, s, np.float32),
                 w=np.ones(rows, np.float32))
    with open(d / "schema.json", "w") as f:
        json.dump({"numShards": n_shards, "numRows": n_shards * rows}, f)
    return str(d)


def test_shards_quarantine_undecodable(tmp_path):
    from shifu_tpu.data.shards import Shards
    d = _shard_set(tmp_path)
    bad = os.path.join(d, "part-00002.npz")
    with open(bad, "r+b") as f:
        f.truncate(os.path.getsize(bad) // 2)      # torn zip
    # strict (default, threshold 0): raises
    with pytest.raises(Exception):
        Shards.open(d).load_all()
    environment.set_property("shifu.data.badThreshold", "0.5")
    obs.set_enabled(True)
    obs.get_registry().reset()
    data = Shards.open(d).load_all()
    assert len(data["y"]) == 3 * 32                # shard 2 quarantined
    assert 2.0 not in data["y"]
    assert obs.get_registry().counter(
        "data.quarantined_shards").value == 1
    # streaming stays strict even with the threshold set
    with pytest.raises(Exception):
        list(Shards.open(d).iter_shards(strict=True))


# -------------------------------------------------- norm: resume mid-step
def test_norm_resumes_at_first_uncommitted_shard(model_set, monkeypatch):
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    _init_stats(model_set)
    control = model_set + "_ctl"
    shutil.copytree(model_set, control)
    _small_chunks_and_shards(monkeypatch)

    set_faults("norm:shard=2:ioerror")
    with pytest.raises(faults.InjectedFault):
        NormalizeProcessor(model_set, params={}).run()

    jpath = os.path.join(model_set, "tmp", "journal", "NORMALIZE.json")
    with open(jpath) as f:
        doc = json.load(f)
    assert doc["status"] == "running"
    assert "shard-00000" in doc["items"] and "shard-00001" in doc["items"]
    assert "shard-00002" not in doc["items"]

    ndir = os.path.join(model_set, "tmp", "NormalizedData")
    part0 = os.path.join(ndir, "part-00000.npz")
    mtime0 = os.stat(part0).st_mtime_ns

    set_faults("")
    assert NormalizeProcessor(model_set, params={}).run() == 0
    # the committed prefix was NOT rewritten — resume started at shard 2
    assert os.stat(part0).st_mtime_ns == mtime0
    with open(jpath) as f:
        assert json.load(f)["status"] == "complete"

    assert NormalizeProcessor(control, params={}).run() == 0
    for sub in ("NormalizedData", "CleanedData"):
        _assert_same_shards(
            _shard_arrays(os.path.join(model_set, "tmp", sub)),
            _shard_arrays(os.path.join(control, "tmp", sub)))
        with open(os.path.join(model_set, "tmp", sub, "schema.json")) as f:
            sa = f.read()
        with open(os.path.join(control, "tmp", sub, "schema.json")) as f:
            assert sa == f.read()


def test_norm_resume_rewrites_truncated_committed_shard(model_set,
                                                        monkeypatch):
    """A committed-LOOKING shard that was later truncated fails journal
    verification on resume and its unit re-runs cleanly."""
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    _init_stats(model_set)
    control = model_set + "_ctl"
    shutil.copytree(model_set, control)
    _small_chunks_and_shards(monkeypatch)

    set_faults("norm:shard=2:ioerror")
    with pytest.raises(faults.InjectedFault):
        NormalizeProcessor(model_set, params={}).run()
    ndir = os.path.join(model_set, "tmp", "NormalizedData")
    part1 = os.path.join(ndir, "part-00001.npz")
    with open(part1, "r+b") as f:
        f.truncate(os.path.getsize(part1) // 2)

    set_faults("")
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(control, params={}).run() == 0
    for sub in ("NormalizedData", "CleanedData"):
        _assert_same_shards(
            _shard_arrays(os.path.join(model_set, "tmp", sub)),
            _shard_arrays(os.path.join(control, "tmp", sub)))


def test_train_precondition_rejects_torn_norm_artifacts(prepared_set):
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.errors import ErrorCode, ShifuError
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    mc_path = os.path.join(prepared_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = "GBT"
    mc.train.params = {"TreeNum": 3, "MaxDepth": 3}
    mc.save(mc_path)
    ndir = os.path.join(prepared_set, "tmp", "NormalizedData")
    shard = os.path.join(ndir, "part-00000.npz")
    size = os.path.getsize(shard)
    with open(shard, "r+b") as f:
        f.truncate(size // 2)
    with pytest.raises(ShifuError) as ei:
        TrainProcessor(prepared_set, params={}).run()
    assert ei.value.error_code == ErrorCode.ERROR_TORN_ARTIFACT
    # re-running norm heals the plane; train then proceeds
    assert NormalizeProcessor(prepared_set, params={}).run() == 0
    assert TrainProcessor(prepared_set, params={}).run() == 0


# --------------------------------------- stats: mid-sweep partial resume
def test_stats_checkpoint_resume_matches_uninterrupted(model_set,
                                                       monkeypatch):
    from shifu_tpu.data.reader import DataSource
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    assert InitProcessor(model_set).run() == 0
    control = model_set + "_ctl"
    shutil.copytree(model_set, control)

    orig = DataSource.iter_chunks
    monkeypatch.setattr(DataSource, "iter_chunks",
                        lambda self, chunk_rows=500: orig(self, 500))
    environment.set_property("shifu.stats.checkpointChunks", "3")

    assert StatsProcessor(control, params={}).run() == 0

    set_faults("stats:chunk=5:ioerror")
    with pytest.raises(faults.InjectedFault):
        StatsProcessor(model_set, params={}).run()
    partial = os.path.join(model_set, "tmp", "stats", "partial_sweep.npz")
    assert os.path.isfile(partial)     # chunk-3 checkpoint landed

    set_faults("")
    assert StatsProcessor(model_set, params={}).run() == 0
    assert not os.path.isfile(partial)  # committed runs drop partials
    with open(os.path.join(model_set, "ColumnConfig.json")) as f:
        resumed = f.read()
    with open(os.path.join(control, "ColumnConfig.json")) as f:
        assert resumed == f.read()


# ------------------------------------- train: crash + resume, bit parity
def _set_train(mdir, alg, params, epochs=None):
    from shifu_tpu.config import ModelConfig
    mc_path = os.path.join(mdir, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = alg
    mc.train.params = params
    if epochs is not None:
        mc.train.numTrainEpochs = epochs
    mc.save(mc_path)


def _load_trees(mdir):
    from shifu_tpu.models import tree as tree_model
    spec, trees = tree_model.load_model(
        os.path.join(mdir, "models", "model0.gbt"))
    return spec, trees


def test_gbt_crash_resume_bit_identical(prepared_set):
    from shifu_tpu.pipeline.train import TrainProcessor
    control = prepared_set + "_ctl"
    shutil.copytree(prepared_set, control)
    params = {"TreeNum": 12, "MaxDepth": 3, "CheckpointInterval": 4}
    _set_train(prepared_set, "GBT", params)
    _set_train(control, "GBT", params)

    assert TrainProcessor(control, params={}).run() == 0

    set_faults("train:tree=9:ioerror")
    with pytest.raises(faults.InjectedFault):
        TrainProcessor(prepared_set, params={}).run()
    # a mid-forest checkpoint committed at a TreeBatch boundary
    assert os.path.isfile(os.path.join(prepared_set, "tmp", "checkpoints",
                                       "forest_ckpt.npz"))

    set_faults("")
    # NO explicit -resume: the torn journal triggers auto-resume
    assert TrainProcessor(prepared_set, params={}).run() == 0

    _, trees_c = _load_trees(control)
    _, trees_r = _load_trees(prepared_set)
    assert len(trees_c) == len(trees_r) == 12
    for tc, tr in zip(trees_c, trees_r):
        assert np.asarray(tc.split_feat).tobytes() == \
            np.asarray(tr.split_feat).tobytes()
        assert np.asarray(tc.left_mask).tobytes() == \
            np.asarray(tr.left_mask).tobytes()
        assert np.asarray(tc.leaf_value).tobytes() == \
            np.asarray(tr.leaf_value).tobytes()


def test_nn_crash_resume_bit_identical(prepared_set):
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.pipeline.train import TrainProcessor
    control = prepared_set + "_ctl"
    shutil.copytree(prepared_set, control)
    params = {"NumHiddenNodes": [8], "CheckpointInterval": 3,
              "Propagation": "R"}
    _set_train(prepared_set, "NN", params, epochs=9)
    _set_train(control, "NN", params, epochs=9)

    assert TrainProcessor(control, params={}).run() == 0

    set_faults("train:epoch=6:ioerror")
    with pytest.raises(faults.InjectedFault):
        TrainProcessor(prepared_set, params={}).run()

    set_faults("")
    assert TrainProcessor(prepared_set, params={}).run() == 0

    _, pc = nn_model.load_model(os.path.join(control, "models",
                                             "model0.nn"))
    _, pr = nn_model.load_model(os.path.join(prepared_set, "models",
                                             "model0.nn"))
    assert len(pc) == len(pr)
    for lc, lr in zip(pc, pr):
        for k in lc:
            assert np.asarray(lc[k]).tobytes() == \
                np.asarray(lr[k]).tobytes(), k


# ----------------------------------------- disk-tail super-batch drains
def _write_tail_shards(d, n=1024, c=6, n_bins=8, seed=3):
    from shifu_tpu.data.shards import Shards
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int16)
    logit = (bins[:, 0] - 3) * 0.8 + (bins[:, 1] == 2) * 1.5 - 0.5
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    w = np.ones(n, np.float32)
    os.makedirs(d, exist_ok=True)
    shard = 0
    for s in range(0, n, 300):
        e = min(s + 300, n)
        np.savez(os.path.join(d, f"part-{shard:05d}.npz"),
                 bins=bins[s:e], y=y[s:e], w=w[s:e])
        shard += 1
    with open(os.path.join(d, "schema.json"), "w") as f:
        json.dump({"columnNums": list(range(c)), "numShards": shard,
                   "numRows": n}, f)
    return Shards.open(d)


def _tail_forest_equal(a_trees, b_trees):
    assert len(a_trees) == len(b_trees)
    for ta, tb in zip(a_trees, b_trees):
        assert np.asarray(ta.split_feat).tobytes() == \
            np.asarray(tb.split_feat).tobytes()
        assert np.asarray(ta.left_mask).tobytes() == \
            np.asarray(tb.left_mask).tobytes()
        assert np.asarray(ta.leaf_value).tobytes() == \
            np.asarray(tb.leaf_value).tobytes()


def test_gbt_tail_superbatch_crash_resume_bit_identical(tmp_path,
                                                        monkeypatch):
    """Kill the coarse-to-fine tail at a super-batch drain (the new
    ``train:superbatch`` site); resuming from the drain-boundary
    checkpoint must reproduce the uninterrupted forest bit-identically
    (checkpoint commits only trees whose score updates are final)."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed

    monkeypatch.setenv("SHIFU_TREE_TAIL_C2F", "1")
    shards = _write_tail_shards(str(tmp_path / "s"))
    budget = 2 * 256 * (6 * 1 + 3 * 4) + 64
    mk = lambda: ShardStream(shards, ("bins", "y", "w"), window_rows=256)
    settings = DTSettings(n_trees=10, depth=3, loss="log", seed=0,
                          checkpoint_every=3)

    control = train_gbt_streamed(mk(), 8, None, settings,
                                 cache_budget=budget)
    assert control.trees_built == 10

    saved = {}

    def ckpt(trees, history, init_score, scores=None):
        saved.update(trees=list(trees), history=list(history),
                     init=init_score,
                     scores=None if scores is None else scores.copy())

    set_faults("train:superbatch=2:ioerror")
    with pytest.raises(faults.InjectedFault):
        train_gbt_streamed(mk(), 8, None, settings, cache_budget=budget,
                           checkpoint_fn=ckpt)
    assert 0 < len(saved["trees"]) < 10      # a mid-forest drain commit

    set_faults("")
    resumed = train_gbt_streamed(
        mk(), 8, None, settings, cache_budget=budget,
        init_trees=saved["trees"], init_score=saved["init"],
        start_history=saved["history"], init_scores=saved["scores"])
    assert resumed.trees_built == 10
    _tail_forest_equal(control.trees, resumed.trees)
    np.testing.assert_allclose(np.array(control.history),
                               np.array(resumed.history), rtol=1e-5)


def test_rf_tail_superbatch_crash_resume_bit_identical(tmp_path):
    """Same site, RF flavor: every tail super-batch is a commit boundary;
    a crash between drains resumes from the last committed batch and the
    regrown forest is bit-identical (hash bags are stateless per
    (tree, row))."""
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf_streamed

    shards = _write_tail_shards(str(tmp_path / "s"))
    budget = 2 * 256 * (6 * 1 + 2 * 4) + 64
    mk = lambda: ShardStream(shards, ("bins", "y", "w"), window_rows=256)
    settings = DTSettings(n_trees=6, depth=3, impurity="entropy",
                          loss="squared", seed=2, tail_tree_batch=2)

    control = train_rf_streamed(mk(), 8, None, settings,
                                cache_budget=budget)
    assert control.trees_built == 6

    saved = {}

    def ckpt(trees, history, init_score, scores=None):
        saved.update(trees=list(trees), history=list(history))

    set_faults("train:superbatch=2:ioerror")
    with pytest.raises(faults.InjectedFault):
        train_rf_streamed(mk(), 8, None, settings, cache_budget=budget,
                          checkpoint_fn=ckpt)
    assert len(saved["trees"]) == 2          # batch-1 commit only

    set_faults("")
    resumed = train_rf_streamed(
        mk(), 8, None, settings, cache_budget=budget,
        init_trees=saved["trees"], start_history=saved["history"])
    assert resumed.trees_built == 6
    _tail_forest_equal(control.trees, resumed.trees)

# ----------------------------------- raw cache: torn commit, wire plane
def _rawcache_manifests(mdir: str):
    """Every committed raw-cache manifest path under tmp/RawCache."""
    root = os.path.join(mdir, "tmp", "RawCache")
    if not os.path.isdir(root):
        return []
    return [os.path.join(root, d, "manifest.json")
            for d in sorted(os.listdir(root))
            if os.path.isfile(os.path.join(root, d, "manifest.json"))]


def _clean_plane_arrays(mdir: str):
    """Per-shard arrays of the clean plane via Shards — transparent to
    npz vs direct-to-wire storage."""
    from shifu_tpu.data.shards import Shards
    s = Shards.open(os.path.join(mdir, "tmp", "CleanedData"))
    return [{k: np.asarray(v).copy() for k, v in d.items()}
            for d in s.iter_shards()]


def test_rawcache_commit_fault_retries_then_lands(model_set):
    """One transient ioerror at the raw-cache manifest commit rides the
    io_retry ladder — the step succeeds AND the cache commits."""
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    assert InitProcessor(model_set).run() == 0
    set_faults("rawcache:commit=0:ioerror")
    assert StatsProcessor(model_set, params={}).run() == 0
    assert _rawcache_manifests(model_set)


def test_rawcache_commit_exhaustion_absent_cache_then_rebuilt(model_set):
    """Retry exhaustion at the commit point abandons the cache WITHOUT
    failing the step (the cache is an optimization, not the output);
    absent manifest == absent cache, and the next pass rebuilds it."""
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    assert InitProcessor(model_set).run() == 0
    set_faults("rawcache:commit=0:ioerror@99")
    assert StatsProcessor(model_set, params={}).run() == 0
    assert _rawcache_manifests(model_set) == []   # commit never landed

    set_faults("")
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert _rawcache_manifests(model_set)         # cold norm rebuilt it


def test_norm_wire_fault_resume_bit_identical(model_set, monkeypatch):
    """An injected failure at the wire append (plus manufactured torn
    tail bytes past the committed wire manifest) resumes from the
    journal: the adopted prefix is kept, the tail re-lands, and the
    final wire plane is bit-identical to an uninterrupted run's."""
    from shifu_tpu.data.spill import wire_dir
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    _init_stats(model_set)
    control = model_set + "_ctl"
    shutil.copytree(model_set, control)
    _small_chunks_and_shards(monkeypatch)

    set_faults("norm:wire=2:ioerror")
    with pytest.raises(faults.InjectedFault):
        NormalizeProcessor(model_set, params={}).run()

    jpath = os.path.join(model_set, "tmp", "journal", "NORMALIZE.json")
    with open(jpath) as f:
        doc = json.load(f)
    assert "shard-00001" in doc["items"]
    assert "shard-00002" not in doc["items"]

    # manufacture the mid-append crash shape: tail bytes past the last
    # committed wire manifest — resume must truncate, not trust them
    wdir = wire_dir(os.path.join(model_set, "tmp", "CleanedData"),
                    ("bins", "y", "w"))
    with open(os.path.join(wdir, "y.raw"), "ab") as f:
        f.write(b"\xff" * 12)

    set_faults("")
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(control, params={}).run() == 0

    a, b = _clean_plane_arrays(model_set), _clean_plane_arrays(control)
    assert len(a) == len(b) and len(a) > 2
    for sa, sb in zip(a, b):
        assert sa.keys() == sb.keys()
        for k in sa:
            assert sa[k].dtype == sb[k].dtype
            assert sa[k].tobytes() == sb[k].tobytes(), k
    _assert_same_shards(
        _shard_arrays(os.path.join(model_set, "tmp", "NormalizedData")),
        _shard_arrays(os.path.join(control, "tmp", "NormalizedData")))
