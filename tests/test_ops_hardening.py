"""Ops hardening: model-set versioning, dynamic rebin, trainer-state
checkpoint/resume (SURVEY.md §5 aux subsystems)."""

import os

import numpy as np
import pytest

from shifu_tpu.config import ModelConfig, load_column_configs
from shifu_tpu.ops.stats_math import merge_adjacent_by_iv
from shifu_tpu.pipeline.manage import (list_versions, save_version,
                                       switch_version)


def test_merge_adjacent_by_iv_groups_similar_bins():
    neg = np.array([100, 98, 102, 10, 12])
    pos = np.array([10, 11, 9, 90, 88])
    groups = merge_adjacent_by_iv(neg, pos, target_bins=2)
    assert groups == [[0, 1, 2], [3, 4]]


def test_merge_respects_iv_keep():
    # clearly distinct bins: merging below target would destroy IV, so with
    # target >= current count nothing merges
    neg = np.array([100, 50, 10, 5])
    pos = np.array([5, 20, 60, 100])
    groups = merge_adjacent_by_iv(neg, pos, target_bins=4, iv_keep=0.99)
    assert len(groups) == 4


def test_stats_rebin_reduces_bins(model_set):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.config import environment
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    before = {c.columnName: c.num_bins()
              for c in load_column_configs(
                  os.path.join(model_set, "ColumnConfig.json"))}
    environment.set_property("shifu.rebin.maxNumBin", "4")
    try:
        assert StatsProcessor(model_set, params={"rebin": True}).run() == 0
    finally:
        environment.set_property("shifu.rebin.maxNumBin", "")
    after = load_column_configs(os.path.join(model_set, "ColumnConfig.json"))
    shrunk = [c for c in after
              if c.num_bins() <= 4 and before.get(c.columnName, 0) > 4]
    assert shrunk, "no column was rebinned down to 4 bins"
    # bin arrays stay consistent after merge
    for c in after:
        bn = c.columnBinning
        if bn.binCountNeg:
            assert len(bn.binCountNeg) == c.num_bins() + 1
            assert len(bn.binCountWoe) == c.num_bins() + 1


def test_rebinned_pipeline_still_trains(model_set):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={"rebin": True}).run() == 0
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0


def test_manage_save_switch(model_set, caplog):
    from shifu_tpu.pipeline.create import InitProcessor
    assert InitProcessor(model_set).run() == 0
    assert save_version(model_set, "v1") == 0
    # mutate the config
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.numTrainEpochs = 777
    mc.save(mc_path)
    assert save_version(model_set, "v2") == 0
    assert set(list_versions(model_set)) >= {"v1", "v2"}
    assert switch_version(model_set, "v1") == 0
    assert ModelConfig.load(mc_path).train.numTrainEpochs != 777
    assert switch_version(model_set, "v2") == 0
    assert ModelConfig.load(mc_path).train.numTrainEpochs == 777
    assert switch_version(model_set, "nope") == 1
    # show / delete / cp (reference ModelAction SHOW/DELETE + `shifu cp`)
    from shifu_tpu.pipeline.manage import (copy_model_set, delete_version,
                                           show_current)
    import logging
    with caplog.at_level(logging.INFO):
        assert show_current(model_set) == 0
    assert "current version: v2" in caplog.text
    assert delete_version(model_set, "v1") == 0
    assert "v1" not in list_versions(model_set)
    assert delete_version(model_set, "v1") == 1
    dst = os.path.join(os.path.dirname(model_set), "clone")
    assert copy_model_set(model_set, dst) == 0
    clone_mc = ModelConfig.load(os.path.join(dst, "ModelConfig.json"))
    assert clone_mc.train.numTrainEpochs == 777       # config carried over
    assert clone_mc.basic.name == "clone"
    assert os.path.isfile(os.path.join(dst, "ColumnConfig.json"))
    assert not os.path.isdir(os.path.join(dst, "models"))  # configs only
    assert copy_model_set(model_set, dst) == 1        # refuses overwrite


def test_device_trace_knob_emits_xplane(model_set, tmp_path):
    """-Dshifu.profile=<dir> wraps the step in a jax.profiler trace
    (SURVEY §5 tracing — the TPU-native upgrade of the reference's
    wall-clock log lines); the knob off emits nothing."""
    from shifu_tpu.config import environment
    from shifu_tpu.pipeline.create import InitProcessor

    trace_dir = str(tmp_path / "trace")
    environment.set_property("shifu.profile", trace_dir)
    try:
        assert InitProcessor(model_set).run() == 0
    finally:
        environment.set_property("shifu.profile", "")
    hits = [os.path.join(r, f) for r, _, fs in os.walk(trace_dir)
            for f in fs if f.endswith(".xplane.pb")]
    assert hits, f"no xplane trace written under {trace_dir}"


def test_checkpoint_save_restore_roundtrip(tmp_path):
    import jax
    from shifu_tpu.train import checkpoint as ckpt
    state = ({"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
             [np.zeros(4), np.ones(2)])
    ckpt.save_state(str(tmp_path), 5, state)
    ckpt.save_state(str(tmp_path), 10, state)
    assert ckpt.latest_epoch(str(tmp_path)) == 10
    template = jax.tree_util.tree_map(np.zeros_like, state)
    epoch, restored = ckpt.restore_state(str(tmp_path), template)
    assert epoch == 10
    np.testing.assert_array_equal(restored[0]["w"], state[0]["w"])
    # shape mismatch -> refused
    bad = ({"w": np.zeros((3, 3))}, [np.zeros(4), np.ones(2)])
    assert ckpt.restore_state(str(tmp_path), bad) is None


def test_train_resume_continues_from_checkpoint():
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble
    import tempfile
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 4)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    tw = np.ones((1, 256), np.float32)
    spec = nn_model.NNModelSpec(input_dim=4, hidden_nodes=[8],
                                activations=["tanh"])
    with tempfile.TemporaryDirectory() as d:
        s1 = TrainSettings(optimizer="ADAM", learning_rate=0.05, epochs=10,
                           checkpoint_dir=d, checkpoint_every=5, seed=7)
        res1 = train_ensemble(x, y, tw, tw, spec, s1)
        from shifu_tpu.train import checkpoint as ckpt
        assert ckpt.latest_epoch(d) == 10
        # resume: runs epochs 10..20 only
        s2 = TrainSettings(optimizer="ADAM", learning_rate=0.05, epochs=20,
                           checkpoint_dir=d, checkpoint_every=5, seed=7,
                           resume=True)
        res2 = train_ensemble(x, y, tw, tw, spec, s2)
        assert len(res2.history) == 10          # only the new epochs ran
        assert res2.train_errors[0] <= res1.train_errors[0] + 1e-6


def test_device_hash_bags_match_host():
    """Device splitmix64 Poisson bags are BIT-identical to the host hash
    draw the streamed trainers key every stateless decision off."""
    import jax.numpy as jnp

    from shifu_tpu.data.streaming import _hash_poisson, row_uniform
    from shifu_tpu.ops.hashing import hash_poisson_device, split_index_u32

    rng = np.random.default_rng(9)
    idx = np.concatenate([
        rng.integers(0, 1 << 31, 4000),
        rng.integers(0, 1 << 62, 1000)]).astype(np.uint64)
    for seed, stream, lam in ((0, 5000, 1.0), (7, 5003, 0.5),
                              (123, 6001, 2.5)):
        host = _hash_poisson(lam, row_uniform(seed, stream, idx))
        hi, lo = split_index_u32(idx)
        dev = np.asarray(hash_poisson_device(
            jnp.asarray(hi), jnp.asarray(lo), seed, stream, lam))
        np.testing.assert_array_equal(host, dev)
