"""Device cost-attribution plane suite (obs/costs + obs/utilization):
costed_jit capture (flops/bytes/memory, compile/launch counts), the
shape-churn recompile sentinel (counter + warn-once — the acceptance
test), lazy module-scope wrapping, analytic Pallas models, cost records
in the flush/trace, the utilization/roofline report (incl. the
deterministic-render golden), padding-waste accounting, timeline cost
annotation + torn-trace hardening, `monitor --once --json`, and
`bench.py --compare` auto-mode."""

import json
import logging
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from shifu_tpu import obs
from shifu_tpu.obs import costs as costs_mod
from shifu_tpu.obs import monitor as monitor_mod
from shifu_tpu.obs import timeline as timeline_mod
from shifu_tpu.obs import utilization as util_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.obs        # `pytest -m obs` collects this suite


@pytest.fixture
def telemetry():
    obs.reset_for_tests()
    obs.set_enabled(True)
    yield obs
    obs.reset_for_tests()


def _metric(name):
    return next((m for m in obs.snapshot() if m["name"] == name), None)


# ------------------------------------------------------------ costed_jit
def test_costed_jit_captures_costs_memory_and_launches(telemetry):
    def f(x, y, n=None):
        return (x @ y).sum() + n

    cj = obs.costed_jit("test.mm", f, static_argnames=("n",))
    assert isinstance(cj, costs_mod.CostedJit)
    v = float(cj(jnp.ones((8, 8)), jnp.ones((8, 8)), n=3))
    assert v == pytest.approx(8 * 8 * 8 + 3)
    float(cj(jnp.ones((8, 8)), jnp.ones((8, 8)), n=3))   # warm launch
    (rec,) = obs.cost_snapshot()
    assert rec["kind"] == "cost" and rec["name"] == "test.mm"
    assert rec["compiles"] == 1 and rec["launches"] == 2
    assert rec["flops"] and rec["flops"] > 0
    assert rec["bytes_accessed"] and rec["bytes_accessed"] > 0
    assert rec["memory"]["args"] > 0 and not rec["analytic"]
    assert "[8,8]" in rec["signature"]
    assert _metric("xla.launches")["value"] == 2
    assert _metric("xla.recompiles") is None      # one signature only


def test_costed_jit_static_values_key_executables(telemetry):
    """Distinct STATIC values are distinct executables (and count as a
    recompile under one name — statics churn like shapes churn)."""
    def f(x, k=2):
        return (x * k).sum()

    cj = obs.costed_jit("test.static", f, static_argnames=("k",))
    assert float(cj(jnp.ones(4), k=2)) == 8.0
    assert float(cj(jnp.ones(4), k=3)) == 12.0
    recs = obs.cost_snapshot()
    assert len(recs) == 2
    assert _metric("xla.recompiles")["value"] == 1


def test_recompile_sentinel_counter_and_warn_once(telemetry, caplog):
    """ACCEPTANCE: two distinct input shapes through ONE costed_jit name
    increment ``xla.recompiles`` and fire the warn-once log EXACTLY
    once (a third shape counts silently)."""
    def f(x):
        return (x * 2.0).sum()

    cj = obs.costed_jit("test.churn", f)
    with caplog.at_level(logging.WARNING, logger="shifu_tpu.obs.costs"):
        float(cj(jnp.ones((4,))))
        float(cj(jnp.ones((8,))))                # recompile 1 -> warns
        float(cj(jnp.ones((16,))))               # recompile 2 -> silent
    assert _metric("xla.recompiles")["value"] == 2
    warned = [r for r in caplog.records
              if "recompiled for a new input signature" in r.message]
    assert len(warned) == 1
    assert "test.churn" in warned[0].message
    # three executables, one launch each, all under the one name
    recs = obs.cost_snapshot()
    assert [r["name"] for r in recs] == ["test.churn"] * 3
    assert all(r["launches"] == 1 for r in recs)


def test_costed_jit_lazy_enables_after_wrap(telemetry):
    """The module-scope form: wrapped while telemetry is OFF (import
    time), it must still attribute once telemetry turns on — and go
    quiet again when it turns off."""
    obs.set_enabled(False)

    def f(x):
        return x.sum()

    lz = costs_mod.costed_jit("test.lazylate", f, lazy=True)
    assert isinstance(lz, costs_mod.CostedJit)
    float(lz(jnp.ones(4)))
    assert obs.cost_snapshot() == []
    obs.set_enabled(True)
    float(lz(jnp.ones(4)))
    (rec,) = obs.cost_snapshot(reset=True)
    assert rec["name"] == "test.lazylate" and rec["launches"] == 1
    obs.set_enabled(False)
    float(lz(jnp.ones(4)))
    assert obs.cost_snapshot() == []


def test_costed_jit_tracer_args_fall_through(telemetry):
    """Called from inside another trace (tracer args), the wrapper must
    fall through to the plain jitted path — correct value, no bogus
    cost entry."""
    inner = obs.costed_jit("test.inner", lambda x: x * 2.0)

    @jax.jit
    def outer(x):
        return inner(x).sum()

    assert float(outer(jnp.ones(4))) == 8.0
    assert all(r["name"] != "test.inner" for r in obs.cost_snapshot())


def test_costed_jit_results_match_plain_jit(telemetry, rng):
    """AOT dispatch is an implementation detail: outputs must equal the
    plain jitted fn's, including committed/sharded-style numpy inputs."""
    def f(x, w):
        return jnp.tanh(x @ w).sum(axis=1)

    x = rng.normal(size=(32, 8)).astype(np.float32)
    w = rng.normal(size=(8, 8)).astype(np.float32)
    cj = obs.costed_jit("test.parity", f)
    np.testing.assert_allclose(np.asarray(cj(x, w)),
                               np.asarray(jax.jit(f)(x, w)), rtol=1e-6)


def test_record_executable_direct_hook(telemetry):
    """The lower-level API: code holding a (lowered, compiled) pair
    registers it; the signature derives from the lowering."""
    def f(x):
        return x * 3.0

    lowered = jax.jit(f).lower(jnp.ones((4, 4)))
    obs.record_executable("test.direct", lowered, lowered.compile())
    (rec,) = obs.cost_snapshot()
    assert rec["name"] == "test.direct" and rec["compiles"] == 1
    assert "[4,4]" in rec["signature"]


# ------------------------------------------------------- analytic models
def test_pallas_and_scatter_cost_models_registered(telemetry):
    import shifu_tpu.ops.hist_pallas  # noqa: F401  (registers pallas.hist)
    import shifu_tpu.ops.tree         # noqa: F401  (registers scatter)
    models = costs_mod.cost_models()
    assert "pallas.hist" in models and "tree.scatter_hist" in models
    est = models["pallas.hist"](rows=1024, n_feat=64, n_bins=64,
                                n_nodes=8, n_stats=2, n_trees=1)
    # dominant term: 2*N*K*B*S*C MACs
    assert est["flops"] >= 2.0 * 1024 * 8 * 64 * 2 * 64
    assert est["bytes_accessed"] > 0


def test_record_model_launch_accumulates(telemetry):
    import shifu_tpu.ops.hist_pallas  # noqa: F401
    for _ in range(3):
        obs.record_model_launch("pallas.hist", rows=512, n_feat=8,
                                n_bins=16, n_nodes=4)
    (rec,) = obs.cost_snapshot()
    assert rec["name"] == "pallas.hist" and rec["analytic"]
    assert rec["launches"] == 3 and rec["flops"] > 0
    assert "rows=512" in rec["signature"]
    # unknown model: silent no-op, never a crash
    obs.record_model_launch("pallas.nope", rows=1)


# ------------------------------------------------ flush / trace plumbing
def test_flush_emits_cost_records_and_backend_meta(telemetry, tmp_path):
    cj = obs.costed_jit("test.flushme", lambda x: x.sum())
    with obs.span("TRAIN", kind="step"):
        float(cj(jnp.ones(16)))
    trace = str(tmp_path / "telemetry" / "trace.jsonl")
    assert obs.flush(trace, step="TRAIN")
    lines = [json.loads(line) for line in open(trace)]
    assert lines[0]["schema_version"] == obs.SCHEMA_VERSION == 14
    assert lines[0]["backend"]["platform"]      # peak-table resolver key
    costs = [ln for ln in lines if ln["kind"] == "cost"]
    assert len(costs) == 1 and costs[0]["name"] == "test.flushme"
    from shifu_tpu.obs.report import load_blocks
    (block,) = load_blocks(trace)
    assert block["costs"] == costs
    # flush drained the cost accumulation: a second flush adds none
    assert obs.flush(trace, step="EMPTY")
    lines2 = [json.loads(line) for line in open(trace)]
    assert sum(1 for ln in lines2 if ln["kind"] == "cost") == 1
    # ...but a warm relaunch re-emits the entry with launches=1
    float(cj(jnp.ones(16)))
    assert obs.flush(trace, step="WARM")
    lines3 = [json.loads(line) for line in open(trace)]
    warm = [ln for ln in lines3 if ln["kind"] == "cost"][-1]
    assert warm["launches"] == 1 and warm["compiles"] == 0


# ------------------------------------------------------------ peak table
def test_resolve_peaks_table_and_env_override(monkeypatch):
    monkeypatch.delenv("SHIFU_TPU_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("SHIFU_TPU_PEAK_BW", raising=False)
    f, b, label = costs_mod.resolve_peaks({"platform": "tpu",
                                           "device_kind": "TPU v4"})
    assert (f, b) == (275e12, 1228e9) and label == "tpu v4"
    f, b, _ = costs_mod.resolve_peaks({"platform": "cpu",
                                       "device_kind": "cpu"})
    assert (f, b) == (1e11, 5e10)
    monkeypatch.setenv("SHIFU_TPU_PEAK_FLOPS", "2e12")
    monkeypatch.setenv("SHIFU_TPU_PEAK_BW", "3e11")
    f, b, label = costs_mod.resolve_peaks({"platform": "cpu",
                                           "device_kind": "cpu"})
    assert (f, b) == (2e12, 3e11)
    assert "SHIFU_TPU_PEAK_FLOPS" in label


def test_verdict_roofline_split():
    # machine balance = 1e11/5e10 = 2 FLOPs/byte
    assert util_mod.verdict_for(4e6, 1e6, 1e11, 5e10) == "compute-bound"
    assert util_mod.verdict_for(1e6, 4e6, 1e11, 5e10) == "bandwidth-bound"
    assert util_mod.verdict_for(0, 0, 1e11, 5e10) == "no-cost-data"


# ------------------------------------------------- utilization report
def _write_golden_trace(td):
    """A hand-built v6 trace with FIXED values — the golden's input."""
    os.makedirs(os.path.join(td, "telemetry"))
    lines = [
        {"kind": "meta", "schema_version": 7, "step": "TRAIN", "ts": 1.0,
         "pid": 7, "backend": {"platform": "cpu", "device_kind": "cpu"}},
        {"kind": "span", "name": "TRAIN", "id": 1, "parent": None,
         "ts": 1.0, "dur_s": 2.0, "tid": "MainThread", "attrs": {}},
        {"kind": "metric", "type": "counter", "name": "ingest.rows_emitted",
         "value": 9000.0},
        {"kind": "metric", "type": "counter", "name": "ingest.rows_padded",
         "value": 1000.0},
        {"kind": "metric", "type": "counter", "name": "xla.recompiles",
         "value": 1.0},
        {"kind": "cost", "name": "gbt.forest", "signature": "f32[100,8]",
         "flops": 4.0e9, "bytes_accessed": 1.0e9, "compiles": 1,
         "launches": 2, "analytic": False},
        {"kind": "cost", "name": "nn.step", "signature": "f32[100,8]",
         "flops": 1.0e9, "bytes_accessed": 4.0e9, "compiles": 1,
         "launches": 1, "analytic": False},
    ]
    with open(os.path.join(td, "telemetry", "trace.jsonl"), "w") as f:
        for ln in lines:
            f.write(json.dumps(ln) + "\n")


def test_utilization_report_golden(telemetry, tmp_path, monkeypatch):
    """CI/tooling satellite: the report renders DETERMINISTICALLY —
    stable plane sort, fixed float formats — so this golden is
    diff-stable."""
    monkeypatch.setenv("SHIFU_TPU_PEAK_FLOPS", "1e11")
    monkeypatch.setenv("SHIFU_TPU_PEAK_BW", "5e10")
    td = str(tmp_path)
    _write_golden_trace(td)
    text = util_mod.render_utilization(td)
    assert text == util_mod.render_utilization(td)   # deterministic
    lines = text.splitlines()
    assert lines[0].startswith("utilization: ")
    assert "== TRAIN  wall 2.000s" in lines
    # gbt: 8e9 flops (4e9 x 2 launches) / 2s = 4e9 FLOP/s = 4% of 1e11;
    # 2e9 B (1e9 x 2) / 2s = 1e9 B/s = 2% of 5e10; intensity 4 >= 2
    gbt = next(ln for ln in lines if ln.strip().startswith("gbt"))
    assert "8.000e+09" in gbt and "4.000e+09" in gbt
    assert "4.00%" in gbt and "2.00%" in gbt
    assert gbt.rstrip().endswith("compute-bound")
    # nn: 1e9/2s = 5e8 FLOP/s (0.5%); 4e9 B -> 2e9 B/s (4%); intensity
    # 0.25 < balance 2 -> bandwidth-bound
    nn = next(ln for ln in lines if ln.strip().startswith("nn"))
    assert "5.000e+08" in nn and nn.rstrip().endswith("bandwidth-bound")
    assert any("2 costed, 2 compile(s), 3 launch(es)" in ln
               and "1 RECOMPILE(S)" in ln for ln in lines)
    # padding waste: 1000 padded of 10000 window rows = 10%
    assert any("1,000 padded of 10,000" in ln and "10.00%" in ln
               for ln in lines)
    # pipeline closing line: MFU = 9e9 flops / (2s * 1e11)
    assert lines[-1].startswith("pipeline: ")
    assert "MFU 4.50%" in lines[-1]


def test_utilization_acceptance_gbt_plus_nn(telemetry, tmp_path, rng):
    """ACCEPTANCE: `analysis --telemetry --utilization` on a GBT-train +
    NN-train run reports per-plane achieved FLOP/s, bytes/s,
    percent-of-peak and a roofline verdict."""
    from shifu_tpu.models.nn import NNModelSpec
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt
    from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble

    n, d = 256, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) < 0.4).astype(np.float32)
    w = np.ones((1, n), np.float32)
    spec = NNModelSpec(input_dim=d, hidden_nodes=[8],
                       activations=["tanh"])
    with obs.span("TRAIN", kind="step"):
        train_ensemble(x, y, w, w, spec,
                       TrainSettings(optimizer="ADAM", learning_rate=0.01,
                                     epochs=3))
    bins = rng.integers(0, 16, size=(512, d)).astype(np.int32)
    yt = (rng.random(512) < 0.3).astype(np.float32)
    wt = np.ones(512, np.float32)
    with obs.span("TRAIN", kind="step"):
        train_gbt(bins, yt, wt, 16, np.zeros(d, bool),
                  DTSettings(n_trees=3, depth=3, loss="log",
                             learning_rate=0.1))
    obs.flush(os.path.join(str(tmp_path), "telemetry", "trace.jsonl"),
              step="TRAIN")

    text = util_mod.render_utilization(str(tmp_path))
    lines = text.splitlines()
    nn_line = next(ln for ln in lines if ln.strip().startswith("nn"))
    gbt_line = next(ln for ln in lines if ln.strip().startswith("gbt"))
    for ln in (nn_line, gbt_line):
        assert "e+0" in ln or "e-0" in ln        # achieved rates render
        assert "%" in ln                         # percent-of-peak
        assert ln.rstrip().endswith(("compute-bound", "bandwidth-bound"))
    assert "launch(es)" in text
    # the CLI surface returns 0 and prints the same payload
    from shifu_tpu.cli import main
    assert main(["--dir", str(tmp_path), "analysis", "--telemetry",
                 "--utilization"]) == 0


# -------------------------------------------------------- padding waste
def test_streamed_windows_count_padded_rows(telemetry, tmp_path):
    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream

    rng = np.random.default_rng(0)
    sd = str(tmp_path / "shards")
    os.makedirs(sd)
    rows = 700                                  # 2 windows of 512: 324 pad
    np.savez(os.path.join(sd, "part-00000.npz"),
             bins=rng.integers(0, 16, (rows, 4)).astype(np.int16),
             y=np.zeros(rows, np.float32), w=np.ones(rows, np.float32))
    with open(os.path.join(sd, "schema.json"), "w") as f:
        json.dump({"columnNums": list(range(4)), "numShards": 1,
                   "numRows": rows}, f)
    stream = ShardStream(Shards.open(sd), ("bins", "y", "w"), 512,
                         spill=False)
    for _ in stream.windows():
        pass
    assert _metric("ingest.rows_emitted")["value"] == rows
    assert _metric("ingest.rows_padded")["value"] == 2 * 512 - rows


# ------------------------------------------- timeline costs + torn lines
def test_timeline_annotates_costs_and_tolerates_torn_tail(telemetry,
                                                          tmp_path):
    """Timeline-hardening satellite: a torn final trace.jsonl line is
    skipped (surfaced in otherData.torn_lines_skipped), and cost
    records annotate the export — root spans carry flops/bytes args,
    executables land as cost: instants."""
    _write_golden_trace(str(tmp_path))
    trace = os.path.join(str(tmp_path), "telemetry", "trace.jsonl")
    with open(trace, "a") as f:
        f.write('{"kind": "cost", "name": "torn')     # crash mid-write
    skipped = []
    out = timeline_mod.export_timeline(str(tmp_path),
                                       str(tmp_path / "tl.json"),
                                       skipped=skipped)
    assert out and len(skipped) == 1
    with open(out) as f:
        doc = json.load(f)
    assert doc["otherData"]["torn_lines_skipped"] == 1
    root = next(e for e in doc["traceEvents"]
                if e["ph"] == "X" and e["name"] == "TRAIN")
    assert root["args"]["flops"] == pytest.approx(9.0e9)   # 4e9*2 + 1e9
    assert root["args"]["bytes_accessed"] == pytest.approx(6.0e9)
    cost_ev = [e for e in doc["traceEvents"]
               if e["ph"] == "i" and e["name"].startswith("cost:")]
    assert {e["name"] for e in cost_ev} == {"cost:gbt.forest",
                                            "cost:nn.step"}
    assert cost_ev[0]["args"]["flops"] > 0


# --------------------------------------------------- monitor --json
def _health_rec(proc, ts, state="running", **kw):
    rec = {"proc": proc, "step": "TRAIN", "state": state, "ts": ts,
           "last_progress_ts": ts, "interval_s": 0.5, "rows": 10}
    rec.update(kw)
    return rec


def test_monitor_json_snapshot_and_exit_codes(tmp_path):
    """Satellite: `monitor --once --json` emits ONE machine-readable doc
    (per-proc health + quorum summary); exit 0 healthy, 3 when any proc
    is stalled or stale."""
    from shifu_tpu.obs.health import health_dir_for
    hd = health_dir_for(str(tmp_path))
    os.makedirs(hd)
    now = time.time()
    with open(os.path.join(hd, "a.json"), "w") as f:
        json.dump(_health_rec("train-1", now), f)
    with open(os.path.join(hd, "b.json"), "w") as f:
        json.dump(_health_rec("train-2", now, state="exited",
                              exit_code=0), f)
    doc, rc = monitor_mod.status_json(str(tmp_path), now=now)
    assert rc == 0
    assert doc["kind"] == "monitor" and doc["schema_version"] == 14
    assert doc["summary"]["counts"] == {"live": 1, "stalled": 0,
                                        "stale": 0, "exited": 1}
    assert doc["summary"]["quorum"] == 1.0
    assert {p["proc"] for p in doc["procs"]} == {"train-1", "train-2"}
    assert all("status" in p and "age_s" in p for p in doc["procs"])
    json.dumps(doc)                              # strictly serializable

    # one proc stops beating -> stale -> exit 3
    with open(os.path.join(hd, "a.json"), "w") as f:
        json.dump(_health_rec("train-1", now - 60), f)
    doc, rc = monitor_mod.status_json(str(tmp_path), now=now)
    assert rc == monitor_mod.EXIT_UNHEALTHY == 3
    assert doc["summary"]["counts"]["stale"] == 1

    # the CLI loop path prints exactly one JSON doc and returns the code
    printed = []
    rc = monitor_mod.run_monitor(str(tmp_path), once=True, json_mode=True,
                                 _print=printed.append)
    assert rc == 3 and len(printed) == 1
    assert json.loads(printed[0])["kind"] == "monitor"
    # empty dir: healthy (nothing running), exit 0, still a JSON doc
    doc, rc = monitor_mod.status_json(str(tmp_path / "none"))
    assert rc == 0 and doc["procs"] == []


def test_monitor_json_cli_exit_zero_empty(tmp_path):
    import subprocess
    import sys
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.run(
        [sys.executable, "-m", "shifu_tpu.cli", "--dir", str(tmp_path),
         "monitor", "--once", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert p.returncode == 0, p.stderr
    doc = json.loads(p.stdout)
    assert doc["kind"] == "monitor" and doc["procs"] == []


# --------------------------------------------------- compare auto-mode
def test_compare_auto_mode_resolution(tmp_path):
    """Satellite: `--compare` with no arguments picks the two newest
    BENCH_r*.json (round order); fewer than two is a clear error."""
    from shifu_tpu.bench import resolve_compare_paths

    # explicit pair passes through untouched
    assert resolve_compare_paths(["a.json", "b.json"]) == ("a.json",
                                                           "b.json")
    with pytest.raises(ValueError, match="exactly two"):
        resolve_compare_paths(["only.json"])
    # auto mode against a synthetic root
    for n in ("BENCH_r01.json", "BENCH_r02.json", "BENCH_r10.json"):
        with open(tmp_path / n, "w") as f:
            json.dump({"metric": "m", "value": 1.0}, f)
    old, new = resolve_compare_paths([], root=str(tmp_path))
    assert os.path.basename(old) == "BENCH_r02.json"
    assert os.path.basename(new) == "BENCH_r10.json"
    (tmp_path / "BENCH_r02.json").unlink()
    (tmp_path / "BENCH_r10.json").unlink()
    with pytest.raises(ValueError, match="at least two BENCH_r"):
        resolve_compare_paths([], root=str(tmp_path))
    # the in-repo trajectory satisfies auto mode (default root)
    old, new = resolve_compare_paths([])
    assert os.path.basename(new) > os.path.basename(old)


def test_compare_auto_mode_cli(tmp_path):
    import subprocess
    import sys
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--compare"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
        timeout=120)
    # repo root holds r01..r05: auto mode runs and prints the table
    assert p.returncode in (0, 2), p.stderr
    assert "bench compare:" in p.stdout
    assert "BENCH_r0" in p.stdout


# ------------------------------------------------------- bench mfu fold
def test_mfu_extras_fold(monkeypatch):
    from shifu_tpu.bench import _mfu_extras
    monkeypatch.setenv("SHIFU_TPU_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("SHIFU_TPU_PEAK_BW", "1e11")
    extras = {}
    col = {"flops_per_window": 2e9, "bytes_per_window": 1e9,
           "rows_per_window": 1000}
    _mfu_extras("nn_train", 10_000.0, col, extras)   # window wall = 0.1s
    assert extras["nn_train_achieved_flops"] == pytest.approx(2e10)
    assert extras["nn_train_mfu"] == pytest.approx(0.02)
    assert extras["nn_train_achieved_bw"] == pytest.approx(1e10)
    assert extras["nn_train_bw_frac_of_peak"] == pytest.approx(0.1)
    assert "peaks_provenance" in extras
    # no rows collected (cost analysis failed): no extras, no crash
    before = dict(extras)
    _mfu_extras("wdl_train", 10_000.0, {}, extras)
    assert extras == before
