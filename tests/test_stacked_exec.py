"""Stacked execution paths (VERDICT round-2 item 7): grid trials as one
vmapped run, bagged scorer as one jit call, PSI flat in column count."""

import json
import os
import time

import numpy as np
import pytest


# --------------------------------------------------------- grid stacking
def test_stackable_groups_partition():
    from shifu_tpu.train.grid_search import expand, stackable_groups
    trials = expand({"Propagation": "ADAM", "LearningRate": [0.1, 0.2],
                     "NumHiddenNodes": [[8], [8, 4]],
                     "ActivationFunc": ["tanh"]})
    assert len(trials) == 4
    groups = stackable_groups(trials)
    # two shapes x two LRs -> 2 groups of 2 stacked trials
    assert sorted(len(g) for g in groups) == [2, 2]
    for g in groups:
        shapes = {json.dumps(trials[t]["NumHiddenNodes"]) for t in g}
        assert len(shapes) == 1


def test_member_hypers_match_serial_runs():
    """One vmapped run with per-member (lr, l2) arrays must reproduce each
    serially-trained trial bit-for-bit (same init, same split)."""
    import jax
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble
    from shifu_tpu.train.sampling import member_masks

    rng = np.random.default_rng(0)
    n, d = 512, 6
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) < 1 / (1 + np.exp(-x[:, 0]))).astype(np.float32)
    spec = nn_model.NNModelSpec(input_dim=d, hidden_nodes=[8],
                                activations=["tanh"], loss="log")
    tw1, vw1 = member_masks(n, 1, valid_rate=0.25, sample_rate=1.0,
                            replacement=False, targets=y, seed=0)
    p0 = nn_model.init_params(jax.random.PRNGKey(0), spec)

    lrs = [0.05, 0.2]
    l2s = [0.0, 1e-3]
    serial = []
    for lr, l2 in zip(lrs, l2s):
        s = TrainSettings(optimizer="ADAM", learning_rate=lr, l2=l2,
                          epochs=8, seed=0)
        r = train_ensemble(x, y, tw1, vw1, spec, s, init_params_list=[p0])
        serial.append(r)

    base = TrainSettings(optimizer="ADAM", learning_rate=lrs[0], l2=l2s[0],
                         epochs=8, seed=0)
    stacked = train_ensemble(
        x, y, np.tile(tw1, (2, 1)), np.tile(vw1, (2, 1)), spec, base,
        init_params_list=[p0, p0],
        member_hypers={"lr_scale": np.array([1.0, lrs[1] / lrs[0]]),
                       "l2": np.array(l2s),
                       "l1": np.zeros(2), "dropout": np.zeros(2)})
    for k in range(2):
        np.testing.assert_allclose(stacked.valid_errors[k],
                                   serial[k].valid_errors[0],
                                   rtol=1e-4, atol=1e-6)


def test_pipeline_grid_stacked_trains_concurrently(model_set):
    """A 4-trial same-shape grid = ONE run (progress shows one trial group);
    report still ranks all 4."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.numTrainEpochs = 8
    mc.train.params = {"Propagation": "ADAM",
                       "LearningRate": [0.02, 0.05, 0.1, 0.2],
                       "NumHiddenNodes": [8], "ActivationFunc": ["tanh"]}
    mc.save(mcp)
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0
    report = json.load(open(os.path.join(model_set, "tmp",
                                         "grid_search.json")))
    assert len(report) == 4
    assert report[0]["validError"] <= report[-1]["validError"]
    # all 4 trials trained as one vmapped group -> progress file has ONE
    # trial tag listing all four indices
    progress = open(os.path.join(model_set, "tmp",
                                 "train.progress")).read()
    assert "Trial [0, 1, 2, 3]" in progress


# --------------------------------------------------------- scorer stacking
def test_scorer_stacks_same_shape_nn(tmp_path):
    import jax
    from shifu_tpu.eval.scorer import Scorer
    from shifu_tpu.models import nn as nn_model

    spec = nn_model.NNModelSpec(input_dim=4, hidden_nodes=[6],
                                activations=["tanh"])
    models = []
    for i in range(5):
        p = nn_model.init_params(jax.random.PRNGKey(i), spec)
        path = os.path.join(tmp_path, f"model{i}.nn")
        nn_model.save_model(path, spec, p)
        models.append(nn_model.IndependentNNModel.load(path))
    sc = Scorer(models)
    groups = sc._stacked_nn_groups()
    assert len(groups) == 1 and len(groups[0][0]) == 5   # one stack of 5
    x = np.random.default_rng(0).normal(size=(64, 4)).astype(np.float32)
    res = sc.score(x)
    # stacked result must equal per-model compute
    for i, m in enumerate(models):
        np.testing.assert_allclose(res.scores[:, i],
                                   m.compute(x)[:, 0] * 1000.0,
                                   rtol=1e-5, atol=1e-3)


def test_scorer_mixed_shapes_fall_back(tmp_path):
    import jax
    from shifu_tpu.eval.scorer import Scorer
    from shifu_tpu.models import nn as nn_model

    specs = [nn_model.NNModelSpec(input_dim=4, hidden_nodes=[6],
                                  activations=["tanh"]),
             nn_model.NNModelSpec(input_dim=4, hidden_nodes=[3],
                                  activations=["tanh"])]
    models = []
    for i, sp in enumerate(specs):
        p = nn_model.init_params(jax.random.PRNGKey(i), sp)
        path = os.path.join(tmp_path, f"model{i}.nn")
        nn_model.save_model(path, sp, p)
        models.append(nn_model.IndependentNNModel.load(path))
    sc = Scorer(models)
    assert sc._stacked_nn_groups() == []     # nothing to stack
    x = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    res = sc.score(x)
    assert res.scores.shape == (16, 2)


# ----------------------------------------------------------------- PSI
def _psi_model_set(model_set, psi_col="channel"):
    from shifu_tpu.config import ModelConfig
    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.stats.psiColumnName = psi_col
    mc.save(mcp)
    return model_set


def test_psi_vectorized_matches_reference_math(model_set):
    """Vectorized PSI equals a direct per-unit histogram computation."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.column_config import load_column_configs
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.ops.stats_math import psi as psi_fn

    _psi_model_set(model_set)
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={"psi": True}).run() == 0
    ccs = load_column_configs(os.path.join(model_set, "ColumnConfig.json"))
    amount = next(c for c in ccs if c.columnName == "amount")
    assert amount.columnStats.psi is not None
    assert amount.columnStats.psi >= 0
    assert len(amount.columnStats.unitStats) == 3      # web/app/pos

    # recompute directly from raw csv for one column
    import pandas as pd
    mc = ModelConfig.load(os.path.join(model_set, "ModelConfig.json"))
    df = pd.read_csv(mc.dataSet.dataPath, sep="|")
    bounds = np.asarray(amount.bin_boundary)
    vals = pd.to_numeric(df["amount"], errors="coerce").to_numpy()
    idx = np.searchsorted(bounds[1:], vals, side="right")
    idx = np.where(np.isnan(vals), len(bounds), idx)   # missing bin
    nb = len(bounds) + 1
    hists = {u: np.bincount(idx[(df["channel"] == u).to_numpy()],
                            minlength=nb)
             for u in sorted(df["channel"].unique())}
    overall = np.sum(list(hists.values()), axis=0)
    for stat in amount.columnStats.unitStats:
        u, v = stat.rsplit(":", 1)
        np.testing.assert_allclose(float(v), psi_fn(overall, hists[u]),
                                   atol=1e-6)


def test_rprop_lr_axis_not_stacked():
    """RPROP ignores LearningRate, so an LR axis must NOT group (stacking
    would scale rprop's adaptive steps by a meaningless multiplier)."""
    from shifu_tpu.train.grid_search import expand, stackable_groups
    trials = expand({"Propagation": "R", "LearningRate": [0.05, 0.1, 0.2],
                     "NumHiddenNodes": [8]})
    groups = stackable_groups(trials)
    assert sorted(len(g) for g in groups) == [1, 1, 1]
    # ...while ADAM's LR axis stacks into one group
    trials = expand({"Propagation": "ADAM", "LearningRate": [0.05, 0.1, 0.2],
                     "NumHiddenNodes": [8]})
    assert [len(g) for g in stackable_groups(trials)] == [3]
