"""`new` + `init` pipeline steps over the synthetic fraud dataset."""

import os

import pytest

from shifu_tpu.config import ColumnFlag, ColumnType, ModelConfig, load_column_configs
from shifu_tpu.data import DataSource, parse_numeric, tag_to_target
from shifu_tpu.pipeline.create import InitProcessor, create_new_model


def test_new_scaffolds_model_config(tmp_path):
    mdir = create_new_model("m1", base_dir=str(tmp_path), algorithm="GBT")
    mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
    assert mc.basic.name == "m1"
    assert mc.train.algorithm.name == "GBT"
    with pytest.raises(FileExistsError):
        create_new_model("m1", base_dir=str(tmp_path))


def test_init_builds_column_config(model_set):
    assert InitProcessor(model_set).run() == 0
    ccs = load_column_configs(os.path.join(model_set, "ColumnConfig.json"))
    by_name = {c.columnName: c for c in ccs}
    assert by_name["tag"].columnFlag == ColumnFlag.Target
    assert by_name["weight"].columnFlag == ColumnFlag.Weight
    # auto-type: country/channel/txn_id categorical, amount numeric
    assert by_name["country"].columnType == ColumnType.C
    assert by_name["channel"].columnType == ColumnType.C
    assert by_name["txn_id"].columnType == ColumnType.C
    assert by_name["amount"].columnType == ColumnType.N
    assert by_name["noise"].columnType == ColumnType.N


def test_reader_and_target_parse(fraud_csv):
    src = DataSource(fraud_csv, "|")
    assert src.header[0] == "txn_id" and src.header[-1] == "tag"
    chunk = src.read_all()
    assert len(chunk) == 4000
    y = tag_to_target(chunk.col("tag"), ["bad"], ["good"])
    assert set(y.tolist()) <= {0.0, 1.0}
    amt, valid = parse_numeric(chunk.col("amount"), missing_values=["", "?"])
    assert valid.sum() < len(valid)  # some missing
    assert amt[valid].min() >= 0
