"""`new` + `init` pipeline steps over the synthetic fraud dataset."""

import os

import numpy as np
import pytest

from shifu_tpu.config import ColumnFlag, ColumnType, ModelConfig, load_column_configs
from shifu_tpu.data import DataSource, parse_numeric, tag_to_target
from shifu_tpu.pipeline.create import InitProcessor, create_new_model


def test_new_scaffolds_model_config(tmp_path):
    mdir = create_new_model("m1", base_dir=str(tmp_path), algorithm="GBT")
    mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
    assert mc.basic.name == "m1"
    assert mc.train.algorithm.name == "GBT"
    with pytest.raises(FileExistsError):
        create_new_model("m1", base_dir=str(tmp_path))


def test_init_builds_column_config(model_set):
    assert InitProcessor(model_set).run() == 0
    ccs = load_column_configs(os.path.join(model_set, "ColumnConfig.json"))
    by_name = {c.columnName: c for c in ccs}
    assert by_name["tag"].columnFlag == ColumnFlag.Target
    assert by_name["weight"].columnFlag == ColumnFlag.Weight
    # auto-type: country/channel/txn_id categorical, amount numeric
    assert by_name["country"].columnType == ColumnType.C
    assert by_name["channel"].columnType == ColumnType.C
    assert by_name["txn_id"].columnType == ColumnType.C
    assert by_name["amount"].columnType == ColumnType.N
    assert by_name["noise"].columnType == ColumnType.N


def test_reader_and_target_parse(fraud_csv):
    src = DataSource(fraud_csv, "|")
    assert src.header[0] == "txn_id" and src.header[-1] == "tag"
    chunk = src.read_all()
    assert len(chunk) == 4000
    y = tag_to_target(chunk.col("tag"), ["bad"], ["good"])
    assert set(y.tolist()) <= {0.0, 1.0}
    amt, valid = parse_numeric(chunk.col("amount"), missing_values=["", "?"])
    assert valid.sum() < len(valid)  # some missing
    assert amt[valid].min() >= 0


def test_hll_estimate_accuracy():
    """HLL distinct estimate within ~5% at p=12 (reference HyperLogLogPlus
    role in AutoTypeDistinctCountMapper)."""
    from shifu_tpu.ops.sketches import HyperLogLog
    rng = np.random.default_rng(0)
    for true_n in (10, 1000, 50_000):
        h = HyperLogLog()
        vals = np.array([f"v{i}" for i in range(true_n)])
        # feed in shuffled chunks with repeats
        for _ in range(3):
            h.update(rng.permutation(vals))
        est = h.estimate()
        assert abs(est - true_n) / true_n < 0.05, (true_n, est)


def test_auto_type_rules(tmp_path):
    """InitModelProcessor.java:185-250 rules: 0/1 binary stays numeric,
    low-cardinality strings go categorical, distinctCount recorded."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.column_config import load_column_configs
    from shifu_tpu.pipeline.create import InitProcessor, create_new_model

    rng = np.random.default_rng(1)
    n = 3000
    rows = ["flag|code|amount|tag"]
    for i in range(n):
        rows.append(f"{rng.integers(0, 2)}|"
                    f"{rng.choice(['A1', 'B2', 'C3'])}|"
                    f"{rng.normal():.4f}|"
                    f"{'bad' if rng.random() < 0.2 else 'good'}")
    csv = tmp_path / "d.csv"
    csv.write_text("\n".join(rows) + "\n")
    mdir = create_new_model("att", base_dir=str(tmp_path))
    mcp = os.path.join(mdir, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.dataSet.dataPath = str(csv)
    mc.dataSet.dataDelimiter = "|"
    mc.dataSet.targetColumnName = "tag"
    mc.dataSet.posTags, mc.dataSet.negTags = ["bad"], ["good"]
    mc.save(mcp)
    assert InitProcessor(mdir).run() == 0
    by_name = {c.columnName: c
               for c in load_column_configs(os.path.join(
                   mdir, "ColumnConfig.json"))}
    assert not by_name["flag"].is_categorical()     # 0/1 binary -> numeric
    assert not by_name["amount"].is_categorical()   # doubles -> numeric
    assert by_name["code"].is_categorical()         # strings -> categorical
    assert by_name["flag"].columnStats.distinctCount == 2
    assert by_name["code"].columnStats.distinctCount == 3
    assert by_name["amount"].columnStats.distinctCount > 2000


def test_nscolumn_matching(tmp_path):
    """Namespaced headers (raw::amount) match bare names in meta files and
    target config (reference column/NSColumn.java equality)."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import InitProcessor, create_new_model

    rows = ["id|raw::amount|raw::kind|tag",
            "r1|10.5|A|bad", "r2|3.2|B|good", "r3|7.7|A|good"]
    csv = tmp_path / "ns.csv"
    csv.write_text("\n".join(rows) + "\n")
    meta = tmp_path / "meta.names"
    meta.write_text("id\n")
    cate = tmp_path / "cate.names"
    cate.write_text("kind\n")                     # bare name, ns'd header
    mdir = create_new_model("nst", base_dir=str(tmp_path))
    mcp = os.path.join(mdir, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.dataSet.dataPath = str(csv)
    mc.dataSet.dataDelimiter = "|"
    mc.dataSet.targetColumnName = "tag"
    mc.dataSet.posTags, mc.dataSet.negTags = ["bad"], ["good"]
    mc.dataSet.metaColumnNameFile = str(meta)
    mc.dataSet.categoricalColumnNameFile = str(cate)
    mc.save(mcp)
    assert InitProcessor(mdir).run() == 0
    by_name = {c.columnName: c for c in load_column_configs(
        os.path.join(mdir, "ColumnConfig.json"))}
    assert by_name["raw::kind"].is_categorical()
    assert by_name["id"].is_meta()
    assert by_name["tag"].is_target()


def test_ns_match_semantics():
    from shifu_tpu.config.column_config import ns_match
    assert ns_match("amount", "raw::amount")       # bare vs namespaced
    assert ns_match("raw::amount", "amount")
    assert ns_match("a::b::amount", "amount")
    assert not ns_match("a::score", "b::score")    # different namespaces
    assert not ns_match("amount", "velocity")


def test_frequent_items_order_independent():
    """MG merge: a globally-frequent sentinel survives regardless of chunk
    order, even through high-cardinality churn."""
    from shifu_tpu.ops.sketches import FrequentItems
    rng = np.random.default_rng(0)
    noise = [f"u{i}" for i in range(40_000)]
    sentinel = ["MISSING"] * 4000
    for order in (noise + sentinel, sentinel + noise):
        f = FrequentItems(cap=1024)
        arr = np.array(order)
        for s in range(0, len(arr), 8192):
            f.update(arr[s:s + 8192])
        assert "MISSING" in f.top(), order[:2]
