"""Multi-tree batched histogram grids + sync-free forest growth.

Parity guards for the tree-batch round: the batched Pallas kernel and the
batched forest growth must be BIT-identical to their sequential
counterparts (both the interpret/Mosaic kernel path and the scatter
fallback), and the trainers' host-sync count must scale with
checkpoint/progress intervals — never with trees.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from shifu_tpu.ops.hist_pallas import (build_histograms_pallas,
                                       build_histograms_pallas_batch)
from shifu_tpu.ops.tree import (build_histograms, build_histograms_batch,
                                grow_forest_jit, grow_tree_jit)


@pytest.mark.parametrize("n,c,b,k,s,tb", [
    (2000, 6, 16, 8, 2, 5),       # typical level shapes
    (1500, 9, 64, 1, 2, 8),       # root level: the skinny-operand case
    (1200, 5, 130, 8, 3, 3),      # bins past one lane tile
    (1000, 4, 64, 128, 2, 3),     # deep level: K_MAX partitioning path
])
def test_batched_kernel_bit_matches_sequential(n, c, b, k, s, tb):
    """Each tree's slice of the batched kernel output must BIT-match a
    sequential single-tree kernel call (same nblk blocking, channel
    pairing and bf16 hi/lo split per tree — only the dispatch fuses)."""
    rng = np.random.default_rng(42)
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node_b = jnp.asarray(rng.integers(-1, k, (tb, n)), jnp.int32)
    stats_b = jnp.asarray(rng.normal(size=(tb, n, s)), jnp.float32)
    out = np.asarray(build_histograms_pallas_batch(
        bins, node_b, stats_b, k, b, interpret=True))
    assert out.shape == (tb, k, c, b, s)
    for t in range(tb):
        ref = np.asarray(build_histograms_pallas(
            bins, node_b[t], stats_b[t], k, b, interpret=True))
        np.testing.assert_array_equal(out[t], ref)


def test_batched_kernel_exact_channels_bit_match():
    """``exact=True`` (bf16-exact RF bag stats) through the batched
    kernel == sequential exact kernel, bit for bit."""
    rng = np.random.default_rng(3)
    n, c, b, k, tb = 1500, 5, 32, 8, 4
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node_b = jnp.asarray(rng.integers(-1, k, (tb, n)), jnp.int32)
    bag = rng.poisson(1.0, (tb, n)).astype(np.float32)
    y = (rng.random(n) < 0.4).astype(np.float32)
    stats_b = jnp.asarray(np.stack([bag, bag * y[None, :]], axis=2))
    out = np.asarray(build_histograms_pallas_batch(
        bins, node_b, stats_b, k, b, interpret=True, exact=True))
    for t in range(tb):
        ref = np.asarray(build_histograms_pallas(
            bins, node_b[t], stats_b[t], k, b, interpret=True, exact=True))
        np.testing.assert_array_equal(out[t], ref)


def test_batched_scatter_fallback_bit_matches_sequential():
    """The CPU scatter fallback (vmapped segment_sum) == per-tree
    sequential scatter builds, bit for bit."""
    rng = np.random.default_rng(0)
    n, c, b, k, s, tb = 2500, 7, 12, 16, 3, 6
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node_b = jnp.asarray(rng.integers(-1, k, (tb, n)), jnp.int32)
    stats_b = jnp.asarray(rng.normal(size=(tb, n, s)), jnp.float32)
    out = np.asarray(build_histograms_batch(bins, node_b, stats_b, k, b))
    for t in range(tb):
        ref = np.asarray(build_histograms(bins, node_b[t], stats_b[t],
                                          k, b))
        np.testing.assert_array_equal(out[t], ref)


def test_batched_sharded_kernel_matches_scatter():
    """Mesh lowering of the batched kernel (shard_map + psum) == the
    scatter path, per tree."""
    from shifu_tpu.ops.hist_pallas import build_histograms_batch_sharded
    from shifu_tpu.parallel.mesh import device_mesh

    rng = np.random.default_rng(7)
    n, c, b, k, tb = 1024, 6, 16, 8, 3
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node_b = jnp.asarray(rng.integers(-1, k, (tb, n)), jnp.int32)
    stats_b = jnp.asarray(rng.normal(size=(tb, n, 2)), jnp.float32)
    mesh = device_mesh(2, devices=jax.devices("cpu")[:8])
    out = np.asarray(build_histograms_batch_sharded(
        bins, node_b, stats_b, k, b, mesh, interpret=True))
    for t in range(tb):
        ref = np.asarray(build_histograms(bins, node_b[t], stats_b[t],
                                          k, b))
        np.testing.assert_allclose(out[t], ref, atol=2e-4, rtol=2e-5)


@pytest.mark.parametrize("impurity,n_classes,max_leaves",
                         [("variance", 0, 0), ("entropy", 0, 0),
                          ("gini", 3, 0), ("variance", 0, 9)])
def test_grow_forest_bit_matches_sequential(impurity, n_classes,
                                            max_leaves):
    """grow_forest_jit (TB trees per program) == TB sequential
    grow_tree_jit calls — split features, masks, leaf values, FI and
    terminal rows all bit-identical."""
    rng = np.random.default_rng(5)
    n, c, n_bins, tb, depth = 1500, 6, 8, 4, 3
    bins = jnp.asarray(rng.integers(0, n_bins, (n, c)), jnp.int32)
    if n_classes > 2:
        y = rng.integers(0, n_classes, n).astype(np.float32)
        stats_b = np.stack([
            rng.poisson(1.0, n).astype(np.float32)[:, None]
            * np.eye(n_classes, dtype=np.float32)[y.astype(int)]
            for _ in range(tb)])
    else:
        y = (rng.random(n) < 0.35).astype(np.float32)
        stats_b = np.stack([
            np.stack([bag, bag * y], axis=1)
            for bag in rng.poisson(1.0, (tb, n)).astype(np.float32)])
    stats_b = jnp.asarray(stats_b)
    cat = jnp.zeros(c, bool).at[1].set(True)
    fa_b = jnp.asarray(rng.random((tb, c)) < 0.8).at[:, 0].set(True)
    args = (n_bins, depth, impurity, 1.0, 0.0, n_classes, False,
            max_leaves, True, None, False)
    outs_b = grow_forest_jit(bins, stats_b, cat, fa_b, *args)
    for t in range(tb):
        outs_1 = grow_tree_jit(bins, stats_b[t], cat, fa_b[t], *args)
        for a, b_ in zip((o[t] for o in outs_b), outs_1):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))


def test_train_rf_tree_batch_bit_identical(monkeypatch):
    """The resident RF trainer with the tree-batched scan builds the SAME
    forest (trees, errors, FI) as the per-tree scan — bags, keys and oob
    vote order replay exactly; a non-multiple chunk exercises the
    remainder path."""
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf

    rng = np.random.default_rng(2)
    n, c, n_bins = 900, 6, 8
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    logit = (bins[:, 0] - 3) * 0.7 + (bins[:, 1] == 2) * 1.4 - 0.4
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    w = np.ones(n, np.float32)
    settings = DTSettings(n_trees=7, depth=3, impurity="entropy",
                          loss="log", feature_subset="SQRT", seed=1)
    monkeypatch.setenv("SHIFU_TREE_BATCH", "1")
    r1 = train_rf(bins, y, w, n_bins, None, settings)
    monkeypatch.setenv("SHIFU_TREE_BATCH", "3")
    rb = train_rf(bins, y, w, n_bins, None, settings)
    assert len(r1.trees) == len(rb.trees) == 7
    for t1, t2 in zip(r1.trees, rb.trees):
        np.testing.assert_array_equal(t1.split_feat, t2.split_feat)
        np.testing.assert_array_equal(t1.left_mask, t2.left_mask)
        np.testing.assert_array_equal(t1.leaf_value, t2.leaf_value)
    np.testing.assert_array_equal(np.asarray(r1.history),
                                  np.asarray(rb.history))
    np.testing.assert_allclose(r1.feature_importance,
                               rb.feature_importance, rtol=1e-6)


def test_train_rf_tree_batch_forced_kernel(monkeypatch):
    """tree_batch > 1 with the FORCED (interpret) kernel on the 8-device
    mesh == the scatter per-tree path — the north-star RF configuration
    keeps the batched MXU grid."""
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf

    rng = np.random.default_rng(4)
    n, c, n_bins = 640, 6, 8
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    y = (rng.random(n) < 0.4).astype(np.float32)
    w = np.ones(n, np.float32)
    settings = DTSettings(n_trees=4, depth=3, impurity="entropy",
                          loss="log", seed=0)
    mesh8 = device_mesh(1, devices=jax.devices("cpu")[:8])
    monkeypatch.setenv("SHIFU_TREE_BATCH", "1")
    r_scatter = train_rf(bins, y, w, n_bins, None, settings, mesh=mesh8)
    monkeypatch.setenv("SHIFU_TREE_BATCH", "4")
    monkeypatch.setenv("SHIFU_HIST_PALLAS", "force")
    r_kernel = train_rf(bins, y, w, n_bins, None, settings, mesh=mesh8)
    for t1, t2 in zip(r_scatter.trees, r_kernel.trees):
        np.testing.assert_array_equal(t1.split_feat, t2.split_feat)
        np.testing.assert_array_equal(t1.left_mask, t2.left_mask)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-4, atol=1e-5)


def test_gbt_early_stop_chunked_matches_per_tree_semantics():
    """Early stop through the chunked device scan stops at the SAME tree
    the old per-tree decision loop would and builds identical trees (the
    chunk tail past the trigger is discarded)."""
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt
    from shifu_tpu.train.early_stop import GBTEarlyStopDecider

    rng = np.random.default_rng(0)
    n, c, n_bins = 800, 5, 8
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    y = (rng.random(n) < 0.5).astype(np.float32)   # pure noise: stops fast
    w = np.ones(n, np.float32)
    base = DTSettings(n_trees=24, depth=2, loss="log", learning_rate=0.5,
                      seed=3)
    import dataclasses
    full = train_gbt(bins, y, w, n_bins, None,
                     dataclasses.replace(base, early_stop=False))
    # replay the reference decision on the full error stream
    stopper = GBTEarlyStopDecider()
    expect = len(full.history)
    for i, (_, va) in enumerate(full.history):
        if stopper.add(va):
            expect = i + 1
            break
    es = train_gbt(bins, y, w, n_bins, None,
                   dataclasses.replace(base, early_stop=True,
                                       early_stop_check=8))
    assert len(es.trees) == expect
    for t1, t2 in zip(full.trees[:expect], es.trees):
        np.testing.assert_array_equal(t1.split_feat, t2.split_feat)
        np.testing.assert_array_equal(t1.left_mask, t2.left_mask)
        np.testing.assert_array_equal(t1.leaf_value, t2.leaf_value)
    np.testing.assert_array_equal(np.asarray(full.history[:expect]),
                                  np.asarray(es.history))


def test_host_syncs_scale_with_chunks_not_trees():
    """Telemetry guard for sync-free growth: the resident trainers'
    device→host fetch count tracks checkpoint/progress chunks (and the
    early-stop check interval), NOT the tree count."""
    from shifu_tpu import obs
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt, train_rf

    rng = np.random.default_rng(1)
    n, c, n_bins = 600, 5, 8
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    y = (rng.random(n) < 0.4).astype(np.float32)
    w = np.ones(n, np.float32)

    def syncs(fn, settings):
        obs.reset_for_tests()
        obs.set_enabled(True)
        try:
            fn(bins, y, w, n_bins, None, settings)
            return obs.get_registry().counter("train.host_syncs").value
        finally:
            obs.reset_for_tests()

    n_trees = 24
    # no progress/checkpoint/early-stop consumer: the whole forest is ONE
    # scan + ONE fetch
    assert syncs(train_gbt, DTSettings(n_trees=n_trees, depth=2,
                                       loss="log")) == 1
    assert syncs(train_rf, DTSettings(n_trees=n_trees, depth=2,
                                      impurity="entropy", loss="log")) == 1
    # early stop (never triggering here: separable data would not — use
    # check interval 8): fetches every 8 trees, not every tree
    s = syncs(train_gbt, DTSettings(n_trees=n_trees, depth=2, loss="log",
                                    learning_rate=0.01, early_stop=True,
                                    early_stop_check=8))
    assert s <= -(-n_trees // 8) + 1, s
