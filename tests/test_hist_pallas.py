"""MXU histogram kernel (ops/hist_pallas.py) vs the scatter-add reference.

The kernel runs in interpret mode here (tests are CPU); on a TPU backend
the same program lowers through Mosaic.  Matching the segment_sum path at
f32 tolerance is the contract that lets the trainers dispatch freely
(reference hot loop: ``DTWorker.java:844-854``).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from shifu_tpu.ops.hist_pallas import build_histograms_pallas
from shifu_tpu.ops.tree import build_histograms


@pytest.mark.parametrize(
    "n,c,b,k,s",
    [
        (1000, 7, 10, 4, 3),      # typical stats shapes, K under one level
        (4096, 16, 64, 1, 3),     # root level
        (5000, 3, 130, 8, 5),     # bins past one lane tile; 5 stat channels
        (2048, 4, 64, 128, 3),    # deep level: K_MAX partitioning path
        (333, 9, 7, 2, 4),        # ragged everything (padding paths)
    ],
)
def test_pallas_matches_segment_sum(n, c, b, k, s):
    rng = np.random.default_rng(42)
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node = jnp.asarray(rng.integers(-1, k, n), jnp.int32)  # -1 = inactive
    stats = jnp.asarray(rng.normal(size=(n, s)), jnp.float32)
    ref = np.asarray(build_histograms(bins, node, stats, k, b))
    out = np.asarray(build_histograms_pallas(bins, node, stats, k, b,
                                             interpret=True))
    assert out.shape == (k, c, b, s)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-5)


def test_pallas_weighted_counts_exact():
    """Integer weights accumulate exactly (counting semantics)."""
    rng = np.random.default_rng(0)
    n, c, b, k = 2500, 5, 16, 8
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node = jnp.asarray(rng.integers(0, k, n), jnp.int32)
    stats = jnp.asarray(rng.integers(0, 5, (n, 2)), jnp.float32)
    out = np.asarray(build_histograms_pallas(bins, node, stats, k, b,
                                             interpret=True))
    gt = np.zeros((k, c, b, 2))
    bins_h, node_h, stats_h = map(np.asarray, (bins, node, stats))
    for i in range(n):
        for j in range(c):
            gt[node_h[i], j, bins_h[i, j]] += stats_h[i]
    np.testing.assert_array_equal(out, gt)
