"""MXU histogram kernel (ops/hist_pallas.py) vs the scatter-add reference.

The kernel runs in interpret mode here (tests are CPU); on a TPU backend
the same program lowers through Mosaic.  Matching the segment_sum path at
f32 tolerance is the contract that lets the trainers dispatch freely
(reference hot loop: ``DTWorker.java:844-854``).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from shifu_tpu.ops.hist_pallas import build_histograms_pallas
from shifu_tpu.ops.tree import build_histograms


@pytest.mark.parametrize(
    "n,c,b,k,s",
    [
        (1000, 7, 10, 4, 3),      # typical stats shapes, K under one level
        (4096, 16, 64, 1, 3),     # root level
        (5000, 3, 130, 8, 5),     # bins past one lane tile; 5 stat channels
        (2048, 4, 64, 128, 3),    # deep level: K_MAX partitioning path
        (333, 9, 7, 2, 4),        # ragged everything (padding paths)
    ],
)
def test_pallas_matches_segment_sum(n, c, b, k, s):
    rng = np.random.default_rng(42)
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node = jnp.asarray(rng.integers(-1, k, n), jnp.int32)  # -1 = inactive
    stats = jnp.asarray(rng.normal(size=(n, s)), jnp.float32)
    ref = np.asarray(build_histograms(bins, node, stats, k, b))
    out = np.asarray(build_histograms_pallas(bins, node, stats, k, b,
                                             interpret=True))
    assert out.shape == (k, c, b, s)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-5)


@pytest.mark.parametrize("n,c,b,k,s", [(2000, 6, 32, 8, 2),
                                       (1500, 5, 64, 64, 3)])
def test_pallas_exact_channels_bit_match(n, c, b, k, s):
    """``exact=True`` (small-integer stats — RF bag counts x 0/1 targets)
    must BIT-match the split path: skipping the f32-recovery dot is only
    legal because the products are exactly representable in bf16."""
    rng = np.random.default_rng(3)
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node = jnp.asarray(rng.integers(-1, k, n), jnp.int32)
    bag = rng.poisson(1.0, n).astype(np.float32)          # integer counts
    y = (rng.random(n) < 0.4).astype(np.float32)
    cols = [bag, bag * y, (bag > 0).astype(np.float32)]
    stats = jnp.asarray(np.stack(cols[:s], axis=1))
    a = np.asarray(build_histograms_pallas(bins, node, stats, k, b,
                                           interpret=True))
    e = np.asarray(build_histograms_pallas(bins, node, stats, k, b,
                                           interpret=True, exact=True))
    np.testing.assert_array_equal(a, e)
    ref = np.asarray(build_histograms(bins, node, stats, k, b))
    np.testing.assert_allclose(e, ref, atol=2e-4, rtol=2e-5)


def test_sharded_kernel_matches_segment_sum():
    """shard_map'd kernel over the mesh data axis + psum == scatter path
    (the DTWorker→DTMaster merge on ICI, VERDICT r3 item 1)."""
    import jax
    from shifu_tpu.ops.hist_pallas import build_histograms_sharded
    from shifu_tpu.parallel.mesh import device_mesh

    n, c, b, k = 1024, 6, 16, 8
    rng = np.random.default_rng(7)
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node = jnp.asarray(rng.integers(-1, k, n), jnp.int32)
    stats = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
    mesh = device_mesh(2, devices=jax.devices("cpu")[:8])  # ensemble axis too
    ref = np.asarray(build_histograms(bins, node, stats, k, b))
    out = np.asarray(build_histograms_sharded(bins, node, stats, k, b,
                                              mesh, interpret=True))
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-5)


def test_gbt_mesh_equivalence_with_kernel(monkeypatch):
    """Forced kernel (interpret on CPU): an 8-device mesh GBT with the
    shard_map'd kernel builds the same trees as the scatter path — the
    north-star config (GBT on a multi-chip mesh) keeps the MXU path."""
    import jax
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt

    rng = np.random.default_rng(3)
    n, c, n_bins = 640, 6, 8
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    logit = (bins[:, 0] - 3) * 0.8 + (bins[:, 1] == 2) * 1.5 - 0.5
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    w = np.ones(n, np.float32)
    settings = DTSettings(n_trees=3, depth=3, loss="log", seed=0)
    mesh8 = device_mesh(1, devices=jax.devices("cpu")[:8])
    r_scatter = train_gbt(bins, y, w, n_bins, None, settings, mesh=mesh8)
    monkeypatch.setenv("SHIFU_HIST_PALLAS", "force")
    r_kernel = train_gbt(bins, y, w, n_bins, None, settings, mesh=mesh8)
    for t1, t8 in zip(r_scatter.trees, r_kernel.trees):
        np.testing.assert_array_equal(t1.split_feat, t8.split_feat)
        np.testing.assert_array_equal(t1.left_mask, t8.left_mask)
        np.testing.assert_allclose(t1.leaf_value, t8.leaf_value,
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(r_scatter.valid_error, r_kernel.valid_error,
                               rtol=1e-4)


def test_pallas_weighted_counts_exact():
    """Integer weights accumulate exactly (counting semantics)."""
    rng = np.random.default_rng(0)
    n, c, b, k = 2500, 5, 16, 8
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node = jnp.asarray(rng.integers(0, k, n), jnp.int32)
    stats = jnp.asarray(rng.integers(0, 5, (n, 2)), jnp.float32)
    out = np.asarray(build_histograms_pallas(bins, node, stats, k, b,
                                             interpret=True))
    gt = np.zeros((k, c, b, 2))
    bins_h, node_h, stats_h = map(np.asarray, (bins, node, stats))
    for i in range(n):
        for j in range(c):
            gt[node_h[i], j, bins_h[i, j]] += stats_h[i]
    np.testing.assert_array_equal(out, gt)


def test_stats_histogram_kernel_matches_scatter():
    """The two-level (hi*64+lo) one-hot MXU stats histogram must agree
    with the scatter lowering: counts exactly, weighted channels within
    the bf16 hi/lo-split residual (~eps_bf16^2 per product)."""
    import jax.numpy as jnp

    from shifu_tpu.ops.binning import _histogram_kernel

    rng = np.random.default_rng(0)
    R, C, B = 3000, 10, 256
    x = (rng.normal(size=(R, C)) * 10).astype(np.float32)
    valid = rng.random((R, C)) > 0.07          # per-CELL missing values
    t = (rng.random(R) < 0.3).astype(np.float32)
    w = rng.uniform(0.5, 2.0, R).astype(np.float32)
    lo = x.min(0) - 1e-3
    hi = x.max(0) + 1e-3
    args = (jnp.asarray(x), jnp.asarray(valid), jnp.asarray(t),
            jnp.asarray(w), jnp.asarray(lo), jnp.asarray(hi), B)
    a = np.asarray(_histogram_kernel(*args, use_pallas=False))
    b = np.asarray(_histogram_kernel(*args, use_pallas=True))
    np.testing.assert_array_equal(a[..., :2], b[..., :2])   # counts exact
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)
    # totals: every valid cell lands in exactly one bucket
    np.testing.assert_allclose(b[..., 0].sum(1) + b[..., 1].sum(1),
                               valid.sum(0), rtol=0, atol=0)


def test_gbt_mesh_equivalence_with_onehot_traversal(monkeypatch):
    """The one-hot traversal lowering under the GSPMD-partitioned mesh
    (the real multi-chip configuration pairs it with the shard_map'd
    kernel) builds the same trees as the gather lowering."""
    import jax

    from shifu_tpu.ops import tree as ot
    from shifu_tpu.parallel.mesh import device_mesh
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt

    rng = np.random.default_rng(4)
    n, c, n_bins = 640, 6, 8
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    logit = (bins[:, 0] - 3) * 0.8 + (bins[:, 1] == 2) * 1.5 - 0.5
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    w = np.ones(n, np.float32)
    settings = DTSettings(n_trees=3, depth=3, loss="log", seed=0)
    mesh8 = device_mesh(1, devices=jax.devices("cpu")[:8])
    r_gather = train_gbt(bins, y, w, n_bins, None, settings, mesh=mesh8)
    monkeypatch.setenv("SHIFU_TREE_ONEHOT", "1")
    ot._onehot_traversal.cache_clear()
    # the lowering choice is resolved at TRACE time and the env var is
    # not in the jit cache key — without clearing the trace caches the
    # second run would reuse the gather executable (vacuous test)
    jax.clear_caches()
    assert ot._use_onehot(8)
    try:
        r_onehot = train_gbt(bins, y, w, n_bins, None, settings,
                             mesh=mesh8)
    finally:
        monkeypatch.setenv("SHIFU_TREE_ONEHOT", "auto")
        ot._onehot_traversal.cache_clear()
        jax.clear_caches()
    for t1, t8 in zip(r_gather.trees, r_onehot.trees):
        np.testing.assert_array_equal(t1.split_feat, t8.split_feat)
        np.testing.assert_array_equal(t1.left_mask, t8.left_mask)
        np.testing.assert_allclose(t1.leaf_value, t8.leaf_value,
                                   rtol=1e-6, atol=1e-7)
