"""Mixed-precision training ladder (``shifu.train.precision``) — the
bounded-AUC and checkpoint contracts of the round-12 speed round.

- ``mixed`` (bf16 forward/backward, f32 master in the optimizer state)
  must train NN and WDL to within a PINNED |dAUC| of the f32 run on the
  shared prepared_set fixture — the acceptance bound for every
  precision change;
- a ``mixed`` checkpoint resumes BIT-exact (bf16 params + f32 master +
  optimizer state dtypes all preserved through the uint16-view npz
  round trip);
- an f32 checkpoint loaded under ``mixed`` fails with the coded
  ``ERROR_CHECKPOINT_PRECISION_MISMATCH`` — never a silent cast.
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from shifu_tpu.config.errors import ErrorCode, ShifuError
from shifu_tpu.models.nn import NNModelSpec
from shifu_tpu.train import checkpoint as ckpt
from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble
from shifu_tpu.train.optimizers import resolve_precision
from shifu_tpu.train.sampling import member_masks

pytestmark = pytest.mark.perf

# the pinned bounded-AUC epsilon: a mixed run may differ from f32 by
# bf16 rounding noise, never by model quality
EPS_AUC = 0.01


def _pipeline_auc(model_set: str, alg, params: dict, epochs: int = 8):
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = alg
    mc.train.numTrainEpochs = epochs
    mc.train.params = params
    mc.save(mc_path)
    assert TrainProcessor(model_set, params={}).run() == 0
    assert EvalProcessor(model_set, params={"run_eval": ""}).run() == 0
    perf = json.load(open(os.path.join(model_set, "evals", "Eval1",
                                       "EvalPerformance.json")))
    return float(perf["areaUnderRoc"])


def test_nn_mixed_bounded_auc(prepared_set):
    from shifu_tpu.config.model_config import Algorithm
    base = {"NumHiddenNodes": [16], "ActivationFunc": ["relu"],
            "LearningRate": 0.01, "Propagation": "ADAM",
            "MiniBatchs": 512}
    auc_f32 = _pipeline_auc(prepared_set, Algorithm.NN, dict(base))
    auc_mixed = _pipeline_auc(prepared_set, Algorithm.NN,
                              dict(base, TrainPrecision="mixed"))
    assert auc_f32 > 0.7                     # the run actually learned
    assert abs(auc_f32 - auc_mixed) <= EPS_AUC


def test_wdl_mixed_bounded_auc(prepared_set):
    from shifu_tpu.config.model_config import Algorithm
    base = {"NumHiddenNodes": [16], "ActivationFunc": ["relu"],
            "EmbedDim": 4, "LearningRate": 0.01, "MiniBatchs": 512}
    auc_f32 = _pipeline_auc(prepared_set, Algorithm.WDL, dict(base))
    auc_mixed = _pipeline_auc(prepared_set, Algorithm.WDL,
                              dict(base, TrainPrecision="mixed"))
    assert auc_f32 > 0.7
    assert abs(auc_f32 - auc_mixed) <= EPS_AUC


def _toy(n=1000, d=8, bags=2, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d) / np.sqrt(d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x @ w)))).astype(np.float32)
    tw, vw = member_masks(n, bags, valid_rate=0.2, seed=seed)
    spec = NNModelSpec(input_dim=d, hidden_nodes=[6],
                       activations=["tanh"])
    return x, y, tw, vw, spec


def _settings(td, name, **kw):
    return TrainSettings(optimizer="ADAM", learning_rate=0.01,
                         checkpoint_dir=os.path.join(td, name),
                         checkpoint_every=4, **kw)


def test_mixed_checkpoint_resume_bit_exact(tmp_path):
    """Crash at epoch 4, resume to 8 — every bf16 param of every member
    must equal the uninterrupted run's BIT for BIT (master copy + opt
    state ride the checkpoint, so the resumed trajectory is exact)."""
    td = str(tmp_path)
    x, y, tw, vw, spec = _toy()
    full = train_ensemble(x, y, tw, vw, spec,
                          _settings(td, "a", epochs=8, precision="mixed"))
    train_ensemble(x, y, tw, vw, spec,
                   _settings(td, "b", epochs=4, precision="mixed"))
    res = train_ensemble(x, y, tw, vw, spec,
                         _settings(td, "b", epochs=8, precision="mixed",
                                   resume=True))
    for pf, pr in zip(full.params, res.params):
        for lf, lr in zip(pf, pr):
            assert lf["w"].dtype == np.dtype("bfloat16")
            assert np.array_equal(np.asarray(lf["w"]), np.asarray(lr["w"]))
            assert np.array_equal(np.asarray(lf["b"]), np.asarray(lr["b"]))
    assert np.array_equal(full.valid_errors, res.valid_errors)


def test_f32_checkpoint_under_mixed_is_coded_error(tmp_path):
    td = str(tmp_path)
    x, y, tw, vw, spec = _toy()
    train_ensemble(x, y, tw, vw, spec,
                   _settings(td, "c", epochs=4, precision="f32"))
    with pytest.raises(ShifuError) as ei:
        train_ensemble(x, y, tw, vw, spec,
                       _settings(td, "c", epochs=8, precision="mixed",
                                 resume=True))
    assert ei.value.error_code is ErrorCode.ERROR_CHECKPOINT_PRECISION_MISMATCH


def test_mixed_checkpoint_under_f32_is_coded_error(tmp_path):
    """The guard is symmetric: a mixed checkpoint must not silently cast
    down onto an f32 run either."""
    td = str(tmp_path)
    x, y, tw, vw, spec = _toy()
    train_ensemble(x, y, tw, vw, spec,
                   _settings(td, "d", epochs=4, precision="mixed"))
    with pytest.raises(ShifuError):
        train_ensemble(x, y, tw, vw, spec,
                       _settings(td, "d", epochs=8, resume=True))


def test_bf16_leaves_roundtrip_npz(tmp_path):
    """The checkpoint layer itself: bfloat16 leaves store as their
    uint16 bit pattern (numpy reloads the raw ml_dtypes descriptor as a
    useless V2 void) and restore onto a bf16 template with dtype AND
    bits preserved."""
    td = str(tmp_path / "ck")
    rng = np.random.default_rng(0)
    state = {"p": jnp.asarray(rng.normal(size=(5, 3)), jnp.bfloat16),
             "master": jnp.asarray(rng.normal(size=(5, 3)), jnp.float32),
             "t": jnp.zeros((), jnp.float32)}
    ckpt.save_state(td, 3, state, precision="mixed")
    got = ckpt.restore_state(td, state, expect_precision="mixed")
    assert got is not None and got[0] == 3
    for k in state:
        a, b = np.asarray(state[k]), np.asarray(got[1][k])
        assert a.dtype == b.dtype and np.array_equal(a, b)
    # precision tag enforced at this layer too
    with pytest.raises(ShifuError):
        ckpt.restore_state(td, state, expect_precision="f32")
    # untagged expectation (legacy callers) still restores
    assert ckpt.restore_state(td, state) is not None


def test_resolve_precision_knob():
    from shifu_tpu.config import environment
    assert resolve_precision("") == "f32"
    assert resolve_precision("MIXED") == "mixed"
    with pytest.raises(ValueError):
        resolve_precision("fp8")
    environment.set_property("shifu.train.precision", "bf16")
    try:
        assert resolve_precision("") == "bf16"
        assert resolve_precision("f32") == "f32"   # explicit wins
    finally:
        environment.set_property("shifu.train.precision", "")


def test_streamed_mixed_close_to_f32(tmp_path):
    """The streamed (full-batch, f32 gradient accumulation) mixed path
    lands within noise of streamed f32 on the same stream."""
    import tempfile

    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream, mask_fn_from_settings
    from shifu_tpu.train.nn_trainer import train_ensemble_streamed

    rng = np.random.default_rng(0)
    n, d = 1500, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d) / np.sqrt(d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x @ w)))).astype(np.float32)
    wc = np.ones(n, np.float32)
    td = str(tmp_path / "shards")
    os.makedirs(td)
    k = 0
    for s in range(0, n, 600):
        e = min(s + 600, n)
        np.savez(os.path.join(td, f"part-{k:05d}.npz"),
                 x=x[s:e], y=y[s:e], w=wc[s:e])
        k += 1
    json.dump({"columnNums": list(range(d)), "numShards": k,
               "numRows": n},
              open(os.path.join(td, "schema.json"), "w"))
    spec = NNModelSpec(input_dim=d, hidden_nodes=[6],
                       activations=["tanh"])
    mask_fn = mask_fn_from_settings(2, valid_rate=0.2, sample_rate=1.0,
                                    replacement=False,
                                    up_sample_weight=1.0, seed=0)
    errs = {}
    for prec in ("f32", "mixed"):
        stream = ShardStream(Shards.open(td), ("x", "y", "w"), 512,
                             spill=False, remainder_multiple=1)
        s = TrainSettings(optimizer="ADAM", learning_rate=0.01,
                          epochs=4, precision=prec)
        errs[prec] = train_ensemble_streamed(stream, spec, s, 2,
                                             mask_fn).valid_errors
    assert np.all(np.abs(errs["f32"] - errs["mixed"]) < 0.02)
