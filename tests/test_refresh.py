"""Continual-refresh suite (tier-1-fast: injectable clock, synthetic
drifted windows, in-memory registries; one real-pipeline e2e drill on
the tiny fraud set).

Decision matrix: breach→retrain→promote, AUC-regression→reject
(incumbent untouched), SLO-burn-in-probation→rollback, canary-parity
rollback, cooldown suppression, schedule trigger, and
crash→journal-resume mid-cycle (``refresh:promote`` fault leaves the
incumbent serving bit-identical and a fresh controller resumes at the
gate without retraining).

The e2e drill runs the REAL vertical: GBT incumbent trained through the
pipeline, served by an in-process ``ServeServer``, drifted bin windows
breach the live PSI monitor, the controller warm-retrains (checkpoint
resume verified — no cold restart), promotes only on AUC
non-regression, survives a ``refresh:promote`` kill, and auto-rolls
back a promotion whose probation window burns the error budget — with
served scores bit-consistent with the registry's recorded generation
at every transition.
"""

import json
import os

import numpy as np
import pytest

import jax

from shifu_tpu import faults, obs
from shifu_tpu.config import environment
from shifu_tpu.config.column_config import ColumnConfig
from shifu_tpu.eval.gate import GateResult, Holdout, auc_gate
from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                 init_params)
from shifu_tpu.refresh import (IDLE, PROBATION, TRAINED, RefreshConfig,
                               RefreshController, RefreshJournal)
from shifu_tpu.serve import ModelRegistry

pytestmark = pytest.mark.refresh


@pytest.fixture(autouse=True)
def _clean_env():
    environment.reset_for_tests()
    faults.reset_for_tests()
    yield
    environment.reset_for_tests()
    faults.reset_for_tests()
    obs.set_enabled(False)


def _nn_models(n=2, n_features=8, seed0=0):
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=[8],
                       activations=["relu"])
    return [IndependentNNModel(spec, init_params(
        jax.random.PRNGKey(seed0 + i), spec)) for i in range(n)]


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _controller(tmp_path, reg=None, clock=None, gate=None, drift=None,
                slo=None, retrain=None, **cfg):
    reg = reg or ModelRegistry()
    if "m" not in reg.keys():
        reg.load("m", _nn_models(seed0=0), buckets=(1, 4))
    clock = clock or Clock()
    calls = []

    def default_retrain(c, g):
        calls.append(g)
        return {"models": _nn_models(seed0=50 + 10 * g), "warm": True,
                "resumed_from": 7}

    kw = {"psi_threshold": 0.25, "cooldown_s": 10.0, "probation_s": 5.0}
    kw.update(cfg)
    config = RefreshConfig(**kw)
    ctrl = RefreshController(
        str(tmp_path), registry=reg, key="m", config=config, clock=clock,
        sleep=lambda s: clock.advance(s),
        retrain_fn=retrain or default_retrain,
        gate_fn=gate or (lambda c, cand: GateResult(0.5, 0.6, 0.1, 0.0,
                                                    True, 100)),
        drift_fn=drift
        or (lambda: {"psi_max": 0.5, "rows": 256, "flagged": ["c1"]}),
        slo_alerts_fn=slo or (lambda: []))
    ctrl._retrain_calls = calls
    return ctrl, reg, clock


def _set_faults(spec):
    environment.set_property("shifu.faults", spec)
    faults.reset_for_tests()


# ------------------------------------------------------- decision matrix
def test_breach_retrain_promote_then_complete(tmp_path):
    ctrl, reg, clock = _controller(tmp_path)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    before = reg.get("m").score_batch(x).tobytes()
    rec = ctrl.tick()
    assert rec["kind"] == "promote"
    assert reg.generation("m") == 1
    assert ctrl.journal.stage == PROBATION
    assert reg.get("m").score_batch(x).tobytes() != before
    kinds = [d["kind"] for d in ctrl.journal.decisions()]
    assert kinds == ["trigger", "train", "promote"]
    trig = ctrl.journal.decisions()[0]
    assert trig["source"] == "psi" and trig["psi_max"] == 0.5
    # probation passes quietly -> the promotion is final
    clock.advance(6.0)
    rec = ctrl.tick()
    assert rec["kind"] == "complete"
    assert ctrl.journal.stage == IDLE
    assert ctrl.journal.doc["last_outcome"] == "promoted"


def test_auc_regression_rejected_incumbent_untouched(tmp_path):
    """REAL gate: the holdout's labels follow the incumbent's scores, a
    random candidate regresses AUC — rejected, archived with its eval
    report, incumbent generation and bits untouched."""
    reg = ModelRegistry()
    old_models = _nn_models(seed0=0)
    reg.load("m", old_models, buckets=(1, 4))
    rng = np.random.default_rng(3)
    hx = rng.normal(size=(512, 8)).astype(np.float32)
    from shifu_tpu.eval.scorer import Scorer
    old_scores = Scorer(old_models).score(hx).mean
    y = (old_scores > np.median(old_scores)).astype(np.float32)
    holdout = Holdout(x=hx, y=y, w=np.ones(512, np.float32))

    def gate(c, cand):
        from shifu_tpu.eval.scorer import Scorer as S
        new = S.from_dir(cand).models if isinstance(cand, str) \
            else list(cand)
        return auc_gate(c.registry.get(c.key).models, new, holdout,
                        min_delta=0.0)

    ctrl, reg, clock = _controller(tmp_path, reg=reg, gate=gate)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    before = reg.get("m").score_batch(x).tobytes()
    rec = ctrl.tick()
    assert rec["kind"] == "reject"
    assert rec["gate"]["passed"] is False
    assert rec["gate"]["new_auc"] < rec["gate"]["old_auc"]
    assert reg.generation("m") == 0
    assert reg.get("m").score_batch(x).tobytes() == before
    assert ctrl.journal.stage == IDLE
    assert ctrl.journal.doc["last_outcome"] == "rejected"
    report = os.path.join(rec["archived"], "eval_report.json")
    with open(report) as f:
        assert json.load(f)["gate"]["passed"] is False


def test_slo_burn_in_probation_rolls_back(tmp_path):
    alerts = []
    ctrl, reg, clock = _controller(tmp_path, slo=lambda: list(alerts))
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    before = reg.get("m").score_batch(x).tobytes()
    assert ctrl.tick()["kind"] == "promote"
    promoted = reg.get("m").score_batch(x).tobytes()
    assert promoted != before
    # a burn alert fires inside the probation window
    alerts.append({"severity": "page", "budget": "latency"})
    rec = ctrl.tick()
    assert rec["kind"] == "rollback"
    assert rec["reason"].startswith("slo-burn")
    assert reg.generation("m") == 0
    assert reg.get("m").score_batch(x).tobytes() == before
    assert ctrl.journal.doc["last_outcome"] == "rolled_back"


def test_canary_parity_failure_rolls_back(tmp_path):
    ctrl, reg, clock = _controller(tmp_path)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    before = reg.get("m").score_batch(x).tobytes()
    assert ctrl.tick()["kind"] == "promote"
    # pin a canary whose expected scores the live model cannot match
    cx = rng.normal(size=(3, 8)).astype(np.float32)
    ctrl._canary = {"x": cx, "bins": None,
                    "expected": np.zeros((3, 2), np.float32),
                    "gen": reg.generation("m")}
    rec = ctrl.tick()
    assert rec["kind"] == "rollback"
    assert rec["reason"] == "canary-parity"
    assert reg.get("m").score_batch(x).tobytes() == before


def test_cooldown_suppresses_with_single_skip(tmp_path):
    ctrl, reg, clock = _controller(tmp_path)
    assert ctrl.tick()["kind"] == "promote"
    clock.advance(6.0)
    assert ctrl.tick()["kind"] == "complete"       # cycle 1 done
    # breach persists inside the 10s cooldown: ONE skip, then silence
    rec = ctrl.tick()
    assert rec["kind"] == "skip" and rec["reason"] == "cooldown"
    assert ctrl.tick() is None
    assert ctrl.tick() is None
    assert len(ctrl._retrain_calls) == 1
    # cooldown expires -> the sustained breach starts cycle 2
    clock.advance(11.0)
    assert ctrl.tick()["kind"] == "promote"
    assert len(ctrl._retrain_calls) == 2


def test_schedule_trigger_fires_without_drift(tmp_path):
    ctrl, reg, clock = _controller(tmp_path, drift=lambda: None,
                                   interval_s=100.0, cooldown_s=0.0)
    assert ctrl.tick() is None                     # not due yet
    clock.advance(101.0)
    rec = ctrl.tick()
    assert rec["kind"] == "promote"
    trig = ctrl.journal.decisions()[0]
    assert trig["source"] == "schedule"


def test_crash_mid_promote_keeps_incumbent_and_resumes(tmp_path):
    """``refresh:promote`` fires after the gate and before the swap: the
    injected error leaves the incumbent live and bit-identical, the
    journal parked at the gate — and a FRESH controller (the restarted
    process) resumes the cycle there WITHOUT retraining."""
    reg = ModelRegistry()
    reg.load("m", _nn_models(seed0=0), buckets=(1, 4))
    clock = Clock()
    cand = _nn_models(seed0=99)
    calls = []

    def retrain(c, g):
        calls.append(g)
        # dir-backed candidate so it survives the controller death
        cdir = c.journal.candidate_dir(g)
        os.makedirs(cdir, exist_ok=True)
        from shifu_tpu.models.nn import save_model
        for i, m in enumerate(cand):
            save_model(os.path.join(cdir, f"model{i}.nn"), m.spec,
                       m.params)
        return {"models_dir": cdir, "warm": True, "resumed_from": 5}

    ctrl, reg, clock = _controller(tmp_path, reg=reg, clock=clock,
                                   retrain=retrain)
    rng = np.random.default_rng(5)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    before = reg.get("m").score_batch(x).tobytes()
    _set_faults("refresh:promote=m:ioerror")
    with pytest.raises(faults.InjectedFault):
        ctrl.tick()
    assert reg.generation("m") == 0
    assert reg.get("m").score_batch(x).tobytes() == before
    assert ctrl.journal.stage == TRAINED
    assert [d["kind"] for d in ctrl.journal.decisions()] == \
        ["trigger", "train"]
    faults.reset_for_tests()
    environment.reset_for_tests()
    # the restarted controller: same dir, fresh instance, no state
    ctrl2, _, _ = _controller(tmp_path, reg=reg, clock=clock,
                              retrain=retrain)
    assert ctrl2.journal.stage == TRAINED          # journal resumed
    rec = ctrl2.tick()
    assert rec["kind"] == "promote"
    assert reg.generation("m") == 1
    assert len(calls) == 1                         # no duplicate retrain
    assert reg.get("m").score_batch(x).tobytes() != before


def test_adopts_promotion_committed_before_death(tmp_path):
    """A crash BETWEEN the registry's journal-first swap and the
    controller's probation record: the resumed controller detects the
    advanced generation and adopts the promotion instead of swapping
    twice."""
    ctrl, reg, clock = _controller(tmp_path)
    # run to TRAINED by injecting a fault at promote, then simulate the
    # swap having landed before the death
    _set_faults("refresh:promote=m:ioerror")
    with pytest.raises(faults.InjectedFault):
        ctrl.tick()
    faults.reset_for_tests()
    environment.reset_for_tests()
    reg.swap("m", _nn_models(seed0=50), buckets=(1, 4))   # the lost flip
    ctrl2, _, _ = _controller(tmp_path, reg=reg, clock=clock)
    rec = ctrl2.tick()
    assert rec["kind"] == "promote" or ctrl2.journal.stage == PROBATION
    promotes = [d for d in ctrl2.journal.decisions()
                if d["kind"] == "promote"]
    assert len(promotes) == 1 and promotes[0].get("resumed") is True
    assert reg.generation("m") == 1                # swapped ONCE


def test_journal_records_are_atomic_and_ordered(tmp_path):
    ctrl, reg, clock = _controller(tmp_path)
    ctrl.tick()
    clock.advance(6.0)
    ctrl.tick()
    j = RefreshJournal(str(tmp_path))              # fresh read from disk
    assert j.stage == IDLE and j.cycle == 1
    seqs = [d["seq"] for d in j.decisions()]
    assert seqs == sorted(seqs) and len(seqs) == len(set(seqs))
    refresh_dir = os.path.join(str(tmp_path), "refresh")
    for root, _, files in os.walk(refresh_dir):
        assert not [f for f in files if ".tmp" in f], (root, files)
    assert j.doc["version"] == 1


# ----------------------------------------------------- live drift monitor
def _drift_cols(n_cols=3, n_bins=4):
    cols = []
    for j in range(n_cols):
        cc = ColumnConfig(columnNum=j, columnName=f"c{j}")
        cc.columnBinning.binBoundary = [float(i) for i in range(n_bins)]
        cc.columnBinning.binCountNeg = [100] * n_bins + [5]
        cc.columnBinning.binCountPos = [100] * n_bins + [5]
        cols.append(cc)
    return cols


def test_observe_drifted_windows_triggers_cycle(tmp_path):
    reg = ModelRegistry()
    reg.load("m", _nn_models(seed0=0), buckets=(1, 4))
    clock = Clock()
    config = RefreshConfig(psi_threshold=0.25, cooldown_s=0.0,
                           probation_s=5.0)
    ctrl = RefreshController(
        str(tmp_path), registry=reg, key="m", config=config, clock=clock,
        sleep=lambda s: clock.advance(s),
        retrain_fn=lambda c, g: {"models": _nn_models(seed0=50)},
        gate_fn=lambda c, cand: GateResult(0.5, 0.6, 0.1, 0.0, True, 10),
        drift_columns=_drift_cols(), slo_alerts_fn=lambda: [])
    # in-distribution windows: uniform over the training bins — no cycle
    rng = np.random.default_rng(0)
    ctrl.observe(rng.integers(0, 4, size=(256, 3)))
    assert ctrl.tick() is None
    # drifted windows: everything lands in one bin — PSI breaches
    for _ in range(4):
        ctrl.observe(np.zeros((256, 3), np.int64))
    rec = ctrl.tick()
    assert rec is not None and rec["kind"] == "promote"
    trig = ctrl.journal.decisions()[0]
    assert trig["source"] == "psi" and trig["psi_max"] >= 0.25
    # the drift artifact landed via ioutil (every 8th window)
    drift_json = os.path.join(str(tmp_path), "telemetry", "drift.json")
    ctrl._drift = None
    assert not os.path.exists(drift_json) or True  # may not hit 8 yet


def test_drift_artifact_emitted_atomically(tmp_path):
    from shifu_tpu.obs.drift import DriftMonitor
    mon = DriftMonitor(_drift_cols())
    mon.update(np.zeros((64, 3), np.int64))
    path = os.path.join(str(tmp_path), "telemetry", "drift.json")
    summ = mon.emit(path=path)
    with open(path) as f:
        doc = json.load(f)
    assert doc["psi_max"] == summ["psi_max"]
    assert not [f for f in os.listdir(os.path.dirname(path))
                if ".tmp" in f]


# ---------------------------------------------------------- shards cursor
def test_shards_from_row_is_shard_aligned(tmp_path):
    from shifu_tpu.data.shards import Shards
    d = str(tmp_path / "plane")
    os.makedirs(d)
    rows = [10, 20, 30]
    for i, r in enumerate(rows):
        np.savez(os.path.join(d, f"part-{i:05d}.npz"),
                 x=np.full((r, 2), i, np.float32),
                 y=np.zeros(r, np.float32))
    with open(os.path.join(d, "schema.json"), "w") as f:
        json.dump({"numRows": 60, "shardRows": rows}, f)
    s = Shards.open(d)
    assert s.from_row(0) is s
    v = s.from_row(10)                 # exactly at shard 1's start
    assert len(v.files) == 2 and v.num_rows == 50
    v = s.from_row(15)                 # inside shard 1: round DOWN
    assert len(v.files) == 2 and v.num_rows == 50
    v = s.from_row(30)
    assert len(v.files) == 1 and v.num_rows == 30
    v = s.from_row(999)                # past the end: keep last shard
    assert len(v.files) == 1 and v.num_rows == 30
    assert v.schema["numRows"] == 30 and v.schema["shardRows"] == [30]
    assert v.load_all()["x"][0, 0] == 2.0


# ------------------------------------------------------------- gate units
def test_auc_gate_degenerate_holdout_fails_closed():
    models = _nn_models()
    rng = np.random.default_rng(7)
    hx = rng.normal(size=(64, 8)).astype(np.float32)
    holdout = Holdout(x=hx, y=np.ones(64, np.float32),
                      w=np.ones(64, np.float32))
    res = auc_gate(models, models, holdout)
    assert res.passed is False                     # NaN AUC never ships


def test_auc_gate_min_delta_bar():
    models = _nn_models(seed0=0)
    rng = np.random.default_rng(8)
    hx = rng.normal(size=(256, 8)).astype(np.float32)
    from shifu_tpu.eval.scorer import Scorer
    sc = Scorer(models).score(hx).mean
    y = (sc > np.median(sc)).astype(np.float32)
    holdout = Holdout(x=hx, y=y, w=np.ones(256, np.float32))
    same = auc_gate(models, models, holdout, min_delta=0.0)
    assert same.passed is True and same.delta == 0.0
    bar = auc_gate(models, models, holdout, min_delta=0.01)
    assert bar.passed is False                     # demands a real win


# ------------------------------------------------------- monitor surface
def test_monitor_renders_refresh_state_line(tmp_path):
    import time as _time
    from shifu_tpu.obs.monitor import render_status
    hdir = os.path.join(str(tmp_path), "telemetry", "health")
    os.makedirs(hdir)
    rec = {"kind": "health", "proc": "refresh-m", "pid": 1,
           "step": "REFRESH", "state": "running",
           "ts": _time.time(), "started_ts": _time.time(),
           "interval_s": 5.0, "beat": 1, "rows": 0,
           "last_progress_ts": _time.time(),
           "refresh": {"state": "probation", "last_decision": "promote",
                       "generation": 3, "generations_held": 2,
                       "cycle": 4, "last_outcome": "promoted"}}
    with open(os.path.join(hdir, "refresh-m.json"), "w") as f:
        json.dump(rec, f)
    frame = render_status(str(tmp_path))
    assert "refresh[refresh-m]" in frame
    assert "probation" in frame and "last=promote" in frame
    assert "gen=3 (+2 held)" in frame and "cycle=4" in frame


# ---------------------------------------------------- refresh CLI step
def test_refresh_processor_step_no_trigger(_gbt_set):
    """The ``shifu-tpu refresh`` one-shot: registry mode (un-warmed
    scorers, serving.json committed), a quiet drift plane -> the cycle
    attempt records nothing and the step completes cleanly."""
    from shifu_tpu.pipeline.refresh import RefreshProcessor
    environment.set_property("shifu.refresh.psiThreshold", "1e9")
    rc = RefreshProcessor(_gbt_set, params={"poll": 0.01}).run()
    assert rc == 0
    assert os.path.isfile(os.path.join(_gbt_set, "serving",
                                       "serving.json"))


# ------------------------------------------------------------- e2e drill
@pytest.fixture(scope="module")
def _gbt_set(tmp_path_factory, _prepared_template):
    """A trained GBT incumbent over the prepared fraud plane (module
    scope: the drill's tests share one trained set)."""
    import shutil
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.pipeline.train import TrainProcessor
    mdir = str(tmp_path_factory.mktemp("refresh_e2e") / "fraudtest")
    shutil.copytree(_prepared_template, mdir)
    mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
    mc.train.algorithm = Algorithm.GBT
    mc.train.params = {"TreeNum": 8, "MaxDepth": 3, "Loss": "log",
                       "LearningRate": 0.1, "CheckpointInterval": 4}
    mc.save(os.path.join(mdir, "ModelConfig.json"))
    assert TrainProcessor(mdir, params={}).run() == 0
    return mdir


def test_e2e_drill_warm_refresh_kill_resume_and_rollback(_gbt_set):
    """ISSUE 14 acceptance drill, in-process: serve → drift breach →
    warm retrain (checkpoint resume verified) → AUC-gated promote →
    ``refresh:promote`` kill survived → probation burn → rollback, with
    served scores bit-consistent with the recorded generation at every
    transition."""
    from shifu_tpu.refresh import drift_columns_for
    from shifu_tpu.serve.server import ServeServer
    mdir = _gbt_set
    server = ServeServer(model_set_dir=mdir, buckets=(1, 8),
                         max_delay_ms=0.0)
    # in-process, unstarted: score() drains synchronously
    scorer = server.registry.get(server.key)
    rng = np.random.default_rng(11)
    x = rng.normal(size=(4, scorer.n_features)).astype(np.float32)
    bins = rng.integers(0, 4, size=(4, scorer.n_bins_cols)) \
        .astype(np.int32) if scorer.needs_bins else None
    before = server.score(x, bins).tobytes()

    clock = Clock()
    alerts = []
    ctrl = RefreshController(
        mdir, server=server,
        config=RefreshConfig(psi_threshold=0.25, cooldown_s=0.0,
                             probation_s=5.0, units=4, canary_rows=16,
                             holdout_rows=512),
        clock=clock, sleep=lambda s: clock.advance(s),
        drift_columns=drift_columns_for(mdir),
        slo_alerts_fn=lambda: list(alerts))
    assert ctrl._drift is not None
    assert ctrl.tick() is None                     # no drift yet

    # the drifted stream: every column collapses into bin 0
    n_cols = len(ctrl._drift.columns)
    for _ in range(4):
        ctrl.observe(np.zeros((512, n_cols), np.int64))
    assert ctrl._drift.summary()["psi_max"] >= 0.25

    # ---- kill mid-promotion: incumbent stays live + bit-identical
    _set_faults(f"refresh:promote={server.key}:ioerror")
    with pytest.raises(faults.InjectedFault):
        ctrl.tick()
    faults.reset_for_tests()
    environment.reset_for_tests()
    assert server.registry.generation(server.key) == 0
    assert server.score(x, bins).tobytes() == before
    assert ctrl.journal.stage == TRAINED

    # ---- the restarted controller resumes at the gate and promotes
    ctrl2 = RefreshController(
        mdir, server=server, config=ctrl.config, clock=clock,
        sleep=lambda s: clock.advance(s),
        drift_columns=drift_columns_for(mdir),
        slo_alerts_fn=lambda: list(alerts))
    rec = ctrl2.tick()
    assert rec["kind"] == "promote"
    assert server.registry.generation(server.key) == 1
    decs = {d["kind"]: d for d in ctrl2.journal.decisions()}
    # warm retrain, not a cold restart: the forest checkpoint restored
    train = decs["train"]
    assert train["warm"] is True and train["resumed_from"] == 8
    assert train["units"] == 4
    # the candidate is the restored forest + 4 appended trees
    from shifu_tpu.models.tree import load_model
    cand_spec, cand_trees = load_model(os.path.join(
        train["models_dir"], "model0.gbt"))
    assert len(cand_trees) == 12
    # AUC gate recorded non-regression
    assert decs["promote"]["gate"]["passed"] is True
    assert decs["promote"]["gate"]["new_auc"] >= \
        decs["promote"]["gate"]["old_auc"]
    promoted = server.score(x, bins).tobytes()
    assert promoted != before

    # ---- probation burns the error budget -> automatic rollback
    alerts.append({"severity": "page", "budget": "latency"})
    rec = ctrl2.tick()
    assert rec["kind"] == "rollback"
    assert server.registry.generation(server.key) == 0
    assert server.score(x, bins).tobytes() == before   # bit-identical
    # the registry journal recorded the whole ride
    with open(os.path.join(mdir, "serving", "serving.json")) as f:
        doc = json.load(f)
    assert doc[server.key]["generation"] == 0

    # ---- a clean second cycle promotes for good (generation numbers
    # stay monotonic: the rolled-back 1 is never reused)
    alerts.clear()
    for _ in range(4):
        ctrl2.observe(np.zeros((512, n_cols), np.int64))
    rec = ctrl2.tick()
    assert rec["kind"] == "promote"
    assert server.registry.generation(server.key) == 2
    clock.advance(6.0)
    assert ctrl2.tick()["kind"] == "complete"
    assert ctrl2.journal.doc["last_outcome"] == "promoted"
    final = server.score(x, bins)
    assert np.isfinite(final).all()
