"""Online-serving plane suite (tier-1-fast: in-process batcher drains,
injectable clock, tiny models — zero sleeps, zero ports).

Covers the serve acceptance surface: padded-bucket launches trim to
bit-identical scores across NN / GBT / WDL model groups, a warmed
server performs ZERO recompiles over a randomized request-size sweep,
deadline/full flush semantics, fault sites (a killed in-flight batch
leaves the registry serviceable; a crashed hot-swap leaves the previous
model live and bit-identical), and the stacked-NN-group cache
invalidation regression in ``eval/scorer.py``.
"""

import json
import os

import numpy as np
import pytest

import jax

from shifu_tpu import faults, obs
from shifu_tpu.config import environment
from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                 init_params)
from shifu_tpu.serve import (AOTScorer, MicroBatcher, ModelRegistry,
                             ServeServer, bucket_ladder, covering_bucket,
                             infer_dims, serve_recompile_count)

pytestmark = pytest.mark.serve


@pytest.fixture(autouse=True)
def _clean_env():
    environment.reset_for_tests()
    faults.reset_for_tests()
    yield
    environment.reset_for_tests()
    faults.reset_for_tests()
    obs.set_enabled(False)


def _nn_models(n=3, n_features=8, hidden=(8,), seed0=0):
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=list(hidden),
                       activations=["relu"] * len(hidden))
    return [IndependentNNModel(spec, init_params(
        jax.random.PRNGKey(seed0 + i), spec)) for i in range(n)]


def _gbt_model(n_features=6, n_bins=8, n_trees=4, depth=3, seed=0):
    from shifu_tpu.models.tree import IndependentTreeModel, TreeModelSpec
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, n_bins, size=(512, n_features)).astype(np.int32)
    y = (rng.random(512) < 0.4).astype(np.float32)
    w = np.ones(512, np.float32)
    settings = DTSettings(n_trees=n_trees, depth=depth, loss="log",
                          learning_rate=0.1)
    res = train_gbt(bins, y, w, n_bins, np.zeros(n_features, bool),
                    settings)
    spec = TreeModelSpec(n_trees=len(res.trees), depth=depth,
                         n_bins=n_bins, **res.spec_kwargs)
    return IndependentTreeModel(spec, res.trees)


def _wdl_model(n_features=8, n_bins_cols=6, seed=3):
    from shifu_tpu.models.wdl import IndependentWDLModel, WDLModelSpec
    from shifu_tpu.models.wdl import init_params as wdl_init
    spec = WDLModelSpec(numeric_dim=3, cat_cardinalities=[8, 8],
                        embed_dim=4, hidden_nodes=[8],
                        activations=["relu"],
                        extra={"num_feat_idx": [0, 2, 4],
                               "cat_col_idx": [1, 3]})
    return IndependentWDLModel(spec, wdl_init(jax.random.PRNGKey(seed),
                                              spec))


# ----------------------------------------------------------- bucket math
def test_bucket_ladder_property_and_default():
    assert bucket_ladder() == (1, 8, 64, 512)
    environment.set_property("shifu.serve.buckets", "4,1,32,4")
    assert bucket_ladder() == (1, 4, 32)
    environment.set_property("shifu.serve.buckets", "junk")
    assert bucket_ladder() == (1, 8, 64, 512)     # unparseable -> default


def test_covering_bucket():
    b = (1, 8, 64)
    assert covering_bucket(b, 1) == 1
    assert covering_bucket(b, 2) == 8
    assert covering_bucket(b, 8) == 8
    assert covering_bucket(b, 64) == 64
    assert covering_bucket(b, 1000) == 64         # caller chunks oversize


def test_infer_dims_mixed_ensemble():
    models = _nn_models(n_features=8) + [_gbt_model(n_features=6)] \
        + [_wdl_model()]
    f, c = infer_dims(models)
    assert f == 8
    assert c >= 4            # gbt split features + wdl cat cols


# ---------------------------------------------------- bucket-pad parity
def _rand_xb(rng, n, scorer, n_bins=8):
    x = rng.normal(size=(n, scorer.n_features)).astype(np.float32)
    b = rng.integers(0, n_bins,
                     size=(n, scorer.n_bins_cols)).astype(np.int32)
    return x, (b if scorer.needs_bins else None)


@pytest.mark.parametrize("kind", ["nn", "gbt", "wdl", "mixed"])
def test_padded_bucket_scores_bit_identical(kind):
    """Scores from a padded bucket launch, after trim, are BIT-identical
    to an exact-size launch of the same rows — across NN, GBT and WDL
    model groups (padding must be invisible, not merely close)."""
    if kind == "nn":
        models = _nn_models()
    elif kind == "gbt":
        models = [_gbt_model(seed=i) for i in range(2)]
    elif kind == "wdl":
        models = [_wdl_model()]
    else:
        models = _nn_models(2) + [_gbt_model(), _wdl_model()]
    scorer = AOTScorer(models, buckets=(1, 4, 16))
    scorer.warm(launch=False)
    rng = np.random.default_rng(7)
    x, bins = _rand_xb(rng, 16, scorer)
    # pad 3 rows -> bucket 4 vs the same executable launched exactly full
    # with the same leading rows: trimmed scores must match bitwise
    exact = scorer.score_batch(x[:4], None if bins is None else bins[:4])
    padded = scorer.score_batch(x[:3], None if bins is None else bins[:3])
    assert padded.tobytes() == exact[:3].tobytes()
    # same at the 16 rung: 13 padded vs 16 exact
    exact16 = scorer.score_batch(x, bins)
    pad16 = scorer.score_batch(x[:13],
                               None if bins is None else bins[:13])
    assert pad16.tobytes() == exact16[:13].tobytes()


def test_oversize_batch_chunks_through_top_bucket():
    models = _nn_models()
    scorer = AOTScorer(models, buckets=(1, 4))
    rng = np.random.default_rng(1)
    x, _ = _rand_xb(rng, 11, scorer)
    full = scorer.score_batch(x)
    assert full.shape == (11, len(models))
    parts = np.concatenate([scorer.score_batch(x[:4]),
                            scorer.score_batch(x[4:8]),
                            scorer.score_batch(x[8:])], axis=0)
    assert full.tobytes() == parts.tobytes()


# -------------------------------------------------- recompile sentinel
def test_warmed_server_zero_recompiles_over_random_sizes():
    """A warmed server performs ZERO xla.recompiles over a randomized
    request-size sweep — every request size pads into a pre-compiled
    rung."""
    models = _nn_models(2) + [_gbt_model()]
    scorer = AOTScorer(models, buckets=(1, 4, 16))
    scorer.warm()
    obs.set_enabled(True)
    rng = np.random.default_rng(11)
    before = serve_recompile_count()
    ctr = obs.counter("xla.recompiles")
    xla_before = ctr.value
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    for n in rng.integers(1, 17, size=40):
        x, bins = _rand_xb(rng, int(n), scorer)
        t = b.submit_burst(x, bins)
        b.drain()
        assert t.wait(10.0).shape == (int(n),)
    assert serve_recompile_count() - before == 0
    assert ctr.value - xla_before == 0


# --------------------------------------------------------- micro-batcher
class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def test_batcher_deadline_flush_with_fake_clock():
    """No flush before the oldest request's deadline; flush after —
    driven entirely by an injected clock, no sleeps."""
    models = _nn_models()
    scorer = AOTScorer(models, buckets=(1, 4, 16))
    scorer.warm(launch=False)
    clk = FakeClock()
    b = MicroBatcher(lambda: scorer, max_delay_s=0.002, clock=clk)
    rng = np.random.default_rng(0)
    t1 = b.submit(rng.normal(size=scorer.n_features))
    clk.t += 0.001
    assert b.pump() == 0 and not t1.done()        # deadline not reached
    t2 = b.submit(rng.normal(size=scorer.n_features))
    clk.t += 0.0015                               # oldest is now 2.5ms old
    assert b.pump() == 2                          # deadline flush, both
    assert t1.done() and t2.done()
    assert b.stats["flush_deadline"] == 1 and b.stats["flush_full"] == 0
    # both coalesced into ONE bucket-4 launch, 2 pad rows counted
    assert b.stats["batches"] == 1
    assert b.stats["rows_padded"] == 2
    assert b.bucket_counts == {4: 1}


def test_batcher_full_bucket_flushes_without_deadline():
    models = _nn_models()
    scorer = AOTScorer(models, buckets=(1, 4))
    scorer.warm(launch=False)
    clk = FakeClock()
    b = MicroBatcher(lambda: scorer, max_delay_s=10.0, clock=clk)
    rng = np.random.default_rng(0)
    t = b.submit_burst(rng.normal(size=(4, scorer.n_features))
                       .astype(np.float32))
    assert b.pump() == 4                          # full top bucket, no wait
    assert b.stats["flush_full"] == 1
    assert t.wait(1.0).shape == (4,)


def test_burst_split_across_launches_keeps_row_order():
    models = _nn_models()
    scorer = AOTScorer(models, buckets=(1, 4))
    scorer.warm(launch=False)
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    rng = np.random.default_rng(5)
    x = rng.normal(size=(10, scorer.n_features)).astype(np.float32)
    t = b.submit_burst(x)
    b.drain()
    got = t.wait(5.0)
    want = scorer.score_batch(x).mean(axis=1)
    assert got.tobytes() == want.astype(np.float32).tobytes()
    assert b.stats["batches"] == 3                # 4 + 4 + 2(padded)


def test_threaded_batcher_serves_closed_loop():
    """One real-thread smoke: worker flushes on its own (small deadline,
    bounded wall time)."""
    models = _nn_models()
    server = ServeServer(models=models, key="t", buckets=(1, 4, 16),
                         max_delay_ms=1.0).start()
    try:
        rng = np.random.default_rng(2)
        x = rng.normal(size=(5, 8)).astype(np.float32)
        out = server.score(x, timeout=10.0)
        assert out.shape == (5,) and np.isfinite(out).all()
        st = server.status()
        assert st["state"] == "serving" and st["models"] == 3
    finally:
        server.stop()


def test_http_front_end_scores_and_reports_health():
    """POST /score + GET /healthz on an ephemeral loopback port (the
    stdlib front-end `shifu-tpu serve` binds)."""
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    from shifu_tpu.serve.server import _make_handler
    server = ServeServer(models=_nn_models(), key="h", buckets=(1, 4),
                         max_delay_ms=1.0).start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(server))
    port = httpd.server_address[1]
    th = threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    try:
        rng = np.random.default_rng(9)
        rows = rng.normal(size=(3, 8)).round(4).tolist()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score",
            data=json.dumps({"rows": rows}).encode(),
            headers={"Content-Type": "application/json"})
        doc = json.load(urllib.request.urlopen(req, timeout=15))
        assert len(doc["scores"]) == 3
        want = server.score(np.asarray(rows, np.float32), timeout=15.0)
        assert np.allclose(doc["scores"], want, atol=1e-4)
        health = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=15))
        assert health["state"] == "serving" and health["models"] == 3
    finally:
        httpd.shutdown()
        httpd.server_close()
        server.stop()


# ----------------------------------------------------------- fault sites
def _set_faults(spec: str) -> None:
    environment.set_property("shifu.faults", spec)
    faults.reset_for_tests()


def test_killed_inflight_batch_leaves_registry_serviceable():
    """serve:request ioerror fails exactly that batch's tickets; the
    next request scores bit-identically to an undisturbed scorer."""
    models = _nn_models()
    reg = ModelRegistry()
    reg.load("m", models, buckets=(1, 4))
    b = MicroBatcher(reg.provider("m"), max_delay_s=0.0)
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 8)).astype(np.float32)
    want = reg.get("m").score_batch(x).mean(axis=1)
    _set_faults("serve:request=0:ioerror")
    t = b.submit_burst(x)
    b.drain()
    with pytest.raises(faults.InjectedFault):
        t.wait(1.0)
    assert b.stats["errors"] == 1
    t2 = b.submit_burst(x)                         # next batch is clean
    b.drain()
    got = t2.wait(1.0)
    assert got.tobytes() == want.astype(np.float32).tobytes()
    assert reg.generation("m") == 0


def test_malformed_burst_fails_batch_not_batcher():
    """Concurrent bursts with mismatched row widths coalesce into one
    batch whose ASSEMBLY raises — that error must complete the batch's
    tickets, and the batcher must stay serviceable for the next
    request (regression: assembly errors escaped ``_launch``)."""
    models = _nn_models()
    scorer = AOTScorer(models, buckets=(1, 4))
    scorer.warm(launch=False)
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    rng = np.random.default_rng(12)
    good = rng.normal(size=(2, scorer.n_features)).astype(np.float32)
    bad = rng.normal(size=(2, scorer.n_features - 3)).astype(np.float32)
    t1 = b.submit_burst(good)
    t2 = b.submit_burst(bad)          # same batch: concatenate raises
    b.drain()
    with pytest.raises(ValueError):
        t1.wait(1.0)
    with pytest.raises(ValueError):
        t2.wait(1.0)
    assert b.stats["errors"] == 1
    t3 = b.submit_burst(good)          # batcher is still serviceable
    b.drain()
    assert t3.wait(1.0).shape == (2,)


def test_missing_bins_burst_fails_batch_not_batcher():
    """One client sends bins, another omits them (needs_bins scorer):
    the mixed batch fails its tickets, the next well-formed request
    scores."""
    scorer = AOTScorer([_gbt_model()], buckets=(1, 4))
    scorer.warm(launch=False)
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    rng = np.random.default_rng(13)
    x, bins = _rand_xb(rng, 2, scorer)
    t1 = b.submit_burst(x, bins)
    t2 = b.submit_burst(x, None)       # omitted bins
    b.drain()
    with pytest.raises((ValueError, TypeError)):
        t1.wait(1.0)
    with pytest.raises((ValueError, TypeError)):
        t2.wait(1.0)
    t3 = b.submit_burst(x, bins)
    b.drain()
    assert t3.wait(1.0).shape == (2,)


class _FlakyScorer:
    """Wraps an AOTScorer; raises an UN-tolerated error type on demand."""

    def __init__(self, inner):
        self.inner = inner
        self.boom = False

    @property
    def buckets(self):
        return self.inner.buckets

    @property
    def needs_bins(self):
        return self.inner.needs_bins

    def score_batch(self, rows, bins=None):
        if self.boom:
            raise KeyError("unexpected per-batch failure")
        return self.inner.score_batch(rows, bins)


def test_worker_thread_survives_unexpected_batch_error():
    """An error OUTSIDE the tolerated set (here a KeyError) fails its
    own batch's tickets but must NOT kill the worker thread — the next
    request still scores (regression: the re-raise propagated through
    ``_run`` and permanently stopped serving)."""
    scorer = AOTScorer(_nn_models(), buckets=(1, 4))
    scorer.warm(launch=False)
    flaky = _FlakyScorer(scorer)
    b = MicroBatcher(lambda: flaky, max_delay_s=0.0005).start()
    try:
        rng = np.random.default_rng(14)
        x = rng.normal(size=(2, scorer.n_features)).astype(np.float32)
        flaky.boom = True
        t = b.submit_burst(x)
        with pytest.raises(KeyError):
            t.wait(10.0)
        flaky.boom = False
        t2 = b.submit_burst(x)         # worker thread must still be alive
        assert t2.wait(10.0).shape == (2,)
        assert b.stats["errors"] == 1
    finally:
        b.stop()


def test_requests_counted_per_submit_not_per_row():
    """``stats['requests']`` counts accepted submit calls; row volume
    is ``stats['rows']`` (regression: bursts counted rows as
    requests, duplicating rows_scored)."""
    scorer = AOTScorer(_nn_models(), buckets=(1, 4))
    scorer.warm(launch=False)
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    rng = np.random.default_rng(16)
    b.submit_burst(rng.normal(size=(3, scorer.n_features))
                   .astype(np.float32))
    b.submit(rng.normal(size=scorer.n_features))
    b.drain()
    assert b.stats["requests"] == 2
    assert b.stats["rows"] == 4


def test_failed_journal_leaves_previous_model_live(tmp_path, monkeypatch):
    """swap() journals BEFORE the flip: if the journal commit fails
    (disk full, perms) the swap raises and the OLD model is still live,
    matching the docstring contract."""
    import shifu_tpu.serve.registry as regmod
    reg = ModelRegistry(state_dir=str(tmp_path))
    reg.load("m", _nn_models(seed0=0), buckets=(1, 4))
    rng = np.random.default_rng(15)
    x = rng.normal(size=(2, 8)).astype(np.float32)
    before = reg.get("m").score_batch(x)

    def boom(path, doc):
        raise OSError("disk full")

    monkeypatch.setattr(regmod, "atomic_write_json", boom)
    with pytest.raises(OSError):
        reg.swap("m", _nn_models(seed0=50), buckets=(1, 4))
    assert reg.generation("m") == 0
    assert reg.get("m").score_batch(x).tobytes() == before.tobytes()
    with open(os.path.join(str(tmp_path), "serving.json")) as f:
        assert json.load(f)["m"]["generation"] == 0
    monkeypatch.undo()                 # journal healthy again: promote
    reg.swap("m", _nn_models(seed0=50), buckets=(1, 4))
    assert reg.generation("m") == 1


def test_crashed_swap_leaves_previous_model_live():
    """serve:swap ioerror after the candidate is built but before the
    flip: the OLD model stays live and scores bit-identical to the
    pre-swap scorer."""
    old_models = _nn_models(seed0=0)
    new_models = _nn_models(seed0=50)
    reg = ModelRegistry()
    reg.load("m", old_models, buckets=(1, 4))
    rng = np.random.default_rng(4)
    x = rng.normal(size=(3, 8)).astype(np.float32)
    before = reg.get("m").score_batch(x)
    _set_faults("serve:swap=m:ioerror")
    with pytest.raises(faults.InjectedFault):
        reg.swap("m", new_models, buckets=(1, 4))
    after = reg.get("m").score_batch(x)
    assert after.tobytes() == before.tobytes()
    assert reg.generation("m") == 0
    # the disarmed site lets the next promote through, and scores change
    faults.reset_for_tests()
    environment.reset_for_tests()
    reg.swap("m", new_models, buckets=(1, 4))
    assert reg.generation("m") == 1
    assert reg.get("m").score_batch(x).tobytes() != before.tobytes()


def test_swap_journal_is_atomic_and_resolvable(tmp_path):
    reg = ModelRegistry(state_dir=str(tmp_path))
    reg.load("m", _nn_models(), buckets=(1, 4))
    reg.swap("m", _nn_models(seed0=9), buckets=(1, 4))
    with open(os.path.join(str(tmp_path), "serving.json")) as f:
        doc = json.load(f)
    assert doc["m"]["generation"] == 1
    assert not [f for f in os.listdir(str(tmp_path)) if ".tmp" in f]


def test_hot_swap_between_batches_drops_nothing():
    reg = ModelRegistry()
    reg.load("m", _nn_models(seed0=0), buckets=(1, 4))
    b = MicroBatcher(reg.provider("m"), max_delay_s=0.0)
    rng = np.random.default_rng(6)
    x = rng.normal(size=(2, 8)).astype(np.float32)
    t1 = b.submit_burst(x)
    b.drain()
    reg.swap("m", _nn_models(seed0=77), buckets=(1, 4))
    t2 = b.submit_burst(x)
    b.drain()
    a, c = t1.wait(1.0), t2.wait(1.0)
    assert np.isfinite(a).all() and np.isfinite(c).all()
    assert a.tobytes() != c.tobytes()              # new model answered


# ------------------------------------- generation history / rollback
def test_rollback_restores_previous_generation_bit_identical():
    reg = ModelRegistry()
    reg.load("m", _nn_models(seed0=0), buckets=(1, 4))
    rng = np.random.default_rng(21)
    x = rng.normal(size=(3, 8)).astype(np.float32)
    gen0 = reg.get("m").score_batch(x).tobytes()
    reg.swap("m", _nn_models(seed0=50), buckets=(1, 4))
    assert reg.generation("m") == 1
    assert reg.get("m").score_batch(x).tobytes() != gen0
    reg.rollback("m")
    assert reg.generation("m") == 0
    assert reg.get("m").score_batch(x).tobytes() == gen0
    # generation numbers are monotonic: the next promotion is 2, not 1
    assert reg.next_generation("m") == 2
    reg.swap("m", _nn_models(seed0=60), buckets=(1, 4))
    assert reg.generation("m") == 2


def test_rollback_without_history_raises_current_stays():
    reg = ModelRegistry()
    reg.load("m", _nn_models(seed0=0), buckets=(1, 4))
    rng = np.random.default_rng(22)
    x = rng.normal(size=(2, 8)).astype(np.float32)
    before = reg.get("m").score_batch(x).tobytes()
    with pytest.raises(LookupError):
        reg.rollback("m")
    assert reg.generation("m") == 0
    assert reg.get("m").score_batch(x).tobytes() == before


def test_crashed_rollback_leaves_current_model_live():
    """serve:swap fires on the rollback path too: an injected error
    before the journal+flip leaves the CURRENT (promoted) model live
    and bit-identical; the disarmed site lets the rollback through."""
    reg = ModelRegistry()
    reg.load("m", _nn_models(seed0=0), buckets=(1, 4))
    rng = np.random.default_rng(23)
    x = rng.normal(size=(3, 8)).astype(np.float32)
    gen0 = reg.get("m").score_batch(x).tobytes()
    reg.swap("m", _nn_models(seed0=50), buckets=(1, 4))
    gen1 = reg.get("m").score_batch(x).tobytes()
    _set_faults("serve:swap=m:ioerror")
    with pytest.raises(faults.InjectedFault):
        reg.rollback("m")
    assert reg.generation("m") == 1
    assert reg.get("m").score_batch(x).tobytes() == gen1
    faults.reset_for_tests()
    environment.reset_for_tests()
    reg.rollback("m")
    assert reg.generation("m") == 0
    assert reg.get("m").score_batch(x).tobytes() == gen0


def test_generation_history_bounded_and_journaled(tmp_path):
    environment.set_property("shifu.serve.generations", "2")
    reg = ModelRegistry(state_dir=str(tmp_path))
    reg.load("m", _nn_models(seed0=0), buckets=(1, 4))
    for s in (10, 20, 30, 40):
        reg.swap("m", _nn_models(seed0=s), buckets=(1, 4))
    hist = reg.generation_history("m")
    assert [h["generation"] for h in hist] == [2, 3]   # bounded at 2
    with open(os.path.join(str(tmp_path), "serving.json")) as f:
        doc = json.load(f)["m"]
    assert doc["generation"] == 4
    assert [h["generation"] for h in doc["history"]] == [2, 3]


def test_restore_resolves_journal_and_rollback_from_dirs(tmp_path):
    """A restarted process restores the promoted generation AND the
    rollback history from serving.json; rollback rebuilds the previous
    scorer from its recorded model dir."""
    from shifu_tpu.models.nn import save_model

    def save_dir(name, seed0):
        d = str(tmp_path / name)
        os.makedirs(d, exist_ok=True)
        for i, m in enumerate(_nn_models(seed0=seed0)):
            save_model(os.path.join(d, f"model{i}.nn"), m.spec, m.params)
        return d

    d0, d1 = save_dir("g0", 0), save_dir("g1", 50)
    state = str(tmp_path / "serving")
    reg = ModelRegistry(state_dir=state)
    reg.load("m", d0, buckets=(1, 4))
    reg.swap("m", d1, buckets=(1, 4))
    rng = np.random.default_rng(24)
    x = rng.normal(size=(3, 8)).astype(np.float32)
    gen0 = Scorer_from_dir_scores(d0, x)
    # fresh process: restore from the journal
    reg2 = ModelRegistry(state_dir=state)
    reg2.restore("m", d0, buckets=(1, 4))
    assert reg2.generation("m") == 1
    assert [h["generation"] for h in reg2.generation_history("m")] == [0]
    reg2.rollback("m")
    assert reg2.generation("m") == 0
    assert reg2.get("m").score_batch(x).tobytes() == gen0


def Scorer_from_dir_scores(d, x):
    from shifu_tpu.eval.scorer import Scorer
    s = AOTScorer(Scorer.from_dir(d).models, buckets=(1, 4))
    return s.score_batch(x).tobytes()


# ------------------------------------------- eval Scorer cache (satellite)
def test_scorer_stacked_groups_rebuild_when_models_change():
    """Regression: ``Scorer._stacked_nn_groups`` cached forever — a
    hot-swap that replaces ``self.models`` on a reused Scorer instance
    must rebuild the stacks, not keep scoring the old ensemble."""
    from shifu_tpu.eval.scorer import Scorer
    rng = np.random.default_rng(8)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    old = _nn_models(2, seed0=0)
    new = _nn_models(2, seed0=123)
    s = Scorer(old)
    first = s.score(x).scores
    s.models = list(new)                          # the hot-swap pattern
    swapped = s.score(x).scores
    fresh = Scorer(new).score(x).scores
    assert swapped.tobytes() == fresh.tobytes()
    assert swapped.tobytes() != first.tobytes()


# --------------------------------------------- bench compare (satellite)
def test_compare_latency_class_lower_is_better(tmp_path, capsys):
    """A serve p99 regression exits 2 like a throughput regression;
    a latency IMPROVEMENT never flags."""
    from shifu_tpu.bench import (compare_bench, is_tracked_latency,
                                 is_tracked_throughput, run_compare)
    assert is_tracked_latency("serve_low_p99_ms")
    assert is_tracked_latency("serve_closed_p50_ms")
    assert not is_tracked_latency("serve_qps_sustained")
    assert not is_tracked_throughput("serve_low_p99_ms")
    assert is_tracked_throughput("serve_qps_sustained")
    assert not is_tracked_throughput("serve_low_qps_offered")
    # raw-serving + fleet extras: QPS-class metrics (and the scaling
    # fraction) gate as throughput; the kill-drill p99 as latency
    assert is_tracked_throughput("serve_raw_qps_frac")
    assert is_tracked_throughput("serve_fleet_2r_qps")
    assert is_tracked_throughput("serve_fleet_scaling_frac")
    assert is_tracked_latency("serve_fleet_kill_p99_ms")
    assert not is_tracked_throughput("serve_fleet_kill_p99_ms")
    old = {"metric": "serve_qps_sustained", "value": 100000.0,
           "extra": {"serve_low_p99_ms": 3.0, "serve_mid_p50_ms": 1.0,
                     "serve_deadline_ms": 2.0}}
    new = {"metric": "serve_qps_sustained", "value": 100000.0,
           "extra": {"serve_low_p99_ms": 9.0,     # 3x worse: regression
                     "serve_mid_p50_ms": 0.5,     # improvement: fine
                     "serve_deadline_ms": 2.0}}   # untracked
    rows, regressed = compare_bench(old, new, threshold=0.9)
    assert regressed == ["serve_low_p99_ms"]
    # at exactly old/threshold the latency metric does NOT regress
    edge = {"metric": "serve_qps_sustained", "value": 100000.0,
            "extra": {"serve_low_p99_ms": 3.0 / 0.9,
                      "serve_mid_p50_ms": 1.0, "serve_deadline_ms": 2.0}}
    _, r2 = compare_bench(old, edge, threshold=0.9)
    assert r2 == []
    po, pn = str(tmp_path / "old.json"), str(tmp_path / "new.json")
    with open(po, "w") as f:
        json.dump(old, f)
    with open(pn, "w") as f:
        json.dump(new, f)
    assert run_compare(po, pn, threshold=0.9) == 2
    out = capsys.readouterr().out
    assert "serve_low_p99_ms" in out and "REGRESSED" in out
    assert run_compare(po, po, threshold=0.9) == 0


# ----------------------------------------------------------- CLI surface
def test_bench_help_lists_serve_plane():
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py"), "--help"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0 and "serve" in out.stdout


def test_cli_serve_selfcheck_on_trained_modelset(prepared_set, capsys):
    """`shifu-tpu serve --selfcheck` loads the trained ensemble from
    <dir>/models, warms the buckets, scores synthetic rows in-process
    and exits 0 — the CI smoke for the production surface."""
    from shifu_tpu.cli import main as cli_main
    from shifu_tpu.config import ModelConfig
    mc = ModelConfig.load(os.path.join(prepared_set, "ModelConfig.json"))
    mc.train.numTrainEpochs = 3
    mc.save(os.path.join(prepared_set, "ModelConfig.json"))
    from shifu_tpu.pipeline.train import TrainProcessor
    assert TrainProcessor(prepared_set, params={}).run() == 0
    rc = cli_main(["--dir", prepared_set,
                   "-Dshifu.serve.buckets=1,4,16", "serve",
                   "--selfcheck", "4"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["selfcheck_rows"] == 4
    assert len(doc["scores_head"]) == 4
    assert doc["buckets"] == [1, 4, 16]
    # journal-style promote wrote the serving manifest atomically
    with open(os.path.join(prepared_set, "serving", "serving.json")) as f:
        j = json.load(f)
    assert list(j.values())[0]["generation"] == 0


# ------------------------------------------------- raw-record serving
def _raw_configs():
    """2 numeric ZSCALE columns + 1 categorical: the minimal mixed
    ColumnConfig snapshot the fused transform has to replay exactly."""
    from shifu_tpu.config import ColumnConfig
    ccs = []
    for j, name in enumerate(("a", "b")):
        cc = ColumnConfig(columnNum=j, columnName=name, finalSelect=True)
        cc.columnBinning.binBoundary = [float("-inf"), 0.0, 1.0]
        cc.columnBinning.binCountNeg = [5, 5, 5]
        cc.columnBinning.binCountPos = [2, 3, 4]
        cc.columnBinning.binPosRate = [2 / 7., 3 / 8., 4 / 9.]
        cc.columnBinning.binCountWoe = [0.1, -0.2, 0.3, 0.0]
        cc.columnStats.mean = 0.4 + j
        cc.columnStats.stdDev = 1.3
        ccs.append(cc)
    cc = ColumnConfig(columnNum=2, columnName="c", finalSelect=True)
    cc.columnBinning.binCategory = ["red", "green", "blue"]
    cc.columnBinning.binCountNeg = [4, 4, 4]
    cc.columnBinning.binCountPos = [1, 2, 3]
    cc.columnBinning.binPosRate = [.2, 1 / 3., 3 / 7.]
    cc.columnBinning.binCountWoe = [0.05, -0.1, 0.2, 0.0]
    ccs.append(cc)
    return ccs


#: raw records exercising every parse edge the offline reader has:
#: missing field, unparseable numeric, unknown category, empty record,
#: string-typed number, int-typed number
_RAW_RECORDS = [
    {"a": 0.5, "b": 1.5, "c": "green"},
    {"a": None, "b": "not-a-number", "c": "chartreuse"},
    {"a": -3, "b": 0.0, "c": "red"},
    {},
    {"a": "2.25", "b": 7, "c": "blue"},
]


def _offline_oracle(mc, ccs, models, records):
    """The offline norm+eval pipeline over JSON records: stringify the
    fields exactly as the CSV reader would, run the host
    DatasetTransformer, score with the batch Scorer, mean-reduce in f32
    — the bit-parity reference for ``score_raw``."""
    import pandas as pd

    from shifu_tpu.data.reader import RawChunk, record_field_str
    from shifu_tpu.data.transform import DatasetTransformer
    from shifu_tpu.eval.scorer import Scorer
    tf = DatasetTransformer(mc, ccs)
    names = [c.columnName for c in tf.columns]
    data = pd.DataFrame({n: [record_field_str(r.get(n)) for r in records]
                         for n in names}, dtype=object)
    tc = tf.transform(RawChunk(columns=names, data=data))
    res = Scorer(models).score(tc.x, bins=tc.bins)
    return np.asarray(res.select("mean"), np.float32)


def _raw_models(kind):
    """A tiny ensemble over the 3-column transform output (x width 3,
    bins width 3) for each model family the serve plane hosts."""
    if kind == "nn":
        return _nn_models(n=2, n_features=3)
    if kind == "gbt":
        from shifu_tpu.models.tree import (IndependentTreeModel,
                                           TreeModelSpec)
        from shifu_tpu.train.dt_trainer import DTSettings, train_gbt
        rng = np.random.default_rng(7)
        bins = rng.integers(0, 4, size=(256, 3)).astype(np.int32)
        y = (rng.random(256) < 0.4).astype(np.float32)
        res = train_gbt(bins, y, np.ones(256, np.float32), 5,
                        np.zeros(3, bool),
                        DTSettings(n_trees=3, depth=3, loss="log",
                                   learning_rate=0.1))
        spec = TreeModelSpec(n_trees=len(res.trees), depth=3, n_bins=5,
                             **res.spec_kwargs)
        return [IndependentTreeModel(spec, res.trees)]
    from shifu_tpu.models.wdl import (IndependentWDLModel, WDLModelSpec)
    from shifu_tpu.models.wdl import init_params as wdl_init
    extra = {"num_feat_idx": [0, 1], "cat_col_idx": [2]}
    cards = [6]
    if kind == "wdl_hashed":
        from shifu_tpu.ops.hashing import column_hash_key
        extra = {**extra, "hash_buckets": 4, "hashed_cols": [0],
                 "hash_keys": [column_hash_key(2)]}
        cards = [4]
    spec = WDLModelSpec(numeric_dim=2, cat_cardinalities=cards,
                        embed_dim=4, hidden_nodes=[8],
                        activations=["relu"], extra=extra)
    return [IndependentWDLModel(spec, wdl_init(jax.random.PRNGKey(5),
                                               spec))]


@pytest.mark.parametrize("kind", ["nn", "gbt", "wdl", "wdl_hashed"])
def test_raw_records_score_bit_identical_to_offline(kind):
    """``score_raw`` over the fused transform is BIT-identical to the
    offline norm+eval pipeline — across NN, GBT, WDL and hashed-ID WDL
    ensembles, including missing/invalid/unknown-category records."""
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.serve.transform import FusedTransform
    mc, ccs = ModelConfig(), _raw_configs()
    models = _raw_models(kind)
    want = _offline_oracle(mc, ccs, models, _RAW_RECORDS)
    server = ServeServer(models=models, key="raw", buckets=(8,),
                         transform=FusedTransform(mc, ccs))
    out = server.score_raw(_RAW_RECORDS)
    assert out["errors"] == []
    got = np.asarray(out["scores"], np.float32)
    assert got.tobytes() == want.tobytes()


def test_raw_modelset_dir_parity_and_offline_oracle(tmp_path):
    """End-to-end from a modelset DIRECTORY: ``ServeServer(dir)`` wires
    the fused transform from the ModelConfig/ColumnConfig snapshot and
    ``score_records_offline`` (the module-level oracle) agrees bitwise."""
    from shifu_tpu.config import save_column_configs
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.models.nn import NNModelSpec, init_params, save_model
    from shifu_tpu.pipeline.evaluate import score_records_offline
    d = str(tmp_path)
    ModelConfig().save(os.path.join(d, "ModelConfig.json"))
    save_column_configs(_raw_configs(), os.path.join(d,
                                                     "ColumnConfig.json"))
    spec = NNModelSpec(input_dim=3, hidden_nodes=[4],
                       activations=["tanh"])
    os.makedirs(os.path.join(d, "models"))
    for i in range(2):
        save_model(os.path.join(d, "models", f"model{i}.nn"), spec,
                   init_params(jax.random.PRNGKey(i), spec))
    want = score_records_offline(d, _RAW_RECORDS)
    server = ServeServer(d, key="m", buckets=(8,)).start()
    try:
        assert server.status()["accepts_raw"] is True
        out = server.score_raw(_RAW_RECORDS)
        got = np.asarray(out["scores"], np.float32)
        assert got.tobytes() == want.tobytes()
    finally:
        server.stop()


def test_raw_warmed_server_zero_recompiles():
    """A warmed raw server performs ZERO recompiles over a randomized
    record-count sweep — the fused-transform signature is part of the
    warmed executable set, not a per-request compile."""
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.serve.transform import FusedTransform
    server = ServeServer(models=_nn_models(n=2, n_features=3),
                         key="raw", buckets=(1, 4, 16),
                         transform=FusedTransform(ModelConfig(),
                                                  _raw_configs()),
                         max_delay_ms=0.0).start()
    try:
        rng = np.random.default_rng(13)
        obs.set_enabled(True)
        before = serve_recompile_count()
        ctr = obs.counter("xla.recompiles")
        xla_before = ctr.value
        for n in rng.integers(1, 17, size=25):
            recs = [{"a": float(rng.normal()), "b": float(rng.normal()),
                     "c": ["red", "green", "blue", "?"][int(rng.integers(4))]}
                    for _ in range(int(n))]
            out = server.score_raw(recs)
            assert all(s is not None for s in out["scores"])
        assert serve_recompile_count() - before == 0
        assert ctr.value - xla_before == 0
    finally:
        server.stop()


def test_raw_malformed_records_rejected_per_record():
    """One bad record never poisons its neighbours: non-object records
    and non-scalar fields get coded errors + null score slots while the
    parseable records around them score BIT-identically to a clean
    batch (the ``-Dshifu.data.badThreshold`` philosophy, per request)."""
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.serve.transform import (ERR_BAD_FIELD, ERR_BAD_RECORD,
                                           FusedTransform)
    server = ServeServer(models=_nn_models(n=2, n_features=3),
                         key="raw", buckets=(4,),
                         transform=FusedTransform(ModelConfig(),
                                                  _raw_configs()))
    good = [{"a": 0.5, "b": 1.5, "c": "green"},
            {"a": -1.0, "b": 0.25, "c": "red"}]
    mixed = [good[0], 123, {"a": [1, 2], "b": 0.0, "c": "red"}, good[1]]
    out = server.score_raw(mixed)
    assert out["scores"][1] is None and out["scores"][2] is None
    codes = {e["index"]: e["code"] for e in out["errors"]}
    assert codes == {1: ERR_BAD_RECORD, 2: ERR_BAD_FIELD}
    clean = server.score_raw(good)
    assert clean["errors"] == []
    got = np.asarray([out["scores"][0], out["scores"][3]], np.float32)
    assert got.tobytes() == np.asarray(clean["scores"],
                                       np.float32).tobytes()
    # an all-bad request still answers (every slot null, every error
    # coded) — the HTTP front-end maps this shape to a 400
    allbad = server.score_raw([None, 7])
    assert allbad["scores"] == [None, None]
    assert len(allbad["errors"]) == 2


def test_raw_http_records_healthz_and_all_bad_400(tmp_path):
    """``POST /score {"records": ...}`` end-to-end on a loopback port:
    partial rejection answers 200 with null slots + coded errors,
    an all-bad payload answers 400, and ``GET /healthz`` advertises
    ``accepts_raw`` (the bit the fleet router refuses to mix)."""
    import threading
    import urllib.error
    import urllib.request
    from http.server import ThreadingHTTPServer

    from shifu_tpu.config import save_column_configs
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.models.nn import NNModelSpec, init_params, save_model
    from shifu_tpu.serve.server import _make_handler
    d = str(tmp_path)
    ModelConfig().save(os.path.join(d, "ModelConfig.json"))
    save_column_configs(_raw_configs(), os.path.join(d,
                                                     "ColumnConfig.json"))
    spec = NNModelSpec(input_dim=3, hidden_nodes=[4],
                       activations=["tanh"])
    os.makedirs(os.path.join(d, "models"))
    save_model(os.path.join(d, "models", "model0.nn"), spec,
               init_params(jax.random.PRNGKey(0), spec))
    server = ServeServer(d, key="m", buckets=(4,),
                         max_delay_ms=1.0).start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(server))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def post(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        return json.load(urllib.request.urlopen(req, timeout=15))
    try:
        doc = post({"records": [{"a": 0.5, "b": 1.5, "c": "green"},
                                17]})
        assert doc["scores"][0] is not None and doc["scores"][1] is None
        assert doc["errors"][0]["index"] == 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            post({"records": [17, None]})
        assert ei.value.code == 400
        health = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=15))
        assert health["accepts_raw"] is True
    finally:
        httpd.shutdown()
        httpd.server_close()
        server.stop()


def test_prebinned_modelset_refuses_raw_and_reports_it():
    """A models-only server (no ColumnConfig snapshot) advertises
    ``accepts_raw: false`` and refuses ``score_raw`` with a pointed
    error instead of scoring garbage."""
    server = ServeServer(models=_nn_models(), key="pb", buckets=(4,))
    assert server.status()["accepts_raw"] is False
    with pytest.raises(ValueError, match="pre-binned"):
        server.score_raw([{"a": 1.0}])
