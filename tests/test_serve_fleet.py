"""Multi-replica serving fleet suite (``pytest -m fleet``).

The in-process tests run real ``ServeServer`` workers behind real
loopback ``ThreadingHTTPServer`` listeners — the router sees genuine
HTTP transport (connection refused on death, real concurrency during a
coordinated swap) without subprocess boot cost, so they stay tier-1
fast.  The subprocess SIGKILL drill (``spawn_worker`` + the
``serve:replica`` fault site) is additionally marked ``slow``.

Covers the fleet acceptance surface: health-aware balancing, requeue on
replica death (every accepted request completes while any replica
lives), the coordinated hot-swap's NO-mixed-model-window invariant
under concurrent load, the ``-Dshifu.serve.canaryFrac`` slice, the
mixed raw/pre-binned fleet refusal, and burial of an unreachable
DRAINING replica at swap-prepare time.
"""

import json
import os
import threading
import time
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

import jax

from shifu_tpu import faults, obs
from shifu_tpu.config import (ColumnConfig, environment,
                              save_column_configs)
from shifu_tpu.config.model_config import ModelConfig
from shifu_tpu.models.nn import NNModelSpec, init_params, save_model
from shifu_tpu.serve.router import (DEAD, DRAINING, UP, ServeRouter,
                                    spawn_worker, wait_for_announce)
from shifu_tpu.serve.server import ServeServer, _make_handler

pytestmark = [pytest.mark.serve, pytest.mark.fleet]


@pytest.fixture(autouse=True)
def _clean_env():
    environment.reset_for_tests()
    faults.reset_for_tests()
    yield
    environment.reset_for_tests()
    faults.reset_for_tests()
    obs.set_enabled(False)


def _modelset(d, n_models=2, seed0=0, subdir="models"):
    """A raw-capable modelset on disk: 2 numeric ZSCALE columns + a tiny
    NN ensemble (every fleet worker loads the same snapshot)."""
    if not os.path.exists(os.path.join(d, "ModelConfig.json")):
        ccs = []
        for j, name in enumerate(("a", "b")):
            cc = ColumnConfig(columnNum=j, columnName=name,
                              finalSelect=True)
            cc.columnBinning.binBoundary = [float("-inf"), 0.0, 1.0]
            cc.columnBinning.binCountNeg = [5, 5, 5]
            cc.columnBinning.binCountPos = [2, 3, 4]
            cc.columnBinning.binPosRate = [2 / 7., 3 / 8., 4 / 9.]
            cc.columnBinning.binCountWoe = [0.1, -0.2, 0.3, 0.0]
            cc.columnStats.mean = 0.4 + j
            cc.columnStats.stdDev = 1.3
            ccs.append(cc)
        ModelConfig().save(os.path.join(d, "ModelConfig.json"))
        save_column_configs(ccs, os.path.join(d, "ColumnConfig.json"))
    spec = NNModelSpec(input_dim=2, hidden_nodes=[4],
                       activations=["tanh"])
    md = os.path.join(d, subdir)
    os.makedirs(md, exist_ok=True)
    for i in range(n_models):
        save_model(os.path.join(md, f"model{i}.nn"), spec,
                   init_params(jax.random.PRNGKey(seed0 + i), spec))
    return md


class _TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that can sever ESTABLISHED connections too —
    ``shutdown()`` only stops the accept loop, which no longer simulates
    transport death now that the router pools keep-alive connections."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._conns = set()
        self._conns_lock = threading.Lock()

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def kill_connections(self):
        import socket as _socket
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for s in conns:
            try:
                s.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class _Fleet:
    """In-process workers behind real loopback HTTP listeners."""

    def __init__(self):
        self.workers = []        # (srv, httpd)
        self.router = ServeRouter(poll_ms=100, stale_s=2)

    def add(self, model_set_dir, name):
        srv = ServeServer(model_set_dir, key="m", buckets=(4, 16),
                          replica=name, max_delay_ms=1.0)
        srv.registry.state_dir = None    # in-memory journal per worker
        srv.start()
        httpd = _TrackingHTTPServer(("127.0.0.1", 0),
                                    _make_handler(srv))
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        self.workers.append((srv, httpd))
        self.router.add_backend(name, httpd.server_address[1])
        return srv, httpd

    def up(self):
        self.router.poll_once()
        self.router.ensure_uniform()
        return self.router.fleet_doc()

    def kill_listener(self, httpd):
        httpd.shutdown()
        httpd.server_close()
        httpd.kill_connections()

    def stop(self):
        self.router.stop(kill_workers=False)
        for srv, httpd in self.workers:
            try:
                httpd.shutdown()
                httpd.server_close()
            except OSError:
                pass
            srv.stop()


@pytest.fixture
def fleet():
    f = _Fleet()
    yield f
    f.stop()


_RECORDS = [{"a": 0.5, "b": 1.5}, {"a": None, "b": "?"}]


# -------------------------------------------------------- basic routing
def test_router_balances_and_reports_uniform_fleet(fleet, tmp_path):
    d = str(tmp_path)
    _modelset(d)
    fleet.add(d, "r0")
    fleet.add(d, "r1")
    doc = fleet.up()
    assert doc["up"] == 2 and doc["accepts_raw"] is True
    base = fleet.router.score({"records": _RECORDS})["scores"]
    assert base[0] is not None
    for _ in range(9):
        out = fleet.router.score({"records": _RECORDS})
        assert out["scores"] == base      # same snapshot everywhere
    reqs = {r.name: r.requests for r in fleet.router.replicas.values()}
    assert all(v > 0 for v in reqs.values()), reqs


def test_requeue_on_replica_death_completes_request(fleet, tmp_path):
    """A replica whose transport dies mid-fleet never fails a request:
    the router requeues on a peer and the answer is identical."""
    d = str(tmp_path)
    _modelset(d)
    fleet.add(d, "r0")
    _, h1 = fleet.add(d, "r1")
    fleet.up()
    base = fleet.router.score({"records": _RECORDS})["scores"]
    obs.set_enabled(True)
    before = obs.counter("serve.fleet_requeues").value
    fleet.kill_listener(h1)
    # r1 will be picked eventually; every request must still complete
    for _ in range(6):
        out = fleet.router.score({"records": _RECORDS})
        assert out["replica"] == "r0" or out["scores"] == base
    assert obs.counter("serve.fleet_requeues").value > before
    # the router noticed: either the health poll drained/buried r1 or
    # its circuit breaker opened and hides it from dispatch
    r1 = fleet.router.replicas["r1"]
    assert r1.state in (DRAINING, DEAD) or r1.breaker.state == "open"


def test_mixed_raw_prebinned_fleet_refused(fleet, tmp_path):
    """``ensure_uniform`` refuses a fleet where one replica lacks the
    transform snapshot — a raw request must never depend on which
    replica it lands on."""
    d = str(tmp_path)
    _modelset(d)
    naked = str(tmp_path / "naked")
    os.makedirs(naked)
    _modelset(naked)                       # then strip the snapshot
    os.remove(os.path.join(naked, "ModelConfig.json"))
    os.remove(os.path.join(naked, "ColumnConfig.json"))
    fleet.add(d, "r0")
    fleet.add(naked, "naked")
    fleet.router.poll_once()
    with pytest.raises(ValueError, match="accepts_raw"):
        fleet.router.ensure_uniform()


# ------------------------------------------------------ coordinated swap
def test_coordinated_swap_has_no_mixed_model_window(fleet, tmp_path):
    """Under concurrent load, for any two requests where a finished
    before b started, gen(a) <= gen(b) — and both generations are
    observed, so the invariant is tested against real traffic."""
    d = str(tmp_path)
    _modelset(d)
    _modelset(d, seed0=100, subdir="models2")
    fleet.add(d, "r0")
    fleet.add(d, "r1")
    fleet.up()
    results, stop = [], threading.Event()

    def pound():
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                o = fleet.router.score({"records": _RECORDS},
                                       timeout=60)
            except RuntimeError:
                continue
            results.append((t0, time.monotonic(), o["generation"]))

    threads = [threading.Thread(target=pound, daemon=True)
               for _ in range(3)]
    [t.start() for t in threads]
    time.sleep(0.25)
    doc = fleet.router.coordinated_swap(os.path.join(d, "models2"))
    time.sleep(0.25)
    stop.set()
    [t.join(timeout=30) for t in threads]
    assert sorted(doc["committed"]) == ["r0", "r1"]
    assert not doc.get("errors")
    gens = {g for _, _, g in results}
    assert gens == {0, 1}, gens
    bad = [(a, b) for a in results for b in results
           if a[1] < b[0] and a[2] > b[2]]
    assert bad == [], f"{len(bad)} mixed-window pairs"


def test_canary_swap_commits_only_a_slice(fleet, tmp_path):
    d = str(tmp_path)
    _modelset(d)
    _modelset(d, seed0=100, subdir="models2")
    fleet.add(d, "r0")
    fleet.add(d, "r1")
    fleet.up()
    doc = fleet.router.coordinated_swap(os.path.join(d, "models2"),
                                        canary=0.5)
    assert len(doc["committed"]) == 1 and len(doc["aborted"]) == 1
    gens = {r.generation for r in fleet.router.replicas.values()
            if r.state == UP}
    assert gens == {0, 1}                  # the explicit mixed slice


def test_canary_frac_property_drives_default(fleet, tmp_path):
    d = str(tmp_path)
    _modelset(d)
    _modelset(d, seed0=100, subdir="models2")
    fleet.add(d, "r0")
    fleet.add(d, "r1")
    fleet.up()
    environment.set_property("shifu.serve.canaryFrac", "0.5")
    doc = fleet.router.coordinated_swap(os.path.join(d, "models2"))
    assert len(doc["committed"]) == 1 and len(doc["aborted"]) == 1


def test_swap_buries_unreachable_draining_replica(fleet, tmp_path):
    """An unreachable DRAINING replica cannot veto the fleet's swap: it
    is buried DEAD and skipped (it serves nothing, so no mixed window),
    while the reachable fleet commits."""
    d = str(tmp_path)
    _modelset(d)
    _modelset(d, seed0=100, subdir="models2")
    fleet.add(d, "r0")
    _, h1 = fleet.add(d, "r1")
    fleet.up()
    fleet.kill_listener(h1)
    fleet.router.replicas["r1"].state = DRAINING
    doc = fleet.router.coordinated_swap(os.path.join(d, "models2"))
    assert doc["committed"] == ["r0"]
    assert fleet.router.replicas["r1"].state == DEAD
    out = fleet.router.score({"records": _RECORDS})
    assert out["generation"] == 1


def test_swap_prepare_failure_on_live_replica_aborts_all(fleet,
                                                         tmp_path):
    """A live replica failing PREPARE aborts the whole swap — the old
    fleet keeps serving generation 0 everywhere (no partial commit)."""
    d = str(tmp_path)
    _modelset(d)
    fleet.add(d, "r0")
    fleet.add(d, "r1")
    fleet.up()
    with pytest.raises(RuntimeError, match="prepare"):
        fleet.router.coordinated_swap(str(tmp_path / "nonexistent"))
    out = fleet.router.score({"records": _RECORDS})
    assert out["generation"] == 0
    assert fleet.router.fleet_doc()["up"] == 2


# ------------------------------------------------------------ fault site
def test_serve_replica_fault_site_declared_and_scoped():
    """The replica-death drill site exists and its point key is the
    replica name — arming r0 must not touch r1's path."""
    assert faults.is_declared_site("serve", "replica")
    environment.set_property("shifu.faults", "serve:replica=r0:ioerror")
    faults.reset_for_tests()
    with pytest.raises(OSError):
        faults.fire("serve", "replica", "r0")
    faults.fire("serve", "replica", "r1")   # different replica: no-op
    faults.fire("serve", "replica", "r0")   # fired once, now disarmed


# ------------------------------------------------- subprocess kill drill
@pytest.mark.slow
def test_replica_sigkill_drill_requeues_and_buries(tmp_path):
    """The real drill: two ``spawn_worker`` subprocesses, the
    ``serve:replica`` fault hard-kills r0 on its first scoring request
    (os._exit — a SIGKILL-equivalent), and the router requeues the
    in-flight request on r1 so it still completes; the next poll
    buries r0."""
    d = str(tmp_path)
    _modelset(d)
    # forwarded to every worker as -Dshifu.faults; the point key scopes
    # the kill to r0 only
    environment.set_property("shifu.faults", "serve:replica=r0:kill")
    fdir = os.path.join(d, "serving", "fleet")
    os.makedirs(fdir, exist_ok=True)
    router = ServeRouter(poll_ms=200, stale_s=5)
    procs = {}
    try:
        for name in ("r0", "r1"):
            ann = os.path.join(fdir, f"{name}.json")
            p = spawn_worker(d, name, ann,
                             extra_env={"JAX_PLATFORMS": "cpu"})
            procs[name] = (p, ann)
        for name, (p, ann) in procs.items():
            doc = wait_for_announce(ann, p, timeout=240)
            router.add_backend(name, doc["port"], proc=p)
        router.poll_once()
        router.ensure_uniform()
        assert router.fleet_doc()["up"] == 2
        # drive until r0 is picked and dies mid-request; every request
        # must nevertheless complete (requeued on r1)
        outs = [router.score({"records": [{"a": 0.5, "b": 1.5}]},
                             timeout=120) for _ in range(4)]
        assert all(o["scores"][0] is not None for o in outs)
        assert procs["r0"][0].poll() is not None     # hard-died
        assert {o["replica"] for o in outs} <= {"r0", "r1"}
        router.poll_once()
        assert router.replicas["r0"].state == DEAD
        out = router.score({"records": [{"a": 0.5, "b": 1.5}]})
        assert out["replica"] == "r1"
    finally:
        router.stop()
        for p, _ in procs.values():
            if p.poll() is None:
                p.kill()


# ------------------------------------------------- overload chaos drill
@pytest.mark.slow
def test_fleet_chaos_sigkill_under_double_load_no_hung_clients(tmp_path):
    """Overload chaos drill: two subprocess replicas under ~2x the
    client concurrency the earlier drills use, r0 SIGKILLed mid-window.
    EVERY request resolves — a score or a CODED fast-fail
    (``OverloadedError`` when the retry budget sheds) — zero hung
    client threads, and the shed fraction stays bounded while r1
    lives."""
    from shifu_tpu.serve.overload import OverloadedError
    d = str(tmp_path)
    _modelset(d)
    fdir = os.path.join(d, "serving", "fleet")
    os.makedirs(fdir, exist_ok=True)
    router = ServeRouter(poll_ms=200, stale_s=5)
    procs = {}
    try:
        for name in ("r0", "r1"):
            ann = os.path.join(fdir, f"{name}.json")
            p = spawn_worker(d, name, ann,
                             extra_env={"JAX_PLATFORMS": "cpu"})
            procs[name] = (p, ann)
        for name, (p, ann) in procs.items():
            doc = wait_for_announce(ann, p, timeout=240)
            router.add_backend(name, doc["port"], proc=p)
        router.poll_once()
        router.ensure_uniform()
        assert router.fleet_doc()["up"] == 2

        ok, shed, uncoded = [], [], []
        stop = threading.Event()

        def pound(i):
            while not stop.is_set():
                try:
                    out = router.score(
                        {"records": [{"a": 0.5, "b": 1.5}]},
                        timeout=30.0, deadline_ms=30000.0)
                    ok.append(out["replica"])
                except OverloadedError:
                    shed.append(i)      # coded fast-fail: acceptable
                except RuntimeError as e:
                    uncoded.append(str(e))

        threads = [threading.Thread(target=pound, args=(i,),
                                    daemon=True) for i in range(4)]
        [t.start() for t in threads]
        time.sleep(1.0)
        procs["r0"][0].kill()           # the real SIGKILL, mid-load
        time.sleep(2.0)
        stop.set()
        [t.join(timeout=60) for t in threads]
        # zero hung clients: every thread exited its loop
        assert not any(t.is_alive() for t in threads)
        total = len(ok) + len(shed) + len(uncoded)
        assert total > 0 and len(ok) > 0
        # every failure is a coded shed; nothing died un-coded while
        # r1 served on
        assert uncoded == [], uncoded[:3]
        # bounded shed rate: the kill may burn the retry budget
        # briefly, but r1 absorbs the fleet — most requests score
        assert len(shed) / total < 0.5, (len(shed), total)
        router.poll_once()
        assert router.replicas["r0"].state == DEAD
        # r1 survived the drill; SLO-burn draining under doubled load is
        # the router doing its job, so only rule out DEAD
        assert router.replicas["r1"].state in (UP, DRAINING)
    finally:
        router.stop()
        for p, _ in procs.values():
            if p.poll() is None:
                p.kill()
