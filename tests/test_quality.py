"""Model-quality observability suite (tier-1-fast except the subprocess
SIGKILL segment-rotation drill, which is additionally marked slow).

Crash-safe score-log segments (atomic rotation, orphan sweep, disk
budget, the ``obs:scorelog`` kill drill), the delayed-label join
(watermark eviction, scalar broadcast, split bursts, drop directory),
the streaming quality monitor (live AUC / ECE / score-PSI vs the
posttrain snapshot), the refresh controller's THIRD trigger source, the
fleet monitor's merged quality row (CLI-subprocess-tested) and the
byte-deterministic ``analysis --telemetry`` quality section.

The e2e drill is the acceptance path: an in-process ``ServeServer``
with sampled score logging on, delayed outcomes arriving with FLIPPED
labels, live AUC collapsing below the posttrain baseline, and the
refresh controller recording a ``quality`` trigger and entering a
retrain cycle — then judging the promoted generation on fresh windows.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from shifu_tpu import faults, obs
from shifu_tpu.config import environment
from shifu_tpu.eval.gate import GateResult
from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                 init_params)
from shifu_tpu.obs import monitor as monitor_mod
from shifu_tpu.obs import report as report_mod
from shifu_tpu.obs.outcomes import OutcomeJoiner, outcomes_drop_dir
from shifu_tpu.obs.quality import (QualityMonitor, load_posttrain_snapshot,
                                   start_quality_monitor,
                                   write_posttrain_snapshot)
from shifu_tpu.obs.scorelog import (ScoreLog, read_score_records,
                                    scorelog_dir)
from shifu_tpu.refresh import RefreshConfig, RefreshController
from shifu_tpu.serve import ModelRegistry
from shifu_tpu.serve.server import ServeServer

pytestmark = pytest.mark.quality

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_env():
    environment.reset_for_tests()
    faults.reset_for_tests()
    yield
    environment.reset_for_tests()
    faults.reset_for_tests()
    obs.set_enabled(False)


def _nn_models(n=2, n_features=8, seed0=0):
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=[8],
                       activations=["relu"])
    return [IndependentNNModel(spec, init_params(
        jax.random.PRNGKey(seed0 + i), spec)) for i in range(n)]


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------- score log
def test_scorelog_roundtrip_rotation_and_close(tmp_path):
    root = str(tmp_path / "scorelog")
    sl = ScoreLog(root, sample_rate=1.0, segment_bytes=96)
    for i in range(6):
        req = sl.log(f"r{i}", [0.25, 0.75], gen=1, ts=100.0 + i)
        assert req == f"r{i}"           # rate 1.0 logs every request
    assert sl.stats["segments"] >= 1    # tiny segments forced rotation
    sl.close()                          # clean shutdown commits the tail
    assert not [n for n in os.listdir(root) if n.endswith(".open")]
    assert "seg-000000.jsonl" in os.listdir(root)
    skipped = []
    recs = read_score_records(root, skipped=skipped)
    assert skipped == []
    assert [r["req"] for r in recs] == [f"r{i}" for i in range(6)]
    assert recs[0] == {"ts": 100.0, "gen": 1, "req": "r0",
                       "scores": [0.25, 0.75]}


def test_scorelog_sampling_off_writes_nothing(tmp_path):
    root = str(tmp_path / "scorelog")
    sl = ScoreLog(root, sample_rate=0.0, segment_bytes=64)
    for i in range(32):
        assert sl.log(f"r{i}", [0.5]) is None
    sl.close()
    assert sl.stats["records"] == 0
    assert os.listdir(root) == []       # no segment was ever opened


def test_scorelog_mints_req_id_when_caller_has_none(tmp_path):
    sl = ScoreLog(str(tmp_path / "sl"), sample_rate=1.0)
    req = sl.log(None, [0.5], gen=0)
    assert isinstance(req, str) and len(req) == 16
    sl.close()


def test_scorelog_budget_prunes_oldest_segments(tmp_path):
    root = str(tmp_path / "scorelog")
    sl = ScoreLog(root, sample_rate=1.0, segment_bytes=64,
                  budget_bytes=200)
    for i in range(40):
        sl.log(f"r{i:03d}", [0.125], gen=0, ts=float(i))
    sl.close()
    assert sl.stats["pruned"] > 0
    names = sorted(os.listdir(root))
    # the newest committed segment survives, the oldest ones are gone
    assert "seg-000000.jsonl" not in names
    recs = read_score_records(root)
    assert recs                          # recent history is intact
    assert recs[-1]["req"] == "r039"


def test_scorelog_reader_skips_torn_tail_and_writer_recovers(tmp_path):
    root = str(tmp_path / "scorelog")
    os.makedirs(root)
    with open(os.path.join(root, "seg-000000.jsonl"), "w") as f:
        f.write(json.dumps({"req": "a", "scores": [0.5]}) + "\n")
        f.write('{"req": "torn', )       # torn line inside a committed seg
    with open(os.path.join(root, "seg-000001.jsonl.open"), "w") as f:
        f.write('{"req": "b", "sco')     # a crashed writer's torn tail
    skipped = []
    recs = read_score_records(root, skipped=skipped)
    assert [r["req"] for r in recs] == ["a"]
    assert "seg-000001.jsonl.open" in skipped
    assert "seg-000000.jsonl:2" in skipped
    # the next writer sweeps the orphan and continues AFTER the committed
    sl = ScoreLog(root, sample_rate=1.0, segment_bytes=8)
    assert sl.recovered == 1
    sl.log("c", [0.25], gen=0, ts=1.0)
    sl.close()
    names = sorted(os.listdir(root))
    assert names == ["seg-000000.jsonl", "seg-000001.jsonl"]
    assert [r["req"] for r in read_score_records(root)] == ["a", "c"]


@pytest.mark.faults
@pytest.mark.slow
def test_scorelog_kill_mid_rotation_subprocess(tmp_path):
    """ACCEPTANCE (satellite): SHIFU_TPU_FAULTS=obs:scorelog=1:kill dies
    before segment 1's atomic commit — segment 0 stays intact, readers
    skip the torn ``.open`` tail with a surfaced count, and the next
    writer sweeps the orphan and keeps going."""
    root = str(tmp_path / "scorelog")
    child = (
        "import sys\n"
        "from shifu_tpu.obs.scorelog import ScoreLog\n"
        "sl = ScoreLog(sys.argv[1], sample_rate=1.0, segment_bytes=48)\n"
        "for i in range(64):\n"
        "    sl.log('r%03d' % i, [0.25, 0.75], gen=0, ts=float(i))\n"
        "sl.close()\n"
        "print('UNREACHABLE')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["SHIFU_TPU_FAULTS"] = "obs:scorelog=1:kill"
    p = subprocess.run([sys.executable, "-c", child, root],
                       capture_output=True, text=True, env=env,
                       cwd=REPO, timeout=120)
    assert p.returncode == 137, p.stdout + p.stderr
    assert "UNREACHABLE" not in p.stdout
    names = sorted(os.listdir(root))
    assert "seg-000000.jsonl" in names          # prior commit intact
    assert "seg-000001.jsonl.open" in names     # the torn final segment
    skipped = []
    recs = read_score_records(root, skipped=skipped)
    assert skipped == ["seg-000001.jsonl.open"]
    assert recs and recs[0]["req"] == "r000"
    sl = ScoreLog(root, sample_rate=1.0, segment_bytes=48)
    assert sl.recovered == 1                    # orphan swept
    sl.log("after-crash", [0.5], gen=1, ts=99.0)
    sl.close()
    assert not [n for n in os.listdir(root) if n.endswith(".open")]
    assert read_score_records(root)[-1]["req"] == "after-crash"


# ------------------------------------------------------ delayed-label join
def test_outcome_join_scalar_broadcast_and_split_burst():
    clock = Clock()
    joined = []
    j = OutcomeJoiner(watermark_s=100.0, clock=clock,
                      on_join=lambda g, s, lab: joined.append((g, s, lab)))
    j.record_prediction("r1", [0.1, 0.2, 0.3], gen=2)
    got = j.add_outcome("r1", 1.0)               # scalar broadcasts
    assert got is not None
    gen, scores, lab = got
    assert gen == 2 and len(scores) == 3
    assert lab.tolist() == [1.0, 1.0, 1.0]
    assert len(joined) == 1 and j.stats["joined_rows"] == 3
    # a burst split across launches concatenates chunks in order
    j.record_prediction("r2", [0.4, 0.5], gen=3)
    j.record_prediction("r2", [0.6], gen=3)
    _, scores, lab = j.add_outcome("r2", [1, 0, 1])
    assert scores.tolist() == pytest.approx([0.4, 0.5, 0.6])
    assert j.pending == 0


def test_outcome_join_watermark_late_eviction_and_malformed():
    clock = Clock()
    j = OutcomeJoiner(watermark_s=10.0, clock=clock)
    j.record_prediction("old", [0.5], gen=0)
    clock.advance(20.0)
    # never-sampled request id -> late
    assert j.add_outcome("unknown", [1.0]) is None
    # the watermark horizon passed -> late, never joined
    assert j.add_outcome("old", [1.0]) is None
    assert j.stats["late"] == 2
    # eviction happens on the feed path too
    j.record_prediction("stale", [0.5], gen=0)
    clock.advance(20.0)
    j.record_prediction("fresh", [0.5], gen=0)
    assert j.stats["evicted"] == 1 and j.pending == 1
    # label/score length mismatch -> malformed, dropped
    assert j.add_outcome("fresh", [1.0, 0.0]) is None
    assert j.stats["malformed"] == 1
    assert j.stats["joined_rows"] == 0


def test_outcome_drop_dir_ingests_wrapper_and_counts_torn(tmp_path):
    clock = Clock()
    j = OutcomeJoiner(watermark_s=100.0, clock=clock)
    j.record_prediction("a", [0.5], gen=0)
    j.record_prediction("b", [0.1, 0.9], gen=0)
    drop = str(tmp_path / "outcomes")
    os.makedirs(drop)
    with open(os.path.join(drop, "feed.jsonl"), "w") as f:
        f.write(json.dumps({"req": "a", "label": 1}) + "\n")
        f.write('{"req": "torn\n')               # torn line -> malformed
        f.write(json.dumps(
            {"outcomes": [{"req": "b", "labels": [0, 1]}]}) + "\n")
    n = j.ingest_drop_dir(drop)
    assert n == 2
    assert j.stats["joined_rows"] == 3
    assert j.stats["malformed"] == 1
    assert os.listdir(drop) == []                # consumed files removed


# -------------------------------------------------------- quality monitor
def _separable(n=512, seed=7, flip=False):
    """(scores, labels): a well-separated synthetic score stream."""
    rng = np.random.default_rng(seed)
    labels = (rng.random(n) < 0.5).astype(np.float32)
    scores = np.clip(np.where(labels > 0.5,
                              rng.normal(700.0, 120.0, n),
                              rng.normal(300.0, 120.0, n)),
                     0.0, 1000.0).astype(np.float32)
    return scores, (1.0 - labels) if flip else labels


def test_write_posttrain_snapshot_doc_and_load(tmp_path):
    scores, _ = _separable()
    path = str(tmp_path / "telemetry" / "posttrain.json")
    doc = write_posttrain_snapshot(path, scores, auc=0.93, scale=1000.0)
    assert doc["kind"] == "posttrain" and doc["rows"] == 512
    assert doc["auc"] == 0.93 and doc["score_scale"] == 1000.0
    assert sum(doc["score_hist"]) == 512
    assert load_posttrain_snapshot(str(tmp_path)) == doc


def test_quality_monitor_label_flip_degrades_live_auc(tmp_path):
    scores, labels = _separable()
    snap = write_posttrain_snapshot(
        str(tmp_path / "posttrain.json"), scores, auc=0.93, scale=1000.0)
    mon = QualityMonitor(snapshot=snap, psi_threshold=0.25,
                         auc_delta=0.05, min_joined=64)
    # matched labels first: healthy, no verdict below min_joined
    mon.observe_scores(0, scores[:32])
    mon.update(0, scores[:32], labels[:32])
    summ = mon.summary()
    assert summ["live_auc"] is None and not summ["degraded"]
    mon.observe_scores(0, scores[32:])
    mon.update(0, scores[32:], labels[32:])
    summ = mon.summary()
    assert summ["live_auc"] > 0.9 and not summ["degraded"]
    assert summ["score_psi"] is not None and summ["score_psi"] < 0.25
    assert summ["ece"] is not None
    # gen 1 serves the SAME scores but outcomes arrive flipped
    mon.observe_scores(1, scores)
    mon.update(1, scores, 1.0 - labels)
    summ = mon.summary()
    assert summ["current_gen"] == 1
    assert summ["live_auc"] < 0.1
    assert summ["degraded"] and summ["reasons"] == ["live-auc"]
    assert set(summ["generations"]) == {"0", "1"}
    c = mon.compact()
    assert c["degraded"] and c["generations"]["1"] == summ["live_auc"]
    mon.reset_windows()
    fresh = mon.summary()
    assert fresh["joined"] == 0 and not fresh["degraded"]


def test_quality_monitor_score_psi_reason_without_labels(tmp_path):
    scores, _ = _separable()
    snap = write_posttrain_snapshot(
        str(tmp_path / "posttrain.json"), scores, auc=0.93, scale=1000.0)
    mon = QualityMonitor(snapshot=snap, psi_threshold=0.25,
                         auc_delta=0.05, min_joined=64)
    # the live distribution collapses onto the top bin: PSI breaches
    # with NO joined labels at all (outputs drifted, outcomes pending)
    mon.observe_scores(0, np.full(256, 990.0, np.float32))
    summ = mon.summary()
    assert summ["live_auc"] is None and summ["joined"] == 0
    assert summ["score_psi"] >= 0.25
    assert summ["degraded"] and summ["reasons"] == ["score-psi"]
    # below the evidence floor the same shift stays verdict-free
    mon2 = QualityMonitor(snapshot=snap, psi_threshold=0.25,
                          auc_delta=0.05, min_joined=64)
    mon2.observe_scores(0, np.full(16, 990.0, np.float32))
    assert not mon2.summary()["degraded"]


def test_start_quality_monitor_is_none_when_plane_off(tmp_path):
    assert start_quality_monitor(str(tmp_path)) is None   # default rate 0
    environment.set_property("shifu.scorelog.sampleRate", "0.5")
    mon = start_quality_monitor(str(tmp_path), psi_threshold=0.25)
    assert isinstance(mon, QualityMonitor)
    assert start_quality_monitor(str(tmp_path), sample_rate=0.0) is None


def test_quality_knob_plumbing():
    environment.set_property("shifu.quality.aucDelta", "0.1")
    environment.set_property("shifu.quality.psiThreshold", "0.4")
    environment.set_property("shifu.quality.minJoined", "7")
    mon = QualityMonitor()
    assert mon.auc_delta == 0.1
    assert mon.psi_threshold == 0.4
    assert mon.min_joined == 7


# ------------------------------------------------- report (golden render)
def test_report_quality_section_byte_deterministic(tmp_path):
    tel = tmp_path / "telemetry"
    tel.mkdir()
    doc = {"kind": "quality", "joined": 1234, "baseline_auc": 0.951234,
           "auc_delta": 0.05, "psi_threshold": 0.25,
           "degraded": True, "reasons": ["score-psi"],
           "generations": {
               "0": {"live_auc": 0.91, "ece": 0.02, "psi": 0.01,
                     "joined": 1000, "scored": 2000},
               "1": {"live_auc": None, "ece": None, "psi": 0.5,
                     "joined": 34, "scored": 3000}}}
    with open(tel / "quality.json", "w") as f:
        json.dump(doc, f)
    out1, out2 = [], []
    report_mod._render_quality(str(tmp_path), out1)
    report_mod._render_quality(str(tmp_path), out2)
    assert out1 == out2                         # byte-deterministic
    assert out1 == [
        "quality: 1,234 joined rows vs posttrain baseline auc 0.9512 "
        "(delta threshold 0.0500, psi threshold 0.2500)",
        "  gen 1: auc=- ece=- psi=0.5000  34 joined / 3,000 scored",
        "  gen 0: auc=0.9100 ece=0.0200 psi=0.0100  1,000 joined / "
        "2,000 scored",
        "  << QUALITY DEGRADED (score-psi)",
        "",
    ]


def test_report_quality_absent_and_torn(tmp_path):
    out = []
    report_mod._render_quality(str(tmp_path), out)
    assert out == []                            # plane never ran: silent
    tel = tmp_path / "telemetry"
    tel.mkdir()
    with open(tel / "quality.json", "w") as f:
        f.write('{"torn')
    report_mod._render_quality(str(tmp_path), out)
    assert len(out) == 1 and "unreadable (torn write?)" in out[0]


# --------------------------------------------------------- fleet monitor
def _q_extras(degraded=False, auc=0.9, psi=0.01, joined=100, gens=None):
    return {"degraded": degraded, "live_auc": auc, "score_psi": psi,
            "joined": joined, "generations": gens or {"0": auc}}


def _write_serve_health(d, proc, quality=None, age_s=0.0):
    hd = os.path.join(d, "telemetry", "health")
    os.makedirs(hd, exist_ok=True)
    now = time.time()
    rec = {"proc": proc, "step": "SERVE", "state": "running",
           "ts": now - age_s, "last_progress_ts": now - age_s,
           "interval_s": 0.5, "rows": 10}
    if quality is not None:
        rec["quality"] = quality
    path = os.path.join(hd, f"{proc}.json")
    with open(path, "w") as f:
        json.dump(rec, f)
    os.utime(path, (now - age_s, now - age_s))


def test_fleet_quality_merges_worst_case():
    recs = [
        {"quality": _q_extras(auc=0.9, psi=0.01, joined=100,
                              gens={"0": 0.9})},
        {"quality": _q_extras(degraded=True, auc=0.7, psi=0.3, joined=50,
                              gens={"0": 0.8, "1": None})},
        {"proc": "no-quality-extras"},
    ]
    fq = monitor_mod.fleet_quality(recs)
    assert fq["procs"] == 2
    assert fq["live_auc"] == 0.7 and fq["score_psi"] == 0.3
    assert fq["joined"] == 150 and fq["degraded"] is True
    assert fq["generations"] == {0: 0.8, 1: None}
    assert monitor_mod.fleet_quality([{"proc": "p"}]) is None


def test_monitor_status_json_exits_unhealthy_on_degraded_quality(tmp_path):
    d = str(tmp_path)
    _write_serve_health(d, "serve-0", quality=_q_extras())
    doc, rc = monitor_mod.status_json(d)
    assert rc == 0 and doc["quality"]["degraded"] is False
    _write_serve_health(d, "serve-1",
                        quality=_q_extras(degraded=True, auc=0.6))
    doc, rc = monitor_mod.status_json(d)
    assert rc == monitor_mod.EXIT_UNHEALTHY
    assert doc["quality"]["degraded"] is True
    assert doc["quality"]["live_auc"] == 0.6
    text = monitor_mod.render_status(d)
    assert "<< QUALITY DEGRADED" in text
    assert "-- quality[serve-1]: auc=0.6000" in text


def test_monitor_aggregate_fleet_quality_row_and_exit(tmp_path):
    d0, d1 = str(tmp_path / "p0"), str(tmp_path / "p1")
    _write_serve_health(d0, "serve-0", quality=_q_extras(auc=0.92))
    _write_serve_health(d1, "serve-1",
                        quality=_q_extras(degraded=True, auc=0.61,
                                          psi=0.4, joined=70))
    doc, rc = monitor_mod.aggregate_json([d0, d1])
    assert rc == monitor_mod.EXIT_UNHEALTHY
    assert not doc["summary"]["quorum_lost"]     # quality, not quorum
    assert doc["quality"]["degraded"] and doc["quality"]["procs"] == 2
    text = monitor_mod.render_aggregate([d0, d1])
    assert "-- fleet quality (2 proc(s)): worst auc=0.6100" in text
    assert "worst psi=0.4000" in text
    assert "<< QUALITY DEGRADED" in text


def test_monitor_aggregate_quality_cli_subprocess(tmp_path):
    """ACCEPTANCE (satellite): `shifu-tpu monitor --once --aggregate`
    merges per-process quality extras, flags the degraded fleet and
    exits 3; a healthy fleet exits 0."""
    d0, d1 = str(tmp_path / "p0"), str(tmp_path / "p1")
    _write_serve_health(d0, "serve-0", quality=_q_extras(auc=0.92))
    _write_serve_health(d1, "serve-1",
                        quality=_q_extras(degraded=True, auc=0.61))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SHIFU_TPU_FAULTS", None)
    p = subprocess.run(
        [sys.executable, "-m", "shifu_tpu.cli", "monitor", "--once",
         "--aggregate", d0, d1],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert p.returncode == monitor_mod.EXIT_UNHEALTHY, p.stdout + p.stderr
    assert "QUALITY DEGRADED" in p.stdout
    assert "fleet quality (2 proc(s))" in p.stdout
    # the fleet recovers: flag off, exit 0
    _write_serve_health(d1, "serve-1", quality=_q_extras(auc=0.9))
    p = subprocess.run(
        [sys.executable, "-m", "shifu_tpu.cli", "monitor", "--once",
         "--aggregate", d0, d1],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "QUALITY DEGRADED" not in p.stdout


# ------------------------------------------------------- bench compare
def test_bench_compare_tracks_detect_s_and_qps_frac():
    from shifu_tpu.bench import (compare_bench, is_tracked_latency,
                                 is_tracked_throughput)
    assert is_tracked_latency("quality_label_flip_detect_s")
    assert is_tracked_throughput("serve_scorelog_qps_frac")
    old = {"metric": "x", "value": 1.0,
           "extra": {"quality_label_flip_detect_s": 2.0,
                     "serve_scorelog_qps_frac": 1.0}}
    # detect time is LOWER-is-better: 2.0s -> 5.0s regresses
    new = {"metric": "x", "value": 1.0,
           "extra": {"quality_label_flip_detect_s": 5.0,
                     "serve_scorelog_qps_frac": 0.99}}
    _, regressed = compare_bench(old, new, threshold=0.9)
    assert regressed == ["quality_label_flip_detect_s"]
    # the scorelog overhead guard: the on/off QPS ratio falling below
    # threshold x old is a tracked throughput regression
    slow = {"metric": "x", "value": 1.0,
            "extra": {"quality_label_flip_detect_s": 2.0,
                      "serve_scorelog_qps_frac": 0.5}}
    _, regressed = compare_bench(old, slow, threshold=0.9)
    assert regressed == ["serve_scorelog_qps_frac"]
    _, regressed = compare_bench(old, old, threshold=0.9)
    assert regressed == []


# ------------------------------------------------- refresh quality trigger
def _controller(tmp_path, quality=None, drift=None, **cfg):
    reg = ModelRegistry()
    reg.load("m", _nn_models(seed0=0), buckets=(1, 4))
    clock = Clock()
    kw = {"psi_threshold": 0.25, "cooldown_s": 10.0, "probation_s": 5.0}
    kw.update(cfg)
    ctrl = RefreshController(
        str(tmp_path), registry=reg, key="m", config=RefreshConfig(**kw),
        clock=clock, sleep=lambda s: clock.advance(s),
        retrain_fn=lambda c, g: {"models": _nn_models(seed0=50 + 10 * g),
                                 "warm": True},
        gate_fn=lambda c, cand: GateResult(0.5, 0.6, 0.1, 0.0, True, 100),
        drift_fn=drift or (lambda: None),
        quality_fn=quality,
        slo_alerts_fn=lambda: [])
    return ctrl, reg, clock


def test_quality_trigger_starts_retrain_cycle(tmp_path):
    qdoc = {"degraded": True, "reasons": ["live-auc"], "live_auc": 0.61,
            "baseline_auc": 0.93, "score_psi": 0.02, "joined": 128}
    ctrl, reg, clock = _controller(tmp_path, quality=lambda: qdoc)
    rec = ctrl.tick()
    assert rec["kind"] == "promote" and reg.generation("m") == 1
    trig = ctrl.journal.decisions()[0]
    assert trig["kind"] == "trigger" and trig["source"] == "quality"
    assert trig["reasons"] == ["live-auc"]
    assert trig["live_auc"] == 0.61 and trig["baseline_auc"] == 0.93
    assert trig["joined"] == 128


def test_quality_healthy_no_trigger(tmp_path):
    qdoc = {"degraded": False, "reasons": [], "live_auc": 0.93,
            "joined": 500}
    ctrl, reg, clock = _controller(tmp_path, quality=lambda: qdoc)
    ctrl.tick()
    assert ctrl.journal.decisions() == []
    assert reg.generation("m") == 0


def test_quality_artifact_trigger_and_staleness_anchor(tmp_path):
    """The artifact path (controller daemon, serve fleet elsewhere): a
    degraded quality.json triggers ONCE — after the cycle it caused, the
    same stale table (ts <= the cycle's end) is that cycle's cause, not
    a new signal; a FRESH degraded table re-triggers."""
    ctrl, reg, clock = _controller(tmp_path)
    tel = os.path.join(str(tmp_path), "telemetry")
    os.makedirs(tel, exist_ok=True)

    def write_quality(ts):
        with open(os.path.join(tel, "quality.json"), "w") as f:
            json.dump({"degraded": True, "reasons": ["live-auc"],
                       "live_auc": 0.6, "baseline_auc": 0.93,
                       "score_psi": 0.02, "joined": 128, "ts": ts}, f)

    write_quality(clock.t)
    assert ctrl.tick()["kind"] == "promote"
    clock.advance(6.0)
    assert ctrl.tick()["kind"] == "complete"
    n_decisions = len(ctrl.journal.decisions())
    # past cooldown, the STALE artifact must not re-trigger
    clock.advance(30.0)
    ctrl.tick()
    assert len(ctrl.journal.decisions()) == n_decisions
    # a fresh degraded table (a later serve beat re-emitted it) does
    write_quality(clock.t)
    rec = ctrl.tick()
    assert rec["kind"] == "promote"
    trig = ctrl.journal.decisions()[n_decisions]
    assert trig["kind"] == "trigger" and trig["source"] == "quality"


# ------------------------------------------------------------- e2e drill
def test_server_quality_plane_off_by_default():
    server = ServeServer(models=_nn_models(), key="m")
    assert server.scorelog is None and server.quality is None
    assert server.outcomes is None and server.batcher.scorelog is None
    out = server.add_outcomes({"req": "x", "labels": [1.0]})
    assert out == {"kind": "outcome", "enabled": False, "joined_rows": 0}
    assert server.quality_doc()["enabled"] is False


def test_e2e_label_flip_drives_quality_trigger_and_retrain(tmp_path):
    """ACCEPTANCE: in-process serve with sampled score logging, delayed
    outcomes with FLIPPED labels, live AUC collapsing below the posttrain
    baseline, the controller recording a `quality` trigger and entering
    a retrain cycle — then judging the new generation on fresh windows."""
    models = _nn_models(n=2, n_features=8, seed0=0)
    rng = np.random.default_rng(11)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    # training-time truth: what the incumbent actually scores on x
    probe = ServeServer(models=models, key="m")
    base_scores = probe.score(x)
    labels = (base_scores > np.median(base_scores)).astype(np.float32)
    from shifu_tpu.eval.metrics import auc_trapezoid, sweep
    c = sweep(base_scores, labels)
    base_auc = float(auc_trapezoid(c.fp / max(c.neg_total, 1e-12),
                                   c.tp / max(c.pos_total, 1e-12)))
    assert base_auc > 0.9
    write_posttrain_snapshot(
        os.path.join(str(tmp_path), "telemetry", "posttrain.json"),
        base_scores, auc=base_auc)

    server = ServeServer(models=models, key="m",
                         model_set_dir=str(tmp_path),
                         scorelog_sample_rate=1.0)
    assert server.scorelog is not None and server.quality is not None
    scores = server.score(x, req_id="burst-0")
    assert server.scorelog.stats["records"] >= 1
    np.testing.assert_allclose(scores, base_scores, rtol=1e-5)
    # the chargeback feed lands with labels OPPOSITE the score order —
    # the model went stale even though the input distribution did not
    out = server.add_outcomes({"req": "burst-0",
                               "labels": (1.0 - labels).tolist()})
    assert out["enabled"] and out["joined_rows"] == 256
    summ = server.quality.summary()
    assert summ["degraded"] and "live-auc" in summ["reasons"]
    assert summ["live_auc"] < base_auc - 0.05
    assert "score-psi" not in summ["reasons"]    # inputs look fine

    clock = Clock()
    ctrl = RefreshController(
        str(tmp_path), server=server,
        config=RefreshConfig(psi_threshold=0.25, cooldown_s=10.0,
                             probation_s=5.0),
        clock=clock, sleep=lambda s: clock.advance(s),
        retrain_fn=lambda c, g: {"models": _nn_models(seed0=50 + 10 * g),
                                 "warm": True},
        gate_fn=lambda c, cand: GateResult(0.5, 0.6, 0.1, 0.0, True, 100),
        drift_fn=lambda: None,
        slo_alerts_fn=lambda: [])
    rec = ctrl.tick()
    assert rec["kind"] == "promote"
    assert server.registry.generation("m") == 1
    trig = ctrl.journal.decisions()[0]
    assert trig["kind"] == "trigger" and trig["source"] == "quality"
    assert "live-auc" in trig["reasons"]
    clock.advance(6.0)
    assert ctrl.tick()["kind"] == "complete"
    # the just-answered degradation must not re-trigger: the promoted
    # generation is judged only on its own traffic
    fresh = server.quality.summary()
    assert fresh["joined"] == 0 and not fresh["degraded"]
    # GET /quality and the heartbeat extras read the same monitor
    qdoc = server.quality_doc()
    assert qdoc["enabled"] and qdoc["joined"] == 0
