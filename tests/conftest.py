"""Test rig: force an 8-device virtual CPU mesh before jax initializes.

The reference only unit-tests master/worker math separately (SURVEY.md §4);
here every distributed code path runs for real on a virtual multi-device mesh.
"""

import os

# Force CPU regardless of inherited JAX_PLATFORMS (e.g. a live TPU tunnel):
# unit tests must run on the virtual 8-device host mesh, deterministically.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

# Persistent XLA compilation cache: the suite is compile-bound (e2e
# pipeline tests trace dozens of executables); re-runs on the same
# machine skip those compiles entirely (measured -31% on test_wdl.py).
# Cache keys cover HLO + flags, so staleness is not a correctness risk.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/shifu_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

# If a TPU-tunnel PJRT plugin (e.g. "axon") was registered by a sitecustomize
# hook before this conftest ran, deregister it: otherwise the first jax op
# dials the tunnel and can block for minutes even under JAX_PLATFORMS=cpu.
try:
    import jax
    import jax._src.xla_bridge as _xb

    # keep "tpu" registered: pallas/mosaic registers tpu MLIR lowerings at
    # import time and needs the platform known, even under JAX_PLATFORMS=cpu
    for _name in [n for n in list(getattr(_xb, "_backend_factories", {}))
                  if n not in ("cpu", "tpu")]:
        _xb._backend_factories.pop(_name, None)
    jax.config.update("jax_platforms", "cpu")  # sitecustomize may have set "axon"
except Exception:
    pass

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def fraud_csv(tmp_path_factory):
    """Synthetic fraud-style dataset: mixed numeric/categorical, missing
    values, a weight column, '|' delimited like the reference's tutorial
    data.  ONE generator serves the suite and the tutorial
    (``examples/make_fraud_data.py``) so they can never drift — the
    golden-parity pins ride on this exact byte stream."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "make_fraud_data",
        os.path.join(os.path.dirname(__file__), "..", "examples",
                     "make_fraud_data.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    d = tmp_path_factory.mktemp("fraud")
    src = mod.make(str(d), n=4000)
    path = os.path.join(str(d), "part-000.csv")
    os.rename(src, path)
    return path


def _scaffold_model_set(base_dir: str, fraud_csv: str) -> str:
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import create_new_model

    mdir = create_new_model("fraudtest", base_dir=base_dir)
    mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
    mc.dataSet.dataPath = fraud_csv
    mc.dataSet.dataDelimiter = "|"
    mc.dataSet.targetColumnName = "tag"
    mc.dataSet.posTags = ["bad"]
    mc.dataSet.negTags = ["good"]
    mc.dataSet.weightColumnName = "weight"
    mc.dataSet.metaColumnNameFile = None
    mc.train.baggingNum = 1
    mc.train.numTrainEpochs = 30
    mc.evals[0].dataSet.dataPath = fraud_csv
    mc.evals[0].dataSet.dataDelimiter = "|"
    mc.save(os.path.join(mdir, "ModelConfig.json"))
    return mdir


@pytest.fixture
def model_set(tmp_path, fraud_csv):
    """A scaffolded model set over the synthetic fraud data, ready for init."""
    return _scaffold_model_set(str(tmp_path), fraud_csv)


@pytest.fixture(scope="session")
def _prepared_template(tmp_path_factory, fraud_csv):
    """init+stats+norm run ONCE on the default config (norm materializes
    both the norm and clean/binned planes, so any algorithm can train from
    a copy) — the suite's pipeline-mechanics tests were each re-running
    these three identical steps."""
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor

    mdir = _scaffold_model_set(
        str(tmp_path_factory.mktemp("prepared")), fraud_csv)
    assert InitProcessor(mdir).run() == 0
    assert StatsProcessor(mdir, params={}).run() == 0
    assert NormalizeProcessor(mdir, params={}).run() == 0
    return mdir


@pytest.fixture
def prepared_set(_prepared_template, tmp_path):
    """A fresh per-test copy of the prepared (post-norm) model set.  Use
    when the test does not change dataSet/stats/normalize config; set
    train config + run TrainProcessor directly."""
    import shutil
    dst = str(tmp_path / "fraudtest")
    shutil.copytree(_prepared_template, dst)
    return dst
