"""Test rig: force an 8-device virtual CPU mesh before jax initializes.

The reference only unit-tests master/worker math separately (SURVEY.md §4);
here every distributed code path runs for real on a virtual multi-device mesh.
"""

import os

# Force CPU regardless of inherited JAX_PLATFORMS (e.g. a live TPU tunnel):
# unit tests must run on the virtual 8-device host mesh, deterministically.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

# Persistent XLA compilation cache: the suite is compile-bound (e2e
# pipeline tests trace dozens of executables); re-runs on the same
# machine skip those compiles entirely (measured -31% on test_wdl.py).
# Cache keys cover HLO + flags, so staleness is not a correctness risk.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/shifu_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

# If a TPU-tunnel PJRT plugin (e.g. "axon") was registered by a sitecustomize
# hook before this conftest ran, deregister it: otherwise the first jax op
# dials the tunnel and can block for minutes even under JAX_PLATFORMS=cpu.
try:
    import jax
    import jax._src.xla_bridge as _xb

    # keep "tpu" registered: pallas/mosaic registers tpu MLIR lowerings at
    # import time and needs the platform known, even under JAX_PLATFORMS=cpu
    for _name in [n for n in list(getattr(_xb, "_backend_factories", {}))
                  if n not in ("cpu", "tpu")]:
        _xb._backend_factories.pop(_name, None)
    jax.config.update("jax_platforms", "cpu")  # sitecustomize may have set "axon"
except Exception:
    pass

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def fraud_csv(tmp_path_factory):
    """Synthetic fraud-style dataset: mixed numeric/categorical, missing
    values, a weight column, '|' delimited like the reference's tutorial data."""
    rng = np.random.default_rng(7)
    n = 4000
    amount = rng.lognormal(3.0, 1.2, n)
    velocity = rng.poisson(3, n).astype(float)
    age_days = rng.integers(0, 2000, n).astype(float)
    country = rng.choice(["US", "GB", "DE", "CN", "BR"], n, p=[.5, .15, .15, .1, .1])
    channel = rng.choice(["web", "app", "pos"], n)
    noise = rng.normal(0, 1, n)
    logit = (0.8 * np.log1p(amount) - 0.004 * age_days + 0.35 * velocity
             + (country == "BR") * 1.2 + (channel == "web") * 0.4 - 4.0)
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    tag = np.where(y == 1, "bad", "good")
    weight = np.round(rng.uniform(0.5, 2.0, n), 3)
    miss = rng.random(n) < 0.05
    amount_s = np.round(amount, 4).astype(str)
    amount_s[miss] = ""
    rows = ["txn_id|amount|velocity|age_days|country|channel|noise|weight|tag"]
    for i in range(n):
        rows.append(f"t{i}|{amount_s[i]}|{velocity[i]:.0f}|{age_days[i]:.0f}|"
                    f"{country[i]}|{channel[i]}|{noise[i]:.5f}|{weight[i]}|{tag[i]}")
    d = tmp_path_factory.mktemp("fraud")
    path = d / "part-000.csv"
    path.write_text("\n".join(rows) + "\n")
    return str(path)


def _scaffold_model_set(base_dir: str, fraud_csv: str) -> str:
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import create_new_model

    mdir = create_new_model("fraudtest", base_dir=base_dir)
    mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
    mc.dataSet.dataPath = fraud_csv
    mc.dataSet.dataDelimiter = "|"
    mc.dataSet.targetColumnName = "tag"
    mc.dataSet.posTags = ["bad"]
    mc.dataSet.negTags = ["good"]
    mc.dataSet.weightColumnName = "weight"
    mc.dataSet.metaColumnNameFile = None
    mc.train.baggingNum = 1
    mc.train.numTrainEpochs = 30
    mc.evals[0].dataSet.dataPath = fraud_csv
    mc.evals[0].dataSet.dataDelimiter = "|"
    mc.save(os.path.join(mdir, "ModelConfig.json"))
    return mdir


@pytest.fixture
def model_set(tmp_path, fraud_csv):
    """A scaffolded model set over the synthetic fraud data, ready for init."""
    return _scaffold_model_set(str(tmp_path), fraud_csv)


@pytest.fixture(scope="session")
def _prepared_template(tmp_path_factory, fraud_csv):
    """init+stats+norm run ONCE on the default config (norm materializes
    both the norm and clean/binned planes, so any algorithm can train from
    a copy) — the suite's pipeline-mechanics tests were each re-running
    these three identical steps."""
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor

    mdir = _scaffold_model_set(
        str(tmp_path_factory.mktemp("prepared")), fraud_csv)
    assert InitProcessor(mdir).run() == 0
    assert StatsProcessor(mdir, params={}).run() == 0
    assert NormalizeProcessor(mdir, params={}).run() == 0
    return mdir


@pytest.fixture
def prepared_set(_prepared_template, tmp_path):
    """A fresh per-test copy of the prepared (post-norm) model set.  Use
    when the test does not change dataSet/stats/normalize config; set
    train config + run TrainProcessor directly."""
    import shutil
    dst = str(tmp_path / "fraudtest")
    shutil.copytree(_prepared_template, dst)
    return dst
