"""Tree grid search / bagging / k-fold (VERDICT r3 item 5: reference
``gs/GridSearch.java:62`` is algorithm-agnostic and
``TrainModelProcessor.java:768-945`` runs bagging/grid jobs for trees
exactly as for NN; the rebuild previously hard-errored)."""

import json
import os

import numpy as np

from shifu_tpu.train.dt_trainer import (DTSettings, train_gbt,
                                        train_gbt_bagged, train_rf,
                                        train_rf_bagged)


def _tree_data(n=1200, c=6, n_bins=8, seed=3):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    logit = (bins[:, 0] - 3) * 0.8 + (bins[:, 1] == 2) * 1.5 - 0.5
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    return bins, y, np.ones(n, np.float32)


def test_gbt_bagged_member_matches_single_run():
    """A 1-member vmapped run must be bit-identical to train_gbt with the
    same masks (the vmap axis adds nothing)."""
    from shifu_tpu.train.sampling import validation_split

    bins, y, w = _tree_data()
    s = DTSettings(n_trees=3, depth=3, loss="log", seed=0)
    vmask = validation_split(len(y), s.valid_rate, s.seed)
    tw = (w * ~vmask)[None, :]
    vw = (w * vmask)[None, :]
    r1 = train_gbt(bins, y, w, 8, None, s)
    rb = train_gbt_bagged(bins, y, tw, vw, 8, None, [s])[0]
    assert len(r1.trees) == len(rb.trees)
    for t1, t2 in zip(r1.trees, rb.trees):
        np.testing.assert_array_equal(t1.split_feat, t2.split_feat)
        np.testing.assert_array_equal(t1.left_mask, t2.left_mask)
        np.testing.assert_allclose(t1.leaf_value, t2.leaf_value,
                                   rtol=1e-5, atol=1e-6)
    assert rb.valid_error == r1.valid_error


def test_rf_bagged_member_matches_single_run():
    bins, y, w = _tree_data()
    s = DTSettings(n_trees=4, depth=3, impurity="entropy", loss="log",
                   seed=1)
    r1 = train_rf(bins, y, w, 8, None, s)
    rb = train_rf_bagged(bins, y, w[None, :], 8, None, [s])[0]
    for t1, t2 in zip(r1.trees, rb.trees):
        np.testing.assert_array_equal(t1.split_feat, t2.split_feat)
    np.testing.assert_allclose(rb.valid_error, r1.valid_error, rtol=1e-5)


def test_gbt_stacked_lr_trials_differ():
    """Members varying only in LearningRate train in ONE executable and
    produce genuinely different forests."""
    from dataclasses import replace

    bins, y, w = _tree_data()
    s = DTSettings(n_trees=3, depth=3, loss="log", seed=0, valid_rate=0.2)
    tw = np.repeat(w[None, :] * 0.8, 2, axis=0)   # same masks both members
    vw = np.repeat(w[None, :] * 0.2, 2, axis=0)
    res = train_gbt_bagged(bins, y, tw, vw, 8, None,
                           [s, replace(s, learning_rate=0.4)])
    assert res[0].valid_error != res[1].valid_error


def test_pipeline_tree_grid_search(prepared_set):
    """List-valued tree params train, grid report lands, best trial saved
    as model0 (the round-3 ValidationError is gone)."""
    model_set = prepared_set
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.pipeline.train import TrainProcessor

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm.GBT
    mc.train.params = {"TreeNum": 6, "MaxDepth": [3, 4], "Loss": "log",
                       "LearningRate": [0.1, 0.3]}
    mc.save(mc_path)
    assert TrainProcessor(model_set, params={}).run() == 0
    assert os.path.isfile(os.path.join(model_set, "models", "model0.gbt"))
    report = json.load(open(os.path.join(model_set, "tmp",
                                         "grid_search.json")))
    assert len(report) == 4                      # 2 depths x 2 lrs
    errs = [r["validError"] for r in report]
    assert errs == sorted(errs)                  # ranked, best first
    assert report[0]["params"]["MaxDepth"] in (3, 4)
    # progress file labels every trial
    progress = open(os.path.join(model_set, "tmp",
                                 "train.progress")).read()
    assert "Trial [3]" in progress


def test_pipeline_rf_bagging(prepared_set):
    """baggingNum > 1 trains independent forests model0..modelB-1."""
    model_set = prepared_set
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.models import tree as tree_model
    from shifu_tpu.pipeline.train import TrainProcessor

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm.RF
    mc.train.baggingNum = 3
    mc.train.params = {"TreeNum": 5, "MaxDepth": 3,
                       "FeatureSubsetStrategy": "HALF"}
    mc.save(mc_path)
    assert TrainProcessor(model_set, params={}).run() == 0
    mdir = os.path.join(model_set, "models")
    paths = sorted(p for p in os.listdir(mdir) if p.startswith("model"))
    assert paths == ["model0.rf", "model1.rf", "model2.rf"]
    # bags must be genuinely different forests (different seeds/bags)
    _, trees0 = tree_model.load_model(os.path.join(mdir, "model0.rf"))
    _, trees1 = tree_model.load_model(os.path.join(mdir, "model1.rf"))
    assert any((a.split_feat != b.split_feat).any()
               for a, b in zip(trees0, trees1))


def test_pipeline_rf_kfold_cv_error(prepared_set):
    """RF k-fold: each fold's model lands and the progress trail shows
    per-fold runs; the saved valid figure is held-out-fold error (the
    oob-only error was the round-4 review finding)."""
    model_set = prepared_set
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.pipeline.train import TrainProcessor

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm.RF
    mc.train.isCrossValidation = True
    mc.train.numKFold = 3
    mc.train.params = {"TreeNum": 4, "MaxDepth": 3, "Loss": "log"}
    mc.save(mc_path)
    assert TrainProcessor(model_set, params={}).run() == 0
    mdir = os.path.join(model_set, "models")
    paths = sorted(p for p in os.listdir(mdir) if p.startswith("model"))
    assert paths == ["model0.rf", "model1.rf", "model2.rf"]


def test_pipeline_gbt_kfold(prepared_set):
    """isCrossValidation trains one forest per fold."""
    model_set = prepared_set
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.pipeline.train import TrainProcessor

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm.GBT
    mc.train.isCrossValidation = True
    mc.train.numKFold = 3
    mc.train.params = {"TreeNum": 4, "MaxDepth": 3, "Loss": "log"}
    mc.save(mc_path)
    assert TrainProcessor(model_set, params={}).run() == 0
    mdir = os.path.join(model_set, "models")
    paths = sorted(p for p in os.listdir(mdir) if p.startswith("model"))
    assert paths == ["model0.gbt", "model1.gbt", "model2.gbt"]


def test_pipeline_tree_grid_streamed(prepared_set):
    """Grid trials train out-of-core too (reference: any algorithm x any
    data size; previously streamed mode fell back to in-RAM with a
    warning).  Trials run as sequential streamed jobs over tiny windows;
    the grid report still ranks and model0 is the best trial."""
    model_set = prepared_set
    from shifu_tpu.config import ModelConfig, environment
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.pipeline.train import TrainProcessor

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm.GBT
    mc.train.params = {"TreeNum": 3, "MaxDepth": 3, "Loss": "log",
                       "LearningRate": [0.1, 0.3]}
    mc.save(mc_path)
    environment.set_property("shifu.train.streaming", "on")
    environment.set_property("shifu.train.windowRows", "512")
    try:
        assert TrainProcessor(model_set, params={}).run() == 0
    finally:
        environment.set_property("shifu.train.streaming", "auto")
        environment.set_property("shifu.train.windowRows", "")
    assert os.path.isfile(os.path.join(model_set, "models", "model0.gbt"))
    report = json.load(open(os.path.join(model_set, "tmp",
                                         "grid_search.json")))
    assert len(report) == 2
    errs = [r["validError"] for r in report]
    assert errs == sorted(errs) and all(np.isfinite(e) for e in errs)


def test_pipeline_rf_bagging_streamed(prepared_set):
    """Streamed bagging: B sequential streamed RF jobs, genuinely
    different forests, one model file per bag."""
    model_set = prepared_set
    from shifu_tpu.config import ModelConfig, environment
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.models import tree as tree_model
    from shifu_tpu.pipeline.train import TrainProcessor

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm.RF
    mc.train.baggingNum = 2
    mc.train.params = {"TreeNum": 3, "MaxDepth": 3,
                       "FeatureSubsetStrategy": "HALF"}
    mc.save(mc_path)
    environment.set_property("shifu.train.streaming", "on")
    environment.set_property("shifu.train.windowRows", "512")
    try:
        assert TrainProcessor(model_set, params={}).run() == 0
    finally:
        environment.set_property("shifu.train.streaming", "auto")
        environment.set_property("shifu.train.windowRows", "")
    mdir = os.path.join(model_set, "models")
    paths = sorted(p for p in os.listdir(mdir) if p.startswith("model"))
    assert paths == ["model0.rf", "model1.rf"]
    _, trees0 = tree_model.load_model(os.path.join(mdir, "model0.rf"))
    _, trees1 = tree_model.load_model(os.path.join(mdir, "model1.rf"))
    assert any((a.split_feat != b.split_feat).any()
               for a, b in zip(trees0, trees1))


def test_pipeline_gbt_bagging_streamed_distinct(prepared_set):
    """Streamed GBT bags draw per-member splits (in-RAM ``distinct``
    semantics) — default-config bags must NOT be identical forests."""
    model_set = prepared_set
    from shifu_tpu.config import ModelConfig, environment
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.models import tree as tree_model
    from shifu_tpu.pipeline.train import TrainProcessor

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm.GBT
    mc.train.baggingNum = 2
    mc.train.params = {"TreeNum": 3, "MaxDepth": 3, "Loss": "log"}
    mc.save(mc_path)
    environment.set_property("shifu.train.streaming", "on")
    environment.set_property("shifu.train.windowRows", "512")
    try:
        assert TrainProcessor(model_set, params={}).run() == 0
    finally:
        environment.set_property("shifu.train.streaming", "auto")
        environment.set_property("shifu.train.windowRows", "")
    mdir = os.path.join(model_set, "models")
    _, trees0 = tree_model.load_model(os.path.join(mdir, "model0.gbt"))
    _, trees1 = tree_model.load_model(os.path.join(mdir, "model1.gbt"))
    assert any(not np.array_equal(a.leaf_value, b.leaf_value)
               for a, b in zip(trees0, trees1))
