"""Remote-source data plane (VERDICT r3 item 3): fsspec-backed reads for
object-storage schemes — the reference's ``RawSourceData.SourceType`` HDFS
duality (``fs/ShifuFileUtils.java``) becomes gs://s3://memory:// streaming;
only Hadoop filesystems remain a coded error."""

import os

import numpy as np
import pytest


def _write_memory_dataset(n=2500, seed=7):
    import fsspec
    fs = fsspec.filesystem("memory")
    rng = np.random.default_rng(seed)
    amount = rng.lognormal(3.0, 1.2, n)
    velocity = rng.poisson(3, n).astype(float)
    country = rng.choice(["US", "GB", "BR"], n, p=[.6, .2, .2])
    logit = 0.8 * np.log1p(amount) + 0.35 * velocity + \
        (country == "BR") * 1.2 - 4.0
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    tag = np.where(y == 1, "bad", "good")
    rows = ["txn_id|amount|velocity|country|tag"]
    for i in range(n):
        rows.append(f"t{i}|{amount[i]:.4f}|{velocity[i]:.0f}|"
                    f"{country[i]}|{tag[i]}")
    half = len(rows) // 2
    with fs.open("/fraud/part-000.csv", "w") as f:
        f.write("\n".join(rows[:half]) + "\n")
    with fs.open("/fraud/part-001.csv", "w") as f:
        f.write(rows[0] + "\n" + "\n".join(rows[half:]) + "\n")
    with fs.open("/fraud/_SUCCESS", "w") as f:
        f.write("")
    return "memory://fraud"


def test_resolve_remote_dir_lists_parts_skips_markers():
    from shifu_tpu.data.reader import resolve_data_files

    path = _write_memory_dataset()
    files = resolve_data_files(path)
    assert [os.path.basename(f) for f in files] == ["part-000.csv",
                                                    "part-001.csv"]
    assert all(f.startswith("memory://") for f in files)


def test_hdfs_still_coded_error():
    from shifu_tpu.config.errors import ShifuError
    from shifu_tpu.data.reader import resolve_data_files

    with pytest.raises(ShifuError, match="hdfs"):
        resolve_data_files("hdfs://nn:8020/data/part-*")


def test_unknown_scheme_coded_error():
    """Typo'd/unknown schemes must stay a coded ShifuError, not a raw
    fsspec ValueError (round-4 review finding)."""
    from shifu_tpu.config.errors import ShifuError
    from shifu_tpu.data.reader import resolve_data_files

    with pytest.raises(ShifuError, match="known scheme"):
        resolve_data_files("s3n://bucket/part-*")


def test_file_scheme_header_resolves(tmp_path):
    from shifu_tpu.data.reader import read_header

    hp = tmp_path / "header"
    hp.write_text("a|b|c\n")
    assert read_header(f"file://{hp}", "|") == ["a", "b", "c"]


def test_datasource_streams_remote_chunks():
    from shifu_tpu.data.reader import DataSource

    path = _write_memory_dataset()
    src = DataSource(path, "|")
    assert src.header[:2] == ["txn_id", "amount"]
    total = sum(len(c) for c in src.iter_chunks(chunk_rows=512))
    assert total == 2500


def test_full_pipeline_over_memory_source(tmp_path):
    """init -> stats -> norm -> train -> eval with dataPath in object
    storage (memory://): the whole pipeline streams remotely, no staging."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import InitProcessor, create_new_model
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    path = _write_memory_dataset()
    meta = tmp_path / "meta.names"
    meta.write_text("txn_id\n")
    mdir = create_new_model("remotetest", base_dir=str(tmp_path))
    mcp = os.path.join(mdir, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.dataSet.dataPath = path
    mc.dataSet.dataDelimiter = "|"
    mc.dataSet.targetColumnName = "tag"
    mc.dataSet.posTags = ["bad"]
    mc.dataSet.negTags = ["good"]
    mc.dataSet.metaColumnNameFile = str(meta)
    mc.train.baggingNum = 1
    mc.train.numTrainEpochs = 60
    mc.evals[0].dataSet.dataPath = path
    mc.evals[0].dataSet.dataDelimiter = "|"
    mc.save(mcp)
    assert InitProcessor(mdir).run() == 0
    assert StatsProcessor(mdir, params={}).run() == 0
    assert NormalizeProcessor(mdir, params={}).run() == 0
    assert TrainProcessor(mdir, params={}).run() == 0
    assert EvalProcessor(mdir, params={"run_eval": "Eval1"}).run() == 0
    import json
    perf = json.load(open(os.path.join(mdir, "evals", "Eval1",
                                       "EvalPerformance.json")))
    # plumbing test: the signal in this 3-feature synthetic caps AUC ~0.78
    assert perf["areaUnderRoc"] > 0.7


def test_webhdfs_scheme_not_gated():
    """webhdfs:// (fsspec's pure-HTTP Hadoop client, no libhdfs needed) is
    a real route to cluster data — it must reach the fsspec backend, not
    the coded hdfs gate; the gate's message points at it."""
    from shifu_tpu.config.errors import ShifuError
    from shifu_tpu.data.reader import _GATED_SCHEMES, resolve_data_files
    assert not any(s.startswith("webhdfs") for s in _GATED_SCHEMES)
    with pytest.raises(ShifuError, match="webhdfs://namenode"):
        resolve_data_files("hdfs://nn:8020/data/part-*")
    # the webhdfs path dies on CONNECTION (no cluster here), never on the
    # gate — whatever fsspec raises, it is not the coded gate message
    try:
        resolve_data_files("webhdfs://127.0.0.1:1/404/part-*")
    except ShifuError as e:                       # pragma: no cover
        assert "no native" not in str(e)
    except Exception:
        pass                                      # connection error = ok
