"""Tree kernels + GBT/RF trainer tests (reference ``core/dtrain/DTTest.java``
pattern, on the virtual 8-device mesh)."""

import os

import numpy as np
import pytest

from shifu_tpu.ops.tree import (TreeArrays, best_splits, build_histograms,
                                grow_tree, n_tree_nodes, predict_tree)
from shifu_tpu.train.dt_trainer import (DTSettings, subset_count, train_gbt,
                                        train_rf)
from shifu_tpu.models import tree as tree_model

import jax.numpy as jnp


def test_histograms_scatter_add():
    bins = np.array([[0, 1], [1, 1], [2, 0], [0, 0]], np.int32)
    node = np.array([0, 0, 1, -1], np.int32)          # row 3 inactive
    stats = np.stack([np.ones(4), np.array([1., 0., 1., 5.]),
                      np.zeros(4)], axis=1).astype(np.float32)
    h = np.asarray(build_histograms(jnp.asarray(bins), jnp.asarray(node),
                                    jnp.asarray(stats), 2, 3))
    assert h.shape == (2, 2, 3, 3)
    # node 0, feature 0: rows 0 (bin0) and 1 (bin1)
    assert h[0, 0, 0, 0] == 1 and h[0, 0, 1, 0] == 1
    assert h[0, 0, 0, 1] == 1.0 and h[0, 0, 1, 1] == 0.0
    # node 1, feature 0: row 2 at bin 2
    assert h[1, 0, 2, 0] == 1 and h[1, 0, 2, 1] == 1.0
    # inactive row contributed nowhere
    assert h[..., 0].sum() == 3 * 2  # 3 active rows x 2 features


def test_perfect_numeric_split():
    """y determined by bin <= 1 on feature 0 — tree must find it."""
    rng = np.random.default_rng(0)
    bins = rng.integers(0, 4, size=(400, 3)).astype(np.int32)
    y = (bins[:, 0] <= 1).astype(np.float64)
    w = np.ones(400)
    t = grow_tree(bins, y, w, 4, depth=2, impurity="variance")
    assert t.split_feat[0] == 0
    pred = np.asarray(predict_tree(jnp.asarray(t.split_feat),
                                   jnp.asarray(t.left_mask),
                                   jnp.asarray(t.leaf_value),
                                   jnp.asarray(bins), 2))
    np.testing.assert_allclose(pred, y, atol=1e-6)


def test_categorical_split_nonconsecutive():
    """Categorical feature where categories {0, 2} are positive — a
    bin-subset split numeric prefixes can't express."""
    rng = np.random.default_rng(1)
    bins = rng.integers(0, 4, size=(600, 2)).astype(np.int32)
    y = np.isin(bins[:, 0], [0, 2]).astype(np.float64)
    w = np.ones(600)
    cat = np.array([True, False])
    t = grow_tree(bins, y, w, 4, depth=1, impurity="variance", cat_mask=cat)
    assert t.split_feat[0] == 0
    pred = np.asarray(predict_tree(jnp.asarray(t.split_feat),
                                   jnp.asarray(t.left_mask),
                                   jnp.asarray(t.leaf_value),
                                   jnp.asarray(bins), 1))
    np.testing.assert_allclose(pred, y, atol=1e-6)
    # left set is exactly {0, 2}
    assert set(np.flatnonzero(t.left_mask[0])) == {0, 2}


@pytest.mark.parametrize("impurity", ["variance", "entropy", "gini",
                                      "friedmanmse"])
def test_impurities_find_signal(impurity):
    rng = np.random.default_rng(2)
    bins = rng.integers(0, 8, size=(1000, 4)).astype(np.int32)
    y = (bins[:, 2] >= 4).astype(np.float64)
    t = grow_tree(bins, y, np.ones(1000), 8, depth=1, impurity=impurity)
    assert t.split_feat[0] == 2


def test_min_instances_blocks_tiny_split():
    bins = np.array([[0], [1], [1], [1]], np.int32)
    y = np.array([1.0, 0.0, 0.0, 0.0])
    t = grow_tree(bins, y, np.ones(4), 2, depth=1, min_instances=2.0)
    assert t.split_feat[0] == -1          # the 1-row split is disallowed


def test_gbt_reduces_error_and_beats_single_tree():
    rng = np.random.default_rng(3)
    n = 3000
    bins = rng.integers(0, 16, size=(n, 6)).astype(np.int32)
    logit = (bins[:, 0] / 8.0 - 1) + ((bins[:, 1] > 8) & (bins[:, 2] < 4)) * 1.5
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    s = DTSettings(n_trees=20, depth=4, loss="log", learning_rate=0.3,
                   valid_rate=0.2, seed=0)
    res = train_gbt(bins, y, np.ones(n), 16, np.zeros(6, bool), s)
    assert res.trees_built == 20
    errs = [h[1] for h in res.history]
    assert errs[-1] < errs[0] * 0.98
    assert res.feature_importance[:3].sum() > res.feature_importance[3:].sum()


def test_rf_oob_error_reasonable():
    rng = np.random.default_rng(4)
    n = 2000
    bins = rng.integers(0, 8, size=(n, 5)).astype(np.int32)
    y = ((bins[:, 0] >= 4) ^ (bins[:, 1] < 2)).astype(np.float64)
    s = DTSettings(n_trees=10, depth=5, impurity="gini",
                   feature_subset="ALL", seed=0)
    res = train_rf(bins, y, np.ones(n), 8, np.zeros(5, bool), s)
    assert res.trees_built == 10
    assert res.valid_error < 0.2          # oob mse well below chance 0.25


def test_feature_subset_counts():
    assert subset_count("ALL", 100) == 100
    assert subset_count("HALF", 100) == 50
    assert subset_count("SQRT", 100) == 10
    assert subset_count("LOG2", 100) == 6
    assert subset_count("ONETHIRD", 100) == 33


def test_tree_model_save_load_roundtrip(tmp_path):
    rng = np.random.default_rng(5)
    bins = rng.integers(0, 8, size=(500, 4)).astype(np.int32)
    y = (bins[:, 1] >= 4).astype(np.float64)
    s = DTSettings(n_trees=5, depth=3, loss="squared", learning_rate=0.5)
    res = train_gbt(bins, y, np.ones(500), 8, np.zeros(4, bool), s)
    spec = tree_model.TreeModelSpec(n_trees=len(res.trees), depth=3, n_bins=8,
                                    **res.spec_kwargs)
    path = os.path.join(tmp_path, "model0.gbt")
    tree_model.save_model(path, spec, res.trees)
    m = tree_model.IndependentTreeModel.load(path)
    pred = m.compute(bins)[:, 0]
    assert pred.shape == (500,)
    # roundtripped model still separates the classes
    assert pred[y == 1].mean() > pred[y == 0].mean() + 0.3


def test_gbt_pipeline_end_to_end(prepared_set):
    model_set = prepared_set          # init/stats/norm ran in the template
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    import json

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm.GBT
    mc.train.params = {"TreeNum": 15, "MaxDepth": 4, "Loss": "log",
                       "LearningRate": 0.3}
    mc.save(mc_path)
    assert TrainProcessor(model_set, params={}).run() == 0
    assert os.path.isfile(os.path.join(model_set, "models", "model0.gbt"))
    assert EvalProcessor(model_set, params={"run_eval": ""}).run() == 0
    perf = json.load(open(os.path.join(model_set, "evals", "Eval1",
                                       "EvalPerformance.json")))
    assert perf["areaUnderRoc"] > 0.75


def test_rf_pipeline_end_to_end(prepared_set):
    model_set = prepared_set          # init/stats/norm ran in the template
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.pipeline.train import TrainProcessor

    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = Algorithm.RF
    mc.train.params = {"TreeNum": 8, "MaxDepth": 5,
                       "FeatureSubsetStrategy": "TWOTHIRDS"}
    mc.save(mc_path)
    assert TrainProcessor(model_set, params={}).run() == 0
    assert os.path.isfile(os.path.join(model_set, "models", "model0.rf"))


def test_leafwise_node_budget():
    """MaxLeaves (reference DTMaster.java:543-560): the node budget caps
    growth best-first by gain — node count never exceeds the budget and
    the strongest split survives."""
    import jax.numpy as jnp
    from shifu_tpu.ops.tree import grow_tree_jit, n_tree_nodes

    rng = np.random.default_rng(3)
    n, c, b, depth = 4000, 6, 8, 4
    bins = rng.integers(0, b, (n, c)).astype(np.int32)
    # col 0 carries a strong signal, others weak
    y = (bins[:, 0] >= 4).astype(np.float32)
    y = np.where(rng.random(n) < 0.05, 1 - y, y)
    w = np.ones(n, np.float32)
    stats = jnp.stack([jnp.asarray(w), jnp.asarray(w * y),
                       jnp.asarray(w * y * y)], axis=1)
    cat = jnp.zeros(c, bool)
    fa = jnp.ones(c, bool)

    def node_count(max_leaves):
        sf, _, _, _, _ = grow_tree_jit(
            jnp.asarray(bins), stats, cat, fa, b, depth, "variance",
            1.0, 0.0, 0, False, max_leaves)
        return int((np.asarray(sf) >= 0).sum()) * 2 + 1

    full = node_count(0)                       # level-wise, no cap
    assert full > 7
    capped = node_count(7)                     # budget of 7 nodes
    assert capped <= 7
    # the root split (strongest gain) must survive the cap
    sf, _, _, _, _ = grow_tree_jit(
        jnp.asarray(bins), stats, cat, fa, b, depth, "variance",
        1.0, 0.0, 0, False, 3)
    sf = np.asarray(sf)
    assert sf[0] == 0                          # root split on the signal col
    assert (sf >= 0).sum() == 1                # budget 3 = exactly one split


def test_gbt_scan_and_loop_paths_identical():
    """The scan fast-path (no early stop) and the per-tree loop (early
    stop enabled, window large enough never to fire) must build identical
    forests — two lowerings of the same math."""
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt

    rng = np.random.default_rng(11)
    n, c, b = 3000, 8, 16
    bins = rng.integers(0, b, (n, c)).astype(np.int32)
    y = (rng.random(n) < 0.4).astype(np.float32)
    w = np.ones(n, np.float32)
    cat = np.zeros(c, bool)
    base = dict(n_trees=5, depth=3, loss="log", learning_rate=0.1,
                seed=7, feature_subset="HALF")
    scan = train_gbt(bins, y, w, b, cat, DTSettings(**base))
    loop = train_gbt(bins, y, w, b, cat,
                     DTSettings(**base, early_stop=True))
    assert scan.trees_built == loop.trees_built == 5
    for ts, tl in zip(scan.trees, loop.trees):
        np.testing.assert_array_equal(ts.split_feat, tl.split_feat)
        np.testing.assert_array_equal(ts.left_mask, tl.left_mask)
        np.testing.assert_allclose(ts.leaf_value, tl.leaf_value, atol=1e-6)
    for (a, b_), (c_, d) in zip(scan.history, loop.history):
        assert abs(a - c_) < 1e-6 and abs(b_ - d) < 1e-6


def test_best_splits_has_cat_fast_path_equivalent():
    """has_cat=False compiles out the order/gather machinery; it must give
    bit-identical splits to the general path on all-numeric histograms."""
    import jax.numpy as jnp
    from shifu_tpu.ops.tree import best_splits, build_histograms

    rng = np.random.default_rng(5)
    n, c, b, k = 2000, 6, 8, 4
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    node = jnp.asarray(rng.integers(0, k, n), jnp.int32)
    t = jnp.asarray(rng.normal(size=n), jnp.float32)
    w = jnp.ones(n, jnp.float32)
    stats = jnp.stack([w, w * t, w * t * t], axis=1)
    hist = build_histograms(bins, node, stats, k, b)
    cat = jnp.zeros(c, bool)
    fa = jnp.ones(c, bool)
    for imp in ("variance", "friedmanmse", "entropy"):
        slow = best_splits(hist, cat, fa, imp, 1.0, 0.0, 0, True)
        fast = best_splits(hist, cat, fa, imp, 1.0, 0.0, 0, False)
        for a_, b_ in zip(slow, fast):
            np.testing.assert_allclose(np.asarray(a_), np.asarray(b_),
                                       atol=1e-6)
    # multiclass fast branch (cls_o = cls) must agree too
    kcls = 3
    yi = rng.integers(0, kcls, n)
    mc_stats = jnp.asarray(np.eye(kcls, dtype=np.float32)[yi])
    mhist = build_histograms(bins, node, mc_stats, k, b)
    for imp in ("entropy", "gini"):
        slow = best_splits(hist=mhist, cat_mask=cat, feat_active=fa,
                           impurity=imp, n_classes=kcls, has_cat=True)
        fast = best_splits(hist=mhist, cat_mask=cat, feat_active=fa,
                           impurity=imp, n_classes=kcls, has_cat=False)
        for a_, b_ in zip(slow, fast):
            np.testing.assert_allclose(np.asarray(a_), np.asarray(b_),
                                       atol=1e-6)


@pytest.mark.parametrize("depth", [4, 10])
def test_onehot_traversal_matches_gather(monkeypatch, depth):
    """The TPU one-hot (matmul-select) traversal must be bit-identical to
    the gather form: every select sums exactly one term at HIGHEST
    precision (``ops/tree.py:_onehot_traversal``).  depth 4 covers the
    fully one-hot path incl. the leaf-value select; depth 10 covers
    level-local one-hots with the >ONEHOT_MAX_NODES leaf fallback."""
    from shifu_tpu.ops import tree as ot

    rng = np.random.default_rng(7)
    n, c, b = 3000, 9, 8
    total = n_tree_nodes(depth)
    bins = jnp.asarray(rng.integers(0, b, (n, c)), jnp.int32)
    sf = rng.integers(0, c, total).astype(np.int32)
    sf[total // 2:] = -1                       # bottom half leaves
    sf[3] = -1                                 # an interior leaf too
    lm = rng.random((total, b)) < 0.5
    lv = rng.normal(size=total).astype(np.float32)
    lv_mc = rng.normal(size=(total, 3)).astype(np.float32)  # multiclass

    outs = {}
    for mode in ("0", "1"):
        monkeypatch.setenv("SHIFU_TREE_ONEHOT", mode)
        ot._onehot_traversal.cache_clear()   # resolved once per process
        # the widest level always keeps the fast path; at depth 10 the
        # total node count (2047) exceeds the cap -> leaf select falls back
        assert ot._use_onehot(1 << (depth - 1)) == (mode == "1")
        assert ot._use_onehot(total) == (mode == "1" and depth == 4)
        # jit caches would otherwise reuse the other mode's lowering
        pred = ot.predict_tree.__wrapped__(jnp.asarray(sf), jnp.asarray(lm),
                                           jnp.asarray(lv), bins, depth)
        pred_mc = ot.predict_tree.__wrapped__(
            jnp.asarray(sf), jnp.asarray(lm), jnp.asarray(lv_mc), bins,
            depth)
        nodes = ot.traverse_nodes(jnp.asarray(sf), jnp.asarray(lm), bins,
                                  depth)
        nidx = ot.node_index_at_level.__wrapped__(
            jnp.asarray(sf), jnp.asarray(lm), bins, depth)
        outs[mode] = [np.asarray(x) for x in (pred, pred_mc, nodes, nidx)]
    for a, o in zip(outs["0"], outs["1"]):
        np.testing.assert_array_equal(a, o)
    # leave the process-wide lowering choice as the default for the rest
    # of the suite (the cache outlives monkeypatch's env restore)
    monkeypatch.setenv("SHIFU_TREE_ONEHOT", "auto")
    ot._onehot_traversal.cache_clear()
