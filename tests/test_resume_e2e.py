"""Kill-and-resume end-to-end suite (subprocess, SIGKILL-equivalent).

Drives the real CLI in subprocesses, hard-kills it at injected phase
boundaries (``SHIFU_TPU_FAULTS=...:kill`` → ``os._exit(137)``, no
cleanup — what a preempted VM leaves behind), resumes, and asserts the
final model AND eval artifacts are bit-identical to an uninterrupted
run.  Marked ``slow`` (each leg pays a fresh interpreter + XLA compile);
the in-process fast subset lives in ``test_faults.py``.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

pytestmark = [pytest.mark.slow, pytest.mark.faults]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(mdir, args, faults_spec="", expect=0):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_ENABLE_X64"] = "true"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/shifu_tpu_jax_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if faults_spec:
        env["SHIFU_TPU_FAULTS"] = faults_spec
    else:
        env.pop("SHIFU_TPU_FAULTS", None)
    p = subprocess.run(
        [sys.executable, "-m", "shifu_tpu.cli", "--dir", mdir] + args,
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert p.returncode == expect, \
        f"rc={p.returncode} (wanted {expect})\n{p.stdout}\n{p.stderr}"
    return p


def _set_train(mdir, alg, params, epochs=None):
    from shifu_tpu.config import ModelConfig
    mc_path = os.path.join(mdir, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    mc.train.algorithm = alg
    mc.train.params = params
    if epochs is not None:
        mc.train.numTrainEpochs = epochs
    mc.save(mc_path)


def _eval_performance(mdir):
    p = os.path.join(mdir, "evals", "Eval1", "EvalPerformance.json")
    with open(p) as f:
        return f.read()


def test_gbt_sigkill_resume_bit_identical_artifacts(prepared_set):
    from shifu_tpu.models import tree as tree_model
    control = prepared_set + "_ctl"
    shutil.copytree(prepared_set, control)
    params = {"TreeNum": 12, "MaxDepth": 3, "CheckpointInterval": 4}
    _set_train(prepared_set, "GBT", params)
    _set_train(control, "GBT", params)

    _run_cli(control, ["train"])
    # hard death right after tree 9's progress line (post tree-batch 8's
    # checkpoint commit)
    _run_cli(prepared_set, ["train"], faults_spec="train:tree=9:kill",
             expect=137)
    assert os.path.isfile(os.path.join(
        prepared_set, "tmp", "checkpoints", "forest_ckpt.npz"))
    # plain re-run: the torn journal auto-resumes from the checkpoint
    _run_cli(prepared_set, ["train"])

    _, tc = tree_model.load_model(os.path.join(control, "models",
                                               "model0.gbt"))
    _, tr = tree_model.load_model(os.path.join(prepared_set, "models",
                                               "model0.gbt"))
    assert len(tc) == len(tr) == 12
    for a, b in zip(tc, tr):
        assert np.asarray(a.split_feat).tobytes() == \
            np.asarray(b.split_feat).tobytes()
        assert np.asarray(a.left_mask).tobytes() == \
            np.asarray(b.left_mask).tobytes()
        assert np.asarray(a.leaf_value).tobytes() == \
            np.asarray(b.leaf_value).tobytes()

    _run_cli(control, ["eval", "-run"])
    _run_cli(prepared_set, ["eval", "-run"])
    assert _eval_performance(control) == _eval_performance(prepared_set)


def test_nn_sigkill_resume_bit_identical_artifacts(prepared_set):
    from shifu_tpu.models import nn as nn_model
    control = prepared_set + "_ctl"
    shutil.copytree(prepared_set, control)
    params = {"NumHiddenNodes": [8], "CheckpointInterval": 3,
              "Propagation": "R"}
    _set_train(prepared_set, "NN", params, epochs=9)
    _set_train(control, "NN", params, epochs=9)

    _run_cli(control, ["train"])
    _run_cli(prepared_set, ["train"], faults_spec="train:epoch=6:kill",
             expect=137)
    _run_cli(prepared_set, ["train"])

    _, pc = nn_model.load_model(os.path.join(control, "models",
                                             "model0.nn"))
    _, pr = nn_model.load_model(os.path.join(prepared_set, "models",
                                             "model0.nn"))
    assert len(pc) == len(pr)
    for lc, lr in zip(pc, pr):
        for k in lc:
            assert np.asarray(lc[k]).tobytes() == \
                np.asarray(lr[k]).tobytes(), k

    _run_cli(control, ["eval", "-run"])
    _run_cli(prepared_set, ["eval", "-run"])
    assert _eval_performance(control) == _eval_performance(prepared_set)


def test_norm_sigkill_resume_completes_cleanly(model_set):
    """Kill `norm` mid-shard-commit via the harness, re-run, and verify
    the journal reaches complete with a consistent schema."""
    _run_cli(model_set, ["init"])
    _run_cli(model_set, ["stats"])
    # kill on shard 0's commit: the whole step is uncommitted
    _run_cli(model_set, ["norm"], faults_spec="norm:shard=0:kill",
             expect=137)
    jpath = os.path.join(model_set, "tmp", "journal", "NORMALIZE.json")
    with open(jpath) as f:
        assert json.load(f)["status"] == "running"
    _run_cli(model_set, ["norm"])
    with open(jpath) as f:
        doc = json.load(f)
    assert doc["status"] == "complete"
    ndir = os.path.join(model_set, "tmp", "NormalizedData")
    with open(os.path.join(ndir, "schema.json")) as f:
        schema = json.load(f)
    parts = [x for x in os.listdir(ndir) if x.endswith(".npz")]
    assert len(parts) == schema["numShards"]
    assert sum(schema["shardRows"]) == schema["numRows"] > 0
