"""Meta-driven config validation — reference ``MetaFactory.java`` +
``store/ModelConfigMeta.json``: declarative type/range/enum/applicability
schema over ModelConfig and train#params; unknown keys are hard errors."""

import pytest

from shifu_tpu.config.meta import (validate_config_fields,
                                   validate_train_conf,
                                   validate_train_params)
from shifu_tpu.config.model_config import Algorithm, ModelConfig, ModelTrainConf


def _problems(params, alg=Algorithm.NN):
    return validate_train_params(params, alg)


def test_unknown_key_is_hard_error_with_suggestion():
    out = _problems({"LearningRat": 0.1})
    assert len(out) == 1
    assert "unknown" in out[0] and "LearningRate" in out[0]


def test_unknown_key_without_close_match():
    out = _problems({"Zorp": 1})
    assert "unknown train#params key 'Zorp'" in out[0]


def test_known_keys_pass():
    assert _problems({"LearningRate": 0.1, "Propagation": "ADAM",
                      "NumHiddenNodes": [30, 10],
                      "ActivationFunc": ["tanh", "relu"],
                      "DropoutRate": 0.2, "MiniBatchs": 128,
                      "Loss": "log", "Seed": 7}) == []


def test_range_violations():
    assert "must be >" in _problems({"LearningRate": 0.0})[0]
    assert "must be <" in _problems({"DropoutRate": 1.0})[0]
    assert _problems({"MaxDepth": 25}, Algorithm.GBT)[0].startswith(
        "train#params.MaxDepth must be <= 20")
    assert _problems({"TreeNum": 0}, Algorithm.RF)


def test_type_violations():
    assert "must be a int" in _problems({"MiniBatchs": 12.5})[0]
    assert "must be a list" in _problems({"NumHiddenNodes": 30})[0]
    assert "elements must be ints" in _problems({"NumHiddenNodes": ["x"]})[0]


def test_enum_violations():
    assert "one of" in _problems({"Propagation": "WARP"})[0]
    assert "one of" in _problems({"Loss": "huber"})[0]
    assert not _problems({"Loss": "hinge"})      # the SVM loss is valid
    assert "not one of" in _problems({"ActivationFunc": ["tanh", "zap"]})[0]
    assert "one of" in _problems({"Impurity": "mse"}, Algorithm.RF)[0]


def test_enum_checks_are_case_insensitive():
    assert _problems({"Propagation": "adam"}) == []
    assert _problems({"Impurity": "ENTROPY"}, Algorithm.RF) == []


def test_per_algorithm_applicability():
    out = _problems({"TreeNum": 100})            # NN with a tree key
    assert "does not apply to algorithm NN" in out[0]
    out = _problems({"DropoutRate": 0.1}, Algorithm.GBT)
    assert "does not apply to algorithm GBT" in out[0]
    assert _problems({"WideEnable": True}, Algorithm.WDL) == []
    assert "does not apply" in _problems({"WideEnable": True},
                                         Algorithm.NN)[0]


def test_grid_trials_validated_individually():
    tc = ModelTrainConf(algorithm=Algorithm.NN,
                        params={"LearningRate": [0.1, 0.2, -1.0],
                                "Propagation": ["ADAM", "WARP"]})
    out = validate_train_conf(tc)
    joined = "\n".join(out)
    assert "LearningRate" in joined        # the -1.0 candidate
    assert "WARP" in joined                # the bad optimizer candidate


def test_grid_list_keys_not_mistaken_for_axes():
    tc = ModelTrainConf(algorithm=Algorithm.NN,
                        params={"NumHiddenNodes": [30, 10]})
    assert validate_train_conf(tc) == []


def test_numeric_strings_accepted():
    assert _problems({"LearningRate": "0.1"}) == []
    assert _problems({"MiniBatchs": "128"}) == []


def test_config_field_rules():
    mc = ModelConfig()
    mc.train.baggingNum = 0
    mc.train.validSetRate = 1.0
    mc.stats.maxNumBin = 1
    out = validate_config_fields(mc)
    joined = "\n".join(out)
    assert "train.baggingNum" in joined
    assert "train.validSetRate" in joined
    assert "stats.maxNumBin" in joined


def test_probe_rejects_typo_end_to_end(tmp_path):
    from shifu_tpu.config.validator import ModelStep, ValidationError, probe
    from shifu_tpu.pipeline.create import create_new_model
    import os
    mdir = create_new_model("metatest", base_dir=str(tmp_path))
    mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
    mc.dataSet.dataPath = "/tmp/d.csv"
    mc.dataSet.targetColumnName = "tag"
    mc.dataSet.posTags, mc.dataSet.negTags = ["1"], ["0"]
    mc.train.params = {"LearningRat": 0.1}
    with pytest.raises(ValidationError, match="LearningRate"):
        probe(mc, ModelStep.TRAIN)


def test_nan_inf_strings_are_problems_not_crashes():
    assert _problems({"MiniBatchs": "nan"})
    assert _problems({"MiniBatchs": "inf"})
    assert _problems({"LearningRate": "nan"})


def test_grid_validates_without_cartesian_blowup():
    # 4 axes x 50 candidates = 6.25M cartesian trials; per-axis validation
    # must finish instantly and still catch the one bad candidate
    import time
    tc = ModelTrainConf(algorithm=Algorithm.NN,
                        params={"LearningRate": [0.1] * 49 + [-1.0],
                                "DropoutRate": [0.1] * 50,
                                "MiniBatchs": list(range(1, 51)),
                                "Seed": list(range(50))})
    t0 = time.perf_counter()
    out = validate_train_conf(tc)
    assert time.perf_counter() - t0 < 1.0
    assert any("LearningRate" in p for p in out)


def test_grid_shape_mismatch_caught_per_combo():
    tc = ModelTrainConf(algorithm=Algorithm.NN,
                        params={"NumHiddenLayers": [1, 3],
                                "NumHiddenNodes": [[10], [10, 5]]})
    assert any("NumHiddenLayers" in p for p in validate_train_conf(tc))


def test_combo_rejects_typo_params(model_set):
    from shifu_tpu.config.validator import ValidationError
    from shifu_tpu.pipeline.combo import run_combo
    import os
    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.params = {"LearningRat": 0.1}
    mc.save(mcp)
    assert run_combo(model_set, "new", "LR:GBT") == 0
    with pytest.raises(ValidationError, match="LearningRate"):
        run_combo(model_set, "init", None)


def test_tf_only_params_loud_fail():
    """algorithm=TENSORFLOW remaps onto the native NN path; TF-on-YARN
    topology params it would silently ignore are a coded, listed
    failure (reference ``TrainModelProcessor.java:395-449`` TF session
    keys)."""
    from shifu_tpu.config.meta import tf_ignored_param_problems

    tc = ModelTrainConf(algorithm=Algorithm.TENSORFLOW,
                        params={"LearningRate": 0.1, "NumPS": 2,
                                "TFWorkerMemory": 2048})
    # the keys themselves are KNOWN (not typos) and TF-applicable
    assert validate_train_conf(tc) == []
    out = tf_ignored_param_problems(tc)
    assert len(out) == 1
    assert "NumPS" in out[0] and "TFWorkerMemory" in out[0]
    assert "silently ignored" in out[0]
    # no TF-only params -> no problem; other algorithms unaffected
    tc2 = ModelTrainConf(algorithm=Algorithm.TENSORFLOW,
                         params={"LearningRate": 0.1})
    assert tf_ignored_param_problems(tc2) == []
    tc3 = ModelTrainConf(algorithm=Algorithm.NN, params={"NumPS": 2})
    assert tf_ignored_param_problems(tc3) == []
    # ...on NN the same key is an applicability error instead
    assert any("does not apply" in p for p in validate_train_conf(tc3))


def test_tf_only_params_fail_probe_and_train(model_set):
    """End-to-end: the TRAIN probe rejects a TENSORFLOW config carrying
    TF-only params with the coded ValidationError, listing them."""
    import os

    from shifu_tpu.config.validator import ValidationError
    from shifu_tpu.pipeline.train import TrainProcessor

    mc = ModelConfig.load(os.path.join(model_set, "ModelConfig.json"))
    mc.train.algorithm = Algorithm.TENSORFLOW
    mc.train.params = {"LearningRate": 0.1, "NumPS": 4}
    mc.save(os.path.join(model_set, "ModelConfig.json"))
    with pytest.raises(ValidationError) as ei:
        TrainProcessor(model_set, params={}).run()
    assert "NumPS" in str(ei.value)
    assert ei.value.problems and "native NN path" in ei.value.problems[0]
