"""Genetic wrapper varselect (reference core/dvarsel/) + eval report
surface (HTML report, eval -norm, export bagging)."""

import json
import os

import numpy as np
import pytest


def _xor_csv(tmp_path, n=3000, seed=11):
    """Two features that are USELESS alone but decisive together (XOR), one
    weakly-informative feature, three noise columns — a filter method (KS)
    cannot see the interaction; a wrapper can."""
    rng = np.random.default_rng(seed)
    f1, f2 = rng.normal(size=n), rng.normal(size=n)
    weak = rng.normal(size=n)
    noise = rng.normal(size=(n, 3))
    xor = (f1 > 0) ^ (f2 > 0)
    logit = 3.0 * np.where(xor, 1.0, -1.0) + 0.3 * weak
    y = rng.random(n) < 1 / (1 + np.exp(-logit))
    tag = np.where(y, "bad", "good")
    rows = ["id|f1|f2|weak|n1|n2|n3|tag"]
    for i in range(n):
        rows.append(f"r{i}|{f1[i]:.5f}|{f2[i]:.5f}|{weak[i]:.5f}|"
                    f"{noise[i,0]:.5f}|{noise[i,1]:.5f}|{noise[i,2]:.5f}|"
                    f"{tag[i]}")
    p = tmp_path / "xor.csv"
    p.write_text("\n".join(rows) + "\n")
    meta = tmp_path / "meta.names"
    meta.write_text("id\n")
    return str(p), str(meta)


@pytest.fixture
def xor_model_set(tmp_path):
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import create_new_model
    csv_path, meta = _xor_csv(tmp_path)
    mdir = create_new_model("xortest", base_dir=str(tmp_path))
    mcp = os.path.join(mdir, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.dataSet.dataPath = csv_path
    mc.dataSet.dataDelimiter = "|"
    mc.dataSet.targetColumnName = "tag"
    mc.dataSet.posTags = ["bad"]
    mc.dataSet.negTags = ["good"]
    mc.dataSet.metaColumnNameFile = meta
    mc.train.baggingNum = 1
    mc.train.numTrainEpochs = 40
    mc.train.params = {"NumHiddenNodes": [8], "ActivationFunc": ["tanh"],
                       "Propagation": "ADAM", "LearningRate": 0.05,
                       "Loss": "log"}
    mc.evals[0].dataSet.dataPath = csv_path
    mc.evals[0].dataSet.dataDelimiter = "|"
    mc.save(mcp)
    return mdir


def _auc_with_filter(mdir, filter_by, filter_num=2):
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.pipeline.varselect import VarSelectProcessor

    mcp = os.path.join(mdir, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.varSelect.filterBy = filter_by
    mc.varSelect.filterNum = filter_num
    mc.save(mcp)
    assert InitProcessor(mdir).run() == 0
    assert StatsProcessor(mdir, params={}).run() == 0
    assert NormalizeProcessor(mdir, params={}).run() == 0   # all candidates
    assert VarSelectProcessor(mdir, params={}).run() == 0
    assert NormalizeProcessor(mdir, params={}).run() == 0   # selected only
    assert TrainProcessor(mdir, params={}).run() == 0
    assert EvalProcessor(mdir, params={"run_eval": "Eval1"}).run() == 0
    perf = json.load(open(os.path.join(mdir, "evals", "Eval1",
                                       "EvalPerformance.json")))
    from shifu_tpu.config.column_config import load_column_configs
    selected = [c.columnName for c in
                load_column_configs(os.path.join(mdir, "ColumnConfig.json"))
                if c.finalSelect]
    return perf["areaUnderRoc"], selected


def test_genetic_wrapper_beats_ks_on_interaction(xor_model_set):
    """KS filter picks individually-scored columns and misses the XOR pair;
    the genetic wrapper finds it — eval AUC gap must be decisive
    (reference: wrapper search exists precisely for interactions,
    core/dvarsel/wrapper/)."""
    auc_ks, sel_ks = _auc_with_filter(xor_model_set, "KS", filter_num=2)
    auc_gen, sel_gen = _auc_with_filter(xor_model_set, "GENETIC",
                                        filter_num=2)
    assert set(sel_gen) == {"f1", "f2"}, sel_gen
    assert auc_gen > 0.9
    assert auc_gen > auc_ks + 0.1, (auc_gen, auc_ks, sel_ks)
    # credit trace persisted for the judge/debugging
    assert os.path.isfile(os.path.join(xor_model_set, "varsels",
                                       "genetic.json"))


def test_genetic_varselect_unit():
    """Direct API: the wrapper recovers the XOR pair from 6 columns."""
    from shifu_tpu.train.dvarsel import WrapperSettings, genetic_varselect
    rng = np.random.default_rng(3)
    n = 2000
    x = rng.normal(size=(n, 6)).astype(np.float32)
    xor = (x[:, 0] > 0) ^ (x[:, 1] > 0)
    y = (rng.random(n) < 1 / (1 + np.exp(-3.0 * np.where(xor, 1, -1)))) \
        .astype(np.float32)
    blocks = {ci: [ci] for ci in range(6)}
    scores, history = genetic_varselect(
        x, y, np.ones(n, np.float32), blocks,
        WrapperSettings(n_select=2, population=12, generations=4,
                        epochs=60, seed=0))
    top2 = sorted(scores, key=scores.get, reverse=True)[:2]
    assert set(top2) == {0, 1}, scores
    assert history[-1]["best"] <= history[0]["best"] + 1e-6


def test_eval_emits_html_report(model_set):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0
    assert EvalProcessor(model_set, params={"run_eval": "Eval1"}).run() == 0
    html = open(os.path.join(model_set, "evals", "Eval1",
                             "report.html")).read()
    assert "<svg" in html and "ROC" in html and "Gain chart" in html
    perf = json.load(open(os.path.join(model_set, "evals", "Eval1",
                                       "EvalPerformance.json")))
    assert f"{perf['areaUnderRoc']:.6f}" in html

    # eval -norm: normalized eval matrix export
    assert EvalProcessor(model_set, params={"norm_eval": "Eval1"}).run() == 0
    norm_path = None
    for root, _, files in os.walk(model_set):
        for f in files:
            if "Norm" in f and "Eval1" in root:
                norm_path = os.path.join(root, f)
    assert norm_path, "eval -norm wrote nothing"
    lines = open(norm_path).read().strip().split("\n")
    assert lines[0].startswith("tag|weight|")
    assert len(lines) > 1000


def test_export_bagging(model_set):
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.export import ExportProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    mcp = os.path.join(model_set, "ModelConfig.json")
    mc = ModelConfig.load(mcp)
    mc.train.baggingNum = 3
    mc.train.numTrainEpochs = 8
    mc.save(mcp)
    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0
    assert ExportProcessor(model_set, params={"type": "bagging"}).run() == 0
    out = os.path.join(model_set, "export", "bagging")
    manifest = json.load(open(os.path.join(out, "ensemble.json")))
    assert len(manifest["members"]) == 3
    for m in manifest["members"]:
        assert os.path.isfile(os.path.join(out, m))
    # baggingpmml: one PMML per member
    assert ExportProcessor(model_set,
                           params={"type": "baggingpmml"}).run() == 0
