"""AUC-parity oracle against the reference's own trained golden models.

The measured baselines (BASELINE.md "Measured baselines") come from scoring
the reference's shipped model artifacts on its shipped eval data:

- NN bag: ``example/cancer-judgement/ModelStore/ModelSet1/models/*.nn``
  (Encog EG text, reference ``core/alg/NNTrainer.java`` output) -> AUC
  0.998528 on EvalSet1.
- GBT: ``example/readablespec/model0.gbt`` (``BinaryDTSerializer.java``
  v4 gzip, cancer-judgement columns) -> AUC 0.940076 on the same rows.

These tests pin (a) the importers keep reproducing those numbers and (b) our
own trainers reach reference AUC within ±0.005 on the same data — the parity
gate BASELINE.json's north star requires.
"""

import os

import numpy as np
import pytest

REF = "/root/reference/src/test/resources/example/cancer-judgement"
MODELSET = f"{REF}/ModelStore/ModelSet1"
GBT_GOLDEN = "/root/reference/src/test/resources/example/readablespec/model0.gbt"

REFERENCE_NN_AUC = 0.998528      # measured: tools/measure_baseline.py
REFERENCE_GBT_AUC = 0.940076     # measured: tools/measure_baseline.py
AUC_TOL = 0.005

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference example data not mounted")


def _cancer(split):
    from shifu_tpu.models.reference_import import load_reference_psv
    cols = load_reference_psv(f"{REF}/DataStore/{split}/part-00",
                              f"{REF}/DataStore/{split}/.pig_header")
    target = (cols["diagnosis"] == "M").astype(np.float32)
    return cols, target


def _normalized(cols, ccs):
    from shifu_tpu.models.reference_import import zscore_matrix
    return zscore_matrix(cols, ccs)


@pytest.fixture(scope="module")
def ccs():
    from shifu_tpu.config.column_config import load_column_configs
    return load_column_configs(f"{MODELSET}/ColumnConfig.json")


@pytest.fixture(scope="module")
def eval_data(ccs):
    cols, target = _cancer("EvalSet1")
    z, raw = _normalized(cols, ccs)
    return z, raw, target


@pytest.fixture(scope="module")
def train_data(ccs):
    cols, target = _cancer("DataSet1")
    z, raw = _normalized(cols, ccs)
    return z, raw, target


def _auc(scores, target):
    from shifu_tpu.eval.metrics import evaluate_scores
    return float(evaluate_scores(np.asarray(scores, np.float32),
                                 target).areaUnderRoc)


def test_reference_nn_golden_auc(eval_data):
    """Importer + our forward reproduce the recorded reference NN AUC."""
    from shifu_tpu.models.nn import IndependentNNModel
    from shifu_tpu.models.reference_import import load_encog_nn

    z, _, target = eval_data
    scores = np.zeros(len(target))
    n_models = 0
    for i in range(8):
        path = f"{MODELSET}/models/model{i}.nn"
        if not os.path.exists(path):
            break
        spec, params = load_encog_nn(path)
        assert spec.input_dim == 30 and spec.hidden_nodes == [45, 45]
        scores += IndependentNNModel(spec, params).compute(z)[:, 0]
        n_models += 1
    assert n_models == 5
    assert abs(_auc(scores / n_models, target) - REFERENCE_NN_AUC) < 2e-3


def test_reference_gbt_golden_auc(eval_data):
    """Importer + faithful node walk reproduce the recorded GBT AUC."""
    from shifu_tpu.models.reference_import import load_reference_tree

    _, raw, target = eval_data
    model = load_reference_tree(GBT_GOLDEN)
    assert model.algorithm == "GBT" and len(model.trees) == 100
    assert abs(_auc(model.compute(raw), target) - REFERENCE_GBT_AUC) < 2e-3


def test_our_nn_reaches_reference_auc(ccs, train_data, eval_data):
    """Our meshed NN ensemble trained with the reference ModelSet1 recipe
    (5 bags, 2x45 sigmoid, 100 epochs) matches reference AUC within tol."""
    from shifu_tpu.models import nn as nn_model
    from shifu_tpu.train.nn_trainer import TrainSettings, train_ensemble
    from shifu_tpu.train.sampling import member_masks

    z_tr, _, y_tr = train_data
    z_ev, _, y_ev = eval_data
    bags = 5
    train_w, valid_w = member_masks(len(y_tr), bags, valid_rate=0.1,
                                    sample_rate=1.0, replacement=True,
                                    targets=y_tr, seed=0)
    spec = nn_model.NNModelSpec(input_dim=z_tr.shape[1],
                                hidden_nodes=[45, 45],
                                activations=["sigmoid", "sigmoid"],
                                loss="squared")
    res = train_ensemble(z_tr, y_tr, train_w, valid_w, spec,
                         TrainSettings(optimizer="ADAM", learning_rate=0.01,
                                       epochs=100, seed=0))
    scores = np.zeros(len(y_ev))
    for params in res.params:
        scores += np.asarray(
            nn_model.forward(params, spec, z_ev))[:, 0]
    auc = _auc(scores / bags, y_ev)
    assert auc >= REFERENCE_NN_AUC - AUC_TOL, f"our NN AUC {auc}"


def test_our_gbt_reaches_reference_auc(ccs, train_data, eval_data):
    """Our jitted GBT on equal-population bins beats/matches the reference
    golden forest's AUC within tol."""
    from shifu_tpu.models.tree import IndependentTreeModel, TreeModelSpec
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt

    _, raw_tr, y_tr = train_data
    _, raw_ev, y_ev = eval_data
    cols = sorted(raw_tr)
    n_bins = 32
    edges = {}
    for c in cols:
        qs = np.quantile(raw_tr[c], np.linspace(0, 1, n_bins)[1:-1])
        edges[c] = np.unique(qs)

    def binned(raw):
        return np.stack([np.searchsorted(edges[c], raw[c]).astype(np.int32)
                         for c in cols], axis=1)

    bins_tr, bins_ev = binned(raw_tr), binned(raw_ev)
    res = train_gbt(bins_tr, y_tr, np.ones(len(y_tr), np.float32), n_bins,
                    np.zeros(len(cols), bool),
                    DTSettings(n_trees=100, depth=4, loss="log",
                               learning_rate=0.05, valid_rate=0.1, seed=0))
    spec = TreeModelSpec(n_trees=len(res.trees), depth=4, n_bins=n_bins,
                         **res.spec_kwargs)
    scores = IndependentTreeModel(spec, res.trees).compute(bins_ev)[:, 0]
    auc = _auc(scores, y_ev)
    assert auc >= REFERENCE_GBT_AUC - AUC_TOL, f"our GBT AUC {auc}"
