"""Eval stack tests — AUC parity cases (reference
``core/evaluation/AreaUnderCurveTest.java`` pattern) + end-to-end eval run."""

import csv
import json
import os

import numpy as np
import pytest

from shifu_tpu.eval.metrics import auc_trapezoid, evaluate_scores
from shifu_tpu.eval.scorer import Scorer, CaseScoreResult


def test_auc_perfect_classifier():
    scores = np.array([0.9, 0.8, 0.7, 0.2, 0.1])
    targets = np.array([1, 1, 1, 0, 0])
    res = evaluate_scores(scores, targets)
    assert res.areaUnderRoc == pytest.approx(1.0)


def test_auc_random_is_half():
    rng = np.random.default_rng(0)
    scores = rng.random(20000)
    targets = (rng.random(20000) < 0.3).astype(float)
    res = evaluate_scores(scores, targets)
    assert res.areaUnderRoc == pytest.approx(0.5, abs=0.02)


def test_auc_matches_rank_statistic():
    """AUC == P(score_pos > score_neg) (Mann-Whitney), the textbook identity."""
    rng = np.random.default_rng(1)
    scores = rng.normal(size=500)
    targets = (rng.random(500) < 0.4).astype(float)
    scores[targets == 1] += 1.0
    res = evaluate_scores(scores, targets)
    pos = scores[targets == 1]
    neg = scores[targets == 0]
    mw = (pos[:, None] > neg[None, :]).mean() + \
        0.5 * (pos[:, None] == neg[None, :]).mean()
    assert res.areaUnderRoc == pytest.approx(mw, abs=1e-6)


def test_device_sweep_matches_host_exactly():
    """Device sweep (sort/cumsum/tie-scans in HBM, one packed fetch) must
    reproduce the host sweep's AUC/wAUC/PR-AUC exactly — ties included —
    and its downsampled curve points must lie ON the host curve."""
    from shifu_tpu.eval.metrics import evaluate_scores_device, sweep

    rng = np.random.default_rng(5)
    n = 5000
    # heavy ties: quantized scores
    scores = np.round(rng.normal(size=n), 2)
    targets = (rng.random(n) < 0.3).astype(float)
    scores[targets == 1] += 0.5
    weights = rng.random(n) + 0.5
    host = evaluate_scores(scores, targets, weights)
    import jax
    enable_x64 = getattr(jax, "enable_x64", None)
    if enable_x64 is None:                 # jax<0.5 spells it experimental
        from jax.experimental import enable_x64
    with enable_x64():            # exactness check at f64 (TPU runs f32)
        curves, dev = evaluate_scores_device(scores, targets, weights)
    assert dev.areaUnderRoc == pytest.approx(host.areaUnderRoc, abs=1e-12)
    assert dev.weightedAuc == pytest.approx(host.weightedAuc, abs=1e-12)
    assert dev.areaUnderPr == pytest.approx(host.areaUnderPr, abs=1e-12)
    # default (f32, the TPU precision) stays within float tolerance
    _, dev32 = evaluate_scores_device(scores, targets, weights)
    assert dev32.areaUnderRoc == pytest.approx(host.areaUnderRoc, abs=2e-4)
    assert dev.recordCount == host.recordCount
    assert dev.posCount == pytest.approx(host.posCount)
    # every downsampled point must be an exact host tie-group end
    hc = sweep(scores, targets, weights)
    host_pts = {(round(t, 9), tp, fp)
                for t, tp, fp in zip(hc.thresholds, hc.tp, hc.fp)}
    for t, tp, fp in zip(curves.thresholds, curves.tp, curves.fp):
        assert (round(t, 9), tp, fp) in host_pts


def test_device_sweep_small_and_degenerate():
    from shifu_tpu.eval.metrics import evaluate_scores_device

    # n < points path + all-one-class degenerate
    scores = np.array([0.9, 0.8, 0.8, 0.1])
    targets = np.array([1.0, 1.0, 0.0, 0.0])
    _, res = evaluate_scores_device(scores, targets)
    host = evaluate_scores(scores, targets)
    assert res.areaUnderRoc == pytest.approx(host.areaUnderRoc, abs=1e-12)
    _, degen = evaluate_scores_device(scores, np.ones(4))
    assert np.isnan(degen.areaUnderRoc)


def test_score_device_matches_host_scorer():
    """score_device must agree with score() and feed sweep_device without
    leaving HBM (the bench/eval resident plane)."""
    import jax
    import jax.numpy as jnp
    from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                     init_params)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    spec = NNModelSpec(input_dim=16, hidden_nodes=[8, 4],
                       activations=["relu", "relu"], output_dim=1)
    models = [IndependentNNModel(spec, init_params(jax.random.PRNGKey(i),
                                                   spec))
              for i in range(3)]
    sc = Scorer(models)
    host = sc.score(x)
    raw_d, mean_d = sc.score_device(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(raw_d), host.scores,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(mean_d), host.mean,
                               rtol=1e-5, atol=1e-5)


def test_weighted_auc_reweights():
    scores = np.array([0.9, 0.8, 0.3, 0.2])
    targets = np.array([1.0, 0.0, 1.0, 0.0])
    unweighted = evaluate_scores(scores, targets)
    # weight the high-score pair heavily -> weighted AUC improves
    weighted = evaluate_scores(scores, targets,
                               np.array([10.0, 0.1, 0.1, 10.0]))
    assert weighted.weightedAuc > unweighted.areaUnderRoc


def test_bucket_points_monotone():
    rng = np.random.default_rng(2)
    scores = rng.random(5000)
    targets = (scores + rng.normal(0, 0.3, 5000) > 0.6).astype(float)
    res = evaluate_scores(scores, targets, buckets=10)
    assert len(res.points) == 10
    recalls = [p.recall for p in res.points]
    actions = [p.actionRate for p in res.points]
    assert recalls == sorted(recalls)
    assert actions == sorted(actions)
    assert res.points[-1].recall == pytest.approx(1.0)
    # threshold column is descending in score
    ths = [p.binLowestScore for p in res.points]
    assert ths == sorted(ths, reverse=True)


def test_degenerate_single_class():
    res = evaluate_scores(np.array([0.5, 0.6]), np.array([1.0, 1.0]))
    assert np.isnan(res.areaUnderRoc)


class _ConstModel:
    def __init__(self, v):
        self.v = v

    def compute(self, x):
        return np.full((len(x), 1), self.v)


def test_scorer_aggregates_and_scale():
    sc = Scorer([_ConstModel(0.2), _ConstModel(0.6)])
    res = sc.score(np.zeros((3, 4)))
    assert res.scores.shape == (3, 2)
    np.testing.assert_allclose(res.mean, 400.0)
    np.testing.assert_allclose(res.max, 600.0)
    np.testing.assert_allclose(res.min, 200.0)
    assert res.select("model1")[0] == 600.0


def test_eval_pipeline_end_to_end(prepared_set):
    model_set = prepared_set          # init/stats/norm ran in the template
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor

    assert TrainProcessor(model_set, params={}).run() == 0
    assert EvalProcessor(model_set, params={"run_eval": ""}).run() == 0

    eval_dir = os.path.join(model_set, "evals", "Eval1")
    perf = json.load(open(os.path.join(eval_dir, "EvalPerformance.json")))
    # the model learned something real: AUC well above chance on train data
    assert perf["areaUnderRoc"] > 0.7
    assert perf["recordCount"] == 4000
    assert len(perf["performance"]) == 10

    with open(os.path.join(eval_dir, "EvalScore")) as f:
        rows = list(csv.reader(f, delimiter="|"))
    assert len(rows) == 4001  # header + all records
    assert rows[0][:3] == ["tag", "weight", "mean"]

    assert os.path.isfile(os.path.join(eval_dir, "EvalConfusionMatrix"))
    assert os.path.isfile(os.path.join(eval_dir, "gainchart.csv"))


def test_eval_crud(prepared_set):
    model_set = prepared_set          # init ran in the template
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    assert EvalProcessor(model_set, params={"new_eval": "EvalX"}).run() == 0
    from shifu_tpu.config import ModelConfig
    mc = ModelConfig.load(os.path.join(model_set, "ModelConfig.json"))
    assert any(e.name == "EvalX" for e in mc.evals)
    assert EvalProcessor(model_set, params={"delete_eval": "EvalX"}).run() == 0
    mc = ModelConfig.load(os.path.join(model_set, "ModelConfig.json"))
    assert not any(e.name == "EvalX" for e in mc.evals)
    assert EvalProcessor(model_set, params={"delete_eval": "nope"}).run() == 1


def test_posttrain_bin_avg_scores(prepared_set):
    model_set = prepared_set          # init/stats/norm ran in the template
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.pipeline.posttrain import PostTrainProcessor
    from shifu_tpu.config import load_column_configs

    assert TrainProcessor(model_set, params={}).run() == 0
    assert PostTrainProcessor(model_set, params={}).run() == 0
    ccs = load_column_configs(os.path.join(model_set, "ColumnConfig.json"))
    scored = [c for c in ccs if c.columnBinning.binAvgScore]
    assert scored, "no binAvgScore written"
    fi_path = os.path.join(model_set, "posttrain", "featureImportance.csv")
    assert os.path.isfile(fi_path)
    lines = open(fi_path).read().strip().splitlines()
    assert len(lines) >= 3
    # ranked descending
    vals = [float(l.split("\t")[1]) for l in lines]
    assert vals == sorted(vals, reverse=True)
