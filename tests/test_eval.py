"""Eval stack tests — AUC parity cases (reference
``core/evaluation/AreaUnderCurveTest.java`` pattern) + end-to-end eval run."""

import csv
import json
import os

import numpy as np
import pytest

from shifu_tpu.eval.metrics import auc_trapezoid, evaluate_scores
from shifu_tpu.eval.scorer import Scorer, CaseScoreResult


def test_auc_perfect_classifier():
    scores = np.array([0.9, 0.8, 0.7, 0.2, 0.1])
    targets = np.array([1, 1, 1, 0, 0])
    res = evaluate_scores(scores, targets)
    assert res.areaUnderRoc == pytest.approx(1.0)


def test_auc_random_is_half():
    rng = np.random.default_rng(0)
    scores = rng.random(20000)
    targets = (rng.random(20000) < 0.3).astype(float)
    res = evaluate_scores(scores, targets)
    assert res.areaUnderRoc == pytest.approx(0.5, abs=0.02)


def test_auc_matches_rank_statistic():
    """AUC == P(score_pos > score_neg) (Mann-Whitney), the textbook identity."""
    rng = np.random.default_rng(1)
    scores = rng.normal(size=500)
    targets = (rng.random(500) < 0.4).astype(float)
    scores[targets == 1] += 1.0
    res = evaluate_scores(scores, targets)
    pos = scores[targets == 1]
    neg = scores[targets == 0]
    mw = (pos[:, None] > neg[None, :]).mean() + \
        0.5 * (pos[:, None] == neg[None, :]).mean()
    assert res.areaUnderRoc == pytest.approx(mw, abs=1e-6)


def test_weighted_auc_reweights():
    scores = np.array([0.9, 0.8, 0.3, 0.2])
    targets = np.array([1.0, 0.0, 1.0, 0.0])
    unweighted = evaluate_scores(scores, targets)
    # weight the high-score pair heavily -> weighted AUC improves
    weighted = evaluate_scores(scores, targets,
                               np.array([10.0, 0.1, 0.1, 10.0]))
    assert weighted.weightedAuc > unweighted.areaUnderRoc


def test_bucket_points_monotone():
    rng = np.random.default_rng(2)
    scores = rng.random(5000)
    targets = (scores + rng.normal(0, 0.3, 5000) > 0.6).astype(float)
    res = evaluate_scores(scores, targets, buckets=10)
    assert len(res.points) == 10
    recalls = [p.recall for p in res.points]
    actions = [p.actionRate for p in res.points]
    assert recalls == sorted(recalls)
    assert actions == sorted(actions)
    assert res.points[-1].recall == pytest.approx(1.0)
    # threshold column is descending in score
    ths = [p.binLowestScore for p in res.points]
    assert ths == sorted(ths, reverse=True)


def test_degenerate_single_class():
    res = evaluate_scores(np.array([0.5, 0.6]), np.array([1.0, 1.0]))
    assert np.isnan(res.areaUnderRoc)


class _ConstModel:
    def __init__(self, v):
        self.v = v

    def compute(self, x):
        return np.full((len(x), 1), self.v)


def test_scorer_aggregates_and_scale():
    sc = Scorer([_ConstModel(0.2), _ConstModel(0.6)])
    res = sc.score(np.zeros((3, 4)))
    assert res.scores.shape == (3, 2)
    np.testing.assert_allclose(res.mean, 400.0)
    np.testing.assert_allclose(res.max, 600.0)
    np.testing.assert_allclose(res.min, 200.0)
    assert res.select("model1")[0] == 600.0


def test_eval_pipeline_end_to_end(model_set):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor

    assert InitProcessor(model_set).run() == 0
    assert StatsProcessor(model_set, params={}).run() == 0
    assert NormalizeProcessor(model_set, params={}).run() == 0
    assert TrainProcessor(model_set, params={}).run() == 0
    assert EvalProcessor(model_set, params={"run_eval": ""}).run() == 0

    eval_dir = os.path.join(model_set, "evals", "Eval1")
    perf = json.load(open(os.path.join(eval_dir, "EvalPerformance.json")))
    # the model learned something real: AUC well above chance on train data
    assert perf["areaUnderRoc"] > 0.7
    assert perf["recordCount"] == 4000
    assert len(perf["performance"]) == 10

    with open(os.path.join(eval_dir, "EvalScore")) as f:
        rows = list(csv.reader(f, delimiter="|"))
    assert len(rows) == 4001  # header + all records
    assert rows[0][:3] == ["tag", "weight", "mean"]

    assert os.path.isfile(os.path.join(eval_dir, "EvalConfusionMatrix"))
    assert os.path.isfile(os.path.join(eval_dir, "gainchart.csv"))


def test_eval_crud(model_set):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    assert InitProcessor(model_set).run() == 0
    assert EvalProcessor(model_set, params={"new_eval": "EvalX"}).run() == 0
    from shifu_tpu.config import ModelConfig
    mc = ModelConfig.load(os.path.join(model_set, "ModelConfig.json"))
    assert any(e.name == "EvalX" for e in mc.evals)
    assert EvalProcessor(model_set, params={"delete_eval": "EvalX"}).run() == 0
    mc = ModelConfig.load(os.path.join(model_set, "ModelConfig.json"))
    assert not any(e.name == "EvalX" for e in mc.evals)
    assert EvalProcessor(model_set, params={"delete_eval": "nope"}).run() == 1


def test_posttrain_bin_avg_scores(model_set):
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.pipeline.posttrain import PostTrainProcessor
    from shifu_tpu.config import load_column_configs

    assert InitProcessor(model_set).run() == 0
    for P in (StatsProcessor, NormalizeProcessor, TrainProcessor):
        assert P(model_set, params={}).run() == 0
    assert PostTrainProcessor(model_set, params={}).run() == 0
    ccs = load_column_configs(os.path.join(model_set, "ColumnConfig.json"))
    scored = [c for c in ccs if c.columnBinning.binAvgScore]
    assert scored, "no binAvgScore written"
    fi_path = os.path.join(model_set, "posttrain", "featureImportance.csv")
    assert os.path.isfile(fi_path)
    lines = open(fi_path).read().strip().splitlines()
    assert len(lines) >= 3
    # ranked descending
    vals = [float(l.split("\t")[1]) for l in lines]
    assert vals == sorted(vals, reverse=True)
