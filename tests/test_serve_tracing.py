"""Per-request tracing suite (serve plane, tier-1-fast): trace-id
propagation end to end, the latency decomposition summing to measured
e2e, batch spans linking member ids (fan-in causality), the zero-cost
guards for sampling off, the ``X-Shifu-Trace`` HTTP header, the
``shifu-serve`` timeline track, and the bench decomposition helper /
compare classes."""

import json
import os
import time

import numpy as np
import pytest

import jax

from shifu_tpu import obs
from shifu_tpu.config import environment
from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                 init_params)
from shifu_tpu.serve import AOTScorer, MicroBatcher, ServeServer
from shifu_tpu.serve.batcher import configured_trace_sample_rate

pytestmark = pytest.mark.serve


@pytest.fixture(autouse=True)
def _clean_env():
    environment.reset_for_tests()
    obs.reset_for_tests()
    yield
    environment.reset_for_tests()
    obs.reset_for_tests()


def _nn_models(n=3, n_features=8, seed0=0):
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=[8],
                       activations=["relu"])
    return [IndependentNNModel(spec, init_params(
        jax.random.PRNGKey(seed0 + i), spec)) for i in range(n)]


def _warm_scorer(buckets=(1, 4, 16)):
    scorer = AOTScorer(_nn_models(), buckets=buckets)
    scorer.warm()
    return scorer


def _request_spans():
    return [r for r in obs.pending_records()
            if r.get("kind") == "span" and r["name"] == "serve.request"]


def _batch_spans():
    return [r for r in obs.pending_records()
            if r.get("kind") == "span" and r["name"] == "serve.batch"]


# ------------------------------------------------------------- sampling
def test_sample_rate_property_reader():
    assert configured_trace_sample_rate() == 0.0
    environment.set_property("shifu.serve.traceSampleRate", "0.25")
    assert configured_trace_sample_rate() == 0.25
    environment.set_property("shifu.serve.traceSampleRate", "7")
    assert configured_trace_sample_rate() == 1.0    # clamped
    environment.set_property("shifu.serve.traceSampleRate", "-1")
    assert configured_trace_sample_rate() == 0.0


def test_sample_rate_zero_writes_zero_request_records():
    """ACCEPTANCE: sampling off (the default) writes NO request/batch
    records even with telemetry fully enabled, and scoring is
    unaffected."""
    obs.set_enabled(True)
    scorer = _warm_scorer()
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    assert b.trace_sample_rate == 0.0
    rng = np.random.default_rng(0)
    for n in (1, 3, 7):
        t = b.submit_burst(rng.normal(size=(n, 8)).astype(np.float32))
        b.drain()
        assert t.wait(10.0).shape == (n,)
    assert _request_spans() == [] and _batch_spans() == []
    snap = {m["name"]: m for m in obs.snapshot()}
    assert "serve.trace_sampled" not in snap


def test_sampled_scores_bit_identical_to_unsampled():
    """Tracing must OBSERVE the batch path, never perturb it: the same
    rows scored with and without a trace id produce bit-identical
    scores."""
    obs.set_enabled(True)
    scorer = _warm_scorer()
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    rng = np.random.default_rng(3)
    x = rng.normal(size=(5, 8)).astype(np.float32)
    t1 = b.submit_burst(x)
    b.drain()
    plain = t1.wait(10.0)
    t2 = b.submit_burst(x, trace_id="parity-check")
    b.drain()
    traced = t2.wait(10.0)
    assert traced.tobytes() == plain.tobytes()


def test_trace_id_minted_when_head_sampled():
    obs.set_enabled(True)
    scorer = _warm_scorer()
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0,
                     trace_sample_rate=1.0)
    rng = np.random.default_rng(4)
    t = b.submit_burst(rng.normal(size=(2, 8)).astype(np.float32))
    b.drain()
    t.wait(10.0)
    (req,) = _request_spans()
    assert req["attrs"]["trace"]                 # minted, non-empty
    assert req["tid"] == "shifu-serve"
    snap = {m["name"]: m for m in obs.snapshot()}
    assert snap["serve.trace_sampled"]["value"] == 1


def test_sampling_disabled_without_telemetry():
    """Head sampling requires telemetry (records would go nowhere);
    rate > 0 with obs off emits nothing and costs nothing."""
    obs.set_enabled(False)
    scorer = _warm_scorer()
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0,
                     trace_sample_rate=1.0)
    t = b.submit_burst(np.random.default_rng(5).normal(
        size=(2, 8)).astype(np.float32))
    b.drain()
    t.wait(10.0)
    assert t.trace is None
    assert obs.pending_records() == []


# ------------------------------------------------------- decomposition
def test_request_span_segments_sum_to_e2e():
    """ACCEPTANCE: a sampled burst's decomposition (queue-wait + pad +
    launch + device) sums, within tolerance, to the measured end-to-end
    latency; every segment is non-negative."""
    obs.set_enabled(True)
    scorer = _warm_scorer()
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    rng = np.random.default_rng(7)
    x = rng.normal(size=(5, 8)).astype(np.float32)
    # a couple of warm loops so lazy one-time costs (fault-site property
    # parse, dispatch path) sit outside the measured request
    for _ in range(3):
        t0 = b.submit_burst(x)
        b.drain()
        t0.wait(10.0)
    t = b.submit_burst(x, trace_id="sum-check")
    b.drain()
    t.wait(10.0)
    measured_e2e = float(t.latencies().max())
    req = next(r for r in _request_spans()
               if r["attrs"]["trace"] == "sum-check")
    a = req["attrs"]
    segments = (a["queue_wait_s"], a["pad_s"], a["launch_s"],
                a["device_s"])
    assert all(s >= 0.0 for s in segments)
    assert a["deadline_wait_s"] <= a["queue_wait_s"] + 1e-9
    total = sum(segments)
    # segments are nested inside e2e: they must not exceed it, and the
    # unattributed remainder (scheduler hops, completion bookkeeping)
    # stays small
    assert total <= a["e2e_s"] + 1e-6
    slack = max(0.5 * a["e2e_s"], 0.02)
    assert a["e2e_s"] - total <= slack, (a, total)
    # the span's own duration agrees with the measured ticket latency
    assert a["e2e_s"] == pytest.approx(measured_e2e,
                                       rel=0.5, abs=0.02)


def test_batch_span_links_all_member_trace_ids():
    """ACCEPTANCE: requests coalescing into one batch produce ONE
    serve.batch span whose links carry every sampled member's trace id,
    and each member's request span points back at the batch index."""
    obs.set_enabled(True)
    scorer = _warm_scorer(buckets=(1, 4, 16))
    clk_rows = np.random.default_rng(8).normal(size=(2, 8)) \
        .astype(np.float32)
    b = MicroBatcher(lambda: scorer, max_delay_s=10.0)
    t1 = b.submit_burst(clk_rows, trace_id="alpha")
    t2 = b.submit_burst(clk_rows, trace_id="beta")
    b.pump(force=True)                       # one coalesced launch
    t1.wait(10.0), t2.wait(10.0)
    (batch,) = _batch_spans()
    assert sorted(batch["attrs"]["links"]) == ["alpha", "beta"]
    assert batch["attrs"]["rows"] == 4
    assert batch["attrs"]["flush"] == "forced"
    reqs = _request_spans()
    assert {r["attrs"]["trace"] for r in reqs} == {"alpha", "beta"}
    assert all(r["attrs"]["batch"] == batch["attrs"]["batch"]
               for r in reqs)


def test_split_burst_emits_one_request_span_after_final_batch():
    obs.set_enabled(True)
    scorer = _warm_scorer(buckets=(1, 4))
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    x = np.random.default_rng(9).normal(size=(10, 8)).astype(np.float32)
    t = b.submit_burst(x, trace_id="split")
    b.drain()
    t.wait(10.0)
    (req,) = _request_spans()
    assert req["attrs"]["batches"] == 3          # 4 + 4 + 2
    assert len(_batch_spans()) == 3
    assert all("split" in bs["attrs"]["links"] for bs in _batch_spans())


def test_failed_batch_marks_trace_error():
    from shifu_tpu import faults
    obs.set_enabled(True)
    scorer = _warm_scorer(buckets=(1, 4))
    environment.set_property("shifu.faults", "serve:request=0:ioerror")
    faults.reset_for_tests()
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    t = b.submit_burst(np.random.default_rng(10).normal(
        size=(2, 8)).astype(np.float32), trace_id="boom")
    b.drain()
    with pytest.raises(faults.InjectedFault):
        t.wait(10.0)
    (req,) = _request_spans()
    assert req["attrs"]["error"] == "InjectedFault"
    (batch,) = _batch_spans()
    assert batch["attrs"]["error"] == "InjectedFault"
    environment.reset_for_tests()
    faults.reset_for_tests()


# ------------------------------------------------------- server / HTTP
def test_http_trace_header_propagates_and_flushes(tmp_path):
    """X-Shifu-Trace rides the HTTP front-end onto the batch pipeline
    (forcing sampling), echoes in the response, and stop() flushes the
    sampled spans into <modelset>/telemetry/trace.jsonl as a SERVE
    block."""
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    from shifu_tpu.serve.server import _make_handler
    obs.set_enabled(True)
    mdir = str(tmp_path)
    server = ServeServer(model_set_dir=mdir, models=_nn_models(),
                         key="h", buckets=(1, 4), max_delay_ms=1.0)
    server.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(server))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        rows = np.random.default_rng(11).normal(size=(2, 8)) \
            .round(4).tolist()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score",
            data=json.dumps({"rows": rows}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Shifu-Trace": "edge-42"})
        doc = json.load(urllib.request.urlopen(req, timeout=15))
        assert doc["trace"] == "edge-42" and len(doc["scores"]) == 2
        # /slo is live on the same front-end
        slo = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/slo", timeout=15))
        assert slo["kind"] == "slo" and "horizons" in slo
        health = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=15))
        assert "queue_depth" in health and "slo" in health
    finally:
        httpd.shutdown()
        httpd.server_close()
        server.stop()
    trace = os.path.join(mdir, "telemetry", "trace.jsonl")
    lines = [json.loads(ln) for ln in open(trace)]
    metas = [ln for ln in lines if ln["kind"] == "meta"]
    assert any(m["step"] == "SERVE" for m in metas)
    spans = [ln for ln in lines if ln.get("kind") == "span"]
    assert any(ln["name"] == "serve.request"
               and ln["attrs"]["trace"] == "edge-42" for ln in spans)


def test_timeline_routes_serve_spans_to_own_track(tmp_path):
    """The exported timeline puts serve.request/serve.batch spans on the
    shifu-serve track, separate from compute and ingest."""
    from shifu_tpu.obs import timeline as timeline_mod
    obs.set_enabled(True)
    scorer = _warm_scorer(buckets=(1, 4))
    b = MicroBatcher(lambda: scorer, max_delay_s=0.0)
    t = b.submit_burst(np.random.default_rng(12).normal(
        size=(2, 8)).astype(np.float32), trace_id="tl")
    b.drain()
    t.wait(10.0)
    trace = os.path.join(str(tmp_path), "telemetry", "trace.jsonl")
    obs.flush(trace, step="SERVE")
    out = timeline_mod.export_timeline(str(tmp_path),
                                       str(tmp_path / "tl.json"))
    doc = json.load(open(out))
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    serve_tids = {e["tid"] for e in spans
                  if e["name"].startswith("serve.")}
    assert serve_tids == {timeline_mod.TID_SERVE}
    labels = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
              if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "shifu-serve" in labels[timeline_mod.TID_SERVE]


# ---------------------------------------------------- zero-cost guard
def test_serve_rate_zero_overhead_within_noise():
    """CI guard (the PR 1 convention extended to the serve path): with
    sampling OFF, the submit->pump->complete hot path under telemetry ON
    must run within noise of the same loop with telemetry fully
    disabled — rate 0 short-circuits before any tracing work (one float
    compare), so the only delta is the pre-existing counter path."""
    scorer = _warm_scorer(buckets=(1, 4))
    rng = np.random.default_rng(13)
    x = rng.normal(size=(4, 8)).astype(np.float32)

    def loop():
        b = MicroBatcher(lambda: scorer, max_delay_s=0.0,
                         trace_sample_rate=0.0)
        tickets = [b.submit_burst(x) for _ in range(50)]
        b.drain()
        for t in tickets:
            t.wait(10.0)

    def best(setup):
        out = []
        for _ in range(5):
            setup()
            t0 = time.perf_counter()
            loop()
            out.append(time.perf_counter() - t0)
        return min(out)

    loop()                                  # warm dispatch paths
    t_off = best(lambda: obs.set_enabled(False))
    t_on = best(lambda: obs.set_enabled(True))
    obs.set_enabled(None)
    assert t_on <= t_off * 1.5 + 1e-3, \
        (f"rate-0 serve path overhead too high with telemetry on: "
         f"{t_on:.4f}s vs {t_off:.4f}s disabled")


# ------------------------------------------------------ bench surfaces
def test_bench_trace_decomposition_helper():
    from shifu_tpu.bench import _trace_decomposition
    spans = [{"kind": "span", "name": "serve.request",
              "attrs": {"e2e_s": 0.010, "queue_wait_s": 0.006,
                        "device_s": 0.002, "pad_s": 0.001}},
             {"kind": "span", "name": "serve.request",
              "attrs": {"e2e_s": 0.020, "queue_wait_s": 0.008,
                        "device_s": 0.010, "pad_s": 0.000}}]
    fr = _trace_decomposition(spans)
    assert fr["serve_queue_frac"] == pytest.approx(0.5)
    assert fr["serve_device_frac"] == pytest.approx(0.35)
    assert fr["serve_pad_frac"] == pytest.approx(0.05)
    assert _trace_decomposition([]) == {}
    # zero/missing e2e records are skipped, not divide-by-zeroed
    assert _trace_decomposition([{"attrs": {"e2e_s": 0}}]) == {}


def test_compare_tracks_decomposition_fracs():
    """Satellite: queue/pad fracs ride the lower-is-better class next
    to the latency percentiles; device_frac stays informational."""
    from shifu_tpu.bench import compare_bench, is_tracked_latency
    assert is_tracked_latency("serve_queue_frac")
    assert is_tracked_latency("serve_pad_frac")
    assert not is_tracked_latency("serve_device_frac")
    assert not is_tracked_latency("serve_trace_sample_rate")
    old = {"metric": "serve_qps_sustained", "value": 1e6,
           "extra": {"serve_queue_frac": 0.5, "serve_pad_frac": 0.01,
                     "serve_device_frac": 0.4}}
    new = {"metric": "serve_qps_sustained", "value": 1e6,
           "extra": {"serve_queue_frac": 0.9,    # waiting longer: bad
                     "serve_pad_frac": 0.01,
                     "serve_device_frac": 0.05}}  # untracked
    _, regressed = compare_bench(old, new, threshold=0.9)
    assert regressed == ["serve_queue_frac"]
