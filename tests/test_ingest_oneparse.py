"""One-parse offline pipeline suite (``pytest -m ingest``).

The tentpole contracts of the parse-pool / raw-cache / direct-to-wire
round, each tested against the serial seed path it replaced:

* pooled parse == serial parse, bit-for-bit (ColumnConfig stats, norm
  shards, quarantine accounting) — including sub-1.0 sample rates,
  where the pooled/cached order parses-then-subsets while the serial
  order subsets-then-parses;
* the columnar raw cache obeys spill-cache semantics: staleness pins
  the source signature, a budget overflow aborts PERMANENTLY, and a
  cache-served pass never touches the string plane
  (``ingest.disk_passes`` stays flat — the disk-pass regression guard);
* wire-only norm output trains bit-identical models to the npz path.
"""

import hashlib
import json
import os
import shutil

import numpy as np
import pytest

from shifu_tpu import obs
from shifu_tpu.config import environment

pytestmark = pytest.mark.ingest


@pytest.fixture(autouse=True)
def _clean_env():
    environment.reset_for_tests()
    yield
    environment.reset_for_tests()
    obs.set_enabled(False)


def _serial_knobs():
    environment.set_property("shifu.ingest.parseWorkers", "0")
    environment.set_property("shifu.ingest.rawCache", "false")
    environment.set_property("shifu.norm.wireOnly", "false")


def _run_init_stats_norm(mdir: str) -> None:
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    assert InitProcessor(mdir).run() == 0
    assert StatsProcessor(mdir, params={}).run() == 0
    assert NormalizeProcessor(mdir, params={}).run() == 0


def _set_sample_rates(mdir: str, stats_rate: float, norm_rate: float) -> None:
    from shifu_tpu.config import ModelConfig
    p = os.path.join(mdir, "ModelConfig.json")
    mc = ModelConfig.load(p)
    mc.stats.sampleRate = stats_rate
    mc.normalize.sampleRate = norm_rate
    mc.save(p)


def _clean_plane(mdir: str):
    """Per-shard arrays of the clean plane via Shards — transparent to
    npz vs wire storage, so serial and wire-only planes compare."""
    from shifu_tpu.data.shards import Shards
    s = Shards.open(os.path.join(mdir, "tmp", "CleanedData"))
    return [{k: np.asarray(v).copy() for k, v in d.items()}
            for d in s.iter_shards()]


# --------------------------------------------- pooled == serial bit-parity
@pytest.fixture
def parity_pair(tmp_path, fraud_csv):
    """(serial_dir, pooled_dir): the same scaffold, sub-1.0 sample rates
    (exercising the sample-order-commutes contract), not yet run."""
    from tests.conftest import _scaffold_model_set
    a = _scaffold_model_set(str(tmp_path / "serial"), fraud_csv)
    b = _scaffold_model_set(str(tmp_path / "pooled"), fraud_csv)
    for d in (a, b):
        _set_sample_rates(d, 0.7, 0.8)
    return a, b


def test_pool_and_cache_bit_parity(parity_pair):
    """stats + norm under the pooled/cached defaults reproduce the
    serial path's ColumnConfig and shard bytes exactly."""
    serial_dir, pooled_dir = parity_pair
    _serial_knobs()
    _run_init_stats_norm(serial_dir)
    environment.reset_for_tests()
    _run_init_stats_norm(pooled_dir)

    # the pooled leg actually engaged the one-parse plane
    assert os.path.isdir(os.path.join(pooled_dir, "tmp", "RawCache"))
    assert not os.path.isdir(os.path.join(serial_dir, "tmp", "RawCache"))

    with open(os.path.join(serial_dir, "ColumnConfig.json")) as f:
        cc_serial = f.read()
    with open(os.path.join(pooled_dir, "ColumnConfig.json")) as f:
        assert cc_serial == f.read()

    ndir_a = os.path.join(serial_dir, "tmp", "NormalizedData")
    ndir_b = os.path.join(pooled_dir, "tmp", "NormalizedData")
    files = sorted(f for f in os.listdir(ndir_a) if f.endswith(".npz"))
    assert files == sorted(f for f in os.listdir(ndir_b)
                           if f.endswith(".npz")) and files
    for f in files:
        da = dict(np.load(os.path.join(ndir_a, f)))
        db = dict(np.load(os.path.join(ndir_b, f)))
        assert da.keys() == db.keys()
        for k in da:
            assert da[k].tobytes() == db[k].tobytes(), (f, k)

    # clean plane: serial wrote npz, pooled wrote direct-to-wire — the
    # Shards reader views must still be bit-identical
    a, b = _clean_plane(serial_dir), _clean_plane(pooled_dir)
    assert len(a) == len(b) and a
    for sa, sb in zip(a, b):
        for k in ("bins", "y", "w"):
            assert sa[k].dtype == sb[k].dtype, k
            assert sa[k].tobytes() == sb[k].tobytes(), k


def test_wire_trained_model_bit_identical(parity_pair):
    """A GBT trained from the wire-only clean plane serializes byte-
    identically to one trained from the serial npz plane."""
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.pipeline.train import TrainProcessor
    serial_dir, pooled_dir = parity_pair
    _serial_knobs()
    _run_init_stats_norm(serial_dir)
    environment.reset_for_tests()
    _run_init_stats_norm(pooled_dir)

    digests = []
    for d in (serial_dir, pooled_dir):
        p = os.path.join(d, "ModelConfig.json")
        mc = ModelConfig.load(p)
        mc.train.algorithm = "GBT"
        mc.train.params = {"TreeNum": 3, "MaxDepth": 3, "Loss": "log"}
        mc.save(p)
        assert TrainProcessor(d, params={}).run() == 0
        mdir = os.path.join(d, "models")
        blobs = []
        for f in sorted(os.listdir(mdir)):
            with open(os.path.join(mdir, f), "rb") as fh:
                blobs.append(fh.read())
        digests.append(hashlib.md5(b"".join(blobs)).hexdigest())
    assert digests[0] == digests[1]


def test_pooled_quarantine_accounting_matches_serial(tmp_path):
    """The pooled producer IS the serial read loop: bad-input quarantine
    counts and the yielded row stream match the serial path exactly."""
    from shifu_tpu.data.reader import DataSource
    d = tmp_path / "data"
    d.mkdir()
    with open(d / "part-aaa.csv", "w") as f:
        for i in range(50):
            f.write(f"{i}|{i * 2}|good\n")
    with open(d / "part-bbb.csv.gz", "wb") as f:
        f.write(b"this is not gzip data\n" * 5)
    environment.set_property("shifu.data.badThreshold", "0.6")
    obs.set_enabled(True)

    def quarantined_after(workers: int):
        environment.set_property("shifu.ingest.parseWorkers", str(workers))
        obs.get_registry().reset()
        ds = DataSource(str(d), "|", header=["a", "b", "tag"])
        rows = sum(len(c) for c in ds.iter_chunks())
        return rows, obs.get_registry().counter(
            "data.quarantined_shards").value

    assert quarantined_after(0) == quarantined_after(4) == (50, 1.0)


# ------------------------------------------------- raw cache semantics
def _source_and_extractor(mdir: str):
    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.column_config import load_column_configs
    from shifu_tpu.data import DataSource
    from shifu_tpu.data.transform import DatasetTransformer
    mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
    ccs = load_column_configs(os.path.join(mdir, "ColumnConfig.json"))
    tf = DatasetTransformer(mc, ccs)
    src = DataSource(mc.dataSet.dataPath, mc.dataSet.dataDelimiter)
    return src, tf.extractor


@pytest.fixture
def inited_set(tmp_path, fraud_csv):
    """init+stats WITHOUT the raw cache — cache behavior under test."""
    from tests.conftest import _scaffold_model_set
    from shifu_tpu.pipeline.create import InitProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    mdir = _scaffold_model_set(str(tmp_path), fraud_csv)
    environment.set_property("shifu.ingest.rawCache", "false")
    assert InitProcessor(mdir).run() == 0
    assert StatsProcessor(mdir, params={}).run() == 0
    environment.reset_for_tests()
    return mdir


def test_cached_pass_never_touches_disk(inited_set):
    """Disk-pass regression guard: the first full pass parses the string
    plane (one ``ingest.disk_passes`` tick) and writes the cache; the
    second pass serves from mmap and ticks NOTHING but rawcache.hits."""
    from shifu_tpu.data.parsepool import iter_extracted
    src, ex = _source_and_extractor(inited_set)
    croot = os.path.join(inited_set, "tmp", "RawCache")
    obs.set_enabled(True)
    obs.get_registry().reset()
    reg = obs.get_registry()

    cold = [e.n for _, e in iter_extracted(src, ex, cache_root=croot)]
    assert reg.counter("ingest.disk_passes").value == 1.0
    assert reg.counter("rawcache.misses").value == 1.0
    assert reg.counter("rawcache.bytes_written").value > 0

    warm = [e.n for _, e in iter_extracted(src, ex, cache_root=croot)]
    assert warm == cold
    assert reg.counter("ingest.disk_passes").value == 1.0  # unchanged
    assert reg.counter("rawcache.hits").value == 1.0


def test_cache_served_chunks_bit_identical(inited_set):
    """Cache replay returns the exact arrays a fresh parse produces,
    including at a sub-1.0 sample rate (subset replayed post-parse)."""
    from shifu_tpu.data.parsepool import iter_extracted
    src, ex = _source_and_extractor(inited_set)
    croot = os.path.join(inited_set, "tmp", "RawCache")
    list(iter_extracted(src, ex, cache_root=croot))      # build cache
    for rate in (1.0, 0.6):
        environment.set_property("shifu.ingest.parseWorkers", "0")
        environment.set_property("shifu.ingest.rawCache", "false")
        serial = list(iter_extracted(src, ex, rate=rate))
        environment.reset_for_tests()
        cached = list(iter_extracted(src, ex, rate=rate,
                                     cache_root=croot))
        assert [ci for ci, _ in serial] == [ci for ci, _ in cached]
        for (_, a), (_, b) in zip(serial, cached):
            # provenance fields legitimately differ at rate < 1: the
            # serial order samples BEFORE parsing (raw_rows shrinks to
            # the sampled count), the replay keeps raw provenance — the
            # payload arrays are the bit-parity contract
            assert a.n == b.n
            if rate >= 1.0:
                assert a.raw_rows == b.raw_rows
                assert a.kept_idx.tobytes() == b.kept_idx.tobytes()
            assert a.target.tobytes() == b.target.tobytes()
            assert a.weight.tobytes() == b.weight.tobytes()
            assert a.numeric.tobytes() == b.numeric.tobytes()
            assert a.numeric_valid.tobytes() == b.numeric_valid.tobytes()
            assert a.categorical.keys() == b.categorical.keys()
            for k in a.categorical:
                assert list(a.categorical[k]) == list(b.categorical[k]), k


def test_cache_staleness_on_source_change(inited_set):
    """Rewriting the source invalidates the cache (signature pins name/
    size/mtime) — the next pass re-parses and re-commits."""
    from shifu_tpu.data.parsepool import cache_dir_for, iter_extracted
    from shifu_tpu.data.rawcache import open_raw_cache, source_signature
    src, ex = _source_and_extractor(inited_set)
    croot = os.path.join(inited_set, "tmp", "RawCache")
    list(iter_extracted(src, ex, cache_root=croot))
    sig = source_signature(src.files)
    cdir = cache_dir_for(croot, sig, ex)
    rd, writable = open_raw_cache(cdir, sig, ex, 262144)
    assert rd is not None

    # a stale signature (the source moved on) must refuse to serve
    stale = [list(s) for s in sig]
    stale[0][1] = (stale[0][1] or 0) + 1
    rd2, writable2 = open_raw_cache(cdir, stale, ex, 262144)
    assert rd2 is None and writable2


def test_cache_budget_abort_is_permanent(inited_set):
    """Overflowing ``rawCacheBudgetBytes`` abandons the cache mid-write
    and leaves a PERMANENT aborted marker: later passes neither serve
    nor re-attempt the build — but still stream correct chunks."""
    from shifu_tpu.data.parsepool import cache_dir_for, iter_extracted
    from shifu_tpu.data.rawcache import open_raw_cache, source_signature
    src, ex = _source_and_extractor(inited_set)
    croot = os.path.join(inited_set, "tmp", "RawCache")
    environment.set_property("shifu.ingest.rawCacheBudgetBytes", "64")
    first = [e.n for _, e in iter_extracted(src, ex, cache_root=croot)]
    assert first and sum(first) > 0

    sig = source_signature(src.files)
    cdir = cache_dir_for(croot, sig, ex)
    with open(os.path.join(cdir, "manifest.json")) as f:
        assert "budget" in json.load(f)["aborted"]
    rd, writable = open_raw_cache(cdir, sig, ex, 262144)
    assert rd is None and not writable

    # even with the budget raised, the marker pins the abort for this
    # exact source — no rebuild thrash, chunks still stream correctly
    environment.reset_for_tests()
    again = [e.n for _, e in iter_extracted(src, ex, cache_root=croot)]
    assert again == first
    with open(os.path.join(cdir, "manifest.json")) as f:
        assert json.load(f).get("aborted")


# ---------------------------------------------- e2e disk-pass regression
def test_cold_pipeline_saves_a_full_disk_pass(tmp_path, fraud_csv):
    """Telemetry-backed acceptance: a cold init→stats→norm under the
    one-parse defaults touches the raw string plane FEWER times than the
    serial seed path (stats pays the only parse; norm rides the cache),
    and the wire-only clean plane skips the npz write-through."""
    from tests.conftest import _scaffold_model_set

    from shifu_tpu.obs.report import load_blocks, trace_path

    def passes(leg: str, serial: bool) -> float:
        mdir = _scaffold_model_set(str(tmp_path / leg), fraud_csv)
        if serial:
            _serial_knobs()
        obs.set_enabled(True)
        obs.get_registry().reset()
        _run_init_stats_norm(mdir)
        # each step's flush snapshots-and-RESETS the registry — total
        # passes are summed from the per-step trace records
        v = sum(float(m.get("value") or 0)
                for block in load_blocks(trace_path(mdir))
                for m in block["metrics"]
                if m.get("name") == "ingest.disk_passes")
        obs.set_enabled(False)
        environment.reset_for_tests()
        if serial:
            assert os.path.exists(os.path.join(
                mdir, "tmp", "CleanedData", "part-00000.npz"))
        else:
            assert not os.path.exists(os.path.join(
                mdir, "tmp", "CleanedData", "part-00000.npz"))
        return v

    serial_passes = passes("serial", True)
    pooled_passes = passes("pooled", False)
    assert pooled_passes <= serial_passes - 1
