"""Generate the synthetic fraud-style tutorial dataset.

Mirrors the reference's bundled tutorial data shape (pipe-delimited,
mixed numeric/categorical, missing values, a weight column, bad/good
tags) so the quickstart below runs the whole pipeline end-to-end on
data that behaves like the real thing.

    python examples/make_fraud_data.py [out_dir] [n_rows]
"""

import os
import sys

import numpy as np


def make(out_dir: str = ".", n: int = 10000, seed: int = 7) -> str:
    rng = np.random.default_rng(seed)
    amount = rng.lognormal(3.0, 1.2, n)
    velocity = rng.poisson(3, n).astype(float)
    age_days = rng.integers(0, 2000, n).astype(float)
    country = rng.choice(["US", "GB", "DE", "CN", "BR"], n,
                         p=[.5, .15, .15, .1, .1])
    channel = rng.choice(["web", "app", "pos"], n)
    noise = rng.normal(0, 1, n)
    logit = (0.8 * np.log1p(amount) - 0.004 * age_days + 0.35 * velocity
             + (country == "BR") * 1.2 + (channel == "web") * 0.4 - 4.0)
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    tag = np.where(y == 1, "bad", "good")
    weight = np.round(rng.uniform(0.5, 2.0, n), 3)
    miss = rng.random(n) < 0.05                 # 5% missing amounts
    amount_s = np.round(amount, 4).astype(str)
    amount_s[miss] = ""
    rows = ["txn_id|amount|velocity|age_days|country|channel|noise|weight|tag"]
    for i in range(n):
        rows.append(
            f"t{i}|{amount_s[i]}|{velocity[i]:.0f}|{age_days[i]:.0f}|"
            f"{country[i]}|{channel[i]}|{noise[i]:.5f}|{weight[i]}|{tag[i]}")
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "fraud.csv")
    with open(path, "w") as f:
        f.write("\n".join(rows) + "\n")
    with open(os.path.join(out_dir, "meta.names"), "w") as f:
        f.write("txn_id\n")                     # id column = meta, not a feature
    print(f"wrote {n} rows -> {path}")
    return path


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else "."
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 10000
    make(out, n)
