"""Measure the reference baseline: score the reference's own trained golden
models (Encog NN bags + binary GBT forest) on the bundled cancer-judgement
data and record AUC — the numbers BASELINE.md's measured table requires.

The reference is JVM-only and this image has no Java, so LOCAL-mode
reference runs are impossible; the trained model files shipped under
``src/test/resources/example`` are the reference's executable output and
scoring them through our compute stack IS the measured reference baseline
(same weights, same data, same metric).

Run: python tools/measure_baseline.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

REF = "/root/reference/src/test/resources/example/cancer-judgement"
MODELSET = f"{REF}/ModelStore/ModelSet1"


def main() -> None:
    from shifu_tpu.config.column_config import load_column_configs
    from shifu_tpu.eval.metrics import evaluate_scores
    from shifu_tpu.models.nn import IndependentNNModel
    from shifu_tpu.models.reference_import import (load_encog_nn,
                                                   load_reference_psv,
                                                   load_reference_tree,
                                                   zscore_matrix)

    ccs = load_column_configs(f"{MODELSET}/ColumnConfig.json")
    cols = load_reference_psv(f"{REF}/DataStore/EvalSet1/part-00",
                              f"{REF}/DataStore/EvalSet1/.pig_header")
    target = (cols["diagnosis"] == "M").astype(np.float32)
    n = len(target)
    z, raw_by_col = zscore_matrix(cols, ccs)

    out = {}

    # ---- reference NN bag (5 Encog models, mean score)
    t0 = time.time()
    scores = np.zeros(n, np.float64)
    n_models = 0
    for i in range(32):
        path = f"{MODELSET}/models/model{i}.nn"
        if not os.path.exists(path):
            break
        spec, params = load_encog_nn(path)
        scores += IndependentNNModel(spec, params).compute(z)[:, 0]
        n_models += 1
    scores /= max(n_models, 1)
    nn_res = evaluate_scores(scores, target)
    out["reference_nn_bag_auc"] = round(float(nn_res.areaUnderRoc), 6)
    out["reference_nn_models"] = n_models
    out["reference_nn_score_seconds"] = round(time.time() - t0, 3)

    # ---- reference GBT forest (readablespec/model0.gbt, same columns)
    gbt_path = "/root/reference/src/test/resources/example/readablespec/model0.gbt"
    t0 = time.time()
    gbt = load_reference_tree(gbt_path)
    gbt_scores = gbt.compute(raw_by_col)
    gbt_res = evaluate_scores(gbt_scores.astype(np.float32), target)
    out["reference_gbt_auc"] = round(float(gbt_res.areaUnderRoc), 6)
    out["reference_gbt_trees"] = len(gbt.trees)
    out["reference_gbt_score_seconds"] = round(time.time() - t0, 3)

    out["eval_rows"] = n
    out["pos_rows"] = int(target.sum())

    # ---- CPU reference-class trainer throughput (Encog stand-in).
    # The reference's LOCAL mode is single-threaded Encog float64 backprop
    # (core/alg/NNTrainer.java); with no JVM in this image we measure the
    # same computation — float64 NumPy minibatch backprop on the bench
    # shapes — on this rig.  bench.py divides its TPU rows/s by this.
    out.update(measure_cpu_backprop())
    print(json.dumps(out, indent=1))


def measure_cpu_backprop(n_features: int = 256, hidden=(512, 256),
                         batch: int = 4096, steps: int = 8) -> dict:
    rng = np.random.default_rng(0)
    dims = [n_features, *hidden, 1]
    ws = [rng.normal(size=(a, b)) / np.sqrt(a)
          for a, b in zip(dims[:-1], dims[1:])]
    bs = [np.zeros(b) for b in dims[1:]]
    x = rng.normal(size=(batch, n_features))
    y = (rng.random((batch, 1)) < 0.5).astype(np.float64)

    def step(lr=1e-3):
        acts = [x]
        h = x
        for w, b in zip(ws[:-1], bs[:-1]):
            h = np.maximum(h @ w + b, 0.0)
            acts.append(h)
        out_ = 1.0 / (1.0 + np.exp(-(h @ ws[-1] + bs[-1])))
        g = (out_ - y) / batch
        for i in range(len(ws) - 1, -1, -1):
            gw = acts[i].T @ g
            gb = g.sum(axis=0)
            if i > 0:
                g = (g @ ws[i].T) * (acts[i] > 0)
            ws[i] -= lr * gw
            bs[i] -= lr * gb

    step()                                     # warm caches
    t0 = time.time()
    for _ in range(steps):
        step()
    dt = time.time() - t0
    return {"cpu_backprop_rows_per_sec": round(steps * batch / dt, 1),
            "cpu_backprop_shapes": f"{n_features}->{hidden}->1 b{batch} f64"}


if __name__ == "__main__":
    main()
