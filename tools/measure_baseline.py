"""Measure the reference baseline: score the reference's own trained golden
models (Encog NN bags + binary GBT forest) on the bundled cancer-judgement
data and record AUC — the numbers BASELINE.md's measured table requires.

The reference is JVM-only and this image has no Java, so LOCAL-mode
reference runs are impossible; the trained model files shipped under
``src/test/resources/example`` are the reference's executable output and
scoring them through our compute stack IS the measured reference baseline
(same weights, same data, same metric).

Run: python tools/measure_baseline.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

REF = "/root/reference/src/test/resources/example/cancer-judgement"
MODELSET = f"{REF}/ModelStore/ModelSet1"


def main() -> None:
    from shifu_tpu.config.column_config import load_column_configs
    from shifu_tpu.eval.metrics import evaluate_scores
    from shifu_tpu.models.nn import IndependentNNModel
    from shifu_tpu.models.reference_import import (load_encog_nn,
                                                   load_reference_psv,
                                                   load_reference_tree,
                                                   zscore_matrix)

    ccs = load_column_configs(f"{MODELSET}/ColumnConfig.json")
    cols = load_reference_psv(f"{REF}/DataStore/EvalSet1/part-00",
                              f"{REF}/DataStore/EvalSet1/.pig_header")
    target = (cols["diagnosis"] == "M").astype(np.float32)
    n = len(target)
    z, raw_by_col = zscore_matrix(cols, ccs)

    out = {}

    # ---- reference NN bag (5 Encog models, mean score)
    t0 = time.time()
    scores = np.zeros(n, np.float64)
    n_models = 0
    for i in range(32):
        path = f"{MODELSET}/models/model{i}.nn"
        if not os.path.exists(path):
            break
        spec, params = load_encog_nn(path)
        scores += IndependentNNModel(spec, params).compute(z)[:, 0]
        n_models += 1
    scores /= max(n_models, 1)
    nn_res = evaluate_scores(scores, target)
    out["reference_nn_bag_auc"] = round(float(nn_res.areaUnderRoc), 6)
    out["reference_nn_models"] = n_models
    out["reference_nn_score_seconds"] = round(time.time() - t0, 3)

    # ---- reference GBT forest (readablespec/model0.gbt, same columns)
    gbt_path = "/root/reference/src/test/resources/example/readablespec/model0.gbt"
    t0 = time.time()
    gbt = load_reference_tree(gbt_path)
    gbt_scores = gbt.compute(raw_by_col)
    gbt_res = evaluate_scores(gbt_scores.astype(np.float32), target)
    out["reference_gbt_auc"] = round(float(gbt_res.areaUnderRoc), 6)
    out["reference_gbt_trees"] = len(gbt.trees)
    out["reference_gbt_score_seconds"] = round(time.time() - t0, 3)

    out["eval_rows"] = n
    out["pos_rows"] = int(target.sum())

    # ---- CPU reference-class trainer throughput (Encog stand-in).
    # The reference's LOCAL mode is single-threaded Encog float64 backprop
    # (core/alg/NNTrainer.java); with no JVM in this image we measure the
    # same computation — float64 NumPy minibatch backprop on the bench
    # shapes — on this rig.  bench.py divides its TPU rows/s by this.
    out.update(measure_cpu_backprop())
    out.update(measure_cpu_tree_trainer())
    out.update(measure_cpu_scalar_scorer())
    out.update(measure_cpu_stats_worker())
    out.update(measure_cpu_varsel_worker())
    print(json.dumps(out, indent=1))


def measure_cpu_backprop(n_features: int = 256, hidden=(512, 256),
                         batch: int = 4096, steps: int = 8) -> dict:
    rng = np.random.default_rng(0)
    dims = [n_features, *hidden, 1]
    ws = [rng.normal(size=(a, b)) / np.sqrt(a)
          for a, b in zip(dims[:-1], dims[1:])]
    bs = [np.zeros(b) for b in dims[1:]]
    x = rng.normal(size=(batch, n_features))
    y = (rng.random((batch, 1)) < 0.5).astype(np.float64)

    def step(lr=1e-3):
        acts = [x]
        h = x
        for w, b in zip(ws[:-1], bs[:-1]):
            h = np.maximum(h @ w + b, 0.0)
            acts.append(h)
        out_ = 1.0 / (1.0 + np.exp(-(h @ ws[-1] + bs[-1])))
        g = (out_ - y) / batch
        for i in range(len(ws) - 1, -1, -1):
            gw = acts[i].T @ g
            gb = g.sum(axis=0)
            if i > 0:
                g = (g @ ws[i].T) * (acts[i] > 0)
            ws[i] -= lr * gw
            bs[i] -= lr * gb

    step()                                     # warm caches
    t0 = time.time()
    for _ in range(steps):
        step()
    dt = time.time() - t0
    return {"cpu_backprop_rows_per_sec": round(steps * batch / dt, 1),
            "cpu_backprop_shapes": f"{n_features}->{hidden}->1 b{batch} f64"}


def measure_cpu_tree_trainer(n_rows: int = 1 << 15, n_features: int = 64,
                             n_bins: int = 64, depth: int = 6,
                             trees: int = 3) -> dict:
    """Single-worker reference-style GBT trainer throughput.

    The reference's DTWorker accumulates per-(node, feature, bin) stats
    with a scalar hot loop (``DTWorker.java:763-884``) and DTMaster scans
    splits per level (``DTMaster.java:274-533``); in this JVM-less image
    the same per-level histogram+split computation runs as float64 NumPy
    (scatter-add via np.add.at per feature — the same memory-bound access
    pattern, vectorized where Java would loop, i.e. generous to the
    reference).  Measured at the bench feature/bin/depth shapes; bench.py
    divides its device rows*trees/s by this x the north-star worker count.
    """
    rng = np.random.default_rng(0)
    bins = rng.integers(0, n_bins, size=(n_rows, n_features)).astype(np.int32)
    y = (rng.random(n_rows) < 0.3).astype(np.float64)
    f = np.zeros(n_rows)
    lr = 0.1

    def train_one_tree():
        grad = y - 1.0 / (1.0 + np.exp(-f))
        stats = np.stack([np.ones(n_rows), grad, grad * grad], axis=1)
        node_idx = np.zeros(n_rows, np.int64)
        feat = {}
        thr = {}
        leaf = np.zeros(2 ** (depth + 1) - 1)
        for level in range(depth):
            n_nodes = 1 << level
            hist = np.zeros((n_nodes, n_features, n_bins, 3))
            for c in range(n_features):          # DTWorker per-feature loop
                np.add.at(hist[:, c], (node_idx, bins[:, c]), stats)
            # DTMaster variance split scan per (node, feature)
            w = hist[..., 0]
            wy = hist[..., 1]
            cw = np.cumsum(w, axis=-1)
            cwy = np.cumsum(wy, axis=-1)
            tw, twy = cw[..., -1:], cwy[..., -1:]
            score = (cwy ** 2 / np.maximum(cw, 1e-12)
                     + (twy - cwy) ** 2 / np.maximum(tw - cw, 1e-12))
            score[..., -1] = -np.inf
            k = score.reshape(n_nodes, -1).argmax(axis=1)
            base = n_nodes - 1
            for node in range(n_nodes):
                feat[base + node] = k[node] // n_bins
                thr[base + node] = k[node] % n_bins
            nf = np.array([feat[base + v] for v in range(n_nodes)])
            nt = np.array([thr[base + v] for v in range(n_nodes)])
            row_bin = bins[np.arange(n_rows), nf[node_idx]]
            node_idx = 2 * node_idx + (row_bin > nt[node_idx])
        # leaves at the bottom level
        n_nodes = 1 << depth
        sw = np.zeros(n_nodes)
        swy = np.zeros(n_nodes)
        np.add.at(sw, node_idx, stats[:, 0])
        np.add.at(swy, node_idx, stats[:, 1])
        leaf_vals = swy / np.maximum(sw, 1e-12)
        return f + lr * leaf_vals[node_idx], leaf

    train_one_tree()                               # warm caches
    t0 = time.time()
    for _ in range(trees):
        f, _ = train_one_tree()
    dt = time.time() - t0
    return {"cpu_tree_rows_trees_per_sec": round(trees * n_rows / dt, 1),
            "cpu_tree_shapes": (f"{n_rows}x{n_features} b{n_bins} "
                                f"d{depth} f64 np.add.at")}


def measure_cpu_scalar_scorer(n_rows: int = 2000, n_features: int = 256,
                              hidden=(512, 256), n_models: int = 5) -> dict:
    """Reference-style eval throughput: ``core/Scorer.java:163-200`` scores
    ONE normalized row at a time across the bagged models; the confusion
    sweep then sorts on the host (``ConfusionMatrix.java:62``).  Stand-in:
    per-row float64 NumPy forwards (vectorized matvecs where Encog loops —
    generous) + a host argsort sweep, single thread."""
    rng = np.random.default_rng(0)
    dims = [n_features, *hidden, 1]
    models = []
    for _ in range(n_models):
        models.append(([rng.normal(size=(a, b)) / np.sqrt(a)
                        for a, b in zip(dims[:-1], dims[1:])],
                       [np.zeros(b) for b in dims[1:]]))
    x = rng.normal(size=(n_rows, n_features))
    y = (rng.random(n_rows) < 0.3).astype(np.float64)

    def score_row(row):
        s = 0.0
        for ws, bs in models:
            h = row
            for w, b in zip(ws[:-1], bs[:-1]):
                h = np.maximum(h @ w + b, 0.0)
            s += 1.0 / (1.0 + np.exp(-(h @ ws[-1] + bs[-1])[0]))
        return s / n_models

    score_row(x[0])                                # warm caches
    t0 = time.time()
    scores = np.fromiter((score_row(x[i]) for i in range(n_rows)),
                         np.float64, count=n_rows)
    order = np.argsort(-scores, kind="stable")
    np.cumsum(y[order])
    dt = time.time() - t0
    return {"cpu_scalar_score_rows_per_sec": round(n_rows / dt, 1),
            "cpu_scalar_score_shapes":
                f"{n_features}->{hidden}->1 x{n_models} models f64 per-row"}


def measure_cpu_stats_worker(n_rows: int = 1 << 15, n_cols: int = 256,
                             num_buckets: int = 4096) -> dict:
    """Single-thread reference-style stats pass: per-column moments + a
    (bucket, pos/neg, weighted) fine-histogram accumulated row-set by
    row-set with np.add.at — the ``UpdateBinningInfoMapper.java:71`` /
    ``BinningPartialDataUDF`` math without the Hadoop plumbing, same
    measurement convention as the tree/scorer baselines."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, n_cols))
    valid = rng.random((n_rows, n_cols)) > 0.05
    t = (rng.random(n_rows) < 0.3)
    w = rng.uniform(0.5, 2.0, n_rows)

    def one_pass():
        hist = np.zeros((n_cols, num_buckets, 4))
        for c in range(n_cols):
            v = valid[:, c]
            xc = x[v, c]
            # pass 1: moments + range
            xc.sum(); (xc * xc).sum(); xc.min(); xc.max()
            lo, hi = xc.min(), xc.max()
            idx = np.clip(((xc - lo) * (num_buckets / max(hi - lo, 1e-30))),
                          0, num_buckets - 1).astype(np.int64)
            tp = t[v]
            wv = w[v]
            np.add.at(hist[c, :, 0], idx[tp], 1.0)
            np.add.at(hist[c, :, 1], idx[~tp], 1.0)
            np.add.at(hist[c, :, 2], idx[tp], wv[tp])
            np.add.at(hist[c, :, 3], idx[~tp], wv[~tp])
        return hist

    one_pass()                                   # warm caches
    t0 = time.time()
    one_pass()
    dt = time.time() - t0
    return {"cpu_stats_rows_per_sec": round(n_rows / dt, 1),
            "cpu_stats_shapes":
                f"{n_rows} rows x {n_cols} cols x {num_buckets} buckets, "
                "np.add.at per column, single thread"}


def measure_cpu_varsel_worker(n_rows: int = 1 << 15, n_features: int = 256,
                              hidden=(16,), candidates: int = 8) -> dict:
    """Single-worker reference-style SE sensitivity loop: the varselect MR
    job (``VarSelectMapper.java:93-120``) re-scores every record with one
    candidate column frozen to its mean through the trained NN and
    accumulates the squared-error rise.  Stand-in: f64 NumPy forwards at
    the varsel bench shapes (fraud-width feature plane, wrapper-scale
    1x16-tanh net — the model class SE/ST actually scores), one frozen
    column at a time (vectorized matvecs where the mapper loops rows —
    generous), single thread.  Rate is rows*candidates/s; bench.py
    divides its device rate by this x the north-star worker count."""
    rng = np.random.default_rng(0)
    dims = [n_features, *hidden, 1]
    ws = [rng.normal(size=(a, b)) / np.sqrt(a)
          for a, b in zip(dims[:-1], dims[1:])]
    bs = [np.zeros(b) for b in dims[1:]]
    x = rng.normal(size=(n_rows, n_features))
    y = (rng.random((n_rows, 1)) < 0.3).astype(np.float64)

    def fwd(m):
        h = m
        for w, b in zip(ws[:-1], bs[:-1]):
            h = np.tanh(h @ w + b)
        return 1.0 / (1.0 + np.exp(-(h @ ws[-1] + bs[-1])))

    mean_x = x.mean(axis=0)
    base = ((fwd(x) - y) ** 2).mean()

    def one_candidate(c):
        xf = x.copy()
        xf[:, c] = mean_x[c]
        return ((fwd(xf) - y) ** 2).mean() - base

    one_candidate(0)                             # warm caches
    t0 = time.time()
    for c in range(1, 1 + candidates):
        one_candidate(c)
    dt = time.time() - t0
    return {"cpu_varsel_rows_cols_per_sec":
                round(candidates * n_rows / dt, 1),
            "cpu_varsel_shapes":
                f"{n_rows} rows x {n_features}->{hidden}->1 f64, "
                f"{candidates} frozen-column forwards, single thread"}


if __name__ == "__main__":
    main()
