"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: end-to-end `train` throughput (rows/sec) of the flagship NN trainer
on a synthetic fraud-style dataset, vs the YARN-cluster-derived baseline.
Runs on whatever jax.devices() offers (one real TPU chip under the driver).

``--plane tail`` runs ONLY the disk-tail streamed-GBT benchmark (the
out-of-core ingest path) — seconds instead of minutes, for iterating on
the spill-cache / H2D pipeline in isolation.

``--compare OLD.json NEW.json [--threshold 0.9]`` runs NO benchmark:
it diffs two recorded payloads (raw bench output or the driver's
BENCH_r0N wrappers) metric-by-metric, prints a regression table, and
exits 2 when any tracked throughput metric fell below threshold x old
or any tracked latency metric (*_p50*/*_p99* — lower is better) rose
above old / threshold — the reader for the in-repo BENCH_r01..
trajectory.

With SHIFU_TPU_TELEMETRY=1 the per-plane numbers also land as a telemetry
JSONL block under ./telemetry/ (same schema as the pipeline steps — the
schema-version handshake is enforced inside run_benchmark, which fails
loudly on a bench/obs schema mismatch).
"""

import argparse
import json
import sys


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--plane",
                    choices=("all", "tail", "rf-repeat", "e2e", "resume",
                             "varsel", "serve", "fleet", "overload",
                             "multihost", "refresh", "quality",
                             "ingest"),
                    default="all",
                    help="'tail' = quick disk-tail streamed-GBT bench; "
                         "'rf-repeat' = RF variance triage (cold-compile "
                         "vs warm-window decomposition); 'e2e' = scripted "
                         "init->stats->norm->train(GBT+NN)->eval rehearsal "
                         "(SHIFU_BENCH_E2E_ROWS sets the row count, "
                         "default 10M); 'resume' = restart-recovery "
                         "overhead (time-to-first-tree from a mid-forest "
                         "checkpoint vs cold/warm starts); 'varsel' = "
                         "streamed mask-batched SE sensitivity vs the "
                         "single-worker per-column loop at identical "
                         "selections; 'serve' = online-serving plane "
                         "(AOT padded-bucket scorer + micro-batcher: "
                         "sustained QPS, p50/p99 per offered load, "
                         "zero-recompile guard); 'fleet' = subprocess "
                         "replica fleets behind the HTTP router "
                         "(1/2/4-replica aggregate QPS + the replica-"
                         "SIGKILL requeue drill); 'overload' = overload-"
                         "protection plane (bounded-admission server at "
                         "1x/2x/4x of measured saturation: goodput "
                         "guarded >= 0.8x saturation at 2x offered "
                         "load, coded sheds, zero hung clients); "
                         "'multihost' = elastic "
                         "multi-controller plane (1/2/4-process quorum-"
                         "gated scaling curve + time-to-recover after a "
                         "mid-train controller kill); 'refresh' = "
                         "continual-refresh plane (drift-triggered warm "
                         "retrain time-to-promoted vs a cold full-"
                         "pipeline retrain on the same drifted stream, "
                         "with a no-SLO-page-during-swap guard); "
                         "'quality' = model-quality observability plane "
                         "(scorelog on-vs-off saturation QPS, guarded "
                         ">= 0.95x, + time-to-detect a synthetic "
                         "label flip via the live-AUC monitor); "
                         "'ingest' = one-parse offline pipeline "
                         "(serial-vs-pooled stats+norm wall-clock on "
                         "the same generated shards: stats_throughput/"
                         "norm_throughput are the pooled raw-rows/sec, "
                         "SHIFU_BENCH_INGEST_ROWS sets the row count, "
                         "default 2M)")
    ap.add_argument("--compare", nargs="*", metavar="PAYLOAD.json",
                    default=None,
                    help="regression-diff two bench payloads (raw JSON "
                         "lines or BENCH_r0N wrappers) metric-by-metric; "
                         "exits 2 when any tracked throughput metric "
                         "falls below --threshold x old — runs NO "
                         "benchmark.  With NO arguments, auto-diffs the "
                         "two newest BENCH_r*.json in the repo root "
                         "(errors cleanly when fewer than two exist)")
    ap.add_argument("--threshold", type=float, default=0.9,
                    help="--compare regression threshold (default 0.9: "
                         "new >= 0.9 x old passes)")
    args = ap.parse_args()

    if args.compare is not None:
        from shifu_tpu.bench import resolve_compare_paths, run_compare
        try:
            old_path, new_path = resolve_compare_paths(args.compare)
        except ValueError as e:
            print(f"bench: {e}", file=sys.stderr)
            sys.exit(2)
        sys.exit(run_compare(old_path, new_path,
                             threshold=args.threshold))

    from shifu_tpu import obs
    from shifu_tpu.bench import run_benchmark

    try:
        result = run_benchmark(plane=args.plane)
    except RuntimeError as e:
        # schema-version handshake failure (bench/obs drift) must land as
        # a nonzero exit for CI, not a stack trace mistaken for a crash
        if "schema" in str(e):
            print(f"bench: {e}", file=sys.stderr)
            sys.exit(2)
        raise
    if obs.enabled():
        # the bench gauges land in BOTH formats: the JSONL trace block
        # and the same OpenMetrics/JSON snapshot the steps export, so an
        # external scraper and BENCH_r0N consumers read one schema
        obs.write_metrics_files("telemetry", step="BENCH")
        obs.flush("telemetry/trace.jsonl", step="BENCH",
                  extra_meta={"headline": result["metric"]})
    print(json.dumps(result))


if __name__ == "__main__":
    main()
