"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: end-to-end `train` throughput (rows/sec) of the flagship NN trainer
on a synthetic fraud-style dataset, vs the YARN-cluster-derived baseline.
Runs on whatever jax.devices() offers (one real TPU chip under the driver).
"""

import json
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from shifu_tpu.bench import run_benchmark

    result = run_benchmark()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
