"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: end-to-end `train` throughput (rows/sec) of the flagship NN trainer
on a synthetic fraud-style dataset, vs the YARN-cluster-derived baseline.
Runs on whatever jax.devices() offers (one real TPU chip under the driver).

``--plane tail`` runs ONLY the disk-tail streamed-GBT benchmark (the
out-of-core ingest path) — seconds instead of minutes, for iterating on
the spill-cache / H2D pipeline in isolation.

With SHIFU_TPU_TELEMETRY=1 the per-plane numbers also land as a telemetry
JSONL block under ./telemetry/ (same schema as the pipeline steps — the
schema-version handshake is enforced inside run_benchmark, which fails
loudly on a bench/obs schema mismatch).
"""

import argparse
import json
import sys


def main() -> None:
    from shifu_tpu import obs
    from shifu_tpu.bench import run_benchmark

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--plane",
                    choices=("all", "tail", "rf-repeat", "e2e", "resume",
                             "varsel"),
                    default="all",
                    help="'tail' = quick disk-tail streamed-GBT bench; "
                         "'rf-repeat' = RF variance triage (cold-compile "
                         "vs warm-window decomposition); 'e2e' = scripted "
                         "init->stats->norm->train(GBT+NN)->eval rehearsal "
                         "(SHIFU_BENCH_E2E_ROWS sets the row count, "
                         "default 10M); 'resume' = restart-recovery "
                         "overhead (time-to-first-tree from a mid-forest "
                         "checkpoint vs cold/warm starts); 'varsel' = "
                         "streamed mask-batched SE sensitivity vs the "
                         "single-worker per-column loop at identical "
                         "selections")
    args = ap.parse_args()

    try:
        result = run_benchmark(plane=args.plane)
    except RuntimeError as e:
        # schema-version handshake failure (bench/obs drift) must land as
        # a nonzero exit for CI, not a stack trace mistaken for a crash
        if "schema" in str(e):
            print(f"bench: {e}", file=sys.stderr)
            sys.exit(2)
        raise
    if obs.enabled():
        obs.flush("telemetry/trace.jsonl", step="BENCH",
                  extra_meta={"headline": result["metric"]})
    print(json.dumps(result))


if __name__ == "__main__":
    main()
