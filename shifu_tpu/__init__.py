"""shifu-tpu: a TPU-native, end-to-end tabular ML pipeline framework.

A from-scratch rebuild of the capabilities of DataS07/shifu (reference:
``/root/reference``) on JAX/XLA/pjit/Pallas: the pipeline
``new -> init -> stats -> norm -> varselect -> train -> posttrain -> eval -> export``
for fraud-style tabular modeling, where the reference's Hadoop/Pig/Guagua/Encog
stack collapses into

- a columnar data plane (sharded readers -> device arrays),
- a compiled compute plane (jit/pjit step functions, Pallas kernels), and
- a pipeline driver speaking the same ``ModelConfig.json`` / ``ColumnConfig.json``
  contract as the reference (reference: ``container/obj/ModelConfig.java:57-95``).
"""

import logging as _logging
import os as _os

__version__ = "0.1.0"

_LOG_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"

# library convention: the package logger never prints unless the APP (CLI,
# pytest, an embedding service) configures handlers — programmatic use of
# the processors/trainers stays silent instead of spraying lastResort
# stderr lines or double-configuring the root logger
_logging.getLogger(__name__).addHandler(_logging.NullHandler())


def _env_level():
    """``SHIFU_TPU_LOG=<level>`` (DEBUG/INFO/WARNING/... or a number)."""
    name = _os.environ.get("SHIFU_TPU_LOG", "").strip()
    if not name:
        return None
    if name.isdigit():
        return int(name)
    return getattr(_logging, name.upper(), None)


# library entry point honoring SHIFU_TPU_LOG: importing shifu_tpu under
# pytest/bench/notebooks with the env var set attaches ONE stream handler
# to the package logger (root logging untouched, so an app's own config
# never double-prints)
_env_handler = None
if _env_level() is not None:
    _env_handler = _logging.StreamHandler()
    _env_handler.setFormatter(_logging.Formatter(_LOG_FORMAT))
    _pkg = _logging.getLogger(__name__)
    _pkg.addHandler(_env_handler)
    _pkg.setLevel(_env_level())


def configure_logging(verbose: bool = False) -> None:
    """CLI entry point: configure root logging once.  Level precedence:
    ``SHIFU_TPU_LOG`` env override > ``-v`` > INFO.  Removes the
    library-entry env handler first so CLI runs never double-print."""
    global _env_handler
    level = _env_level()
    if level is None:
        level = _logging.DEBUG if verbose else _logging.INFO
    pkg = _logging.getLogger(__name__)
    if _env_handler is not None:
        pkg.removeHandler(_env_handler)
        _env_handler = None
    _logging.basicConfig(level=level, format=_LOG_FORMAT)
    pkg.setLevel(level)
