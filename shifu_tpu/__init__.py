"""shifu-tpu: a TPU-native, end-to-end tabular ML pipeline framework.

A from-scratch rebuild of the capabilities of DataS07/shifu (reference:
``/root/reference``) on JAX/XLA/pjit/Pallas: the pipeline
``new -> init -> stats -> norm -> varselect -> train -> posttrain -> eval -> export``
for fraud-style tabular modeling, where the reference's Hadoop/Pig/Guagua/Encog
stack collapses into

- a columnar data plane (sharded readers -> device arrays),
- a compiled compute plane (jit/pjit step functions, Pallas kernels), and
- a pipeline driver speaking the same ``ModelConfig.json`` / ``ColumnConfig.json``
  contract as the reference (reference: ``container/obj/ModelConfig.java:57-95``).
"""

__version__ = "0.1.0"
