"""Mesh/sharding substrate (mesh.py) + the elastic multi-controller
step protocol (elastic.py)."""

from .elastic import (ElasticConfig, ElasticContext,  # noqa: F401
                      elastic_context_for, elastic_enabled)
from .mesh import device_mesh  # noqa: F401
