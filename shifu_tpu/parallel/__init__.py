"""Mesh/sharding substrate (see mesh.py)."""

from .mesh import device_mesh  # noqa: F401
