"""Device mesh substrate — the Guagua-BSP replacement.

The reference's distributed backbone is a Guagua master/worker BSP loop on
YARN (workers compute local gradients/histograms, master sums and broadcasts;
``NNMaster.java:240-286``, ``TrainModelProcessor.java:661-1029``).  Here that
whole stack collapses into SPMD under ``jax.jit`` over a ``Mesh``:

- the ``data`` axis shards rows (the worker shards); gradient aggregation is
  the ``psum`` XLA inserts for replicated-param grads — the master's
  accumulate step, but on ICI instead of ZooKeeper/Netty;
- the ``ensemble`` axis shards bagging/grid-search members (the reference's
  N parallel YARN jobs, ``TrainModelProcessor.java:684-945``) — members train
  simultaneously as one vmapped program, sharded across devices.
- multi-host: after :func:`initialize_distributed`, ``device_mesh()`` spans
  the fleet (jax.devices() is global, host-major), so the data axis keeps a
  host's rows on its own ICI domain and only psum combines cross DCN; with
  n_ensemble = n_hosts each member pins to one host.

Quorum/straggler logic (97% + 2s timeout) has no analogue: the mesh is
synchronous.  Fail-over maps to checkpoint/restore instead.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np


def device_mesh(n_ensemble: int = 1,
                devices: Optional[Sequence] = None) -> "jax.sharding.Mesh":
    """Build a 2D ``(ensemble, data)`` mesh over the available devices.

    The ensemble axis gets ``gcd(n_devices, n_ensemble)`` devices (never more
    than there are members to train); the rest go to data parallelism.  With
    one ensemble member this degenerates to a pure data-parallel layout.
    """
    import jax
    from jax.sharding import Mesh

    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    e = math.gcd(n, max(1, n_ensemble))
    grid = np.asarray(devs).reshape(e, n // e)
    return Mesh(grid, ("ensemble", "data"))


def pad_rows(n: int, multiple: int) -> int:
    """Rows to add so n divides the data-axis extent."""
    r = n % multiple
    return 0 if r == 0 else multiple - r


def shard_chunk_rows(mesh, *arrays):
    """Device-put per-row chunk arrays (1D [R] or 2D [R, C]) with rows
    sharded over the mesh ``data`` axis, zero-padded so every shard is
    equal-sized (shard_mapped kernels need that; zero rows are invalid/
    weightless by construction at every call site).  Returns the device
    arrays plus a live-row bool mask marking real rows — ``None`` mask
    (and plain single-device arrays) when ``mesh`` is None or its data
    axis is 1.  This is the stats/eval-plane row scatter, the counterpart
    of the trainers' ``_shard_rows`` (reference: each Guagua/MR worker
    reads its own input split, ``ShifuInputFormat``)."""
    import jax.numpy as jnp

    ds = int(mesh.shape["data"]) if mesh is not None else 1
    if ds <= 1:
        return tuple(jnp.asarray(a) for a in arrays) + (None,)
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    n = arrays[0].shape[0]
    pad = pad_rows(n, ds)
    live = np.ones(n, bool)          # padded below like every other array
    out = []
    for a in list(arrays) + [live]:
        a = np.asarray(a)
        if pad:
            a = np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
        spec = P("data") if a.ndim == 1 else P("data", None)
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)


# ------------------------------------------------------------- multi-host
def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap — the reference's Guagua/ZooKeeper coordination
    role (``GuaguaConstants`` zk wiring, ``TrainModelProcessor.java``
    cluster submit): after this, ``jax.devices()`` is the GLOBAL device set
    across hosts, a ``device_mesh`` spans them, and XLA routes collectives
    over ICI within a host and DCN across hosts.

    Args default from SHIFU_COORDINATOR / SHIFU_NUM_PROCESSES /
    SHIFU_PROCESS_ID (set by the launcher, one process per host).

    Coordinator connect rides the same bounded exponential-backoff+jitter
    ladder as :func:`ioutil.io_retry` (``shifu.io.retries`` attempts,
    ``shifu.io.retryBaseMs`` base; counter ``dcn.connect_retries``) —
    a controller restarted into a live job retries while the coordinator
    re-admits it, and an exhausted ladder raises a CODED error instead
    of hanging the launcher.
    """
    import os
    import random
    import time

    coordinator = coordinator or os.environ.get("SHIFU_COORDINATOR")
    if coordinator is None:
        return      # single-host run: stays a true no-op (no jax import)
    import jax
    if num_processes is None:
        num_processes = int(os.environ["SHIFU_NUM_PROCESSES"])
    if process_id is None:
        process_id = int(os.environ["SHIFU_PROCESS_ID"])
    from ..config import environment
    attempts = max(0, environment.get_int("shifu.io.retries", 3)) + 1
    base = environment.get_int("shifu.io.retryBaseMs", 50) / 1000.0
    for attempt in range(attempts):
        try:
            jax.distributed.initialize(coordinator,
                                       num_processes=num_processes,
                                       process_id=process_id)
            return
        except (OSError, RuntimeError, ValueError) as e:
            # jaxlib surfaces connect/handshake failures as RuntimeError
            # (XlaRuntimeError subclasses it); ValueError covers a
            # malformed address.  A ladder that ends still raises CODED.
            if attempt + 1 >= attempts:
                from ..config.errors import ErrorCode, ShifuError
                raise ShifuError(
                    ErrorCode.ERROR_DCN_CONNECT,
                    f"coordinator {coordinator} (process "
                    f"{process_id}/{num_processes}) after {attempts} "
                    f"attempt(s): {e}") from e
            from .. import obs
            # retry ladder only spins on coordinator weather — the
            # factory lookup is as cold as the backoff sleep
            obs.counter("dcn.connect_retries").inc()  # shifu-lint: disable=telemetry-guard
            delay = base * (2 ** attempt) * (1.0 + random.random())
            import logging
            logging.getLogger(__name__).warning(
                "jax.distributed.initialize(%s) failed (attempt %d/%d, "
                "retrying in %.0f ms): %s", coordinator, attempt + 1,
                attempts, delay * 1000, e)
            time.sleep(delay)


def shard_rows_from_local(mesh, local_rows: "np.ndarray"):
    """Build the GLOBAL row-sharded array from THIS host's row block — the
    multi-host data feed (each host reads its own shard files, reference
    worker-split role of ``ShifuInputFormat``).  Rows concatenate in
    process order; the per-host block must divide the host's share of the
    data axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P("data") if local_rows.ndim == 1 else P("data", None)
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local_rows)
