"""Device mesh substrate — the Guagua-BSP replacement.

The reference's distributed backbone is a Guagua master/worker BSP loop on
YARN (workers compute local gradients/histograms, master sums and broadcasts;
``NNMaster.java:240-286``, ``TrainModelProcessor.java:661-1029``).  Here that
whole stack collapses into SPMD under ``jax.jit`` over a ``Mesh``:

- the ``data`` axis shards rows (the worker shards); gradient aggregation is
  the ``psum`` XLA inserts for replicated-param grads — the master's
  accumulate step, but on ICI instead of ZooKeeper/Netty;
- the ``ensemble`` axis shards bagging/grid-search members (the reference's
  N parallel YARN jobs, ``TrainModelProcessor.java:684-945``) — members train
  simultaneously as one vmapped program, sharded across devices.

Quorum/straggler logic (97% + 2s timeout) has no analogue: the mesh is
synchronous.  Fail-over maps to checkpoint/restore instead.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np


def device_mesh(n_ensemble: int = 1,
                devices: Optional[Sequence] = None) -> "jax.sharding.Mesh":
    """Build a 2D ``(ensemble, data)`` mesh over the available devices.

    The ensemble axis gets ``gcd(n_devices, n_ensemble)`` devices (never more
    than there are members to train); the rest go to data parallelism.  With
    one ensemble member this degenerates to a pure data-parallel layout.
    """
    import jax
    from jax.sharding import Mesh

    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    e = math.gcd(n, max(1, n_ensemble))
    grid = np.asarray(devs).reshape(e, n // e)
    return Mesh(grid, ("ensemble", "data"))


def pad_rows(n: int, multiple: int) -> int:
    """Rows to add so n divides the data-axis extent."""
    r = n % multiple
    return 0 if r == 0 else multiple - r
