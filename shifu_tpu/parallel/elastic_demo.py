"""Self-contained elastic-training demo controller — one process of an
N-controller quorum-gated NN job over a shared control-plane directory.

``bench.py --plane multihost`` and ``tests/test_multihost.py`` both
launch this module as a subprocess per controller::

    python -m shifu_tpu.parallel.elastic_demo --out DIR --proc I --nproc N

Each controller deterministically regenerates the SAME global dataset,
takes its contiguous row block (its "shard files"), trains the streamed
NN ensemble with the elastic step protocol (``parallel/elastic``), and
commits ``result-<proc>.json`` + ``params-<proc>.npz`` into ``--out``
so the caller can compare controllers bit-for-bit and read the AUC.
The cross-process combine rides the ``telemetry/steps/`` control plane
only — no jax.distributed, no cross-process collectives — which is the
point: this path works (and tests) on jaxlib builds without gloo.

A fault spec in ``SHIFU_TPU_FAULTS`` (e.g. ``dcn:step=3:kill``) turns a
controller into the worker-loss drill; relaunching it with the same
``--proc`` exercises the journal-backed rejoin.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _force_small_cpu() -> None:
    """Pin the demo to 2 virtual CPU devices (replacing any inherited
    count — the test suite exports 8) and its own compile cache, like
    tests/helpers/multihost_worker.py."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return                      # a real accelerator rig: leave it be
    # own compilation cache: the suite's persistent cache may hold AOT
    # entries recorded under a different device count / machine features
    # (same hazard tests/helpers/multihost_worker.py guards against)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = \
        os.environ.get("SHIFU_MH_CACHE", "/tmp/shifu_tpu_mh_cache")
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=2")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def _auc(scores, y) -> float:
    """Rank-based ROC AUC (ties get average rank)."""
    import numpy as np
    scores = np.asarray(scores, np.float64)
    y = np.asarray(y) > 0.5
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(len(scores), np.float64)
    ranks[order] = np.arange(1, len(scores) + 1, dtype=np.float64)
    # average tied ranks
    s_sorted = scores[order]
    i = 0
    while i < len(s_sorted):
        j = i
        while j + 1 < len(s_sorted) and s_sorted[j + 1] == s_sorted[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = ranks[order[i:j + 1]].mean()
        i = j + 1
    npos = int(y.sum())
    nneg = len(y) - npos
    if npos == 0 or nneg == 0:
        return 0.5
    return float((ranks[y].sum() - npos * (npos + 1) / 2) / (npos * nneg))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True,
                    help="shared job dir (control plane + results)")
    ap.add_argument("--proc", type=int, required=True)
    ap.add_argument("--nproc", type=int, required=True)
    ap.add_argument("--rows", type=int, default=4096,
                    help="GLOBAL row count (each controller owns 1/nproc)")
    ap.add_argument("--features", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--window", type=int, default=0,
                    help="stream window rows (0 = local rows / 2)")
    ap.add_argument("--quorum-frac", type=float, default=None)
    ap.add_argument("--timeout-ms", type=float, default=None)
    ap.add_argument("--staleness", type=int, default=None)
    args = ap.parse_args(argv)
    _force_small_cpu()

    import numpy as np

    from ..config import environment
    environment.set_property("shifu.dcn.elastic", "true")
    if args.quorum_frac is not None:
        environment.set_property("shifu.dcn.quorumFrac", args.quorum_frac)
    if args.timeout_ms is not None:
        environment.set_property("shifu.dcn.stepTimeoutMs",
                                 args.timeout_ms)
    if args.staleness is not None:
        environment.set_property("shifu.dcn.staleness", args.staleness)

    t_start = time.time()
    out = os.path.abspath(args.out)
    os.makedirs(out, exist_ok=True)

    # ---- the SAME global dataset on every controller (seeded), each
    # owning a contiguous row block — its "shard files"
    rng = np.random.default_rng(11)
    D = args.features
    x_all = rng.normal(size=(args.rows, D)).astype(np.float32)
    wvec = (rng.normal(size=D) / np.sqrt(D)).astype(np.float32)
    y_all = (1.0 / (1.0 + np.exp(-(x_all @ wvec) * 3))
             > rng.random(args.rows)).astype(np.float32)
    per = args.rows // args.nproc
    lo, hi = args.proc * per, (args.proc + 1) * per
    ddir = os.path.join(out, f"data-{args.proc}")
    os.makedirs(ddir, exist_ok=True)

    from .. import ioutil
    ioutil.atomic_savez(os.path.join(ddir, "part-00000.npz"),
                        x=x_all[lo:hi], y=y_all[lo:hi],
                        w=np.ones(hi - lo, np.float32))
    ioutil.atomic_write_json(os.path.join(ddir, "schema.json"), {
        "outputNames": [f"c{i}" for i in range(D)],
        "columnNums": list(range(D)), "numShards": 1, "numRows": hi - lo})

    from ..data.shards import Shards
    from ..data.streaming import ShardStream, mask_fn_from_settings
    from ..models.nn import NNModelSpec
    from ..parallel.elastic import ElasticContext
    from ..parallel.mesh import device_mesh
    from ..train.nn_trainer import TrainSettings, train_ensemble_streamed

    mesh = device_mesh(n_ensemble=1)
    data_size = int(mesh.shape["data"])
    window = args.window or max(data_size, (hi - lo) // 2)
    window -= window % data_size
    stream = ShardStream(Shards.open(ddir), ("x", "y", "w"), window)
    spec = NNModelSpec(input_dim=D, hidden_nodes=[8],
                       activations=["tanh"], loss="log")
    settings = TrainSettings(optimizer="ADAM", learning_rate=0.05,
                             epochs=args.epochs, batch_size=0, seed=7)
    mask_fn = mask_fn_from_settings(1, valid_rate=0.25, seed=7)

    ctx = ElasticContext(out, proc=f"ctrl-{args.proc}").start()
    t_train = time.time()
    try:
        res = train_ensemble_streamed(stream, spec, settings, 1, mask_fn,
                                      mesh=mesh, elastic=ctx)
    except BaseException:
        ctx.stop(exit_code=1)
        raise
    train_s = time.time() - t_train
    dcn_stats = {"rejoined": ctx.rejoined, "incarnation": ctx.incarnation,
                 "catchup_steps": ctx.catchup_steps,
                 "steps_closed": ctx.steps_closed,
                 "step_timeouts": ctx.step_timeouts,
                 "late_applied": ctx.late_applied,
                 "late_dropped": ctx.late_dropped}
    ctx.stop(exit_code=0)

    # ---- results: bit-comparable params + an AUC on the GLOBAL plane
    import jax.numpy as jnp

    from ..models.nn import forward
    params = res.params[0]
    flat = {f"l{i}_{k}": np.asarray(layer[k])
            for i, layer in enumerate(params) for k in ("w", "b")}
    ioutil.atomic_savez(os.path.join(out, f"params-{args.proc}.npz"),
                        **flat)
    scores = np.asarray(forward(params, spec, jnp.asarray(x_all)))[:, 0]
    auc = _auc(scores, y_all)
    checksum = float(sum(np.abs(v).sum() for v in flat.values()))

    ioutil.atomic_write_json(os.path.join(out,
                                          f"result-{args.proc}.json"), {
        "proc": args.proc, "checksum": checksum, "auc": round(auc, 6),
        "epochs_run": res.epochs_run,
        "history": [[round(a, 6), round(b, 6)] for a, b in res.history],
        "dcn": dcn_stats, "wall_s": round(time.time() - t_start, 3),
        "train_s": round(train_s, 3), "rows_local": hi - lo,
        "window": window})
    print(f"ELASTIC-OK proc={args.proc} checksum={checksum:.8f} "
          f"auc={auc:.4f} catchup={dcn_stats['catchup_steps']} "
          f"rejoined={int(dcn_stats['rejoined'])}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
