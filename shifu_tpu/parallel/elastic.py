"""Elastic multi-controller step protocol — quorum-gated DCN collectives.

The reference's signature production trick is surviving worker loss at
scale: Guagua's master/worker BSP closes an iteration when 97% of
workers have reported or a 2 s timeout expires (``GuaguaConstants``
quorum wiring, BASELINE.md), so one dead YARN container never hangs a
1000-worker job.  Our in-mesh ``psum`` path has the opposite failure
mode — it is synchronous, so one dead process hangs every peer inside
the collective.  This module is the escape hatch: an OPT-IN
(``-Dshifu.dcn.elastic``) step protocol where the cross-process combine
rides a shared-filesystem control plane instead of the collective, so
the surviving controllers can close a step without the dead one.

Per step, each controller commits a CONTRIBUTION record (host-side
partial sums + step id, atomic via :mod:`ioutil`) into
``<modelset>/telemetry/steps/`` beside its heartbeat.  A step CLOSES
when ``-Dshifu.dcn.quorumFrac`` (default 0.97) of the live members have
contributed or ``-Dshifu.dcn.stepTimeoutMs`` (default 2000) expires;
the first controller to observe the close condition publishes the
close record EXCLUSIVELY (first-writer-wins ``os.link`` commit), and
every controller — including one that lost the race or was straggling —
proceeds with the SAME quorum aggregate, summed in sorted-contributor
order so the bits agree everywhere.  A straggler whose contribution
lands after its step closed is either dropped (quorum mode,
``-Dshifu.dcn.staleness=0``) or folded into a later step's aggregate
within ``staleness`` steps (bounded-staleness mode) — the sync/async
trade-off "How to scale distributed deep learning?" frames (PAPERS.md).

Liveness rides the EXISTING heartbeat staleness rule (:mod:`obs.health`
``classify``): a controller whose heartbeat goes stale/exited drops out
of the live set, the quorum denominator shrinks, and a membership
EPOCH record is published (same exclusive commit) so every survivor
agrees on who is in the job.  The dead controller REJOINS without a job
restart: close records double as a step journal, so a restarted
controller replays the committed step prefix (``closed_step``) —
applying the recorded aggregates without re-streaming its data — and
catches up to the front in seconds (``dcn.catchup_steps``).

Control-plane layout (all commits atomic; close/epoch exclusive)::

    telemetry/steps/member-<proc>.json   join record (incarnation, pid)
    telemetry/steps/epoch-<n>.json       membership epoch chain
    telemetry/steps/c-<step>-<proc>.json contribution (payload inline)
    telemetry/steps/close-<step>.json    close record + quorum aggregate

Fault sites: ``dcn:step=<s>`` fires at step ``s``'s boundary (before
the contribution commit — a kill there is the worker-loss drill) and
``train:rejoin=<s>`` fires when a rejoined controller starts replaying
step ``s`` from the journal.
"""

from __future__ import annotations

import base64
import io
import json
import logging
import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import faults
from ..ioutil import atomic_write_json, sweep_orphan_tmp

log = logging.getLogger(__name__)

STEPS_DIRNAME = "steps"

# close verdicts
CLOSE_QUORUM = "quorum"      # quorumFrac of live members contributed
CLOSE_TIMEOUT = "timeout"    # stepTimeoutMs expired with a partial set


def elastic_enabled() -> bool:
    """The ``-Dshifu.dcn.elastic`` master switch (default off: the
    in-mesh ``psum`` path stays the fast default)."""
    from ..config import environment
    return environment.get_bool("shifu.dcn.elastic", False)


def steps_dir_for(model_set_dir: str) -> str:
    return os.path.join(os.path.abspath(model_set_dir), "telemetry",
                        STEPS_DIRNAME)


@dataclass
class ElasticConfig:
    """Knob bundle for the step protocol (see module docs)."""
    quorum_frac: float = 0.97
    step_timeout_ms: float = 2000.0
    staleness: int = 0           # 0 = quorum mode (drop late); >0 = bounded
    poll_interval_s: float = 0.02

    @classmethod
    def from_env(cls) -> "ElasticConfig":
        from ..config import environment
        return cls(
            quorum_frac=environment.get_float("shifu.dcn.quorumFrac", 0.97),
            step_timeout_ms=environment.get_float("shifu.dcn.stepTimeoutMs",
                                                  2000.0),
            staleness=environment.get_int("shifu.dcn.staleness", 0))


def quorum_needed(n_live: int, frac: float) -> int:
    """Contributors required to close over ``n_live`` members — never
    below 1 (a lone survivor must be able to make progress)."""
    return max(1, math.ceil(frac * n_live - 1e-9))


# ---------------------------------------------------------------- payloads
def encode_payload(payload: Dict[str, np.ndarray]) -> str:
    """Arrays -> base64(npz): the contribution/close records carry their
    payload INLINE so each record commits in one atomic file (a torn
    npz-sidecar pair cannot exist)."""
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in payload.items()})
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_payload(data: str) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(base64.b64decode(data))) as z:
        return {k: z[k] for k in z.files}


def sum_payloads(payloads: Sequence[Dict[str, np.ndarray]]
                 ) -> Dict[str, np.ndarray]:
    """Element-wise sum — callers pass contributions in SORTED proc
    order so fp reassociation cannot diverge between controllers."""
    out: Dict[str, np.ndarray] = {}
    for p in payloads:
        for k, v in p.items():
            out[k] = v if k not in out else out[k] + v
    return out


# ---------------------------------------------------- pure close decision
@dataclass
class QuorumStep:
    """One step's close decision for one controller's view — PURE state
    (injectable clock), so the quorum semantics are unit-testable
    without processes, files, or sleeps."""
    step: int
    cfg: ElasticConfig
    live: Set[str]
    opened_at: float
    contributed: Set[str] = field(default_factory=set)

    @property
    def deadline(self) -> float:
        return self.opened_at + self.cfg.step_timeout_ms / 1000.0

    @property
    def needed(self) -> int:
        return quorum_needed(len(self.live), self.cfg.quorum_frac)

    def offer(self, proc: str) -> None:
        self.contributed.add(proc)

    def update_live(self, live: Set[str]) -> None:
        self.live = set(live)

    def stragglers(self) -> List[str]:
        return sorted(self.live - self.contributed)

    def decide(self, now: float) -> Optional[str]:
        """``None`` (keep waiting) | CLOSE_QUORUM | CLOSE_TIMEOUT.  A
        timeout close still needs at least one contribution (the
        decider's own, in practice) — an empty aggregate is not a step."""
        if len(self.contributed & self.live) >= self.needed:
            return CLOSE_QUORUM
        if now >= self.deadline and self.contributed:
            return CLOSE_TIMEOUT
        return None


@dataclass
class StepResult:
    """What a closed step hands back to the trainer."""
    step: int
    payload: Dict[str, np.ndarray]
    contributors: List[str]
    stragglers: List[str]
    reason: str
    epoch: int
    closed_by: str
    late_applied: List[Tuple[int, str]] = field(default_factory=list)

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "StepResult":
        return cls(step=int(doc["step"]),
                   payload=decode_payload(doc["payload"]),
                   contributors=list(doc.get("contributors") or []),
                   stragglers=list(doc.get("stragglers") or []),
                   reason=str(doc.get("reason") or CLOSE_QUORUM),
                   epoch=int(doc.get("epoch") or 0),
                   closed_by=str(doc.get("by") or "?"),
                   late_applied=[(int(s), p) for s, p in
                                 (doc.get("late") or [])])


# ------------------------------------------------------------ file board
class StepBoard:
    """The shared-filesystem control plane: contribution / close /
    membership records under ``telemetry/steps/``.  Every write is
    atomic; close and epoch records are EXCLUSIVE (first-writer-wins
    via ``os.link`` — the loser reads the winner's record, so exactly
    one authoritative close exists per step)."""

    def __init__(self, steps_dir: str, health_dir: Optional[str] = None):
        self.steps_dir = steps_dir
        # liveness reads the EXISTING heartbeat plane next door
        self.health_dir = health_dir or os.path.join(
            os.path.dirname(os.path.abspath(steps_dir)), "health")

    def ensure(self) -> None:
        os.makedirs(self.steps_dir, exist_ok=True)
        sweep_orphan_tmp(self.steps_dir)

    # ------------------------------------------------------------ helpers
    def _path(self, name: str) -> str:
        return os.path.join(self.steps_dir, name)

    def _read_json(self, name: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._path(name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _exclusive_publish(self, name: str, doc: Dict[str, Any]) -> bool:
        """First-writer-wins commit: write a temp file, ``os.link`` it to
        the final name (fails atomically if the name exists), unlink the
        temp.  Returns True when THIS writer won the name."""
        path = self._path(name)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:  # shifu-lint: disable=atomic-write
            # not raw-write debt: the exclusive commit below links the
            # fully-written temp into place (os.link has no overwrite
            # mode, unlike os.replace, which is exactly the point)
            json.dump(doc, f, indent=1)
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    # ------------------------------------------------------ contributions
    def contribute(self, step: int, proc: str,
                   payload: Dict[str, np.ndarray],
                   epoch: int = 0, late: bool = False) -> None:
        atomic_write_json(self._path(f"c-{step:06d}-{proc}.json"), {
            "kind": "dcn_contribution", "step": step, "proc": proc,
            "epoch": epoch, "late": late, "ts": round(time.time(), 3),
            "payload": encode_payload(payload)}, indent=0)

    def has_contribution(self, step: int, proc: str) -> bool:
        return os.path.isfile(self._path(f"c-{step:06d}-{proc}.json"))

    def contributions(self, step: int) -> Dict[str, Dict[str, Any]]:
        """proc -> committed contribution record for ``step`` (payload
        left encoded; decode lazily at aggregation)."""
        out: Dict[str, Dict[str, Any]] = {}
        prefix = f"c-{step:06d}-"
        try:
            names = os.listdir(self.steps_dir)
        except OSError:
            return out
        for name in names:
            if name.startswith(prefix) and name.endswith(".json"):
                doc = self._read_json(name)
                if doc is not None:
                    out[name[len(prefix):-5]] = doc
        return out

    # ------------------------------------------------------------- closes
    def close_doc(self, step: int) -> Optional[Dict[str, Any]]:
        return self._read_json(f"close-{step:06d}.json")

    def try_close(self, step: int, doc: Dict[str, Any]) -> bool:
        return self._exclusive_publish(f"close-{step:06d}.json", doc)

    def last_closed_step(self) -> int:
        """Highest closed step id, -1 when none — the committed step
        prefix a rejoiner replays."""
        last = -1
        try:
            names = os.listdir(self.steps_dir)
        except OSError:
            return last
        for name in names:
            if name.startswith("close-") and name.endswith(".json"):
                try:
                    last = max(last, int(name[6:-5]))
                except ValueError:
                    pass
        return last

    # --------------------------------------------------------- membership
    def announce(self, proc: str, step_name: Optional[str] = None) -> int:
        """Commit (or refresh) this controller's join record; returns the
        incarnation (1 on first join, +1 per restart — a rejoin)."""
        prev = self._read_json(f"member-{proc}.json")
        inc = int(prev.get("incarnation", 0)) + 1 if prev else 1
        atomic_write_json(self._path(f"member-{proc}.json"), {
            "kind": "dcn_member", "proc": proc, "pid": os.getpid(),
            "incarnation": inc, "step_name": step_name,
            "ts": round(time.time(), 3)})
        return inc

    def members(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        try:
            names = os.listdir(self.steps_dir)
        except OSError:
            return out
        for name in names:
            if name.startswith("member-") and name.endswith(".json"):
                doc = self._read_json(name)
                if doc is not None:
                    out[name[7:-5]] = doc
        return out

    def live_members(self, now: Optional[float] = None) -> Dict[str, int]:
        """proc -> incarnation for every member the heartbeat staleness
        rule still considers alive.  A member with a stale/exited health
        record is DEAD; one with no health record yet gets the benefit
        of the doubt (it announced, its first beat may be in flight)."""
        from ..obs.health import classify, read_health
        now = time.time() if now is None else now
        health = {r.get("proc"): r for r in read_health(self.health_dir)}
        out: Dict[str, int] = {}
        for proc, doc in self.members().items():
            rec = health.get(proc)
            if rec is not None and classify(rec, now=now) in ("stale",
                                                             "exited"):
                continue
            out[proc] = int(doc.get("incarnation", 1))
        return out

    # ------------------------------------------------------ epoch chain
    def current_epoch(self) -> Tuple[int, Dict[str, int]]:
        """(epoch number, member->incarnation map) of the newest epoch
        record — (0, {}) before the first bump."""
        best, members = 0, {}
        try:
            names = os.listdir(self.steps_dir)
        except OSError:
            return best, members
        for name in names:
            if name.startswith("epoch-") and name.endswith(".json"):
                try:
                    n = int(name[6:-5])
                except ValueError:
                    continue
                if n > best:
                    doc = self._read_json(name) or {}
                    best, members = n, dict(doc.get("members") or {})
        return best, members

    def maybe_bump_epoch(self, live: Dict[str, int], by: str,
                         reason: str = "membership") -> int:
        """Publish epoch N+1 when the live member/incarnation map
        changed (join, leave, OR rejoin — a restart bumps even though
        the set of names is unchanged).  Races resolve exclusively;
        returns the current epoch number either way."""
        n, members = self.current_epoch()
        if members == live:
            return n
        if self._exclusive_publish(f"epoch-{n + 1:06d}.json", {
                "kind": "dcn_epoch", "epoch": n + 1, "members": live,
                "previous": members, "by": by, "reason": reason,
                "ts": round(time.time(), 3)}):
            log.info("membership epoch %d: %s (%s)", n + 1,
                     sorted(live), reason)
            return n + 1
        return self.current_epoch()[0]


# --------------------------------------------------------------- context
class ElasticContext:
    """One controller's handle on the elastic job: join the membership,
    heartbeat, and run :meth:`step` once per training step.  Clock and
    sleep are injectable so the quorum/timeout semantics unit-test
    without wall time."""

    def __init__(self, model_set_dir: str, proc: str,
                 cfg: Optional[ElasticConfig] = None,
                 step_name: str = "TRAIN",
                 heartbeat: bool = True,
                 now_fn: Callable[[], float] = time.time,
                 sleep_fn: Callable[[float], None] = time.sleep):
        from ..obs.health import health_dir_for
        self.model_set_dir = model_set_dir
        self.proc = proc
        self.cfg = cfg or ElasticConfig.from_env()
        self.step_name = step_name
        self.board = StepBoard(steps_dir_for(model_set_dir),
                               health_dir=health_dir_for(model_set_dir))
        self._heartbeat_wanted = heartbeat
        self._hb = None
        self._now = now_fn
        self._sleep = sleep_fn
        self.incarnation = 0
        self.rejoined = False
        self._rejoin_announced = False
        # protocol stats mirrored as plain attributes (the obs counters
        # are null instruments when telemetry is off; rejoin/catch-up
        # accounting must survive that for results and tests)
        self.catchup_steps = 0
        self.steps_closed = 0
        self.step_timeouts = 0
        self.late_applied = 0
        self.late_dropped = 0

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ElasticContext":
        from .. import obs
        self.board.ensure()
        self.incarnation = self.board.announce(self.proc, self.step_name)
        self.rejoined = self.incarnation > 1
        if self.rejoined:
            obs.counter("dcn.rejoins").inc()
            log.info("controller %s REJOINING (incarnation %d) — will "
                     "replay the committed step prefix", self.proc,
                     self.incarnation)
        if self._heartbeat_wanted:
            # the protocol's death detector IS the heartbeat staleness
            # rule, so the elastic heartbeat runs regardless of the
            # telemetry switch (unlike obs.start_heartbeat) — opting
            # into elastic mode opts into its control-plane files
            from ..obs.health import HeartbeatWriter
            self._hb = HeartbeatWriter(self.board.health_dir,
                                       step=self.step_name,
                                       proc=self.proc).start()
        self._refresh_live(reason="join")
        return self

    def stop(self, exit_code: Optional[int] = 0) -> None:
        if self._hb is not None:
            self._hb.stop(exit_code=exit_code)
            self._hb = None

    def __enter__(self) -> "ElasticContext":
        return self.start()

    def __exit__(self, et, ev, tb) -> None:
        self.stop(exit_code=0 if et is None else 1)

    # ------------------------------------------------------------ internals
    def _refresh_live(self, reason: str = "membership") -> Dict[str, int]:
        from .. import obs
        live = self.board.live_members(now=self._now())
        epoch = self.board.maybe_bump_epoch(live, by=self.proc,
                                            reason=reason)
        obs.gauge("dcn.membership_epoch").set(float(epoch))
        obs.gauge("dcn.live_members").set(float(len(live)))
        return live

    def _late_candidates(self, closing_step: int,
                         applied: Set[Tuple[int, str]]
                         ) -> Tuple[List[Tuple[int, str, Dict[str, Any]]],
                                    List[Tuple[int, str]]]:
        """(apply, dropped): late contributions to already-closed steps
        not yet folded by a prior close — split into the ones still
        inside the staleness window (folded into THIS close's aggregate)
        and the ones that aged out (recorded dropped so no later closer
        re-counts them).  Quorum mode (staleness=0) drops everything."""
        apply: List[Tuple[int, str, Dict[str, Any]]] = []
        dropped: List[Tuple[int, str]] = []
        scan_from = max(0, closing_step - 2 * max(self.cfg.staleness, 1)
                        - 2)
        for s in range(scan_from, closing_step):
            close = self.board.close_doc(s)
            if close is None:
                continue
            in_close = set(close.get("contributors") or [])
            for proc, doc in sorted(self.board.contributions(s).items()):
                if proc in in_close or (s, proc) in applied:
                    continue
                if self.cfg.staleness > 0 and \
                        closing_step - s <= self.cfg.staleness:
                    apply.append((s, proc, doc))
                else:
                    dropped.append((s, proc))
        return apply, dropped

    def _applied_late(self, closing_step: int) -> Set[Tuple[int, str]]:
        """Late pairs already folded (or dropped) by earlier closes —
        read back from the close chain so a late contribution is applied
        EXACTLY once across racing closers."""
        out: Set[Tuple[int, str]] = set()
        scan_from = max(0, closing_step - 2 * max(self.cfg.staleness, 1)
                        - 2)
        for s in range(scan_from, closing_step):
            close = self.board.close_doc(s)
            if close is None:
                continue
            for pair in (close.get("late") or []):
                out.add((int(pair[0]), pair[1]))
            for pair in (close.get("late_dropped") or []):
                out.add((int(pair[0]), pair[1]))
        return out

    def _try_close(self, qs: QuorumStep, verdict: str,
                   contribs: Dict[str, Dict[str, Any]]
                   ) -> Optional[StepResult]:
        from .. import obs
        procs = sorted(contribs)
        payloads = [decode_payload(contribs[p]["payload"]) for p in procs]
        applied = self._applied_late(qs.step)
        late, dropped_pairs = self._late_candidates(qs.step, applied)
        late_pairs: List[Tuple[int, str]] = []
        for s, proc, doc in late:
            payloads.append(decode_payload(doc["payload"]))
            late_pairs.append((s, proc))
        epoch, _ = self.board.current_epoch()
        doc = {
            "kind": "dcn_close", "step": qs.step, "reason": verdict,
            "contributors": procs, "stragglers": qs.stragglers(),
            "needed": qs.needed, "live": sorted(qs.live),
            "epoch": epoch, "by": self.proc,
            "late": [[s, p] for s, p in late_pairs],
            "late_dropped": [[s, p] for s, p in dropped_pairs],
            "ts": round(time.time(), 3),
            "payload": encode_payload(sum_payloads(payloads)),
        }
        if not self.board.try_close(qs.step, doc):
            return None                      # lost the race: read winner's
        self.steps_closed += 1
        obs.counter("dcn.steps_closed").inc()
        if verdict == CLOSE_TIMEOUT:
            self.step_timeouts += 1
            obs.counter("dcn.step_timeouts").inc()
            log.warning("dcn step %d closed on TIMEOUT with %d/%d "
                        "contributors (stragglers: %s)", qs.step,
                        len(procs), len(qs.live), qs.stragglers())
        if late_pairs:
            self.late_applied += len(late_pairs)
            obs.counter("dcn.late_applied").inc(len(late_pairs))
        if dropped_pairs:
            self.late_dropped += len(dropped_pairs)
            obs.counter("dcn.late_dropped").inc(len(dropped_pairs))
        return StepResult.from_doc(doc)

    # ------------------------------------------------------------ protocol
    def closed_step(self, step: int) -> Optional[StepResult]:
        """The close record for ``step`` if it exists — the journal read
        a rejoined controller replays INSTEAD of recomputing (fires the
        ``train:rejoin`` site on its first replayed step)."""
        doc = self.board.close_doc(step)
        if doc is None:
            return None
        from .. import obs
        if self.rejoined and not self._rejoin_announced:
            self._rejoin_announced = True
            faults.fire("train", "rejoin", step)
            log.info("controller %s replaying committed steps from %d",
                     self.proc, step)
        self.catchup_steps += 1
        obs.counter("dcn.catchup_steps").inc()
        return StepResult.from_doc(doc)

    def step(self, step: int, payload: Dict[str, np.ndarray]
             ) -> StepResult:
        """Run one quorum-gated step: commit this controller's
        contribution, wait for quorum/timeout/another controller's
        close, and return the authoritative aggregate."""
        from .. import obs
        faults.fire("dcn", "step", step)
        existing = self.board.close_doc(step)
        if existing is not None:
            # we are BEHIND the front (masked straggler or rejoiner):
            # in bounded-staleness mode our work still lands late; in
            # quorum mode it is dropped — either way we adopt the
            # committed aggregate and stay in lockstep
            if self.cfg.staleness > 0 \
                    and not self.board.has_contribution(step, self.proc):
                self.board.contribute(step, self.proc, payload,
                                      epoch=existing.get("epoch", 0),
                                      late=True)
            return StepResult.from_doc(existing)
        live = self._refresh_live()
        epoch, _ = self.board.current_epoch()
        self.board.contribute(step, self.proc, payload, epoch=epoch)
        t0 = self._now()
        qs = QuorumStep(step=step, cfg=self.cfg,
                        live=set(live) | {self.proc}, opened_at=t0)
        with obs.span("dcn.step", step=step):
            while True:
                doc = self.board.close_doc(step)
                if doc is not None:
                    res = StepResult.from_doc(doc)
                    break
                contribs = self.board.contributions(step)
                for p in contribs:
                    qs.offer(p)
                verdict = qs.decide(self._now())
                if verdict is not None:
                    res = self._try_close(qs, verdict, contribs)
                    if res is not None:
                        break
                    continue                 # lost the race — reread
                self._sleep(self.cfg.poll_interval_s)
                # liveness refresh INSIDE the wait: a peer dying mid-step
                # must shrink the quorum denominator or the step would
                # only ever close by timeout
                qs.update_live(
                    set(self._refresh_live()) | {self.proc})
        obs.counter("dcn.step_wait_seconds").inc(
            max(0.0, self._now() - t0))
        return res


# ---------------------------------------------------------- trainer glue
def grad_codec(zero_grads):
    """(ravel, unravel) for shipping a gradient pytree over the control
    plane as ONE f32 vector (elastic transport is f32 regardless of the
    training precision): ``ravel`` casts+flattens against the
    accumulator template, ``unravel`` restores the tree and re-narrows
    each leaf to the accumulator's dtype so bf16 training still applies
    an own-width update.  jax imports stay inside (this module is
    jax-free for the monitor/lint surface)."""
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    _, unravel_f32 = ravel_pytree(jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, jnp.float32), zero_grads))

    def ravel(tree) -> np.ndarray:
        flat, _ = ravel_pytree(jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32), tree))
        return np.asarray(flat, np.float32)

    def unravel(flat: np.ndarray):
        tree = unravel_f32(jnp.asarray(flat, jnp.float32))
        return jax.tree_util.tree_map(
            lambda a, z: a.astype(z.dtype), tree, zero_grads)

    return ravel, unravel


# ----------------------------------------------------------- pipeline glue
def elastic_context_for(model_set_dir: str, step_name: str = "TRAIN"
                        ) -> Optional[ElasticContext]:
    """The pipeline entry: an :class:`ElasticContext` when
    ``-Dshifu.dcn.elastic`` is on AND this run has a stable controller
    identity (``SHIFU_PROCESS_ID``) — ``None`` otherwise (single-
    controller runs stay on the in-mesh fast path untouched)."""
    if not elastic_enabled():
        return None
    pid = os.environ.get("SHIFU_PROCESS_ID")
    if pid is None:
        log.warning("shifu.dcn.elastic is on but SHIFU_PROCESS_ID is "
                    "unset — elastic mode needs a stable controller "
                    "identity to rejoin as; staying synchronous")
        return None
    return ElasticContext(model_set_dir, proc=f"ctrl-{int(pid)}",
                          step_name=step_name)
