"""Meta-driven config validation — the reference's
``container/meta/MetaFactory.java`` + ``store/ModelConfigMeta.json``
(1,003 LoC of declarative key schemas) rebuilt as a rule table.

Every ModelConfig scalar field and every ``train#params`` key validates
against a declarative Rule (type, range, allowed values, per-algorithm
applicability).  UNKNOWN ``train#params`` keys are hard errors with a
did-you-mean suggestion — a typo like ``LearningRat`` fails ``probe()``
instead of silently falling back to the default (the exact failure mode
MetaFactory exists to prevent).  Grid-search trials validate individually
(reference ``GridSearch`` expands before submission).
"""

from __future__ import annotations

import difflib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .model_config import Algorithm

NN_FAMILY = ("NN", "LR", "SVM", "TENSORFLOW")
TREE_FAMILY = ("GBT", "RF", "DT")


@dataclass(frozen=True)
class Rule:
    """One key's schema: accepted kinds + constraints.

    kind: 'int' | 'float' | 'bool' | 'str' | 'list' | 'intlist' | 'strlist'
    lo/hi: numeric range (inclusive unless *_open); allowed: value set
    (case-insensitive for strings); algs: algorithms the key applies to
    (None = all).
    """
    kind: str
    lo: Optional[float] = None
    hi: Optional[float] = None
    lo_open: bool = False
    hi_open: bool = False
    allowed: Optional[Tuple[str, ...]] = None
    algs: Optional[Tuple[str, ...]] = None


_OPTIMIZERS = ("B", "Q", "R", "M", "ADAM", "SGD", "MOMENTUM", "NESTEROV",
               "RMSPROP", "ADAGRAD")
_ACTIVATIONS = ("sigmoid", "tanh", "relu", "leakyrelu", "ptanh", "swish",
                "linear", "log", "sin", "softmax")
_LOSSES = ("squared", "absolute", "log", "hinge")
_IMPURITIES = ("variance", "friedmanmse", "entropy", "gini")
_SUBSETS = ("ALL", "HALF", "SQRT", "LOG2", "ONETHIRD", "TWOTHIRDS")
_INITIALIZERS = ("xavier", "he", "lecun", "zero", "default",
                 "herandomizer", "lecunrandomizer")

# ------------------------------------------------- train#params schema
# provenance: reference ``core/dtrain/CommonConstants.java`` key constants,
# ``DTMaster.java:91`` tree init region, ``NNMaster``/``DTrainUtils`` NN
# region, ``core/dtrain/wdl/`` WDL params.
TRAIN_PARAM_RULES: Dict[str, Rule] = {
    # NN / LR family
    "Propagation": Rule("str", allowed=_OPTIMIZERS, algs=NN_FAMILY),
    "Optimizer": Rule("str", allowed=_OPTIMIZERS, algs=NN_FAMILY + ("WDL",)),
    "NumHiddenLayers": Rule("int", lo=0, hi=64, algs=NN_FAMILY),
    "NumHiddenNodes": Rule("intlist", lo=1, algs=NN_FAMILY + ("WDL",)),
    "ActivationFunc": Rule("strlist", allowed=_ACTIVATIONS,
                           algs=NN_FAMILY + ("WDL",)),
    "LearningRate": Rule("float", lo=0.0, lo_open=True, hi=100.0),
    "LearningDecay": Rule("float", lo=0.0, hi=1.0, hi_open=True,
                          algs=NN_FAMILY),
    "RegularizedConstant": Rule("float", lo=0.0,
                                algs=NN_FAMILY + ("WDL",)),
    "L2Const": Rule("float", lo=0.0, algs=NN_FAMILY + ("WDL",)),
    "L1Const": Rule("float", lo=0.0, algs=NN_FAMILY),
    "L1orL2": Rule("str", allowed=("NONE", "L1", "L2"), algs=NN_FAMILY),
    "DropoutRate": Rule("float", lo=0.0, hi=1.0, hi_open=True,
                        algs=NN_FAMILY),
    "MiniBatchs": Rule("int", lo=0, algs=NN_FAMILY + ("WDL",)),
    "WindowSize": Rule("int", lo=1, algs=NN_FAMILY + ("WDL",)),
    "WeightInitializer": Rule("str", allowed=_INITIALIZERS, algs=NN_FAMILY),
    "TmpModelEpochs": Rule("int", lo=0, algs=NN_FAMILY),
    "FixedLayers": Rule("intlist", algs=NN_FAMILY),
    "FixedBias": Rule("bool", algs=NN_FAMILY),
    "EnableEarlyStop": Rule("bool"),
    "ValidationTolerance": Rule("float", lo=0.0, algs=NN_FAMILY),
    "OutputActivationFunc": Rule("str", allowed=_ACTIVATIONS,
                                 algs=NN_FAMILY),
    # TPU matmul precision: bfloat16 inputs + f32 accumulation feed the MXU
    # at full rate (no reference analogue; Encog is f64 CPU)
    "Precision": Rule("str", allowed=("highest", "float32", "default",
                                      "bfloat16", "tensorfloat32"),
                      algs=NN_FAMILY),
    # training-precision ladder (round 12): f32 keeps today's math;
    # bf16 trains fully narrow; mixed keeps an f32 master copy in the
    # optimizer state with bf16 forward/backward ("" defers to the
    # -Dshifu.train.precision property)
    "TrainPrecision": Rule("str", allowed=("f32", "bf16", "mixed"),
                           algs=NN_FAMILY + ("WDL",)),
    "Loss": Rule("str", allowed=_LOSSES),
    # SVM (reference core/alg/SVMTrainer.java param keys)
    "Kernel": Rule("str", allowed=("linear", "rbf", "radialbasisfunction",
                                   "poly", "sigmoid"), algs=("SVM",)),
    "Gamma": Rule("float", lo=0.0, lo_open=True, algs=("SVM",)),
    "Const": Rule("float", lo=0.0, lo_open=True, algs=("SVM",)),
    "Coef0": Rule("float", algs=("SVM",)),
    "Degree": Rule("int", lo=1, hi=10, algs=("SVM",)),
    "Seed": Rule("int"),
    "CheckpointInterval": Rule("int", lo=0),
    # tree family
    "TreeNum": Rule("int", lo=1, hi=100000, algs=TREE_FAMILY),
    # trees between device-side early-stop decisions (sync-free growth:
    # errors accumulate on device and fetch in bulk)
    "EarlyStopCheckInterval": Rule("int", lo=1, hi=10000,
                                   algs=TREE_FAMILY),
    # RF same-round trees grown per batched device program (multi-tree
    # Pallas histogram grids); 0 = auto
    "TreeBatch": Rule("int", lo=0, hi=64, algs=TREE_FAMILY),
    # disk-tail super-batch: trees fed by ONE tail re-stream in streamed
    # RF (one disk pass feeds the whole batch's level histograms); 0 =
    # auto (budget-derived from shifu.tree.tailSuperBatchBytes)
    "TailTreeBatch": Rule("int", lo=0, hi=1024, algs=TREE_FAMILY),
    "MaxDepth": Rule("int", lo=1, hi=20, algs=TREE_FAMILY),
    # -1 (default) = level-wise; >0 enables the leaf-wise node budget
    # (reference DTMaster.java:129-137 MaxLeaves / isLeafWise)
    "MaxLeaves": Rule("int", lo=-1, hi=1 << 20, algs=TREE_FAMILY),
    "Impurity": Rule("str", allowed=_IMPURITIES, algs=TREE_FAMILY),
    "FeatureSubsetStrategy": Rule("str", allowed=_SUBSETS,
                                  algs=TREE_FAMILY),
    "MinInstancesPerNode": Rule("float", lo=0.0, algs=TREE_FAMILY),
    "MinInfoGain": Rule("float", lo=0.0, algs=TREE_FAMILY),
    # TENSORFLOW-only topology/resource keys (reference TF-on-YARN bridge,
    # ``TrainModelProcessor.java:395-449`` session setup): recognized so
    # they don't read as typos, but the tpu-native NN path that serves
    # algorithm=TENSORFLOW has no ps/worker topology — a TRAIN probe with
    # any of them present fails loudly (``tf_ignored_param_problems``)
    # instead of training while silently ignoring them
    "NumPS": Rule("int", lo=1, algs=("TENSORFLOW",)),
    "NumTFWorkers": Rule("int", lo=1, algs=("TENSORFLOW",)),
    "TFWorkerMemory": Rule("int", lo=1, algs=("TENSORFLOW",)),
    "TFPSMemory": Rule("int", lo=1, algs=("TENSORFLOW",)),
    # WDL family
    "EmbedColumnNum": Rule("int", lo=1, algs=("WDL",)),
    "EmbedDim": Rule("int", lo=1, algs=("WDL",)),
    "NumEmbedColumnIds": Rule("intlist", algs=("WDL",)),
    "NumEmbedOuputs": Rule("int", lo=1, algs=("WDL",)),
    "WideEnable": Rule("bool", algs=("WDL",)),
    "DeepEnable": Rule("bool", algs=("WDL",)),
    "WDLL2Reg": Rule("float", lo=0.0, algs=("WDL",)),
}

# ------------------------------------------------- ModelConfig field schema
# dotted path -> Rule; checked via attribute walk on every probe
CONFIG_RULES: Dict[str, Rule] = {
    "train.baggingNum": Rule("int", lo=1, hi=1000),
    "train.numTrainEpochs": Rule("int", lo=1, hi=1_000_000),
    "train.validSetRate": Rule("float", lo=0.0, hi=1.0, hi_open=True),
    "train.baggingSampleRate": Rule("float", lo=0.0, lo_open=True, hi=1.0),
    "train.upSampleWeight": Rule("float", lo=1.0),
    "train.convergenceThreshold": Rule("float", lo=0.0),
    "train.epochsPerIteration": Rule("int", lo=1),
    "train.workerThreadCount": Rule("int", lo=1, hi=1024),
    "stats.maxNumBin": Rule("int", lo=2, hi=32767),
    "stats.sampleRate": Rule("float", lo=0.0, lo_open=True, hi=1.0),
    "stats.binningMethod": Rule("str"),
    "normalize.stdDevCutOff": Rule("float", lo=0.0, lo_open=True),
    "normalize.sampleRate": Rule("float", lo=0.0, lo_open=True, hi=1.0),
    "varSelect.filterNum": Rule("int", lo=0),
}


def _as_number(v: Any) -> Optional[float]:
    import math
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        x = float(v)
    elif isinstance(v, str):
        try:
            x = float(v)
        except ValueError:
            return None
    else:
        return None
    return x if math.isfinite(x) else None    # 'nan'/'inf' are not values


def _check_value(key: str, v: Any, rule: Rule) -> List[str]:
    import enum
    if isinstance(v, enum.Enum):       # config enums validate by value
        v = v.value
    problems: List[str] = []

    def range_check(x: float) -> None:
        if rule.lo is not None and (x < rule.lo
                                    or (rule.lo_open and x == rule.lo)):
            op = ">" if rule.lo_open else ">="
            problems.append(f"{key} must be {op} {rule.lo:g}, got {v!r}")
        elif rule.hi is not None and (x > rule.hi
                                      or (rule.hi_open and x == rule.hi)):
            op = "<" if rule.hi_open else "<="
            problems.append(f"{key} must be {op} {rule.hi:g}, got {v!r}")

    if rule.kind in ("int", "float"):
        x = _as_number(v)
        if x is None or (rule.kind == "int" and x != int(x)):
            problems.append(f"{key} must be a {rule.kind}, got {v!r}")
        else:
            range_check(x)
    elif rule.kind == "bool":
        if not isinstance(v, bool) and str(v).lower() not in ("true", "false"):
            problems.append(f"{key} must be a boolean, got {v!r}")
    elif rule.kind == "str":
        if not isinstance(v, str):
            problems.append(f"{key} must be a string, got {v!r}")
        elif rule.allowed and str(v).lower() not in \
                tuple(a.lower() for a in rule.allowed):
            problems.append(f"{key} must be one of {list(rule.allowed)}, "
                            f"got {v!r}")
    elif rule.kind in ("intlist", "strlist"):
        if not isinstance(v, (list, tuple)):
            problems.append(f"{key} must be a list, got {v!r}")
        else:
            for e in v:
                if rule.kind == "intlist":
                    x = _as_number(e)
                    if x is None or x != int(x):
                        problems.append(f"{key} elements must be ints, "
                                        f"got {e!r}")
                        break
                    range_check(x)
                elif rule.allowed and str(e).lower() not in \
                        tuple(a.lower() for a in rule.allowed):
                    problems.append(f"{key} element {e!r} not one of "
                                    f"{list(rule.allowed)}")
                    break
    return problems


TF_ONLY_PARAMS = tuple(k for k, r in TRAIN_PARAM_RULES.items()
                       if r.algs == ("TENSORFLOW",))


def tf_ignored_param_problems(train_conf) -> List[str]:
    """``algorithm=TENSORFLOW`` remaps onto the native jitted NN path
    (``pipeline/train.py`` TrainProcessor.process) — TF-on-YARN-only
    topology/resource params would train-while-ignored there, the exact
    silent failure MetaFactory exists to prevent.  Fail loudly, listing
    every offender."""
    if train_conf.algorithm != Algorithm.TENSORFLOW:
        return []
    present = sorted(k for k in (train_conf.params or {})
                     if k in TF_ONLY_PARAMS)
    if not present:
        return []
    return [f"algorithm TENSORFLOW trains on the native NN path (no "
            f"TF-on-YARN ps/worker topology) — train#params {present} "
            "would be silently ignored; remove them or use a TF-on-YARN "
            "deployment"]


def unknown_param_problems(params: Dict[str, Any]) -> List[str]:
    """Hard errors for keys no algorithm knows, with a did-you-mean hint."""
    problems: List[str] = []
    for key in (params or {}):
        if key not in TRAIN_PARAM_RULES:
            hint = difflib.get_close_matches(key, TRAIN_PARAM_RULES, n=1,
                                             cutoff=0.6)
            suffix = f" — did you mean {hint[0]!r}?" if hint else ""
            problems.append(f"unknown train#params key {key!r}{suffix}")
    return problems


def _nn_shape_problems(params: Dict[str, Any], alg: str) -> List[str]:
    """Cross-field NN shape consistency (layers vs nodes vs activations)."""
    if alg not in NN_FAMILY:
        return []
    problems: List[str] = []
    layers = params.get("NumHiddenLayers")
    nodes = params.get("NumHiddenNodes")
    acts = params.get("ActivationFunc")
    try:
        if layers is not None and nodes is not None \
                and int(layers) != len(nodes):
            problems.append("NumHiddenLayers must equal len(NumHiddenNodes)")
        if layers is not None and acts is not None \
                and int(layers) != len(acts):
            problems.append("NumHiddenLayers must equal len(ActivationFunc)")
    except (TypeError, ValueError):
        pass    # malformed values already reported by the per-key rules
    return problems


def validate_train_params(params: Dict[str, Any],
                          algorithm: Algorithm) -> List[str]:
    """Validate one trial's train#params against the schema.  Grid-search
    list-of-candidates values must be expanded BEFORE calling (use
    :func:`validate_train_conf`, which does)."""
    problems: List[str] = list(unknown_param_problems(params))
    alg = algorithm.name
    for key, v in (params or {}).items():
        rule = TRAIN_PARAM_RULES.get(key)
        if rule is None:
            continue    # reported above
        if rule.algs is not None and alg not in rule.algs:
            problems.append(f"train#params {key!r} does not apply to "
                            f"algorithm {alg} (valid for "
                            f"{list(rule.algs)})")
            continue
        problems.extend(_check_value(f"train#params.{key}", v, rule))
    problems.extend(_nn_shape_problems(params or {}, alg))
    return problems


def validate_train_conf(train_conf) -> List[str]:
    """Validate train#params; grid-search candidates validate individually
    WITHOUT materializing the cartesian product (every rule is per-key, so
    per-axis candidate checks are exact in O(sum of axis lengths); only the
    tiny NN shape cross-check walks its own 3-axis product)."""
    import itertools

    from ..train import grid_search
    params = train_conf.params or {}
    alg = train_conf.algorithm
    if not grid_search.is_grid_search(params):
        return validate_train_params(params, alg)

    problems: List[str] = []
    seen = set()

    def add(ps: Sequence[str]) -> None:
        for p in ps:
            if p not in seen:
                seen.add(p)
                problems.append(p)

    def candidates(k: str, v: Any) -> list:
        if isinstance(v, list) and grid_search._is_axis(k, v):
            return list(v)
        return [v]

    for k, v in params.items():
        for c in candidates(k, v):
            add(validate_train_params({k: c}, alg))
    shape = {k: candidates(k, params[k])
             for k in ("NumHiddenLayers", "NumHiddenNodes", "ActivationFunc")
             if k in params}
    if shape:
        keys = list(shape)
        for combo in itertools.product(*(shape[k] for k in keys)):
            add(_nn_shape_problems(dict(zip(keys, combo)), alg.name))
    return problems


def validate_config_fields(mc) -> List[str]:
    """Walk CONFIG_RULES dotted paths over the ModelConfig object tree."""
    problems: List[str] = []
    for path, rule in CONFIG_RULES.items():
        obj = mc
        ok = True
        for part in path.split("."):
            obj = getattr(obj, part, None)
            if obj is None:
                ok = False
                break
        if ok:
            problems.extend(_check_value(path, obj, rule))
    return problems
