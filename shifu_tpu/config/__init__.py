from .model_config import (  # noqa: F401
    Algorithm, BinningAlgorithm, BinningMethod, CustomPaths, EvalConfig,
    FilterBy, ModelBasicConf, ModelConfig, ModelNormalizeConf, ModelStatsConf,
    ModelTrainConf, ModelVarSelectConf, MultipleClassification, NormType,
    PrecisionType, RawSourceData, RunMode, SourceType,
)
from .column_config import (  # noqa: F401
    ColumnBinning, ColumnConfig, ColumnFlag, ColumnStats, ColumnType,
    build_initial_column_configs, candidate_columns, load_column_configs,
    save_column_configs, selected_columns, target_column,
)
from .path_finder import PathFinder  # noqa: F401
