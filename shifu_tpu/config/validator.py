"""ModelInspector — per-step semantic validation of ModelConfig.

Analogue of reference ``core/validator/ModelInspector.java:57,93``: each
pipeline step calls ``probe(model_config, step)`` before running; failures
raise ``ValidationError`` with every problem listed.
"""

from __future__ import annotations

import enum
import os
from typing import List

from .model_config import ModelConfig


class ModelStep(enum.Enum):
    NEW = "NEW"
    INIT = "INIT"
    STATS = "STATS"
    NORMALIZE = "NORMALIZE"
    VARSELECT = "VARSELECT"
    TRAIN = "TRAIN"
    POSTTRAIN = "POSTTRAIN"
    EVAL = "EVAL"
    EXPORT = "EXPORT"


from .errors import ErrorCode, ShifuError


class ValidationError(ShifuError, ValueError):
    """Coded config failure (1051) in the ShifuError hierarchy; ValueError
    base keeps existing ``except ValueError`` callers working."""

    def __init__(self, problems: List[str]):
        self.problems = problems
        super().__init__(ErrorCode.ERROR_MODELCONFIG_NOT_VALIDATION,
                         "\n  - " + "\n  - ".join(problems))


def _check_name_file(path: str, model_set_dir: str, what: str,
                     problems: List[str]) -> None:
    """Reference ``ModelInspector.checkFile`` via ``checkVarSelect``: a
    configured column-name file must exist."""
    if not path:
        return
    p = path if os.path.isabs(path) else os.path.join(model_set_dir, path)
    if not os.path.isfile(p):
        problems.append(f"{what} file does not exist: {path}")


def _check_column_conf(mc: ModelConfig, model_set_dir: str,
                       problems: List[str]) -> None:
    """Cross-list column checks (reference
    ``ModelInspector.checkColumnConf``, ``:213-262``): the target must not
    appear in meta / forceRemove / forceSelect, and with forceEnable the
    three lists must not overlap each other."""
    from .column_config import ns_in, read_column_name_file
    ds, vs = mc.dataSet, mc.varSelect
    target = ds.targetColumnName
    meta = read_column_name_file(ds.metaColumnNameFile, model_set_dir)
    frm = read_column_name_file(vs.forceRemoveColumnNameFile, model_set_dir)
    fsel = read_column_name_file(vs.forceSelectColumnNameFile,
                                 model_set_dir)
    # NSColumn equality throughout — a bare name matches its namespaced
    # variant, the same matching the runtime force/meta application uses
    if target and ns_in(target, meta):
        problems.append("the target column must not be a meta column")
    if vs.forceEnable and target and ns_in(target, frm):
        problems.append("the target column must not be force-removed")
    if vs.forceEnable and target and ns_in(target, fsel):
        problems.append("the target column must not be force-selected")
    if vs.forceEnable:
        for a, b, an, bn in ((meta, frm, "meta", "forceRemove"),
                             (meta, fsel, "meta", "forceSelect"),
                             (fsel, frm, "forceSelect", "forceRemove")):
            both = sorted(x for x in a if ns_in(x, b))
            if both:
                problems.append(
                    f"column(s) {both[:5]} appear in both {an} "
                    f"and {bn} lists")


def probe(mc: ModelConfig, step: ModelStep, model_set_dir: str = ".") -> None:
    problems: List[str] = []

    if not mc.basic.name:
        problems.append("basic.name must not be empty")

    # meta-driven field schema (reference MetaFactory/ModelConfigMeta.json):
    # declarative type/range/enum checks over the whole config tree
    from .meta import validate_config_fields, validate_train_conf
    problems.extend(validate_config_fields(mc))
    if step == ModelStep.TRAIN:
        # every train#params key checked; unknown keys (typos) are hard
        # errors; grid-search candidate lists expand per trial
        problems.extend(validate_train_conf(mc.train))

    if step in (ModelStep.INIT, ModelStep.STATS, ModelStep.NORMALIZE,
                ModelStep.VARSELECT, ModelStep.TRAIN, ModelStep.POSTTRAIN):
        ds = mc.dataSet
        if not ds.dataPath:
            problems.append("dataSet.dataPath must be set")
        elif step == ModelStep.INIT and "://" not in ds.dataPath:
            # reference checkRawData → checkFile (:359-372, :939);
            # dataPath may be a glob ('data/part-*') — resolve it the way
            # the reader does rather than os.path.exists
            p = ds.dataPath if os.path.isabs(ds.dataPath) \
                else os.path.join(model_set_dir, ds.dataPath)
            import glob as _glob
            if not (os.path.exists(p) or _glob.glob(p)):
                problems.append(
                    f"dataSet.dataPath does not exist: {ds.dataPath}")
        if not ds.targetColumnName:
            problems.append("dataSet.targetColumnName must be set")
        if not ds.posTags and not ds.negTags:
            problems.append("dataSet.posTags/negTags must define the target classes")
        overlap = set(map(str, ds.posTags)) & set(map(str, ds.negTags))
        if overlap:
            problems.append(f"posTags and negTags overlap: {sorted(overlap)}")
        _check_column_conf(mc, model_set_dir, problems)

    if step == ModelStep.STATS:
        # reference checkStatsConf (:263-305)
        from .model_config import BinningAlgorithm, BinningMethod
        st = mc.stats
        multiclass = mc.is_multi_class()
        per_class = (BinningMethod.EqualPositive, BinningMethod.EqualNegtive,
                     BinningMethod.WeightEqualPositive,
                     BinningMethod.WeightEqualNegative)
        if multiclass and st.binningMethod in per_class:
            problems.append("multi-class classification cannot use "
                            "EqualPositive/EqualNegtive binning methods")
        if multiclass and st.binningAlgorithm != BinningAlgorithm.SPDTI:
            problems.append("only the SPDTI binning algorithm supports "
                            "multi-class classification")
        # maxNumBin range lives in the meta schema (single source of truth)

    if step in (ModelStep.VARSELECT, ModelStep.TRAIN):
        # reference checkVarSelect (:316-357): configured force/candidate
        # files must exist
        vs = mc.varSelect
        if vs.forceEnable:
            _check_name_file(vs.candidateColumnNameFile, model_set_dir,
                             "varSelect.candidateColumnNameFile", problems)
            _check_name_file(vs.forceRemoveColumnNameFile, model_set_dir,
                             "varSelect.forceRemoveColumnNameFile", problems)
            _check_name_file(vs.forceSelectColumnNameFile, model_set_dir,
                             "varSelect.forceSelectColumnNameFile", problems)

    if step == ModelStep.TRAIN:
        # cross-field rules the per-key schema can't express (NN shape
        # consistency lives in meta.validate_train_params, per trial;
        # reference checkTrainSetting :451-560)
        tr = mc.train
        if tr.isCrossValidation and tr.numKFold < 2:
            problems.append("train.numKFold must be >= 2 when isCrossValidation")
        if tr.numKFold is not None and tr.numKFold > 20:
            # reference checkTrainSetting: k-fold capped at 20
            problems.append("train.numKFold must be <= 20")
        # baggingNum / rates / epochs / convergenceThreshold ranges live in
        # the meta schema (meta.py CONFIG_FIELD_RULES), checked above

    if step == ModelStep.EVAL:
        if not mc.evals:
            problems.append("no eval sets configured")
        for e in mc.evals:
            if not e.name:
                problems.append("eval set without a name")
            if not e.dataSet.dataPath:
                problems.append(f"eval {e.name}: dataSet.dataPath must be set")

    if problems:
        raise ValidationError(problems)
