"""ModelInspector — per-step semantic validation of ModelConfig.

Analogue of reference ``core/validator/ModelInspector.java:57,93``: each
pipeline step calls ``probe(model_config, step)`` before running; failures
raise ``ValidationError`` with every problem listed.
"""

from __future__ import annotations

import enum
import os
from typing import List

from .model_config import ModelConfig


class ModelStep(enum.Enum):
    NEW = "NEW"
    INIT = "INIT"
    STATS = "STATS"
    NORMALIZE = "NORMALIZE"
    VARSELECT = "VARSELECT"
    TRAIN = "TRAIN"
    POSTTRAIN = "POSTTRAIN"
    EVAL = "EVAL"
    EXPORT = "EXPORT"
    REFRESH = "REFRESH"


from .errors import ErrorCode, ShifuError


class ValidationError(ShifuError, ValueError):
    """Coded config failure (1051) in the ShifuError hierarchy; ValueError
    base keeps existing ``except ValueError`` callers working."""

    def __init__(self, problems: List[str]):
        self.problems = problems
        super().__init__(ErrorCode.ERROR_MODELCONFIG_NOT_VALIDATION,
                         "\n  - " + "\n  - ".join(problems))


def _check_data_path(path: str, model_set_dir: str, what: str,
                     problems: List[str]) -> None:
    """Local data-path existence, resolved the way the reader does (glob
    patterns included); remote schemes are checked at read time."""
    if "://" in path:
        return
    p = path if os.path.isabs(path) else os.path.join(model_set_dir, path)
    import glob as _glob
    if not (os.path.exists(p) or _glob.glob(p)):
        problems.append(f"{what} does not exist: {path}")


def _check_name_file(path: str, model_set_dir: str, what: str,
                     problems: List[str]) -> None:
    """Reference ``ModelInspector.checkFile`` via ``checkVarSelect``: a
    configured column-name file must exist."""
    if not path:
        return
    p = path if os.path.isabs(path) else os.path.join(model_set_dir, path)
    if not os.path.isfile(p):
        problems.append(f"{what} file does not exist: {path}")


def _check_column_conf(mc: ModelConfig, model_set_dir: str,
                       problems: List[str]) -> None:
    """Cross-list column checks (reference
    ``ModelInspector.checkColumnConf``, ``:213-262``): the target must not
    appear in meta / forceRemove / forceSelect, and with forceEnable the
    three lists must not overlap each other."""
    from .column_config import ns_in, read_column_name_file
    ds, vs = mc.dataSet, mc.varSelect
    target = ds.targetColumnName
    meta = read_column_name_file(ds.metaColumnNameFile, model_set_dir)
    frm = read_column_name_file(vs.forceRemoveColumnNameFile, model_set_dir)
    fsel = read_column_name_file(vs.forceSelectColumnNameFile,
                                 model_set_dir)
    # NSColumn equality throughout — a bare name matches its namespaced
    # variant, the same matching the runtime force/meta application uses
    if target and ns_in(target, meta):
        problems.append("the target column must not be a meta column")
    if vs.forceEnable and target and ns_in(target, frm):
        problems.append("the target column must not be force-removed")
    if vs.forceEnable and target and ns_in(target, fsel):
        problems.append("the target column must not be force-selected")
    if vs.forceEnable:
        for a, b, an, bn in ((meta, frm, "meta", "forceRemove"),
                             (meta, fsel, "meta", "forceSelect"),
                             (fsel, frm, "forceSelect", "forceRemove")):
            both = sorted(x for x in a if ns_in(x, b))
            if both:
                problems.append(
                    f"column(s) {both[:5]} appear in both {an} "
                    f"and {bn} lists")


def probe(mc: ModelConfig, step: ModelStep, model_set_dir: str = ".") -> None:
    problems: List[str] = []

    if not mc.basic.name:
        problems.append("basic.name must not be empty")

    # meta-driven field schema (reference MetaFactory/ModelConfigMeta.json):
    # declarative type/range/enum checks over the whole config tree
    from .meta import validate_config_fields, validate_train_conf
    problems.extend(validate_config_fields(mc))
    if step == ModelStep.TRAIN:
        # every train#params key checked; unknown keys (typos) are hard
        # errors; grid-search candidate lists expand per trial
        problems.extend(validate_train_conf(mc.train))
        # TENSORFLOW remaps to the native NN trainer — TF-only params it
        # would silently ignore are a loud, listed failure
        from .meta import tf_ignored_param_problems
        problems.extend(tf_ignored_param_problems(mc.train))

    if step in (ModelStep.INIT, ModelStep.STATS, ModelStep.NORMALIZE,
                ModelStep.VARSELECT, ModelStep.TRAIN, ModelStep.POSTTRAIN):
        ds = mc.dataSet
        if not ds.dataPath:
            problems.append("dataSet.dataPath must be set")
        elif step == ModelStep.INIT:
            # reference checkRawData → checkFile (:359-372, :939)
            _check_data_path(ds.dataPath, model_set_dir,
                             "dataSet.dataPath", problems)
        if step == ModelStep.INIT and ds.headerPath and \
                "://" not in ds.headerPath:
            # reference checkRawData also probes the header file (:366-369)
            hp = ds.headerPath if os.path.isabs(ds.headerPath) \
                else os.path.join(model_set_dir, ds.headerPath)
            if not os.path.isfile(hp):
                problems.append(
                    f"dataSet.headerPath does not exist: {ds.headerPath}")
        if not ds.targetColumnName:
            problems.append("dataSet.targetColumnName must be set")
        if not ds.posTags and not ds.negTags:
            problems.append("dataSet.posTags/negTags must define the target classes")
        overlap = set(map(str, ds.posTags)) & set(map(str, ds.negTags))
        if overlap:
            problems.append(f"posTags and negTags overlap: {sorted(overlap)}")
        _check_column_conf(mc, model_set_dir, problems)

    if step == ModelStep.STATS:
        # reference probe() at STATS verifies the configured column-name
        # files exist (:121-131) before checkStatsConf
        _check_name_file(mc.dataSet.metaColumnNameFile, model_set_dir,
                         "dataSet.metaColumnNameFile", problems)
        _check_name_file(mc.dataSet.categoricalColumnNameFile,
                         model_set_dir,
                         "dataSet.categoricalColumnNameFile", problems)
        # reference checkStatsConf (:263-305)
        from .model_config import BinningAlgorithm, BinningMethod
        st = mc.stats
        multiclass = mc.is_multi_class()
        per_class = (BinningMethod.EqualPositive, BinningMethod.EqualNegtive,
                     BinningMethod.WeightEqualPositive,
                     BinningMethod.WeightEqualNegative)
        if multiclass and st.binningMethod in per_class:
            problems.append("multi-class classification cannot use "
                            "EqualPositive/EqualNegtive binning methods")
        if multiclass and st.binningAlgorithm != BinningAlgorithm.SPDTI:
            problems.append("only the SPDTI binning algorithm supports "
                            "multi-class classification")
        # maxNumBin range lives in the meta schema (single source of truth)

    if step in (ModelStep.VARSELECT, ModelStep.TRAIN):
        # reference checkVarSelect (:316-357): configured force/candidate
        # files must exist
        vs = mc.varSelect
        if vs.forceEnable:
            _check_name_file(vs.candidateColumnNameFile, model_set_dir,
                             "varSelect.candidateColumnNameFile", problems)
            _check_name_file(vs.forceRemoveColumnNameFile, model_set_dir,
                             "varSelect.forceRemoveColumnNameFile", problems)
            _check_name_file(vs.forceSelectColumnNameFile, model_set_dir,
                             "varSelect.forceSelectColumnNameFile", problems)
        # reference checkVarSelect :335-343: postCorrelationMetric SE only
        # composes with filterBy SE (the SE stats exist only then); the
        # value itself is an enum (reference PostCorrelationMetric)
        pcm = (vs.postCorrelationMetric or "").upper()
        if pcm and pcm not in ("IV", "KS", "SE"):
            problems.append("varSelect.postCorrelationMetric must be one "
                            f"of IV/KS/SE, got {vs.postCorrelationMetric!r}")
        if pcm == "SE" and vs.filterBy.name != "SE":
            problems.append("varSelect.filterBy and "
                            "varSelect.postCorrelationMetric must both be "
                            "SE (reference ModelInspector.checkVarSelect)")

    if step == ModelStep.TRAIN:
        # cross-field rules the per-key schema can't express (NN shape
        # consistency lives in meta.validate_train_params, per trial;
        # reference checkTrainSetting :451-560)
        from .model_config import (Algorithm, MultipleClassification)
        tr = mc.train
        if tr.isCrossValidation and tr.numKFold < 2:
            problems.append("train.numKFold must be >= 2 when isCrossValidation")
        if tr.numKFold is not None and tr.numKFold > 20:
            # reference checkTrainSetting: k-fold capped at 20
            problems.append("train.numKFold must be <= 20")
        multiclass = mc.is_multi_class() and len(mc.dataSet.posTags) > 2
        ova_algs = (Algorithm.NN, Algorithm.RF, Algorithm.GBT, Algorithm.DT)
        if multiclass and \
                tr.multiClassifyMethod == MultipleClassification.ONEVSALL \
                and tr.algorithm not in ova_algs:
            # reference checkTrainSetting :513-520
            problems.append("'one vs all' multi-class works with "
                            "RF/GBT/DT/NN only")
        if multiclass and \
                tr.multiClassifyMethod == MultipleClassification.NATIVE \
                and tr.algorithm == Algorithm.RF:
            # reference checkTrainSetting :522-534
            imp = str((tr.params or {}).get("Impurity", "entropy")).lower()
            if imp not in ("entropy", "gini"):
                problems.append("Impurity must be entropy/gini for NATIVE "
                                "multi-class RF")
        if str((tr.params or {}).get("Loss", "")).lower() == "hinge" and \
                tr.algorithm != Algorithm.SVM:
            problems.append("Loss 'hinge' is the SVM objective — use "
                            "algorithm SVM (or log/squared/absolute)")
        # baggingNum / rates / epochs / convergenceThreshold ranges live in
        # the meta schema (meta.py CONFIG_FIELD_RULES), checked above

    if step == ModelStep.EVAL:
        if not mc.evals:
            problems.append("no eval sets configured")
        for e in mc.evals:
            if not e.name:
                problems.append("eval set without a name")
            if not e.dataSet.dataPath:
                problems.append(f"eval {e.name}: dataSet.dataPath must be set")
            else:
                # reference probe() EVAL loop: checkRawData per eval set
                _check_data_path(e.dataSet.dataPath, model_set_dir,
                                 f"eval {e.name}: dataPath", problems)
            _check_name_file(e.scoreMetaColumnNameFile, model_set_dir,
                             f"eval {e.name}: scoreMetaColumnNameFile",
                             problems)
            if e.performanceBucketNum is not None and \
                    not (0 < e.performanceBucketNum <= 1000):
                problems.append(f"eval {e.name}: performanceBucketNum must "
                                "be in (0, 1000]")

    if problems:
        raise ValidationError(problems)
