"""ModelInspector — per-step semantic validation of ModelConfig.

Analogue of reference ``core/validator/ModelInspector.java:57,93``: each
pipeline step calls ``probe(model_config, step)`` before running; failures
raise ``ValidationError`` with every problem listed.
"""

from __future__ import annotations

import enum
import os
from typing import List

from .model_config import Algorithm, ModelConfig


class ModelStep(enum.Enum):
    NEW = "NEW"
    INIT = "INIT"
    STATS = "STATS"
    NORMALIZE = "NORMALIZE"
    VARSELECT = "VARSELECT"
    TRAIN = "TRAIN"
    POSTTRAIN = "POSTTRAIN"
    EVAL = "EVAL"
    EXPORT = "EXPORT"


class ValidationError(ValueError):
    def __init__(self, problems: List[str]):
        self.problems = problems
        super().__init__("ModelConfig validation failed:\n  - " + "\n  - ".join(problems))


def probe(mc: ModelConfig, step: ModelStep, model_set_dir: str = ".") -> None:
    problems: List[str] = []

    if not mc.basic.name:
        problems.append("basic.name must not be empty")

    if step in (ModelStep.INIT, ModelStep.STATS, ModelStep.NORMALIZE,
                ModelStep.VARSELECT, ModelStep.TRAIN, ModelStep.POSTTRAIN):
        ds = mc.dataSet
        if not ds.dataPath:
            problems.append("dataSet.dataPath must be set")
        if not ds.targetColumnName:
            problems.append("dataSet.targetColumnName must be set")
        if not ds.posTags and not ds.negTags:
            problems.append("dataSet.posTags/negTags must define the target classes")
        overlap = set(map(str, ds.posTags)) & set(map(str, ds.negTags))
        if overlap:
            problems.append(f"posTags and negTags overlap: {sorted(overlap)}")

    if step == ModelStep.STATS:
        if mc.stats.maxNumBin < 2:
            problems.append("stats.maxNumBin must be >= 2")
        if not (0.0 < mc.stats.sampleRate <= 1.0):
            problems.append("stats.sampleRate must be in (0, 1]")

    if step == ModelStep.NORMALIZE:
        if mc.normalize.stdDevCutOff <= 0:
            problems.append("normalize.stdDevCutOff must be > 0")

    if step == ModelStep.TRAIN:
        tr = mc.train
        if tr.baggingNum < 1:
            problems.append("train.baggingNum must be >= 1")
        if tr.numTrainEpochs < 1:
            problems.append("train.numTrainEpochs must be >= 1")
        if not (0.0 <= tr.validSetRate < 1.0):
            problems.append("train.validSetRate must be in [0, 1)")
        if tr.isCrossValidation and tr.numKFold < 2:
            problems.append("train.numKFold must be >= 2 when isCrossValidation")
        if not (0.0 < tr.baggingSampleRate <= 1.0):
            problems.append("train.baggingSampleRate must be in (0, 1]")
        if tr.algorithm in (Algorithm.GBT, Algorithm.RF, Algorithm.DT):
            depth = tr.params.get("MaxDepth", 10)
            if not (1 <= int(depth) <= 20):
                problems.append("train.params.MaxDepth must be in [1, 20]")
        if tr.algorithm == Algorithm.NN:
            layers = tr.params.get("NumHiddenLayers")
            nodes = tr.params.get("NumHiddenNodes")
            acts = tr.params.get("ActivationFunc")
            if layers is not None and nodes is not None and int(layers) != len(nodes):
                problems.append("NumHiddenLayers must equal len(NumHiddenNodes)")
            if layers is not None and acts is not None and int(layers) != len(acts):
                problems.append("NumHiddenLayers must equal len(ActivationFunc)")

    if step == ModelStep.EVAL:
        if not mc.evals:
            problems.append("no eval sets configured")
        for e in mc.evals:
            if not e.name:
                problems.append("eval set without a name")
            if not e.dataSet.dataPath:
                problems.append(f"eval {e.name}: dataSet.dataPath must be set")

    if problems:
        raise ValidationError(problems)
