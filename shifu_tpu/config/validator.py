"""ModelInspector — per-step semantic validation of ModelConfig.

Analogue of reference ``core/validator/ModelInspector.java:57,93``: each
pipeline step calls ``probe(model_config, step)`` before running; failures
raise ``ValidationError`` with every problem listed.
"""

from __future__ import annotations

import enum
import os
from typing import List

from .model_config import ModelConfig


class ModelStep(enum.Enum):
    NEW = "NEW"
    INIT = "INIT"
    STATS = "STATS"
    NORMALIZE = "NORMALIZE"
    VARSELECT = "VARSELECT"
    TRAIN = "TRAIN"
    POSTTRAIN = "POSTTRAIN"
    EVAL = "EVAL"
    EXPORT = "EXPORT"


from .errors import ErrorCode, ShifuError


class ValidationError(ShifuError, ValueError):
    """Coded config failure (1051) in the ShifuError hierarchy; ValueError
    base keeps existing ``except ValueError`` callers working."""

    def __init__(self, problems: List[str]):
        self.problems = problems
        super().__init__(ErrorCode.ERROR_MODELCONFIG_NOT_VALIDATION,
                         "\n  - " + "\n  - ".join(problems))


def probe(mc: ModelConfig, step: ModelStep, model_set_dir: str = ".") -> None:
    problems: List[str] = []

    if not mc.basic.name:
        problems.append("basic.name must not be empty")

    # meta-driven field schema (reference MetaFactory/ModelConfigMeta.json):
    # declarative type/range/enum checks over the whole config tree
    from .meta import validate_config_fields, validate_train_conf
    problems.extend(validate_config_fields(mc))
    if step == ModelStep.TRAIN:
        # every train#params key checked; unknown keys (typos) are hard
        # errors; grid-search candidate lists expand per trial
        problems.extend(validate_train_conf(mc.train))

    if step in (ModelStep.INIT, ModelStep.STATS, ModelStep.NORMALIZE,
                ModelStep.VARSELECT, ModelStep.TRAIN, ModelStep.POSTTRAIN):
        ds = mc.dataSet
        if not ds.dataPath:
            problems.append("dataSet.dataPath must be set")
        if not ds.targetColumnName:
            problems.append("dataSet.targetColumnName must be set")
        if not ds.posTags and not ds.negTags:
            problems.append("dataSet.posTags/negTags must define the target classes")
        overlap = set(map(str, ds.posTags)) & set(map(str, ds.negTags))
        if overlap:
            problems.append(f"posTags and negTags overlap: {sorted(overlap)}")

    if step == ModelStep.TRAIN:
        # cross-field rules the per-key schema can't express (NN shape
        # consistency lives in meta.validate_train_params, per trial)
        tr = mc.train
        if tr.isCrossValidation and tr.numKFold < 2:
            problems.append("train.numKFold must be >= 2 when isCrossValidation")

    if step == ModelStep.EVAL:
        if not mc.evals:
            problems.append("no eval sets configured")
        for e in mc.evals:
            if not e.name:
                problems.append("eval set without a name")
            if not e.dataSet.dataPath:
                problems.append(f"eval {e.name}: dataSet.dataPath must be set")

    if problems:
        raise ValidationError(problems)
