"""Error-code taxonomy — reference ``exception/ShifuErrorCode.java`` +
``exception/ShifuException.java``: every user-facing failure carries a
stable numeric code and message so scripts and operators can branch on
category, not string-match tracebacks.

Codes keep the reference's numbering blocks (1000s=fs/data, 1050s=config,
1150s=data shape, 1250s=models, 1300s=eval); JVM/Hadoop-only codes (pig
jobs, HDFS copies, Akka) are dissolved with those subsystems.
"""

from __future__ import annotations

import enum


class ErrorCode(enum.Enum):
    # --- input / filesystem (1000s)
    ERROR_INPUT_NOT_FOUND = (1001, "The input data is not found")
    ERROR_HEADER_NOT_FOUND = (1002, "The header is not found")
    ERROR_LOAD_MODELCONFIG = (1003, "Could not load ModelConfig")
    ERROR_WRITE_MODELCONFIG = (1004, "Could not write ModelConfig file")
    ERROR_LOAD_COLCONFIG = (1005, "Could not load ColumnConfig")
    ERROR_WRITE_COLCONFIG = (1006, "Could not write ColumnConfig file")
    ERROR_REMOTE_SOURCE = (1007, "Remote source type needs staging to a "
                                 "local path")
    ERROR_NO_EVAL_SET = (1015, "No eval set configured")
    # --- config validation (1050s)
    ERROR_MODELCONFIG_NOT_VALIDATION = (
        1051, "The ModelConfig file did not pass the validation")
    ERROR_UNSUPPORT_ALG = (1052, "Unsupported algorithm")
    ERROR_GRIDCONFIG_NOT_VALIDATION = (
        1055, "The grid search config did not pass the validation")
    # rebuild-specific: ordered-pipeline precondition (the reference's
    # cluster steps fail inside Pig/Hadoop instead)
    ERROR_STEP_PRECONDITION = (
        1061, "A prerequisite pipeline step has not run")
    # rebuild-specific: a step's commit journal says its artifacts are
    # torn/incomplete (crash-consistency layer, pipeline/journal.py)
    ERROR_TORN_ARTIFACT = (
        1062, "A pipeline artifact is torn or incomplete")
    # rebuild-specific: the multi-controller coordinator connect retry
    # ladder exhausted (parallel/mesh.initialize_distributed) — raised
    # coded instead of hanging the launcher on a dead coordinator
    ERROR_DCN_CONNECT = (
        1063, "Could not connect to the distributed coordinator")
    # --- data shape (1150s)
    ERROR_EXCEED_COL = (1151, "Input data has more fields than the header")
    ERROR_LESS_COL = (1152, "Input data has fewer fields than the header")
    ERROR_NO_EQUAL_COLCONFIG = (
        1153, "Input data length is not equal to column config size")
    ERROR_NO_TARGET_COLUMN = (1154, "No target column in training data")
    ERROR_INVALID_TARGET_VALUE = (1155, "Invalid target value")
    # rebuild-specific: quarantined bad rows/shards exceeded
    # shifu.data.badThreshold (bounded bad-input tolerance)
    ERROR_BAD_DATA_THRESHOLD = (
        1156, "Malformed input exceeded the configured bad-data threshold")
    # --- models (1250s)
    ERROR_MODEL_FILE_NOT_FOUND = (1250, "The model file is not found")
    ERROR_FAIL_TO_LOAD_MODEL_FILE = (1251, "Failed to load the model file")
    # rebuild-specific: a trainer-state checkpoint was written under a
    # different shifu.train.precision than the resuming run — silently
    # casting the master copy / optimizer state would corrupt the resume
    ERROR_CHECKPOINT_PRECISION_MISMATCH = (
        1252, "Checkpoint precision does not match shifu.train.precision")
    # --- eval (1300s)
    ERROR_MODEL_EVALSET_DOESNT_EXIST = (1301, "The evalset doesn't exist")
    ERROR_MODEL_EVALSET_ALREADY_EXIST = (1302, "The evalset already exists")
    ERROR_EVAL_SELECTOR_EMPTY = (
        1305, "performanceScoreSelector is empty or not set properly")

    def __init__(self, code: int, message: str):
        self.code = code
        self.message = message


class ShifuError(Exception):
    """Base error with a stable code (reference ``ShifuException``)."""

    def __init__(self, error_code: ErrorCode, detail: str = ""):
        self.error_code = error_code
        msg = f"[{error_code.code}] {error_code.message}"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
