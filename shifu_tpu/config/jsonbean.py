"""Minimal JSON<->dataclass mapping with unknown-key tolerance.

The reference stores pipeline state in ``ModelConfig.json`` / ``ColumnConfig.json``
(Jackson beans, reference ``container/obj/``).  We keep the exact camelCase key
contract so model sets written by the reference load here unchanged, and vice
versa.  Unknown keys are preserved round-trip in ``extra`` instead of erroring,
mirroring Jackson's permissive deserialization config.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import typing
from typing import Any, Dict, Type, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T")


def _unwrap_optional(tp):
    if get_origin(tp) is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _coerce(tp, value):
    """Coerce a JSON value into the annotated type ``tp``."""
    if value is None:
        return None
    tp = _unwrap_optional(tp)
    origin = get_origin(tp)
    if origin in (list, typing.List):
        (elem,) = get_args(tp) or (Any,)
        return [_coerce(elem, v) for v in value]
    if origin in (dict, typing.Dict):
        args = get_args(tp)
        vt = args[1] if len(args) == 2 else Any
        return {k: _coerce(vt, v) for k, v in value.items()}
    if isinstance(tp, type) and dataclasses.is_dataclass(tp):
        return from_dict(tp, value)
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        if isinstance(tp, type) and isinstance(value, tp):
            return value
        return parse_enum(tp, value)
    if tp is float and isinstance(value, (int, float)):
        return float(value)
    if tp is int and isinstance(value, float) and value == int(value):
        return int(value)
    if tp is bool and isinstance(value, str):
        return value.strip().lower() in ("true", "1", "yes")
    return value


def parse_enum(enum_cls, value):
    """Case-insensitive enum parse, accepting both names and values.

    Mirrors the reference's forgiving deserializers (e.g. ``NormTypeDeserializer``)
    which accept ``"zscale"``/``"ZSCALE"`` alike.
    """
    if isinstance(value, enum_cls):
        return value
    s = str(value).strip()
    for member in enum_cls:
        if member.name.lower() == s.lower() or str(member.value).lower() == s.lower():
            return member
    raise ValueError(f"{s!r} is not a valid {enum_cls.__name__} "
                     f"(choices: {[m.name for m in enum_cls]})")


def from_dict(cls: Type[T], data: Dict[str, Any]) -> T:
    """Build dataclass ``cls`` from a JSON dict; unknown keys land in ``extra``."""
    if data is None:
        return None
    hints = get_type_hints(cls)
    field_names = {f.name for f in dataclasses.fields(cls)}
    kwargs = {}
    extra = {}
    for key, value in data.items():
        if key in field_names and key != "extra":
            kwargs[key] = _coerce(hints[key], value)
        else:
            extra[key] = value
    obj = cls(**kwargs)
    if extra and "extra" in field_names:
        obj.extra = extra
    return obj


def to_dict(obj) -> Any:
    """Dataclass -> JSON-ready dict (camelCase keys preserved, enums -> names)."""
    if obj is None:
        return None
    if dataclasses.is_dataclass(obj):
        out = {}
        for f in dataclasses.fields(obj):
            if f.name == "extra":
                continue
            out[f.name] = to_dict(getattr(obj, f.name))
        extra = getattr(obj, "extra", None)
        if extra:
            out.update(extra)
        return out
    if isinstance(obj, enum.Enum):
        return obj.name
    if isinstance(obj, list):
        return [to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, float) and obj != obj:  # NaN is not valid JSON
        return None
    return obj


def dumps(obj, **kw) -> str:
    kw.setdefault("indent", 2)
    return json.dumps(to_dict(obj), **kw)


def loads(cls: Type[T], s: str) -> T:
    return from_dict(cls, json.loads(s))
