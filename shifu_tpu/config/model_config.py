"""ModelConfig tree — JSON-compatible with the reference's ``ModelConfig.json``.

Mirrors the bean tree at reference ``container/obj/ModelConfig.java:57-95``:
``basic / dataSet / stats / varSelect / normalize / train / evals`` with the
same camelCase keys, so model sets are interchangeable between the reference
and this framework.  Enum families: algorithms ``ModelTrainConf.java:43``
(NN, LR, SVM, DT, RF, GBT, TENSORFLOW, WDL), norm types
``ModelNormalizeConf.java:34-46``, binning methods/algorithms
``ModelStatsConf.java:34-51``.
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import jsonbean
from .jsonbean import parse_enum


class SourceType(enum.Enum):
    LOCAL = "LOCAL"
    HDFS = "HDFS"
    S3 = "S3"
    GCS = "GCS"


class RunMode(enum.Enum):
    """LOCAL = single host; DIST/MAPRED = multi-device SPMD (TPU mesh here).

    The reference dispatches on this at ``TrainModelProcessor.java:184-201``;
    here LOCAL means single-device jit and DIST means pjit over the full mesh.
    """
    LOCAL = "local"
    DIST = "dist"
    MAPRED = "mapred"
    TPU = "tpu"


class Algorithm(enum.Enum):
    NN = "NN"
    LR = "LR"
    SVM = "SVM"
    DT = "DT"
    RF = "RF"
    GBT = "GBT"
    TENSORFLOW = "TENSORFLOW"
    WDL = "WDL"


class NormType(enum.Enum):
    """All 17 norm types of reference ``ModelNormalizeConf.java:34-46``."""
    OLD_ZSCORE = "OLD_ZSCORE"
    OLD_ZSCALE = "OLD_ZSCALE"
    ZSCORE = "ZSCORE"
    ZSCALE = "ZSCALE"
    WOE = "WOE"
    WEIGHT_WOE = "WEIGHT_WOE"
    HYBRID = "HYBRID"
    WEIGHT_HYBRID = "WEIGHT_HYBRID"
    WOE_ZSCORE = "WOE_ZSCORE"
    WOE_ZSCALE = "WOE_ZSCALE"
    WEIGHT_WOE_ZSCORE = "WEIGHT_WOE_ZSCORE"
    WEIGHT_WOE_ZSCALE = "WEIGHT_WOE_ZSCALE"
    ONEHOT = "ONEHOT"
    ZSCALE_ONEHOT = "ZSCALE_ONEHOT"
    ASIS_WOE = "ASIS_WOE"
    ASIS_PR = "ASIS_PR"
    DISCRETE_ZSCORE = "DISCRETE_ZSCORE"
    DISCRETE_ZSCALE = "DISCRETE_ZSCALE"
    ZSCALE_INDEX = "ZSCALE_INDEX"
    ZSCORE_INDEX = "ZSCORE_INDEX"
    WOE_INDEX = "WOE_INDEX"
    WOE_ZSCALE_INDEX = "WOE_ZSCALE_INDEX"

    def is_woe(self) -> bool:
        return self in (NormType.WOE, NormType.WEIGHT_WOE, NormType.WOE_ZSCORE,
                        NormType.WOE_ZSCALE, NormType.WEIGHT_WOE_ZSCORE,
                        NormType.WEIGHT_WOE_ZSCALE)

    def is_weighted(self) -> bool:
        return "WEIGHT" in self.name


class PrecisionType(enum.Enum):
    """Norm-output rounding family, reference ``NormalizeUDF.java:540-570``."""
    FLOAT7 = "FLOAT7"
    FLOAT16 = "FLOAT16"
    FLOAT32 = "FLOAT32"
    DOUBLE64 = "DOUBLE64"


class BinningMethod(enum.Enum):
    EqualNegtive = "EqualNegtive"
    EqualInterval = "EqualInterval"
    EqualPositive = "EqualPositive"
    EqualTotal = "EqualTotal"
    WeightEqualNegative = "WeightEqualNegative"
    WeightEqualInterval = "WeightEqualInterval"
    WeightEqualPositive = "WeightEqualPositive"
    WeightEqualTotal = "WeightEqualTotal"


class BinningAlgorithm(enum.Enum):
    Native = "Native"
    SPDT = "SPDT"
    SPDTI = "SPDTI"
    MunroPat = "MunroPat"
    MunroPatI = "MunroPatI"
    DynamicBinning = "DynamicBinning"


class FilterBy(enum.Enum):
    KS = "KS"
    IV = "IV"
    MIX = "MIX"
    PARETO = "PARETO"
    SE = "SE"
    ST = "ST"
    FI = "FI"
    GENETIC = "GENETIC"      # dvarsel wrapper search (core/dvarsel/)


class MultipleClassification(enum.Enum):
    NATIVE = "NATIVE"
    ONEVSALL = "ONEVSALL"
    ONEVSREST = "ONEVSREST"
    ONEVSONE = "ONEVSONE"


@dataclass
class CustomPaths:
    modelsPath: Optional[str] = None
    scorePath: Optional[str] = None
    confusionMatrixPath: Optional[str] = None
    performancePath: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ModelBasicConf:
    name: str = ""
    author: str = ""
    description: Optional[str] = None
    version: str = "0.1.0"
    runMode: RunMode = RunMode.LOCAL
    postTrainOn: bool = False
    customPaths: Optional[Dict[str, str]] = None
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class RawSourceData:
    """Reference ``container/obj/RawSourceData.java``."""
    source: SourceType = SourceType.LOCAL
    dataPath: Optional[str] = None
    validationDataPath: Optional[str] = None
    dataDelimiter: str = "|"
    headerPath: Optional[str] = None
    headerDelimiter: str = "|"
    filterExpressions: Optional[str] = None
    weightColumnName: Optional[str] = None
    targetColumnName: Optional[str] = None
    posTags: List[str] = field(default_factory=list)
    negTags: List[str] = field(default_factory=list)
    missingOrInvalidValues: List[str] = field(
        default_factory=lambda: ["", "*", "#", "?", "null", "~"])
    metaColumnNameFile: Optional[str] = None
    categoricalColumnNameFile: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ModelStatsConf:
    maxNumBin: int = 10
    cateMaxNumBin: int = 0
    binningMethod: BinningMethod = BinningMethod.EqualPositive
    sampleRate: float = 1.0
    sampleNegOnly: bool = False
    binningAlgorithm: BinningAlgorithm = BinningAlgorithm.SPDTI
    psiColumnName: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ModelVarSelectConf:
    forceEnable: bool = True
    forceSelectColumnNameFile: Optional[str] = None
    forceRemoveColumnNameFile: Optional[str] = None
    candidateColumnNameFile: Optional[str] = None
    filterEnable: bool = True
    filterNum: int = 200
    filterOutRatio: Optional[float] = None
    filterBy: FilterBy = FilterBy.KS
    postCorrelationMetric: Optional[str] = None   # IV | KS | SE (ref enum)
    autoFilterEnable: bool = False
    missingRateThreshold: float = 0.98
    correlationThreshold: float = 1.0
    minIvThreshold: float = 0.0
    minKsThreshold: float = 0.0
    params: Optional[Dict[str, Any]] = None
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ModelNormalizeConf:
    stdDevCutOff: float = 4.0
    sampleRate: float = 1.0
    sampleNegOnly: bool = False
    normType: NormType = NormType.ZSCALE
    precisionType: PrecisionType = PrecisionType.FLOAT32
    isParquet: bool = False
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ModelTrainConf:
    baggingNum: int = 1
    baggingWithReplacement: bool = False
    baggingSampleRate: float = 1.0
    validSetRate: float = 0.2
    numTrainEpochs: int = 100
    epochsPerIteration: int = 1
    trainOnDisk: bool = False
    isContinuous: bool = False
    isCrossValidation: bool = False
    numKFold: int = -1
    upSampleWeight: float = 1.0
    stratifiedSample: bool = False
    workerThreadCount: int = 4
    algorithm: Algorithm = Algorithm.NN
    params: Dict[str, Any] = field(default_factory=dict)
    gridConfigFile: Optional[str] = None
    multiClassifyMethod: MultipleClassification = MultipleClassification.NATIVE
    convergenceThreshold: float = 0.0
    earlyStopEnable: bool = False
    customPaths: Optional[Dict[str, str]] = None
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class EvalConfig:
    name: str = ""
    dataSet: RawSourceData = field(default_factory=RawSourceData)
    performanceBucketNum: int = 10
    performanceScoreSelector: str = "mean"
    scoreMetaColumnNameFile: Optional[str] = None
    gsMetricName: Optional[str] = None
    customPaths: Optional[CustomPaths] = None
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ModelConfig:
    basic: ModelBasicConf = field(default_factory=ModelBasicConf)
    dataSet: RawSourceData = field(default_factory=RawSourceData)
    stats: ModelStatsConf = field(default_factory=ModelStatsConf)
    varSelect: ModelVarSelectConf = field(default_factory=ModelVarSelectConf)
    normalize: ModelNormalizeConf = field(default_factory=ModelNormalizeConf)
    train: ModelTrainConf = field(default_factory=ModelTrainConf)
    evals: List[EvalConfig] = field(default_factory=list)
    extra: Dict[str, Any] = field(default_factory=dict)

    # ------------------------------------------------------------------ io
    @classmethod
    def load(cls, path: str) -> "ModelConfig":
        with open(path) as f:
            return jsonbean.loads(cls, f.read())

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(jsonbean.dumps(self))
            f.write("\n")
        os.replace(tmp, path)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelConfig":
        return jsonbean.from_dict(cls, d)

    def to_dict(self) -> Dict[str, Any]:
        return jsonbean.to_dict(self)

    # ------------------------------------------------------------- helpers
    @property
    def model_set_name(self) -> str:
        return self.basic.name

    @property
    def algorithm(self) -> Algorithm:
        return self.train.algorithm

    def is_classification(self) -> bool:
        return bool(self.dataSet.posTags or self.dataSet.negTags)

    def is_multi_class(self) -> bool:
        return len(self.dataSet.posTags) > 1 and not self.dataSet.negTags

    def is_regression(self) -> bool:
        return not self.is_multi_class()

    def flatten_tags(self) -> List[str]:
        return list(self.dataSet.posTags) + list(self.dataSet.negTags)

    def get_eval(self, name: str) -> Optional[EvalConfig]:
        for e in self.evals:
            if e.name == name:
                return e
        return None

    @classmethod
    def create(cls, name: str, description: str = "") -> "ModelConfig":
        """Fresh config for ``shifu new`` (reference ``CreateModelProcessor``)."""
        mc = cls()
        mc.basic.name = name
        mc.basic.description = description or f"model set {name}"
        mc.dataSet.dataPath = os.path.join(".", name, "data")
        mc.evals = [EvalConfig(name="Eval1",
                               dataSet=RawSourceData(dataPath=os.path.join(".", name, "evaldata")))]
        return mc

