"""Central knob registry — every ``-Dshifu.*`` property and ``SHIFU_*``
environment variable this codebase reads, declared in ONE place.

The reference Shifu scatters its configuration across ``PropertyKey``
constants, ``shifuconfig`` and ad-hoc ``System.getProperty`` reads — the
config-sprawl failure mode "Hidden Technical Debt in Machine Learning
Systems" names as what kills ML pipelines at scale.  Eleven PRs in we
had the same debt: 100+ knob literals across 35+ files with no central
manifest, so a typo'd ``-Dshifu.serve.maxDelayMS`` silently no-ops and
a doc mentioning a dead knob rots forever.

The ``knob-registry`` lint rule (``shifu_tpu/lint/rules.py``) enforces:

- every ``environment.get_*``/``set_property`` / ``os.environ`` read of
  a ``shifu.*`` / ``SHIFU_*`` literal anywhere in ``shifu_tpu/`` must
  name a knob declared here;
- every ``-Dshifu.*`` / ``SHIFU_*`` token *mentioned* in a docstring,
  help text or error message must be declared too (a truncated
  line-wrapped mention passes if it is a prefix of a declared name);
- every declared knob must appear in the README knob table, and must be
  read somewhere (no dead declarations).

Property names match case-insensitively (``environment.get_property``
lowercases on fallback, and ``SHIFU_FOO_BAR`` env vars fold to
``shifu.foo.bar``), so ``shifu.train.windowrows`` resolves to the
declared ``shifu.train.windowRows``.

Declaring a knob: add a :class:`Knob` to ``KNOBS`` below, in its plane's
section, and add the name to the README table (``shifu-tpu lint`` fails
otherwise — the table cannot rot)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

__all__ = ["Knob", "KNOBS", "is_declared", "is_declared_prefix",
           "knob_table_markdown"]


@dataclass(frozen=True)
class Knob:
    name: str            # "shifu.serve.maxDelayMs" or "SHIFU_TREE_BATCH"
    kind: str            # "property" (-D / shifuconfig) or "env"
    type: str            # int | float | bool | str
    default: str         # rendered default ("" = unset / derived)
    doc: str             # one line


def _k(name: str, kind: str, type_: str, default: str, doc: str) -> Knob:
    return Knob(name, kind, type_, default, doc)


_DECLS: Tuple[Knob, ...] = (
    # ---- telemetry / observability plane
    _k("shifu.telemetry", "property", "bool", "off",
       "master telemetry switch (same as --telemetry / SHIFU_TPU_TELEMETRY)"),
    _k("shifu.tpu.telemetry", "property", "bool", "off",
       "alias of shifu.telemetry (env-folded SHIFU_TPU_TELEMETRY form)"),
    _k("shifu.telemetry.fence", "property", "bool", "off",
       "block_until_ready-fence spans for exact device timings"),
    _k("shifu.telemetry.heartbeatSeconds", "property", "float", "5",
       "heartbeat commit interval for obs/health writers"),
    _k("shifu.profile", "property", "str", "",
       "jax.profiler capture dir for this step (--profile)"),
    _k("shifu.drift.psiThreshold", "property", "float", "0.25",
       "PSI above which the drift monitor flags a column"),
    _k("SHIFU_TPU_TELEMETRY", "env", "bool", "0",
       "enable telemetry (1/true/on; same as shifu.telemetry)"),
    _k("SHIFU_TPU_TELEMETRY_FENCE", "env", "bool", "0",
       "env form of shifu.telemetry.fence"),
    _k("SHIFU_TPU_HEARTBEAT_S", "env", "float", "5",
       "env form of shifu.telemetry.heartbeatSeconds"),
    _k("SHIFU_TPU_LOG", "env", "str", "",
       "library log level override (DEBUG/INFO/...)"),
    _k("SHIFU_TPU_PEAK_FLOPS", "env", "float", "",
       "override the backend peak-FLOP/s table (roofline report)"),
    _k("SHIFU_TPU_PEAK_BW", "env", "float", "",
       "override the backend peak-bytes/s table (roofline report)"),
    # ---- fault injection
    _k("shifu.faults", "property", "str", "",
       "deterministic fault spec: site:point=value:action[@count],..."),
    _k("SHIFU_TPU_FAULTS", "env", "str", "",
       "env form of shifu.faults"),
    # ---- IO / artifact plane
    _k("shifu.io.retries", "property", "int", "3",
       "transient-IO retry attempts absorbed before re-raising"),
    _k("shifu.io.retryBaseMs", "property", "int", "50",
       "retry backoff base (doubles per attempt, jittered)"),
    _k("shifu.data.badThreshold", "property", "float", "0",
       "bounded bad-input tolerance: rows/shards quarantined up to this"),
    # ---- ingest / streaming plane
    _k("shifu.stream.spill", "property", "bool", "true",
       "mmap binned spill cache for re-sweeps"),
    _k("shifu.stream.spillBudgetBytes", "property", "int", "8589934592",
       "spill cache size budget (bytes)"),
    _k("shifu.stream.spillDir", "property", "str", "",
       "spill cache directory (default: under the modelset tmp)"),
    _k("shifu.stream.prefetch", "property", "int", "2",
       "prepared-window pipeline depth (H2D double-buffering)"),
    _k("SHIFU_TPU_PREFETCH", "env", "int", "2",
       "env form of shifu.stream.prefetch"),
    _k("shifu.ingest.parseWorkers", "property", "int", "-1",
       "raw-shard parse pool threads (-1 auto min(cores,8); 0 inline)"),
    _k("shifu.ingest.rawCache", "property", "bool", "true",
       "columnar raw-parse cache shared across pipeline steps"),
    _k("shifu.ingest.rawCacheBudgetBytes", "property", "int", "8589934592",
       "raw cache size budget (bytes; overflow aborts permanently)"),
    _k("shifu.norm.wireOnly", "property", "bool", "true",
       "norm emits the clean plane direct-to-wire (no clean npz)"),
    # ---- stats plane
    _k("shifu.stats.onePass", "property", "bool", "true",
       "one-pass fused stats sweep (false restores two-pass)"),
    _k("shifu.stats.fusedBudgetBytes", "property", "int", "1073741824",
       "device-resident budget for the fused stats sweep"),
    _k("shifu.stats.checkpointChunks", "property", "int", "0",
       "checkpoint accumulator partials every N chunks (0 = off)"),
    _k("shifu.rebin.ivKeepRatio", "property", "float", "0.95",
       "stats -rebin: IV mass to keep when merging bins"),
    _k("shifu.rebin.minBinInstCnt", "property", "int", "0",
       "stats -rebin: minimum instances per bin"),
    _k("shifu.rebin.maxNumBin", "property", "int", "",
       "stats -rebin: target bin count (default: stats.maxNumBin)"),
    # ---- train plane
    _k("shifu.train.streaming", "property", "str", "auto",
       "stream training windows from disk (on/off/auto by memory budget)"),
    _k("shifu.train.memoryBudgetBytes", "property", "int", "2147483648",
       "in-RAM plane budget driving the streaming auto decision"),
    _k("shifu.train.windowRows", "property", "int", "0",
       "streamed window height (0 = derived)"),
    _k("shifu.train.deviceCacheBytes", "property", "int", "1073741824",
       "HBM-resident window cache budget (ResidentCache)"),
    _k("shifu.train.precision", "property", "str", "f32",
       "training precision ladder: f32 | bf16 | mixed"),
    # ---- WDL sharded categorical plane (train/wdl_shard)
    _k("shifu.wdl.shardTables", "property", "str", "auto",
       "row-shard WDL embed/wide tables + optimizer moments over the "
       "data axis (on/off/auto by shardMinBytes)"),
    _k("shifu.wdl.shardMinBytes", "property", "int", "67108864",
       "auto gate: shard the WDL categorical plane when params+moments "
       "exceed this many bytes"),
    _k("shifu.wdl.hashBuckets", "property", "int", "0",
       "hashed-ID bucket space: categorical columns wider than this map "
       "through splitmix64 (0 = exact ids; params.HashBuckets wins)"),
    _k("shifu.wdl.serveCopy", "property", "str", "auto",
       "serve-time WDL table copy: full | sharded | hot | auto (sharded "
       "when multi-device and over shardMinBytes)"),
    _k("shifu.wdl.serveHotRows", "property", "int", "65536",
       "hot serve copy: exact head rows kept per table (cold tail "
       "squashes to one fallback row)"),
    _k("shifu.tree.tailSuperBatchBytes", "property", "int", "268435456",
       "histogram budget deriving the disk-tail tree super-batch"),
    _k("shifu.tree.tailCoarseToFine", "property", "bool", "auto",
       "GBT disk-tail coarse-to-fine speculation (default on for "
       "accelerator backends)"),
    _k("shifu.tree.tailCandidateK", "property", "int", "0",
       "bounded-candidate split scan K for the disk tail (0 = exact)"),
    _k("shifu.tree.tailHistBudgetBytes", "property", "int", "268435456",
       "per-sweep histogram budget for the streamed tail"),
    _k("shifu.tree.quantKernel", "property", "str", "auto",
       "uint8 quantized tree traversal (auto/0/force; env "
       "SHIFU_TREE_QUANT)"),
    _k("SHIFU_TREE_BATCH", "env", "int", "8",
       "resident RF/GBT trees grown per jitted program"),
    _k("SHIFU_TAIL_TREE_BATCH", "env", "int", "",
       "disk-tail super-batch width override (default budget-derived)"),
    _k("SHIFU_TREE_TAIL_C2F", "env", "bool", "auto",
       "env form of shifu.tree.tailCoarseToFine"),
    _k("SHIFU_TREE_QUANT", "env", "str", "auto",
       "quantized traversal: 0 pins classic, force pins the kernel"),
    _k("SHIFU_TREE_ONEHOT", "env", "str", "auto",
       "one-hot-matmul histogram path override"),
    _k("SHIFU_HIST_PALLAS", "env", "bool", "1",
       "Pallas histogram kernels (0 = jnp scatter fallback)"),
    _k("SHIFU_HIST_NBLK", "env", "int", "0",
       "Pallas histogram row-block count override (0 = derived)"),
    # ---- varselect plane
    _k("shifu.varsel.batched", "property", "bool", "true",
       "mask-batched streamed sensitivity (false = per-column oracle)"),
    _k("shifu.varsel.maskBatch", "property", "int", "32",
       "candidate masks evaluated per vmapped program"),
    # ---- serving plane
    _k("shifu.serve.buckets", "property", "str", "1/8/64/512",
       "padded-batch bucket ladder (slash-separated rungs)"),
    _k("shifu.serve.maxDelayMs", "property", "float", "2",
       "micro-batcher deadline flush bound"),
    _k("shifu.serve.bucketRefineEvery", "property", "int", "512",
       "batches between occupancy-driven ladder refinements (0 = off)"),
    _k("shifu.serve.traceSampleRate", "property", "float", "0",
       "per-request trace head-sampling rate (0..1)"),
    _k("shifu.serve.sloP99Ms", "property", "float", "",
       "p99 latency SLO (default 2x maxDelayMs)"),
    _k("shifu.serve.sloAvailability", "property", "float", "0.999",
       "availability SLO for error-budget burn alerts"),
    _k("shifu.serve.generations", "property", "int", "3",
       "previous serving generations kept rollback-able per key"),
    _k("shifu.serve.fleetPollMs", "property", "float", "500",
       "fleet router health-poll cadence across replicas"),
    _k("shifu.serve.fleetStaleS", "property", "float", "10",
       "replica unreachable this long is declared dead and drained"),
    _k("shifu.serve.canaryFrac", "property", "float", "0",
       "coordinated-swap canary slice: commit ceil(frac*N) replicas, "
       "abort the rest (0 = commit the whole fleet)"),
    _k("shifu.serve.maxQueueRows", "property", "int", "0",
       "admission cap: queued rows beyond this fast-fail with a coded "
       "429/overloaded (0 = auto, 128x the top bucket rung)"),
    _k("shifu.serve.requestDeadlineMs", "property", "float", "0",
       "default per-request deadline; expired tickets are shed before "
       "pad/launch with a coded 504 (0 = none; X-Shifu-Deadline-Ms "
       "overrides per request)"),
    _k("shifu.serve.retryBudgetFrac", "property", "float", "0.1",
       "router retry budget: requeues allowed per recent success "
       "(token bucket; 0 = no retries)"),
    _k("shifu.serve.hedgeMs", "property", "float", "0",
       "hedged second dispatch after the router-observed p99 (this "
       "value is the floor/fallback delay; 0 = hedging off)"),
    _k("shifu.serve.breakerFailures", "property", "int", "3",
       "consecutive transport/5xx failures that open a replica's "
       "circuit breaker (half-open probe after cooldown; 0 = off)"),
    _k("shifu.serve.brownout", "property", "bool", "true",
       "brownout degradation: sustained SLO burn or queue buildup "
       "flips the worker into a degraded mode (shrunk flush deadline, "
       "sampling/refinement off) with hysteresis on recovery"),
    # ---- continual refresh plane (refresh/)
    _k("shifu.refresh.psiThreshold", "property", "float", "",
       "PSI breach that triggers a refresh cycle (default: "
       "shifu.drift.psiThreshold)"),
    _k("shifu.refresh.intervalS", "property", "float", "0",
       "wall-clock refresh schedule in seconds (0 = drift-only)"),
    _k("shifu.refresh.cooldownS", "property", "float", "300",
       "minimum seconds between refresh cycles (thrash guard: a "
       "sustained breach records ONE skip per window)"),
    _k("shifu.refresh.minAucDelta", "property", "float", "0",
       "holdout AUC bar a candidate must clear to promote (0 = strict "
       "non-regression)"),
    _k("shifu.refresh.probationS", "property", "float", "60",
       "post-promotion probation window watched for SLO burn / canary "
       "parity before the promotion is final"),
    _k("shifu.refresh.units", "property", "int", "0",
       "extra epochs/trees per warm retrain (0 = the configured "
       "numTrainEpochs / TreeNum budget, warm-started)"),
    _k("shifu.refresh.canaryRows", "property", "int", "64",
       "canary batch size pinned at promotion for probation bit-parity "
       "checks"),
    # ---- model-quality observability plane (obs/scorelog+outcomes+quality)
    _k("shifu.scorelog.sampleRate", "property", "float", "0",
       "serve-path score-log head-sampling rate (0..1; 0 = plane off)"),
    _k("shifu.scorelog.segmentBytes", "property", "int", "1048576",
       "score-log segment size before atomic rotation commit"),
    _k("shifu.scorelog.budgetBytes", "property", "int", "67108864",
       "score-log disk budget: oldest committed segments pruned over "
       "this"),
    _k("shifu.quality.watermarkS", "property", "float", "3600",
       "delayed-label join window: predictions older than this are "
       "evicted unjoined"),
    _k("shifu.quality.aucDelta", "property", "float", "0.05",
       "live-AUC drop vs the posttrain baseline that marks the model "
       "degraded (the quality refresh trigger)"),
    _k("shifu.quality.psiThreshold", "property", "float", "",
       "score-distribution PSI breach threshold (default: "
       "shifu.drift.psiThreshold)"),
    _k("shifu.quality.minJoined", "property", "int", "64",
       "joined rows per generation before live AUC / calibration / "
       "score PSI are judged"),
    # ---- multi-host / elastic DCN plane
    _k("shifu.dcn.elastic", "property", "bool", "false",
       "quorum-gated elastic multi-controller step protocol (the "
       "in-mesh psum path stays the fast default)"),
    _k("shifu.dcn.quorumFrac", "property", "float", "0.97",
       "fraction of live controllers whose contributions close a step "
       "(also the monitor's QUORUM LOST threshold)"),
    _k("shifu.dcn.stepTimeoutMs", "property", "float", "2000",
       "elastic step timeout: survivors proceed with the partial "
       "aggregate after this"),
    _k("shifu.dcn.staleness", "property", "int", "0",
       "bounded-staleness window: late contributions fold into a close "
       "within this many steps (0 = quorum mode, drop late)"),
    # ---- multi-host / launcher
    _k("SHIFU_COORDINATOR", "env", "str", "",
       "jax.distributed coordinator address (host:port); unset = "
       "single-process"),
    _k("SHIFU_NUM_PROCESSES", "env", "int", "",
       "process count for the multi-controller job"),
    _k("SHIFU_PROCESS_ID", "env", "int", "",
       "this controller's process index"),
    _k("SHIFU_MH_CACHE", "env", "str", "/tmp/shifu_tpu_mh_cache",
       "multihost demo/bench workers' own XLA compile-cache dir"),
    _k("SHIFU_TPU_HOME", "env", "str", "",
       "home dir holding conf/shifuconfig global properties"),
    _k("SHIFU_HOME", "env", "str", "",
       "fallback for SHIFU_TPU_HOME (reference launcher compat)"),
    # ---- bench harness
    _k("SHIFU_BENCH_TAIL_FLOOR", "env", "float", "",
       "bench --plane tail throughput floor (rows*trees/s)"),
    _k("SHIFU_BENCH_SERVE_FLOOR", "env", "float", "",
       "bench --plane serve sustained-QPS floor"),
    _k("SHIFU_BENCH_SERVE_P99_SLOP_MS", "env", "float", "",
       "bench serve p99-vs-deadline slop allowance"),
    _k("SHIFU_BENCH_E2E_ROWS", "env", "int", "",
       "bench --plane e2e generated row count"),
    _k("SHIFU_BENCH_INGEST_ROWS", "env", "int", "2000000",
       "bench --plane ingest generated row count (serial vs pooled legs)"),
    _k("SHIFU_BENCH_REFRESH_ROWS", "env", "int", "200000",
       "bench --plane refresh base row count (drift stream adds 1/4)"),
    _k("SHIFU_BENCH_WDL_TABLE_ROWS", "env", "int", "",
       "bench wdl_shard: per-table cardinality for the oversized-table "
       "scenario (default fits the replicated baseline)"),
    _k("SHIFU_BENCH_SERVE_RAW_FLOOR", "env", "float", "0.8",
       "bench serve: raw-record QPS floor as a fraction of the "
       "pre-binned rate (the fused transform must stay nearly free)"),
    _k("SHIFU_BENCH_FLEET_SCALING", "env", "float", "0.8",
       "bench --plane fleet: 2-replica aggregate-QPS scaling floor "
       "(qps_2r / (2 * qps_1r))"),
    _k("SHIFU_BENCH_OVERLOAD_FLOOR", "env", "float", "0.8",
       "bench --plane overload: goodput floor at 2x offered load as a "
       "fraction of the measured saturation QPS"),
)

KNOBS: Dict[str, Knob] = {k.name: k for k in _DECLS}
if len(KNOBS) != len(_DECLS):            # duplicate declaration = a bug
    raise AssertionError("duplicate knob declaration in config/knobs.py")

# case-insensitive lookup for the property namespace (env folding
# lowercases: SHIFU_TRAIN_WINDOWROWS -> shifu.train.windowrows)
_PROPS_LOWER: Dict[str, str] = {
    k.name.lower(): k.name for k in _DECLS if k.kind == "property"}


def is_declared(name: str) -> bool:
    """Exact declared knob?  Properties match case-insensitively."""
    if name in KNOBS:
        return True
    return name.lower() in _PROPS_LOWER


def is_declared_prefix(token: str) -> bool:
    """Is ``token`` a strict prefix of some declared knob?  Forgives
    line-wrapped mentions in docstrings (``SHIFU_TAIL_TREE_`` +
    newline + ``BATCH``)."""
    tl = token.lower()
    return any(n.lower().startswith(tl) for n in KNOBS)


def knob_table_markdown() -> str:
    """The README knob table (two sections, stable order) — the
    knob-registry rule cross-checks every declared name appears in the
    README, so regenerate with
    ``python -c "from shifu_tpu.config import knobs; print(knobs.knob_table_markdown())"``."""
    out = []
    for kind, title in (("property", "`-Dshifu.*` properties (also "
                         "settable via `$SHIFU_TPU_HOME/conf/shifuconfig`"
                         " or env-folded `SHIFU_FOO_BAR` forms)"),
                        ("env", "`SHIFU_*` environment variables")):
        out.append(f"**{title}**")
        out.append("")
        out.append("| knob | type | default | what it does |")
        out.append("|---|---|---|---|")
        for k in _DECLS:
            if k.kind != kind:
                continue
            dflt = k.default if k.default != "" else "–"
            out.append(f"| `{k.name}` | {k.type} | {dflt} | {k.doc} |")
        out.append("")
    return "\n".join(out)
