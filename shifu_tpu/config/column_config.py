"""ColumnConfig — per-column metadata, JSON-compatible with the reference.

Mirrors reference ``container/obj/ColumnConfig.java`` (+ ``ColumnStats.java``,
``ColumnBinning.java``): one entry per input column holding type, flag,
selection state, stats (ks/iv/woe/mean/std/...), and binning (boundaries,
per-bin counts / pos-rates / woe).  ``ColumnConfig.json`` is a JSON list of
these entries, written after ``init`` and enriched by ``stats``/``varselect``.
"""

from __future__ import annotations

import enum
import math
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from . import jsonbean


class ColumnType(enum.Enum):
    """Reference ``container/obj/ColumnType.java:18-21``: A=auto, N=numerical,
    C=categorical, H=hybrid (numerical w/ categorical missing buckets)."""
    A = "A"
    N = "N"
    C = "C"
    H = "H"


class ColumnFlag(enum.Enum):
    """Reference ``ColumnConfig.java:38-40``."""
    ForceSelect = "ForceSelect"
    ForceRemove = "ForceRemove"
    Candidate = "Candidate"
    Meta = "Meta"
    Target = "Target"
    Weight = "Weight"


@dataclass
class ColumnStats:
    max: Optional[float] = None
    min: Optional[float] = None
    mean: Optional[float] = None
    median: Optional[float] = None
    p25th: Optional[float] = None
    p75th: Optional[float] = None
    totalCount: Optional[int] = None
    distinctCount: Optional[int] = None
    missingCount: Optional[int] = None
    validNumCount: Optional[int] = None
    stdDev: Optional[float] = None
    missingPercentage: Optional[float] = None
    woe: Optional[float] = None
    ks: Optional[float] = None
    iv: Optional[float] = None
    weightedKs: Optional[float] = None
    weightedIv: Optional[float] = None
    weightedWoe: Optional[float] = None
    skewness: Optional[float] = None
    kurtosis: Optional[float] = None
    psi: Optional[float] = None
    unitStats: Optional[List[str]] = None
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ColumnBinning:
    length: int = 0
    binBoundary: Optional[List[float]] = None
    binCategory: Optional[List[str]] = None
    binCountNeg: Optional[List[int]] = None
    binCountPos: Optional[List[int]] = None
    binPosRate: Optional[List[float]] = None
    binAvgScore: Optional[List[int]] = None
    binWeightedNeg: Optional[List[float]] = None
    binWeightedPos: Optional[List[float]] = None
    binCountWoe: Optional[List[float]] = None
    binWeightedWoe: Optional[List[float]] = None
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ColumnConfig:
    columnNum: int = 0
    version: str = "0.1.0"
    columnName: str = ""
    columnType: ColumnType = ColumnType.N
    columnFlag: Optional[ColumnFlag] = None
    finalSelect: bool = False
    sampleValues: Optional[List[str]] = None
    hybridThreshold: Optional[float] = None
    columnStats: ColumnStats = field(default_factory=ColumnStats)
    columnBinning: ColumnBinning = field(default_factory=ColumnBinning)
    extra: Dict[str, Any] = field(default_factory=dict)

    # ----------------------------------------------------------- predicates
    def is_numerical(self) -> bool:
        return self.columnType in (ColumnType.N, ColumnType.A)

    def is_categorical(self) -> bool:
        return self.columnType == ColumnType.C

    def is_hybrid(self) -> bool:
        return self.columnType == ColumnType.H

    def is_target(self) -> bool:
        return self.columnFlag == ColumnFlag.Target

    def is_meta(self) -> bool:
        return self.columnFlag == ColumnFlag.Meta

    def is_weight(self) -> bool:
        return self.columnFlag == ColumnFlag.Weight

    def is_force_select(self) -> bool:
        return self.columnFlag == ColumnFlag.ForceSelect

    def is_force_remove(self) -> bool:
        return self.columnFlag == ColumnFlag.ForceRemove

    def is_candidate(self) -> bool:
        """A column eligible for stats/training: not target/meta/weight."""
        return self.columnFlag not in (ColumnFlag.Target, ColumnFlag.Meta,
                                       ColumnFlag.Weight, ColumnFlag.ForceRemove)

    # ------------------------------------------------------------- binning
    @property
    def bin_boundary(self) -> Optional[List[float]]:
        return self.columnBinning.binBoundary

    @property
    def bin_category(self) -> Optional[List[str]]:
        return self.columnBinning.binCategory

    @property
    def bin_pos_rate(self) -> Optional[List[float]]:
        return self.columnBinning.binPosRate

    @property
    def bin_count_woe(self) -> Optional[List[float]]:
        return self.columnBinning.binCountWoe

    @property
    def bin_weighted_woe(self) -> Optional[List[float]]:
        return self.columnBinning.binWeightedWoe

    def num_bins(self) -> int:
        """Number of value bins (excluding the trailing missing-value bin)."""
        if self.is_categorical():
            return len(self.columnBinning.binCategory or [])
        return len(self.columnBinning.binBoundary or [])

    def mean(self) -> float:
        return self.columnStats.mean if self.columnStats.mean is not None else 0.0

    def std_dev(self) -> float:
        sd = self.columnStats.stdDev
        return sd if sd is not None and sd > 1e-12 else 1.0


# --------------------------------------------------------------------- io
def load_column_configs(path: str) -> List[ColumnConfig]:
    import json
    with open(path) as f:
        data = json.load(f)
    return [jsonbean.from_dict(ColumnConfig, d) for d in data]


def save_column_configs(configs: List[ColumnConfig], path: str) -> None:
    import json
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump([jsonbean.to_dict(c) for c in configs], f, indent=2)
        f.write("\n")
    os.replace(tmp, path)


# ----------------------------------------------------------------- helpers
def build_initial_column_configs(header: List[str], target: Optional[str],
                                 meta_cols: Optional[List[str]] = None,
                                 categorical_cols: Optional[List[str]] = None,
                                 weight_col: Optional[str] = None) -> List[ColumnConfig]:
    """``shifu init``: one ColumnConfig per header column with flags assigned
    (reference ``InitModelProcessor.java:74,89``)."""
    # NSColumn matching throughout: a bare name in a column file matches
    # its namespaced variants in the header and vice versa
    meta = set(meta_cols or [])
    cate = set(categorical_cols or [])
    configs = []
    for i, name in enumerate(header):
        cc = ColumnConfig(columnNum=i, columnName=name)
        if target is not None and ns_match(name, target):
            cc.columnFlag = ColumnFlag.Target
            cc.columnType = ColumnType.C
        elif weight_col is not None and ns_match(name, weight_col):
            cc.columnFlag = ColumnFlag.Weight
        elif ns_in(name, meta):
            cc.columnFlag = ColumnFlag.Meta
        if ns_in(name, cate):
            cc.columnType = ColumnType.C
        configs.append(cc)
    return configs


def selected_columns(configs: List[ColumnConfig]) -> List[ColumnConfig]:
    """Columns in the model input, in columnNum order: finalSelect or ForceSelect."""
    out = [c for c in configs
           if (c.finalSelect or c.is_force_select()) and c.is_candidate()]
    return sorted(out, key=lambda c: c.columnNum)


def candidate_columns(configs: List[ColumnConfig]) -> List[ColumnConfig]:
    return [c for c in configs if c.is_candidate()]


def target_column(configs: List[ColumnConfig]) -> Optional[ColumnConfig]:
    for c in configs:
        if c.is_target():
            return c
    return None


# -------------------------------------------------------- namespaced names
NS_DELIMITER = "::"          # reference Constants.NAMESPACE_DELIMITER


def ns_simple(name: str) -> str:
    """The simple (last) identifier of a possibly-namespaced column name —
    reference ``column/NSColumn.java``: 'raw::a::amount' -> 'amount'."""
    return name.rsplit(NS_DELIMITER, 1)[-1] if NS_DELIMITER in name else name


def read_column_name_file(path, base_dir: str = ".") -> set:
    """One-name-per-line column file (force/meta/candidate lists):
    blank lines and '#' comments skipped.  The single reader shared by
    validation (``validator.probe``) and selection
    (``varselect._apply_force_files``) so both interpret the same file
    identically."""
    if not path:
        return set()
    p = path if os.path.isabs(path) else os.path.join(base_dir, path)
    if not os.path.isfile(p):
        return set()
    out = set()
    for line in open(p):
        line = line.strip()
        if line and not line.startswith("#"):
            out.add(line)
    return out


def ns_match(a: str, b: str) -> bool:
    """NSColumn equality: exact full-name match, or a BARE name matching a
    namespaced variant of it (``NSColumn.equals``).  Two different
    namespaces never match — 'a::score' names a different column than
    'b::score'."""
    if a == b:
        return True
    if (NS_DELIMITER in a) != (NS_DELIMITER in b):
        return ns_simple(a) == ns_simple(b)
    return False


def ns_in(name: str, names) -> bool:
    """``name`` matches any entry of ``names`` under NSColumn equality."""
    if name in names:          # fast path: exact
        return True
    return any(ns_match(name, other) for other in names)
