"""Global key-value config, the reference's ``Environment`` + ``shifuconfig``.

Three tiers, mirroring reference ``util/Environment.java:35,62-73`` and
``ShifuCLI.java:430-453``:

1. per-model ``ModelConfig.json`` (see ``model_config``),
2. global ``$SHIFU_TPU_HOME/conf/shifuconfig`` (``key=value`` lines),
3. ``-Dkey=value`` CLI overrides (highest priority).

Environment variables prefixed ``SHIFU_`` are folded in between tiers 2 and 3.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

_props: Dict[str, str] = {}
_loaded = False


def _load_config_file(path: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    if not os.path.isfile(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            key, sep, val = line.partition("=")
            if sep:
                out[key.strip()] = val.strip()
    return out


def _ensure_loaded() -> None:
    global _loaded
    if _loaded:
        return
    home = os.environ.get("SHIFU_TPU_HOME") or os.environ.get("SHIFU_HOME")
    if home:
        _props.update(_load_config_file(os.path.join(home, "conf", "shifuconfig")))
    for k, v in os.environ.items():
        if k.startswith("SHIFU_"):
            _props.setdefault(k.lower().replace("_", "."), v)
    _loaded = True


def set_property(key: str, value: Any) -> None:
    _ensure_loaded()
    _props[key] = str(value)


def get_property(key: str, default: Optional[str] = None) -> Optional[str]:
    _ensure_loaded()
    v = _props.get(key)
    if v is None:
        # env vars lowercase on import (SHIFU_TRAIN_WINDOWROWS ->
        # shifu.train.windowrows) — camelCase property names still match
        v = _props.get(key.lower())
    # empty string = unset (clearing a property restores the default)
    return default if v is None or v == "" else v


def get_int(key: str, default: int) -> int:
    v = get_property(key)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def get_float(key: str, default: float) -> float:
    v = get_property(key)
    try:
        return float(v) if v is not None else default
    except ValueError:
        return default


def get_bool(key: str, default: bool) -> bool:
    v = get_property(key)
    if v is None:
        return default
    return v.strip().lower() in ("true", "1", "yes", "on")


def all_properties() -> Dict[str, str]:
    _ensure_loaded()
    return dict(_props)


def reset_for_tests() -> None:
    global _loaded
    _props.clear()
    _loaded = False
