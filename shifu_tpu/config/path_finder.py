"""PathFinder — canonical layout of every pipeline artifact.

TPU-native analogue of reference ``fs/PathFinder.java:38,94-630``: one place
that knows where each step reads/writes inside a model-set directory.  The
reference's LOCAL/HDFS duality collapses to plain paths (a GCS/posix prefix
both work through fsspec-style string paths; everything here is os.path based
and works on any mounted filesystem).
"""

from __future__ import annotations

import os
from typing import Optional

from .model_config import ModelConfig

MODEL_CONFIG_JSON = "ModelConfig.json"
COLUMN_CONFIG_JSON = "ColumnConfig.json"


class PathFinder:
    def __init__(self, model_config: ModelConfig, model_set_dir: str = "."):
        self.model_config = model_config
        self.root = os.path.abspath(model_set_dir)

    # ------------------------------------------------------------- configs
    @property
    def model_config_path(self) -> str:
        return os.path.join(self.root, MODEL_CONFIG_JSON)

    @property
    def column_config_path(self) -> str:
        return os.path.join(self.root, COLUMN_CONFIG_JSON)

    # --------------------------------------------------------------- steps
    @property
    def tmp_dir(self) -> str:
        return os.path.join(self.root, "tmp")

    @property
    def stats_dir(self) -> str:
        return os.path.join(self.tmp_dir, "stats")

    @property
    def raw_cache_dir(self) -> str:
        """Columnar raw-parse cache root (``data/rawcache``) — one
        subdirectory per (source signature, row identity)."""
        return os.path.join(self.tmp_dir, "RawCache")

    @property
    def prebin_path(self) -> str:
        """Sketch/quantile output of the binning pass."""
        return os.path.join(self.stats_dir, "prebinning.json")

    @property
    def correlation_path(self) -> str:
        return os.path.join(self.root, "correlation.csv")

    @property
    def psi_path(self) -> str:
        return os.path.join(self.stats_dir, "psi.json")

    @property
    def norm_dir(self) -> str:
        """Normalized (float) dataset shards — NN/LR/WDL input."""
        return os.path.join(self.tmp_dir, "NormalizedData")

    @property
    def clean_dir(self) -> str:
        """Binned (int) dataset shards — tree-model input.  The reference keeps
        the same duality (cleaned vs normalized data,
        ``TrainModelProcessor.java:1366-1372``)."""
        return os.path.join(self.tmp_dir, "CleanedData")

    @property
    def models_dir(self) -> str:
        return os.path.join(self.root, "models")

    @property
    def tmp_models_dir(self) -> str:
        return os.path.join(self.tmp_dir, "modelsTmp")

    @property
    def varsel_dir(self) -> str:
        return os.path.join(self.root, "varsels")

    @property
    def varsel_history_path(self) -> str:
        return os.path.join(self.varsel_dir, "varsel.history")

    def model_path(self, index: int, alg: Optional[str] = None) -> str:
        if alg is None:
            alg = self.model_config.train.algorithm.name
            # TENSORFLOW trains through the NN path and shares its
            # extension; SVM is its own hinge-loss model (model0.svm)
            alg = {"TENSORFLOW": "nn"}.get(alg, alg)
        return os.path.join(self.models_dir, f"model{index}.{alg.lower()}")

    def tmp_model_path(self, index: int, epoch: int, alg: Optional[str] = None) -> str:
        alg = (alg or self.model_config.train.algorithm.name).lower()
        return os.path.join(self.tmp_models_dir, f"model{index}-{epoch}.{alg}")

    @property
    def checkpoint_dir(self) -> str:
        return os.path.join(self.tmp_dir, "checkpoints")

    # ------------------------------------------------------------ journals
    @property
    def journal_dir(self) -> str:
        """Per-step commit journals (crash consistency, pipeline/journal)."""
        return os.path.join(self.tmp_dir, "journal")

    def journal_path(self, step: str) -> str:
        return os.path.join(self.journal_dir, f"{step}.json")

    @property
    def stats_partial_path(self) -> str:
        """Mid-sweep stats accumulator checkpoint (resume support)."""
        return os.path.join(self.stats_dir, "partial_sweep.npz")

    @property
    def progress_path(self) -> str:
        return os.path.join(self.tmp_dir, "train.progress")

    @property
    def val_error_path(self) -> str:
        return os.path.join(self.tmp_dir, "val.error")

    # ---------------------------------------------------------------- eval
    def eval_dir(self, eval_name: str) -> str:
        return os.path.join(self.root, "evals", eval_name)

    def eval_score_path(self, eval_name: str) -> str:
        return os.path.join(self.eval_dir(eval_name), "EvalScore")

    def eval_confusion_path(self, eval_name: str) -> str:
        return os.path.join(self.eval_dir(eval_name), "EvalConfusionMatrix")

    def eval_performance_path(self, eval_name: str) -> str:
        return os.path.join(self.eval_dir(eval_name), "EvalPerformance.json")

    def eval_norm_path(self, eval_name: str) -> str:
        return os.path.join(self.eval_dir(eval_name), "EvalNormalized")

    # ------------------------------------------------------------ posttrain
    @property
    def post_train_dir(self) -> str:
        return os.path.join(self.root, "posttrain")

    @property
    def bin_avg_score_path(self) -> str:
        return os.path.join(self.post_train_dir, "binAvgScore.csv")

    @property
    def feature_importance_path(self) -> str:
        return os.path.join(self.post_train_dir, "featureImportance.csv")

    # -------------------------------------------------------------- export
    @property
    def export_dir(self) -> str:
        return os.path.join(self.root, "export")

    def pmml_path(self, index: int) -> str:
        return os.path.join(self.export_dir, f"{self.model_config.basic.name}{index}.pmml")

    # ----------------------------------------------------------- telemetry
    @property
    def telemetry_dir(self) -> str:
        """Span/metric JSONL traces (``obs/``) — the counters/ logs
        surface the reference kept in YARN job history."""
        return os.path.join(self.root, "telemetry")

    @property
    def telemetry_trace_path(self) -> str:
        return os.path.join(self.telemetry_dir, "trace.jsonl")

    @property
    def health_dir(self) -> str:
        """Per-process heartbeat files (``obs/health``) — the live
        progress surface ``shifu-tpu monitor`` tails."""
        return os.path.join(self.telemetry_dir, "health")

    @property
    def metrics_prom_path(self) -> str:
        """OpenMetrics text exposition (``obs/exporter``)."""
        return os.path.join(self.telemetry_dir, "metrics.prom")

    @property
    def metrics_json_path(self) -> str:
        return os.path.join(self.telemetry_dir, "metrics.json")

    @property
    def drift_path(self) -> str:
        """Per-column live-PSI table (``obs/drift``)."""
        return os.path.join(self.telemetry_dir, "drift.json")

    @property
    def posttrain_snapshot_path(self) -> str:
        """Training-time score distribution + AUC baseline
        (``obs/quality``) — what live quality is judged against."""
        return os.path.join(self.telemetry_dir, "posttrain.json")

    @property
    def quality_path(self) -> str:
        """Live model-quality table (``obs/quality``): per-generation
        live AUC / calibration / score PSI."""
        return os.path.join(self.telemetry_dir, "quality.json")

    # ------------------------------------------------------------- backups
    @property
    def backup_dir(self) -> str:
        return os.path.join(self.root, ".backup")

    def ensure_dirs(self) -> None:
        for d in (self.tmp_dir, self.stats_dir, self.models_dir,
                  self.tmp_models_dir, self.checkpoint_dir,
                  self.journal_dir):
            os.makedirs(d, exist_ok=True)
