"""Eval layer: batch scorer, metrics sweep, reports."""
