"""Eval metrics: confusion-matrix sweep, ROC/PR/gain curves, AUC.

Reference ``core/ConfusionMatrix.java:62,553`` sorts scores descending and
walks thresholds accumulating unit + weighted tp/fp/tn/fn per bucket;
``core/eval/AreaUnderCurve.java:61-97`` integrates ROC by trapezoid;
``PerformanceEvaluator.java`` assembles the report.  Here the whole sweep is
one vectorized sort + cumsum — every threshold at once — and buckets are
sampled from the full curve afterwards.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


@dataclass
class PerformancePoint:
    """One row of the reference's per-bucket report
    (``PerformanceResult``/``ConfusionMatrixObject``)."""
    binLowestScore: float
    tp: float
    fp: float
    fn: float
    tn: float
    precision: float
    recall: float            # catch rate / TPR
    fpr: float               # action rate on goods
    actionRate: float        # share of population at/above threshold
    liftUnit: float          # recall / actionRate
    weightedTp: float = 0.0
    weightedFp: float = 0.0
    weightedFn: float = 0.0
    weightedTn: float = 0.0
    weightedPrecision: float = 0.0
    weightedRecall: float = 0.0
    weightedFpr: float = 0.0


@dataclass
class PerformanceResult:
    areaUnderRoc: float
    weightedAuc: float
    areaUnderPr: float
    points: List[PerformancePoint] = field(default_factory=list)
    modelCount: int = 1
    recordCount: int = 0
    posCount: float = 0.0
    negCount: float = 0.0

    def to_dict(self) -> Dict:
        def clean(v):
            # NaN is not legal JSON — degenerate (single-class) sweeps
            # serialize as null
            return None if isinstance(v, float) and np.isnan(v) else v
        return {
            "areaUnderRoc": clean(self.areaUnderRoc),
            "weightedAuc": clean(self.weightedAuc),
            "areaUnderPr": clean(self.areaUnderPr),
            "recordCount": self.recordCount,
            "posCount": self.posCount,
            "negCount": self.negCount,
            "modelCount": self.modelCount,
            "performance": [vars(p) for p in self.points],
        }


def auc_trapezoid(fpr: np.ndarray, tpr: np.ndarray) -> float:
    """Trapezoid AUC over a monotone curve (reference
    ``AreaUnderCurve.java:61-97``)."""
    order = np.argsort(fpr, kind="stable")
    return float(np.trapezoid(tpr[order], fpr[order]))


@dataclass
class SweepCurves:
    """Full-resolution cumulative curves, scores descending."""
    thresholds: np.ndarray
    tp: np.ndarray
    fp: np.ndarray
    wtp: np.ndarray
    wfp: np.ndarray
    pos_total: float
    neg_total: float
    wpos_total: float
    wneg_total: float


def sweep(scores: np.ndarray, targets: np.ndarray,
          weights: Optional[np.ndarray] = None) -> SweepCurves:
    """Sort-desc + cumsum over every threshold at once.

    Tied scores collapse to one curve point (the end of the tie block): a
    threshold can only sit between distinct score values, so keeping
    intra-tie prefixes would make AUC depend on input row order.  The
    trapezoid over block ends integrates the diagonal across each tie."""
    scores = np.asarray(scores, np.float64)
    targets = np.asarray(targets, np.float64)
    w = np.ones_like(scores) if weights is None else np.asarray(weights, np.float64)
    order = np.argsort(-scores, kind="stable")
    s, t, ww = scores[order], targets[order], w[order]
    tp = np.cumsum(t)
    fp = np.cumsum(1.0 - t)
    wtp = np.cumsum(t * ww)
    wfp = np.cumsum((1.0 - t) * ww)
    if len(s):
        ends = np.flatnonzero(np.diff(s) != 0)
        keep = np.concatenate([ends, [len(s) - 1]])
        s, tp, fp, wtp, wfp = s[keep], tp[keep], fp[keep], wtp[keep], wfp[keep]
    return SweepCurves(thresholds=s, tp=tp, fp=fp, wtp=wtp, wfp=wfp,
                       pos_total=float(tp[-1]) if len(tp) else 0.0,
                       neg_total=float(fp[-1]) if len(fp) else 0.0,
                       wpos_total=float(wtp[-1]) if len(wtp) else 0.0,
                       wneg_total=float(wfp[-1]) if len(wfp) else 0.0)


CURVE_POINTS = 1024     # device-sweep downsample resolution (charts/buckets)


def _sweep_device_impl(s, t, w, points: int):
    """Whole confusion sweep ON DEVICE; one packed fetch.

    The host sweep (above) argsorts fetched scores — on this rig a
    full-set fetch costs 100-250 ms before sorting starts, putting eval
    ~2 orders below the train plane (BENCH_r03).  Here sort, cumsums and
    the tie-group reductions all run on device and only
    ``5*points + 7`` floats cross the link.

    Deliberately scatter-free (TPU serializes scatters): tie groups are
    resolved with cummax/cummin scans + gathers —
      start_idx[i] = index of row i's tie-group start (forward cummax)
      end_idx[i]   = index of its group end (reverse cummin)
    AUC/wAUC use the tie-corrected Mann-Whitney sum, which equals the
    trapezoid over the tie-collapsed curve exactly; PR-AUC accumulates
    per-group trapezoid contributions at group-end rows.
    """
    import jax
    import jax.numpy as jnp

    n = s.shape[0]
    # f64 when x64 is live (checked, not assumed: .astype(f64) under
    # disabled x64 truncates with a warning per call)
    f = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    tiny = 1e-12
    neg_s, t, w = jax.lax.sort(
        (-s.astype(f), t.astype(f), w.astype(f)), num_keys=1,
        is_stable=True)
    s = -neg_s
    idx = jnp.arange(n)
    tp = jnp.cumsum(t)
    fp = jnp.cumsum(1.0 - t)
    wtp = jnp.cumsum(t * w)
    wfp = jnp.cumsum((1.0 - t) * w)
    pos, neg, wpos, wneg = tp[-1], fp[-1], wtp[-1], wfp[-1]

    newg = jnp.concatenate([jnp.ones(1, bool), s[1:] != s[:-1]])
    is_end = jnp.concatenate([s[1:] != s[:-1], jnp.ones(1, bool)])
    start_idx = jax.lax.cummax(jnp.where(newg, idx, -1))
    end_idx = jnp.flip(jax.lax.cummin(
        jnp.flip(jnp.where(is_end, idx, n - 1))))
    j_prev = start_idx - 1                      # end of the previous group
    jp = jnp.maximum(j_prev, 0)
    has_prev = j_prev >= 0

    fp_end, wfp_end = fp[end_idx], wfp[end_idx]
    fp_before = jnp.where(has_prev, fp[jp], 0.0)
    wfp_before = jnp.where(has_prev, wfp[jp], 0.0)
    # exact tie-corrected AUC: per positive row, negatives strictly below
    # + half the negatives tied with it
    auc = jnp.sum(t * ((neg - fp_end) + 0.5 * (fp_end - fp_before))) \
        / jnp.maximum(pos * neg, tiny)
    wauc = jnp.sum((t * w) * ((wneg - wfp_end)
                              + 0.5 * (wfp_end - wfp_before))) \
        / jnp.maximum(wpos * wneg, tiny)

    # PR-AUC trapezoid over group ends (r_{-1}=0, p_{-1}=p_0, matching
    # the host evaluate_curves integration)
    tp_end = tp[end_idx]
    prec_end = tp_end / jnp.maximum(tp_end + fp_end, tiny)
    rec_end = tp_end / jnp.maximum(pos, tiny)
    prev_tp = jnp.where(has_prev, tp[jp], 0.0)
    prev_fp = jnp.where(has_prev, fp[jp], 0.0)
    prev_prec = jnp.where(
        has_prev, prev_tp / jnp.maximum(prev_tp + prev_fp, tiny), prec_end)
    prev_rec = prev_tp / jnp.maximum(pos, tiny)
    pr_auc = jnp.sum(jnp.where(
        is_end, (rec_end - prev_rec) * (prec_end + prev_prec) * 0.5, 0.0))

    # downsampled curve: 'points' equal-population rows snapped to their
    # tie-group end (cumulative population at row i is exactly i+1)
    rows = jnp.clip((jnp.arange(1, points + 1) * n) // points - 1, 0, n - 1)
    e = end_idx[rows]
    packed = jnp.concatenate([
        s[e], tp[e], fp[e], wtp[e], wfp[e],
        jnp.stack([auc, wauc, pr_auc, pos, neg, wpos, wneg])])
    return packed


_sweep_device_jit = None      # lazily jitted (keeps jax import lazy here)


def sweep_device(scores, targets, weights=None,
                 points: int = CURVE_POINTS):
    """Device-side :func:`sweep`: returns ``(SweepCurves, exact_aucs)``.

    ``scores``/``targets``/``weights`` may live on device already (the
    scorer's resident plane) — nothing but the packed curve crosses the
    link.  ``exact_aucs`` is ``(auc, wauc, pr_auc)`` at full resolution;
    the curves are downsampled to ``points`` for charts/buckets.
    """
    import jax
    import jax.numpy as jnp

    n = int(scores.shape[0])
    if n == 0:
        return sweep(np.zeros(0), np.zeros(0)), (float("nan"),) * 3
    if weights is None:
        weights = jnp.ones(n, jnp.float32)
    global _sweep_device_jit
    if _sweep_device_jit is None:
        _sweep_device_jit = jax.jit(_sweep_device_impl,
                                    static_argnames=("points",))
    packed = np.asarray(_sweep_device_jit(
        jnp.asarray(scores), jnp.asarray(targets), jnp.asarray(weights),
        min(points, n)))
    p = min(points, n)
    thr, tp, fp, wtp, wfp = (packed[i * p:(i + 1) * p] for i in range(5))
    auc, wauc, pr_auc, pos, neg, wpos, wneg = packed[5 * p:]
    if p > 1:     # collapse duplicate group snaps (ties / n < points)
        keep = np.concatenate([np.flatnonzero(np.diff(thr) != 0),
                               [p - 1]])
        thr, tp, fp, wtp, wfp = (a[keep] for a in (thr, tp, fp, wtp, wfp))
    curves = SweepCurves(thresholds=thr, tp=tp, fp=fp, wtp=wtp, wfp=wfp,
                         pos_total=float(pos), neg_total=float(neg),
                         wpos_total=float(wpos), wneg_total=float(wneg))
    return curves, (float(auc), float(wauc), float(pr_auc))


def evaluate_scores_device(scores, targets, weights=None,
                           buckets: int = 10,
                           points: int = CURVE_POINTS):
    """Device-plane :func:`evaluate_scores`: returns ``(curves, result)``
    with AUC/wAUC/PR-AUC computed exactly on device (the bucket rows come
    from the downsampled curve — boundary error ≤ 1/points of the
    population, the reference's own bucket granularity is 1/10)."""
    curves, (auc, wauc, pr_auc) = sweep_device(scores, targets, weights,
                                               points)
    result = evaluate_curves(curves, buckets)
    if not np.isnan(result.areaUnderRoc):
        result.areaUnderRoc = auc
        result.weightedAuc = wauc
        result.areaUnderPr = pr_auc
    return curves, result


def evaluate_scores(scores: np.ndarray, targets: np.ndarray,
                    weights: Optional[np.ndarray] = None,
                    buckets: int = 10) -> PerformanceResult:
    """Full eval report: AUC (unit + weighted), PR AUC, per-bucket confusion
    rows at ``buckets`` equal-population thresholds (reference
    ``performanceBucketNum``, default 10)."""
    return evaluate_curves(sweep(scores, targets, weights), buckets)


def evaluate_curves(c: SweepCurves, buckets: int = 10) -> PerformanceResult:
    """Report from precomputed curves — callers that also render charts
    (``eval/report.py``) sweep ONCE and share."""
    n = len(c.thresholds)           # distinct thresholds (ties collapsed)
    total = int(c.pos_total + c.neg_total)
    if n == 0 or c.pos_total == 0 or c.neg_total == 0:
        return PerformanceResult(float("nan"), float("nan"), float("nan"),
                                 recordCount=total, posCount=c.pos_total,
                                 negCount=c.neg_total)
    tpr = c.tp / c.pos_total
    fpr = c.fp / c.neg_total
    wtpr = c.wtp / max(c.wpos_total, 1e-12)
    wfpr = c.wfp / max(c.wneg_total, 1e-12)
    precision = c.tp / np.maximum(c.tp + c.fp, 1e-12)

    auc = auc_trapezoid(np.concatenate([[0.0], fpr, [1.0]]),
                        np.concatenate([[0.0], tpr, [1.0]]))
    wauc = auc_trapezoid(np.concatenate([[0.0], wfpr, [1.0]]),
                         np.concatenate([[0.0], wtpr, [1.0]]))
    # PR AUC over recall axis
    pr_auc = float(np.trapezoid(
        np.concatenate([[precision[0]], precision]),
        np.concatenate([[0.0], tpr])))

    points = []
    cum_pop = c.tp + c.fp
    for b in range(1, buckets + 1):
        # bucket boundary = threshold closest to b/buckets population share
        i = min(n - 1, int(np.searchsorted(cum_pop, b * total / buckets)))
        tp_, fp_ = float(c.tp[i]), float(c.fp[i])
        fn_, tn_ = c.pos_total - tp_, c.neg_total - fp_
        wtp_, wfp_ = float(c.wtp[i]), float(c.wfp[i])
        wfn_, wtn_ = c.wpos_total - wtp_, c.wneg_total - wfp_
        action = float(cum_pop[i]) / total
        points.append(PerformancePoint(
            binLowestScore=float(c.thresholds[i]),
            tp=tp_, fp=fp_, fn=fn_, tn=tn_,
            precision=tp_ / max(tp_ + fp_, 1e-12),
            recall=tp_ / max(c.pos_total, 1e-12),
            fpr=fp_ / max(c.neg_total, 1e-12),
            actionRate=action,
            liftUnit=(tp_ / max(c.pos_total, 1e-12)) / max(action, 1e-12),
            weightedTp=wtp_, weightedFp=wfp_, weightedFn=wfn_, weightedTn=wtn_,
            weightedPrecision=wtp_ / max(wtp_ + wfp_, 1e-12),
            weightedRecall=wtp_ / max(c.wpos_total, 1e-12),
            weightedFpr=wfp_ / max(c.wneg_total, 1e-12)))
    return PerformanceResult(
        areaUnderRoc=auc, weightedAuc=wauc, areaUnderPr=pr_auc, points=points,
        recordCount=total, posCount=c.pos_total, negCount=c.neg_total)


def gain_chart_rows(result: PerformanceResult) -> List[Dict]:
    """Gain-chart table (reference ``core/eval/GainChart.java`` csv body)."""
    return [{"actionRate": p.actionRate, "recall": p.recall,
             "precision": p.precision, "lift": p.liftUnit,
             "weightedRecall": p.weightedRecall, "score": p.binLowestScore}
            for p in result.points]


def evaluate_multiclass(class_scores: np.ndarray, targets: np.ndarray,
                        weights: Optional[np.ndarray] = None) -> Dict:
    """Multi-class eval report: weighted accuracy (argmax vote, reference
    ``MultiClsTagPredictor.predictTag``), per-class one-vs-rest AUC, macro
    AUC, and the K x K weighted confusion matrix.

    class_scores: [n, K]; targets: [n] class indices.
    """
    class_scores = np.asarray(class_scores, np.float64)
    t = np.asarray(targets).astype(int)
    n, k = class_scores.shape
    w = np.ones(n) if weights is None else np.asarray(weights, np.float64)
    pred = class_scores.argmax(axis=1)
    acc = float((w * (pred == t)).sum() / max(w.sum(), 1e-12))
    conf = np.zeros((k, k))
    np.add.at(conf, (t, pred), w)
    aucs = []
    for ci in range(k):
        c = sweep(class_scores[:, ci], (t == ci).astype(float), w)
        if c.pos_total > 0 and c.neg_total > 0:
            aucs.append(auc_trapezoid(c.fp / c.neg_total, c.tp / c.pos_total))
        else:
            aucs.append(float("nan"))
    finite = [a for a in aucs if np.isfinite(a)]
    return {"nClasses": k, "recordCount": int(n),
            "accuracy": acc, "errorRate": 1.0 - acc,
            "perClassAuc": [float(a) for a in aucs],
            "macroAuc": float(np.mean(finite)) if finite else float("nan"),
            "classCounts": np.bincount(t, minlength=k).tolist(),
            "confusionMatrix": conf.tolist()}
