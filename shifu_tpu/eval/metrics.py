"""Eval metrics: confusion-matrix sweep, ROC/PR/gain curves, AUC.

Reference ``core/ConfusionMatrix.java:62,553`` sorts scores descending and
walks thresholds accumulating unit + weighted tp/fp/tn/fn per bucket;
``core/eval/AreaUnderCurve.java:61-97`` integrates ROC by trapezoid;
``PerformanceEvaluator.java`` assembles the report.  Here the whole sweep is
one vectorized sort + cumsum — every threshold at once — and buckets are
sampled from the full curve afterwards.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


@dataclass
class PerformancePoint:
    """One row of the reference's per-bucket report
    (``PerformanceResult``/``ConfusionMatrixObject``)."""
    binLowestScore: float
    tp: float
    fp: float
    fn: float
    tn: float
    precision: float
    recall: float            # catch rate / TPR
    fpr: float               # action rate on goods
    actionRate: float        # share of population at/above threshold
    liftUnit: float          # recall / actionRate
    weightedTp: float = 0.0
    weightedFp: float = 0.0
    weightedFn: float = 0.0
    weightedTn: float = 0.0
    weightedPrecision: float = 0.0
    weightedRecall: float = 0.0
    weightedFpr: float = 0.0


@dataclass
class PerformanceResult:
    areaUnderRoc: float
    weightedAuc: float
    areaUnderPr: float
    points: List[PerformancePoint] = field(default_factory=list)
    modelCount: int = 1
    recordCount: int = 0
    posCount: float = 0.0
    negCount: float = 0.0

    def to_dict(self) -> Dict:
        def clean(v):
            # NaN is not legal JSON — degenerate (single-class) sweeps
            # serialize as null
            return None if isinstance(v, float) and np.isnan(v) else v
        return {
            "areaUnderRoc": clean(self.areaUnderRoc),
            "weightedAuc": clean(self.weightedAuc),
            "areaUnderPr": clean(self.areaUnderPr),
            "recordCount": self.recordCount,
            "posCount": self.posCount,
            "negCount": self.negCount,
            "modelCount": self.modelCount,
            "performance": [vars(p) for p in self.points],
        }


def auc_trapezoid(fpr: np.ndarray, tpr: np.ndarray) -> float:
    """Trapezoid AUC over a monotone curve (reference
    ``AreaUnderCurve.java:61-97``)."""
    order = np.argsort(fpr, kind="stable")
    return float(np.trapezoid(tpr[order], fpr[order]))


@dataclass
class SweepCurves:
    """Full-resolution cumulative curves, scores descending."""
    thresholds: np.ndarray
    tp: np.ndarray
    fp: np.ndarray
    wtp: np.ndarray
    wfp: np.ndarray
    pos_total: float
    neg_total: float
    wpos_total: float
    wneg_total: float


def sweep(scores: np.ndarray, targets: np.ndarray,
          weights: Optional[np.ndarray] = None) -> SweepCurves:
    """Sort-desc + cumsum over every threshold at once.

    Tied scores collapse to one curve point (the end of the tie block): a
    threshold can only sit between distinct score values, so keeping
    intra-tie prefixes would make AUC depend on input row order.  The
    trapezoid over block ends integrates the diagonal across each tie."""
    scores = np.asarray(scores, np.float64)
    targets = np.asarray(targets, np.float64)
    w = np.ones_like(scores) if weights is None else np.asarray(weights, np.float64)
    order = np.argsort(-scores, kind="stable")
    s, t, ww = scores[order], targets[order], w[order]
    tp = np.cumsum(t)
    fp = np.cumsum(1.0 - t)
    wtp = np.cumsum(t * ww)
    wfp = np.cumsum((1.0 - t) * ww)
    if len(s):
        ends = np.flatnonzero(np.diff(s) != 0)
        keep = np.concatenate([ends, [len(s) - 1]])
        s, tp, fp, wtp, wfp = s[keep], tp[keep], fp[keep], wtp[keep], wfp[keep]
    return SweepCurves(thresholds=s, tp=tp, fp=fp, wtp=wtp, wfp=wfp,
                       pos_total=float(tp[-1]) if len(tp) else 0.0,
                       neg_total=float(fp[-1]) if len(fp) else 0.0,
                       wpos_total=float(wtp[-1]) if len(wtp) else 0.0,
                       wneg_total=float(wfp[-1]) if len(wfp) else 0.0)


def evaluate_scores(scores: np.ndarray, targets: np.ndarray,
                    weights: Optional[np.ndarray] = None,
                    buckets: int = 10) -> PerformanceResult:
    """Full eval report: AUC (unit + weighted), PR AUC, per-bucket confusion
    rows at ``buckets`` equal-population thresholds (reference
    ``performanceBucketNum``, default 10)."""
    return evaluate_curves(sweep(scores, targets, weights), buckets)


def evaluate_curves(c: SweepCurves, buckets: int = 10) -> PerformanceResult:
    """Report from precomputed curves — callers that also render charts
    (``eval/report.py``) sweep ONCE and share."""
    n = len(c.thresholds)           # distinct thresholds (ties collapsed)
    total = int(c.pos_total + c.neg_total)
    if n == 0 or c.pos_total == 0 or c.neg_total == 0:
        return PerformanceResult(float("nan"), float("nan"), float("nan"),
                                 recordCount=total, posCount=c.pos_total,
                                 negCount=c.neg_total)
    tpr = c.tp / c.pos_total
    fpr = c.fp / c.neg_total
    wtpr = c.wtp / max(c.wpos_total, 1e-12)
    wfpr = c.wfp / max(c.wneg_total, 1e-12)
    precision = c.tp / np.maximum(c.tp + c.fp, 1e-12)

    auc = auc_trapezoid(np.concatenate([[0.0], fpr, [1.0]]),
                        np.concatenate([[0.0], tpr, [1.0]]))
    wauc = auc_trapezoid(np.concatenate([[0.0], wfpr, [1.0]]),
                         np.concatenate([[0.0], wtpr, [1.0]]))
    # PR AUC over recall axis
    pr_auc = float(np.trapezoid(
        np.concatenate([[precision[0]], precision]),
        np.concatenate([[0.0], tpr])))

    points = []
    cum_pop = c.tp + c.fp
    for b in range(1, buckets + 1):
        # bucket boundary = threshold closest to b/buckets population share
        i = min(n - 1, int(np.searchsorted(cum_pop, b * total / buckets)))
        tp_, fp_ = float(c.tp[i]), float(c.fp[i])
        fn_, tn_ = c.pos_total - tp_, c.neg_total - fp_
        wtp_, wfp_ = float(c.wtp[i]), float(c.wfp[i])
        wfn_, wtn_ = c.wpos_total - wtp_, c.wneg_total - wfp_
        action = float(cum_pop[i]) / total
        points.append(PerformancePoint(
            binLowestScore=float(c.thresholds[i]),
            tp=tp_, fp=fp_, fn=fn_, tn=tn_,
            precision=tp_ / max(tp_ + fp_, 1e-12),
            recall=tp_ / max(c.pos_total, 1e-12),
            fpr=fp_ / max(c.neg_total, 1e-12),
            actionRate=action,
            liftUnit=(tp_ / max(c.pos_total, 1e-12)) / max(action, 1e-12),
            weightedTp=wtp_, weightedFp=wfp_, weightedFn=wfn_, weightedTn=wtn_,
            weightedPrecision=wtp_ / max(wtp_ + wfp_, 1e-12),
            weightedRecall=wtp_ / max(c.wpos_total, 1e-12),
            weightedFpr=wfp_ / max(c.wneg_total, 1e-12)))
    return PerformanceResult(
        areaUnderRoc=auc, weightedAuc=wauc, areaUnderPr=pr_auc, points=points,
        recordCount=total, posCount=c.pos_total, negCount=c.neg_total)


def gain_chart_rows(result: PerformanceResult) -> List[Dict]:
    """Gain-chart table (reference ``core/eval/GainChart.java`` csv body)."""
    return [{"actionRate": p.actionRate, "recall": p.recall,
             "precision": p.precision, "lift": p.liftUnit,
             "weightedRecall": p.weightedRecall, "score": p.binLowestScore}
            for p in result.points]


def evaluate_multiclass(class_scores: np.ndarray, targets: np.ndarray,
                        weights: Optional[np.ndarray] = None) -> Dict:
    """Multi-class eval report: weighted accuracy (argmax vote, reference
    ``MultiClsTagPredictor.predictTag``), per-class one-vs-rest AUC, macro
    AUC, and the K x K weighted confusion matrix.

    class_scores: [n, K]; targets: [n] class indices.
    """
    class_scores = np.asarray(class_scores, np.float64)
    t = np.asarray(targets).astype(int)
    n, k = class_scores.shape
    w = np.ones(n) if weights is None else np.asarray(weights, np.float64)
    pred = class_scores.argmax(axis=1)
    acc = float((w * (pred == t)).sum() / max(w.sum(), 1e-12))
    conf = np.zeros((k, k))
    np.add.at(conf, (t, pred), w)
    aucs = []
    for ci in range(k):
        c = sweep(class_scores[:, ci], (t == ci).astype(float), w)
        if c.pos_total > 0 and c.neg_total > 0:
            aucs.append(auc_trapezoid(c.fp / c.neg_total, c.tp / c.pos_total))
        else:
            aucs.append(float("nan"))
    finite = [a for a in aucs if np.isfinite(a)]
    return {"nClasses": k, "recordCount": int(n),
            "accuracy": acc, "errorRate": 1.0 - acc,
            "perClassAuc": [float(a) for a in aucs],
            "macroAuc": float(np.mean(finite)) if finite else float("nan"),
            "classCounts": np.bincount(t, minlength=k).tolist(),
            "confusionMatrix": conf.tolist()}
