"""Scorer + ModelRunner — reference ``core/Scorer.java:53`` /
``core/ModelRunner.java:54`` batched.

The reference scores one normalized row at a time across bagged models
(thread pool per model, ``Scorer.java:163-200``); here all rows × all models
run as batched jitted forwards — the per-model thread pool becomes the MXU's
batch dimension.
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..models import load_any

SCORE_SCALE = 1000.0  # reference scales [0,1] raw scores by 1000


def discover_model_paths(models_dir: str) -> List[str]:
    """model* files in NUMERIC member order (model2 before model10) — the
    one discovery rule for the scorer, exports, and anything else that
    walks the models dir."""
    def index_key(p: str) -> tuple:
        stem = os.path.splitext(os.path.basename(p))[0]
        digits = "".join(ch for ch in stem if ch.isdigit())
        return (int(digits) if digits else 0, p)

    return sorted((p for p in glob.glob(os.path.join(models_dir, "model*.*"))
                   if not p.endswith(".json")),  # convert sidecars
                  key=index_key)


@dataclass
class CaseScoreResult:
    """Batch analogue of reference ``container/CaseScoreResult``: per-row
    aggregate + per-model scores (already scaled)."""
    scores: np.ndarray       # [n, models] scaled
    mean: np.ndarray         # [n]
    max: np.ndarray
    min: np.ndarray
    median: np.ndarray

    def select(self, selector: str) -> np.ndarray:
        s = (selector or "mean").lower()
        if s in ("mean", "avg"):
            return self.mean
        if s == "max":
            return self.max
        if s == "min":
            return self.min
        if s == "median":
            return self.median
        if s.startswith("model"):
            return self.scores[:, int(s[5:])]
        raise ValueError(f"unknown score selector {selector!r}")


class Scorer:
    """Multi-model batch scorer over normalized feature matrices."""

    def __init__(self, models: Sequence, scale: float = SCORE_SCALE,
                 mesh=None):
        if not models:
            raise ValueError("no models to score with")
        self.models = list(models)
        self.scale = scale
        # (ensemble, data) mesh: batch rows shard over the data axis so
        # every chip scores its own rows (the reference spreads eval over
        # the cluster, ``EvalModelProcessor.java:424-436``); None = the
        # single-chip layout
        self.mesh = mesh
        self._groups = None          # lazy same-shape NN stacks
        self._groups_src = None      # models the cache was built from
        self._bins_dtype = None      # lazy narrowest bins dtype

    @classmethod
    def from_dir(cls, models_dir: str, scale: float = SCORE_SCALE,
                 mesh=None) -> "Scorer":
        paths = discover_model_paths(models_dir)
        models = [load_any(p) for p in paths]
        if not models:
            from ..config.errors import ErrorCode, ShifuError
            raise ShifuError(ErrorCode.ERROR_MODEL_FILE_NOT_FOUND,
                             f"no model files in {models_dir} — run `train`")
        return cls(models, scale, mesh=mesh)

    def _put(self, a, dtype=None):
        """Rows onto the device, data-axis sharded (and zero-padded to
        divide it) under a multi-device mesh — :meth:`score` trims the
        padded scores after the fetch.  Single-device: jnp.asarray, so a
        device-resident batch never round-trips the host."""
        import jax.numpy as jnp
        if self.mesh is None or int(self.mesh.shape.get("data", 1)) <= 1:
            return jnp.asarray(a) if dtype is None else jnp.asarray(a, dtype)
        from ..parallel.mesh import shard_chunk_rows
        return shard_chunk_rows(self.mesh, np.asarray(a, dtype))[0]

    def _stacked_nn_groups(self):
        """Same-shape NN/LR models stacked for ONE vmapped forward — the
        bagged ensemble was trained stacked (``train_ensemble``); scoring it
        unstacked is pure overhead (reference scores each model on its own
        thread, ``Scorer.java:163-200``).

        The cache is keyed off model IDENTITY (hot-swap reuses Scorer
        instances and replaces ``self.models``): any change to the list
        rebuilds the stacks — a stale cache would silently keep scoring
        the old ensemble."""
        if self._groups is not None and self._groups_src is not None \
                and len(self._groups_src) == len(self.models) \
                and all(a is b for a, b in zip(self._groups_src,
                                               self.models)):
            return self._groups
        import jax
        import jax.numpy as jnp

        from ..models.nn import forward
        by_shape = {}
        for i, m in enumerate(self.models):
            sp = getattr(m, "spec", None)
            if type(m).__name__ != "IndependentNNModel" or sp is None:
                continue
            key = (sp.input_dim, tuple(sp.hidden_nodes),
                   tuple(sp.activations), sp.output_dim,
                   sp.output_activation)
            by_shape.setdefault(key, []).append(i)
        self._groups = []
        for idxs in by_shape.values():
            if len(idxs) < 2:
                continue
            spec = self.models[idxs[0]].spec
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs),
                *[self.models[i].params for i in idxs])
            fwd = jax.jit(lambda ps, xv, spec=spec: jax.vmap(
                lambda p: forward(p, spec, xv))(ps))
            self._groups.append((idxs, stacked, fwd))
        self._groups_src = list(self.models)
        return self._groups

    def score(self, x: np.ndarray,
              bins: Optional[np.ndarray] = None) -> CaseScoreResult:
        """Tree models consume the binned matrix (``input_kind == 'bins'``),
        NN/LR the normalized floats — both come from one transform pass.
        Same-shape NN models score as one stacked jit call.  Thin host
        wrapper over :meth:`score_device` — ONE [n, M] fetch, aggregates
        on host (the dispatch rules live in one place)."""
        if self._bins_dtype is None:
            # bins ride the narrowest dtype the ensemble admits (uint8
            # wire contract — the quantized traversal consumes it
            # directly; 1/4 the eval plane's H2D bin bytes)
            from ..ops.tree_quant import ensemble_bins_dtype, quant_scoring
            self._bins_dtype = ensemble_bins_dtype(self.models) \
                if quant_scoring() else np.dtype(np.int32)
        raw_d, _ = self.score_device(
            self._put(x, np.float32),
            None if bins is None else self._put(bins, self._bins_dtype))
        raw = np.asarray(raw_d)[:len(x)]     # drop mesh padding rows
        return CaseScoreResult(scores=raw, mean=raw.mean(axis=1),
                               max=raw.max(axis=1), min=raw.min(axis=1),
                               median=np.median(raw, axis=1))

    def score_device(self, x_dev, bins_dev=None):
        """Device-plane scoring: per-model columns stay in HBM; returns
        ``(scores [n, M], mean [n])`` device arrays (feed them straight to
        :func:`shifu_tpu.eval.metrics.sweep_device` — nothing crosses the
        link).  Same dispatch rules as :meth:`score`."""
        import jax.numpy as jnp
        cols = [None] * len(self.models)
        for idxs, stacked, fwd in self._stacked_nn_groups():
            outs = fwd(stacked, x_dev)                 # [M, n, out] device
            for pos, i in enumerate(idxs):
                cols[i] = outs[pos][:, 0]
        for i, m in enumerate(self.models):
            if cols[i] is not None:
                continue
            kind = getattr(m, "input_kind", "norm")
            if kind in ("bins", "both") and bins_dev is None:
                raise ValueError(f"{type(m).__name__} requires binned input "
                                 "— pass bins= to the scorer")
            if kind == "bins":
                cols[i] = jnp.asarray(m.compute(bins_dev))[:, 0]
            elif kind == "both":
                cols[i] = jnp.asarray(m.compute_full(x_dev, bins_dev))[:, 0]
            else:
                cols[i] = jnp.asarray(m.compute(x_dev))[:, 0]
        raw = jnp.stack(cols, axis=1) * self.scale
        return raw, raw.mean(axis=1)

    # ------------------------------------------------------- multi-class
    def n_classes(self) -> int:
        """K from any model's spec extra (``n_classes`` is stamped by both
        the NATIVE and OVA training paths); 0 = binary ensemble."""
        for m in self.models:
            spec = getattr(m, "spec", None)
            if spec is not None:
                k = (getattr(spec, "extra", None) or {}).get("n_classes")
                if k:
                    return int(k)
        return 0

    def score_classes(self, x: np.ndarray,
                      bins: Optional[np.ndarray] = None) -> np.ndarray:
        """[n, K] class scores: NATIVE models contribute their whole
        softmax/distribution row, OVA binary models their ``class_index``
        column; contributors average per class (reference
        ``MultiClsTagPredictor`` assembles scores the same way)."""
        k = self.n_classes()
        if k < 2:
            raise ValueError("score_classes needs multi-class models")
        sums = cnts = None
        for m in self.models:
            kind = getattr(m, "input_kind", "norm")
            inp = bins if kind == "bins" else x
            out = np.asarray(m.compute(inp))
            if sums is None:
                sums = np.zeros((out.shape[0], k))
                cnts = np.zeros(k)
            spec = getattr(m, "spec", None)
            ci = (getattr(spec, "extra", None) or {}).get("class_index") \
                if spec is not None else None
            if out.shape[1] == k:
                sums += out
                cnts += 1.0
            elif ci is not None:
                sums[:, int(ci)] += out[:, 0]
                cnts[int(ci)] += 1.0
            else:
                raise ValueError(
                    f"{type(m).__name__} is neither K-output NATIVE nor "
                    "class-indexed OVA — cannot assemble class scores")
        return sums / np.maximum(cnts, 1.0)[None, :]


class ModelRunner:
    """raw chunk -> normalize -> score (reference ``ModelRunner.compute``,
    also the engine inside ``EvalScoreUDF``)."""

    def __init__(self, model_config, column_configs, models: Sequence,
                 for_eval_set: Optional[int] = None, scale: float = SCORE_SCALE,
                 mesh=None):
        from ..data.transform import DatasetTransformer
        self.transformer = DatasetTransformer(model_config, column_configs,
                                              for_eval_set=for_eval_set)
        self.scorer = Scorer(models, scale, mesh=mesh)

    def compute(self, chunk) -> Dict[str, np.ndarray]:
        tc = self.transformer.transform(chunk)
        res = self.scorer.score(tc.x, bins=tc.bins)
        return {"result": res, "target": tc.target, "weight": tc.weight,
                "n": tc.n, "bins": tc.bins}

    def compute_classes(self, chunk) -> Dict[str, np.ndarray]:
        """Multi-class scoring: [n, K] class scores instead of per-model
        scalar scores."""
        tc = self.transformer.transform(chunk)
        cs = self.scorer.score_classes(tc.x, bins=tc.bins)
        return {"class_scores": cs, "target": tc.target,
                "weight": tc.weight, "n": tc.n}
