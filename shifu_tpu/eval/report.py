"""Eval HTML report — ROC / PR / gain charts + summary + per-bucket table.

The reference renders `EvalPerformance` through Highcharts templates
(``core/eval/GainChart.java``, ``ConfusionMatrix.java:553`` HTML path);
here the report is one dependency-free standalone HTML file with inline
SVG curves, built from the full-resolution sweep (not just the 10 buckets).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .metrics import PerformanceResult, SweepCurves

_W, _H, _PAD = 420, 300, 42


def _downsample(xs: np.ndarray, ys: np.ndarray,
                max_pts: int = 256) -> Tuple[np.ndarray, np.ndarray]:
    if len(xs) <= max_pts:
        return xs, ys
    idx = np.unique(np.linspace(0, len(xs) - 1, max_pts).astype(int))
    return xs[idx], ys[idx]


def _polyline(xs: np.ndarray, ys: np.ndarray, color: str) -> str:
    xs, ys = _downsample(np.asarray(xs, float), np.asarray(ys, float))
    px = _PAD + xs * (_W - 2 * _PAD)
    py = _H - _PAD - ys * (_H - 2 * _PAD)
    pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in zip(px, py))
    return (f'<polyline fill="none" stroke="{color}" stroke-width="2" '
            f'points="{pts}"/>')


def _svg_chart(title: str, xlabel: str, ylabel: str,
               curves: Sequence[Tuple[np.ndarray, np.ndarray, str, str]],
               diagonal: bool = False) -> str:
    parts = [f'<svg width="{_W}" height="{_H}" '
             'style="background:#fff;border:1px solid #ccc">']
    # axes
    parts.append(f'<line x1="{_PAD}" y1="{_H - _PAD}" x2="{_W - _PAD}" '
                 f'y2="{_H - _PAD}" stroke="#444"/>')
    parts.append(f'<line x1="{_PAD}" y1="{_PAD}" x2="{_PAD}" '
                 f'y2="{_H - _PAD}" stroke="#444"/>')
    for t in (0.0, 0.25, 0.5, 0.75, 1.0):
        x = _PAD + t * (_W - 2 * _PAD)
        y = _H - _PAD - t * (_H - 2 * _PAD)
        parts.append(f'<text x="{x:.0f}" y="{_H - _PAD + 14}" '
                     f'font-size="9" text-anchor="middle">{t:g}</text>')
        parts.append(f'<text x="{_PAD - 6}" y="{y + 3:.0f}" font-size="9" '
                     f'text-anchor="end">{t:g}</text>')
    if diagonal:
        parts.append(f'<line x1="{_PAD}" y1="{_H - _PAD}" '
                     f'x2="{_W - _PAD}" y2="{_PAD}" stroke="#bbb" '
                     'stroke-dasharray="4"/>')
    legend_y = _PAD - 24
    for i, (xs, ys, color, label) in enumerate(curves):
        parts.append(_polyline(xs, ys, color))
        lx = _PAD + i * 130
        parts.append(f'<rect x="{lx}" y="{legend_y + 16}" width="10" '
                     f'height="3" fill="{color}"/>')
        parts.append(f'<text x="{lx + 14}" y="{legend_y + 21}" '
                     f'font-size="10">{label}</text>')
    parts.append(f'<text x="{_W / 2}" y="{_PAD - 24}" font-size="12" '
                 f'text-anchor="middle" font-weight="bold">{title}</text>')
    parts.append(f'<text x="{_W / 2}" y="{_H - 8}" font-size="10" '
                 f'text-anchor="middle">{xlabel}</text>')
    parts.append(f'<text x="12" y="{_H / 2}" font-size="10" '
                 f'text-anchor="middle" transform="rotate(-90 12 '
                 f'{_H / 2})">{ylabel}</text>')
    parts.append("</svg>")
    return "".join(parts)


def html_report(name: str, c: SweepCurves,
                result: PerformanceResult) -> str:
    """Render from the SAME SweepCurves evaluate_curves consumed — one sort
    over the eval set, two consumers."""
    if c.pos_total == 0 or c.neg_total == 0:
        return (f"<html><body><h1>Eval {name}</h1><p>degenerate eval set "
                "(single class) — no curves</p></body></html>")
    tpr = c.tp / c.pos_total
    fpr = c.fp / c.neg_total
    wtpr = c.wtp / max(c.wpos_total, 1e-12)
    precision = c.tp / np.maximum(c.tp + c.fp, 1e-12)
    total = c.pos_total + c.neg_total
    action = (c.tp + c.fp) / total
    waction = (c.wtp + c.wfp) / max(c.wpos_total + c.wneg_total, 1e-12)

    roc = _svg_chart("ROC", "false positive rate", "catch rate",
                     [(fpr, tpr, "#d4712b", "unit"),
                      (c.wfp / max(c.wneg_total, 1e-12), wtpr, "#3b6fb0",
                       "weighted")], diagonal=True)
    pr = _svg_chart("Precision-Recall", "recall", "precision",
                    [(tpr, precision, "#d4712b", "unit")])
    gain = _svg_chart("Gain chart", "action rate", "catch rate",
                      [(action, tpr, "#d4712b", "unit"),
                       (waction, wtpr, "#3b6fb0", "weighted")],
                      diagonal=True)

    def fmt(v):
        return "n/a" if v is None or (isinstance(v, float) and np.isnan(v)) \
            else f"{v:.6f}" if isinstance(v, float) else str(v)

    rows = []
    cols = ["binLowestScore", "actionRate", "recall", "precision", "fpr",
            "liftUnit", "tp", "fp", "fn", "tn"]
    for p in result.points:
        rows.append("<tr>" + "".join(
            f"<td>{getattr(p, col):.4f}</td>" if isinstance(
                getattr(p, col), float) else f"<td>{getattr(p, col)}</td>"
            for col in cols) + "</tr>")
    table = ("<table border='1' cellspacing='0' cellpadding='3' "
             "style='border-collapse:collapse;font-size:12px'>"
             "<tr>" + "".join(f"<th>{col}</th>" for col in cols) + "</tr>"
             + "".join(rows) + "</table>")

    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>Eval {name}</title></head>
<body style="font-family:sans-serif;max-width:960px;margin:auto">
<h1>Eval report — {name}</h1>
<table border="0" cellpadding="4" style="font-size:14px">
<tr><td>records</td><td>{result.recordCount}</td>
<td>positives</td><td>{result.posCount:g}</td>
<td>negatives</td><td>{result.negCount:g}</td>
<td>models</td><td>{result.modelCount}</td></tr>
<tr><td>AUC</td><td><b>{fmt(result.areaUnderRoc)}</b></td>
<td>weighted AUC</td><td><b>{fmt(result.weightedAuc)}</b></td>
<td>PR AUC</td><td><b>{fmt(result.areaUnderPr)}</b></td><td></td><td></td></tr>
</table>
<div>{roc} {pr}</div>
<div>{gain}</div>
<h2>Per-bucket confusion</h2>
{table}
</body></html>
"""
