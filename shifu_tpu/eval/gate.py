"""Old-vs-new holdout gate — the promotion decision in one place.

A retrained candidate only reaches the serving fleet if it is at least
as good as the incumbent on data neither of them trained on.  This
module is that gate, reusable by the continual-refresh controller, the
combo/eval tooling and tests alike:

- :func:`load_holdout` slices the NEWEST window of the materialized
  plane (the tail shards of ``NormalizedData`` + ``CleanedData`` — the
  freshest rows, exactly the distribution the candidate claims to fix);
- :func:`auc_gate` scores BOTH ensembles on that same holdout through
  the batch :class:`~shifu_tpu.eval.scorer.Scorer` and compares AUC:
  the candidate passes iff ``new_auc >= old_auc + min_delta``
  (``-Dshifu.refresh.minAucDelta``, default 0 — strict non-regression).

The result carries both AUCs and the verdict; the refresh journal
archives it with every promote/reject decision so "why did generation 7
not ship" is a file, not a guess.
"""

from __future__ import annotations

import logging
import os
from dataclasses import asdict, dataclass
from typing import Any, Dict, Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)

DEFAULT_HOLDOUT_ROWS = 4096


@dataclass
class GateResult:
    old_auc: float
    new_auc: float
    delta: float                 # new - old
    min_delta: float             # the bar (non-regression at 0)
    passed: bool
    rows: int

    def report(self) -> Dict[str, Any]:
        return {k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in asdict(self).items()}


@dataclass
class Holdout:
    x: np.ndarray                          # [n, d] normalized floats
    y: np.ndarray                          # [n] targets
    w: np.ndarray                          # [n] weights
    bins: Optional[np.ndarray] = None      # [n, c] binned ints (trees/WDL)

    @property
    def rows(self) -> int:
        return int(len(self.y))


def min_auc_delta(override: Optional[float] = None) -> float:
    """The promotion bar: ``shifu.refresh.minAucDelta`` (default 0 =
    the candidate must not regress AUC; positive demands a real win)."""
    if override is not None:
        return float(override)
    from ..config import environment
    return environment.get_float("shifu.refresh.minAucDelta", 0.0)


def load_holdout(model_set_dir: str,
                 max_rows: int = DEFAULT_HOLDOUT_ROWS) -> Holdout:
    """The newest rows of the materialized plane as an eval holdout:
    tail shards of the norm plane (x/y/w) and, when present, the
    row-aligned clean plane (bins) — both written by the same ``norm``
    pass, so shard k covers the same rows in both."""
    from ..data.shards import Shards
    norm = Shards.open(os.path.join(model_set_dir, "tmp",
                                    "NormalizedData"))
    rows = norm.shard_rows
    # walk shards back-to-front until max_rows is covered
    start, have = len(rows), 0
    while start > 0 and have < max_rows:
        start -= 1
        have += rows[start]
    parts = [p for p in norm.iter_shards(start=start, strict=True)]
    x = np.concatenate([p["x"] for p in parts])[-max_rows:]
    y = np.concatenate([p["y"] for p in parts])[-max_rows:]
    w = np.concatenate([p["w"] for p in parts])[-max_rows:]
    bins = None
    clean_dir = os.path.join(model_set_dir, "tmp", "CleanedData")
    if os.path.isfile(os.path.join(clean_dir, "schema.json")):
        clean = Shards.open(clean_dir)
        if clean.n_shards == norm.n_shards:
            cparts = [p for p in clean.iter_shards(start=start,
                                                   strict=True)]
            bins = np.concatenate([p["bins"] for p in cparts])[-max_rows:]
    return Holdout(x=x, y=y, w=w, bins=bins)


def holdout_auc(models: Sequence, holdout: Holdout) -> float:
    """Weighted-mean-ensemble AUC of ``models`` on the holdout (the same
    mean-score aggregation the serving plane answers with)."""
    from .metrics import evaluate_scores
    from .scorer import Scorer
    scorer = Scorer(list(models))
    bins = holdout.bins
    needs_bins = any(getattr(m, "input_kind", "norm") in ("bins", "both")
                     for m in scorer.models)
    res = scorer.score(holdout.x, bins if needs_bins else None)
    perf = evaluate_scores(res.mean, holdout.y, holdout.w)
    return float(perf.areaUnderRoc)


def auc_gate(old_models: Sequence, new_models: Sequence,
             holdout: Holdout,
             min_delta: Optional[float] = None) -> GateResult:
    """Score incumbent and candidate on the SAME holdout; the candidate
    passes iff its AUC does not regress past ``min_delta``.  A holdout
    with a degenerate class mix (NaN AUC) fails the gate loudly — an
    unmeasurable candidate must never ship on a coin flip."""
    bar = min_auc_delta(min_delta)
    old_auc = holdout_auc(old_models, holdout)
    new_auc = holdout_auc(new_models, holdout)
    measurable = not (np.isnan(old_auc) or np.isnan(new_auc))
    delta = (new_auc - old_auc) if measurable else float("nan")
    passed = bool(measurable and delta >= bar)
    log.info("auc gate: old=%.6f new=%.6f delta=%+.6f bar=%+g -> %s",
             old_auc, new_auc, delta, bar,
             "PROMOTE" if passed else "REJECT")
    return GateResult(old_auc=old_auc, new_auc=new_auc, delta=delta,
                      min_delta=bar, passed=passed, rows=holdout.rows)
