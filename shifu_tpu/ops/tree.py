"""Decision-tree kernels: histogram build + split-gain scan + batched predict.

Reference mapping (``core/dtrain/dt/``):
- per-(node,feature,bin) stats accumulation (``DTWorker.java:763-884``, the
  thread-parallel ``impurity.featureUpdate`` hot loop at ``:844-854``) →
  one ``segment_sum`` scatter-add per feature over the whole row shard, all
  features vmapped;
- ``Impurity.computeImpurity`` split scan (``dt/Impurity.java:38-734``:
  Variance:106, FriedmanMSE:255, Entropy:368, Gini:553) → vectorized prefix
  sums over the bin axis for every (node, feature) at once;
- categorical splits sort bins by response rate then scan prefixes
  (``Impurity.java:33`` comment) → per-(node,feature) ``argsort`` + gather;
- trees are complete binary arrays with positional ids (``dt/Node.java``
  ``indexToLevel`` layout): ``split_feat[node]``, per-bin ``left_mask`` —
  one uniform representation for numeric (bin <= k) and categorical
  (bin-subset) splits (``dt/Split.java`` numeric threshold / SimpleBitSet).

Everything is binned (int bins from the cleaned data plane), so a split is
always "bin ∈ left set" — scoring never touches raw floats.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

EPS = 1e-12


@dataclass
class TreeArrays:
    """Complete binary tree, node i's children at 2i+1 / 2i+2."""
    split_feat: np.ndarray   # [nodes] int32, -1 = leaf
    left_mask: np.ndarray    # [nodes, n_bins] bool: bin goes left
    leaf_value: np.ndarray   # [nodes] float32
    depth: int

    @property
    def n_nodes(self) -> int:
        return len(self.split_feat)


def n_tree_nodes(depth: int) -> int:
    return (1 << (depth + 1)) - 1


# ------------------------------------------------------------- histograms
@partial(jax.jit, static_argnames=("n_nodes", "n_bins"))
def build_histograms(bins, node_idx, stats, n_nodes: int, n_bins: int):
    """Scatter-add per-row stats into (node, feature, bin) cells.

    bins: [N, C] int32; node_idx: [N] int32 level-local (-1 = inactive);
    stats: [N, S] float32 (S stat channels, e.g. [w, w*y, w*y^2]).
    Returns [n_nodes, C, n_bins, S].
    """
    active = node_idx >= 0
    seg_base = jnp.where(active, node_idx, 0) * n_bins
    masked = stats * active[:, None].astype(stats.dtype)

    def per_feature(bcol):
        idx = seg_base + bcol
        return jax.ops.segment_sum(masked, idx, num_segments=n_nodes * n_bins)

    out = jax.vmap(per_feature, in_axes=1)(bins)        # [C, nodes*bins, S]
    c = bins.shape[1]
    return out.reshape(c, n_nodes, n_bins, -1).transpose(1, 0, 2, 3)


# ------------------------------------------------------------- split scan
def _impurity_score(w, wy, wy2, kind: str):
    """Per-partition purity score; gain = score_L + score_R - score_P.
    variance/friedman use sum^2/weight (equivalent to SSE reduction);
    entropy/gini use binary class counts (pos = wy, neg = w - wy)."""
    if kind in ("variance", "friedmanmse"):
        return wy * wy / jnp.maximum(w, EPS)
    pos = jnp.clip(wy, 0.0, None)
    neg = jnp.clip(w - wy, 0.0, None)
    tot = jnp.maximum(pos + neg, EPS)
    p = pos / tot
    if kind == "entropy":
        h = -(jnp.where(p > 0, p * jnp.log2(jnp.maximum(p, EPS)), 0.0)
              + jnp.where(1 - p > 0, (1 - p) * jnp.log2(jnp.maximum(1 - p, EPS)),
                          0.0))
        return -tot * h
    if kind == "gini":
        return -tot * 2.0 * p * (1 - p)
    raise ValueError(f"unknown impurity {kind!r}")


@partial(jax.jit, static_argnames=("impurity",))
def best_splits(hist, cat_mask, feat_active, impurity: str = "variance",
                min_instances: float = 1.0, min_gain: float = 0.0):
    """Best split per node from the level histogram.

    hist: [nodes, C, B, 3] (w, wy, wy2); cat_mask: [C] bool (categorical →
    bins sorted by response before the prefix scan); feat_active: [C] bool
    (feature sub-sampling, reference featureSubsetStrategy).

    Returns (gain [nodes], feat [nodes], left_mask [nodes, B],
             leaf_value [nodes], node_w [nodes]).
    """
    w, wy, wy2 = hist[..., 0], hist[..., 1], hist[..., 2]
    n_nodes, c, b = w.shape

    # ---- per-(node,feat) bin order: natural for numeric, response-sorted
    # for categorical (empty bins pushed last so prefixes skip them)
    rate = wy / jnp.maximum(w, EPS)
    sort_key = jnp.where(w > 0, -rate, jnp.inf)
    cat_order = jnp.argsort(sort_key, axis=-1)            # [nodes, C, B]
    nat_order = jnp.broadcast_to(jnp.arange(b), (n_nodes, c, b))
    order = jnp.where(cat_mask[None, :, None], cat_order, nat_order)

    w_o = jnp.take_along_axis(w, order, axis=-1)
    wy_o = jnp.take_along_axis(wy, order, axis=-1)
    wy2_o = jnp.take_along_axis(wy2, order, axis=-1)

    cw = jnp.cumsum(w_o, axis=-1)
    cwy = jnp.cumsum(wy_o, axis=-1)
    cwy2 = jnp.cumsum(wy2_o, axis=-1)
    tw, twy, twy2 = cw[..., -1:], cwy[..., -1:], cwy2[..., -1:]

    score_l = _impurity_score(cw, cwy, cwy2, impurity)
    score_r = _impurity_score(tw - cw, twy - cwy, twy2 - cwy2, impurity)
    score_p = _impurity_score(tw, twy, twy2, impurity)
    gain = score_l + score_r - score_p                     # [nodes, C, B]

    valid = (cw >= min_instances) & (tw - cw >= min_instances)
    valid = valid & feat_active[None, :, None]
    valid = valid.at[..., -1].set(False)                   # full prefix = no split
    gain = jnp.where(valid, gain, -jnp.inf)

    best_k = jnp.argmax(gain, axis=-1)                     # [nodes, C]
    best_gain_f = jnp.take_along_axis(gain, best_k[..., None], axis=-1)[..., 0]
    best_feat = jnp.argmax(best_gain_f, axis=-1)           # [nodes]
    node_gain = jnp.take_along_axis(best_gain_f, best_feat[:, None],
                                    axis=-1)[:, 0]

    # ---- build left_mask for the winning (feat, k): order[:k+1] goes left
    k_sel = jnp.take_along_axis(best_k, best_feat[:, None], axis=-1)  # [nodes,1]
    order_sel = jnp.take_along_axis(
        order, best_feat[:, None, None], axis=1)[:, 0]     # [nodes, B]
    ranks = jnp.argsort(order_sel, axis=-1)                # bin -> position
    left_mask = ranks <= k_sel

    node_w = tw[..., 0, 0]
    leaf_value = twy[..., 0, 0] / jnp.maximum(node_w, EPS)
    ok = jnp.isfinite(node_gain) & (node_gain > min_gain)
    feat = jnp.where(ok, best_feat, -1)
    return node_gain, feat.astype(jnp.int32), left_mask & ok[:, None], \
        leaf_value, node_w


# ------------------------------------------------------------------ grow
def grow_tree(bins, targets, weights, n_bins: int, depth: int,
              impurity: str = "variance", min_instances: float = 1.0,
              min_gain: float = 0.0, cat_mask: Optional[np.ndarray] = None,
              feat_active: Optional[np.ndarray] = None) -> TreeArrays:
    """Level-wise growth (reference ``DTMaster.java:543-600`` level mode):
    every node of a level splits in one histogram+scan step; the per-row
    node index update is the worker's tree traversal."""
    n, c = bins.shape
    bins = jnp.asarray(bins, jnp.int32)
    t = jnp.asarray(targets, jnp.float32)
    wt = jnp.asarray(weights, jnp.float32)
    stats = jnp.stack([wt, wt * t, wt * t * t], axis=1)
    cat = jnp.zeros(c, bool) if cat_mask is None else jnp.asarray(cat_mask)
    fa = jnp.ones(c, bool) if feat_active is None else jnp.asarray(feat_active)

    total = n_tree_nodes(depth)
    split_feat = np.full(total, -1, np.int32)
    left_mask = np.zeros((total, n_bins), bool)
    leaf_value = np.zeros(total, np.float32)

    node_idx = jnp.zeros(n, jnp.int32)       # level-local position, -1 done
    for level in range(depth + 1):
        n_nodes = 1 << level
        hist = build_histograms(bins, node_idx, stats, n_nodes, n_bins)
        gain, feat, lmask, leaf, node_w = best_splits(
            hist, cat, fa, impurity, min_instances, min_gain)
        feat = np.asarray(feat)
        lmask = np.asarray(lmask)
        leaf = np.asarray(leaf)
        base = n_nodes - 1                   # global id of level start
        is_last = level == depth
        for i in range(n_nodes):
            g = base + i
            leaf_value[g] = leaf[i]
            if not is_last and feat[i] >= 0:
                split_feat[g] = feat[i]
                left_mask[g] = lmask[i]
        if is_last:
            break
        # rows whose node didn't split freeze; others descend
        feat_d = jnp.asarray(feat)
        lmask_d = jnp.asarray(lmask)
        node_feat = feat_d[jnp.maximum(node_idx, 0)]
        active = (node_idx >= 0) & (node_feat >= 0)
        row_bin = jnp.take_along_axis(
            bins, jnp.maximum(node_feat, 0)[:, None], axis=1)[:, 0]
        goes_left = lmask_d[jnp.maximum(node_idx, 0), row_bin]
        node_idx = jnp.where(active,
                             2 * node_idx + jnp.where(goes_left, 0, 1), -1)
        if not bool(jnp.any(node_idx >= 0)):
            break
    return TreeArrays(split_feat=split_feat, left_mask=left_mask,
                      leaf_value=leaf_value, depth=depth)


# ---------------------------------------------------------------- predict
@partial(jax.jit, static_argnames=("depth",))
def predict_tree(split_feat, left_mask, leaf_value, bins, depth: int):
    """Batched traversal: one gather per level over all rows."""
    n = bins.shape[0]
    node = jnp.zeros(n, jnp.int32)           # global node ids
    for _ in range(depth):
        feat = split_feat[node]
        is_split = feat >= 0
        row_bin = jnp.take_along_axis(
            bins, jnp.maximum(feat, 0)[:, None], axis=1)[:, 0]
        goes_left = left_mask[node, row_bin]
        child = jnp.where(goes_left, 2 * node + 1, 2 * node + 2)
        node = jnp.where(is_split, child, node)
    return leaf_value[node]


def predict_forest(trees, bins, weights=None) -> np.ndarray:
    """Weighted-average forest prediction (RF mean vote / GBT partial sums
    are built by the caller)."""
    bins = jnp.asarray(bins, jnp.int32)
    preds = [np.asarray(predict_tree(jnp.asarray(t.split_feat),
                                     jnp.asarray(t.left_mask),
                                     jnp.asarray(t.leaf_value),
                                     bins, t.depth)) for t in trees]
    preds = np.stack(preds, axis=0)
    if weights is None:
        return preds.mean(axis=0)
    w = np.asarray(weights)[:, None]
    return (preds * w).sum(axis=0)
